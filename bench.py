"""Benchmark harness — all five BASELINE.json configs.

Prints exactly ONE JSON line on stdout:

    {"metric": "blake2b_batched_blob_hash_throughput", "value": N,
     "unit": "GiB/s", "vs_baseline": N, "backend": ..., "configs": {...}}

The headline metric is config 3 (the 50 GiB/s north-star target);
``configs`` carries one result object per BASELINE config:

  1 roundtrip     sessions/sec of the test/basic.js encode->decode flow
  2 replay        rows/sec of 1M-row change-log replay (native engine)
  3 hash          GiB/s of batched BLAKE2b blob hashing   (target 50)
  4 cdc           GiB/s of content-defined chunking incl. host select
  5 merkle_diff   entries/sec of two-snapshot tree diff    (target 10M)
  6 resume        ms from transport fault to first re-delivered frame
                  (checkpoint export -> reconnect -> redelivery; ROBUSTNESS.md)
  7 wire_batch    rows/s per-record vs columnar ChangeBatch framing A/B
  8 fused_e2e     GiB/s bytes->digests: fused single-pass route vs the
                  two-pass route (min-of-reps A/B; ISSUE 7)
  9 hub_soak      N concurrent sessions on ONE shared ReplicationHub:
                  aggregate GiB/s + per-session fairness (min/median
                  session throughput ratio; ISSUE 8)
  10 fanout       one-to-many broadcast: peers x delivered-MiB/s matrix
                  with hash-once counter proof + stalled-peer p99
                  isolation (ISSUE 9)
  11 reconcile_rateless  anti-entropy A/B at k in {10, 1000, 100000} on
                  1M+1M divergent replicas: rateless coded symbols vs
                  the sketch-table exchange vs the tree descent — wire
                  bytes and wall clock per arm (ISSUE 10)
  12 snapshot_bootstrap  content-addressed snapshot transfer: 2%-stale
                  joiner wire ratio vs cold full transfer (target
                  <= 0.05 at 1 GiB), 8-joiner cold flash crowd with
                  hash-once counter proof (hash_ratio 1.0), and a
                  torn-wire exactly-once resume arm (ISSUE 12)
  13 wire_pump    kernel-bypass transport pump A/B: e2e bytes->digest
                  over a real socket, native batched-syscall pump vs
                  the Python reference, plus hub aggregate vs session
                  count 1/4/16 (the GIL-flatness probe; ISSUE 14)
  14 gossip_converge  N-replica epidemic anti-entropy: rounds/seconds
                  to byte-identical replicas and total wire bytes vs
                  divergence size at N in {4, 16, 64} (ISSUE 15)
  15 edge_scaling  C10k control plane: 1/100/1k/10k concurrent
                  mixed-QoS sessions through ONE event-driven edge
                  loop — peak table occupancy, finish-flood
                  sessions/s, p99, admission/shed counts (must stay
                  zero on a properly sized hub; ISSUE 17).  Not in
                  the default set: request with BENCH_CONFIGS=15
                  (the 10k cohort spawns a client subprocess)

Robustness (round-1 failure was a backend-init crash that cost the round
its only perf artifact): device-backend init is retried with backoff and
falls back to CPU, recording the error; each config runs in its own
try/except so one failure cannot blank the others; ``--quick`` is small
on every backend (<30 s on CPU).

Env knobs: BENCH_ITEMS / BENCH_ITEM_MIB / BENCH_CHUNK (config 3),
BENCH_REPLAY_ROWS, BENCH_CDC_MIB / BENCH_CDC_REPS, BENCH_MERKLE_LOG2,
BENCH_ROUNDTRIPS, BENCH_RESUME_ROWS / BENCH_RESUME_REPS (config 6),
BENCH_CONFIGS (comma list, default "1,2,3,4,5,6,7,8,9,10,11"),
BENCH_RECONCILE_N / BENCH_RECONCILE_KS (config 11),
BENCH_FUSED_MIB / BENCH_FUSED_REPS / BENCH_FUSED_DEVICE (config 8),
BENCH_HUB_SESSIONS / BENCH_HUB_ROWS / BENCH_HUB_BLOB_KIB /
BENCH_HUB_MESH (config 9), BENCH_FANOUT_ROWS / BENCH_FANOUT_BLOB_KIB /
BENCH_FANOUT_PEERS / BENCH_FANOUT_STALL_S (config 10),
BENCH_SNAPSHOT_MIB / BENCH_SNAPSHOT_JOINERS / BENCH_SNAPSHOT_STALE
(config 12), BENCH_PUMP_MIB / BENCH_PUMP_REPS / BENCH_PUMP_SESSIONS
(config 13), BENCH_GOSSIP_N / BENCH_GOSSIP_RECORDS /
BENCH_GOSSIP_DIVERGENCE (config 14), BENCH_EDGE_N (config 15).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time
import traceback


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _timed_reps(fenced_run, reps: int) -> list[float]:
    """Time ``reps`` calls individually; caller takes the median.

    Each call must fence its own completion (device configs end in a
    small D2H).  Median-of-reps is the headline on device configs: the
    dev chip is shared, and one congestion spike in one rep should not
    misprice a kernel (identical code measured 9-21 GiB/s across one
    congested afternoon).  The aggregate over sum(dts) is reported
    alongside for transparency.
    """
    dts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fenced_run()
        dts.append(time.perf_counter() - t0)
    return dts


def _timed_reps_pipelined(dispatch, fence, reps: int, depth: int = 2):
    """Sustained per-rep timing with ``depth`` reps in flight.

    The dev tunnel's fence round-trip is ~66 ms (measured on a trivial
    scalar op, round 4) — serial fence-per-rep timing bills that latency
    against every rep, understating a 4 GiB hash dispatch by ~1.7x.
    Here rep k+1 is dispatched before rep k is fenced, so the fence's
    link round-trip rides under the next rep's device compute; per-rep
    spans are fence-to-fence, i.e. steady-state device cost.

    Honesty unchanged: EVERY rep's output is still individually forced
    off-device (the only reliable completion proof on platforms where
    block_until_ready returns early) — only the host's wait overlaps.
    ``BENCH_SERIAL_FENCE=1`` restores the round-3 serial methodology.
    """
    if os.environ.get("BENCH_SERIAL_FENCE") == "1":
        return _timed_reps(lambda: fence(dispatch()), reps)
    depth = max(1, depth)
    # priming rep, fenced untimed: without it the FIRST timed span has
    # no older rep completing under it and eats the full fence RTT the
    # helper exists to hide — at reps=2 that biases the median ~25%
    primer = dispatch()
    inflight = [dispatch() for _ in range(min(depth, reps))]
    launched = len(inflight)
    fence(primer)
    dts = []
    t_prev = time.perf_counter()
    while inflight:
        fence(inflight.pop(0))
        now = time.perf_counter()
        dts.append(now - t_prev)
        t_prev = now
        if launched < reps:
            inflight.append(dispatch())
            launched += 1
    return dts


def _peak_span(dts: list) -> float:
    """Fastest CREDIBLE span for the diagnostic peak fields: under
    pipelined fencing, a stall in span k lets rep k+1 finish on device
    early, so span k+1 collapses toward the bare fence RTT — faster
    than the hardware ever ran.  Two guards (advisor r4): spans under
    half the median are queue-drain artifacts, and a span whose
    PREDECESSOR was an outlier-high (>1.5x median) is still partially
    drain-compressed even inside the 0.5–1.0x band — exclude both.
    The peak_* fields remain upper bounds on uncontended capability,
    never headlines (the median is the headline)."""
    med = statistics.median(dts)
    cred = [d for i, d in enumerate(dts)
            if d >= 0.5 * med and (i == 0 or dts[i - 1] <= 1.5 * med)]
    return min(cred) if cred else med


def _fence_mode() -> str:
    """Recorded in every device-config result: pipelined vs serial fence
    numbers differ ~1.7x on the tunneled link, so cross-round artifact
    comparisons must not mix them blindly."""
    return "serial" if os.environ.get("BENCH_SERIAL_FENCE") == "1" else "pipelined"


def _env_int(name, default):
    return int(os.environ.get(name, default))


def _probe_stage(stdout: str | None) -> str | None:
    """Last ``STAGE <name>`` marker the probe printed: the init stage it
    was IN when it died/hung (obs.device.INIT_STAGES ladder)."""
    stage = None
    for line in (stdout or "").splitlines():
        if line.startswith("STAGE "):
            stage = line.split(None, 1)[1].strip()
    return stage


def _probe_failure(message: str, stdout: str | None,
                   elapsed_s: float) -> dict:
    """Structured backend_error record (ISSUE 5 satellite: the BENCH
    json's ``backend_error`` carries ``stage`` and ``elapsed_s``, not
    just an opaque string like round 5's "backend init hung (> 87s)")."""
    return {
        "message": message,
        "stage": _probe_stage(stdout),
        "elapsed_s": round(elapsed_s, 1),
    }


def _probe_backend(platform: str | None, timeout: float) -> tuple[str | None, dict | None]:
    """Initialize JAX in a THROWAWAY subprocess and report its backend.

    Round 1 died on "Unable to initialize backend 'axon': UNAVAILABLE";
    worse, a wedged device tunnel can make ``jax.devices()`` hang forever
    (observed: >300 s with no exception).  A subprocess probe turns both
    failure modes into something the parent can retry or route around —
    the parent only initializes a platform the probe verified.

    The probe prints staged progress markers (the obs.device watchdog
    ladder: platform_probe -> first_device_call -> first_compile) so a
    hang names the stage it is stuck in: ``subprocess.TimeoutExpired``
    carries the partial stdout captured before the kill.
    """
    import subprocess

    code = "print('STAGE platform_probe', flush=True)\nimport jax\n"
    if platform:
        code += f"jax.config.update('jax_platforms', {platform!r})\n"
    # EXECUTE something and fetch it, not just list devices: a wedged
    # tunnel (observed round 3: >6h outage) can enumerate devices fine
    # while every launch hangs — the probe must prove the device RUNS
    code += (
        "import numpy as np, jax.numpy as jnp\n"
        "print('STAGE first_device_call', flush=True)\n"
        "devs = jax.devices()\n"
        "print('STAGE first_compile', flush=True)\n"
        "x = np.asarray(jnp.arange(8) * 2)\n"
        "assert x[3] == 6\n"
        "print('PROBE', jax.default_backend(), len(devs))\n"
    )
    t0 = time.monotonic()
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", errors="replace")
        return None, _probe_failure(
            f"backend init hung (> {timeout:.0f}s)", stdout,
            time.monotonic() - t0)
    elapsed = time.monotonic() - t0
    if r.returncode == 0:
        for line in r.stdout.splitlines():
            if line.startswith("PROBE "):
                return line.split()[1], None
        return None, _probe_failure("probe produced no backend line",
                                    r.stdout, elapsed)
    tail = [ln for ln in r.stderr.strip().splitlines() if ln.strip()]
    return None, _probe_failure(
        tail[-1] if tail else f"probe exited {r.returncode}",
        r.stdout, elapsed)


def _probe_loop(
    force: str | None,
    deadline_ts: float,
    probe_timeout: float,
    probe_fn=None,
    sleep_s: float = 20.0,
    reserve_s: float = 60.0,
    on_first_failure=None,
) -> tuple[str | None, object]:
    """Probe for a working device backend across the WHOLE remaining budget.

    Round 3's driver artifact fell back to CPU because one 90 s probe hit a
    transient tunnel wedge and the run never looked again — while the very
    same chip answered for a ~50-minute window later that day.  This loop
    re-probes until the budget (minus ``reserve_s`` for at least starting a
    config) is gone:

    * probe succeeds on an accelerator -> return it immediately;
    * probe succeeds on plain CPU -> there is no device to wait for
      (CI/laptop): return failure at once, the caller runs the fallback;
    * probe fails/hangs -> the wedged-tunnel signature: sleep and re-probe.

    ``on_first_failure`` fires once, before the first sleep — main() uses it
    to start the CPU-fallback subprocess so waiting costs nothing.
    ``probe_fn`` is injectable for the hang-then-recover test.
    """
    probe = probe_fn or _probe_backend
    err = None  # str (scripted/legacy) or the _probe_failure dict
    failed_once = False
    while True:
        # a short deadline shrinks the probe timeout rather than skipping
        # the probe: a healthy device answers in seconds
        budget = min(probe_timeout, deadline_ts - time.monotonic() - reserve_s)
        if budget <= 0:
            return None, err or "probe budget exhausted"
        backend, perr = probe(force, budget)
        if backend is not None and backend != "cpu":
            return backend, None
        if backend == "cpu":
            # a healthy jax with no accelerator: re-probing cannot change it
            return None, perr or "no accelerator backend present"
        err = perr
        if not failed_once:
            failed_once = True
            if on_first_failure is not None:
                on_first_failure()
        remaining = deadline_ts - time.monotonic()
        if remaining - reserve_s <= sleep_s:
            return None, err
        msg = err.get("message") if isinstance(err, dict) else err
        stage = err.get("stage") if isinstance(err, dict) else None
        log(f"bench: backend probe failed ({msg}"
            + (f", stuck in stage {stage}" if stage else "")
            + f"); re-probe in {sleep_s:.0f}s "
            f"({remaining:.0f}s of budget left)")
        time.sleep(sleep_s)


def _start_cpu_fallback(device_keys: list[str], quick: bool,
                        budget_s: float, trace_dir: str | None = None,
                        flight_dir: str | None = None):
    """Launch ``bench.py`` for the device configs on the CPU backend in a
    subprocess, so fallback numbers accrue WHILE the parent keeps probing
    for the real device (a wedged tunnel must cost neither)."""
    import subprocess

    env = dict(os.environ)
    env["BENCH_PLATFORM"] = "cpu"
    env["BENCH_NO_FALLBACK"] = "1"  # the child must not recurse
    env["BENCH_CONFIGS"] = ",".join(device_keys)
    env["BENCH_DEADLINE"] = str(max(60, int(budget_s)))
    argv = [sys.executable, os.path.abspath(__file__)]
    if quick:
        argv.append("--quick")
    if _METRICS["on"]:  # fallback numbers deserve attribution too
        argv.append("--metrics")
    if trace_dir:  # own subdir: the parent's device leg may trace too
        argv.append(f"--trace={os.path.join(trace_dir, 'cpu_fallback')}")
    if flight_dir:  # shared dir is safe: bundle names carry the pid
        argv.append(f"--flight-dir={flight_dir}")
    log(f"bench: starting CPU-fallback subprocess for configs "
        f"{env['BENCH_CONFIGS']}")
    return subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env
    )


def _collect_cpu_fallback(proc, timeout: float) -> dict:
    """Parse the fallback child's one-line JSON artifact into its configs."""
    if proc is None:
        return {}
    try:
        out, _ = proc.communicate(timeout=max(5.0, timeout))
    except Exception as e:
        log(f"bench: CPU-fallback subprocess unusable ({e})")
        try:
            proc.kill()
        except OSError:
            pass
        return {}
    for line in (out or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line).get("configs", {})
            except json.JSONDecodeError:
                pass
    return {}


def _merge_fallback(configs: dict, fallback: dict) -> list[str]:
    """Fill configs the device leg failed/never ran with the CPU child's
    clean results, tagging each so the artifact says which engine produced
    it.  Returns the names that were filled."""
    filled = []
    for name, res in fallback.items():
        if "error" in res:
            continue
        have = configs.get(name)
        if have is None or "error" in have:
            res = dict(res)
            res["backend"] = "cpu-fallback"
            configs[name] = res
            filled.append(name)
    return filled


# ---------------------------------------------------------------------------
# wire cost plane capture (ISSUE 20): goodput_ratio / overhead_ratio fields
# for configs 7/10/11/12/14, read off the WireCostBoard with the plane lit —
# the same watermarks `obs fleet` gates in production, so the checked-in
# snapshot is the wire_ratio baseline ROADMAP item 4's compression tier
# will be diffed against
# ---------------------------------------------------------------------------


def _wirecost_ratios(*links) -> tuple:
    """(goodput_ratio, overhead_ratio) aggregated over the named board
    links (both directions), or over EVERY link when none are named —
    payload-weighted, from the live WireCostBoard ledger."""
    from dat_replication_protocol_tpu.obs.wirecost import WIRECOST

    snap = WIRECOST.snapshot()["links"]
    payload = framing = total = 0
    for name, rec in snap.items():
        if links and name.split("|", 1)[0] not in links:
            continue
        payload += rec["payload_bytes"]
        framing += rec["framing_bytes"]
        total += rec["ledger_bytes"]
    if not total:
        return None, None
    return round(payload / total, 5), round(framing / total, 5)


def _wirecost_decode_ratios(wire: bytes) -> tuple:
    """(goodput_ratio, overhead_ratio) of one recorded wire: the bytes
    are replayed through a LIT session decoder and the board's per-link
    watermarks are the ratios.  Obs state is saved/restored; the board
    is reset so the ledger holds exactly this wire."""
    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics
    from dat_replication_protocol_tpu.obs.wirecost import WIRECOST

    was_on = obs_metrics.OBS.on
    obs_metrics.enable()
    WIRECOST.reset_for_tests()
    try:
        dec = protocol.decode()
        dec.on_error(lambda e: None)
        step = 1 << 20
        for off in range(0, len(wire), step):
            dec.write(wire[off:off + step])
        return _wirecost_ratios("session")
    finally:
        WIRECOST.reset_for_tests()
        obs_metrics.OBS.on = was_on


# ---------------------------------------------------------------------------
# config 1: test/basic.js-shaped roundtrip (reference: test/basic.js:1-127)
# ---------------------------------------------------------------------------


def bench_roundtrip(quick: bool, backend: str) -> dict:
    import dat_replication_protocol_tpu as protocol

    n = _env_int("BENCH_ROUNDTRIPS", 200 if quick else 2000)

    def one_session():
        got = []
        enc = protocol.encode()
        dec = protocol.decode()
        dec.change(lambda ch, done: (got.append(ch.key), done()))
        dec.blob(lambda blob, done: blob.collect(lambda b: (got.append(b), done())))
        enc.change({"key": "a", "change": 1, "from_": 0, "to": 1, "value": b"v"})
        ws = enc.blob(12)
        ws.write(b"hello ")
        ws.end(b"world!")
        enc.change({"key": "b", "change": 2, "from_": 1, "to": 2})
        enc.finalize()
        protocol.pipe(enc, dec)
        assert got == ["a", b"hello world!", "b"], got

    one_session()  # correctness gate + warmup
    t0 = time.perf_counter()
    for _ in range(n):
        one_session()
    dt = time.perf_counter() - t0

    # bulk decode rate: a change+blob log pushed through Decoder.write in
    # 256 KiB chunks (the native-indexed hot path; round-2 verdict item 5)
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import (
        TYPE_BLOB,
        TYPE_CHANGE,
        frame,
    )

    rows = _env_int("BENCH_DECODE_ROWS", 20_000 if quick else 400_000)
    block_n = min(rows, 4096)
    parts = []
    for i in range(block_n):
        parts.append(frame(TYPE_CHANGE, encode_change({
            "key": f"key-{i:07d}", "change": i, "from": i, "to": i + 1,
            "value": b"v" * (i % 48),
        })))
        if i % 64 == 0:
            parts.append(frame(TYPE_BLOB, b"B" * 512))
    block = b"".join(parts)
    reps = -(-rows // block_n)
    wire = block * reps
    nframes = (block_n + -(-block_n // 64)) * reps

    dec = protocol.decode()
    counted = {"changes": 0}
    dec.change(lambda ch, done: (counted.__setitem__(
        "changes", counted["changes"] + 1), done()))
    t0 = time.perf_counter()
    for off in range(0, len(wire), 1 << 18):
        dec.write(wire[off : off + (1 << 18)])
    dec.end()
    ddt = time.perf_counter() - t0
    assert counted["changes"] == block_n * reps, counted
    decode_mib_s = len(wire) / ddt / (1 << 20)
    log(
        f"bench[roundtrip]: bulk decode {len(wire) / (1 << 20):.1f} MiB in "
        f"{ddt:.3f}s = {decode_mib_s:.1f} MiB/s ({nframes / ddt:,.0f} frames/s)"
    )

    # blob-dominated wire: byte throughput of the slicing fast path
    blob_frame = frame(TYPE_BLOB, b"B" * (256 << 10))
    blob_wire = blob_frame * (8 if quick else 64)
    dec2 = protocol.decode()
    seen = {"blobs": 0}
    dec2.blob(lambda blob, done: (
        blob.on_data(lambda _c: None),
        blob.on_end(lambda: (seen.__setitem__("blobs", seen["blobs"] + 1),
                             done())),
    ))
    t0 = time.perf_counter()
    for off in range(0, len(blob_wire), 1 << 18):
        dec2.write(blob_wire[off : off + (1 << 18)])
    dec2.end()
    bdt = time.perf_counter() - t0
    assert seen["blobs"] == len(blob_wire) // len(blob_frame)
    blob_mib_s = len(blob_wire) / bdt / (1 << 20)
    log(f"bench[roundtrip]: blob decode {blob_mib_s:.0f} MiB/s")
    return {
        "metric": "session_roundtrip_rate",
        "value": round(n / dt, 1),
        "unit": "sessions/s",
        "vs_baseline": None,
        "decode_mib_s": round(decode_mib_s, 1),
        "decode_frames_s": round(nframes / ddt, 0),
        "decode_blob_mib_s": round(blob_mib_s, 1),
    }


# ---------------------------------------------------------------------------
# config 2: 1M-row change-log replay (native framing + proto decode)
# ---------------------------------------------------------------------------


def bench_replay(quick: bool, backend: str) -> dict:
    import numpy as np

    from dat_replication_protocol_tpu.runtime import native, replay
    from dat_replication_protocol_tpu.wire.change_codec import Change, encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    rows = _env_int("BENCH_REPLAY_ROWS", 20_000 if quick else 1_000_000)
    # build the log from a repeated block of distinct records: encoding
    # 1M rows one-by-one in Python would dominate setup time
    block_n = min(rows, 4096)
    recs = [
        Change(
            key=f"key-{i:07d}",
            change=i,
            from_=i,
            to=i + 1,
            value=b"v" * (i % 48),
            subset="s" if i % 3 else None,
        )
        for i in range(block_n)
    ]
    block = b"".join(frame(TYPE_CHANGE, encode_change(c)) for c in recs)
    reps = -(-rows // block_n)
    log_buf = np.frombuffer(block * reps, dtype=np.uint8)
    total_rows = block_n * reps

    t0 = time.perf_counter()
    cols, frames = replay.replay_log(log_buf)
    dt = time.perf_counter() - t0
    assert len(cols) == total_rows

    # the inverse path: bulk log construction (native columnar encoder),
    # measured over enough rows that the interval is timing-stable.
    # Fed as dicts so encode_rows_s keeps billing the per-row
    # from_dict conversion the metric has always included
    dicts = [c.to_dict() for c in recs]
    replay.encode_change_log(dicts[:64])  # warm the path
    enc_reps = max(1, min(total_rows, 100_000) // block_n)
    big = dicts * enc_reps
    t0 = time.perf_counter()
    wire = replay.encode_change_log(big)
    edt = time.perf_counter() - t0
    assert wire == block * enc_reps
    enc_rows = len(big)

    # columnar re-encode (replay_log's exact inverse, zero Python/row):
    # the decoded columns of the full log straight back to wire bytes
    t0 = time.perf_counter()
    cwire = replay.encode_change_columns(cols)
    cdt = time.perf_counter() - t0
    assert len(cwire) == log_buf.nbytes
    return {
        "metric": "change_log_replay_rate",
        "value": round(total_rows / dt, 0),
        "unit": "rows/s",
        "vs_baseline": None,
        "native": native.available(),
        "rows": total_rows,
        "reduced_config": total_rows < 1_000_000,
        "full_config": "1M rows (BASELINE config 2)",
        "log_mib": round(log_buf.nbytes / (1 << 20), 1),
        "encode_rows_s": round(enc_rows / edt, 0),
        "encode_columns_rows_s": round(total_rows / cdt, 0),
    }


# ---------------------------------------------------------------------------
# config 3: batched BLAKE2b blob hashing (headline; target >= 50 GiB/s)
# ---------------------------------------------------------------------------


def bench_hash(quick: bool, backend: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dat_replication_protocol_tpu.ops.blake2b import BLOCK_BYTES, blake2b_packed

    use_pallas = backend in ("tpu", "axon")
    if quick:
        d_items, d_mib, d_chunk = (2048, 1, 2048) if use_pallas else (32, 0.125, 32)
    elif use_pallas:
        d_items, d_mib, d_chunk = 10240, 1, 4096
    else:
        d_items, d_mib, d_chunk = 64, 0.125, 32
    items = _env_int("BENCH_ITEMS", d_items)
    item_mib = float(os.environ.get("BENCH_ITEM_MIB", d_mib))
    chunk = min(_env_int("BENCH_CHUNK", d_chunk), items)
    if use_pallas:
        chunk = max(1024, chunk // 1024 * 1024)  # pallas tiles 1024 items

    item_bytes = max(BLOCK_BYTES, int(item_mib * (1 << 20)) // BLOCK_BYTES * BLOCK_BYTES)
    nblocks = item_bytes // BLOCK_BYTES
    reps = max(1, -(-items // chunk))  # ceil: honor the full item count
    log(
        f"bench[hash]: pallas={use_pallas} items={reps * chunk} x {item_bytes} B "
        f"(chunk={chunk}, reps={reps})"
    )

    if not use_pallas:
        from dat_replication_protocol_tpu.runtime import native as _native
        from dat_replication_protocol_tpu.utils.routing import prefer_host

        if prefer_host("DAT_DEVICE_HASH") and _native.available():
            # the engine the routing layer actually picks on a CPU host
            # ("batch or stay home"), measured THROUGH the routed entry
            # point (_host_hash_batch: join + native C pass + per-row
            # bytes) so the number is what a backend='tpu' session pays
            # per batch — not the raw C kernel.  The XLA-scan number
            # stays alongside for cross-round continuity but represents
            # nothing a user would run here.
            from dat_replication_protocol_tpu.backend.tpu_backend import (
                _host_hash_batch,
            )

            hb = _env_int("BENCH_HOST_HASH_MIB", 32 if quick else 256) << 20
            hitems = max(64, hb // item_bytes)  # >= 64: the router's own
            # native-path threshold
            rng0 = np.random.default_rng(3)
            payloads = [
                rng0.integers(0, 256, item_bytes, dtype=np.uint8).tobytes()
                for _ in range(hitems)
            ]
            _host_hash_batch(payloads[:64])  # warm (.so build/load)
            t0 = time.perf_counter()
            digs0 = _host_hash_batch(payloads)
            hdt = time.perf_counter() - t0
            assert len(digs0) == hitems
            host_gib_s = hitems * item_bytes / hdt / (1 << 30)
            host_fields = {"host_items": hitems,
                           "host_volume_gib":
                               round(hitems * item_bytes / (1 << 30), 3)}
            log(f"bench[hash]: routed host engine {host_gib_s:.3f} GiB/s "
                f"({hitems} x {item_bytes} B)")
        else:
            host_gib_s = None
    else:
        host_gib_s = None

    kh, kl = jax.random.split(jax.random.PRNGKey(0))
    variant = "xla-scan"
    if use_pallas:
        from dat_replication_protocol_tpu.ops.blake2b_pallas import blake2b_native

        shape = (nblocks, 16, 8, chunk // 8)
        mh = jax.random.bits(kh, shape, dtype=jnp.uint32)
        ml = jax.random.bits(kl, shape, dtype=jnp.uint32)
        lengths = jnp.full((8, chunk // 8), item_bytes, dtype=jnp.uint32)
        jax.block_until_ready((mh, ml))

        # self-select the kernel variant: one warmed+fenced calibration
        # rep each (register-resident vs VMEM-resident working vectors
        # rank differently depending on the chip's scheduler; the bench
        # should capture the best configuration, not a guess)
        t0 = time.perf_counter()
        best = None
        golden = None  # digest output of a TESTED variant: others must
        # reproduce it.  Every composition except (False, True) has a
        # CPU byte-exactness test (test_blake2b_pallas.py), so any of
        # those may anchor; (False, True) is covered ONLY by this guard
        # and never anchors.
        cpu_tested = {(False, False), (True, False), (True, True)}
        # (False, True) runs LAST: it is the only composition without a
        # CPU byte-exactness test, so it must never anchor golden — and
        # visiting it after every anchor-capable variant means a single
        # baseline compile failure cannot permanently skip it
        for vs, sl in ((False, False), (True, False), (True, True),
                       (False, True)):
            kern = lambda vs=vs, sl=sl: blake2b_native(  # noqa: E731
                mh, ml, lengths, vmem_state=vs, state_loads=sl)
            try:
                if golden is None and (vs, sl) not in cpu_tested:
                    log(f"bench[hash]: no tested baseline compiled yet; "
                        f"skipping unanchorable variant vmem={vs} sloads={sl}")
                    continue
                hh, hl = kern()  # compile + warm
                probe = (np.asarray(hh), np.asarray(hl))  # FULL digests:
                # a lane-partial miscompile must not slip past the guard
                if golden is None:
                    golden = probe
                elif not (np.array_equal(golden[0], probe[0])
                          and np.array_equal(golden[1], probe[1])):
                    # never self-select a miscompiled variant for the
                    # headline number, however fast it runs
                    log(f"bench[hash]: variant vmem={vs} sloads={sl} "
                        f"DIGEST MISMATCH vs baseline; skipped")
                    continue
                # median of 3, pipeline-fenced: one rep can misprice by
                # >2x on the shared chip and would silently pick the
                # wrong kernel; serial fencing would additionally bury
                # variant deltas under the ~66 ms link RTT
                cals = _timed_reps_pipelined(
                    kern,
                    lambda o: (np.asarray(o[0][:1, :1]),
                               np.asarray(o[1][:1, :1])),
                    3,
                )
                cal = statistics.median(cals)
            except Exception as e:
                log(f"bench[hash]: variant vmem={vs} sloads={sl} failed ({e})")
                continue
            log(f"bench[hash]: calibrate vmem={vs} sloads={sl}: "
                f"{cal:.3f}s/rep (median of 3)")
            if best is None or cal < best[1]:
                best = (kern, cal, vs, sl)
        if best is None:
            raise RuntimeError("no hash kernel variant ran")
        run = best[0]
        variant = f"pallas(vmem_state={best[2]},state_loads={best[3]})"
        log(
            f"bench[hash]: compile+calibrate {time.perf_counter() - t0:.1f}s "
            f"-> {variant}"
        )
    else:
        shape = (chunk, nblocks, 16)
        mh = jax.random.bits(kh, shape, dtype=jnp.uint32)
        ml = jax.random.bits(kl, shape, dtype=jnp.uint32)
        lengths = jnp.full((chunk,), item_bytes, dtype=jnp.uint32)
        run = lambda: blake2b_packed(mh, ml, lengths)  # noqa: E731
        jax.block_until_ready((mh, ml))
        t0 = time.perf_counter()
        np.asarray(run()[0])
        log(f"bench[hash]: compile+first-run {time.perf_counter() - t0:.1f}s")

    # completion barrier: a tiny slice of every rep's output (on the
    # tunneled axon platform block_until_ready returns before execution
    # ends, so a transfer is the only reliable fence).  The digests
    # themselves stay in HBM — their consumer is the on-device Merkle
    # stage (batch/feed.leaves_from_columns -> ops.merkle.build_tree),
    # not the host; fetching all of them would bill the ~8.5 MiB/s dev
    # tunnel's D2H against the kernel (~45% of wall time at these rates).
    def fence(out):
        hh, hl = out
        np.asarray(hh[:1, :1])
        np.asarray(hl[:1, :1])

    rep_dts = _timed_reps_pipelined(run, fence, reps)
    dt = sum(rep_dts)
    total = reps * chunk * item_bytes
    gib_s = (chunk * item_bytes) / statistics.median(rep_dts) / (1 << 30)
    log(
        f"bench[hash]: {total / (1 << 30):.1f} GiB in {dt:.3f}s = "
        f"{gib_s:.2f} GiB/s median ({total / dt / (1 << 30):.2f} aggregate)"
    )

    # honest end-to-end variant: host log buffer -> pack_ragged -> H2D ->
    # digests -> D2H, the batch/feed.py:hash_extents path.  Small volume
    # by design: the tunneled dev link moves H2D at ~33 MiB/s (measured),
    # so this figure characterizes the host+transfer pipeline, not the
    # kernel; h2d_mib_s is recorded alongside so the artifact shows the
    # link it was measured over.
    from dat_replication_protocol_tpu.batch.feed import hash_extents

    # sized so the feed layer's pipelining actually engages: with
    # pipeline_bytes=16 MiB the 1024-item batch splits into multiple
    # chunks whose uploads stream under earlier chunks' compute (on the
    # TPU path the pallas item floor makes the chunks wider — still >= 2)
    e2e_items = 128 if quick else 1024
    e2e_item = 1 << 18  # 256 KiB
    e2e_pipe = {"pipeline_bytes": 16 << 20}
    buf = np.random.default_rng(1).integers(
        0, 256, e2e_items * e2e_item, dtype=np.uint8
    )
    offs = np.arange(e2e_items, dtype=np.int64) * e2e_item
    lens = np.full(e2e_items, e2e_item, dtype=np.int64)
    hash_extents(buf, offs, lens, **e2e_pipe)  # warmup/compile at the FULL
    # batch shape: a smaller warmup would leave the timed call paying a
    # fresh jit specialization and mislabel compile time as pipeline time
    t0 = time.perf_counter()
    digs = hash_extents(buf, offs, lens, **e2e_pipe)
    e2e_dt = time.perf_counter() - t0
    assert len(digs) == e2e_items
    e2e_gib_s = buf.nbytes / e2e_dt / (1 << 30)

    # session-level digest rate: blob frames through the backend='tpu'
    # decoder, digests included — the engine the routing layer actually
    # picks on THIS host (device batches on an accelerator, native/hashlib
    # on a CPU host; round-3 verdict weak #4's acceptance measure)
    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.wire.framing import TYPE_BLOB as _TB
    from dat_replication_protocol_tpu.wire.framing import frame as _frame

    blob_frame = _frame(_TB, b"B" * (256 << 10))
    sess_wire = blob_frame * (16 if quick else 128)
    dec = protocol.decode(backend="tpu")
    counted = {"n": 0}
    dec.on_digest(lambda k, s, d: counted.__setitem__("n", counted["n"] + 1))
    dec.blob(lambda blob, done: (blob.on_data(lambda _c: None),
                                 blob.on_end(done)))
    t0 = time.perf_counter()
    for off in range(0, len(sess_wire), 1 << 18):
        dec.write(sess_wire[off:off + (1 << 18)])
    dec.end()
    sdt = time.perf_counter() - t0
    assert counted["n"] == len(sess_wire) // len(blob_frame)
    session_mib_s = len(sess_wire) / sdt / (1 << 20)
    log(f"bench[hash]: session digest path {session_mib_s:.0f} MiB/s "
        f"({counted['n']} blobs)")

    probe_bytes = min(32 << 20, buf.nbytes)
    x = jnp.asarray(buf[:probe_bytes])
    t0 = time.perf_counter()
    np.asarray(x[:8])
    h2d = (probe_bytes / (1 << 20)) / (time.perf_counter() - t0)
    # overlap factor: e2e throughput as a fraction of the measured link —
    # with H2D staged under compute (batch/feed pipelining) a link-bound
    # path should sit near 1.0; round 3 measured 0.03-0.3 with nothing
    # overlapped
    e2e_vs_link = (e2e_gib_s * 1024) / h2d
    log(
        f"bench[hash]: e2e host->digest {e2e_gib_s:.3f} GiB/s "
        f"({buf.nbytes >> 20} MiB; link h2d ~{h2d:.0f} MiB/s; "
        f"{e2e_vs_link:.2f}x link)"
    )
    out = {
        "metric": "blake2b_batched_blob_hash_throughput",
        "value": round(gib_s, 3),
        "unit": "GiB/s",
        "vs_baseline": round(gib_s / 50.0, 4),
        # VERDICT r4 weak #5: below-config shapes must say so in-band,
        # not rely on the reader cross-checking items x item_bytes
        "reduced_config": total < (10240 << 20),
        "full_config": "10240 x 1 MiB (BASELINE config 3)",
        "aggregate_gib_s": round(total / dt / (1 << 30), 3),
        # best credible rep: on the shared dev chip this approximates
        # the uncontended rate (diagnostic only; the median stays the
        # headline; see _peak_span for the queue-drain guard)
        "peak_gib_s": round(
            (chunk * item_bytes) / _peak_span(rep_dts) / (1 << 30), 3
        ),
        "fence": _fence_mode(),
        "kernel_variant": variant,
        "e2e_host_gib_s": round(e2e_gib_s, 3),
        "session_digest_mib_s": round(session_mib_s, 1),
        "h2d_mib_s": round(h2d, 1),
        "e2e_vs_link": round(e2e_vs_link, 3),
        "items": reps * chunk,
        "item_bytes": item_bytes,
    }
    if host_gib_s is not None:
        # headline = the routed engine on this host; the scan number
        # stays alongside for cross-round continuity
        out["value"] = round(host_gib_s, 3)
        out["vs_baseline"] = round(host_gib_s / 50.0, 4)
        out["kernel_variant"] = "native-host"
        out["xla_scan_gib_s"] = round(gib_s, 3)
        # the peak was measured on the scan path, not the routed host
        # engine — rename it alongside the scan median so peak < value
        # can't read as nonsense
        out["xla_scan_peak_gib_s"] = out.pop("peak_gib_s")
        out.update(host_fields)  # the host run's own volume/provenance
    return out


# ---------------------------------------------------------------------------
# config 4: content-defined chunking over a large blob (10 GiB volume)
# ---------------------------------------------------------------------------


def bench_cdc(quick: bool, backend: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dat_replication_protocol_tpu.ops import rabin

    on_tpu = backend in ("tpu", "axon")
    if quick:
        slab_mib, reps = (64, 2) if on_tpu else (2, 2)
    elif on_tpu:
        # 10 GiB total volume via a 2 GiB slab (the per-call cap): the
        # round-4 phase attribution measured ~63 ms of fixed per-slab
        # cost (dispatch + fence round-trips through the tunnel) against
        # a ~5 ms/GiB marginal kernel cost, so fewer, larger slabs are
        # strictly better until the cap
        slab_mib, reps = 2048, 5
    else:
        slab_mib, reps = 8, 2
    slab_mib = _env_int("BENCH_CDC_MIB", slab_mib)
    reps = _env_int("BENCH_CDC_REPS", reps)
    slab_bytes = slab_mib << 20
    avg_bits = 13

    if not on_tpu:
        from dat_replication_protocol_tpu.runtime import native
        from dat_replication_protocol_tpu.utils.routing import prefer_host

        # the branch label must match what chunk_stream will actually
        # route to: prefer_host consults the same decision (and the
        # DAT_DEVICE_CDC override) chunk_stream does
        if prefer_host("DAT_DEVICE_CDC") and native.available():
            # the engine the routing layer actually picks on a CPU host:
            # the native C gear scan + native greedy select through
            # chunk_stream ("batch or stay home" — the XLA-scan path
            # measures ~0.0002 GiB/s here and represents nothing a user
            # would run)
            host_mib = _env_int("BENCH_CDC_HOST_MIB", 64 if quick else 256)
            data = np.random.default_rng(7).integers(
                0, 256, host_mib << 20, dtype=np.uint8
            )
            rabin.chunk_stream(data[: 4 << 20], avg_bits=avg_bits)  # warm
            t0 = time.perf_counter()
            cuts = rabin.chunk_stream(data, avg_bits=avg_bits)
            dt = time.perf_counter() - t0
            gib_s = data.nbytes / dt / (1 << 30)
            log(f"bench[cdc]: native host engine {gib_s:.2f} GiB/s "
                f"({len(cuts)} chunks / {host_mib} MiB)")
            return {
                "metric": "cdc_chunking_throughput",
                "value": round(gib_s, 3),
                "unit": "GiB/s",
                "vs_baseline": None,
                "volume_gib": round(data.nbytes / (1 << 30), 2),
                "engine": "native-host",
                "chunks": len(cuts),
                "reduced_config": data.nbytes < (10 << 30),
                "full_config": "10 GiB blob (BASELINE config 4)",
            }

    # the blob lives in HBM (the framework's hot path hashes/chunks data
    # that the feed layer already staged on device); the timed loop is
    # kernel + on-device sparse extraction + O(candidates) D2H + greedy
    # min/max select (native C) — everything a consumer of cut offsets
    # pays.  Mirrors the hash bench's device-resident methodology.
    words = jax.random.bits(
        jax.random.PRNGKey(7), (slab_bytes // 4,), dtype=jnp.uint32
    )
    jax.block_until_ready(words)

    def begin():
        return rabin.candidates_begin(
            words, slab_bytes, avg_bits, thin_bits=avg_bits - 2
        )

    def finish(collect):
        return rabin._greedy_select(
            collect(), slab_bytes, 1 << (avg_bits - 2), 1 << (avg_bits + 2)
        )

    # self-select the extraction route (bitmask kernel + window reduce,
    # first-hit kernel, or the fused window-first kernel): the
    # serial-chain analysis favors bitmask over first-hit and the fused
    # route saves the mask's HBM round-trip, but the bench should
    # capture the best configuration the chip actually delivers, not a
    # prediction (same policy as the hash kernel calibration; all
    # routes produce identical cuts — tested, and guarded again below)
    if not (os.environ.get("DAT_CDC_ROUTE")
            or "DAT_CDC_FIRST_KERNEL" in os.environ):
        cal = {}
        golden_cuts = None
        # "fused" is pallas-only; off-Pallas it silently aliases bitmask
        # — timing it there would duplicate a leg and could mislabel
        # extract_route in the artifact.  rabin.pallas_active is the one
        # owner of that decision (the probe's platform string and jax's
        # backend name can differ on the tunneled platform)
        routes = (("bitmask", "first", "fused") if rabin.pallas_active()
                  else ("bitmask", "first"))
        # advisor r4: EVERY route is validated against a HOST reference
        # before it may participate — a miscutting route must not win
        # (or disqualify the correct routes) by forfeit.  The reference
        # covers a prefix (full-slab D2H would cost minutes on the
        # tunneled link); every cut below prefix_end - 2*max_size is
        # determined by the prefix bytes alone, so that comparison is
        # exact.  Cross-route full-slab equality (the golden check
        # below) covers the remaining 99%+ of the slab: a route that
        # passes the prefix but diverges later is logged WITH the
        # divergence position — not silently dropped — because at that
        # point the prefix can no longer say which side is wrong.
        from dat_replication_protocol_tpu.runtime import native as _nat

        have_native = _nat.available()
        pre_b = min((8 if have_native else 1) << 20, slab_bytes)
        pre = np.frombuffer(
            np.asarray(words[: pre_b // 4]).tobytes(), dtype=np.uint8
        )
        # the reference applies the SAME window thinning as the device
        # routes (begin() passes thin_bits=avg_bits-2): unthinned
        # greedy can legitimately pick a candidate thinning dropped,
        # and every route would then spuriously fail the check
        thn = avg_bits - 2
        ref_cands = (
            _nat.gear_candidates(pre, avg_bits, thn)
            if have_native
            else np.asarray(
                rabin.host_thin(
                    rabin.host_candidates(pre.tobytes(), avg_bits), thn
                ),
                dtype=np.int64,
            )
        )
        ref_cuts = rabin._greedy_select(
            np.asarray(ref_cands, dtype=np.int64),
            pre_b, 1 << (avg_bits - 2), 1 << (avg_bits + 2),
        )
        lim = pre_b - 2 * (1 << (avg_bits + 2))
        want = [c for c in ref_cuts if c < lim]
        for route in routes:
            os.environ["DAT_CDC_ROUTE"] = route
            try:
                cuts0 = finish(begin())  # compile + warm
                got = [c for c in cuts0 if c < lim]
                if got != want:
                    log(f"bench[cdc]: route {route} FAILED host-"
                        f"reference prefix check; excluded")
                    continue
                if golden_cuts is None:
                    golden_cuts = cuts0
                elif cuts0 != golden_cuts:
                    # both passed the host prefix but diverge later in
                    # the slab: exclude this route from selection and
                    # say exactly where, so the artifact's log is
                    # debuggable instead of a silent forfeit
                    div = next(
                        (i for i, (a, b) in enumerate(
                            zip(cuts0, golden_cuts)) if a != b),
                        min(len(cuts0), len(golden_cuts)),
                    )
                    log(f"bench[cdc]: route {route} CUT MISMATCH vs "
                        f"golden beyond the verified prefix (first "
                        f"divergence at cut #{div}: "
                        f"{cuts0[div] if div < len(cuts0) else 'END'} vs "
                        f"{golden_cuts[div] if div < len(golden_cuts) else 'END'}); "
                        f"excluded — neither side host-verified there")
                    continue
                # median of 3, pipelined like the headline loop so
                # route deltas aren't buried under the link RTT AND one
                # congestion spike can't lock the slower route in (the
                # helper also honors BENCH_SERIAL_FENCE, keeping route
                # selection under the same fencing the headline uses)
                cal[route] = statistics.median(
                    _timed_reps_pipelined(begin, finish, 3)
                )
            except Exception as e:
                log(f"bench[cdc]: route {route} failed ({e})")
        if cal:
            pick = min(cal, key=cal.get)
            os.environ["DAT_CDC_ROUTE"] = pick
            log(f"bench[cdc]: route calibration {cal} -> {pick}")
        else:
            os.environ.pop("DAT_CDC_ROUTE", None)

    cuts = finish(begin())  # warmup/compile
    nchunks = len(cuts)
    # depth-2 pipeline: slab N's position D2H rides under slab N+1's scan,
    # the same overlap chunk_stream applies to real multi-slab streams
    t0 = time.perf_counter()
    pending = []
    for _ in range(reps):
        pending.append(begin())
        if len(pending) >= 2:
            finish(pending.pop(0))
    while pending:
        finish(pending.pop(0))
    dt = time.perf_counter() - t0
    total = reps * slab_bytes
    gib_s = total / dt / (1 << 30)
    log(
        f"bench[cdc]: {total / (1 << 30):.1f} GiB in {dt:.3f}s = {gib_s:.2f} GiB/s "
        f"({nchunks} chunks/slab)"
    )

    # kernel-only rate (no extraction/transfer): the gear scan over
    # device-resident tiles, completion fenced by a scalar reduction
    stride = 1 << 17
    T = slab_bytes // stride
    rows = jax.random.bits(
        jax.random.PRNGKey(8), (T, (stride + 256) // 4), dtype=jnp.uint32
    )
    if on_tpu:
        from dat_replication_protocol_tpu.ops.rabin_pallas import (
            gear_candidates_pallas,
        )

        kern = jax.jit(lambda w: jnp.sum(gear_candidates_pallas(w, avg_bits)))
    else:
        kern = jax.jit(lambda w: jnp.sum(rabin.gear_candidates_tiled(w, avg_bits)))
    np.asarray(kern(rows))
    kdts = _timed_reps_pipelined(lambda: kern(rows), np.asarray, reps)
    kernel_gib_s = rows.nbytes / statistics.median(kdts) / (1 << 30)
    log(f"bench[cdc]: kernel-only {kernel_gib_s:.2f} GiB/s")
    return {
        "metric": "cdc_chunking_throughput",
        "value": round(gib_s, 3),
        "unit": "GiB/s",
        "vs_baseline": None,
        "volume_gib": round(total / (1 << 30), 2),
        "reduced_config": total < (10 << 30),
        "full_config": "10 GiB blob (BASELINE config 4)",
        "kernel_only_gib_s": round(kernel_gib_s, 3),
        "kernel_peak_gib_s": round(rows.nbytes / _peak_span(kdts) / (1 << 30), 3),
        "fence": _fence_mode(),
        "extract_route": rabin.effective_route(),
        "chunks_per_slab": nchunks,
    }


# ---------------------------------------------------------------------------
# config 5: Merkle diff of two snapshots (target >= 10M entries/sec)
# ---------------------------------------------------------------------------


def bench_merkle(quick: bool, backend: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dat_replication_protocol_tpu.ops.merkle import (
        diff_root_guided_packed,
        unpack_mask,
    )

    on_tpu = backend in ("tpu", "axon")
    if quick:
        log2 = 10  # compile time scales with level count on CPU
    else:
        log2 = 20 if on_tpu else 16
    log2 = _env_int("BENCH_MERKLE_LOG2", log2)
    n = 1 << log2

    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    a_hh = jax.random.bits(keys[0], (n, 4), dtype=jnp.uint32)
    a_hl = jax.random.bits(keys[1], (n, 4), dtype=jnp.uint32)
    # b differs from a in ~1% of leaves
    flip = jax.random.bernoulli(keys[2], 0.01, (n, 1))
    b_hh = jnp.where(flip, a_hh ^ 1, a_hh)
    b_hl = a_hl
    jax.block_until_ready((a_hh, a_hl, b_hh, b_hl))

    def dispatch():
        bits, _, _ = diff_root_guided_packed(a_hh, a_hl, b_hh, b_hl)
        return bits

    def fence(bits):
        # honest end-to-end: packed-mask transfer + host bit expansion +
        # index extraction included in every rep
        return np.nonzero(unpack_mask(bits, n))[0]

    idx = fence(dispatch())  # warmup/compile
    reps = 3 if quick else 10
    rep_dts = _timed_reps_pipelined(dispatch, fence, reps)
    dt = sum(rep_dts)
    rate = n / statistics.median(rep_dts)

    # the routed LOCAL diff engine (ops.merkle.diff_snapshots): on a CPU
    # host that is one vectorized compare — the tree walk above stays
    # the headline (it IS config 5's metric), this field shows what a
    # local caller gets from the routing layer
    from dat_replication_protocol_tpu.ops.merkle import diff_snapshots
    from dat_replication_protocol_tpu.utils.routing import prefer_host

    local_rate = None
    if prefer_host("DAT_DEVICE_MERKLE"):
        ah, al = np.asarray(a_hh), np.asarray(a_hl)
        bh, bl = np.asarray(b_hh), np.asarray(b_hl)
        lidx = diff_snapshots(ah, al, bh, bl)  # warm
        ldts = _timed_reps(
            lambda: diff_snapshots(ah, al, bh, bl), 3 if quick else 10
        )
        local_rate = n / statistics.median(ldts)
        assert len(lidx) == len(idx)
        log(f"bench[merkle]: routed local diff {local_rate / 1e6:.1f} "
            f"M entries/s")
    log(
        f"bench[merkle]: {log2}-level diff x{reps} in {dt:.3f}s = "
        f"{rate / 1e6:.2f} M entries/s median ({reps * n / dt / 1e6:.2f} "
        f"aggregate; {len(idx)} differing leaves)"
    )
    # divergent-replica reconciliation rate (round-2 verdict missing #2):
    # two logs differing by inserts/deletes/flips, end-to-end through
    # hashing, key-addressed sketches, and the cell-level tree diff
    from dat_replication_protocol_tpu.ops import reconcile

    # full config-5 snapshot scale by default (round-4 verdict #4: 1M+1M;
    # 1.85M records/s at 200k said nothing about slot-table pressure or
    # bucketing at the scale the config names)
    rrows = _env_int("BENCH_RECONCILE_ROWS", 2_000 if quick else 1_000_000)
    keys_a = [b"row-%07d" % i for i in range(rrows)]
    recs_a = [b"value-of:" + k for k in keys_a]
    keys_b = list(keys_a)
    recs_b = list(recs_a)
    rng = np.random.default_rng(5)
    # positions drawn once against the ORIGINAL length (stable spread of
    # inserts across the log; the insert loop itself is O(k·n) memmove —
    # ~0.7 s at 1M, measured, and untimed setup either way)
    pos = sorted((int(p) for p in rng.integers(0, rrows,
                                               max(1, rrows // 1000))),
                 reverse=True)
    for j, p in enumerate(pos):
        keys_b.insert(p, b"new-%d" % j)
        recs_b.insert(p, b"value-of-new-%d" % j)
    log2_slots = max(8, (rrows * 2).bit_length())
    # warm pass pays the jit compiles (same shapes as the timed pass);
    # the timed pass measures the pipeline, not XLA's cold start
    reconcile.reconcile(
        reconcile.LogSummary(recs_a, keys_a, log2_slots),
        reconcile.LogSummary(recs_b, keys_b, log2_slots),
    )
    t0 = time.perf_counter()
    sa = reconcile.LogSummary(recs_a, keys_a, log2_slots)
    sb = reconcile.LogSummary(recs_b, keys_b, log2_slots)
    out = reconcile.reconcile(sa, sb)
    rdt = time.perf_counter() - t0
    rrate = (len(keys_a) + len(keys_b)) / rdt
    log(
        f"bench[merkle]: reconcile {len(keys_a)}+{len(keys_b)} records in "
        f"{rdt:.3f}s = {rrate / 1e6:.2f} M records/s "
        f"({len(out['slots'])} differing cells)"
    )
    return {
        "metric": "merkle_diff_rate",
        "value": round(rate, 0),
        "unit": "entries/s",
        "vs_baseline": round(rate / 10e6, 4),
        "aggregate_entries_s": round(reps * n / dt, 0),
        "peak_entries_s": round(n / _peak_span(rep_dts), 0),
        "fence": _fence_mode(),
        "leaves": n,
        "reduced_config": n < (1 << 20) or (len(keys_a) + len(keys_b)) < 2_000_000,
        "full_config": "2 x 1M leaves; reconcile 1M+1M records "
                       "(BASELINE config 5)",
        "local_diff_entries_s": round(local_rate, 0) if local_rate else None,
        "reconcile_records_s": round(rrate, 0),
        "reconcile_records": len(keys_a) + len(keys_b),
    }


# ---------------------------------------------------------------------------
# config 6: resume latency — checkpoint export -> reconnect -> first
# re-delivered frame (ROBUSTNESS.md's recovery-cost number)
# ---------------------------------------------------------------------------


def bench_resume(quick: bool, backend: str) -> dict:
    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.session.faults import (
        FaultPlan,
        FaultyReader,
        TransportFault,
        bytes_reader,
    )
    from dat_replication_protocol_tpu.session.reconnect import (
        BackoffPolicy,
        run_resumable,
    )
    from dat_replication_protocol_tpu.session.resume import WireJournal

    rows = _env_int("BENCH_RESUME_ROWS", 2_000 if quick else 20_000)
    reps = _env_int("BENCH_RESUME_REPS", 20 if quick else 100)

    enc = protocol.encode()
    journal = WireJournal()
    enc.attach_journal(journal)
    # fleet-plane cursors (ISSUE 11): with --metrics the config's
    # --fleet-snapshot view carries this link's append/acked offsets
    journal.watermark("bench-resume")
    for i in range(rows):
        enc.change({"key": f"key-{i:07d}", "change": i, "from": i,
                    "to": i + 1, "value": b"v" * (i % 48)})
    enc.finalize()
    while enc.read(1 << 18) is not None:
        pass
    wire = journal.read_from(0)
    drop_at = len(wire) // 2

    lat = []

    def one() -> None:
        dec = protocol.decode()
        times = {}

        class TimedReader(FaultyReader):
            def read(self, n):
                try:
                    return super().read(n)
                except TransportFault:
                    times["fault"] = time.perf_counter()
                    raise

        def on_change_after(c, done):
            if "fault" in times and "redeliver" not in times:
                times["redeliver"] = time.perf_counter()
            done()

        dec.change(on_change_after)

        def source(ckpt, failures):
            plan = FaultPlan(
                seed=failures,
                drop_at=(drop_at - ckpt.wire_offset) if failures == 0 else None,
            )
            return TimedReader(bytes_reader(wire[ckpt.wire_offset:]), plan)

        # base=0: measure the machinery, not the (configurable) backoff
        run_resumable(source, dec,
                      BackoffPolicy(base=0.0, max_retries=2, seed=0),
                      chunk_size=1 << 16, expected_total=len(wire),
                      stall_timeout=30)
        assert dec.finished and dec.changes == rows
        lat.append(times["redeliver"] - times["fault"])

    one()  # correctness gate + warmup
    lat.clear()
    t0 = time.perf_counter()
    for _ in range(reps):
        one()
    dt = time.perf_counter() - t0
    lat_ms = sorted(x * 1e3 for x in lat)
    med = statistics.median(lat_ms)
    log(f"bench[resume]: {reps} faulted sessions ({rows} rows) in {dt:.2f}s; "
        f"fault->first-redelivered-frame median {med:.3f} ms "
        f"(p90 {lat_ms[int(0.9 * (len(lat_ms) - 1))]:.3f} ms)")
    return {
        "metric": "resume_latency",
        "value": round(med, 3),
        "unit": "ms",
        "vs_baseline": None,
        "p90_ms": round(lat_ms[int(0.9 * (len(lat_ms) - 1))], 3),
        "rows": rows,
        "wire_bytes": len(wire),
        "sessions_s": round(reps / dt, 1),
    }


# ---------------------------------------------------------------------------
# config 7: wire-level A/B — per-record Change frames vs columnar
# ChangeBatch frames (rows/s both directions + bytes-on-wire; ISSUE 6)
# ---------------------------------------------------------------------------


def bench_wire_batch(quick: bool, backend: str) -> dict:
    import numpy as np

    from dat_replication_protocol_tpu.runtime import native, replay
    from dat_replication_protocol_tpu.wire.change_codec import Change, \
        encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    rows = _env_int("BENCH_WIRE_BATCH_ROWS", 50_000 if quick else 1_000_000)
    batch_rows = _env_int("BENCH_WIRE_BATCH_SIZE", 65_536)
    # the config-2 replay shape: distinct keys within a block, the block
    # repeated to scale — change logs revisit keys, which is exactly
    # what the batch dictionary monetizes
    block_n = min(rows, 4096)
    recs = [
        Change(
            key=f"key-{i:07d}",
            change=i,
            from_=i,
            to=i + 1,
            value=b"v" * (i % 48),
            subset="s" if i % 3 else None,
        )
        for i in range(block_n)
    ]
    block = b"".join(frame(TYPE_CHANGE, encode_change(c)) for c in recs)
    reps = -(-rows // block_n)
    per_record_wire = block * reps
    total_rows = block_n * reps
    cols, _frames = replay.replay_log(
        np.frombuffer(per_record_wire, np.uint8))

    # A: per-record framing — columnar bulk encoder (the incumbent)
    replay.encode_change_columns(replay._slice_columns(cols, 0, 64))  # warm
    t0 = time.perf_counter()
    a_wire = replay.encode_change_columns(cols)
    a_dt = time.perf_counter() - t0
    assert len(a_wire) == len(per_record_wire)

    # B: ChangeBatch framing — same rows, columnar frames
    replay.encode_batch_frames(replay._slice_columns(cols, 0, 64))  # warm
    t0 = time.perf_counter()
    b_wire = replay.encode_batch_frames(cols, rows_per_batch=batch_rows)
    b_dt = time.perf_counter() - t0

    # B decode: whole-log replay of the batch wire (the e2e replay rate)
    b_buf = np.frombuffer(b_wire, np.uint8)
    t0 = time.perf_counter()
    b_cols, _bf = replay.replay_log(b_buf)
    bd_dt = time.perf_counter() - t0
    assert len(b_cols) == total_rows
    assert b_cols.row(0).to_dict() == cols.row(0).to_dict()
    assert b_cols.row(total_rows - 1).to_dict() == \
        cols.row(total_rows - 1).to_dict()

    # A decode, for the same-shape comparison
    t0 = time.perf_counter()
    a_cols, _af = replay.replay_log(np.frombuffer(per_record_wire, np.uint8))
    ad_dt = time.perf_counter() - t0
    assert len(a_cols) == total_rows

    ratio = len(b_wire) / len(per_record_wire)
    # wire cost plane (ISSUE 20): the batch wire replayed through a lit
    # session decoder — goodput/overhead of the bytes the A/B actually
    # compares, off the board's own ledger
    goodput, overhead = _wirecost_decode_ratios(b_wire)
    log(
        f"bench[wire_batch]: {total_rows} rows — encode "
        f"{total_rows / a_dt:,.0f} rows/s per-record vs "
        f"{total_rows / b_dt:,.0f} rows/s batch ({a_dt / b_dt:.1f}x); "
        f"decode {total_rows / ad_dt:,.0f} vs {total_rows / bd_dt:,.0f} "
        f"rows/s; wire {len(per_record_wire)} -> {len(b_wire)} bytes "
        f"({(1 - ratio) * 100:.1f}% smaller)"
    )
    return {
        "metric": "wire_batch_encode_rate",
        "value": round(total_rows / b_dt, 0),
        "unit": "rows/s",
        "vs_baseline": None,
        "native": native.available(),
        "rows": total_rows,
        "reduced_config": total_rows < 1_000_000,
        "full_config": "1M rows (config-2 shape), 64Ki-row batches",
        "batch_rows_per_frame": batch_rows,
        "per_record_encode_rows_s": round(total_rows / a_dt, 0),
        "per_record_decode_rows_s": round(total_rows / ad_dt, 0),
        "batch_decode_rows_s": round(total_rows / bd_dt, 0),
        "per_record_bytes": len(per_record_wire),
        "batch_bytes": len(b_wire),
        "bytes_ratio": round(ratio, 4),
        "goodput_ratio": goodput,
        "overhead_ratio": overhead,
    }


# ---------------------------------------------------------------------------
# config 8: single-pass content addressing A/B — the fused1p route vs the
# two-pass route, bytes -> digests end to end (ISSUE 7)
# ---------------------------------------------------------------------------


def bench_fused_e2e(quick: bool, backend: str) -> dict:
    import numpy as np

    from dat_replication_protocol_tpu.backend.tpu_backend import (
        _host_hash_batch,
    )
    from dat_replication_protocol_tpu.ops.rabin import chunk_stream
    from dat_replication_protocol_tpu.runtime import native
    from dat_replication_protocol_tpu.runtime.content import content_digests

    mib = _env_int("BENCH_FUSED_MIB", 32 if quick else 256)
    reps = _env_int("BENCH_FUSED_REPS", 2 if quick else 3)
    buf = np.random.default_rng(11).integers(0, 256, mib << 20,
                                             dtype=np.uint8)
    n = buf.nbytes

    # pin the HOST engines for the host-group A/B: on an accelerator-
    # backed box the routing layer would otherwise send both routes to
    # the device pipeline and the host comparison would mislabel what
    # ran.  Restored before the (opt-in) device leg below.
    saved_env = {k: os.environ.get(k)
                 for k in ("DAT_DEVICE_CDC", "DAT_DEVICE_HASH")}
    os.environ["DAT_DEVICE_CDC"] = "0"
    os.environ["DAT_DEVICE_HASH"] = "0"
    try:
        out = _bench_fused_e2e_pinned(quick, buf, n, mib, reps)
    finally:
        # restore even when a correctness gate raises: run_config catches
        # the exception and the rest of the bench (the device leg
        # included) must not silently route to host engines
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return _bench_fused_e2e_device_leg(quick, out)


def _bench_fused_e2e_pinned(quick: bool, buf, n: int, mib: int,
                            reps: int) -> dict:
    from dat_replication_protocol_tpu.backend.tpu_backend import (
        _host_hash_batch,
    )
    from dat_replication_protocol_tpu.ops.rabin import chunk_stream
    from dat_replication_protocol_tpu.runtime import native
    from dat_replication_protocol_tpu.runtime.content import content_digests

    # A: the TWO-PASS route — the incumbent bytes->digests composition a
    # session pays today: the gear scan streams every byte once for the
    # cuts, then every chunk is sliced into a payload object and re-read
    # by the routed host digest engine (exactly what a DigestPipeline
    # submit stream does).  Blob bytes cross memory twice, plus a
    # payload materialization per chunk.
    def two_pass():
        cuts = chunk_stream(buf)
        payloads = [buf[a:b].tobytes()
                    for a, b in zip([0] + cuts[:-1], cuts)]
        return cuts, _host_hash_batch(payloads)

    # B: the FUSED single-pass route — cuts and digests in one sweep
    # (native dat_cdc_hash via content_digests' fused1p routing)
    def fused():
        return content_digests(buf, route="fused1p")

    # correctness gate: both routes must produce identical cuts+digests
    # (the fuzz suite pins this; the bench re-checks the exact shapes it
    # times so an artifact can never record a miscutting win)
    cuts_a, digs_a = two_pass()
    cuts_f, digs_f = fused()
    assert list(cuts_a) == list(cuts_f), "route cut divergence"
    assert all(bytes(digs_f[i]) == digs_a[i] for i in
               range(0, len(cuts_f), max(1, len(cuts_f) // 64)))

    # min-of-reps (best rep) on BOTH sides, with the sides INTERLEAVED
    # A,B,A,B,...: the box is shared, and measuring one whole side then
    # the other lets a steal/scheduling drift spanning one side's reps
    # bias the RATIO — interleaving makes drift hit both sides alike,
    # and the min still discards isolated spikes
    tps, fus, t2s = [], [], []
    for _ in range(reps):
        tps.extend(_timed_reps(lambda: two_pass(), 1))
        fus.extend(_timed_reps(lambda: fused(), 1))
        # diagnostic: the strong two-pass (native extents, no per-chunk
        # payload slicing — the content_digests(route="2p") engine this
        # PR also adds); fusion's margin over IT isolates the
        # single-sweep win from the slicing win
        t2s.extend(_timed_reps(
            lambda: content_digests(buf, route="2p"), 1))
    tp, fu, t2 = min(tps), min(fus), min(t2s)
    fused_gib = n / fu / (1 << 30)
    two_gib = n / tp / (1 << 30)
    ratio = fused_gib / two_gib
    log(f"bench[fused_e2e]: {mib} MiB x{reps} — fused1p {fused_gib:.2f} "
        f"GiB/s vs two-pass {two_gib:.2f} GiB/s ({ratio:.2f}x; "
        f"extents two-pass {n / t2 / (1 << 30):.2f})")

    out = {
        "metric": "fused_e2e_throughput",
        "value": round(fused_gib, 3),
        "unit": "GiB/s",
        "vs_baseline": None,
        "native": native.available(),
        "volume_mib": mib,
        "reps": reps,
        "reduced_config": n < (2 << 30),
        "full_config": "2 GiB bytes->digests, min-of-reps",
        "chunks": len(cuts_f),
        "two_pass_gib_s": round(two_gib, 3),
        "two_pass_extents_gib_s": round(n / t2 / (1 << 30), 3),
        "fused_vs_two_pass": round(ratio, 3),
    }

    return out


def _bench_fused_e2e_device_leg(quick: bool, out: dict) -> dict:
    """The opt-in device-group A/B (armed for the next TPU window via
    _when_tpu_returns.sh): the single-residency device pipeline vs the
    two-pass host-repack composition, same A/B discipline.  Runs OUTSIDE
    the host-engine env pin (the routing must be free) and initializes
    jax, which the host leg must never do."""
    import numpy as np

    from dat_replication_protocol_tpu.ops.rabin import chunk_stream

    reps = _env_int("BENCH_FUSED_REPS", 2 if quick else 3)
    if os.environ.get("BENCH_FUSED_DEVICE") == "1":
        import jax

        from dat_replication_protocol_tpu.batch.feed import hash_extents
        from dat_replication_protocol_tpu.ops.fused_cdc_hash_pallas import (
            content_begin,
        )

        dmib = _env_int("BENCH_FUSED_DEVICE_MIB", 64 if quick else 1024)
        dbuf = np.random.default_rng(12).integers(0, 256, dmib << 20,
                                                  dtype=np.uint8)

        def dev_fused():
            cuts, hh, hl = content_begin(dbuf)()
            np.asarray(hh[:1, :1])  # completion fence
            return cuts

        def dev_two_pass():
            cuts = chunk_stream(dbuf)
            ends = np.asarray(cuts, np.int64)
            offs = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
            hash_extents(dbuf, offs, ends - offs)
            return cuts

        assert list(dev_fused()) == list(dev_two_pass())  # warm + gate
        df = min(_timed_reps(lambda: dev_fused(), reps))
        dt2 = min(_timed_reps(lambda: dev_two_pass(), reps))
        out["device_fused_gib_s"] = round(dbuf.nbytes / df / (1 << 30), 3)
        out["device_two_pass_gib_s"] = round(
            dbuf.nbytes / dt2 / (1 << 30), 3)
        out["device_volume_mib"] = dmib
        out["device_backend"] = jax.default_backend()
        log(f"bench[fused_e2e]: device leg fused "
            f"{out['device_fused_gib_s']} vs two-pass "
            f"{out['device_two_pass_gib_s']} GiB/s "
            f"({jax.default_backend()})")
    return out


# ---------------------------------------------------------------------------
# config 9: multi-session hub soak — N concurrent sessions multiplexed
# onto ONE shared ReplicationHub/DigestPipeline (ISSUE 8).  Headline is
# aggregate decode+digest GiB/s; fairness is min/median per-session
# throughput (weighted-fair batching should hold it near 1.0 — a value
# near 0 means one session starved, the regression the gate watches).
# ---------------------------------------------------------------------------


def bench_hub_soak(quick: bool, backend: str) -> dict:
    import threading

    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.hub import ReplicationHub

    sessions = _env_int("BENCH_HUB_SESSIONS", 8 if quick else 16)
    rows = _env_int("BENCH_HUB_ROWS", 2_048 if quick else 16_384)
    blob_kib = _env_int("BENCH_HUB_BLOB_KIB", 256 if quick else 2_048)
    # BENCH_HUB_MESH=auto|N (ROADMAP item 1 device leg): shard the
    # cross-session hash batch over the device mesh — the
    # `--hub-mesh auto` capture _when_tpu_returns.sh arms; on a host
    # backend the factory falls back to the single-engine path
    mesh = os.environ.get("BENCH_HUB_MESH") or None
    if mesh is not None and mesh != "auto":
        mesh = int(mesh)

    # per-session wires built untimed: a bulk change run (the native
    # bulk decode path) plus one blob, distinct keys per session
    wires = []
    for i in range(sessions):
        e = protocol.encode()
        e.change_many([
            {"key": f"s{i}-{j:06d}", "change": j, "from": j, "to": j + 1,
             "value": b"v" * 64}
            for j in range(rows)
        ])
        b = e.blob(blob_kib << 10)
        b.write(bytes(blob_kib << 10))
        b.end()
        e.finalize()
        parts = []
        while True:
            d = e.read(1 << 20)
            if d is None:
                break
            parts.append(d)
        wires.append(b"".join(parts))
    total_bytes = sum(len(w) for w in wires)

    hub = ReplicationHub(mesh=mesh, linger_s=0.002, window_items=1 << 16,
                         window_bytes=64 << 20, parked_budget=1 << 30,
                         max_sessions=sessions + 1)
    done = [None] * sessions
    start_gate = threading.Event()

    def run_one(i: int) -> None:
        start_gate.wait(30)
        t0 = time.perf_counter()
        s = hub.register(f"s{i}")
        dec = protocol.decode(backend="tpu", pipeline=s)
        n = {"d": 0}
        dec.on_digest(lambda kind, seq, d: n.__setitem__("d", n["d"] + 1))
        wire = wires[i]
        step = 1 << 18
        for off in range(0, len(wire), step):
            dec.write(wire[off:off + step])
        dec.end()
        assert dec.finished
        s.close()
        done[i] = (time.perf_counter() - t0, n["d"])

    threads = [threading.Thread(target=run_one, args=(i,), daemon=True)
               for i in range(sessions)]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    start_gate.set()
    for t in threads:
        t.join(600)
    wall = time.perf_counter() - t0
    hub.close()
    assert all(d is not None for d in done), "hub soak session hung"
    digests = sum(d[1] for d in done)
    assert digests == sessions * (rows + 1)

    per_tput = [len(wires[i]) / done[i][0] for i in range(sessions)]
    ordered = sorted(per_tput)
    median = ordered[sessions // 2]
    fairness = (ordered[0] / median) if median > 0 else 0.0
    agg = total_bytes / wall / (1 << 30)
    log(f"bench[hub_soak]: {sessions} sessions x ({rows} rows + "
        f"{blob_kib} KiB blob) — aggregate {agg:.3f} GiB/s, fairness "
        f"min/median {fairness:.2f}, {digests} digests")
    return {
        "metric": "hub_soak_aggregate_throughput",
        "value": round(agg, 3),
        "unit": "GiB/s",
        "vs_baseline": None,
        "sessions": sessions,
        "rows_per_session": rows,
        "blob_kib": blob_kib,
        "total_mib": round(total_bytes / (1 << 20), 1),
        "digests": digests,
        "fairness_min_median": round(fairness, 3),
        "session_gib_s_min": round(ordered[0] / (1 << 30), 4),
        "session_gib_s_median": round(median / (1 << 30), 4),
        "mesh": mesh,
        "reduced_config": sessions < 16 or rows < 16_384,
        "full_config": "16 sessions x (16384 rows + 2 MiB blob) on one "
                       "shared hub",
    }


def bench_fanout(quick: bool, backend: str) -> dict:
    """Config 10 (ISSUE 9): one-to-many fan-out — hash once, serve N.

    One wire session is decoded (digested) EXACTLY ONCE while N
    downstream peers receive its bytes through the BroadcastLog /
    FanoutServer windowed scatter-gather path.  Three proofs in one
    artifact:

    * **peers x MiB/s matrix** — aggregate delivered throughput must
      SCALE with peer count (per-peer marginal cost is a windowed
      writev of already-framed bytes, not a re-hash + re-copy);
    * **hash-once** — the digest-work byte counters
      (device.native.hash.bytes / device.submit.bytes / device.h2d.
      bytes) stay CONSTANT as peers grow (``hash_ratio`` ~ 1.0);
    * **stall isolation** — one peer stalled for ``stall_s`` seconds
      mid-wire leaves the other peers' p99 append->delivery frame
      latency flat (``stalled_arm_p99_ms``), budget-gated.

    Peers are accounting-only sinks (accept-everything, zero copies) —
    the library-level fan-out capacity; the fd/writev kernel path is
    exercised by the unit/chaos suites and the sidecar.
    """
    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.fanout import FanoutServer
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    rows = _env_int("BENCH_FANOUT_ROWS", 2_048 if quick else 16_384)
    blob_kib = _env_int("BENCH_FANOUT_BLOB_KIB", 256 if quick else 2_048)
    peer_counts = [
        int(x) for x in os.environ.get(
            "BENCH_FANOUT_PEERS",
            "1,8,32" if quick else "1,8,64,256").split(",") if x.strip()
    ]
    stall_s = float(os.environ.get("BENCH_FANOUT_STALL_S",
                                   "0.5" if quick else "3.0"))

    # the source wire, built untimed
    e = protocol.encode()
    e.change_many([
        {"key": f"f-{j:06d}", "change": j, "from": j, "to": j + 1,
         "value": b"v" * 64}
        for j in range(rows)
    ])
    b = e.blob(blob_kib << 10)
    b.write(bytes(blob_kib << 10))
    b.end()
    e.finalize()
    parts = []
    while True:
        d = e.read(1 << 20)
        if d is None:
            break
        parts.append(d)
    wire = b"".join(parts)
    step = 1 << 18

    # the hash-once proof reads obs counters: enable telemetry for this
    # config (conftest-style save/restore; the overhead rides both
    # sides of the matrix equally)
    _DIGEST_COUNTERS = ("device.native.hash.bytes", "device.submit.bytes",
                        "device.h2d.bytes")

    def _digest_work() -> int:
        snap = obs_metrics.snapshot()["counters"]
        return sum(int(snap.get(k, 0)) for k in _DIGEST_COUNTERS)

    def _count_sink():
        # accounting-only consumer: accepts every view, copies nothing
        return lambda views: sum(len(v) for v in views)

    was_on = obs_metrics.OBS.on
    obs_metrics.enable()
    from dat_replication_protocol_tpu.obs.wirecost import WIRECOST
    WIRECOST.reset_for_tests()
    try:
        matrix: dict = {}
        p99_by_n: dict = {}
        hash_by_n: dict = {}
        for n in peer_counts:
            srv = FanoutServer(retention_budget=len(wire) + (1 << 20),
                               stall_timeout=60.0)
            try:
                peers = [srv.attach_peer(f"p{i}", sink=_count_sink())
                         for i in range(n)]
                dec = protocol.decode(backend="tpu")
                ndig = {"d": 0}
                dec.on_digest(
                    lambda kind, seq, d: ndig.__setitem__("d",
                                                          ndig["d"] + 1))
                h0 = _digest_work()
                t0 = time.perf_counter()
                for off in range(0, len(wire), step):
                    chunk = wire[off:off + step]
                    srv.publish(chunk)   # fan-out: bytes only
                    dec.write(chunk)     # digest work: exactly once
                dec.end()
                srv.seal()
                assert srv.drain(120), "fan-out drain hung"
                wall = time.perf_counter() - t0
                hash_by_n[str(n)] = _digest_work() - h0
                assert dec.finished and ndig["d"] == rows + 1
                stats = [p.stats() for p in peers]
                assert all(st["done"] and st["sent_bytes"] == len(wire)
                           for st in stats)
                matrix[str(n)] = round(
                    n * len(wire) / wall / (1 << 20), 1)
                p99s = [st["lat_p99_ms"] for st in stats
                        if st["lat_p99_ms"] is not None]
                p99_by_n[str(n)] = max(p99s) if p99s else None
            finally:
                srv.close()
            log(f"bench[fanout]: {n} peers — {matrix[str(n)]} MiB/s "
                f"aggregate, p99 {p99_by_n[str(n)]} ms, digest-work "
                f"{hash_by_n[str(n)]} bytes")

        hash_vals = [v for v in hash_by_n.values() if v > 0]
        hash_ratio = (round(max(hash_vals) / min(hash_vals), 4)
                      if hash_vals else None)
        # wire cost plane (ISSUE 20): the matrix ran lit, so the board's
        # session ledger already holds the digest leg's wire — read the
        # goodput/overhead watermarks straight off it
        goodput, overhead = _wirecost_ratios("session")

        # stalled-peer arm: one of 8 peers stops accepting for stall_s
        # seconds at the half-way byte (below the shed timeout — it
        # lags, bounded by its window, and must not move the others'
        # p99)
        n_stall = 8
        srv = FanoutServer(retention_budget=len(wire) + (1 << 20),
                           stall_timeout=max(60.0, stall_s * 4))
        try:
            gate = {"t": None}
            stalled_got = {"n": 0}

            def stall_sink(views):
                if gate["t"] is None:
                    gate["t"] = time.perf_counter() + stall_s
                if time.perf_counter() < gate["t"]:
                    budget = len(wire) // 2 - stalled_got["n"]
                    if budget <= 0:
                        return 0
                else:
                    budget = 1 << 60
                take = 0
                for v in views:
                    take += min(len(v), budget - take)
                    if take >= budget:
                        break
                stalled_got["n"] += take
                return take

            staller = srv.attach_peer("staller", sink=stall_sink)
            healthy = [srv.attach_peer(f"h{i}", sink=_count_sink())
                       for i in range(n_stall - 1)]
            for off in range(0, len(wire), step):
                srv.publish(wire[off:off + step])
            srv.seal()
            assert srv.drain(120 + stall_s), "stalled arm drain hung"
            h_stats = [p.stats() for p in healthy]
            assert all(st["done"] and st["sent_bytes"] == len(wire)
                       for st in h_stats)
            st_stall = staller.stats()
            assert st_stall["done"] and st_stall["shed"] is None
            stalled_p99 = max(st["lat_p99_ms"] for st in h_stats
                              if st["lat_p99_ms"] is not None)
        finally:
            srv.close()
        log(f"bench[fanout]: stalled arm ({stall_s}s) — healthy p99 "
            f"{stalled_p99} ms")
    finally:
        WIRECOST.reset_for_tests()
        obs_metrics.OBS.on = was_on

    top = str(max(peer_counts))
    return {
        "metric": "fanout_aggregate_delivered_throughput",
        "value": matrix[top],
        "unit": "MiB/s",
        "vs_baseline": None,
        "peers": int(top),
        "wire_mib": round(len(wire) / (1 << 20), 2),
        "rows": rows,
        "blob_kib": blob_kib,
        "peers_mib_s": matrix,
        "p99_ms": p99_by_n,
        "digest_work_bytes": hash_by_n,
        "hash_ratio": hash_ratio,
        "stall_s": stall_s,
        "stalled_arm_p99_ms": stalled_p99,
        "goodput_ratio": goodput,
        "overhead_ratio": overhead,
        "reduced_config": rows < 16_384 or int(top) < 256,
        "full_config": "1/8/64/256 peers x (16384 rows + 2 MiB blob), "
                       "3 s stalled-peer arm",
    }


# ---------------------------------------------------------------------------
# config 11: rateless coded-symbol reconciliation A/B — wire bytes and
# wall-clock vs the sketch-table exchange and the tree-guided descent
# at k ∈ {10, 1000, 100000} on 1M+1M divergent replicas (ISSUE 10)
# ---------------------------------------------------------------------------


def bench_reconcile_rateless(quick: bool, backend: str) -> dict:
    """Config 11 (ISSUE 10): three anti-entropy protocols reconciling
    the same two divergent change logs (n records each, symmetric
    difference k), each billed its REAL wire bytes and wall clock:

    * **rateless** (the new path): coded-symbol stream + peeling decode
      + ChangeBatch record exchange (`runtime/reconcile_driver.py`) —
      O(k) wire, no estimate of k;
    * **sketch** (the incumbent): `ops/reconcile.LogSummary` tables
      exchanged whole (O(nslots) wire) + differing-slot record exchange
      (collision overhead included);
    * **tree** (the remote refinement): the same sketch tables walked
      via the `tree_sync` descent (O(diff · log n) wire in log n round
      trips) — levels folded on the HOST engine so this config never
      initializes a device backend (`import jax` alone is the descent
      helper's only jax exposure).

    The acceptance claims ride the MIDDLE k arm (k=1000 at full
    config): rateless wire <= 5% of the sketch exchange, and rateless
    end-to-end wall-clock beats the sketch path.  The k=100000 arm
    documents the crossover honestly — when the diff stops being
    small, the O(n) table pass wins wall-clock while rateless still
    wins wire.
    """
    import numpy as np

    from dat_replication_protocol_tpu.ops import reconcile
    from dat_replication_protocol_tpu.runtime import native, replay
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        _batch_wire_len,
        _select_rows,
        reconcile_local,
    )
    from dat_replication_protocol_tpu.runtime.tree_sync import (
        TreeSyncSession,
        sync,
    )

    n = _env_int("BENCH_RECONCILE_N", 20_000 if quick else 1_000_000)
    ks = [int(x) for x in os.environ.get(
        "BENCH_RECONCILE_KS",
        "10,100" if quick else "10,1000,100000").split(",") if x.strip()]
    ks = [k for k in ks if 2 <= k <= n // 2]
    kmax = max(ks)

    # synthetic change log, columnar from the start (no per-record
    # Python): fixed-width keys/values, every record unique.  A is rows
    # [0, n); the k-arm's B is rows [k//2, n + k - k//2) — k//2 records
    # only in A, k - k//2 only in B, everything else shared.
    total = n + (kmax - kmax // 2)
    key_w, val_w = 10, 16
    key_heap = b"".join(b"r-%08d" % i for i in range(total))
    val_heap = b"".join(b"value-of-%07x" % (i & 0xFFFFFFF)
                        for i in range(total))
    assert len(val_heap) == val_w * total
    buf = np.frombuffer(key_heap + val_heap, np.uint8)
    ar = np.arange(total, dtype=np.int64)
    cols = replay.ChangeColumns(
        buf=buf,
        change=(ar & 0xFFFFFFFF).astype(np.uint32),
        from_=(ar & 0xFFFFFFFF).astype(np.uint32),
        to=((ar + 1) & 0xFFFFFFFF).astype(np.uint32),
        key_off=ar * key_w,
        key_len=np.full(total, key_w, np.int64),
        sub_off=np.zeros(total, np.int64),
        sub_len=np.full(total, -1, np.int64),
        val_off=len(key_heap) + ar * val_w,
        val_len=np.full(total, val_w, np.int64),
    )
    # sketch-path inputs, materialized untimed (its API takes lists):
    # canonical payload bytes + key bytes per record
    payloads = replay.canonical_change_payloads(cols)
    keys_list = [key_heap[i * key_w:(i + 1) * key_w]
                 for i in range(total)]
    log2_slots = max(8, (n * 2).bit_length())
    nslots = 1 << log2_slots

    def _table_levels(table):
        """Host-engine merkle levels over sketch-table cells (cells are
        digest-shaped; ops/reconcile.table_leaves' layout in numpy)."""
        level = np.ascontiguousarray(table).view(np.uint8).reshape(-1, 32)
        raws = [level]
        while len(level) > 1:
            half = len(level) // 2
            offs = np.arange(half, dtype=np.int64) * 64
            lens = np.full(half, 64, np.int64)
            level = native.hash_many_fallback(level.reshape(-1), offs, lens)
            raws.append(level)
        hh, hl = [], []
        for raw in raws:
            w = raw.view("<u4").reshape(-1, 8)
            hl.append(np.ascontiguousarray(w[:, 0::2]))
            hh.append(np.ascontiguousarray(w[:, 1::2]))
        return hh, hl

    arms = {}
    for k in ks:
        ka, kb = k // 2, k - k // 2
        a_cols = replay._slice_columns(cols, 0, n)
        b_cols = replay._slice_columns(cols, ka, n + kb)

        # --- rateless: digests + symbol stream + peel + records, e2e
        t0 = time.perf_counter()
        out = reconcile_local(RatelessReplica(a_cols),
                              RatelessReplica(b_cols))
        rl_wall = time.perf_counter() - t0
        assert len(out["a_rows"]) == ka and len(out["b_rows"]) == kb
        rl_wire = out["wire_bytes"]

        # --- sketch: summaries + whole-table exchange + slot bucketing
        t0 = time.perf_counter()
        sa = reconcile.LogSummary(payloads[:n], keys_list[:n], log2_slots)
        sb = reconcile.LogSummary(payloads[ka:n + kb],
                                  keys_list[ka:n + kb], log2_slots)
        sum_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        slots = reconcile.diff_sketches(sa.table, sb.table)
        rows_a = np.nonzero(np.isin(sa.slots, slots))[0]
        rows_b = np.nonzero(np.isin(sb.slots, slots))[0]
        rec_wire = (_batch_wire_len(_select_rows(a_cols, rows_a))
                    + _batch_wire_len(_select_rows(b_cols, rows_b)))
        sk_wall = sum_wall + time.perf_counter() - t0
        sk_wire = nslots * 32 + len(slots) * 8 + rec_wire

        # --- tree-guided descent over the same tables (reuses the
        # summaries: its e2e wall = summary build + levels + descent)
        t0 = time.perf_counter()
        ta = TreeSyncSession(*_table_levels(sa.table))
        tb = TreeSyncSession(*_table_levels(sb.table))
        transcript = []
        tslots = sync(ta, tb, transcript)
        rows_a = np.nonzero(np.isin(sa.slots, tslots))[0]
        rows_b = np.nonzero(np.isin(sb.slots, tslots))[0]
        tr_rec = (_batch_wire_len(_select_rows(a_cols, rows_a))
                  + _batch_wire_len(_select_rows(b_cols, rows_b)))
        tr_wall = sum_wall + time.perf_counter() - t0
        tr_wire = sum(nb for _, nb in transcript) + tr_rec
        assert sorted(tslots) == sorted(slots.tolist())

        arms[str(k)] = {
            "rateless_wall_s": round(rl_wall, 3),
            "rateless_wire": rl_wire,
            "rateless_symbols": out["symbols"],
            "rateless_rounds": out["rounds"],
            "sketch_wall_s": round(sk_wall, 3),
            "sketch_wire": sk_wire,
            "tree_wall_s": round(tr_wall, 3),
            "tree_wire": tr_wire,
            "wire_ratio_vs_sketch": round(rl_wire / sk_wire, 5),
            "speedup_vs_sketch": round(sk_wall / rl_wall, 3),
        }
        log(f"bench[reconcile_rateless]: k={k} — rateless "
            f"{rl_wire} B / {rl_wall:.2f}s ({out['symbols']} symbols, "
            f"{out['rounds']} rounds) vs sketch {sk_wire} B / "
            f"{sk_wall:.2f}s vs tree {tr_wire} B / {tr_wall:.2f}s")

    # wire cost plane leg (ISSUE 20): one LIT two-replica exchange at a
    # scaled shape (same fixed-width key/value records) prices the
    # reconcile wire's framing overhead on the board's own ledger —
    # symbols + repair batches per direction, transport-tiled
    from dat_replication_protocol_tpu.cluster import (
        ReplicaNode,
        gossip_exchange,
    )
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics
    from dat_replication_protocol_tpu.obs.wirecost import WIRECOST

    n_cost = min(n, 4096)
    k_cost = max(2, min(128, n_cost // 8))
    ka_c, kb_c = k_cost // 2, k_cost - k_cost // 2

    def _cost_recs(lo: int, hi: int) -> list:
        return [{"key": "r-%08d" % i, "change": i, "from": i, "to": i + 1,
                 "value": b"value-of-%07x" % (i & 0xFFFFFFF)}
                for i in range(lo, hi)]

    was_on = obs_metrics.OBS.on
    obs_metrics.enable()
    WIRECOST.reset_for_tests()
    try:
        ra = ReplicaNode("a", _cost_recs(0, n_cost))
        rb = ReplicaNode("b", _cost_recs(ka_c, n_cost + kb_c))
        gossip_exchange(ra, rb)
        goodput, overhead = _wirecost_ratios()
    finally:
        WIRECOST.reset_for_tests()
        obs_metrics.OBS.on = was_on

    mid = str(ks[min(1, len(ks) - 1)])
    m = arms[mid]
    return {
        "metric": "reconcile_rateless_rate",
        "value": round(2 * n / m["rateless_wall_s"], 0),
        "unit": "records/s",
        "vs_baseline": None,
        "native": native.available(),
        "n": n,
        "ks": ks,
        "mid_k": int(mid),
        "arms": arms,
        "wire_ratio_mid": m["wire_ratio_vs_sketch"],
        "speedup_vs_sketch_mid": m["speedup_vs_sketch"],
        "goodput_ratio": goodput,
        "overhead_ratio": overhead,
        "reduced_config": n < 1_000_000,
        "full_config": "1M+1M replicas, k in {10, 1000, 100000}",
    }


# ---------------------------------------------------------------------------
# config 12: content-addressed snapshot bootstrap — stale-joiner wire
# scales with staleness, a cold flash crowd shares one hash pass, and
# mid-snapshot resume is exactly-once (ISSUE 12)
# ---------------------------------------------------------------------------


def bench_snapshot_bootstrap(quick: bool, backend: str) -> dict:
    """Config 12 (ISSUE 12): the snapshot bootstrap's three claims,
    each measured on the real protocol with exact wire metering:

    * **stale arm** — a joiner whose dataset diverges in ~2% of its
      CDC chunks reconciles its chunk set (weighted rateless symbols)
      and moves <= 5% of the cold full-transfer bytes: bytes-on-wire
      scale with STALENESS, not dataset size;
    * **cold flash crowd** — N joiners bootstrap the same manifest and
      the source's digest-work counters stay flat (``hash_ratio`` 1.0):
      the dataset is hashed once at materialize, the shared cold log is
      framed once, every session is served zero-copy slices;
    * **chaos arm** — a recorded joiner wire is torn mid-CHUNKS-frame
      and resumed through the reconnect driver: the assembled dataset
      is byte-exact and every chunk verified EXACTLY once.

    Host-group: the protocol core is numpy + native; no device backend
    is initialized (the TPU watch script drives the device legs).
    """
    import numpy as np

    from dat_replication_protocol_tpu.obs import metrics as obs_metrics
    from dat_replication_protocol_tpu.runtime.snapshot_driver import (
        SnapshotSource,
        snapshot_local,
    )

    mib = _env_int("BENCH_SNAPSHOT_MIB", 8 if quick else 1024)
    joiners = _env_int("BENCH_SNAPSHOT_JOINERS", 8)
    stale_frac = float(os.environ.get("BENCH_SNAPSHOT_STALE", "0.02"))

    rng = np.random.default_rng(12)
    data = rng.integers(0, 256, mib << 20, dtype=np.uint8)

    # SOURCE digest-work counters: dataset bytes through the fused
    # chunk-hash pass (host route) or shipped to the device (device
    # route).  device.native.hash.bytes is deliberately excluded — the
    # joiners' own merkle-root verification (32 B/chunk, per session BY
    # DESIGN) rides it and would read as false source work.
    _DIGEST_COUNTERS = ("cdc.fused.bytes",
                        "device.submit.bytes", "device.h2d.bytes")

    def _digest_work() -> int:
        snap = obs_metrics.snapshot()["counters"]
        return sum(int(snap.get(k, 0)) for k in _DIGEST_COUNTERS)

    was_on = obs_metrics.OBS.on
    obs_metrics.enable()
    try:
        h0 = _digest_work()
        t0 = time.perf_counter()
        src = SnapshotSource(data)  # ONE hash+read pass, counted
        mat_wall = time.perf_counter() - t0
        hash_once = _digest_work() - h0

        # -- stale arm: 2% of chunks diverge ------------------------------
        n_chunks = len(src.offs)
        pick = rng.choice(n_chunks, size=max(1, int(n_chunks * stale_frac)),
                          replace=False)
        stale = data.copy()
        stale[src.offs[pick]] ^= 0x5A
        t0 = time.perf_counter()
        out = snapshot_local(src, stale.tobytes())
        stale_wall = time.perf_counter() - t0
        assert out["data"] == data.tobytes()
        stale_wire = out["wire_bytes"]
        del stale

        # -- cold flash crowd: N joiners, one hash pass --------------------
        h1 = _digest_work()
        t0 = time.perf_counter()
        cold_wire = None
        for _ in range(joiners):
            cold = snapshot_local(src, None)
            assert cold["data"] == data.tobytes()
            cold_wire = cold["wire_bytes"]
        crowd_wall = time.perf_counter() - t0
        crowd_hash = _digest_work() - h1
        hash_ratio = (hash_once + crowd_hash) / max(1, hash_once)

        # -- chaos arm: torn mid-chunk, resumed exactly-once ---------------
        # the arm records a REAL session snapshot wire with the plane
        # already lit: reset the board first so its tx ledger holds
        # exactly that wire's goodput/overhead (ISSUE 20)
        from dat_replication_protocol_tpu.obs.wirecost import WIRECOST
        WIRECOST.reset_for_tests()
        chaos = _snapshot_chaos_arm(src, data)
        goodput, overhead = _wirecost_ratios("session")
        WIRECOST.reset_for_tests()
    finally:
        obs_metrics.OBS.on = was_on

    ratio = stale_wire / max(1, cold_wire)
    log(f"bench[snapshot_bootstrap]: {mib} MiB, {n_chunks} chunks — "
        f"stale({stale_frac:.0%}) {stale_wire} B vs cold {cold_wire} B "
        f"(ratio {ratio:.4f}); crowd x{joiners} hash_ratio "
        f"{hash_ratio:.3f}; chaos {chaos}")
    return {
        "metric": "snapshot_bootstrap_stale_wire_ratio",
        "value": round(ratio, 5),
        "unit": "ratio",
        "vs_baseline": None,
        "dataset_mib": mib,
        "chunks": n_chunks,
        "stale_frac": stale_frac,
        "stale_wire_bytes": stale_wire,
        "cold_wire_bytes": cold_wire,
        "stale_wall_s": round(stale_wall, 3),
        "materialize_wall_s": round(mat_wall, 3),
        "chunks_reused": out["chunks_reused"],
        "symbols": out["symbols"],
        "joiners": joiners,
        "crowd_wall_s": round(crowd_wall, 3),
        "crowd_mib_s": round(joiners * mib / max(crowd_wall, 1e-9), 1),
        "hash_once_bytes": hash_once,
        "crowd_hash_bytes": crowd_hash,
        "hash_ratio": round(hash_ratio, 4),
        "chaos": chaos,
        "goodput_ratio": goodput,
        "overhead_ratio": overhead,
        "reduced_config": mib < 1024,
        "full_config": "1 GiB dataset, 2% stale chunks, 8-joiner cold "
                       "crowd, torn-wire resume",
    }


def _snapshot_chaos_arm(src, data) -> dict:
    """Record one stale-joiner wire, tear it inside the first CHUNKS
    frame, resume through the reconnect driver, and prove exactly-once:
    byte-exact assembly, every wanted chunk verified once."""
    import numpy as np

    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.runtime.snapshot_driver import (
        SnapshotJoiner,
        SnapshotResponder,
    )
    from dat_replication_protocol_tpu.session.faults import (
        FaultPlan,
        FaultyReader,
        bytes_reader,
    )
    from dat_replication_protocol_tpu.session.reconnect import (
        BackoffPolicy,
        run_resumable,
    )
    from dat_replication_protocol_tpu.session.resume import WireJournal
    from dat_replication_protocol_tpu.wire import snapshot_codec as sn
    from dat_replication_protocol_tpu.wire.framing import (
        CAP_SNAPSHOT,
        iter_frames,
    )

    # the chaos dataset is a small window of the bench dataset: the
    # exactly-once contract is size-independent and the recorded wire
    # replays byte-at-a-time territory
    chaos_data = np.ascontiguousarray(data[: 4 << 20])
    from dat_replication_protocol_tpu.runtime.snapshot_driver import (
        SnapshotSource,
    )

    csrc = SnapshotSource(chaos_data)
    stale = chaos_data.copy()
    stale[csrc.offs[:: max(1, len(csrc.offs) // 20)]] ^= 0x5A
    resp = SnapshotResponder(csrc)
    pilot = SnapshotJoiner(stale.tobytes())
    e = protocol.encode(peer_caps=CAP_SNAPSHOT)
    j = WireJournal()
    e.attach_journal(j)
    pending = list(resp.begin_payloads())
    while pending and not pilot.done:
        replies = []
        for payload in pending:
            e.snapshot_frame(payload)
            replies.extend(pilot.handle(sn.decode_snapshot(payload)))
        pending = []
        for r in replies:
            pending.extend(resp.handle(sn.decode_snapshot(r)))
    e.finalize()
    while e.read(4096) is not None:
        pass
    wanted = pilot.chunks_verified
    wire = j.read_from(0)

    # first CHUNKS frame extent -> truncate mid-body
    cut = None
    for _start, _tid, p0, end in iter_frames(wire):
        if wire[p0] == sn.SN_CHUNKS:
            cut = p0 + (end - p0) // 2  # mid-body
            break
    assert cut is not None

    joiner = SnapshotJoiner(stale.tobytes())
    dec = protocol.decode()
    dec.snapshot(lambda msg, done: (joiner.handle(msg), done()))

    def source(ckpt, failures):
        remaining = wire[ckpt.wire_offset:]
        plan = FaultPlan(truncate_at=cut) if failures == 0 else FaultPlan()
        return FaultyReader(bytes_reader(remaining), plan)

    stats = run_resumable(
        source, dec, BackoffPolicy(base=0.0005, cap=0.005, max_retries=4),
        expected_total=len(wire))
    out = joiner.result()
    return {
        "resumed": stats["reconnects"] >= 1,
        "exactly_once": (out["data"] == chaos_data.tobytes()
                         and joiner.chunks_verified == wanted),
        "reconnects": stats["reconnects"],
        "chunks_verified": joiner.chunks_verified,
        "wanted": wanted,
    }


# ---------------------------------------------------------------------------
# config 13: kernel-bypass wire pump (ISSUE 14, ROADMAP item 5)
# ---------------------------------------------------------------------------


def bench_wire_pump(quick: bool, backend: str) -> dict:
    """Config 13: the batched-syscall transport pump A/B (host group).

    Three proofs in one config, all over REAL kernel sockets:

    * **e2e bytes->digest A/B** — one digest session (the sidecar
      shape: TpuDecoder, no per-row handler) pumped through a
      socketpair, native pump vs the Python reference pump, sides
      interleaved + max-of-reps.  ``value`` (and ``e2e_host_gib_s``)
      is the native route; ``pump_ratio`` the A/B.
    * **hub aggregate vs session count** — N concurrent sessions, each
      its own socketpair + native pump feeding one shared
      ReplicationHub; ``hub_agg_gib_s`` per count and
      ``hub_scaling`` = agg(max)/agg(1), the GIL-flatness probe (a
      GIL-bound wire path pins this at ~1.0 regardless of cores).
    * **syscall economics** — ``syscalls_saved``/``pump_batches`` from
      the ``transport.pump.*`` counters (requires ``--metrics``;
      ``None`` otherwise): messages landed minus kernel entries paid.
    """
    import socket
    import threading

    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.hub import ReplicationHub
    from dat_replication_protocol_tpu.session import pump as spump

    mib = _env_int("BENCH_PUMP_MIB", 16 if quick else 64)
    reps = _env_int("BENCH_PUMP_REPS", 2 if quick else 3)
    counts = [int(x) for x in os.environ.get(
        "BENCH_PUMP_SESSIONS", "1,4" if quick else "1,4,16").split(",")]

    def build_wire(total_mib: int, seed: int = 0) -> bytes:
        # the sidecar session shape at wire-bound proportions: a bulk
        # change run (the columnar bulk-decode path, ~1.5% of bytes —
        # more and the PER-ROW digest submits dominate the measurement,
        # hiding the wire path this config exists to price) + 1 MiB
        # blobs (the extent path) for the volume
        rows = (total_mib << 20) // 64 // 89  # ~89 wire bytes per row
        e = protocol.encode()
        e.change_many([
            {"key": f"s{seed}-{j:07d}", "change": j, "from": j,
             "to": j + 1, "value": b"v" * 64}
            for j in range(rows)
        ])
        for _ in range(max(1, total_mib - (total_mib // 64))):
            b = e.blob(1 << 20)
            b.write(bytes(1 << 20))
            b.end()
        e.finalize()
        parts = []
        while True:
            d = e.read(1 << 20)
            if d is None:
                break
            parts.append(d)
        return b"".join(parts)

    def run_session_over_socket(wire: bytes, pipeline=None) -> float:
        """One digest session pumped through a socketpair on the
        CURRENT route; returns seconds."""
        a, b = socket.socketpair()
        try:
            # deployment-shaped kernel buffers (1 MiB): the default
            # ~208 KiB socketpair buffer caps what one batched receive
            # can drain — both routes get the same window (fair A/B)
            a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 20)
            b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 20)
            dec = (protocol.decode(backend="tpu", pipeline=pipeline)
                   if pipeline is not None
                   else protocol.decode(backend="tpu"))
            seen = {"d": 0}
            dec.on_digest(
                lambda k, s, d: seen.__setitem__("d", seen["d"] + 1))
            dec.blob(lambda blob, done: (blob.on_data(lambda _c: None),
                                         blob.on_end(done)))

            def feed() -> None:
                mv = memoryview(wire)
                while mv:
                    sent = a.send(mv[:1 << 20])
                    mv = mv[sent:]
                a.shutdown(socket.SHUT_WR)

            t = threading.Thread(target=feed, daemon=True)
            t.start()
            t0 = time.perf_counter()
            spump.recv_pump(dec, b.fileno())
            dt = time.perf_counter() - t0
            t.join(60)
            assert dec.finished and seen["d"] > 0, "pump session failed"
            return dt
        finally:
            a.close()
            b.close()

    wire = build_wire(mib)
    gib = len(wire) / (1 << 30)

    # A/B interleaved (the config-8 doctrine): route env flipped per
    # side, max-of-reps per side so a scheduler hiccup on the shared
    # box cannot misprice either pump
    best = {"native": 0.0, "python": 0.0}
    prev_route = os.environ.get("DAT_PUMP")
    try:
        for _ in range(reps):
            for route in ("python", "native"):
                os.environ["DAT_PUMP"] = route
                dt = run_session_over_socket(wire)
                best[route] = max(best[route], gib / dt)

        # hub aggregate vs session count, native route (each session:
        # its own socketpair + pump thread into the SHARED hub)
        os.environ["DAT_PUMP"] = "native"
        sess_mib = max(4, mib // 8)
        hub_agg: dict = {}
        for n_sessions in counts:
            wires = [build_wire(sess_mib, seed=i + 1)
                     for i in range(n_sessions)]
            hub = ReplicationHub(linger_s=0.002, window_items=1 << 16,
                                 window_bytes=64 << 20,
                                 parked_budget=1 << 30,
                                 max_sessions=n_sessions + 1)
            done = [None] * n_sessions
            gate = threading.Event()

            def run_one(i: int) -> None:
                gate.wait(30)
                s = hub.register(f"p{i}")
                try:
                    done[i] = run_session_over_socket(wires[i],
                                                      pipeline=s)
                finally:
                    s.close()

            threads = [threading.Thread(target=run_one, args=(i,),
                                        daemon=True)
                       for i in range(n_sessions)]
            for t in threads:
                t.start()
            t0 = time.perf_counter()
            gate.set()
            for t in threads:
                t.join(300)
            wall = time.perf_counter() - t0
            hub.close()
            assert all(d is not None for d in done), "hub pump arm hung"
            total = sum(len(w) for w in wires)
            hub_agg[str(n_sessions)] = round(total / wall / (1 << 30), 4)
    finally:
        if prev_route is None:
            os.environ.pop("DAT_PUMP", None)
        else:
            os.environ["DAT_PUMP"] = prev_route

    # syscall economics, when the registry is live (--metrics)
    saved = batches = None
    if _METRICS["on"]:
        from dat_replication_protocol_tpu.obs import metrics as obs_metrics

        counters = obs_metrics.snapshot().get("counters", {})
        saved = int(counters.get("transport.pump.syscalls_saved", 0))
        batches = int(counters.get("transport.pump.batches", 0))

    ratio = best["native"] / best["python"] if best["python"] else 0.0
    first = hub_agg[str(counts[0])]
    last = hub_agg[str(counts[-1])]
    # the GIL-flatness assertion gates on the curve's PEAK over its
    # 1-session anchor: a GIL-bound wire path pins every point at
    # ~1.0x; a batched GIL-released one rises with sessions until the
    # host runs out of cores (a 2-core CI box peaks at 4 sessions and
    # oversubscribes at 16 — the curve itself is the artifact)
    peak = max(hub_agg.values())
    scaling = (peak / first) if first else 0.0
    log(f"bench[wire_pump]: e2e {mib} MiB — native {best['native']:.3f} "
        f"GiB/s vs python {best['python']:.3f} ({ratio:.2f}x); hub agg "
        f"{hub_agg} (peak scaling {scaling:.2f})")
    return {
        "metric": "wire_pump_e2e_throughput",
        "value": round(best["native"], 3),
        "unit": "GiB/s",
        "vs_baseline": None,
        # the ROADMAP item 5 target metric by its own name: host
        # bytes->digest through a real kernel socket, native route
        "e2e_host_gib_s": round(best["native"], 3),
        "python_pump_gib_s": round(best["python"], 3),
        "pump_ratio": round(ratio, 3),
        "volume_mib": mib,
        "reps": reps,
        "hub_sessions": counts,
        "hub_agg_gib_s": hub_agg,
        "hub_agg_1": first,
        "hub_agg_last": last,
        "hub_agg_peak": round(peak, 4),
        "hub_scaling": round(scaling, 3),
        "pump_batches": batches,
        "syscalls_saved": saved,
        "probe": spump.probe_caps(),
        "reduced_config": mib < 64 or counts[-1] < 16,
        "full_config": "64 MiB e2e A/B + hub aggregate at 1/4/16 "
                       "sessions over socketpairs",
    }


# config 14: N-replica gossip convergence — the epidemic anti-entropy
# mesh (ISSUE 15, ROADMAP item 4): rounds/time to byte-identical
# replicas and total wire bytes vs the divergence actually moved, at
# N in {4, 16, 64}


def bench_gossip_converge(quick: bool, backend: str) -> dict:
    import time as _time

    from dat_replication_protocol_tpu.cluster import ClusterSim
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics
    from dat_replication_protocol_tpu.obs.propagation import PROPAGATION
    from dat_replication_protocol_tpu.obs.wirecost import WIRECOST

    ns_env = os.environ.get("BENCH_GOSSIP_N")
    ns = [int(x) for x in ns_env.split(",")] if ns_env else (
        [4, 8] if quick else [4, 16, 64])
    records = int(os.environ.get("BENCH_GOSSIP_RECORDS",
                                 "32" if quick else "192"))
    divergence = int(os.environ.get("BENCH_GOSSIP_DIVERGENCE",
                                    "8" if quick else "24"))
    res: dict = {}
    # the propagation plane LIT (ISSUE 19): this config prices its own
    # overhead by its own gate — exchange_p99_s comes from the plane's
    # board, and the seconds headline carries the lit-path cost
    was_on = obs_metrics.OBS.on
    obs_metrics.enable()
    try:
        for n in ns:
            # clean links: this config measures the protocol's cost,
            # not its robustness (the chaos sweep in tests/ owns
            # that); the fixed seed pins sampling so rounds are
            # reproducible
            PROPAGATION.reset_for_tests()
            WIRECOST.reset_for_tests()
            sim = ClusterSim(n, seed=20_240, chaos=False,
                             records_per=records, divergence=divergence)
            t0 = _time.perf_counter()
            out = sim.run()
            dt = _time.perf_counter() - t0
            if not out["converged"]:
                return {"error": f"gossip mesh n={n} did not converge "
                                 f"within {out['bound']} rounds"}
            # wire_x: total gossip wire over the divergence bytes that
            # HAD to move — the O(diff) headline at mesh scale (1.0
            # would be a perfect oracle; rateless symbols + record
            # framing ride on top)
            wire_x = (sim.wire_bytes / sim.divergence_bytes
                      if sim.divergence_bytes else 0.0)
            p99 = PROPAGATION.exchange_p99()
            # wire cost plane (ISSUE 20): every exchange of this mesh
            # ran lit, so the board ledger holds exactly this n's wire
            goodput, overhead = _wirecost_ratios()
            res[n] = {"rounds": out["rounds"], "seconds": round(dt, 3),
                      "wire_bytes": sim.wire_bytes,
                      "divergence_bytes": sim.divergence_bytes,
                      "wire_x": round(wire_x, 3),
                      "exchange_p99_s": round(p99 or 0.0, 6),
                      "goodput_ratio": goodput,
                      "overhead_ratio": overhead}
            log(f"bench[gossip_converge]: n={n} rounds={out['rounds']} "
                f"{dt:.2f}s wire={sim.wire_bytes} "
                f"(divergence {sim.divergence_bytes}, x{wire_x:.2f}, "
                f"exchange p99 {p99 or 0.0:.4f}s)")
    finally:
        PROPAGATION.reset_for_tests()
        WIRECOST.reset_for_tests()
        obs_metrics.OBS.on = was_on
    top = max(ns)
    return {
        "metric": "gossip_converge_seconds",
        # the headline: wall seconds for the LARGEST mesh to reach
        # byte-identical replicas from full divergence
        "value": res[top]["seconds"],
        "unit": "s",
        "vs_baseline": None,
        "ns": ns,
        "records_per": records,
        "divergence_per": divergence,
        "rounds_top": res[top]["rounds"],
        "wire_x_top": res[top]["wire_x"],
        # the convergence-plane budget fields (ISSUE 19): p99 wall
        # seconds of one lit exchange at the top mesh size, and the
        # rounds the top mesh took to converge — both gated in
        # perf_budgets.json so the plane's own overhead is priced
        "exchange_p99_s": res[top]["exchange_p99_s"],
        "rounds_to_converge": res[top]["rounds"],
        "goodput_ratio": res[top]["goodput_ratio"],
        "overhead_ratio": res[top]["overhead_ratio"],
        **{f"rounds_{n}": res[n]["rounds"] for n in ns},
        **{f"seconds_{n}": res[n]["seconds"] for n in ns},
        **{f"wire_bytes_{n}": res[n]["wire_bytes"] for n in ns},
        **{f"wire_x_{n}": res[n]["wire_x"] for n in ns},
        **{f"exchange_p99_s_{n}": res[n]["exchange_p99_s"] for n in ns},
        "reduced_config": top < 64 or records < 192 or divergence < 24,
        "full_config": "N in {4,16,64}, 192 base + 24 unique records "
                       "per replica, clean links, fixed seed, "
                       "propagation plane lit",
    }


def _edge_client_main(n: int, port: int, wire_hex: str) -> None:
    """The client half of config 15, run as a SUBPROCESS (its own
    RLIMIT_NOFILE budget: N concurrent sessions need N client fds plus
    N server fds, and the container's hard cap cannot carry both sides
    of 10k in one process).  Protocol on the pipe: print ``HELD k``
    when the whole cohort is connected and parked mid-wire, wait for
    ``GO`` on stdin, finish the flood, print one JSON result line."""
    import selectors as _selectors
    import socket as _socket

    wire = bytes.fromhex(wire_hex)
    half = len(wire) // 2
    addr = ("127.0.0.1", port)
    CONNECT_CHUNK = 128  # outstanding connects: stay under the backlog
    sel = _selectors.DefaultSelector()
    # client FSM rows: [sock, state, t_sent, latency, reply_bytes]
    clients = []
    t_ramp0 = time.perf_counter()
    started = 0
    held = 0
    failures = 0
    deadline = time.monotonic() + 300
    # -- ramp: connect everyone, send HALF the wire, park -------------
    while held + failures < n:
        if time.monotonic() > deadline:
            raise TimeoutError(f"edge_scaling ramp stuck at {held}/{n}")
        while started < n and (started - held - failures) < CONNECT_CHUNK:
            s = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            s.setblocking(False)
            s.connect_ex(addr)
            row = [s, "connecting", 0.0, 0.0, 0]
            clients.append(row)
            sel.register(s, _selectors.EVENT_WRITE, row)
            started += 1
        for skey, _mask in sel.select(0.05):
            row = skey.data
            if row[1] != "connecting":
                continue
            s = row[0]
            err = s.getsockopt(_socket.SOL_SOCKET, _socket.SO_ERROR)
            sel.unregister(s)
            if err:
                s.close()
                row[1] = "failed"
                failures += 1
                continue
            s.sendall(wire[:half])
            row[1] = "held"
            held += 1
    ramp_s = time.perf_counter() - t_ramp0
    print(f"HELD {held}", flush=True)
    if sys.stdin.readline().strip() != "GO":
        raise RuntimeError("edge client: no GO from the bench driver")
    # -- finish flood: the measured phase -----------------------------
    t0 = time.perf_counter()
    reading = 0
    for row in clients:
        if row[1] != "held":
            continue
        s = row[0]
        s.sendall(wire[half:])
        s.shutdown(_socket.SHUT_WR)
        row[1] = "reading"
        row[2] = time.perf_counter()
        sel.register(s, _selectors.EVENT_READ, row)
        reading += 1
    done = 0
    while done < reading:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"edge_scaling finish stuck at {done}/{reading}")
        for skey, _mask in sel.select(0.05):
            row = skey.data
            s = row[0]
            try:
                data = s.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                data = b""
            if data:
                row[4] += len(data)
                continue
            row[3] = time.perf_counter() - row[2]
            row[1] = "done"
            sel.unregister(s)
            s.close()
            done += 1
    finish_s = time.perf_counter() - t0
    sel.close()
    ok = sum(1 for row in clients if row[1] == "done" and row[4] > 0)
    lats = sorted(row[3] for row in clients if row[1] == "done")
    p99 = lats[max(0, int(0.99 * (len(lats) - 1)))] if lats else 0.0
    print(json.dumps({
        "held": held, "failures": failures, "done": done, "ok": ok,
        "ramp_s": round(ramp_s, 3), "finish_s": round(finish_s, 3),
        "p99_s": round(p99, 4),
    }), flush=True)


def bench_edge_scaling(quick: bool, backend: str) -> dict:
    """Config 15 (ISSUE 17): the C10k claim — 1/100/1k/10k concurrent
    mixed-QoS-class sessions through ONE event-driven edge loop.

    Every client connects and parks mid-wire until the whole cohort is
    admitted (peak table occupancy == N, verified from the loop's own
    snapshot), then the cohort finishes at once: the finish flood is
    the measured phase.  Headline: finish-phase sessions/s at the top
    N; the budget gate additionally holds ``ok_fraction`` at 1.0 and
    admission-ladder counts (rejected/shed) at ZERO — overload
    machinery must stay dark on a properly sized hub, at every scale.
    The client cohort runs in a subprocess (fd budget: N sessions are
    N fds on EACH side).

    ISSUE 18: the run is captured with the obs gate ON so the turn
    profiler is lit — the loop's own per-turn accounting yields
    ``loop_lag_max_s``/``p99_turn_s`` per cohort size, budget-gated at
    the top N.  The flight-deck numbers are therefore measured WITH
    profiler overhead included: the budget holds both the telemetry
    and its cost."""
    import subprocess
    import threading

    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.edge import EdgeLoop
    from dat_replication_protocol_tpu.hub import ReplicationHub
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    ns_env = os.environ.get("BENCH_EDGE_N")
    counts = [int(x) for x in ns_env.split(",")] if ns_env else (
        [1, 100, 1000] if quick else [1, 100, 1000, 10000])

    import resource
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = max(counts) + 512
    if soft < want:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(want, hard), hard))
        except (ValueError, OSError):
            pass
        soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    dropped = [n for n in counts if n + 512 > soft]
    if dropped:
        log(f"bench[edge_scaling]: fd limit {soft} drops counts "
            f"{dropped} (needs 1 fd/session + slack per side)")
        counts = [n for n in counts if n + 512 <= soft] or [1]

    # one tiny session wire, built untimed: a single change frame —
    # this config measures the TABLE (admission, readiness, teardown
    # at scale), not byte throughput (config 13 owns that)
    e = protocol.encode()
    e.change({"key": "edge-bench", "change": 0, "from": 0, "to": 1,
              "value": b"v" * 64})
    e.finalize()
    parts = []
    while True:
        d = e.read(1 << 16)
        if d is None:
            break
        parts.append(d)
    wire = b"".join(parts)

    res: dict = {}
    was_on = obs_metrics.OBS.on
    obs_metrics.enable()  # lit turn profiler: measure WITH the flight deck on
    try:
        for n in counts:
            hub = ReplicationHub(max_sessions=n + 8, linger_s=0.002)
            qos_of = lambda i, peer, mode: \
                "latency" if i % 2 else "throughput"  # noqa: E731
            loop = EdgeLoop(hub, qos_of=qos_of, max_sessions=n,
                            tick=0.02, drain_timeout=60.0)
            port = loop.bind("127.0.0.1", 0)
            server = threading.Thread(target=loop.serve, daemon=True)
            server.start()
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--edge-client", str(n), str(port), wire.hex()],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True)
            try:
                line = proc.stdout.readline().strip()
                if not line.startswith("HELD "):
                    raise RuntimeError(f"edge client died during ramp: "
                                       f"{line!r}")
                held = int(line.split()[1])
                # peak occupancy: every held session sits in the ONE
                # table — wait for the accept side to drain its backlog
                # (held sessions cannot finish: half their wire is
                # missing)
                deadline = time.monotonic() + 120
                peak = loop.snapshot()["sessions"]
                while peak < held and time.monotonic() < deadline:
                    time.sleep(0.01)
                    peak = max(peak, loop.snapshot()["sessions"])
                proc.stdin.write("GO\n")
                proc.stdin.flush()
                out = json.loads(proc.stdout.readline())
                proc.wait(timeout=60)
            finally:
                if proc.poll() is None:
                    proc.kill()
                loop.close()
            server.join(30)
            snap = loop.snapshot()
            hub.close()
            finish_s = out["finish_s"]
            prof = snap["loop"]  # the profiler's own turn accounting
            res[n] = {
                "sessions_s": (round(out["done"] / finish_s, 1)
                               if finish_s else 0.0),
                "p99_s": out["p99_s"],
                "ramp_s": out["ramp_s"],
                "finish_s": finish_s,
                "peak_sessions": peak,
                "ok": out["ok"],
                "admitted": snap["admitted"],
                "rejected": snap["rejected"],
                "shed": snap["shed"],
                "loop_lag_max_s": round(prof["lag_max_s"], 6),
                "p99_turn_s": round(prof["p99_work_s"], 6),
                "loop_turns": prof["turns"],
            }
            log(f"bench[edge_scaling]: n={n} peak={peak} "
                f"{res[n]['sessions_s']}/s "
                f"p99={out['p99_s'] * 1e3:.1f}ms "
                f"(ramp {out['ramp_s']:.2f}s, finish {finish_s:.2f}s, "
                f"ok {out['ok']}, lag_max "
                f"{res[n]['loop_lag_max_s'] * 1e3:.1f}ms, p99 turn "
                f"{res[n]['p99_turn_s'] * 1e3:.1f}ms)")
    finally:
        obs_metrics.OBS.on = was_on
    top = max(counts)
    total_ok = sum(res[n]["ok"] for n in counts)
    return {
        "metric": "edge_scaling_sessions_per_s",
        # the headline: finish-flood completions/s at the LARGEST
        # concurrent cohort
        "value": res[top]["sessions_s"],
        "unit": "sessions/s",
        "vs_baseline": None,
        "ns": counts,
        "wire_bytes": len(wire),
        # the C10k acceptance row: cohort size the ONE table actually
        # held at once, and the clean-completion fraction
        "peak_sessions_top": res[top]["peak_sessions"],
        "ok_fraction": round(total_ok / sum(counts), 6),
        "p99_s_top": res[top]["p99_s"],
        "rejected_total": sum(res[n]["rejected"] for n in counts),
        "shed_total": sum(res[n]["shed"] for n in counts),
        # ISSUE 18 flight-deck rows: worst loop overrun and p99 turn
        # time at the top cohort, straight from the turn profiler
        "loop_lag_max_s_top": res[top]["loop_lag_max_s"],
        "p99_turn_s_top": res[top]["p99_turn_s"],
        **{f"sessions_s_{n}": res[n]["sessions_s"] for n in counts},
        **{f"p99_s_{n}": res[n]["p99_s"] for n in counts},
        **{f"peak_{n}": res[n]["peak_sessions"] for n in counts},
        **{f"loop_lag_max_s_{n}": res[n]["loop_lag_max_s"]
           for n in counts},
        **{f"p99_turn_s_{n}": res[n]["p99_turn_s"] for n in counts},
        "reduced_config": top < 10000,
        "full_config": "1/100/1k/10k concurrent mixed-QoS sessions "
                       "through one edge loop on host, turn profiler "
                       "lit (obs gate ON)",
    }


# ---------------------------------------------------------------------------


BENCHES = {
    "1": ("roundtrip", bench_roundtrip),
    "2": ("replay", bench_replay),
    "3": ("hash", bench_hash),
    "4": ("cdc", bench_cdc),
    "5": ("merkle_diff", bench_merkle),
    "6": ("resume", bench_resume),
    "7": ("wire_batch", bench_wire_batch),
    "8": ("fused_e2e", bench_fused_e2e),
    "9": ("hub_soak", bench_hub_soak),
    "10": ("fanout", bench_fanout),
    "11": ("reconcile_rateless", bench_reconcile_rateless),
    "12": ("snapshot_bootstrap", bench_snapshot_bootstrap),
    "13": ("wire_pump", bench_wire_pump),
    "14": ("gossip_converge", bench_gossip_converge),
    "15": ("edge_scaling", bench_edge_scaling),
}


_state: dict = {"configs": {}, "backend": None, "backend_error": None}
_emitted = False

# --metrics: attach a per-config obs-registry snapshot to each config's
# result so BENCH_*.json rounds carry attribution (which layer moved),
# not just a headline number.  The registry is reset between configs so
# each snapshot is that config's own story.
_METRICS = {"on": False}


def _metrics_on() -> None:
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    _METRICS["on"] = True
    obs_metrics.enable()


def _attach_metrics(res: dict) -> None:
    """Attach the registry snapshot to one config result (no-op unless
    --metrics), then reset values for the next config."""
    if not _METRICS["on"]:
        return
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    res["metrics"] = obs_metrics.snapshot()
    obs_metrics.REGISTRY.reset()


def _device_telemetry_subset() -> dict:
    """device./backend.-prefixed slice of the live registry — the
    partial device telemetry that rides a failed backend init's
    ``backend_error`` record (ISSUE 5 satellite)."""
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    snap = obs_metrics.snapshot()

    def pick(d: dict) -> dict:
        return {k: v for k, v in d.items()
                if k.startswith(("device.", "backend."))}

    return {"counters": pick(snap.get("counters", {})),
            "gauges": pick(snap.get("gauges", {})),
            # device.chiplock.wait lives here — the contention story a
            # failed device run most needs in its post-mortem
            "histograms": pick(snap.get("histograms", {}))}


def _export_config_trace(name: str, trace_dir) -> None:
    """--trace artifact per config: the obs span/event rings exported
    as one Chrome trace JSON (Perfetto-loadable) under
    <trace_dir>/configs/<name>.trace.json, rings cleared after so each
    artifact is that config's own story.  The rings only fill while
    telemetry is on (--metrics / DAT_OBS) — frame spans and joined
    jax-annotation spans alike — so without it the artifact is an
    empty shell; pass --metrics alongside --trace for span content."""
    if not trace_dir:
        return
    try:
        from dat_replication_protocol_tpu.obs import events as obs_events
        from dat_replication_protocol_tpu.obs import tracing as obs_tracing

        try:
            out = os.path.join(trace_dir, "configs", f"{name}.trace.json")
            obs_tracing.export_chrome_trace(out)
            log(f"bench: config {name} trace -> {out}")
        finally:
            # clear even when the export failed: a leftover ring would
            # leak THIS config's spans into the next config's artifact.
            # The engine-select memo resets with the rings — otherwise
            # every config after the first would carry no
            # device.engine.select attribution in its artifact.
            from dat_replication_protocol_tpu.obs import device as obs_device

            obs_tracing.SPANS.clear()
            obs_events.EVENTS.clear()
            obs_device.reset_engine_notes()
    except Exception as e:  # an unwritable dir must not blank the run
        log(f"bench: config {name} trace export failed ({e})")


def _export_config_fleet(name: str, fleet_dir) -> None:
    """--fleet-snapshot artifact per config (ISSUE 11): the same JSON
    record the sidecar's /snapshot endpoint serves — registry metrics,
    jit_sites, watermark links — dumped under
    <fleet_dir>/configs/<name>.fleet.json next to the --trace
    artifacts, so a bench run leaves per-config fleet views an
    `obs fleet` file target (or a human) can read directly.  Like the
    trace export, content needs --metrics/DAT_OBS; dark runs dump an
    honest near-empty shell."""
    try:
        if fleet_dir:
            from dat_replication_protocol_tpu.obs.http import (
                default_snapshot,
            )

            out = os.path.join(fleet_dir, "configs", f"{name}.fleet.json")
            os.makedirs(os.path.dirname(out), exist_ok=True)
            with open(out, "w", encoding="utf-8") as f:
                json.dump(default_snapshot(), f, default=repr)
                f.write("\n")
            log(f"bench: config {name} fleet view -> {out}")
    except Exception as e:  # an unwritable dir must not blank the run
        log(f"bench: config {name} fleet export failed ({e})")
    finally:
        # like the per-config ring clears, and UNCONDITIONALLY (not
        # only under --fleet-snapshot): a config's watermark links
        # must not leak into the next config's snapshot, and a link's
        # cursor closures must not pin the config's journal buffers
        # for the rest of the run
        from dat_replication_protocol_tpu.obs.watermarks import WATERMARKS

        WATERMARKS.reset_for_tests()


def _emit() -> None:
    """Print the one JSON artifact line from whatever has completed.

    Idempotent; also called by the deadline watchdog, so even a wedged
    device call mid-run leaves a parseable artifact (round 1 left none).
    """
    global _emitted
    if _emitted:
        return
    _emitted = True
    configs = _state["configs"]
    headline = configs.get("hash", {})
    out = {
        "metric": "blake2b_batched_blob_hash_throughput",
        # null, not 0.0, when the headline config produced no number — a
        # fake zero is indistinguishable from a measured failure downstream
        "value": headline.get("value"),
        "unit": "GiB/s",
        "vs_baseline": headline.get("vs_baseline"),
        "backend": _state["backend"],
        "configs": configs,
    }
    if "error" in headline:
        out["error"] = headline["error"]
    if _state["backend_error"]:
        out["backend_error"] = _state["backend_error"]
    print(json.dumps(out), flush=True)


def main() -> None:
    import contextlib
    import threading

    if sys.argv[1:2] == ["--edge-client"]:
        # config 15's client cohort, re-invoked as a subprocess: the fd
        # budget (1 fd/session/process) is why this is not a thread
        _edge_client_main(int(sys.argv[2]), int(sys.argv[3]),
                          sys.argv[4])
        return

    quick = "--quick" in sys.argv
    if "--metrics" in sys.argv:
        _metrics_on()
    trace_dir = None
    flight_dir = None
    fleet_dir = None
    args = sys.argv[1:]
    for i, arg in enumerate(args):
        if arg.startswith("--trace="):
            trace_dir = arg.split("=", 1)[1]
        elif arg == "--trace":
            trace_dir = "/tmp/dat_bench_trace"
        elif arg.startswith("--flight-dir="):
            flight_dir = arg.split("=", 1)[1]
        elif arg == "--flight-dir" and i + 1 < len(args) \
                and not args[i + 1].startswith("-"):
            flight_dir = args[i + 1]
        elif arg.startswith("--fleet-snapshot="):
            fleet_dir = arg.split("=", 1)[1]
        elif arg == "--fleet-snapshot" and i + 1 < len(args) \
                and not args[i + 1].startswith("-"):
            fleet_dir = args[i + 1]
    if flight_dir:
        # armed recorder: a stuck backend init (the watchdog below) or
        # any structured session error dumps a post-mortem bundle here
        from dat_replication_protocol_tpu.obs import flight as obs_flight

        obs_flight.arm(flight_dir)
    which = [
        k.strip()
        for k in os.environ.get(
            "BENCH_CONFIGS", "1,2,3,4,5,6,7,8,9,10,11,12,13").split(",")
        if k.strip() in BENCHES
    ]

    # hard deadline: emit whatever completed and exit 0 — a wedged device
    # call (observed: jax.devices() hanging >300 s) must not blank the run
    start_ts = time.monotonic()
    deadline = float(os.environ.get("BENCH_DEADLINE", 600 if quick else 1800))
    watchdog = threading.Timer(
        deadline, lambda: (log(f"bench: deadline {deadline:.0f}s hit"), _emit(),
                           os._exit(0)),
    )
    watchdog.daemon = True
    watchdog.start()
    # last line of defense: even an uncaught exception anywhere below must
    # still leave a parseable artifact (_emit is idempotent; the watchdog's
    # os._exit path already emits itself)
    import atexit

    atexit.register(_emit)

    def run_config(key: str, backend: str) -> None:
        name, fn = BENCHES[key]
        t0 = time.perf_counter()
        try:
            res = fn(quick, backend)
            res["seconds"] = round(time.perf_counter() - t0, 2)
            # fleet view BEFORE _attach_metrics: that call resets the
            # registry, and the view's whole point is this config's
            # live metrics + watermark links
            _export_config_fleet(name, fleet_dir)
            _attach_metrics(res)
            _state["configs"][name] = res
            log(f"bench: config {key} ({name}) ok in {res['seconds']}s")
        except Exception as e:
            log(f"bench: config {key} ({name}) FAILED: {e}")
            traceback.print_exc(file=sys.stderr)
            err_res = {"error": f"{type(e).__name__}: {e}"}
            _export_config_fleet(name, fleet_dir)
            _attach_metrics(err_res)  # partial-work attribution
            _state["configs"][name] = err_res
        _export_config_trace(name, trace_dir)

    # configs 1, 2, 6, 7, 8 need no JAX: run them before any backend
    # init so a wedged/broken device stack cannot cost their numbers
    # (config 8's opt-in device leg initializes jax itself — it is for
    # the TPU watch script, which only fires when the tunnel answers)
    for key in which:
        if key in ("1", "2", "6", "7", "8", "9", "10", "11", "12", "13",
                   "14", "15"):
            run_config(key, "host")

    # priority order for the device leg: the headline hash config first,
    # then merkle (second target), then cdc (largest volume) — a device
    # that appears late in the budget must still yield config 3
    priority = {"3": 0, "5": 1, "4": 2}
    device_keys = sorted(
        (k for k in which
         if k not in ("1", "2", "6", "7", "8", "9", "10", "11", "12",
                      "13", "14", "15")),
        key=lambda k: priority.get(k, 9)
    )
    if device_keys:
        deadline_ts = start_ts + deadline
        force = os.environ.get("BENCH_PLATFORM") or None

        def run_device_leg(backend: str) -> None:
            import jax

            from dat_replication_protocol_tpu.obs.device import (
                BackendInitWatchdog,
            )
            from dat_replication_protocol_tpu.utils.cache import (
                enable_compile_cache,
            )

            # host-side setup only before the chip lock: nothing below
            # may touch the device yet (a pre-lock init would race a
            # peer's capture — the exact contamination the lock closes)
            enable_compile_cache("bench", env_var="BENCH_COMPILE_CACHE")
            if force:
                # the dev image's sitecustomize re-forces JAX_PLATFORMS
                # after env vars are read; jax.config wins over both
                jax.config.update("jax_platforms", force)
            # --trace wraps the device configs in a jax.profiler capture
            # (open with TensorBoard/Perfetto); library spans from
            # utils.trace annotate pack/dispatch/collect phases
            if trace_dir:
                from dat_replication_protocol_tpu.utils.trace import trace_to

                ctx = trace_to(trace_dir)
                log(f"bench: tracing device configs to {trace_dir}")
            else:
                ctx = contextlib.nullcontext()
            # exclusive chip mutex: a concurrent diagnostic on the same
            # chip contaminated round 4's only driver-shaped hash capture
            # (22.76 vs 37.9 uncontended).  Wait a bounded slice of the
            # remaining budget for a peer to finish; if it never does,
            # run anyway and let the artifact SAY contended rather than
            # blank the run.
            from dat_replication_protocol_tpu.utils.chiplock import chip_lock

            lock_wait = max(
                30.0, min(300.0, (deadline_ts - time.monotonic()) / 4)
            )
            with ctx, chip_lock(max_wait=lock_wait) as lease:
                if not lease.uncontended:
                    log(f"bench: chip lock contended "
                        f"(held={lease.held}, waited {lease.waited_s:.0f}s)")
                # staged init under a deadline, INSIDE the lock (the
                # first device touch happens here): the probe verified
                # the platform, but the in-process init can still wedge
                # — when it does, the watchdog emits backend.init.stuck
                # naming the stage and dumps a flight bundle (with
                # --flight-dir) while the bench deadline watchdog
                # handles artifact emission
                wd_deadline = max(
                    30.0, min(300.0, deadline_ts - time.monotonic() - 30.0)
                )
                with BackendInitWatchdog(deadline_s=wd_deadline) as wd:
                    wd.stage("platform_probe")
                    wd.stage("first_device_call")
                    ndev = len(jax.devices())
                    wd.stage("first_compile")
                    import numpy as _np

                    assert int(_np.asarray(
                        jax.jit(lambda: jax.numpy.arange(4))())[3]) == 3
                if wd.fired:
                    log(f"bench: backend init exceeded {wd_deadline:.0f}s "
                        f"watchdog (recovered); see backend.init.* events")
                log(f"bench: in-process backend up ({ndev} device(s), "
                    f"{wd.elapsed_s:.1f}s)")
                for key in device_keys:
                    run_config(key, backend)
                    res = _state["configs"].get(BENCHES[key][0])
                    if res is not None and "error" not in res:
                        res.update(lease.as_fields())

        def run_device_leg_guarded(backend: str) -> None:
            # an init failure (unwritable compile-cache dir, trace setup,
            # jax import) must still leave per-config errors + an artifact
            try:
                run_device_leg(backend)
            except Exception as e:
                log(f"bench: device leg failed outright: {e}")
                traceback.print_exc(file=sys.stderr)
                for key in device_keys:
                    _state["configs"].setdefault(
                        BENCHES[key][0], {"error": f"{type(e).__name__}: {e}"}
                    )
                if _state["backend_error"] is None:
                    # the IN-PROCESS failure path is where the watchdog's
                    # stage events and device gauges actually live — the
                    # structured record + telemetry subset must ride this
                    # branch, not just the subprocess-probe one
                    be: dict = {"message": f"{type(e).__name__}: {e}",
                                "stage": None, "elapsed_s": None}
                    if _METRICS["on"]:
                        from dat_replication_protocol_tpu.obs import (
                            events as obs_events,
                        )

                        # attribute a stage ONLY when the init itself
                        # failed: the watchdog's done event carries the
                        # raising exception type when its block raised,
                        # and no error when init completed — a
                        # post-init failure (unwritable trace dir,
                        # chip-lock error) must not read as "backend
                        # init stuck in first_compile"
                        st = obs_events.EVENTS.last("backend.init.stage")
                        done = obs_events.EVENTS.last("backend.init.done")
                        init_failed = done is None or \
                            done["fields"].get("error") is not None
                        if st is not None and init_failed:
                            be["stage"] = st["fields"].get("stage")
                            be["elapsed_s"] = st["fields"].get("elapsed_s")
                        be["telemetry"] = _device_telemetry_subset()
                    _state["backend_error"] = be

        if force == "cpu":
            # explicit CPU run (and the fallback child itself): no probing
            _state["backend"] = "cpu"
            run_device_leg_guarded("cpu")
        else:
            fb: dict = {"proc": None}
            allow_fb = not os.environ.get("BENCH_NO_FALLBACK")

            def start_fallback() -> None:
                if allow_fb and fb["proc"] is None:
                    try:
                        fb["proc"] = _start_cpu_fallback(
                            device_keys, quick,
                            budget_s=deadline_ts - time.monotonic() - 30,
                            trace_dir=trace_dir, flight_dir=flight_dir,
                        )
                    except Exception as e:  # fork/ENOMEM: keep the run alive
                        log(f"bench: could not start CPU fallback ({e})")

            try:
                backend, backend_err = _probe_loop(
                    force, deadline_ts,
                    probe_timeout=60 if quick else 90,
                    on_first_failure=start_fallback,
                )
            except Exception as e:  # e.g. jax import failure
                backend, backend_err = None, f"{type(e).__name__}: {e}"
                log(f"bench: backend probe failed outright: {e}")
            # no telemetry subset here: the probe ran in a throwaway
            # subprocess whose registry died with it, and the parent has
            # not touched the device yet — the stage/elapsed fields ARE
            # this branch's device story.  The in-process failure path
            # (run_device_leg_guarded) attaches the subset, where it is
            # actually populated.
            _state["backend_error"] = backend_err
            if backend is not None:
                _state["backend"] = backend
                log(f"bench: backend={backend} (probed)")
                run_device_leg_guarded(backend)
                need = [
                    nm for nm in (BENCHES[k][0] for k in device_keys)
                    if "error" in _state["configs"].get(nm, {"error": 1})
                ]
                if need:
                    filled = _merge_fallback(
                        _state["configs"],
                        _collect_cpu_fallback(
                            fb["proc"], deadline_ts - time.monotonic()
                        ),
                    )
                    if filled:
                        log(f"bench: CPU fallback filled {filled}")
                elif fb["proc"] is not None:
                    # every device config landed: the child's results would
                    # all be discarded — don't stall the run on its exit
                    log("bench: device leg complete; discarding CPU-fallback "
                        "child")
                    try:
                        fb["proc"].kill()
                        fb["proc"].wait(timeout=10)
                    except Exception:
                        pass
            else:
                _state["backend"] = "cpu"
                log(f"bench: no device backend ({backend_err}); using the "
                    f"CPU-fallback results")
                start_fallback()  # in case the first probe said plain cpu
                filled = _merge_fallback(
                    _state["configs"],
                    _collect_cpu_fallback(
                        fb["proc"], deadline_ts - time.monotonic()
                    ),
                )
                for key in device_keys:
                    name = BENCHES[key][0]
                    if name not in _state["configs"]:
                        # slim per-config record: the telemetry subset
                        # rides ONCE on the top-level backend_error, not
                        # duplicated into every missing config
                        if isinstance(backend_err, dict):
                            _state["configs"][name] = {
                                "error": backend_err["message"],
                                "stage": backend_err.get("stage"),
                            }
                        else:
                            _state["configs"][name] = {"error": backend_err}

    watchdog.cancel()
    _emit()


if __name__ == "__main__":
    main()
