"""Benchmark harness — headline: batched BLAKE2b blob-hash throughput.

Runs BASELINE.json config 3 ("10k x 1 MiB blob stream BLAKE2b
content-hashing (batched)") on the default JAX backend and prints exactly
ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` is measured GiB/s divided by the 50 GiB/s north-star
target (the reference itself publishes no numbers — BASELINE.md).

The payload batch is generated directly on device in the packed layout
consumed by the hash kernel — the bench measures the device kernel, not
host byte-shuffling (the host feed path is benched separately by the
replay-engine config).  On TPU this is the Pallas kernel
(:mod:`dat_replication_protocol_tpu.ops.blake2b_pallas`); on CPU the
portable XLA-scan path, on much smaller defaults.  HBM is bounded by
hashing a resident chunk of items repeatedly until the config's total
volume is reached.

Env knobs: BENCH_ITEMS (default 10240), BENCH_ITEM_MIB (default 1),
BENCH_CHUNK (items resident at once, default 4096 on TPU; rounded to the
Pallas kernel's 1024-item tile there).
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dat_replication_protocol_tpu.ops.blake2b import (
        BLOCK_BYTES,
        blake2b_packed,
    )

    backend = jax.default_backend()
    use_pallas = backend == "tpu"
    quick = "--quick" in sys.argv

    if quick:
        d_items, d_mib, d_chunk = 2048, 0.125, 2048
    elif use_pallas:
        d_items, d_mib, d_chunk = 10240, 1, 4096
    else:
        d_items, d_mib, d_chunk = 64, 0.125, 32
    items = int(os.environ.get("BENCH_ITEMS", d_items))
    item_mib = float(os.environ.get("BENCH_ITEM_MIB", d_mib))
    chunk = int(os.environ.get("BENCH_CHUNK", d_chunk))
    chunk = min(chunk, items)
    if use_pallas:
        # the pallas kernel tiles the batch in 1024-item blocks
        chunk = max(1024, chunk // 1024 * 1024)

    item_bytes = int(item_mib * (1 << 20))
    nblocks = max(1, item_bytes // BLOCK_BYTES)
    item_bytes = nblocks * BLOCK_BYTES
    reps = max(1, items // chunk)

    log(
        f"bench: backend={backend} pallas={use_pallas} "
        f"items={reps * chunk} x {item_bytes} B (chunk={chunk}, reps={reps})"
    )

    kh, kl = jax.random.split(jax.random.PRNGKey(0))
    if use_pallas:
        from dat_replication_protocol_tpu.ops.blake2b_pallas import (
            blake2b_native,
        )

        shape = (nblocks, 16, 8, chunk // 8)
        mh = jax.random.bits(kh, shape, dtype=jnp.uint32)
        ml = jax.random.bits(kl, shape, dtype=jnp.uint32)
        lengths = jnp.full((8, chunk // 8), item_bytes, dtype=jnp.uint32)
        run = lambda: blake2b_native(mh, ml, lengths)  # noqa: E731
    else:
        shape = (chunk, nblocks, 16)
        mh = jax.random.bits(kh, shape, dtype=jnp.uint32)
        ml = jax.random.bits(kl, shape, dtype=jnp.uint32)
        lengths = jnp.full((chunk,), item_bytes, dtype=jnp.uint32)
        run = lambda: blake2b_packed(mh, ml, lengths)  # noqa: E731
    jax.block_until_ready((mh, ml))

    # warmup / compile
    t0 = time.perf_counter()
    np.asarray(run()[0])
    log(f"bench: compile+first-run {time.perf_counter() - t0:.1f}s")

    # time via host transfer of the (tiny) digest outputs: on the tunneled
    # axon platform block_until_ready returns before execution completes,
    # so fetching the digests is the reliable completion barrier
    t0 = time.perf_counter()
    outs = [run() for _ in range(reps)]
    for hh, hl in outs:
        np.asarray(hh)
        np.asarray(hl)
    elapsed = time.perf_counter() - t0

    total_bytes = reps * chunk * item_bytes
    gib_s = total_bytes / elapsed / (1 << 30)
    log(f"bench: {total_bytes / (1 << 30):.1f} GiB in {elapsed:.3f}s")

    print(
        json.dumps(
            {
                "metric": "blake2b_batched_blob_hash_throughput",
                "value": round(gib_s, 3),
                "unit": "GiB/s",
                "vs_baseline": round(gib_s / 50.0, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
