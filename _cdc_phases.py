"""CDC e2e phase attribution at the bench shape (1 GiB slab).

Times each stage of the fast path separately, all device stages fenced
by a scalar reduction so the tunnel's early-returning block_until_ready
cannot lie:

  A. gear kernel, native layout (no transposes)
  B. gear kernel via gear_candidates_pallas (input+output transposes)
  C. full _extract_first_occ (kernel + window reduce + occ/offs pack)
  D. full candidates_begin().collect() (adds D2H + host unpack/nonzero)
  E. D + native greedy select (the whole e2e leg)
"""
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

from dat_replication_protocol_tpu.ops import rabin
from dat_replication_protocol_tpu.ops.rabin_pallas import (
    gear_candidates_native,
    gear_candidates_pallas,
)
from dat_replication_protocol_tpu.utils.cache import enable_compile_cache
from dat_replication_protocol_tpu.utils.chiplock import chip_lock

enable_compile_cache("bench", env_var="BENCH_COMPILE_CACHE")

# diagnostics must never share the chip with a bench capture (round-4
# lesson); held for the process lifetime, released by the kernel on exit
_lock_cm = chip_lock()  # keep the CM alive: a bare __enter__() on a
# temporary would be GC'd, running the generator's finally and RELEASING
# the flock immediately (caught in round-5 review)
_lease = _lock_cm.__enter__()
print(f"chip lock: uncontended={_lease.uncontended}", flush=True)

slab_b = 1 << 30
stride = 1 << 17
T = slab_b // stride
avg_bits = 13
thin_bits = avg_bits - 2

words = jax.random.bits(jax.random.PRNGKey(5), (slab_b // 4,), dtype=jnp.uint32)
jax.block_until_ready(words)

# pre-transposed native-layout input (with the prefix rows the real path
# builds): rows (T, _PREFIX_WORDS + stride/4)
rows_flat = rabin._build_rows(
    words.reshape(T, stride // 4).reshape(-1),
    jnp.zeros((rabin._PREFIX_WORDS,), jnp.uint32), T, stride,
)
S = rows_flat.shape[1] * 4
ng = S // rabin.GROUP
native = jnp.transpose(
    rows_flat.reshape(T, ng, rabin.GROUP // 4), (1, 2, 0)
).reshape(ng, rabin.GROUP // 4, 8, T // 8)
native = jax.device_put(native)
jax.block_until_ready(native)


def timed(tag, fn, reps=3):
    fn()
    dts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        dts.append(time.perf_counter() - t0)
    med = statistics.median(dts)
    print(f"{tag}: {med*1e3:.1f} ms ({slab_b / med / (1<<30):.2f} GiB/s)",
          flush=True)
    return med


kern_n = jax.jit(lambda w: jnp.sum(gear_candidates_native(w, avg_bits)))
timed("A kernel native-layout", lambda: np.asarray(kern_n(native)))

kern_p = jax.jit(lambda r: jnp.sum(gear_candidates_pallas(r, avg_bits)))
timed("B kernel via pallas wrapper (transposes)",
      lambda: np.asarray(kern_p(rows_flat)))

pre = jnp.zeros((rabin._PREFIX_WORDS,), jnp.uint32)
cap0 = min(max(256, slab_b >> max(avg_bits - 2, 0)), slab_b >> thin_bits)


def extract_fenced():
    occ, offs = rabin._extract_first_occ(
        words, pre, T, stride, avg_bits, cap0, True, thin_bits,
        route="bitmask",
    )
    np.asarray(jnp.sum(occ) + jnp.sum(offs.astype(jnp.uint32)))


timed("C extract_first_occ fenced on device", extract_fenced)

timed("D candidates collect (D2H + host)",
      lambda: rabin.candidates_begin(words, slab_b, avg_bits,
                                     thin_bits=thin_bits)())


def e2e():
    c = rabin.candidates_begin(words, slab_b, avg_bits, thin_bits=thin_bits)
    rabin._greedy_select(c(), slab_b, 1 << (avg_bits - 2),
                         1 << (avg_bits + 2))


timed("E full e2e (collect + greedy)", e2e)

# sub-attribution of the extraction tail: window-reduce alone, in both
# layouts (the transposed (T,S/PACK) one the code uses today vs a
# native-layout leading-axis reduce)
bits_n = gear_candidates_native(native, avg_bits)
jax.block_until_ready(bits_n)
gpw = (1 << thin_bits) // rabin.GROUP  # groups per window


@jax.jit
def reduce_native(bits):
    # (ng, 8, 8, T/8): drop warm-up group 0, then windows of gpw groups
    v = bits[1:]
    nwpt = (ng - 1) // gpw
    v = v.reshape(nwpt, gpw * (rabin.GROUP // rabin.PACK), 8, T // 8)
    # first-set-bit across axis 1 in stream word order, elementwise lanes
    wnz = v != jnp.uint32(0)
    first_w = jnp.argmax(wnz, axis=1).astype(jnp.int32)
    wval = jnp.take_along_axis(v, first_w[:, None], axis=1)[:, 0]
    lsb = wval & (jnp.uint32(0) - wval)
    bitpos = rabin._popcount32(lsb - jnp.uint32(1)).astype(jnp.int32)
    inwin = jnp.where(
        jnp.any(wnz, axis=1),
        first_w * rabin.PACK + bitpos, 1 << 30,
    )
    return jnp.sum(jnp.where(inwin < (1 << 30), inwin, 0))


timed("F window-reduce native-layout (fenced)",
      lambda: np.asarray(reduce_native(bits_n)))

bits_t = gear_candidates_pallas(rows_flat, avg_bits)
jax.block_until_ready(bits_t)
wpw = (1 << thin_bits) // rabin.PACK


@jax.jit
def reduce_transposed(bits):
    vw = bits[:, rabin._PREFIX // rabin.PACK:
              rabin._PREFIX // rabin.PACK + stride // rabin.PACK]
    first = rabin._first_bit_per_window(vw.reshape(-1, wpw))
    return jnp.sum(jnp.where(first < (1 << 30), first, 0))


timed("G window-reduce transposed-layout (fenced)",
      lambda: np.asarray(reduce_transposed(bits_t)))
