"""One gossip replica: a columnar change log + rateless anti-entropy +
an optional fan-out group behind ONE small state machine (ISSUE 15,
ROADMAP item 4).

Everything shipped before this module is pairwise or one-to-many; a
:class:`ReplicaNode` composes those pieces into the N-replica epidemic
shape — convergence from *any* divergence with no distinguished
source:

* the **log** is the PR 6 columnar change log (records are the set
  elements; content identity is the canonical per-record digest the
  digest pipeline already defines);
* **anti-entropy** is PR 10 rateless reconciliation
  (:func:`gossip_exchange` below runs the real codec payloads through
  the PR 2 chaos transport, so flips/truncations/drops land at real
  wire offsets);
* the **fan-out leg** is a PR 9 :class:`~..fanout.log.BroadcastLog`:
  applied repairs are published once and every group follower drains
  them hash-once, with the retention budget and its
  ``SnapshotNeeded`` → PR 12 snapshot-bootstrap arm intact;
* the **steering signal** is the PR 11 fleet plane: gossip round /
  repair / quarantine counters ride the registry and the sidecar
  snapshot (``--replica``).

"Simplicity Scales" is the design yardstick: one replica state machine
(:data:`STATES`), the staged failure vocabulary preserved verbatim
(transport faults retry, corruption is structured, repeated corruption
quarantines), and convergence — byte-identical content digests — as
the only invariant.

Failure contract (ROBUSTNESS.md "Convergence contract"):

* a transport-class failure (drop, truncation, a partitioned link)
  changes NO replica state — the exchange simply did not happen;
* a corruption-class failure surfaces as ONE structured
  :class:`~..wire.framing.ProtocolError` per exchange — never a wrong
  diff, never a partial apply;
* a peer whose exchanges are corrupt ``byzantine_after`` consecutive
  times is **quarantined** with a structured
  :class:`ByzantineDivergence` (peer + arm + wire coordinates); gossip
  continues around it — the mesh converges without the liar.
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from typing import Optional

import numpy as np

from ..fanout.log import BroadcastLog, SnapshotNeeded
from ..obs import propagation as _propagation
from ..obs import wirecost as _wirecost
from ..obs.events import emit as _emit
from ..obs.metrics import OBS as _OBS, counter as _counter
from ..runtime import replay
from ..runtime.reconcile_driver import (
    DEFAULT_BATCH0,
    DEFAULT_OVERHEAD_CAP,
    RatelessReplica,
    ResponderState,
)
from ..session.faults import FaultPlan, FaultyReader, TransportFault
from ..wire import reconcile_codec as rc
from ..wire.change_codec import Change
from ..wire.framing import ProtocolError, frame_wire_len, \
    header_len as _header_len

__all__ = [
    "ByzantineDivergence",
    "PeerQuarantined",
    "ReplicaNode",
    "ByzantineReplicaNode",
    "gossip_exchange",
    "classify_error",
    "STATES",
]

# the one replica state machine ("Simplicity Scales"): a node is idle
# between rounds, gossiping during an exchange, draining its group
# feeds, bootstrapping over the snapshot protocol, or crashed (churn)
STATES = ("idle", "gossip", "fanout", "bootstrap", "crashed")

# default corrupt-exchange threshold before a peer is quarantined: one
# corrupt exchange can be the WIRE (a flipped byte on a chaotic link);
# a repeat offender is a liar, not a bad cable.  Suspicion is
# CUMULATIVE per peer — a byzantine replica that lies only when its
# content is requested (the wrong-chunk shape) cannot launder its
# record by interleaving clean exchanges.  Deployments with genuinely
# lossy long-lived links should raise this per their flip rate.
DEFAULT_BYZANTINE_AFTER = 2

_M_ROUNDS = _counter("gossip.rounds")
_M_EXCHANGES = _counter("gossip.exchanges")
_M_REPAIRS_IN = _counter("gossip.repairs.applied")
_M_REPAIRS_OUT = _counter("gossip.repairs.sent")
_M_QUARANTINES = _counter("gossip.quarantines")
_M_TRANSPORT = _counter("gossip.transport.failures")
_M_CORRUPT = _counter("gossip.corrupt.exchanges")
_M_BOOTSTRAPS = _counter("gossip.bootstraps")

_BAD_LABEL_CHARS = '{},="\n\r'


def _check_key(value: str) -> str:
    # replica keys ride label sets and JSON breakdowns, same boundary
    # contract as hub/fanout/watermark keys
    if not isinstance(value, str) or not value or any(
            c in value for c in _BAD_LABEL_CHARS):
        raise ValueError(
            f"replica key {value!r} must be a non-empty string "
            'containing none of {},=" or newlines')
    return value


class ByzantineDivergence(ProtocolError):
    """A peer's wire provably diverged from its claims: coded symbols
    that cannot have come from a real set, repair records whose content
    does not hash to the digests they answer, or a fan-out ack that
    regresses.  Structured like every error in this stack
    (``frame``/``offset`` wire coordinates) plus the cluster fields:
    ``peer`` names the quarantined replica, ``arm`` the detection arm
    (``wrong-symbol`` / ``wrong-chunk-digest`` / ``ack-regression`` /
    ``feed-corrupt``).  Raising this is the decode-consistency
    contract: divergence is NEVER silent."""

    def __init__(self, message: str, *, peer: str,
                 arm: Optional[str] = None, frame: Optional[int] = None,
                 offset: Optional[int] = None,
                 cause: Optional[BaseException] = None):
        super().__init__(message, frame=frame, offset=offset, cause=cause)
        self.peer = peer
        self.arm = arm


class PeerQuarantined(ProtocolError):
    """Refusal to gossip with a quarantined peer.  Carries the same
    structured coordinates (``peer``, the refusing side's ``offset`` in
    exchanges = its round counter) so a refused dialer can tell this
    apart from a dead link."""

    def __init__(self, message: str, *, peer: str,
                 frame: Optional[int] = None,
                 offset: Optional[int] = None):
        super().__init__(message, frame=frame, offset=offset)
        self.peer = peer


def classify_error(err: BaseException) -> str:
    """The exchange failure taxonomy: ``transport`` (retryable, no
    state changed — drops, truncations, dead links) vs ``corruption``
    (a structured protocol failure — suspicion accrues toward
    quarantine)."""
    if isinstance(err, TransportFault):
        return "transport"
    if isinstance(err, ProtocolError):
        return "corruption"
    return "corruption" if isinstance(err, ValueError) else "transport"


def _content_digest(digests: np.ndarray) -> bytes:
    """The replica content digest: BLAKE2b over the SORTED unique
    canonical record digests — framing- and order-independent, so two
    replicas holding the same record set hash byte-identically no
    matter how their logs interleaved."""
    if len(digests) == 0:
        return hashlib.blake2b(b"", digest_size=32).digest()
    view = np.ascontiguousarray(digests).view("<u8").reshape(len(digests), 4)
    order = np.lexsort(tuple(view[:, i] for i in range(3, -1, -1)))
    return hashlib.blake2b(
        np.ascontiguousarray(digests[order]).tobytes(),
        digest_size=32).digest()


class _ChaosLink:
    """One direction of a gossip link: payloads stream through the PR 2
    fault state, so a plan's flip/truncate/drop coordinates land at
    real accumulated wire offsets across the round's messages."""

    __slots__ = ("_buf", "_reader", "_plan")

    def __init__(self, plan: Optional[FaultPlan]):
        self._plan = plan
        self._buf = bytearray()
        self._reader = None if plan is None else FaultyReader(
            self._pull, plan)

    def _pull(self, n: int) -> bytes:
        take = bytes(self._buf[:max(1, n)])
        del self._buf[:max(1, n)]
        return take

    @property
    def offset(self) -> int:
        return 0 if self._reader is None else self._reader.offset

    def send(self, payload: bytes) -> bytes:
        """Deliver ``payload`` through the link.  Raises
        :class:`TransportFault` on a drop OR a truncation (a short
        delivery is a dead connection at message granularity — the
        session layer's clean-EOF-mid-stream).  Flips arrive as
        corrupted bytes for the codec to refuse."""
        if self._reader is None:
            return payload
        self._buf += payload
        out = bytearray()
        while len(out) < len(payload):
            chunk = self._reader.read(len(payload) - len(out))
            if not chunk:
                raise TransportFault(
                    f"gossip link truncated at byte {self._reader.offset}",
                    offset=self._reader.offset)
            out += chunk
        return bytes(out)


class ReplicaNode:
    """See module docstring.  Thread-safe: the live sidecar drives one
    node from a gossip timer thread AND inbound responder sessions;
    the sim drives it single-threaded."""

    def __init__(self, key: str, records=(), *, seed: int = 0,
                 engine: str = "auto",
                 byzantine_after: int = DEFAULT_BYZANTINE_AFTER,
                 fanout_retention: Optional[int] = None,
                 delivered_form: bool = False):
        self.key = _check_key(key)
        # delivered_form (the LIVE-mesh mode, load_replica_node): the
        # log is normalized to the per-record DELIVERED materialization
        # (absent optionals collapsed to ''/b'', the reference's
        # observed defaults) because that is the form every decoder
        # delivery produces — a live replica whose set kept absent-form
        # digests would re-reconcile those records against its peers
        # forever (ship -> materialize -> re-encode changes identity).
        # The in-process sim keeps the byte-exact wire form; the live
        # drivers' faithful-absent shipping is the ROADMAP follow-on.
        self.delivered_form = bool(delivered_form)
        self._engine = engine
        self._lock = threading.Lock()
        # the log is WIRE BYTES, not row objects: repairs arrive as
        # framed batch/record bytes and are absorbed verbatim, so
        # absent-vs-present-empty optionals (and therefore canonical
        # digests) survive byte-exactly — materializing rows would
        # collapse absent to '' and silently fork the digest set
        # datlint: guarded-by(self._lock): self._wire, self._replica, self._wire_ver
        self._wire = bytearray(self._as_wire(records))
        self._replica: Optional[RatelessReplica] = None
        self._wire_ver = 0
        self.state = "idle"
        self.round = 0
        self.byzantine_after = max(1, int(byzantine_after))
        self.quarantined: dict[str, ByzantineDivergence] = {}
        self._suspect: dict[str, int] = {}
        self._rng = random.Random(seed)
        self.stats = {
            "rounds": 0, "sampled": 0, "exchanges_ok": 0,
            "transport_failures": 0, "corrupt_exchanges": 0,
            "refusals": 0, "repairs_applied": 0, "repairs_sent": 0,
            "quarantines": 0, "bootstraps": 0, "wire_bytes": 0,
        }
        # the fan-out leg: applied repairs are published ONCE into this
        # log; group followers drain it hash-free.  log_gen lets a
        # follower detect a restarted owner (fresh log, fresh offsets)
        # and re-attach at the new window instead of misreading
        # mid-frame.
        self.log: Optional[BroadcastLog] = (
            BroadcastLog(retention_budget=fanout_retention)
            if fanout_retention else None)
        self.log_gen = 0
        # follower-side feed cursors: owner key -> (owner log_gen, off)
        self._feed_pos: dict[str, tuple] = {}
        # owner-side follower acks: follower key -> offset (validated
        # monotonic + <= log.end; a violation is the ack-regression arm)
        self._follower_acks: dict[str, int] = {}

    # -- log ------------------------------------------------------------------

    def _as_wire(self, records) -> bytes:
        """Records (Change objects / dicts) or already-framed wire
        bytes, as wire bytes (normalized to the delivered
        materialization in ``delivered_form`` mode)."""
        if isinstance(records, (bytes, bytearray, memoryview)):
            wire = bytes(records)
            if not self.delivered_form or not wire:
                return wire
            cols, _ = replay.replay_log(np.frombuffer(wire, np.uint8))
            records = [cols.row(i) for i in range(len(cols))]
        records = [Change.from_dict(r) if isinstance(r, dict) else r
                   for r in records]
        if self.delivered_form:
            records = [Change(key=r.key, change=r.change, from_=r.from_,
                              to=r.to, value=r.value or b"",
                              subset=r.subset or "") for r in records]
        return replay.encode_change_log(records) if records else b""

    @property
    def replica(self) -> RatelessReplica:
        """The node's reconciliation state, rebuilt lazily after the
        log changed (RatelessReplica is immutable by design).  The
        build runs OUTSIDE the node lock — it hashes the whole log and
        can reach the one-time native-library load — with a version
        guard: a log mutated mid-build just discards the stale build
        (blocking-under-lock contract)."""
        with self._lock:
            rep = self._replica
            if rep is not None:
                return rep
            wire = bytes(self._wire)
            ver = self._wire_ver
        rep = RatelessReplica(wire)
        with self._lock:
            if self._replica is None and self._wire_ver == ver:
                self._replica = rep
            return self._replica if self._replica is not None else rep

    @property
    def record_count(self) -> int:
        """Distinct record states held (the log may carry duplicate
        frames; identity is the canonical digest set)."""
        return self.replica.n

    def content_digest(self) -> bytes:
        """Byte-identical across replicas holding the same record set —
        the convergence invariant the sweep asserts."""
        return _content_digest(self.replica.digests)

    def canonical_wire(self) -> bytes:
        """The log as framed wire bytes (the snapshot-bootstrap
        dataset and the checkpoint payload)."""
        with self._lock:
            return bytes(self._wire)

    def absorb(self, repairs, count: Optional[int] = None,
               peer: Optional[str] = None) -> int:
        """Append repair wire (or records) to the log verbatim
        (duplicates are harmless — identity is the canonical digest
        set).  Returns the record count absorbed (``count`` when the
        caller already decoded it)."""
        wire = self._as_wire(repairs)
        if not wire:
            return 0
        with self._lock:
            self._wire += wire
            self._replica = None
            self._wire_ver += 1
        n = count if count is not None else len(
            replay.replay_log(np.frombuffer(wire, np.uint8))[0])
        self.stats["repairs_applied"] += n
        if _OBS.on:
            _M_REPAIRS_IN.inc(n)
        return n

    # -- byzantine hooks (overridden by ByzantineReplicaNode) ----------------

    def coded_symbols_out(self, engine: Optional[str] = None):
        return self.replica.coded_symbols(engine or self._engine)

    def ship_wire(self, rows: np.ndarray) -> bytes:
        """Rows as byte-preserving columnar batch frames (absent
        optionals keep their sentinels, so canonical digests survive
        the trip)."""
        return replay.encode_batch_frames(
            self.replica.columns_for_rows(rows))

    def feed_ack_for(self, owner_key: str, offset: int) -> int:
        return offset

    def publish_wire(self, wire: bytes) -> bytes:
        return wire

    # -- sampling / quarantine ------------------------------------------------

    def begin_round(self, rnd: Optional[int] = None) -> None:
        """One jittered-timer tick: advance the round counter (the
        fleet plane's rounds-behind input)."""
        self.round = self.round + 1 if rnd is None else rnd
        self.stats["rounds"] += 1
        if _OBS.on:
            _M_ROUNDS.inc()

    def sample_peer(self, peers) -> Optional[str]:
        """Pick this round's gossip partner: uniform over the known
        peers minus self and the quarantined set."""
        live = [p for p in peers
                if p != self.key and p not in self.quarantined]
        if not live:
            return None
        self.stats["sampled"] += 1
        return self._rng.choice(live)

    def is_quarantined(self, peer: str) -> bool:
        return peer in self.quarantined

    def refuse_if_quarantined(self, peer: str) -> None:
        if peer in self.quarantined:
            raise PeerQuarantined(
                f"replica {self.key!r} refuses {peer!r}: quarantined "
                f"({self.quarantined[peer].arm})",
                peer=peer, offset=self.round)

    def note_success(self, peer: str) -> None:
        # deliberately does NOT clear suspicion: corruption suspicion
        # is cumulative per peer (see DEFAULT_BYZANTINE_AFTER) — clean
        # exchanges do not launder a liar's record
        self.stats["exchanges_ok"] += 1
        if _OBS.on:
            _M_EXCHANGES.inc()

    def note_transport_failure(self, peer: str) -> None:
        self.stats["transport_failures"] += 1
        if _OBS.on:
            _M_TRANSPORT.inc()

    def note_corruption(self, peer: str,
                        err: BaseException) -> Optional[ByzantineDivergence]:
        """Corruption-class failure with ``peer``: accrue suspicion;
        at ``byzantine_after`` cumulative corrupt exchanges the peer
        is quarantined and the structured divergence returned."""
        self.stats["corrupt_exchanges"] += 1
        if _OBS.on:
            _M_CORRUPT.inc()
        n = self._suspect.get(peer, 0) + 1
        self._suspect[peer] = n
        if n < self.byzantine_after or peer in self.quarantined:
            return None
        return self.quarantine(peer, err)

    def quarantine(self, peer: str,
                   err: BaseException) -> ByzantineDivergence:
        """Quarantine ``peer`` with a structured divergence record;
        gossip continues around it (sampling skips it, inbound
        exchanges are refused with :class:`PeerQuarantined`)."""
        if isinstance(err, ByzantineDivergence) and err.peer == peer:
            div = err
        else:
            div = ByzantineDivergence(
                f"replica {peer!r} quarantined by {self.key!r}: {err}",
                peer=peer,
                arm=getattr(err, "arm", None) or "wrong-symbol",
                frame=getattr(err, "frame", None),
                offset=getattr(err, "offset", None), cause=err)
        self.quarantined[peer] = div
        self._suspect.pop(peer, None)
        self.stats["quarantines"] += 1
        if _OBS.on:
            _M_QUARANTINES.inc()
            _emit("gossip.quarantine", replica=self.key, peer=peer,
                  arm=div.arm or "?", offset=div.offset or 0)
        return div

    # -- fan-out leg ----------------------------------------------------------

    def publish_repairs(self, wire: bytes) -> int:
        """Publish applied repair WIRE into the broadcast log —
        hash-once economics: the bytes that crossed the gossip link
        are republished verbatim, every follower drains views of the
        same bytes, nothing is re-encoded or re-hashed here."""
        if self.log is None or not wire:
            return 0
        wire = self.publish_wire(bytes(wire))
        self.log.append(wire)
        return len(wire)

    def note_follower_ack(self, follower: str, offset: int) -> None:
        """Owner-side ack validation (the fan-out byzantine arm): an
        ack that regresses or claims bytes never produced is a liar,
        not flow control."""
        if self.log is None:
            return
        last = self._follower_acks.get(follower, 0)
        if offset < last or offset > self.log.end:
            div = ByzantineDivergence(
                f"byzantine ack from {follower!r}: offset {offset} "
                f"outside [{last}, {self.log.end}]",
                peer=follower, arm="ack-regression", offset=offset)
            self.quarantine(follower, div)
            raise div
        self._follower_acks[follower] = offset

    def drain_feed(self, owner: "ReplicaNode") -> int:
        """Follower-side group drain: pull the owner's new broadcast
        bytes, decode, absorb.  Raises :class:`SnapshotNeeded` when the
        retention budget trimmed past this follower (the caller runs
        the PR 12 bootstrap), :class:`ByzantineDivergence` on a feed
        that does not parse."""
        if owner.log is None or self.is_quarantined(owner.key):
            return 0
        self.state = "fanout"
        try:
            gen, off = self._feed_pos.get(owner.key, (owner.log_gen, 0))
            if gen != owner.log_gen:
                # the owner restarted: fresh log, fresh offsets — re-
                # attach at the start of its retained window (a real
                # subscriber would renegotiate its attach the same way)
                gen, off = owner.log_gen, owner.log.start
            data = owner.log.read_from(off)  # raises SnapshotNeeded
            if not data:
                self._feed_pos[owner.key] = (gen, off)
                return 0
            try:
                cols, _ = replay.replay_log(
                    np.frombuffer(data, np.uint8))
            except (ValueError, ProtocolError) as e:
                div = ByzantineDivergence(
                    f"broadcast feed from {owner.key!r} does not parse "
                    f"at byte {off}: {e}",
                    peer=owner.key, arm="feed-corrupt", offset=off,
                    cause=e)
                self.quarantine(owner.key, div)
                raise div from e
            self.absorb(data, count=len(cols), peer=owner.key)
            new_off = off + len(data)
            self._feed_pos[owner.key] = (gen, new_off)
            owner.note_follower_ack(
                self.key, self.feed_ack_for(owner.key, new_off))
            return len(cols)
        finally:
            self.state = "idle"

    # -- bootstrap (PR 12) ----------------------------------------------------

    def bootstrap_from(self, owner: "ReplicaNode") -> dict:
        """Churn/flash-crowd recovery over the content-addressed
        snapshot protocol: fetch the owner's dataset as verified chunks
        (O(diff) for a stale log, the shared cold log for an empty
        one), merge with everything this node already holds, and
        re-attach the feed cursor at the owner's live window."""
        from ..runtime.snapshot_driver import SnapshotSource, snapshot_local

        self.state = "bootstrap"
        try:
            have = self.canonical_wire() or None
            res = snapshot_local(SnapshotSource(owner.canonical_wire()),
                                 have=have, engine=self._engine)
            self.absorb(res["data"], peer=owner.key)
            if owner.log is not None:
                self._feed_pos[owner.key] = (owner.log_gen, owner.log.end)
            self.stats["bootstraps"] += 1
            self.stats["wire_bytes"] += res["wire_bytes"]
            if _OBS.on:
                _M_BOOTSTRAPS.inc()
                _emit("gossip.bootstrap", replica=self.key,
                      owner=owner.key, wire_bytes=res["wire_bytes"])
            return res
        finally:
            self.state = "idle"

    # -- churn ----------------------------------------------------------------

    def checkpoint(self) -> dict:
        """Restartable state: the log as wire bytes plus the cursors a
        resumed node needs (round counter, feed positions, log
        window)."""
        with self._lock:
            wire = bytes(self._wire)
        return {
            "key": self.key,
            "round": self.round,
            "wire": wire,
            "feeds": dict(self._feed_pos),
            "log_end": None if self.log is None else self.log.end,
            "delivered_form": self.delivered_form,
        }

    @classmethod
    def from_checkpoint(cls, ckpt: dict, **kw) -> "ReplicaNode":
        """Churn restart: rebuild from :meth:`checkpoint`.  The
        broadcast log restarts EMPTY on a fresh generation — followers
        detect the generation change and re-attach; anything this node
        published after the checkpoint re-spreads through normal
        gossip."""
        kw.setdefault("delivered_form", ckpt.get("delivered_form", False))
        node = cls(ckpt["key"], ckpt["wire"], **kw)
        node.round = ckpt["round"]
        node._feed_pos = dict(ckpt["feeds"])
        node.log_gen = 1  # a restart is a new feed generation
        return node

    def crash(self) -> None:
        self.state = "crashed"

    # -- telemetry ------------------------------------------------------------

    def snapshot(self) -> dict:
        """The gossip record ``--stats-fd`` / ``/snapshot`` carry (the
        fleet plane's per-replica convergence input)."""
        return {
            "replica": self.key,
            "state": self.state,
            "round": self.round,
            "records": self.record_count,
            "digest": self.content_digest().hex(),
            "quarantined": sorted(self.quarantined),
            # quarantine PROVENANCE (ISSUE 19): the structured
            # ByzantineDivergence coordinates, so the fleet matrix can
            # show not just THAT a peer is out but which arm caught it
            # and where on the wire — checkable against the byzantine
            # injector's ground truth
            "quarantine": {
                peer: {"arm": err.arm, "frame": err.frame,
                       "offset": err.offset}
                for peer, err in sorted(self.quarantined.items())
            },
            "suspicion": {k: v for k, v in sorted(self._suspect.items())},
            **{k: v for k, v in self.stats.items()},
        }


class ByzantineReplicaNode(ReplicaNode):
    """The adversary: a replica that lies on one arm of the protocol.
    The injector side of the byzantine oracle — tests know exactly what
    it corrupts, so every quarantine can be checked against ground
    truth.  ``arm``:

    * ``wrong-symbol`` — coded symbols XOR-corrupted after the build:
      checksums cannot verify, the peel never completes, the responder
      fails structurally at its symbol cap;
    * ``wrong-chunk`` — repair records shipped with corrupted content:
      the receiving side's digest verification refuses the whole
      apply (``wrong-chunk-digest``);
    * ``ack-regression`` — fan-out feed acks regress: the owner's ack
      validation quarantines the follower;
    * ``feed-corrupt`` — published broadcast wire is corrupted: the
      follower's decode refuses the feed.
    """

    ARMS = ("wrong-symbol", "wrong-chunk", "ack-regression",
            "feed-corrupt")

    def __init__(self, key: str, records=(), *, arm: str = "wrong-symbol",
                 **kw):
        if arm not in self.ARMS:
            raise ValueError(f"unknown byzantine arm {arm!r}")
        super().__init__(key, records, **kw)
        self.arm = arm
        self._evil_rng = random.Random(0xBAD)
        self._ack_memo: dict[str, int] = {}

    def coded_symbols_out(self, engine: Optional[str] = None):
        syms = super().coded_symbols_out(engine)
        if self.arm != "wrong-symbol":
            return syms
        outer = self

        class _Corrupt:
            def extend(self, m: int) -> np.ndarray:
                cells = np.array(syms.extend(m), copy=True)
                if len(cells):
                    # flip digest words in every cell: the 64-bit
                    # checksums cannot verify, no pure cell ever peels
                    cells[:, 3] ^= np.uint32(
                        outer._evil_rng.randrange(1, 1 << 30))
                return cells

        return _Corrupt()

    def ship_wire(self, rows: np.ndarray) -> bytes:
        if self.arm != "wrong-chunk":
            return super().ship_wire(rows)
        # structurally valid records whose content no longer hashes to
        # the digests they answer — the wrong-chunk-digest arm
        out = []
        for r in self.replica.records_for_rows(rows):
            v = bytearray(r.value or b"\x00")
            v[0] ^= 0xFF
            out.append(Change(key=r.key, change=r.change, from_=r.from_,
                              to=r.to, value=bytes(v), subset=r.subset))
        return replay.encode_change_log(out)

    def feed_ack_for(self, owner_key: str, offset: int) -> int:
        if self.arm != "ack-regression":
            return offset
        prev = self._ack_memo.get(owner_key)
        self._ack_memo[owner_key] = offset
        if prev is None:
            return offset  # first ack honest: establish a frontier...
        # ...then regress behind it — provably byzantine, whatever the
        # real drain position did
        return max(0, prev - 1 - self._evil_rng.randrange(4))

    def publish_wire(self, wire: bytes) -> bytes:
        if self.arm != "feed-corrupt" or len(wire) < 2:
            return wire
        b = bytearray(wire)
        b[0] ^= 0x80  # torn frame header: followers cannot parse
        return bytes(b)


# -- the exchange engine ------------------------------------------------------


def gossip_exchange(initiator: ReplicaNode, responder: ReplicaNode, *,
                    plan_out: Optional[FaultPlan] = None,
                    plan_back: Optional[FaultPlan] = None,
                    engine: str = "auto", batch0: int = DEFAULT_BATCH0,
                    overhead_cap: float = DEFAULT_OVERHEAD_CAP) -> dict:
    """One anti-entropy exchange between two nodes, message-metered
    like :func:`~..runtime.reconcile_driver.reconcile_local` but with
    every payload streamed through the chaos transport
    (:class:`_ChaosLink` per direction).

    On success both nodes have absorbed exactly the symmetric
    difference and the stats dict reports wire/symbol/repair counts.
    Failure is the taxonomy :func:`classify_error` names: transport
    faults left both logs untouched; corruption raised ONE structured
    ProtocolError (a :class:`ByzantineDivergence` when the responder's
    verification caught provably-wrong content) — never a wrong diff,
    never a partial apply."""
    responder.refuse_if_quarantined(initiator.key)
    initiator.refuse_if_quarantined(responder.key)
    initiator.state = responder.state = "gossip"
    # the ISSUE 19 lit/dark fork (PR 18 discipline): the dark twin
    # `_exchange` references NO propagation symbol — asserted at the
    # bytecode level — so the disabled cost of the whole convergence
    # plane is the one `_OBS.on` attribute load below
    try:
        if _OBS.on:
            return _exchange_lit(initiator, responder, plan_out,
                                 plan_back, engine, batch0, overhead_cap)
        return _exchange(initiator, responder, plan_out, plan_back,
                         engine, batch0, overhead_cap)
    finally:
        initiator.state = responder.state = "idle"


def _exchange(initiator, responder, plan_out, plan_back, engine,
              batch0, overhead_cap) -> dict:
    rep_a = initiator.replica
    rep_b = responder.replica
    state = ResponderState(rep_b, engine=engine, overhead_cap=overhead_cap)
    out_link = _ChaosLink(plan_out)
    back_link = _ChaosLink(plan_back)
    # per-direction byte meter; the *_framing/*_msgs halves are plain
    # arithmetic the lit twin turns into the wire cost ledger's
    # payload/framing split (this function stays the DARK twin: no
    # telemetry symbol, just two integer adds per message)
    wire = {"a2b": 0, "b2a": 0, "a2b_framing": 0, "b2a_framing": 0,
            "a2b_msgs": 0, "b2a_msgs": 0}
    msg_i = {"n": 0}

    def corrupt(side: str, e: Exception) -> ProtocolError:
        return ProtocolError(
            f"corrupt gossip payload ({side}): {e}",
            frame=msg_i["n"], offset=wire["a2b"] + wire["b2a"], cause=e)

    def a2b(payload: bytes) -> list:
        """One initiator->responder message; returns the decoded
        replies that survived the back link."""
        msg_i["n"] += 1
        wire["a2b"] += frame_wire_len(len(payload))
        wire["a2b_framing"] += _header_len(len(payload))
        wire["a2b_msgs"] += 1
        got = out_link.send(payload)
        try:
            msg = rc.decode_reconcile(got)
        except ValueError as e:
            raise corrupt("initiator->responder", e) from e
        replies = state.handle(msg)
        out = []
        for r in replies:
            wire["b2a"] += frame_wire_len(len(r))
            wire["b2a_framing"] += _header_len(len(r))
            wire["b2a_msgs"] += 1
            got_r = back_link.send(r)
            try:
                out.append(rc.decode_reconcile(got_r))
            except ValueError as e:
                raise corrupt("responder->initiator", e) from e
        return out

    syms = initiator.coded_symbols_out(engine)
    replies = a2b(rc.encode_begin(rep_a.n))
    sent = 0
    rounds = 0
    final = None
    while final is None:
        if replies and replies[-1].kind in (rc.RC_DONE, rc.RC_FAIL):
            final = replies[-1]
            break
        m = batch0 if sent == 0 else sent * 2
        cells = syms.extend(m)[sent:]
        payload = rc.encode_symbols(sent, cells)
        sent = m
        rounds += 1
        replies = a2b(payload)
    if final.kind == rc.RC_FAIL:
        state.result()  # raises the responder's structured error
    # -- record exchange: both directions travel the chaos links, both
    # are verified, and NOTHING is absorbed until every wire crossing
    # succeeded — a transport fault mid-shipment leaves both logs
    # exactly as they were (the no-partial-apply contract)
    wants = final.digests
    rows = rep_a.rows_for_digests(wants)
    if (rows < 0).any():
        raise ProtocolError(
            "peer requested records this replica does not hold",
            frame=msg_i["n"], offset=wire["a2b"] + wire["b2a"])
    for_responder = for_initiator = None
    n_for_b = n_for_a = 0
    if len(rows):
        batch = initiator.ship_wire(rows)
        wire["a2b"] += len(batch)
        got = out_link.send(batch)
        n_for_b = _verify_repairs(got, wants, corrupt,
                                  "initiator->responder",
                                  initiator.key, msg_i["n"],
                                  wire["a2b"] + wire["b2a"])
        for_responder = got
    b_rows = state.local_only_rows()
    if len(b_rows):
        batch = responder.ship_wire(b_rows)
        wire["b2a"] += len(batch)
        got = back_link.send(batch)
        # structural validity only in this direction: the initiator
        # has no digest expectation for the responder's local-only set
        # (that is the protocol's information asymmetry) — content
        # identity is re-derived from the bytes themselves
        n_for_a = _decoded_rows(got, corrupt, "responder->initiator")
        for_initiator = got
    # -- commit point ---------------------------------------------------------
    applied_b = applied_a = 0
    if for_responder:
        applied_b = responder.absorb(for_responder, count=n_for_b,
                                     peer=initiator.key)
        initiator.stats["repairs_sent"] += len(rows)
        if _OBS.on:
            _M_REPAIRS_OUT.inc(len(rows))
    if for_initiator:
        applied_a = initiator.absorb(for_initiator, count=n_for_a,
                                     peer=responder.key)
        responder.stats["repairs_sent"] += len(b_rows)
        if _OBS.on:
            _M_REPAIRS_OUT.inc(len(b_rows))
    total = wire["a2b"] + wire["b2a"]
    initiator.stats["wire_bytes"] += total
    responder.stats["wire_bytes"] += total
    return {
        "ok": True,
        "wire_bytes": total,
        "wire_a2b": wire["a2b"],
        "wire_b2a": wire["b2a"],
        "framing_a2b": wire["a2b_framing"],
        "framing_b2a": wire["b2a_framing"],
        "msgs_a2b": wire["a2b_msgs"],
        "msgs_b2a": wire["b2a_msgs"],
        "symbols": sent,
        "rounds": rounds,
        "diff": int(len(wants) + len(b_rows)),
        "applied_initiator": applied_a,
        "applied_responder": applied_b,
        "wire_initiator": for_initiator or b"",
        "wire_responder": for_responder or b"",
        "want_digests": wants,
    }


def _exchange_lit(initiator, responder, plan_out, plan_back, engine,
                  batch0, overhead_cap) -> dict:
    """The lit twin of :func:`_exchange` (ISSUE 19): same engine, plus
    one ``gossip.exchange`` provenance record per direction and the
    divergence/frontier watermarks — the diff size IS the exchange's
    own peel result, the delivered digest prefixes are the edges of
    the meshdoctor's propagation tree.  Reached only through the
    ``_OBS.on`` fork in :func:`gossip_exchange`."""
    rnd = max(initiator.round, responder.round)
    t0 = time.monotonic()
    try:
        res = _exchange(initiator, responder, plan_out, plan_back,
                        engine, batch0, overhead_cap)
    except Exception as e:
        seconds = time.monotonic() - t0
        outcome = classify_error(e)
        err = f"{type(e).__name__}: {e}"
        for a, b, role in ((initiator, responder, "initiator"),
                           (responder, initiator, "responder")):
            _propagation.record_exchange(
                a.key, b.key, role=role, rnd=rnd, outcome=outcome,
                seconds=seconds, t0=t0, error=err)
            # the wire cost doctrine (ISSUE 20): a faulted exchange
            # leaves every watermark where it was — only the failure
            # counter moves (fabricated ratios would read as healthy)
            _wirecost.note_failure(f"{a.key}->{b.key}", "tx", err)
        raise
    seconds = time.monotonic() - t0
    outcome = "converged" if res["diff"] == 0 else "progress"
    deliv_i = deliv_r = ()
    if res["wire_responder"]:
        deliv_r = _propagation.digest_prefixes(res["want_digests"])
    if res["wire_initiator"]:
        deliv_i = _propagation.digest_prefixes(RatelessReplica(
            np.frombuffer(res["wire_initiator"], np.uint8)).digests)
    repair = len(res["wire_initiator"]) + len(res["wire_responder"])
    _propagation.record_exchange(
        initiator.key, responder.key, role="initiator", rnd=rnd,
        outcome=outcome, seconds=seconds, diff=res["diff"],
        wire_bytes=res["wire_bytes"], repair_bytes=repair,
        delivered=deliv_i, delivered_peer=deliv_r, t0=t0)
    _propagation.record_exchange(
        responder.key, initiator.key, role="responder", rnd=rnd,
        outcome=outcome, seconds=seconds, diff=res["diff"],
        wire_bytes=res["wire_bytes"], repair_bytes=repair,
        delivered=deliv_r, delivered_peer=deliv_i, t0=t0)
    for node in (initiator, responder):
        _propagation.note_frontier(node.key, node.content_digest().hex(),
                                   node.record_count, rnd)
    # -- wire cost ledger (ISSUE 20): the exchange meter's per-direction
    # totals, split exactly — symbol/control traffic is class
    # `reconcile` (payload vs framing from the dark twin's arithmetic),
    # shipped repair batches are class `change_batch` (already-framed
    # journal bytes), and the direction total anchors the tiling audit
    # as transport ground truth.  Directed link names match the
    # propagation board's (`replica->peer`).
    rep_r = len(res["wire_responder"])  # repair bytes a->b
    rep_i = len(res["wire_initiator"])  # repair bytes b->a
    for link, wire_total, framing, msgs, rep in (
            (f"{initiator.key}->{responder.key}", res["wire_a2b"],
             res["framing_a2b"], res["msgs_a2b"], rep_r),
            (f"{responder.key}->{initiator.key}", res["wire_b2a"],
             res["framing_b2a"], res["msgs_b2a"], rep_i)):
        _wirecost.account("reconcile", link, "tx",
                          wire_total - framing - rep, framing, msgs)
        if rep:
            _wirecost.account("change_batch", link, "tx", rep, 0)
            _wirecost.note_diff(link, "tx", rep)
        _wirecost.note_transport(link, "tx", wire_total)
    return res


def _decoded_rows(data: bytes, corrupt, side: str) -> int:
    """Structural validation of a repair batch: the row count, or the
    exchange's ONE structured error."""
    try:
        cols, _ = replay.replay_log(np.frombuffer(data, np.uint8))
        return len(cols)
    except (ValueError, ProtocolError) as e:
        raise corrupt(side, e) from e


def _verify_repairs(data: bytes, wants: np.ndarray, corrupt, side: str,
                    peer: str, frame: int, offset: int) -> int:
    """The decode-consistency check at apply time: the records shipped
    to answer a want list must hash EXACTLY to the wanted digest set —
    wrong content, extra records, or missing records all refuse the
    whole apply with a structured divergence (never a partial or
    silently-wrong log).  Returns the row count for accounting."""
    try:
        got_rep = RatelessReplica(
            np.frombuffer(data, np.uint8))
    except (ValueError, ProtocolError) as e:
        raise corrupt(side, e) from e
    got = got_rep.digests
    want = np.ascontiguousarray(wants)
    if len(got) == len(want):
        if {bytes(d) for d in got} == {bytes(d) for d in want}:
            return len(got_rep.cols)
    raise ByzantineDivergence(
        f"repair records from {peer!r} do not hash to the requested "
        f"digest set ({len(got)} distinct received, {len(want)} "
        f"requested)", peer=peer, arm="wrong-chunk-digest", frame=frame,
        offset=offset)
