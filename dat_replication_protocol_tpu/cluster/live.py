"""The live gossip leg: a :class:`~.node.ReplicaNode` dialing real
peers over TCP (the sidecar ``--replica`` mode).

One daemon sidecar serves inbound reconcile sessions against the
node's CURRENT log (:func:`serve_responder_session`) while the
:class:`GossipDriver` timer thread periodically — on a jittered
:class:`~..session.reconnect.BackoffPolicy` schedule, so N replicas
started together do not phase-lock their dials — samples a peer
address, runs one PR 10 reconciliation as the initiator, and absorbs
the records received.  Both directions mutate the same node under its
lock; convergence needs no coordinator, only the timer.

Failure taxonomy is the node's: connection errors are transport-class
(the peer may be down or partitioned — retry later), structured
protocol failures accrue suspicion, and ``byzantine_after``
consecutive corrupt exchanges quarantine the address (gossip continues
around it).  Counters ride the ``gossip.*`` registry names and the
:meth:`GossipDriver.snapshot` record the sidecar's ``--stats-fd`` /
``/snapshot`` lines carry — the fleet plane's rounds-behind input.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Optional

from ..obs import propagation as _propagation
from ..obs.metrics import OBS as _OBS, counter as _counter
from ..runtime.reconcile_driver import run_initiator, run_responder
from ..session.reconnect import BackoffPolicy
from ..wire.framing import ProtocolError
from .node import ReplicaNode, classify_error

__all__ = ["GossipDriver", "serve_responder_session",
           "absorb_responder_stats"]

_M_DIALS = _counter("gossip.dials")

DEFAULT_INTERVAL = 1.0
DIAL_TIMEOUT = 10.0


def absorb_responder_stats(node: ReplicaNode, stats: dict) -> dict:
    """Fold one completed responder exchange into the node: absorb the
    initiator's records, stamp ``applied``, count repairs shipped.
    Shared by the threaded :func:`serve_responder_session` and the
    event-driven edge's replica sessions (ISSUE 17) — the mutation
    rides the node's own lock inside ``absorb`` either way."""
    applied = node.absorb(stats["received"]) if stats["received"] else 0
    stats["applied"] = applied
    if stats.get("records_sent"):
        node.stats["repairs_sent"] += stats["records_sent"]
    return stats


def serve_responder_session(node: ReplicaNode, read_bytes, write_bytes,
                            close_write=None, *,
                            peer: str = "inbound") -> dict:
    """Serve one inbound anti-entropy session against the node's
    current replica state and absorb whatever the initiator shipped.
    Returns the responder stats dict (``run_responder``'s, plus
    ``applied``); raises the session's ONE structured ProtocolError on
    a failed decode.  ``peer`` labels the provenance record when the
    transport knows the dialer (the event-driven edge passes the
    remote address; the bare TCP leg cannot)."""
    t0 = time.monotonic()
    try:
        stats = run_responder(node.replica, read_bytes, write_bytes,
                              close_write=close_write)
        out = absorb_responder_stats(node, stats)
    except Exception as e:
        if _OBS.on:
            _propagation.record_exchange(
                node.key, peer, role="responder", rnd=node.round,
                outcome=classify_error(e),
                seconds=time.monotonic() - t0,
                error=f"{type(e).__name__}: {e}")
        raise
    if _OBS.on:
        diff = out["applied"] + out.get("records_sent", 0)
        _propagation.record_exchange(
            node.key, peer, role="responder", rnd=node.round,
            outcome="converged" if diff == 0 else "progress",
            seconds=time.monotonic() - t0, diff=diff,
            wire_bytes=len(out.get("received") or b""),
            repair_bytes=len(out.get("received") or b""))
        _propagation.note_frontier(node.key, node.content_digest().hex(),
                                   node.record_count, node.round)
    return out


class GossipDriver:
    """See module docstring.  ``peers`` is a list of ``host:port``
    strings (other ``--replica`` sidecars)."""

    def __init__(self, node: ReplicaNode, peers, *,
                 interval: float = DEFAULT_INTERVAL,
                 policy: Optional[BackoffPolicy] = None,
                 seed: Optional[int] = None,
                 dial_timeout: float = DIAL_TIMEOUT):
        self.node = node
        self.peers = [p for p in peers if p]
        if not self.peers:
            raise ValueError("gossip needs at least one peer address")
        self.interval = interval
        # the jittered round timer IS a BackoffPolicy: attempt 1 with
        # base=interval sleeps uniform(0, 2*interval) — mean one
        # interval, never phase-locked; consecutive all-transport
        # rounds escalate the attempt, so a fully-partitioned replica
        # backs off instead of hammering dead links
        self._policy = policy if policy is not None else BackoffPolicy(
            base=interval, cap=interval * 8, max_retries=1 << 30,
            seed=seed)
        self._dial_timeout = dial_timeout
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._failed_streak = 0
        self.peer_stats = {p: {"ok": 0, "transport": 0, "corrupt": 0}
                           for p in self.peers}
        # monotonic stamp of the last SUCCESSFUL exchange per peer: a
        # silently-dead link shows up as a growing age, not a frozen
        # counter (ISSUE 19 satellite; surfaced by snapshot())
        self._last_success: dict[str, Optional[float]] = {
            p: None for p in self.peers}
        self._thread = threading.Thread(
            target=self._run, name=f"gossip-{node.key}", daemon=True)

    def start(self) -> "GossipDriver":
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    # -- one round -----------------------------------------------------------

    def gossip_once(self) -> Optional[dict]:
        """One dial + exchange (also callable synchronously from
        tests).  Returns the initiator stats on success, None on a
        transport-class failure (the peer keeps its suspicion
        counters)."""
        node = self.node
        node.begin_round()
        addr = node.sample_peer(self.peers)
        if addr is None:
            return None
        host, _, port = addr.rpartition(":")
        t0 = time.monotonic()
        if _OBS.on:
            _M_DIALS.inc()
        try:
            conn = socket.create_connection(
                (host or "127.0.0.1", int(port)),
                timeout=self._dial_timeout)
        except OSError as e:
            node.note_transport_failure(addr)
            self.peer_stats[addr]["transport"] += 1
            if _OBS.on:
                self._record_lit("transport", addr, t0, error=str(e))
            return None
        try:
            # kernel-level timeouts, NOT settimeout(): Python's timeout
            # mode flips the fd to O_NONBLOCK, which the raw-fd pump
            # route cannot ride — SO_RCVTIMEO/SO_SNDTIMEO keep the
            # socket blocking and surface a wedged peer as EAGAIN on
            # either route (classified transport, round abandoned)
            # bounded by the SO_RCVTIMEO/SO_SNDTIMEO set immediately
            # below — settimeout(T) would flip the fd to O_NONBLOCK,
            # which the raw-fd pump route cannot ride
            # datlint: disable=unbounded-join -- SO_RCVTIMEO+SO_SNDTIMEO set below bound every op at the kernel
            conn.settimeout(None)
            tv = struct.pack(
                "ll", int(self._dial_timeout),
                int((self._dial_timeout % 1.0) * 1_000_000))
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
            # through the pump selector, like every other sidecar
            # session leg: DAT_PUMP=native upgrades the dial half of
            # the exchange too (the PR 14 zero-new-flags contract)
            from ..session.pump import io_for_socket

            rd, wr = io_for_socket(conn)
            stats = run_initiator(
                node.replica, rd, wr,
                close_write=lambda: conn.shutdown(socket.SHUT_WR))
        except ProtocolError as e:
            if classify_error(e) == "corruption":
                self.peer_stats[addr]["corrupt"] += 1
                node.note_corruption(addr, e)
                if _OBS.on:
                    self._record_lit("corruption", addr, t0,
                                     error=f"{type(e).__name__}: {e}")
            else:
                node.note_transport_failure(addr)
                self.peer_stats[addr]["transport"] += 1
                if _OBS.on:
                    self._record_lit("transport", addr, t0, error=str(e))
            return None
        except OSError as e:
            node.note_transport_failure(addr)
            self.peer_stats[addr]["transport"] += 1
            if _OBS.on:
                self._record_lit("transport", addr, t0, error=str(e))
            return None
        finally:
            try:
                conn.close()
            except OSError:
                pass
        node.note_success(addr)
        self.peer_stats[addr]["ok"] += 1
        self._last_success[addr] = time.monotonic()
        applied = node.absorb(stats["received"]) if stats["received"] \
            else 0
        if stats.get("records_sent"):
            node.stats["repairs_sent"] += stats["records_sent"]
        if _OBS.on:
            diff = applied + stats.get("records_sent", 0)
            self._record_lit(
                "converged" if diff == 0 else "progress", addr, t0,
                diff=diff, wire_bytes=len(stats.get("received") or b""))
            _propagation.note_frontier(
                node.key, node.content_digest().hex(),
                node.record_count, node.round)
        return stats

    def _record_lit(self, outcome: str, addr: str, t0: float, *,
                    diff: Optional[int] = None, wire_bytes: int = 0,
                    error: Optional[str] = None) -> None:
        """One lit-path provenance record for the dial leg (the live
        initiator never goes through :func:`~.node.gossip_exchange`,
        so it records its own direction here)."""
        _propagation.record_exchange(
            self.node.key, addr, role="initiator", rnd=self.node.round,
            outcome=outcome, seconds=time.monotonic() - t0, diff=diff,
            wire_bytes=wire_bytes, repair_bytes=wire_bytes, t0=t0,
            error=error)

    def _run(self) -> None:
        while not self._stop.is_set():
            # jittered wait FIRST: N replicas started together must
            # not all dial at t=0
            attempt = 1 + min(6, self._failed_streak)
            self._stop.wait(self._policy.delay(attempt))
            if self._stop.is_set():
                return
            try:
                ok = self.gossip_once() is not None
            except Exception:
                ok = False  # a dying exchange never kills the timer
            self._failed_streak = 0 if ok else self._failed_streak + 1

    # -- telemetry -----------------------------------------------------------

    def snapshot(self) -> dict:
        """The gossip record ``--stats-fd`` / ``/snapshot`` carry.
        Each peer entry grows ``last_success_age_s`` (None until the
        first success — a silently-dead link is a growing age, not a
        frozen counter) and the node's cumulative ``suspicion`` toward
        that address (ISSUE 19 satellite)."""
        out = self.node.snapshot()
        out["interval"] = self.interval
        now = time.monotonic()
        peers = {}
        for addr, st in self.peer_stats.items():
            entry = dict(st)
            last = self._last_success.get(addr)
            entry["last_success_age_s"] = (
                None if last is None else round(now - last, 6))
            entry["suspicion"] = self.node._suspect.get(addr, 0)
            peers[addr] = entry
        out["peers"] = peers
        return out
