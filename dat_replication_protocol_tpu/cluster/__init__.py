"""N-replica epidemic anti-entropy: the gossip mesh (ISSUE 15,
ROADMAP item 4).

``cluster`` composes the pairwise and one-to-many pieces the stack
already proves — PR 10 rateless reconciliation, PR 9 broadcast
fan-out, PR 12 snapshot bootstrap, PR 2 chaos transport, PR 11 fleet
watermarks — into a replica-set runtime with no distinguished source:

* :class:`~.node.ReplicaNode` — one replica's state machine;
* :func:`~.node.gossip_exchange` — one chaos-capable anti-entropy
  exchange (exact diff, ONE structured error, or a clean transport
  failure — never a wrong diff, never a partial apply);
* :class:`~.sim.ClusterSim` — the in-process acceptance harness
  (partitions that heal, churn, flash crowds, byzantine replicas);
* :class:`~.live.GossipDriver` — the sidecar ``--replica`` timer loop
  dialing real peers over TCP.

See ROBUSTNESS.md "Convergence contract" and DESIGN.md §10.
"""

from .live import GossipDriver, serve_responder_session
from .node import (
    ByzantineDivergence,
    ByzantineReplicaNode,
    PeerQuarantined,
    ReplicaNode,
    classify_error,
    gossip_exchange,
)
from .sim import ClusterSim

__all__ = [
    "ByzantineDivergence",
    "ByzantineReplicaNode",
    "PeerQuarantined",
    "ReplicaNode",
    "ClusterSim",
    "GossipDriver",
    "classify_error",
    "gossip_exchange",
    "serve_responder_session",
]
