"""In-process cluster harness: N replicas over FaultPlan-seeded links.

The acceptance layer for the gossip mesh (ISSUE 15): everything is
derived from ONE seed — the record sets, the peer sampling, the link
chaos, the partition cut and its heal round, the churn schedule, the
flash-crowd join, the byzantine replica and its arm — so a failing
seed is a reproducer, not a flake (the PR 2 doctrine, applied to a
whole cluster).

One :meth:`ClusterSim.step` is one gossip round:

1. scheduled events fire (churn crash/restart, flash-crowd joins,
   periodic checkpoints);
2. every alive replica samples a peer and runs one
   :func:`~.node.gossip_exchange` over the link's chaos plans
   (:meth:`~..session.faults.FaultPlan.for_sweep` partition/link
   axis) — transport failures change nothing, corruption surfaces
   structurally, repeated corruption quarantines;
3. the fan-out leg drains every follower's broadcast feed (applied
   repairs spread hash-once); the retention budget is enforced, and a
   follower trimmed past bootstraps over the PR 12 snapshot protocol;
4. convergence is evaluated: the run converges when every healthy
   replica's content digest is byte-identical (and, with no byzantine
   replica, equal to the ground-truth union).

:meth:`ClusterSim.run` drives rounds until convergence or the bounded
round budget (:meth:`rounds_bound`) runs out — the bound is asserted
by the chaos sweep, so "partitions heal within a bounded number of
gossip rounds" is a tested claim, not prose.
"""

from __future__ import annotations

import math
import random
from typing import Optional

from ..fanout.log import SnapshotNeeded
from ..obs import propagation as _propagation
from ..obs.metrics import OBS as _OBS
from ..session.faults import FaultPlan, TransportFault
from ..wire.framing import ProtocolError
from .node import (
    DEFAULT_BYZANTINE_AFTER,
    ByzantineDivergence,
    ByzantineReplicaNode,
    PeerQuarantined,
    ReplicaNode,
    classify_error,
    gossip_exchange,
)

__all__ = ["ClusterSim"]

# per-exchange wire-length scale handed to the fault-plan generator
# (fault offsets are drawn inside it; an exchange that ends sooner
# simply never reaches the coordinate)
DEFAULT_WIRE_EST = 4096


def _rand_value(rng: random.Random, lo: int = 12, hi: int = 48) -> bytes:
    return bytes(rng.randrange(256) for _ in range(rng.randrange(lo, hi)))


class ClusterSim:
    """See module docstring.

    ``byzantine`` is a replica index (or None); ``byzantine_arm`` one
    of :data:`~.node.ByzantineReplicaNode.ARMS`.  ``churn=True``
    schedules one crash/restart-from-checkpoint; ``flash_crowd=J``
    joins J empty replicas mid-run (cold snapshot bootstrap);
    ``fanout=True`` gives every replica a broadcast log with
    ``fanout_retention`` bytes of history (small budgets exercise the
    trim -> SnapshotNeeded -> bootstrap arm).
    """

    def __init__(self, n: int, seed: int, *, records_per: int = 24,
                 divergence: int = 6, engine: str = "auto",
                 chaos: bool = True, byzantine: Optional[int] = None,
                 byzantine_arm: str = "wrong-symbol",
                 byzantine_after: int = DEFAULT_BYZANTINE_AFTER,
                 churn: bool = False, flash_crowd: int = 0,
                 fanout: bool = False, fanout_retention: int = 1 << 15,
                 checkpoint_every: int = 3,
                 wire_est: int = DEFAULT_WIRE_EST):
        if n < 2:
            raise ValueError("a cluster needs at least 2 replicas")
        if byzantine is not None and not 0 <= byzantine < n:
            raise ValueError(f"byzantine index {byzantine} outside 0..{n-1}")
        self.n0 = n
        self.seed = seed
        self.engine = engine
        self.chaos = chaos
        self.fanout = fanout
        self.wire_est = wire_est
        self.checkpoint_every = max(1, checkpoint_every)
        self.byzantine_key = None if byzantine is None else f"r{byzantine}"
        self.round = 0
        self.wire_bytes = 0
        self.converged_at: Optional[int] = None
        self.events: list[dict] = []
        rng = random.Random(seed * 48_271 + n)
        node_kw = dict(engine=engine, byzantine_after=byzantine_after,
                       fanout_retention=fanout_retention if fanout
                       else None)
        self._node_kw = node_kw
        # the record universe: a shared base plus per-replica unique
        # divergence — every replica starts strictly diverged from
        # every other, with no distinguished source holding the union
        base = [{"key": f"base-{i}", "change": i, "from": 0, "to": 1,
                 "value": _rand_value(rng), "subset": "base"}
                for i in range(records_per)]
        self.nodes: dict[str, ReplicaNode] = {}
        self._index: dict[str, int] = {}
        honest_records = list(base)
        for i in range(n):
            key = f"r{i}"
            uniq = [{"key": f"u{i}-{j}", "change": j, "from": 0, "to": 1,
                     "value": _rand_value(rng), "subset": f"u{i}"}
                    for j in range(divergence)]
            if key == self.byzantine_key:
                # the liar holds real unique records too (so the
                # wrong-chunk arm has content to corrupt when honest
                # peers request it), but they are EXCLUDED from the
                # honest ground-truth union: with a byzantine replica
                # the sweep asserts healthy-set equality, not equality
                # to a fixed union (which arm fired decides whether the
                # liar's records ever legitimately spread)
                node = ByzantineReplicaNode(key, base + uniq,
                                            arm=byzantine_arm,
                                            seed=seed * 131 + i, **node_kw)
            else:
                node = ReplicaNode(key, base + uniq,
                                   seed=seed * 131 + i, **node_kw)
                honest_records.extend(uniq)
            self.nodes[key] = node
            self._index[key] = i
        expected_node = ReplicaNode("expected", honest_records)
        self.expected_digest = expected_node.content_digest()
        # divergence size in bytes (the bench's denominator): wire the
        # mesh MUST move for every replica to reach the union
        self.union_wire_bytes = len(expected_node.canonical_wire())
        self.divergence_bytes = sum(
            max(0, self.union_wire_bytes - len(nd.canonical_wire()))
            for nd in self.nodes.values())
        # deterministic schedules, all from the one seed
        self.partition = (FaultPlan.partition_scenario(seed, n)
                          if chaos else None)
        self._churn: Optional[dict] = None
        if churn:
            victims = [i for i in range(n) if i != byzantine]
            crash = rng.randrange(2, 5)
            self._churn = {"replica": rng.choice(victims),
                           "crash_round": crash,
                           "restart_round": crash + rng.randrange(2, 4)}
        self._flash: Optional[dict] = None
        if flash_crowd:
            self._flash = {"round": rng.randrange(2, 5),
                           "joiners": int(flash_crowd)}
        # static follow graph for the fan-out leg: each replica follows
        # its two ring predecessors' broadcast logs
        self._follows: dict[str, list[str]] = {}
        if fanout:
            for i in range(n):
                owners = {f"r{(i - 1) % n}", f"r{(i - 2) % n}"} - {f"r{i}"}
                self._follows[f"r{i}"] = sorted(owners)
        self._checkpoints: dict[str, dict] = {
            k: nd.checkpoint() for k, nd in self.nodes.items()}
        self._down: dict[str, ReplicaNode] = {}
        self._rng = rng
        if _OBS.on:
            # the meshdoctor's ground-truth frame + provenance roots:
            # what each replica held BEFORE any exchange (round 0)
            _propagation.note_mesh(n, seed, self.rounds_bound())
            for key, nd in self.nodes.items():
                _propagation.note_hold(
                    key, _propagation.digest_prefixes(nd.replica.digests))
                _propagation.note_frontier(
                    key, nd.content_digest().hex(), nd.record_count, 0)

    # -- views ---------------------------------------------------------------

    def alive(self) -> list[str]:
        return [k for k, nd in self.nodes.items()
                if nd.state != "crashed"]

    def healthy(self) -> list[str]:
        """Alive and not the byzantine replica — the set the
        convergence invariant quantifies over."""
        return [k for k in self.alive() if k != self.byzantine_key]

    def content_digests(self) -> dict:
        return {k: self.nodes[k].content_digest().hex()
                for k in self.alive()}

    def converged(self) -> bool:
        """Every healthy replica byte-identical (and equal to the
        ground-truth union when no byzantine replica is configured) —
        only evaluable once all scheduled churn/joins have happened."""
        if self._churn and self.round < self._churn["restart_round"]:
            return False
        if self._flash and self.round < self._flash["round"]:
            return False
        digests = {self.nodes[k].content_digest()
                   for k in self.healthy()}
        if len(digests) != 1:
            return False
        if self.byzantine_key is None:
            return digests == {self.expected_digest}
        return True

    def rounds_bound(self) -> int:
        """The asserted convergence budget: epidemic spread is
        O(log n) rounds; partitions/churn/joins shift the start line;
        chaos links and a byzantine replica eat a bounded number of
        exchanges.  Generous but FINITE — the sweep fails any seed
        that wanders past it."""
        n = max(2, self.n0 + (self._flash["joiners"]
                              if self._flash else 0))
        base = 3 * math.ceil(math.log2(n)) + 10
        start = 0
        if self.partition is not None:
            start = max(start, self.partition["heal_round"])
        if self._churn is not None:
            start = max(start, self._churn["restart_round"])
        if self._flash is not None:
            start = max(start, self._flash["round"])
        if self.byzantine_key is not None:
            base += 4
        return start + base

    # -- one gossip round ----------------------------------------------------

    def step(self) -> dict:
        self.round += 1
        rnd = self.round
        ev: dict = {"round": rnd, "exchanges": [], "quarantines": [],
                    "bootstraps": [], "churn": None, "joined": []}
        self._fire_schedules(rnd, ev)
        if rnd % self.checkpoint_every == 0:
            for k in self.alive():
                self._checkpoints[k] = self.nodes[k].checkpoint()
        keys = self.alive()
        for key in keys:
            node = self.nodes.get(key)
            if node is None or node.state == "crashed":
                continue
            node.begin_round(rnd)
            peer_key = node.sample_peer(keys)
            if peer_key is None:
                continue
            target = self.nodes[peer_key]
            rec = {"round": rnd, "initiator": key, "responder": peer_key,
                   "outcome": "ok", "error": None}
            if target.state == "crashed":
                node.note_transport_failure(peer_key)
                rec["outcome"] = "transport"
                rec["error"] = "peer crashed"
                if _OBS.on:
                    # never reaches gossip_exchange's lit fork: the
                    # dial itself found a dead peer
                    _propagation.record_exchange(
                        key, peer_key, role="initiator", rnd=rnd,
                        outcome="transport", seconds=0.0,
                        error="peer crashed")
                ev["exchanges"].append(rec)
                continue
            plan_out = plan_back = None
            if self.chaos:
                li, lt = self._index[key], self._index[peer_key]
                plan_out = FaultPlan.for_sweep(
                    self.seed, self.wire_est, link=(li, lt),
                    n_replicas=self.n0, gossip_round=rnd)
                plan_back = FaultPlan.for_sweep(
                    self.seed, self.wire_est, link=(lt, li),
                    n_replicas=self.n0, gossip_round=rnd)
            try:
                res = gossip_exchange(node, target, plan_out=plan_out,
                                      plan_back=plan_back,
                                      engine=self.engine)
            except PeerQuarantined as e:
                node.stats["refusals"] += 1
                rec["outcome"] = "refused"
                rec["error"] = str(e)
                if _OBS.on:
                    # refusal happens BEFORE the exchange engine's lit
                    # fork (the quarantine check is the front door), so
                    # the provenance record is made here
                    _propagation.record_exchange(
                        key, peer_key, role="initiator", rnd=rnd,
                        outcome="refused", seconds=0.0, error=str(e))
            except TransportFault as e:
                node.note_transport_failure(peer_key)
                target.note_transport_failure(key)
                rec["outcome"] = "transport"
                rec["error"] = str(e)
            except (ProtocolError, ValueError) as e:
                rec["outcome"] = classify_error(e)
                rec["error"] = f"{type(e).__name__}: {e}"
                for by, suspect in ((node, peer_key), (target, key)):
                    div = by.note_corruption(suspect, e)
                    if div is not None:
                        ev["quarantines"].append(
                            {"round": rnd, "by": by.key, "peer": div.peer,
                             "arm": div.arm})
            else:
                node.note_success(peer_key)
                target.note_success(key)
                self.wire_bytes += res["wire_bytes"]
                rec["wire_bytes"] = res["wire_bytes"]
                rec["diff"] = res["diff"]
                if self.fanout:
                    node.publish_repairs(res["wire_initiator"])
                    target.publish_repairs(res["wire_responder"])
            ev["exchanges"].append(rec)
        if self.fanout:
            self._fanout_leg(rnd, ev)
        ev["digests"] = self.content_digests()
        if self.converged_at is None and self.converged():
            self.converged_at = rnd
        self.events.append(ev)
        return ev

    def _fire_schedules(self, rnd: int, ev: dict) -> None:
        ch = self._churn
        if ch is not None:
            key = f"r{ch['replica']}"
            if rnd == ch["crash_round"]:
                node = self.nodes.pop(key)
                node.crash()
                self._down[key] = node
                ev["churn"] = {"crashed": key}
            elif rnd == ch["restart_round"]:
                old = self._down.pop(key)
                node = type(old).from_checkpoint(
                    self._checkpoints[key],
                    seed=self.seed * 131 + ch["replica"], **self._node_kw)
                node.log_gen = old.log_gen + 1
                self.nodes[key] = node
                ev["churn"] = {"restarted": key,
                               "from_round":
                                   self._checkpoints[key]["round"]}
        if self._flash is not None and rnd == self._flash["round"]:
            donors = self.healthy()
            for j in range(self._flash["joiners"]):
                key = f"j{j}"
                node = ReplicaNode(key, (),
                                   seed=self.seed * 977 + j,
                                   **self._node_kw)
                self.nodes[key] = node
                self._index[key] = self.n0 + j
                donor = self.nodes[self._rng.choice(donors)]
                res = node.bootstrap_from(donor)
                self.wire_bytes += res["wire_bytes"]
                if self.fanout:
                    self._follows[key] = [donor.key]
                if _OBS.on:
                    # snapshot bootstrap is an out-of-band acquisition:
                    # a provenance ROOT, not an exchange delivery
                    _propagation.note_hold(
                        key,
                        _propagation.digest_prefixes(node.replica.digests),
                        rnd=rnd)
                    _propagation.note_frontier(
                        key, node.content_digest().hex(),
                        node.record_count, rnd)
                ev["joined"].append({"replica": key, "donor": donor.key,
                                     "wire_bytes": res["wire_bytes"]})

    def _fanout_leg(self, rnd: int, ev: dict) -> None:
        for key in self.alive():
            node = self.nodes[key]
            for owner_key in self._follows.get(key, ()):
                owner = self.nodes.get(owner_key)
                if owner is None or owner.state == "crashed":
                    continue
                try:
                    node.drain_feed(owner)
                except SnapshotNeeded:
                    # the retention budget trimmed past this follower:
                    # the PR 12 bootstrap is the recovery protocol
                    res = node.bootstrap_from(owner)
                    self.wire_bytes += res["wire_bytes"]
                    ev["bootstraps"].append(
                        {"round": rnd, "replica": key,
                         "owner": owner_key,
                         "wire_bytes": res["wire_bytes"]})
                except ByzantineDivergence as e:
                    by = owner.key if e.arm == "ack-regression" else key
                    ev["quarantines"].append(
                        {"round": rnd, "by": by, "peer": e.peer,
                         "arm": e.arm})
        for key in self.alive():
            log = self.nodes[key].log
            if log is not None:
                log.enforce_retention()
        if _OBS.on:
            # feed drains deliver records OUTSIDE any exchange: record
            # them as provenance holds (change-only via the frontier),
            # or the meshdoctor would flag feed-spread digests as
            # orphaned when a follower later re-ships them
            for key in self.alive():
                nd = self.nodes[key]
                if _propagation.note_frontier(
                        key, nd.content_digest().hex(),
                        nd.record_count, rnd):
                    _propagation.note_hold(
                        key,
                        _propagation.digest_prefixes(nd.replica.digests),
                        rnd=rnd)

    # -- the driver ----------------------------------------------------------

    def byzantine_quarantined(self) -> bool:
        return self.byzantine_key is not None and any(
            q["peer"] == self.byzantine_key
            for e in self.events for q in e["quarantines"])

    def run(self, max_rounds: Optional[int] = None) -> dict:
        """Step until convergence or the bounded round budget runs
        out.  With a byzantine replica the mesh keeps gossiping past
        convergence (still bounded) until the liar is quarantined —
        exactly what a live mesh does; ``rounds`` reports the
        convergence round either way."""
        bound = self.rounds_bound() if max_rounds is None else max_rounds
        while self.round < bound:
            if self.converged_at is not None and (
                    self.byzantine_key is None
                    or self.byzantine_quarantined()):
                break
            self.step()
        quarantines = [q for e in self.events for q in e["quarantines"]]
        bootstraps = [b for e in self.events for b in e["bootstraps"]]
        return {
            "converged": self.converged_at is not None,
            "rounds": self.converged_at
            if self.converged_at is not None else self.round,
            "bound": bound,
            "wire_bytes": self.wire_bytes,
            "digests": self.content_digests(),
            "expected_digest": self.expected_digest.hex(),
            "quarantines": quarantines,
            "bootstraps": bootstraps,
            "byzantine": self.byzantine_key,
            "partition": self.partition,
        }
