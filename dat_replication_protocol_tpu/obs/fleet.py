"""Fleet aggregator: join replica watermarks into live per-link lag.

The fleet plane's control layer (ISSUE 11).  One aggregator polls N
*targets* — scrape endpoints (:mod:`.http`), ``--stats-fd`` JSONL
files, or in-process callables — and joins their ``watermarks``
sections into per-link replication lag:

* **lag in bytes** is exact: ``sender append − receiver parsed`` for
  one link, both cursors read from state the data plane already
  maintains (no wire traffic, no coordination protocol — replicas
  export, the aggregator joins, "Simplicity Scales");
* **lag in seconds** is clock-free: the sender's append-marks ring
  timestamps every wire frontier on the SENDER's monotonic clock, and
  the age of the oldest unparsed byte is
  ``sender_monotonic_at_snapshot − mark_time`` — no wall-clock
  synchronization between replicas, ever (the PR 4 wire-offset trick,
  applied to time);
* **convergence** rides the reconcile gauges
  (``reconcile.symbols.seen`` / ``reconcile.decoded.diff``) and the
  terminal watermark identity: a link whose append == parsed has lag
  exactly 0 — not "small", zero — because both numbers count the same
  bytes.

A bounded history ring per link supports rate/burn computation (bytes
drained per second, polls-until-caught-up).  Rendering is either a
plain-ANSI one-screen TTY dashboard (:func:`render_dashboard`) or
``--check slo.json``: declarative SLOs evaluated into the same
row-shaped report ``perf-check`` emits, exit 1 on breach — CI gates on
fleet health exactly like it gates on perf budgets.

SLO file schema (JSON object; every key optional — an empty object
passes vacuously is NOT allowed, same contract as perf budgets):

``max_lag_bytes`` / ``max_lag_seconds``
    per-link bounds at the final poll;
``require_converged``
    every joined link must be at lag exactly 0;
``max_shed`` / ``max_rejected``
    fleet-wide sums of hub/fanout shed + rejected counters;
``recompile_budget``
    max jit traces per site across targets (the PR 5 sentinel);
``require_healthz``
    every target's ``/healthz`` (or snapshot-embedded health) must be
    ok;
``max_events_dropped``
    per-target event-ring drop bound;
``gossip``
    the replica-mesh convergence SLO (ISSUE 15) — an object with any
    of ``require_converged`` (every target's gossip content digest
    byte-identical: the mesh converged, not "close"),
    ``max_rounds_behind`` (per-replica bound on gossip-round PROGRESS
    behind the fleet frontier since this aggregator's first sight —
    restart/stagger-proof, see ``_join_gossip``), and
    ``max_quarantined`` (per-replica quarantine-count bound).
    Evaluated over the ``gossip`` records ``--replica`` sidecars embed
    in their snapshots; no targets reporting gossip is a loud failure,
    same contract as an unjoined link.  The mesh convergence plane
    (ISSUE 19) adds ``max_convergence_rounds`` (validated against the
    epidemic ``rounds_bound()`` floor — a bound below it is an
    unreachable SLO and fails as a misconfiguration),
    ``max_divergence_bytes`` (per undirected pair, from the exchange's
    own peel watermark; frontier digest equality is authoritative for
    "exactly 0"), ``max_exchange_age_s`` (per directed link, age of
    the last SUCCESSFUL exchange), and ``max_exchange_p99_s``
    (fleet-wide exchange-latency quantile).  These evaluate over the
    ``propagation`` sections; no targets reporting the plane is a loud
    failure (the PR 18 "lag unknown" rule).
``min_goodput_fraction`` / ``max_overhead_ratio``
    the wire cost plane's SLO keys (ISSUE 20): per directed link,
    payload/total and framing/total from the joined ``wirecost``
    ledgers.  A link with no transport ground truth yet reports its
    ratio as None — evaluated as a FAILURE, never as a free pass
    (unknown is not zero).
``max_egress_bytes_per_peer``
    per-peer delivered-byte bound over the fan-out amplification
    ledgers — the ROADMAP item 4 egress cost model as a gate.
    All three cost keys fail loudly when NO target reports a
    ``wirecost`` section: a dark cost plane is indistinguishable from
    an unmetered one.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from collections import deque
from typing import Callable, Optional
from urllib.request import urlopen

from .watermarks import link_lag

__all__ = [
    "FleetTarget",
    "FleetView",
    "evaluate_slo",
    "load_slo",
    "render_dashboard",
    "SLO_KEYS",
    "GOSSIP_SLO_KEYS",
    "MESH_SLO_KEYS",
    "WIRECOST_SLO_KEYS",
    "mesh_rounds_floor",
]

DEFAULT_HISTORY = 128
DEFAULT_TIMEOUT = 5.0

SLO_KEYS = frozenset({
    "max_lag_bytes", "max_lag_seconds", "require_converged",
    "max_shed", "max_rejected", "recompile_budget", "require_healthz",
    "max_events_dropped", "max_loop_lag_s", "gossip",
    # the wire cost plane (ISSUE 20): evaluated over joined
    # ``wirecost`` sections; dark plane = loud failure
    "min_goodput_fraction", "max_overhead_ratio",
    "max_egress_bytes_per_peer",
})

# the cost keys evaluated over the joined wirecost sections — grouped
# so evaluate_slo can apply the one dark-plane rule to all of them
WIRECOST_SLO_KEYS = frozenset({
    "min_goodput_fraction", "max_overhead_ratio",
    "max_egress_bytes_per_peer",
})

# the mesh convergence plane's SLO vocabulary (ISSUE 19): evaluated
# over the ``propagation`` sections ``--replica`` sidecars embed — the
# per-pair divergence watermarks, per-link last-success ages, and the
# exchange-latency quantile the plane itself measures
MESH_SLO_KEYS = frozenset({
    "max_convergence_rounds", "max_divergence_bytes",
    "max_exchange_age_s", "max_exchange_p99_s",
})

GOSSIP_SLO_KEYS = frozenset({
    "require_converged", "max_rounds_behind", "max_quarantined",
}) | MESH_SLO_KEYS


def _join_gossip(snaps: dict, baselines: dict) -> dict:
    """Per-target gossip records joined into the convergence view:
    each ``--replica`` target's round/digest/quarantine state plus the
    per-replica **rounds-behind** column.

    Live round counters are LIFETIME values on unsynchronized
    processes — a replica restarted an hour into the fleet's life
    reports round ~5 against its peers' ~3600 while being fully
    converged, so comparing absolute positions would breach forever on
    any restart or staggered start.  Rounds-behind is therefore
    *progress since this aggregator first saw the target*:
    ``max over targets of (round − baseline) − own (round −
    baseline)`` — zero across a healthy mesh whatever the absolute
    counters, growing only for a replica whose gossip timer stops
    advancing with the fleet.  A round counter that goes BACKWARD
    (restart) re-baselines instead of reading as "behind".  The
    ``baselines`` dict is the caller's per-view memory
    (:class:`FleetView` owns one)."""
    records = {tname: snap["gossip"] for tname, snap in snaps.items()
               if isinstance((snap or {}).get("gossip"), dict)}
    if not records:
        return {}
    deltas = {}
    for tname, r in records.items():
        rnd = int(r.get("round", 0))
        base = baselines.setdefault(tname, rnd)
        if rnd < base:
            baselines[tname] = base = rnd
        deltas[tname] = rnd - base
    top = max(deltas.values())
    out = {}
    for tname, r in records.items():
        out[tname] = {
            "replica": r.get("replica"),
            "round": int(r.get("round", 0)),
            "rounds_behind": top - deltas[tname],
            "records": r.get("records"),
            "digest": r.get("digest"),
            "quarantined": list(r.get("quarantined") or ()),
            # structured quarantine PROVENANCE (ISSUE 19): which arm
            # caught each quarantined peer and where on the wire —
            # the byzantine oracle checks these against ground truth
            "quarantine": dict(r.get("quarantine") or {}),
            "suspicion": dict(r.get("suspicion") or {}),
            "state": r.get("state"),
        }
    return out


def _join_mesh(snaps: dict) -> dict:
    """Join every target's ``propagation`` section (ISSUE 19) into the
    fleet convergence matrix: per directed link the freshest exchange
    watermark across targets (by round), per replica the freshest
    frontier, per UNDIRECTED pair the effective divergence — **frontier
    digest equality is authoritative**: a link watermark is the diff at
    the pair's LAST exchange, so a pair whose frontiers are
    byte-identical has divergence exactly 0 whatever a stale watermark
    says.  ``exchange_p99_s`` is the worst per-target p99 (quantiles
    do not merge across windows; the max is the conservative fleet
    bound)."""
    links: dict = {}
    frontier: dict = {}
    p99 = None
    count = 0
    for tname, snap in sorted(snaps.items()):
        prop = (snap or {}).get("propagation")
        if not isinstance(prop, dict):
            continue
        for lname, rec in (prop.get("links") or {}).items():
            cur = links.get(lname)
            if cur is None or int(rec.get("round") or 0) >= \
                    int(cur.get("round") or 0):
                links[lname] = dict(rec, target=tname)
        for rname, rec in (prop.get("frontier") or {}).items():
            cur = frontier.get(rname)
            if cur is None or int(rec.get("round") or 0) >= \
                    int(cur.get("round") or 0):
                frontier[rname] = dict(rec, target=tname)
        xs = prop.get("exchange_seconds") or {}
        if xs.get("p99") is not None:
            p99 = xs["p99"] if p99 is None else max(p99, xs["p99"])
            count += int(xs.get("count") or 0)
    if not links and not frontier:
        return {}
    pairs: dict = {}
    for lname, rec in links.items():
        a, _, b = lname.partition("->")
        key = "<->".join(sorted((a, b)))
        cur = pairs.get(key)
        if cur is not None and int(cur.get("round") or 0) > \
                int(rec.get("round") or 0):
            continue
        da = (frontier.get(a) or {}).get("digest")
        db = (frontier.get(b) or {}).get("digest")
        conv = da is not None and da == db
        pairs[key] = {
            "round": rec.get("round"),
            "converged": conv,
            "divergence_records": 0 if conv
            else rec.get("divergence_records"),
            "divergence_bytes": 0 if conv
            else rec.get("divergence_bytes"),
            "last_success_age_s": rec.get("last_success_age_s"),
            "outcome": rec.get("outcome"),
        }
    return {"links": links, "pairs": pairs, "frontier": frontier,
            "exchange_p99_s": p99, "exchange_count": count}


def _join_wirecost(snaps: dict) -> dict:
    """Join every target's ``wirecost`` section (ISSUE 20) into the
    fleet cost matrix: per directed link the freshest ledger across
    targets (by ledger total — the counters are monotonic, so the
    largest ledger IS the latest view of that link), per fan-out link
    the freshest amplification record (by source bytes, same
    monotonicity argument).  Targets with no section contribute
    nothing; an empty join is the dark-plane signal the SLO rows fail
    loudly on."""
    links: dict = {}
    amp: dict = {}
    for tname, snap in sorted(snaps.items()):
        wc = (snap or {}).get("wirecost")
        if not isinstance(wc, dict):
            continue
        for lname, rec in (wc.get("links") or {}).items():
            cur = links.get(lname)
            if cur is None or int(rec.get("ledger_bytes") or 0) >= \
                    int(cur.get("ledger_bytes") or 0):
                links[lname] = dict(rec, target=tname)
        for aname, rec in (wc.get("amplification") or {}).items():
            cur = amp.get(aname)
            if cur is None or int(rec.get("source_bytes") or 0) >= \
                    int(cur.get("source_bytes") or 0):
                amp[aname] = dict(rec, target=tname)
    if not links and not amp:
        return {}
    return {"links": links, "amplification": amp}


class FleetTarget:
    """One polled replica.  ``spec`` is an ``http(s)://`` endpoint (its
    ``/snapshot`` route is fetched, ``/healthz`` alongside), a filesystem
    path to a ``--stats-fd`` JSONL file (the last complete snapshot
    line is used; ``emit_seq`` gaps are counted as dropped lines), or a
    zero-argument callable returning the snapshot dict (in-process
    fleets: tests, bench legs)."""

    def __init__(self, spec, name: Optional[str] = None,
                 timeout: float = DEFAULT_TIMEOUT):
        self._spec = spec
        self._timeout = timeout
        if callable(spec):
            self.kind = "callable"
            self.name = name or getattr(spec, "__name__", "inproc")
        elif isinstance(spec, str) and spec.startswith(("http://",
                                                        "https://")):
            self.kind = "http"
            self.name = name or spec
        elif isinstance(spec, str):
            self.kind = "file"
            self.name = name or os.path.basename(spec)
        else:
            raise ValueError(f"unknown fleet target spec {spec!r}")
        self.last_error: Optional[str] = None
        self.last_emit_seq: Optional[int] = None
        self.dropped_lines = 0  # emit_seq gaps observed across polls

    def poll(self) -> Optional[dict]:
        """One snapshot dict, or None (the failure is recorded on
        ``last_error`` — an unreachable replica is a visible state, not
        an exception that kills the whole poll)."""
        try:
            if self.kind == "callable":
                snap = self._spec()
            elif self.kind == "http":
                base = self._spec.rstrip("/")
                with urlopen(base + "/snapshot",
                             timeout=self._timeout) as r:
                    snap = json.loads(r.read().decode("utf-8"))
            else:
                snap = self._read_last_line(self._spec)
        except Exception as e:
            self.last_error = f"{type(e).__name__}: {e}"
            return None
        if snap is None:
            self.last_error = "no complete snapshot line yet"
            return None
        self.last_error = None
        seq = snap.get("emit_seq")
        if isinstance(seq, int):
            if self.last_emit_seq is not None \
                    and seq > self.last_emit_seq + 1:
                # lines the emitter consumed a seq for but this reader
                # never saw: EAGAIN skips, torn-line latches, or a
                # truncated tail — surfaced, not silently absorbed
                self.dropped_lines += seq - self.last_emit_seq - 1
            self.last_emit_seq = seq
        return snap

    def poll_healthz(self, snap: Optional[dict] = None) -> Optional[dict]:
        """The target's staged health record: fetched from ``/healthz``
        for endpoint targets (503 bodies are still parsed — degraded IS
        the answer), read from the snapshot's embedded ``healthz`` key
        for file/callable targets (the sidecar's ``--stats-fd`` lines
        carry one).  Pass the snapshot already polled this sample via
        ``snap`` to avoid re-polling."""
        if self.kind == "http":
            base = self._spec.rstrip("/")
            try:
                with urlopen(base + "/healthz",
                             timeout=self._timeout) as r:
                    return json.loads(r.read().decode("utf-8"))
            except Exception as e:
                body = getattr(e, "read", None)
                if body is not None:
                    try:  # HTTPError 503 carries the staged record
                        return json.loads(body().decode("utf-8"))
                    except Exception:
                        pass
                return {"ok": False,
                        "error": f"{type(e).__name__}: {e}"}
        if snap is None:
            snap = self.poll()
        return (snap or {}).get("healthz")

    @staticmethod
    def _read_last_line(path: str) -> Optional[dict]:
        # the last COMPLETE JSON line wins; a torn final line (emitter
        # mid-write, or latched dead mid-record) parses as garbage and
        # is skipped — exactly the JSONL consumer discipline the event
        # sink documents
        last = None
        with open(path, encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    obj = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(obj, dict) and "watermarks" in obj:
                    last = obj
        return last


def _join_links(snaps: dict) -> dict:
    """Join every target's watermark links by link name.  Returns
    ``{link: {"offsets", "marks", "mark_clock", "targets", "lag_bytes",
    "lag_seconds"}}``.  When sender and receiver cursors come from
    DIFFERENT targets (the normal fleet case), the seconds join uses
    the marks + monotonic stamp of the target that exported the
    ``append`` cursor — one clock, the sender's."""
    links: dict = {}
    for tname, snap in snaps.items():
        wm = (snap or {}).get("watermarks") or {}
        clock = wm.get("monotonic")
        for lname, rec in (wm.get("links") or {}).items():
            entry = links.setdefault(lname, {
                "offsets": {}, "marks": [], "mark_clock": None,
                "marks_dropped": 0, "targets": []})
            entry["targets"].append(tname)
            offsets = rec.get("offsets") or {}
            for role, value in offsets.items():
                entry["offsets"][role] = value
            marks = rec.get("marks") or []
            src = rec.get("marks_from")
            if src and not marks:
                src_rec = (wm.get("links") or {}).get(src)
                if src_rec:
                    marks = src_rec.get("marks") or []
            if "append" in offsets:
                # the sender side of the join: its marks and ITS clock
                entry["marks"] = marks
                entry["mark_clock"] = clock
                entry["marks_dropped"] = rec.get("marks_dropped", 0)
    for entry in links.values():
        lag_bytes, lag_seconds = link_lag(
            entry["offsets"], entry["marks"],
            entry["mark_clock"] if entry["mark_clock"] is not None
            else 0.0,
            marks_dropped=entry["marks_dropped"])
        if entry["mark_clock"] is None and lag_bytes is not None:
            # no sender clock came with the marks: behind -> unknown
            # age, caught up -> exactly 0 (the byte identity needs no
            # clock at all)
            lag_seconds = None if lag_bytes else 0.0
        entry["lag_bytes"] = lag_bytes
        entry["lag_seconds"] = lag_seconds
    return links


def _join_loops(snaps: dict) -> dict:
    """Join every target's event-loop lag records (the watermark
    snapshot's ``loops`` section, ISSUE 18) keyed ``target:loop`` —
    two sidecars each running ``edge0`` must not shadow each other."""
    out: dict = {}
    for tname, snap in snaps.items():
        wm = (snap or {}).get("watermarks") or {}
        for lname, rec in sorted((wm.get("loops") or {}).items()):
            if not isinstance(rec, dict):
                continue
            out[f"{tname}:{lname}"] = dict(rec, target=tname, loop=lname)
    return out


def _counter_sum(snaps: dict, names: tuple) -> int:
    total = 0
    for snap in snaps.values():
        counters = ((snap or {}).get("metrics") or {}).get("counters") or {}
        for name, v in counters.items():
            base = name.partition("{")[0]
            if base in names:
                total += int(v)
    return total


class FleetView:
    """N targets, joined.  :meth:`poll` takes one fleet-wide sample;
    the per-link history ring feeds rate computation and the
    dashboard's sparklines."""

    def __init__(self, targets, history: int = DEFAULT_HISTORY):
        self.targets = [t if isinstance(t, FleetTarget) else FleetTarget(t)
                        for t in targets]
        if not self.targets:
            raise ValueError("a fleet needs at least one target")
        # target names key the per-poll snapshot dict: two targets
        # sharing one (two anonymous lambdas, twice the same file)
        # would silently shadow each other in every join
        seen: dict = {}
        for t in self.targets:
            n = seen.get(t.name, 0)
            seen[t.name] = n + 1
            if n:
                t.name = f"{t.name}#{n + 1}"
        self._history: dict[str, deque] = {}
        self._hist_len = history
        # per-target first-seen gossip round: the rounds-behind
        # baseline (_join_gossip — live counters are lifetime values)
        self._gossip_baseline: dict = {}
        self.polls = 0

    def poll(self, healthz: bool = False) -> dict:
        """One sample: per-target snapshot + joined links + fleet-wide
        overload counters (+ per-target health with ``healthz=True``).
        Unreachable targets appear in ``errors`` — visible, never
        fatal."""
        now = time.monotonic()
        snaps: dict = {}
        errors: dict = {}
        for t in self.targets:
            snap = t.poll()
            if snap is None:
                errors[t.name] = t.last_error
            else:
                snaps[t.name] = snap
        links = _join_links(snaps)
        for lname, entry in links.items():
            ring = self._history.setdefault(
                lname, deque(maxlen=self._hist_len))
            ring.append((now, entry["lag_bytes"], entry["lag_seconds"]))
            entry["drain_bps"] = self._drain_rate(ring)
        sample = {
            "polled": now,
            "targets": {name: {
                "ts": snap.get("ts"),
                "events_dropped": snap.get("events_dropped", 0),
                "emit_seq": snap.get("emit_seq"),
                "jit_sites": snap.get("jit_sites") or {},
                "hub": snap.get("hub"),
                "fanout": snap.get("fanout"),
                "edge": snap.get("edge"),
            } for name, snap in snaps.items()},
            "errors": errors,
            "links": links,
            "loops": _join_loops(snaps),
            "gossip": _join_gossip(snaps, self._gossip_baseline),
            "mesh": _join_mesh(snaps),
            "wirecost": _join_wirecost(snaps),
            "shed": _counter_sum(snaps, ("hub.shed", "fanout.peer.shed",
                                         "edge.shed")),
            "rejected": _counter_sum(snaps, ("hub.rejected",
                                             "fanout.rejected",
                                             "edge.rejected")),
            "reconcile": {
                "rounds": _counter_sum(snaps, ("reconcile.rounds",)),
                "symbols_seen": self._gauge_max(snaps,
                                                "reconcile.symbols.seen"),
                "decoded_diff": self._gauge_max(snaps,
                                                "reconcile.decoded.diff"),
            },
            "dropped_lines": {t.name: t.dropped_lines
                              for t in self.targets if t.dropped_lines},
        }
        if healthz:
            # file/callable targets reuse the snapshot this sample
            # already took (their health rides the snapshot record);
            # only endpoint targets pay a second request, to /healthz
            sample["healthz"] = {
                t.name: t.poll_healthz(snap=snaps.get(t.name))
                for t in self.targets}
        self.polls += 1
        return sample

    @staticmethod
    def _gauge_max(snaps: dict, name: str) -> float:
        best = 0.0
        for snap in snaps.values():
            gauges = ((snap or {}).get("metrics") or {}).get("gauges") or {}
            v = gauges.get(name)
            if v is not None:
                best = max(best, float(v))
        return best

    @staticmethod
    def _drain_rate(ring) -> Optional[float]:
        """Bytes/second the link's lag is shrinking at over the ring
        window (negative: the link is falling further behind)."""
        pts = [(t, b) for t, b, _s in ring if b is not None]
        if len(pts) < 2:
            return None
        (t0, b0), (t1, b1) = pts[0], pts[-1]
        if t1 <= t0:
            return None
        return round((b0 - b1) / (t1 - t0), 1)

    def history(self, link: str) -> list:
        return list(self._history.get(link, ()))


# -- SLO gate -----------------------------------------------------------------


def load_slo(path: str) -> dict:
    """Parse + validate an SLO file.  Malformed input (not an object,
    unknown keys, non-numeric bounds, or NO evaluable keys) raises
    ``ValueError`` — a gate that silently evaluates nothing is not a
    gate (the perf-budget precedent)."""
    with open(path, encoding="utf-8") as f:
        slo = json.load(f)
    if not isinstance(slo, dict):
        raise ValueError(f"SLO file {path}: expected a JSON object")
    unknown = set(slo) - SLO_KEYS
    if unknown:
        raise ValueError(
            f"SLO file {path}: unknown key(s) {sorted(unknown)} "
            f"(known: {sorted(SLO_KEYS)})")
    if not slo:
        raise ValueError(
            f"SLO file {path}: no evaluable keys — an empty SLO would "
            "pass vacuously")
    for key in ("max_lag_bytes", "max_lag_seconds", "max_shed",
                "max_rejected", "recompile_budget", "max_events_dropped",
                "max_loop_lag_s", "min_goodput_fraction",
                "max_overhead_ratio", "max_egress_bytes_per_peer"):
        if key in slo and not isinstance(slo[key], (int, float)):
            raise ValueError(f"SLO file {path}: {key} must be a number")
    for key in ("require_converged", "require_healthz"):
        if key in slo and not isinstance(slo[key], bool):
            raise ValueError(f"SLO file {path}: {key} must be a boolean")
    if "min_goodput_fraction" in slo \
            and not 0 <= slo["min_goodput_fraction"] <= 1:
        raise ValueError(
            f"SLO file {path}: min_goodput_fraction must be in [0, 1] — "
            "a fraction above 1 is an unreachable SLO, and an "
            "unreachable gate is a misconfiguration")
    if "gossip" in slo:
        g = slo["gossip"]
        if not isinstance(g, dict):
            raise ValueError(f"SLO file {path}: gossip must be an object")
        unknown = set(g) - GOSSIP_SLO_KEYS
        if unknown:
            raise ValueError(
                f"SLO file {path}: unknown gossip key(s) "
                f"{sorted(unknown)} (known: {sorted(GOSSIP_SLO_KEYS)})")
        if not g:
            raise ValueError(
                f"SLO file {path}: empty gossip object would pass "
                "vacuously")
        for key in ("max_rounds_behind", "max_quarantined",
                    "max_convergence_rounds", "max_divergence_bytes",
                    "max_exchange_age_s", "max_exchange_p99_s"):
            if key in g and not isinstance(g[key], (int, float)):
                raise ValueError(
                    f"SLO file {path}: gossip.{key} must be a number")
        if "require_converged" in g \
                and not isinstance(g["require_converged"], bool):
            raise ValueError(
                f"SLO file {path}: gossip.require_converged must be a "
                "boolean")
    return slo


def mesh_rounds_floor(n_replicas: int) -> int:
    """The epidemic rounds floor an SLO's ``max_convergence_rounds``
    must clear: ``3*ceil(log2(n)) + 10`` — the no-chaos core of
    :meth:`~..cluster.sim.ClusterSim.rounds_bound`.  A bound below what
    epidemic spread mathematically needs is an unreachable SLO, and an
    unreachable gate is a misconfiguration, not a standard."""
    return 3 * math.ceil(math.log2(max(2, int(n_replicas)))) + 10


def _evaluate_mesh_slo(g: dict, mesh: dict, row) -> None:
    """The mesh-key rows of the gossip SLO (ISSUE 19), over a joined
    ``mesh`` sample (:func:`_join_mesh`)."""
    frontier = mesh.get("frontier") or {}
    links = mesh.get("links") or {}
    pairs = mesh.get("pairs") or {}
    if "max_convergence_rounds" in g:
        bound = g["max_convergence_rounds"]
        n = len(frontier) or 2
        floor = mesh_rounds_floor(n)
        if bound < floor:
            row("gossip.max_convergence_rounds", "slo", False,
                f"bound {bound} is below the epidemic rounds_bound() "
                f"floor {floor} for {n} replica(s) — an unreachable SLO")
        else:
            digests = {r.get("digest") for r in frontier.values()}
            conv = len(digests) == 1 and None not in digests
            last_change = max((int(r.get("round") or 0)
                               for r in frontier.values()), default=0)
            if conv:
                row("gossip.max_convergence_rounds", "fleet",
                    last_change <= bound,
                    f"converged at round {last_change}, bound {bound}")
            else:
                cur = max([int(r.get("round") or 0)
                           for r in links.values()] + [last_change],
                          default=0)
                row("gossip.max_convergence_rounds", "fleet",
                    cur <= bound,
                    f"not converged at round {cur} ({len(digests)} "
                    f"distinct frontiers), bound {bound}")
    if "max_divergence_bytes" in g:
        bound = g["max_divergence_bytes"]
        if not pairs:
            row("gossip.max_divergence_bytes", "-", False,
                "no exchange watermarks joined: divergence unknown")
        for pname, p in sorted(pairs.items()):
            db = p.get("divergence_bytes")
            if p.get("converged"):
                row("gossip.max_divergence_bytes", pname, True,
                    "frontiers byte-identical (divergence exactly 0)")
            elif db is None:
                row("gossip.max_divergence_bytes", pname, False,
                    "no completed peel yet: divergence unknown")
            else:
                row("gossip.max_divergence_bytes", pname, db <= bound,
                    f"divergence {db} byte(s) "
                    f"({p.get('divergence_records')} record(s)) at "
                    f"round {p.get('round')}, bound {bound}")
    if "max_exchange_age_s" in g:
        bound = g["max_exchange_age_s"]
        if not links:
            row("gossip.max_exchange_age_s", "-", False,
                "no exchange watermarks joined: link ages unknown")
        for lname, rec in sorted(links.items()):
            age = rec.get("last_success_age_s")
            if age is None:
                row("gossip.max_exchange_age_s", lname, False,
                    "no successful exchange on this link yet: a "
                    "silently-dead link, not a passing one")
            else:
                row("gossip.max_exchange_age_s", lname, age <= bound,
                    f"last successful exchange {age:.3f}s ago, "
                    f"bound {bound}")
    if "max_exchange_p99_s" in g:
        bound = g["max_exchange_p99_s"]
        p99 = mesh.get("exchange_p99_s")
        if p99 is None:
            row("gossip.max_exchange_p99_s", "fleet", False,
                "no completed exchanges: p99 unknown")
        else:
            row("gossip.max_exchange_p99_s", "fleet", p99 <= bound,
                f"exchange p99 {p99:.4f}s over "
                f"{mesh.get('exchange_count', 0)} exchange(s), "
                f"bound {bound}")


def evaluate_slo(slo: dict, sample: dict) -> list[dict]:
    """One fleet sample against one SLO: verdict rows in the
    ``perf-check`` shape (``{"check", "subject", "status", "detail"}``;
    callers gate on ``any(r["status"] == "fail")``)."""
    rows: list[dict] = []

    def row(check: str, subject: str, ok: bool, detail: str) -> None:
        rows.append({"check": check, "subject": subject,
                     "status": "ok" if ok else "fail", "detail": detail})

    links = sample.get("links") or {}
    if "max_lag_bytes" in slo or "max_lag_seconds" in slo \
            or slo.get("require_converged"):
        if not links:
            row("lag", "-", False,
                "no joined links: nothing to evaluate lag against")
    for lname, entry in sorted(links.items()):
        lb, ls = entry.get("lag_bytes"), entry.get("lag_seconds")
        if "max_lag_bytes" in slo:
            bound = slo["max_lag_bytes"]
            if lb is None:
                row("max_lag_bytes", lname, False,
                    "link not joined (one side missing)")
            else:
                row("max_lag_bytes", lname, lb <= bound,
                    f"lag {lb} byte(s), bound {bound}")
        if "max_lag_seconds" in slo:
            bound = slo["max_lag_seconds"]
            if lb == 0:
                row("max_lag_seconds", lname, True, "caught up (lag 0)")
            elif ls is None:
                row("max_lag_seconds", lname, False,
                    "behind with no age attribution (marks missing)")
            else:
                row("max_lag_seconds", lname, ls <= bound,
                    f"oldest unparsed byte {ls:.3f}s old, bound {bound}")
        if slo.get("require_converged"):
            row("require_converged", lname, lb == 0,
                f"lag {lb} byte(s) (must be exactly 0)")
    if "gossip" in slo:
        g = slo["gossip"]
        gossip = sample.get("gossip") or {}
        if not gossip:
            row("gossip", "-", False,
                "no targets report gossip records: nothing to "
                "evaluate convergence against")
        if g.get("require_converged") and gossip:
            digests = {r.get("digest") for r in gossip.values()}
            ok = len(digests) == 1 and None not in digests
            row("gossip.require_converged", "fleet", ok,
                "all replica content digests byte-identical" if ok else
                f"{len(digests)} distinct content digests across "
                f"{len(gossip)} replicas")
        for tname, r in sorted(gossip.items()):
            if "max_rounds_behind" in g:
                bound = g["max_rounds_behind"]
                rb = r["rounds_behind"]
                row("gossip.max_rounds_behind", tname, rb <= bound,
                    f"{rb} round(s) behind the fleet frontier, "
                    f"bound {bound}")
            if "max_quarantined" in g:
                bound = g["max_quarantined"]
                nq = len(r["quarantined"])
                row("gossip.max_quarantined", tname, nq <= bound,
                    f"{nq} peer(s) quarantined, bound {bound}")
        mesh_keys = MESH_SLO_KEYS & set(g)
        if mesh_keys:
            mesh = sample.get("mesh") or {}
            if not mesh:
                # the PR 18 "lag unknown" rule, applied to the mesh: an
                # SLO over a plane nobody reports must fail loudly —
                # a dark plane is indistinguishable from a broken one
                row("gossip.mesh", "-", False,
                    "no targets report propagation records: the mesh "
                    "convergence plane is dark — nothing to evaluate "
                    f"{sorted(mesh_keys)} against")
            else:
                _evaluate_mesh_slo(g, mesh, row)
    cost_keys = WIRECOST_SLO_KEYS & set(slo)
    if cost_keys:
        wc = sample.get("wirecost") or {}
        if not wc:
            # the dark-plane rule (ISSUE 20, same shape as the mesh):
            # a cost SLO over a plane nobody reports must fail loudly —
            # an unmetered wire is indistinguishable from a free one
            row("wirecost", "-", False,
                "no targets report wire cost records: the wire cost "
                "plane is dark — nothing to evaluate "
                f"{sorted(cost_keys)} against")
        else:
            wlinks = wc.get("links") or {}
            if ("min_goodput_fraction" in slo
                    or "max_overhead_ratio" in slo) and not wlinks:
                row("wirecost.links", "-", False,
                    "no per-link ledgers joined: goodput/overhead "
                    "unknown")
            for lname, rec in sorted(wlinks.items()):
                if "min_goodput_fraction" in slo:
                    bound = slo["min_goodput_fraction"]
                    gf = rec.get("goodput_fraction")
                    if gf is None:
                        row("min_goodput_fraction", lname, False,
                            "no bytes attributed yet: goodput unknown "
                            "(unknown is not a pass)")
                    else:
                        row("min_goodput_fraction", lname, gf >= bound,
                            f"goodput {gf:.4f} "
                            f"({rec.get('payload_bytes')}/"
                            f"{rec.get('ledger_bytes')} byte(s)), "
                            f"floor {bound}")
                if "max_overhead_ratio" in slo:
                    bound = slo["max_overhead_ratio"]
                    ov = rec.get("overhead_ratio")
                    if ov is None:
                        row("max_overhead_ratio", lname, False,
                            "no bytes attributed yet: overhead unknown "
                            "(unknown is not a pass)")
                    else:
                        row("max_overhead_ratio", lname, ov <= bound,
                            f"overhead {ov:.4f} "
                            f"({rec.get('framing_bytes')}/"
                            f"{rec.get('ledger_bytes')} byte(s)), "
                            f"bound {bound}")
            if "max_egress_bytes_per_peer" in slo:
                bound = slo["max_egress_bytes_per_peer"]
                amp = wc.get("amplification") or {}
                if not amp:
                    row("max_egress_bytes_per_peer", "-", False,
                        "no fan-out amplification ledgers joined: "
                        "per-peer egress unknown")
                for aname, view_ in sorted(amp.items()):
                    for peer, nbytes in sorted(
                            (view_.get("peers") or {}).items()):
                        row("max_egress_bytes_per_peer",
                            f"{aname}:{peer}", nbytes <= bound,
                            f"delivered {nbytes} byte(s), bound {bound}")
    if "max_loop_lag_s" in slo:
        bound = slo["max_loop_lag_s"]
        loops = sample.get("loops") or {}
        if not loops:
            row("max_loop_lag_s", "-", False,
                "no targets report event-loop lag: nothing to "
                "evaluate against")
        for lname, rec in sorted(loops.items()):
            if rec.get("state") != "live":
                row("max_loop_lag_s", lname, False,
                    "loop telemetry dark (obs gate off): lag unknown")
                continue
            lag = float(rec.get("lag_s", 0.0))
            row("max_loop_lag_s", lname, lag <= bound,
                f"loop lag {lag:.3f}s "
                f"(max {float(rec.get('lag_max_s', 0.0)):.3f}s), "
                f"bound {bound}")
    if "max_shed" in slo:
        row("max_shed", "fleet", sample.get("shed", 0) <= slo["max_shed"],
            f"shed {sample.get('shed', 0)}, bound {slo['max_shed']}")
    if "max_rejected" in slo:
        row("max_rejected", "fleet",
            sample.get("rejected", 0) <= slo["max_rejected"],
            f"rejected {sample.get('rejected', 0)}, "
            f"bound {slo['max_rejected']}")
    if "recompile_budget" in slo:
        bound = slo["recompile_budget"]
        worst, site = 0, "-"
        for tname, t in (sample.get("targets") or {}).items():
            for sname, rec in (t.get("jit_sites") or {}).items():
                if rec.get("traces", 0) > worst:
                    worst, site = rec["traces"], f"{tname}:{sname}"
        row("recompile_budget", site, worst <= bound,
            f"worst site traced {worst}x, bound {bound}")
    if "max_events_dropped" in slo:
        bound = slo["max_events_dropped"]
        for tname, t in sorted((sample.get("targets") or {}).items()):
            dropped = t.get("events_dropped", 0)
            row("max_events_dropped", tname, dropped <= bound,
                f"ring dropped {dropped}, bound {bound}")
    if slo.get("require_healthz"):
        hz = sample.get("healthz") or {}
        if not hz:
            row("require_healthz", "-", False,
                "no healthz records polled")
        for tname, rec in sorted(hz.items()):
            ok = bool(rec and rec.get("ok"))
            degraded = "-"
            if rec and not ok:
                degraded = ",".join(
                    s for s, st in (rec.get("stages") or {}).items()
                    if not st.get("ok")) or rec.get("error", "?")
            row("require_healthz", tname, ok,
                "healthy" if ok else f"degraded: {degraded}")
    for tname, err in sorted((sample.get("errors") or {}).items()):
        row("reachable", tname, False, f"target unreachable: {err}")
    return rows


def run_fleet_check(targets, slo_path: str, polls: int = 3,
                    interval: float = 0.5, out=None) -> int:
    """The CI gate: poll, evaluate the FINAL sample, report one line
    per check, exit 1 on breach (the ``perf-check`` contract for fleet
    health).  A malformed SLO is itself a failure row — a gate must
    fail loudly, never pass on an unreadable contract."""
    out = out if out is not None else sys.stdout
    try:
        slo = load_slo(slo_path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"FAIL slo          {type(e).__name__}: {e}", file=out)
        print("fleet-check: 1 check(s), 1 failed — SLO BREACH", file=out)
        return 1
    view = FleetView(targets)
    sample = None
    for i in range(max(1, polls)):
        if i:
            time.sleep(interval)
        sample = view.poll(healthz=bool(slo.get("require_healthz")))
    rows = evaluate_slo(slo, sample)
    failed = 0
    for r in rows:
        mark = "OK  " if r["status"] == "ok" else "FAIL"
        subject = f"{r['check']}[{r['subject']}]"
        print(f"{mark} {subject:<40} {r['detail']}", file=out)
        failed += r["status"] == "fail"
    verdict = "SLO BREACH" if failed else "within SLO"
    print(f"fleet-check: {len(rows)} check(s), {failed} failed — "
          f"{verdict}", file=out)
    return 1 if failed else 0


# -- TTY dashboard ------------------------------------------------------------

_SPARK = " ▁▂▃▄▅▆▇█"


def _sparkline(values, width: int = 24) -> str:
    vals = [v for v in values if v is not None][-width:]
    if not vals:
        return "-" * width
    top = max(vals) or 1
    return "".join(_SPARK[min(8, int(8 * v / top + 0.5))]
                   for v in vals).rjust(width)


def render_dashboard(view: FleetView, sample: dict,
                     width: int = 78) -> str:
    """One screen, plain ANSI (no curses, no deps): per-target health
    column, per-link lag + sparkline over the history ring, overload /
    convergence summary, recent errors.  Returns the frame as a string
    (the CLI clears + prints; tests assert on content)."""
    lines: list[str] = []
    bar = "─" * width
    lines.append(f"fleet · {len(view.targets)} target(s) · "
                 f"poll #{view.polls}")
    lines.append(bar)
    hz = sample.get("healthz") or {}
    for t in view.targets:
        if t.name in (sample.get("errors") or {}):
            status = f"UNREACHABLE  {sample['errors'][t.name]}"
        elif hz and hz.get(t.name) is not None:
            status = "healthy" if hz[t.name].get("ok") else "DEGRADED"
        else:
            # reachable but no health record (a bare snapshot file):
            # an honest "up", not a fabricated DEGRADED
            status = "up"
        drop = f"  dropped_lines={t.dropped_lines}" if t.dropped_lines \
            else ""
        lines.append(f"  {t.name[:40]:<40} {status}{drop}")
    lines.append(bar)
    links = sample.get("links") or {}
    if links:
        lines.append(f"  {'link':<20} {'lag_bytes':>10} {'age_s':>8} "
                     f"{'drain_B/s':>10}  history")
        for lname, entry in sorted(links.items()):
            ring = view.history(lname)
            lb = entry.get("lag_bytes")
            ls = entry.get("lag_seconds")
            dr = entry.get("drain_bps")
            lines.append(
                f"  {lname[:20]:<20} "
                f"{('-' if lb is None else str(lb)):>10} "
                f"{('-' if ls is None else f'{ls:.3f}'):>8} "
                f"{('-' if dr is None else str(dr)):>10}  "
                f"{_sparkline([b for _t, b, _s in ring])}")
    else:
        lines.append("  (no joined links yet)")
    loops = sample.get("loops") or {}
    if loops:
        # the edge flight deck (ISSUE 18): per-loop lag watermarks
        lines.append(bar)
        lines.append(f"  {'loop':<28} {'lag_s':>8} {'max_s':>8} "
                     f"{'oldest_s':>9} {'turns':>8}")
        for lname, r in sorted(loops.items()):
            if r.get("state") != "live":
                lines.append(f"  {lname[:28]:<28} DARK (obs gate off)")
                continue
            lines.append(
                f"  {lname[:28]:<28} "
                f"{float(r.get('lag_s', 0.0)):>8.3f} "
                f"{float(r.get('lag_max_s', 0.0)):>8.3f} "
                f"{float(r.get('oldest_ready_s', 0.0)):>9.3f} "
                f"{r.get('turns', 0):>8}")
    gossip = sample.get("gossip") or {}
    if gossip:
        # the per-replica convergence column (ISSUE 15): rounds-behind
        # the fleet frontier + the content digest everyone must agree on
        lines.append(bar)
        lines.append(f"  {'replica':<20} {'round':>7} {'behind':>7} "
                     f"{'records':>8} {'quar':>5}  digest")
        for tname, r in sorted(gossip.items()):
            lines.append(
                f"  {str(r.get('replica') or tname)[:20]:<20} "
                f"{r['round']:>7} {r['rounds_behind']:>7} "
                f"{str(r.get('records', '-')):>8} "
                f"{len(r['quarantined']):>5}  "
                f"{(r.get('digest') or '?')[:16]}")
    mesh = sample.get("mesh") or {}
    if mesh:
        # the convergence matrix (ISSUE 19): per-pair divergence from
        # the exchange's own peel, per-link success age, exchange p99
        lines.append(bar)
        lines.append(f"  {'pair':<16} {'div_rec':>8} {'div_B':>8} "
                     f"{'ok_age_s':>9} {'round':>6}  outcome")
        for pname, p in sorted((mesh.get("pairs") or {}).items()):
            dr, db = p.get("divergence_records"), p.get("divergence_bytes")
            age = p.get("last_success_age_s")
            lines.append(
                f"  {pname[:16]:<16} "
                f"{('?' if dr is None else str(dr)):>8} "
                f"{('?' if db is None else str(db)):>8} "
                f"{('-' if age is None else f'{age:.2f}'):>9} "
                f"{str(p.get('round', '-')):>6}  "
                f"{'converged' if p.get('converged') else p.get('outcome') or '?'}")
        p99 = mesh.get("exchange_p99_s")
        lines.append(
            f"  exchange p99 "
            f"{('-' if p99 is None else f'{p99:.4f}s')} over "
            f"{mesh.get('exchange_count', 0)} exchange(s)")
        for tname, r in sorted(gossip.items()):
            for peer, q in sorted((r.get("quarantine") or {}).items()):
                lines.append(
                    f"  quarantine {r.get('replica') or tname}: {peer} "
                    f"arm={q.get('arm')} frame={q.get('frame')} "
                    f"offset={q.get('offset')}")
    wc = sample.get("wirecost") or {}
    if wc:
        # the wire cost matrix (ISSUE 20): per directed link the
        # goodput/overhead split and the tiling residual; per fan-out
        # link the amplification factor
        lines.append(bar)
        lines.append(f"  {'cost link':<22} {'bytes':>10} {'goodput':>8} "
                     f"{'overhead':>9} {'resid':>6} {'saved':>8}")
        for lname, r in sorted((wc.get("links") or {}).items()):
            gf, ov = r.get("goodput_fraction"), r.get("overhead_ratio")
            rb = r.get("residual_bytes")
            lines.append(
                f"  {lname[:22]:<22} "
                f"{r.get('ledger_bytes', 0):>10} "
                f"{('?' if gf is None else f'{gf:.3f}'):>8} "
                f"{('?' if ov is None else f'{ov:.3f}'):>9} "
                f"{('?' if rb is None else str(rb)):>6} "
                f"{r.get('batch_saved_bytes', 0):>8}")
        for aname, a in sorted((wc.get("amplification") or {}).items()):
            ampf = a.get("amplification")
            lines.append(
                f"  amplification {aname}: "
                f"{('?' if ampf is None else f'{ampf:.2f}x')} "
                f"({a.get('delivered_bytes', 0)} delivered / "
                f"{a.get('source_bytes', 0)} source, "
                f"{len(a.get('peers') or {})} peer(s))")
    lines.append(bar)
    rec = sample.get("reconcile") or {}
    lines.append(
        f"  shed={sample.get('shed', 0)} "
        f"rejected={sample.get('rejected', 0)} "
        f"reconcile_rounds={rec.get('rounds', 0)} "
        f"symbols={int(rec.get('symbols_seen', 0))} "
        f"diff={int(rec.get('decoded_diff', 0))}")
    return "\n".join(lines)


def run_dashboard(targets, interval: float = 2.0,
                  max_polls: Optional[int] = None, out=None) -> int:
    """The live TTY loop: clear, render, sleep.  ``max_polls`` bounds
    the loop (tests, one-shot inspection); Ctrl-C exits cleanly."""
    out = out if out is not None else sys.stdout
    view = FleetView(targets)
    n = 0
    try:
        while max_polls is None or n < max_polls:
            sample = view.poll(healthz=True)
            frame = render_dashboard(view, sample)
            if out.isatty():
                print("\x1b[2J\x1b[H" + frame, file=out, flush=True)
            else:
                print(frame, file=out, flush=True)
            n += 1
            if max_polls is None or n < max_polls:
                time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0
