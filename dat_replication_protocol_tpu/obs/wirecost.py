"""Wire cost plane: per-link byte ledger + amplification watermarks
(ISSUE 20).

At millions of users egress bytes ARE the cost model (ROADMAP item 4),
yet before this module no plane could say where a link's bytes went:
``wire.batch.bytes_saved`` priced one layer, fan-out counted delivered
bytes, reconcile counted symbols — nothing joined them into goodput vs
overhead per link.  This board is the simple, exact ledger the
negotiated-compression tier will be judged against ("Simplicity
Scales"): every wire byte on every link is attributed to exactly ONE
frame class at the existing choke points —

* encoder header push (``session/encoder.py``) — tx attribution at
  frame build time, payload and framing split exactly;
* both decoder dispatch loops + ``write_indexed``
  (``session/decoder.py``) — rx attribution at frame delivery;
* the fan-out gather (``fanout/server.py``) — source intake vs
  per-peer delivered bytes (the amplification numerator);
* the gossip exchange wire meter (``cluster/node.py``) — symbol
  traffic (class ``reconcile``) vs repair batches (``change_batch``);
* the pump send/recv steps (``session/pump.py``) — the TRANSPORT
  ground truth the ledger is audited against.

The headline invariant (the chaos oracle in
``tests/test_wirecost.py``): the ledger EXACTLY TILES the wire — the
sum of per-class bytes (payload + framing) equals the transport/
journal byte ground truth at every poll, and the unattributed residual
is EXACTLY 0 at convergence.  Faults leave the last watermark in place
and bump ``failures`` (unknown is reported as unknown, never zero —
the ISSUE 19 doctrine: fabricating 0 reads as healthy, the direction
an SLO gate must never err in).

Frame classes: ``change``, ``change_batch``, ``blob``, ``reconcile``,
``snapshot`` — plus the synthetic export class ``framing`` (the sum of
header bytes across all classes).  Derived per-link watermarks:

``goodput_fraction``
    payload bytes / total wire bytes (None until bytes flow);
``overhead_ratio``
    framing bytes / total wire bytes;
``batch_saved_bytes``
    batch savings realized (exact arithmetic vs the per-record
    encoding — mirrored on BOTH ends since ISSUE 20 satellite 1);
``reconcile_wire_per_diff_byte``
    reconcile-class wire bytes per delivered diff byte (None until a
    peel completes);
``snapshot_cold_ratio``
    snapshot-class wire bytes per dataset byte (None until the
    dataset size is known);
``amplification`` (per fan-out link)
    delivered bytes summed over peers / source bytes published;
``residual_bytes``
    transport ground truth − ledger total (None until the transport
    reports; exactly 0 at convergence).

Export surface (the PR 8 collector machinery):
``wire.cost.bytes{link=,dir=,class=}``,
``wire.cost.frames{link=,dir=,class=}``,
``wire.cost.saved_bytes{link=,dir=}``,
``wire.cost.failures{link=,dir=}``,
``wire.cost.source_bytes{link=}``,
``wire.cost.delivered_bytes{link=,peer=}`` as counters;
``wire.cost.goodput_fraction{link=,dir=}``,
``wire.cost.overhead_ratio{link=,dir=}``,
``wire.cost.reconcile_wire_per_diff_byte{link=,dir=}``,
``wire.cost.snapshot_cold_ratio{link=,dir=}``,
``wire.cost.amplification{link=}``,
``wire.cost.residual_bytes{link=,dir=}`` as gauges (None-valued
watermarks are SKIPPED, never exported as 0).

Dark-path discipline (the PR 18/19 contract): NOTHING here runs unless
``OBS.on`` — every instrumented hot path forks once on the gate into a
dark twin whose bytecode provably references no symbol of this module
(asserted in ``tests/test_wirecost.py``), so the disabled cost of the
whole plane is one attribute load per fork point.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .metrics import REGISTRY as _REGISTRY, OBS as _OBS

__all__ = [
    "WIRECOST",
    "WireCostBoard",
    "CLASSES",
    "account",
    "note_saved",
    "note_diff",
    "note_dataset",
    "note_source",
    "note_delivered",
    "note_transport",
    "note_failure",
]

# the frame-class vocabulary (OBSERVABILITY.md "Wire cost plane"); the
# synthetic class ``framing`` exists only in the export — every
# account() call carries its framing bytes alongside its payload, so
# the ledger tiles by construction
CLASSES = ("change", "change_batch", "blob", "reconcile", "snapshot")

_DIRS = ("tx", "rx")


def _new_rec(now: float) -> dict:
    return {
        # cls -> {"payload": int, "framing": int, "frames": int}
        "classes": {},
        # transport ground truth (pump/journal); 0 = not reporting yet,
        # and residual_bytes stays None until it does
        "transport": 0,
        "saved": 0,
        "diff_bytes": None,
        "dataset_bytes": None,
        "failures": 0,
        "error": None,
        "_mono": now,
    }


class WireCostBoard:
    """Process-global per-(link, direction) wire byte ledger +
    amplification watermarks.  See module docstring; the instance is
    :data:`WIRECOST`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # datlint: guarded-by(self._lock): self._links, self._amp
        # (link, dir) -> ledger record, monotonic-stamped
        self._links: dict[tuple, dict] = {}
        # link -> {"source": int, "delivered": {peer: int}} — the
        # fan-out amplification inputs (one publisher, many peers)
        self._amp: dict[str, dict] = {}
        self._collector_fn = self._collect

    # -- recording -----------------------------------------------------------

    def account(self, cls: str, link: str, direction: str,
                payload_len: int, framing_len: int,
                frames: int = 1) -> None:
        """Attribute one frame (or a run of ``frames`` frames) to a
        class on a directed link.  ``payload_len``/``framing_len`` are
        the run TOTALS — the tiling invariant is that their sum over
        all account() calls equals the transport byte count.  ``cls``
        is a literal at every call site (the datlint obs-discipline
        contract: the class vocabulary must stay greppable)."""
        if cls not in CLASSES:
            raise ValueError(f"unknown wire cost class: {cls!r}")
        if direction not in _DIRS:
            raise ValueError(f"unknown wire cost direction: {direction!r}")
        now = time.monotonic()
        with self._lock:
            rec = self._links.setdefault((link, direction), _new_rec(now))
            c = rec["classes"].setdefault(
                cls, {"payload": 0, "framing": 0, "frames": 0})
            c["payload"] += int(payload_len)
            c["framing"] += int(framing_len)
            c["frames"] += int(frames)
            rec["_mono"] = now
        _REGISTRY.register_collector("wirecost", self._collector_fn)

    def note_saved(self, link: str, direction: str, saved: int) -> None:
        """Batch savings realized on a directed link (exact arithmetic:
        per-record estimate − batch wire bytes, from
        ``batch_codec.estimate_per_record_bytes``).  Recorded on BOTH
        ends (satellite 1) so the sender==receiver cross-check is an
        equality, not a proxy."""
        now = time.monotonic()
        with self._lock:
            rec = self._links.setdefault((link, direction), _new_rec(now))
            rec["saved"] += int(saved)
            rec["_mono"] = now
        _REGISTRY.register_collector("wirecost", self._collector_fn)

    def note_diff(self, link: str, direction: str,
                  diff_bytes: int) -> None:
        """Diff bytes a completed reconcile exchange delivered — the
        denominator of ``reconcile_wire_per_diff_byte`` (None until
        the first completed peel; a failed exchange never touches it)."""
        now = time.monotonic()
        with self._lock:
            rec = self._links.setdefault((link, direction), _new_rec(now))
            rec["diff_bytes"] = (rec["diff_bytes"] or 0) + int(diff_bytes)
            rec["_mono"] = now
        _REGISTRY.register_collector("wirecost", self._collector_fn)

    def note_dataset(self, link: str, direction: str,
                     dataset_bytes: int) -> None:
        """Dataset (cold) bytes a snapshot bootstrap covered — the
        denominator of ``snapshot_cold_ratio``."""
        now = time.monotonic()
        with self._lock:
            rec = self._links.setdefault((link, direction), _new_rec(now))
            rec["dataset_bytes"] = (
                (rec["dataset_bytes"] or 0) + int(dataset_bytes))
            rec["_mono"] = now
        _REGISTRY.register_collector("wirecost", self._collector_fn)

    def note_source(self, link: str, nbytes: int) -> None:
        """Source bytes published into a fan-out link (the
        amplification denominator)."""
        with self._lock:
            amp = self._amp.setdefault(link, {"source": 0, "delivered": {}})
            amp["source"] += int(nbytes)
        _REGISTRY.register_collector("wirecost", self._collector_fn)

    def note_delivered(self, link: str, peer: str, nbytes: int) -> None:
        """Bytes a fan-out link delivered to one peer (the
        amplification numerator, summed over peers)."""
        with self._lock:
            amp = self._amp.setdefault(link, {"source": 0, "delivered": {}})
            amp["delivered"][peer] = (
                amp["delivered"].get(peer, 0) + int(nbytes))
        _REGISTRY.register_collector("wirecost", self._collector_fn)

    def note_transport(self, link: str, direction: str,
                       nbytes: int) -> None:
        """Transport ground truth: raw bytes the pump moved on a
        directed link.  The ledger is audited against this — residual
        = transport − sum(classes), exported only once the transport
        reports (0 transport = unknown, not a free pass)."""
        now = time.monotonic()
        with self._lock:
            rec = self._links.setdefault((link, direction), _new_rec(now))
            rec["transport"] += int(nbytes)
            rec["_mono"] = now
        _REGISTRY.register_collector("wirecost", self._collector_fn)

    def note_failure(self, link: str, direction: str,
                     error: Optional[str] = None) -> None:
        """A wire fault on a directed link: every watermark keeps its
        last value (the cost did not heal; fabricating fresh ratios
        would read as healthy) — only the failure counter and the
        error string move."""
        now = time.monotonic()
        with self._lock:
            rec = self._links.setdefault((link, direction), _new_rec(now))
            rec["failures"] += 1
            if error is not None:
                rec["error"] = error
            rec["_mono"] = now
        _REGISTRY.register_collector("wirecost", self._collector_fn)

    # -- export --------------------------------------------------------------

    @staticmethod
    def _watermarks(rec: dict) -> dict:
        """Derived per-ledger watermarks; None wherever a denominator
        is not yet known (unknown, not zero)."""
        payload = sum(c["payload"] for c in rec["classes"].values())
        framing = sum(c["framing"] for c in rec["classes"].values())
        total = payload + framing
        wm = {
            "ledger_bytes": total,
            "payload_bytes": payload,
            "framing_bytes": framing,
            "goodput_fraction": (payload / total) if total else None,
            "overhead_ratio": (framing / total) if total else None,
            "batch_saved_bytes": rec["saved"],
            "residual_bytes": ((rec["transport"] - total)
                               if rec["transport"] else None),
        }
        rc = rec["classes"].get("reconcile")
        wm["reconcile_wire_per_diff_byte"] = (
            (rc["payload"] + rc["framing"]) / rec["diff_bytes"]
            if rc and rec["diff_bytes"] else None)
        sn = rec["classes"].get("snapshot")
        wm["snapshot_cold_ratio"] = (
            (sn["payload"] + sn["framing"]) / rec["dataset_bytes"]
            if sn and rec["dataset_bytes"] else None)
        return wm

    @staticmethod
    def _amp_view(amp: dict) -> dict:
        delivered = sum(amp["delivered"].values())
        return {
            "source_bytes": amp["source"],
            "delivered_bytes": delivered,
            "peers": dict(amp["delivered"]),
            "amplification": ((delivered / amp["source"])
                              if amp["source"] else None),
        }

    def snapshot(self) -> dict:
        """The ``wirecost`` section of the sidecar snapshot record
        (JSON-able): per-directed-link ledger + watermarks with ages on
        THIS process's monotonic clock, plus per-link amplification."""
        now = time.monotonic()
        with self._lock:
            links = {f"{link}|{d}": {
                "classes": {k: dict(v) for k, v in rec["classes"].items()},
                "transport_bytes": rec["transport"],
                "diff_bytes": rec["diff_bytes"],
                "dataset_bytes": rec["dataset_bytes"],
                "failures": rec["failures"],
                "error": rec["error"],
                "age_s": round(now - rec["_mono"], 6),
                **self._watermarks(rec),
            } for (link, d), rec in self._links.items()}
            amp = {link: self._amp_view(a) for link, a in self._amp.items()}
        return {"monotonic": now, "links": links, "amplification": amp}

    def _collect(self) -> dict:
        """Registry collector: the ledger as labeled counters and the
        watermarks as labeled gauges (bounded cardinality — one entry
        per live directed link per class; None watermarks skipped)."""
        counters: dict = {}
        gauges: dict = {}
        with self._lock:
            links = [(k, {
                "classes": {c: dict(v) for c, v in rec["classes"].items()},
                "transport": rec["transport"], "saved": rec["saved"],
                "diff_bytes": rec["diff_bytes"],
                "dataset_bytes": rec["dataset_bytes"],
                "failures": rec["failures"], "error": rec["error"],
            }) for k, rec in self._links.items()]
            amps = [(link, self._amp_view(a))
                    for link, a in self._amp.items()]
        for (link, d), rec in links:
            framing_total = 0
            for cls, c in rec["classes"].items():
                counters[f"wire.cost.bytes{{link={link},dir={d},"
                         f"class={cls}}}"] = c["payload"]
                counters[f"wire.cost.frames{{link={link},dir={d},"
                         f"class={cls}}}"] = c["frames"]
                framing_total += c["framing"]
            if rec["classes"]:
                counters[f"wire.cost.bytes{{link={link},dir={d},"
                         "class=framing}"] = framing_total
            if rec["saved"]:
                counters[f"wire.cost.saved_bytes{{link={link},dir={d}}}"] \
                    = rec["saved"]
            if rec["failures"]:
                counters[f"wire.cost.failures{{link={link},dir={d}}}"] \
                    = rec["failures"]
            wm = self._watermarks(rec)
            for key in ("goodput_fraction", "overhead_ratio",
                        "reconcile_wire_per_diff_byte",
                        "snapshot_cold_ratio", "residual_bytes"):
                if wm[key] is None:
                    continue  # denominator unknown: skipped, not zero
                gauges[f"wire.cost.{key}{{link={link},dir={d}}}"] = \
                    float(wm[key])
        for link, view in amps:
            counters[f"wire.cost.source_bytes{{link={link}}}"] = \
                view["source_bytes"]
            for peer, nbytes in view["peers"].items():
                counters[f"wire.cost.delivered_bytes{{link={link},"
                         f"peer={peer}}}"] = nbytes
            if view["amplification"] is not None:
                gauges[f"wire.cost.amplification{{link={link}}}"] = \
                    float(view["amplification"])
        return {"counters": counters, "gauges": gauges}

    def reset_for_tests(self) -> None:
        """Drop every ledger and amplification record (process-global
        state — test isolation is explicit, the conftest
        ``obs_enabled`` contract)."""
        with self._lock:
            self._links.clear()
            self._amp.clear()


WIRECOST = WireCostBoard()


# -- the instrumentation surface (callers hold the OBS.on gate) --------------


def account(cls: str, link: str, direction: str, payload_len: int,
            framing_len: int, frames: int = 1) -> None:
    """Module-level forwarder for lit helpers that hoist the module
    (``from ..obs import wirecost as _wirecost``); same literal-class
    contract as :meth:`WireCostBoard.account`."""
    WIRECOST.account(cls, link, direction, payload_len, framing_len,
                     frames)


def note_saved(link: str, direction: str, saved: int) -> None:
    WIRECOST.note_saved(link, direction, saved)


def note_diff(link: str, direction: str, diff_bytes: int) -> None:
    WIRECOST.note_diff(link, direction, diff_bytes)


def note_dataset(link: str, direction: str, dataset_bytes: int) -> None:
    WIRECOST.note_dataset(link, direction, dataset_bytes)


def note_source(link: str, nbytes: int) -> None:
    WIRECOST.note_source(link, nbytes)


def note_delivered(link: str, peer: str, nbytes: int) -> None:
    WIRECOST.note_delivered(link, peer, nbytes)


def note_transport(link: str, direction: str, nbytes: int) -> None:
    WIRECOST.note_transport(link, direction, nbytes)


def note_failure(link: str, direction: str,
                 error: Optional[str] = None) -> None:
    WIRECOST.note_failure(link, direction, error)


# re-exported so instrumentation call sites can assert the plane's own
# gate state in tests without importing metrics twice
OBS = _OBS
