"""Causal tracing: wire-offset-correlated spans + Chrome trace export.

The wire protocol already carries a perfect causal key: the byte
offset every frame starts at (the same offset ``Decoder.checkpoint()``
resumes from).  This module is the span half of the observability
layer (ISSUE 4): nestable, thread-correct named spans recorded into a
bounded ring, plus zero-duration *instants* the session layer uses to
tag every encoder frame emission and decoder frame dispatch with its
wire offset — end-to-end tracing with no wire-format change.

* :class:`trace_span` — ``with trace_span("reconnect.attempt", ...):``
  context manager.  Nesting is tracked per-thread (a threadlocal parent
  stack), so spans opened concurrently on pump/ack/sidecar threads
  never corrupt each other's parent links.  Gated on the same hoisted
  ``OBS.on`` gate as the metrics layer.
* :func:`trace_instant` — the frame-tagging hot path: one record, zero
  duration.  Call sites guard with ``if _OBS.on:`` so the disabled
  path stays one attribute load (OBSERVABILITY.md's budget); this
  function does NOT re-check the gate.
* :data:`SPANS` — the process-global bounded span ring (an
  :class:`~.events.EventLog` subclass: same wraparound accounting and
  the same atomic JSONL sink discipline).
* :func:`to_chrome_trace` / :func:`export_chrome_trace` — Chrome
  trace-event JSON (Perfetto / chrome://tracing loadable).  JAX
  profiler annotations recorded through :mod:`...utils.trace` ride in
  like any other span (field ``src="jax"``), so host wire phases and
  device dispatch phases share one timeline.
* :func:`attach_jsonl_sink` — mirror events AND spans into one JSONL
  file through a shared lock (lines never interleave); the offline
  timeline CLI (``python -m dat_replication_protocol_tpu.obs``)
  consumes exactly these files.

Span record shape (one JSON object per line on a sink)::

    {"seq": 12, "ts": 103.2, "dur": 0.0018, "span": "reconnect.attempt",
     "id": 7, "parent": 3, "tid": 139923, "fields": {"offset": 4711}}

Frame instants use ``span`` names ``encoder.frame`` / ``decoder.frame``
(and ``decoder.frame.run`` for a native bulk-dispatch run) with fields
``offset`` (wire offset of the frame's first header byte), ``wire_len``
(header + payload bytes), ``kind`` (``change``/``blob``) and, for runs,
``frames``.  Both peers compute offsets from the same framing rules
(:func:`~..wire.framing.header_len`), so a sender's emission instant
and the receiver's dispatch instant for one frame carry the SAME
offset — that equality is the whole causal-correlation contract.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional

from .events import EVENTS, EventLog
from .metrics import OBS

__all__ = [
    "SPANS",
    "SpanLog",
    "trace_span",
    "trace_instant",
    "to_chrome_trace",
    "export_chrome_trace",
    "attach_jsonl_sink",
]

DEFAULT_SPAN_CAPACITY = 4096


class SpanLog(EventLog):
    """Bounded ring of span records — EventLog's ring/sink machinery
    with span-shaped records (``span`` instead of ``event``, plus
    ``dur``/``id``/``parent``/``tid``)."""

    def record(self, name: str, ts: float, dur: float, span_id: int,
               parent: Optional[int], tid: int, fields: dict) -> None:
        """Append one finished span.  NOT gated: the producing context
        managers / call sites own the ``OBS.on`` check (a span that
        STARTED while the gate was on still records if the gate flips
        mid-span)."""
        self._append({"seq": 0, "ts": ts, "dur": dur, "span": name,
                      "id": span_id, "parent": parent, "tid": tid,
                      "fields": fields})

    def spans(self, name: Optional[str] = None) -> list[dict]:
        """Snapshot of retained span records, oldest first."""
        with self._lock:
            records = list(self._ring)
        if name is None:
            return records
        return [r for r in records if r.get("span") == name]


SPANS = SpanLog(DEFAULT_SPAN_CAPACITY)

# span ids are process-wide so parent links stay unambiguous across
# threads; count().__next__ is atomic under the GIL
_span_ids = itertools.count(1)

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class trace_span:
    """Nestable named span; thread-correct via a threadlocal parent
    stack.  Cheap no-op while the gate is off (one gate check at enter,
    one slot check at exit) — hot per-frame sites use
    :func:`trace_instant` behind their own ``if _OBS.on:`` guard
    instead, keeping the disabled path at one attribute load."""

    __slots__ = ("name", "fields", "_t0", "_id", "_parent", "_on")

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields

    def __enter__(self) -> "trace_span":
        if not OBS.on:
            self._on = False
            return self
        self._on = True
        st = _stack()
        self._id = next(_span_ids)
        self._parent = st[-1] if st else None
        st.append(self._id)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._on:
            st = _stack()
            if st and st[-1] == self._id:
                st.pop()
            fields = self.fields
            if exc_type is not None:
                # a span that ended by exception says so — post-mortem
                # timelines need the failing phase, not just the error
                fields = dict(fields, error=exc_type.__name__)
            SPANS.record(self.name, self._t0,
                         time.monotonic() - self._t0, self._id,
                         self._parent, threading.get_ident(), fields)
        return False


def trace_instant(name: str, **fields) -> None:
    """Zero-duration span (a Chrome 'instant') — the frame-tagging hot
    path.  Call sites guard with ``if _OBS.on:``; this function does
    not re-check the gate."""
    st = getattr(_tls, "stack", None)
    SPANS.record(name, time.monotonic(), 0.0, next(_span_ids),
                 st[-1] if st else None, threading.get_ident(), fields)


# -- Chrome trace-event export ------------------------------------------------


def to_chrome_trace(spans: Optional[list] = None,
                    events: Optional[list] = None) -> dict:
    """Chrome trace-event JSON from span + event records (defaults:
    the live ``SPANS`` / ``EVENTS`` rings).  Loadable by Perfetto and
    chrome://tracing: spans with duration become complete events
    (``ph: "X"``), frame instants and log events become instants
    (``ph: "i"``).  Timestamps/durations are microseconds as the format
    requires; JAX annotation spans (``src="jax"``) are joined in like
    any other span."""
    if spans is None:
        spans = SPANS.spans()
    if events is None:
        events = EVENTS.events()
    pid = os.getpid()
    trace_events = []
    for r in spans:
        if "span" not in r:
            continue
        args = dict(r.get("fields") or {})
        args["seq"] = r.get("seq", 0)
        if r.get("parent") is not None:
            args["parent"] = r["parent"]
        ev = {
            "name": r["span"],
            "ts": r.get("ts", 0.0) * 1e6,
            "pid": pid,
            "tid": r.get("tid", 0),
            "args": args,
        }
        if r.get("dur"):
            ev["ph"] = "X"
            ev["dur"] = r["dur"] * 1e6
        else:
            ev["ph"] = "i"
            ev["s"] = "t"  # thread-scoped instant
        trace_events.append(ev)
    for e in events:
        if "event" not in e:
            continue
        trace_events.append({
            "name": e["event"],
            "ph": "i",
            "s": "p",  # process-scoped instant
            "ts": e.get("ts", 0.0) * 1e6,
            "pid": pid,
            "tid": 0,
            "args": dict(e.get("fields") or {}, seq=e.get("seq", 0)),
        })
    trace_events.sort(key=lambda ev: ev["ts"])
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "metadata": {"pid": pid},
    }


def export_chrome_trace(path: str, spans: Optional[list] = None,
                        events: Optional[list] = None) -> str:
    """Write :func:`to_chrome_trace` to ``path`` atomically (tmp +
    rename); returns the path."""
    doc = to_chrome_trace(spans, events)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


# -- shared JSONL sink --------------------------------------------------------


class _LockedLineFile:
    """A ``write(str)`` sink shared by the event and span logs: one
    lock across both, so their lines can never interleave mid-record
    (each log's own ``_sink_lock`` only serializes within that log)."""

    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()

    def write(self, s: str) -> None:
        with self._lock:
            # serializing this file I/O is this lock's entire job (one
            # line per record across BOTH logs); it is a leaf lock —
            # nothing else is ever acquired under it.  Callers holding
            # other locks are not excused by this marker.
            # datlint: allow-blocking-under-lock(file-io)
            self._f.write(s)
            # datlint: allow-blocking-under-lock(file-io)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.close()


def attach_jsonl_sink(path: str) -> _LockedLineFile:
    """Mirror every subsequent event AND span as JSONL into ``path``
    (append mode) through one shared lock.  Returns the sink — call
    ``close()`` after detaching.  The offline timeline CLI consumes
    these files: one per peer."""
    sink = _LockedLineFile(open(path, "a", encoding="utf-8"))
    EVENTS.attach_sink(sink)
    SPANS.attach_sink(sink)
    return sink
