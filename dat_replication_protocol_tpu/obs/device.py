"""Device-path telemetry: recompile sentinel + backend-init watchdog.

PRs 3-4 made the *host* session datapath observable; the device path —
Pallas kernels, the DigestPipeline, mesh programs — stayed dark: the
round-5 bench artifact ends with an opaque ``"backend init hung
(> 87s)"`` and the recompile hazards behind the round-2 ~2000x CDC
regression (SURVEY.md §5) were guarded only by code comments.  This
module extends the same zero-dependency ``obs`` discipline (hoisted
``OBS.on`` gate, literal names, bounded rings) down to the device
boundary.  JAX is never imported at module level — the session layer
must stay importable (and hang-proof) in device-less processes.

Three parts:

* **Recompile sentinel** — :func:`jit_site` wraps a jitted callable
  with a named call-site.  Per call (gate on) it detects whether the
  call TRACED (a fresh specialization) vs hit the jit cache, via the
  callable's own lowering-cache size when it exposes one
  (``PjitFunction._cache_size``) and an arg-shape-signature closure
  otherwise.  Every trace records a ``device.jit.trace`` event with
  the site and the arg-shape signature; :data:`SENTINEL` aggregates
  per-site calls/traces; :class:`RecompileBudget` flags any site that
  recompiles more than N times per process — the unbucketed-batch-size
  failure mode ``ops/blake2b.py`` buckets against (jit specializes per
  (B, nblocks); an unbucketed stream recompiles every distinct count,
  minutes each on the CPU scanned path).
* **Backend-init watchdog** — :class:`BackendInitWatchdog` wraps
  backend bring-up in a ``backend.init`` span with staged progress
  events (``platform_probe`` -> ``first_device_call`` ->
  ``first_compile``) and a deadline timer that, instead of today's
  opaque multi-minute hang, emits ``backend.init.stuck`` naming the
  stage it is stuck IN and dumps a flight-recorder bundle (when armed)
  whose manifest carries the stage and elapsed seconds.
* **Device gauges / engine attribution** — :func:`sample_device_gauges`
  snapshots live-buffer count and device bytes-in-use at phase
  boundaries (only when a backend is ALREADY initialized: the sampler
  must never be the thing that wedges); :func:`note_engine` records
  ``device.engine.select`` events when a routing layer's
  pallas/native/host choice changes.

Catalog and budget: OBSERVABILITY.md (device-telemetry section).
"""
from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional

from .events import emit as _emit
from .metrics import OBS as _OBS
from .metrics import counter as _counter
from .metrics import gauge as _gauge
from . import flight as _flight
from . import tracing as _tracing

__all__ = [
    "SENTINEL",
    "JitSentinel",
    "RecompileBudget",
    "BackendInitWatchdog",
    "jit_site",
    "note_engine",
    "sample_device_gauges",
    "DEFAULT_RECOMPILE_BUDGET",
]

# jit-cache traffic across ALL sites (per-site split: SENTINEL.snapshot)
_M_JIT_CALLS = _counter("device.jit.calls")
_M_JIT_TRACES = _counter("device.jit.traces")
_G_LIVE_BUFFERS = _gauge("device.mem.live_buffers")
_G_BYTES_IN_USE = _gauge("device.mem.bytes_in_use")

# traces per site before the sentinel flags it: generous enough for the
# legitimate power-of-two bucket ladder (a handful of (B, nblocks)
# shapes per engine), small enough to catch an unbucketed stream within
# its first dozen batches instead of after a 2000x regression ships
DEFAULT_RECOMPILE_BUDGET = 8

# shape-signature sets are bounded: a pathological site (the exact bug
# class the sentinel hunts) would otherwise grow the set forever — past
# the cap every unseen signature still COUNTS as a trace, it just is
# not retained
_MAX_RETAINED_SIGS = 256


def _sig_of(v) -> object:
    shape = getattr(v, "shape", None)
    if shape is not None:
        return (tuple(shape), str(getattr(v, "dtype", "")))
    if isinstance(v, (bool, int, float, str, bytes, type(None))):
        return v
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,) + tuple(_sig_of(x) for x in v)
    return type(v).__name__


def _signature(args: tuple, kwargs: dict) -> tuple:
    """Hashable abstract signature of one call: shapes/dtypes for
    array-likes, values for static scalars — the same axes jit
    specializes on, so a new signature approximates a new trace."""
    sig = tuple(_sig_of(a) for a in args)
    if kwargs:
        sig += tuple((k, _sig_of(kwargs[k])) for k in sorted(kwargs))
    return sig


def _sig_str(sig: tuple) -> str:
    """Compact display form for events ("(8, 16)u32" style)."""

    def one(p) -> str:
        if isinstance(p, tuple) and len(p) == 2 and isinstance(p[0], tuple):
            return f"{p[0]}{p[1]}"
        return repr(p)

    return ",".join(one(p) for p in sig)


class _SiteStats:
    """Per-site aggregate; shared by every wrapper registered under one
    name (e.g. one mesh program per mesh, one site name)."""

    __slots__ = ("name", "lock", "calls", "traces", "sigs", "flagged",
                 "last_signature")

    def __init__(self, name: str):
        self.name = name
        self.lock = threading.Lock()
        self.calls = 0
        self.traces = 0
        self.sigs: set = set()
        self.flagged = False
        self.last_signature: Optional[str] = None


class JitSentinel:
    """Process-global per-site trace/call accounting."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sites: dict[str, _SiteStats] = {}

    def _stats(self, name: str) -> _SiteStats:
        with self._lock:
            st = self._sites.get(name)
            if st is None:
                st = self._sites[name] = _SiteStats(name)
            return st

    def snapshot(self) -> dict:
        """``{site: {"calls": n, "traces": n}}`` for every site that has
        been CALLED (registered-but-idle sites are omitted)."""
        with self._lock:
            sites = list(self._sites.values())
        out = {}
        for st in sites:
            with st.lock:
                if st.calls:
                    out[st.name] = {"calls": st.calls, "traces": st.traces}
        return out

    def over_budget(self, limit: int = DEFAULT_RECOMPILE_BUDGET) -> list[dict]:
        """Sites whose trace count exceeds ``limit``, worst first."""
        out = []
        for name, rec in self.snapshot().items():
            if rec["traces"] > limit:
                out.append({"site": name, **rec})
        out.sort(key=lambda r: -r["traces"])
        return out

    def reset_for_tests(self) -> None:
        """Zero every site's VALUES in place, keeping the registrations
        (and the stats objects module-level ``jit_site`` wrappers hold)
        intact — clearing the dict would orphan those handles, exactly
        the hazard ``Registry.reset`` documents."""
        with self._lock:
            sites = list(self._sites.values())
        for st in sites:
            with st.lock:
                st.calls = 0
                st.traces = 0
                st.sigs.clear()
                st.flagged = False
                st.last_signature = None


SENTINEL = JitSentinel()


class RecompileBudget:
    """The enforceable face of the sentinel: ``check()`` returns every
    site recompiling more than ``limit`` times this process (empty =
    healthy), for callers that want a hard gate rather than events."""

    def __init__(self, limit: int = DEFAULT_RECOMPILE_BUDGET,
                 sentinel: JitSentinel = SENTINEL):
        if limit < 1:
            raise ValueError("recompile budget must be >= 1")
        self.limit = limit
        self._sentinel = sentinel

    def check(self) -> list[dict]:
        return self._sentinel.over_budget(self.limit)

    def ok(self) -> bool:
        return not self.check()


_trace_state_clean: Optional[Callable[[], bool]] = None


def _outside_jax_trace() -> bool:
    """True when we are NOT inside a jax trace.  Sites wrapped by the
    sentinel are also called from INSIDE other jitted programs (mesh
    steps call ``blake2b_packed``, ``diff_root_guided_packed`` calls
    ``diff_root_guided``); those invocations run once per OUTER trace
    and never per execution, so counting them would report
    calls == traces — the exact pathology signature the sentinel
    exists to flag — for perfectly healthy inner sites.  Bound lazily:
    jax is never imported here, only observed if already loaded."""
    global _trace_state_clean
    fn = _trace_state_clean
    if fn is None:
        jax = sys.modules.get("jax")
        if jax is None:
            return True  # no jax in the process: nothing can be tracing
        try:
            fn = jax.core.trace_state_clean
        except Exception:
            fn = lambda: True  # noqa: E731 — no introspection available
        _trace_state_clean = fn
    try:
        return fn()
    except Exception:
        return True


class _JitSite:
    """The wrapper :func:`jit_site` returns.  Disabled path: one gate
    attribute load, then straight through to the wrapped callable.
    Trace-time invocations (the wrapper called while an OUTER program
    traces) bypass accounting entirely — see :func:`_outside_jax_trace`.
    Unknown attributes (``lower``, ``clear_cache``, ...) delegate to the
    wrapped jit so the site stays a drop-in."""

    __slots__ = ("_fn", "_stats", "_cache_size", "_cache_seen")

    def __init__(self, name: str, fn: Callable):
        self._fn = fn
        self._stats = SENTINEL._stats(name)
        cs = getattr(fn, "_cache_size", None)
        self._cache_size = cs if callable(cs) else None
        # high-water of the jit cache size this wrapper has accounted
        # for: the trace CLAIM happens under the stats lock against it,
        # so two threads overlapping one trace charge it exactly once
        self._cache_seen: Optional[int] = None

    @property
    def site(self) -> str:
        return self._stats.name

    @property
    def __wrapped__(self) -> Callable:
        return self._fn

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __call__(self, *args, **kwargs):
        if not _OBS.on:
            return self._fn(*args, **kwargs)
        if not _outside_jax_trace():
            return self._fn(*args, **kwargs)
        cs = self._cache_size
        before = cs() if cs is not None else None
        out = self._fn(*args, **kwargs)
        sig = None
        st = self._stats
        with st.lock:
            st.calls += 1
            if cs is not None:
                # a trace happened iff the cache grew DURING this call
                # (growth outside the sampling window — e.g. trace-time
                # bypassed invocations compiling under an outer jit —
                # never counts), and is CLAIMED against the high-water
                # under the lock: a cache-hit call overlapping another
                # thread's trace sees the growth already claimed and
                # stays silent.  (Two DISTINCT concurrent traces can
                # collapse to one count — undercount, never a false
                # recompile alarm.)
                now = cs()
                seen = self._cache_seen
                traced = now > before and (seen is None or now > seen)
                if seen is None or now > seen:
                    self._cache_seen = now
                if traced:
                    sig = _signature(args, kwargs)
            else:
                sig = _signature(args, kwargs)
                traced = sig not in st.sigs
            if sig is not None and len(st.sigs) < _MAX_RETAINED_SIGS:
                st.sigs.add(sig)
            if traced:
                st.traces += 1
                traces = st.traces
                st.last_signature = _sig_str(sig)
                flag = traces > DEFAULT_RECOMPILE_BUDGET and not st.flagged
                if flag:
                    st.flagged = True
            else:
                traces = st.traces
                flag = False
        _M_JIT_CALLS.inc()
        if traced:
            _M_JIT_TRACES.inc()
            _emit("device.jit.trace", site=st.name, signature=_sig_str(sig),
                  traces=traces)
            if flag:
                # the unbucketed-batch-size failure mode, caught live:
                # one event per site per process, however long it runs
                _emit("device.jit.recompile_budget", site=st.name,
                      traces=traces, budget=DEFAULT_RECOMPILE_BUDGET,
                      signature=_sig_str(sig))
        return out


def jit_site(name: str, fn: Callable) -> _JitSite:
    """Register ``fn`` (a jitted callable) as the named call-site and
    return the sentinel wrapper.  ``name`` is a dot-separated literal
    (the obs-discipline rule enforces greppability at call sites)."""
    return _JitSite(name, fn)


# -- engine-selection attribution ---------------------------------------------

# last engine noted per component: the select event records CHANGES,
# not every dispatch — a steady pipeline emits one line, a flapping
# router shows every flap
_engine_lock = threading.Lock()
_engine_last: dict[str, str] = {}


def note_engine(component: str, engine: str, key=None, **fields) -> None:
    """Record ``device.engine.select`` when ``component``'s routed
    engine changes (pallas / xla-scan / native / hashlib / ...).  Call
    sites guard with ``if _OBS.on:``; this function does not re-check
    the gate.

    ``key`` widens the change-only memo for decisions that are
    legitimately per-shape: the blake2b batch edge picks its engine
    per block-count BUCKET, and a payload mix straddling the pallas
    item floor would otherwise flap pallas<->xla-scan on every
    dispatch, churning the bounded event ring with noise."""
    memo = component if key is None else (component, key)
    with _engine_lock:
        if _engine_last.get(memo) == engine:
            return
        _engine_last[memo] = engine
    _emit("device.engine.select", component=component, engine=engine,
          **fields)


def reset_engine_notes() -> None:
    """Forget the change-only memo so the NEXT dispatch re-emits every
    component's ``device.engine.select``.  Capture boundaries call this
    alongside clearing the event/span rings (bench's per-config trace
    export, the test fixture) — a cleared ring with a warm memo would
    silently drop engine attribution from every later capture."""
    with _engine_lock:
        _engine_last.clear()


# -- device memory gauges -----------------------------------------------------


def sample_device_gauges() -> bool:
    """Update ``device.mem.live_buffers`` / ``device.mem.bytes_in_use``
    from an ALREADY-initialized jax backend; returns True when a sample
    was taken.  Never initializes a backend itself: on a wedged device
    tunnel that first init is exactly the hang the watchdog exists to
    attribute, so an uninitialized process samples nothing."""
    if not _OBS.on:
        return False
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return False
    try:
        import jax  # noqa: PLC0415 — guaranteed imported already

        _G_LIVE_BUFFERS.set(float(len(jax.live_arrays())))
        stats = jax.local_devices()[0].memory_stats() or {}
        if "bytes_in_use" in stats:
            _G_BYTES_IN_USE.set(float(stats["bytes_in_use"]))
        return True
    except Exception:
        return False


# -- backend-init watchdog ----------------------------------------------------

# the canonical stage ladder (callers may add their own stages between;
# the names below are what bench.py's probe and the docs use)
INIT_STAGES = ("platform_probe", "first_device_call", "first_compile")


class BackendInitWatchdog:
    """Deadline + staged progress around backend bring-up.

    Usage::

        with BackendInitWatchdog(deadline_s=90) as wd:
            wd.stage("platform_probe")
            import jax; jax.config.update(...)
            wd.stage("first_device_call")
            jax.devices()
            wd.stage("first_compile")
            jax.jit(f)(x)

    Each ``stage()`` emits ``backend.init.stage`` and samples the
    device gauges.  If the deadline expires before ``__exit__``, the
    timer thread emits ``backend.init.stuck`` naming the stage the init
    is stuck IN and dumps a flight bundle (reason
    ``backend-init-stuck``) whose manifest ``extra`` carries the stage,
    elapsed seconds, and the full stage timeline — the answer the
    round-5 ``"backend init hung (> 87s)"`` string never gave.  The
    watchdog only OBSERVES: the wrapped init keeps running (callers
    own their own timeouts/subprocesses)."""

    def __init__(self, deadline_s: float = 90.0,
                 on_timeout: Optional[Callable[["BackendInitWatchdog"], None]]
                 = None):
        if deadline_s <= 0:
            raise ValueError("deadline must be positive")
        self.deadline_s = deadline_s
        self.fired = False
        self.finished = False
        self.stages: list[tuple[str, float]] = []  # (name, elapsed_s)
        self._on_timeout = on_timeout
        self._lock = threading.Lock()
        self._t0 = 0.0
        self._timer: Optional[threading.Timer] = None
        self._span = None

    @property
    def current_stage(self) -> Optional[str]:
        with self._lock:
            return self.stages[-1][0] if self.stages else None

    @property
    def elapsed_s(self) -> float:
        return time.monotonic() - self._t0

    def __enter__(self) -> "BackendInitWatchdog":
        self._t0 = time.monotonic()
        self._span = _tracing.trace_span("backend.init",
                                         deadline_s=self.deadline_s)
        self._span.__enter__()
        self._timer = threading.Timer(self.deadline_s, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def stage(self, name: str) -> None:
        """Enter a named init stage (names are literals at call sites —
        same greppability contract as event names)."""
        elapsed = self.elapsed_s
        with self._lock:
            self.stages.append((name, round(elapsed, 3)))
        if _OBS.on:
            _emit("backend.init.stage", stage=name,
                  elapsed_s=round(elapsed, 3))
        sample_device_gauges()

    def _fire(self) -> None:
        with self._lock:
            if self.finished:
                return
            self.fired = True
            stage = self.stages[-1][0] if self.stages else None
            timeline = list(self.stages)
        elapsed = round(self.elapsed_s, 3)
        if _OBS.on:
            _emit("backend.init.stuck", stage=stage, elapsed_s=elapsed,
                  deadline_s=self.deadline_s)
        # bundle FIRST: sampling gauges talks to the very backend that
        # just proved itself wedged and can block this timer thread
        # forever — the post-mortem must already be on disk by then
        # (the registry in the bundle carries the gauges the last
        # healthy stage() sampled).
        _flight.dump(
            "backend-init-stuck",
            extra={"stage": stage, "elapsed_s": elapsed,
                   "deadline_s": self.deadline_s,
                   "stages": [{"stage": s, "at_s": at} for s, at in timeline]},
        )
        cb = self._on_timeout
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass  # an observer callback must never break the init
        # last, for the same reason the bundle came first: if the
        # wedged backend hangs this sample, only the (daemon) timer
        # thread is lost
        sample_device_gauges()

    def __exit__(self, exc_type, exc, tb) -> bool:
        with self._lock:
            self.finished = True
        if self._timer is not None:
            self._timer.cancel()
            # an init that completes RIGHT AT the deadline races a
            # _fire already past its finished check — by then the init
            # really did exceed the deadline, so the stuck record is
            # earned; joining just makes the ordering deterministic
            # (stuck/dump land before done, and self.fired is stable
            # once this returns)
            if self._timer.is_alive():
                self._timer.join(timeout=2.0)
        if _OBS.on:
            _emit("backend.init.done", elapsed_s=round(self.elapsed_s, 3),
                  stages=len(self.stages), stuck=self.fired,
                  error=(exc_type.__name__ if exc_type else None))
        sample_device_gauges()
        if self._span is not None:
            self._span.__exit__(exc_type, exc, tb)
        return False
