"""Perf-budget regression gate: bench artifacts become a contract.

Five rounds of ``BENCH_*.json`` files accumulated as an unread trail —
nothing failed when a number regressed (the round-2 ~2000x CDC
regression shipped exactly that way).  This module compares one
``bench.py --metrics`` artifact (the one-line JSON bench prints)
against checked-in per-metric budgets and returns a verdict; the CLI
(``python -m dat_replication_protocol_tpu.obs perf-check``) exits
nonzero on regression, so CI and the driver can gate on it.

Budget file format (``artifacts/perf_budgets.json``)::

    {
      "configs": {
        "<config name>": {
          "group": "host" | "device",
          "checks": [
            {"field": "value",          # key in the config's result
             "direction": "higher",     # "higher" = bigger is better
             "reference": 16691.4,      # from BENCH history (PERF.md)
             "ratio": 0.05,             # fail below reference*ratio
             "reduced_ratio": 0.02}     # looser bound when the result
          ]                             # says reduced_config: true
        }
      }
    }

Semantics:

* ``direction: "higher"`` fails when ``value < reference * ratio``;
  ``"lower"`` (latencies) fails when ``value > reference / ratio``.
* **Reduced-config aware**: a result carrying ``reduced_config: true``
  (bench's own in-band below-full-shape marker) is judged against
  ``reduced_ratio`` when present — quick/CI shapes get the loose
  bound, a full-config capture the real one.
* ``--host-only`` evaluates only ``group: "host"`` configs (1/2/6/7 run
  with no JAX backend at all) — the CPU-safe tier-1 mode.
* A budgeted config that is missing from the snapshot, or carries an
  ``"error"``, fails — a gate that passes on absent data is not a gate
  (``"optional": true`` on the config entry downgrades that to a skip,
  for device configs that legitimately vanish on device-less runners).

Ratios are deliberately generous (PERF.md: budgets are set from BENCH
history at ~5-20x headroom): the gate exists to catch order-of-
magnitude cliffs mechanically, not to flake on shared-chip noise.
"""

from __future__ import annotations

import json
from typing import Optional

__all__ = ["load_budgets", "check_snapshot", "DEFAULT_BUDGETS_PATH"]

DEFAULT_BUDGETS_PATH = "artifacts/perf_budgets.json"


def load_budgets(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        budgets = json.load(f)
    if "configs" not in budgets or not isinstance(budgets["configs"], dict):
        raise ValueError(f"{path}: budget file has no 'configs' table")
    return budgets


def _check_one(config: str, result: dict, check: dict) -> dict:
    """Evaluate one check against one config result; returns a verdict
    row: {config, field, status: ok|fail|skip, ...}."""
    field = check.get("field", "value")
    direction = check.get("direction", "higher")
    reference = check.get("reference")
    ratio = check.get("ratio", 0.1)
    reduced = bool(result.get("reduced_config"))
    if reduced and "reduced_ratio" in check:
        ratio = check["reduced_ratio"]
    if (reference is None or direction not in ("higher", "lower")
            or not isinstance(ratio, (int, float)) or ratio <= 0):
        # a malformed budget entry is a per-check failure row, never a
        # traceback — the gate's contract is exit 1 + a readable report
        return {"config": config, "field": field, "status": "fail",
                "detail": "malformed check (needs reference, a "
                          "higher/lower direction, and a positive ratio)"}
    value = result.get(field)
    if not isinstance(value, (int, float)):
        return {"config": config, "field": field, "status": "fail",
                "value": value,
                "detail": f"field {field!r} missing or non-numeric"}
    if direction == "higher":
        bound = reference * ratio
        ok = value >= bound
        rel = "<" if not ok else ">="
    else:
        bound = reference / ratio
        ok = value <= bound
        rel = ">" if not ok else "<="
    return {
        "config": config, "field": field,
        "status": "ok" if ok else "fail",
        "value": value, "bound": bound, "reference": reference,
        "ratio": ratio, "direction": direction, "reduced": reduced,
        "detail": f"{field}={value:g} {rel} bound {bound:g} "
                  f"(reference {reference:g} x ratio {ratio:g}"
                  f"{', reduced config' if reduced else ''})",
    }


def check_snapshot(snapshot: dict, budgets: dict,
                   host_only: bool = False) -> list[dict]:
    """Evaluate every budgeted config against a bench artifact dict
    (the parsed one-line JSON).  Returns verdict rows; callers gate on
    ``any(r["status"] == "fail")``."""
    configs = snapshot.get("configs", {})
    rows: list[dict] = []
    for name, entry in budgets["configs"].items():
        group = entry.get("group", "device")
        if host_only and group != "host":
            rows.append({"config": name, "field": "-", "status": "skip",
                         "detail": f"group {group!r} skipped (--host-only)"})
            continue
        result = configs.get(name)
        optional = bool(entry.get("optional"))
        if result is None or "error" in (result or {}):
            status = "skip" if optional else "fail"
            why = ("absent from snapshot" if result is None
                   else f"errored: {result['error']}")
            rows.append({"config": name, "field": "-", "status": status,
                         "detail": f"config {why}"
                                   + (" (optional)" if optional else "")})
            continue
        checks = entry.get("checks")
        if not isinstance(checks, list) or not checks:
            # a budgeted config with nothing evaluable would pass
            # vacuously — a gate that passes on absent checks is not a
            # gate (same contract as missing/errored configs)
            rows.append({"config": name, "field": "-", "status": "fail",
                         "detail": "budget entry has no evaluable checks"})
            continue
        for check in checks:
            rows.append(_check_one(name, result, check))
    return rows


def run_check(snapshot_path: str, budgets_path: str = DEFAULT_BUDGETS_PATH,
              host_only: bool = False,
              out=None) -> int:
    """Load, evaluate, report (one line per check to ``out``, default
    stdout); returns the process exit code (1 on any failure)."""
    import sys

    out = out if out is not None else sys.stdout
    with open(snapshot_path, encoding="utf-8") as f:
        snapshot = _parse_snapshot(f.read(), snapshot_path)
    budgets = load_budgets(budgets_path)
    rows = check_snapshot(snapshot, budgets, host_only=host_only)
    failed = 0
    for r in rows:
        mark = {"ok": "OK  ", "fail": "FAIL", "skip": "skip"}[r["status"]]
        print(f"{mark} {r['config']:<12} {r['detail']}", file=out)
        failed += r["status"] == "fail"
    verdict = "REGRESSION" if failed else "within budget"
    print(f"perf-check: {len(rows)} check(s), {failed} failed — {verdict}",
          file=out)
    return 1 if failed else 0


def _parse_snapshot(text: str, path: str) -> dict:
    """A bench artifact file is one JSON object, but driver logs wrap
    noise around it — and may interleave OTHER JSON lines (periodic
    ``--stats-fd`` snapshots).  Bench prints its artifact LAST, so scan
    lines in reverse and prefer the first object that actually carries
    a ``configs`` table; fall back to the last parseable object."""
    text = text.strip()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        fallback = None
        for ln in reversed(text.splitlines()):
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                obj = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "configs" in obj:
                return obj
            if fallback is None:
                fallback = obj
        if fallback is not None:
            return fallback
        raise ValueError(f"{path}: no parseable bench JSON found")


def find_first_failure(rows: list[dict]) -> Optional[dict]:
    for r in rows:
        if r["status"] == "fail":
            return r
    return None
