"""Zero-dependency metrics core: counters, gauges, histograms.

Design constraints (ISSUE 3 acceptance, OBSERVABILITY.md):

* **Disabled path is one attribute load.**  Instrumentation sites hold
  a pre-bound metric handle (created at module import) and guard with
  ``if _OBS.on:`` — no registry lookup, no dict allocation, no call at
  all when telemetry is off.  ``OBS`` is a one-slot object so the
  check compiles to LOAD_GLOBAL + LOAD_ATTR + POP_JUMP, the same
  hoisted-gate trick as ``_fastpath_gate``.
* **The gate is a runtime LATCH, not a per-call env read.**  Unlike
  ``DAT_FASTPATH_DISABLE`` (a behavior fork that must stay re-readable,
  see the env-cache-policy rule), the obs gate exists precisely so hot
  paths do NOT pay an environ lookup: ``DAT_OBS=1`` seeds the initial
  state, and :func:`enable` / :func:`disable` flip it at runtime
  (the sidecar's ``--stats-fd`` does, tests do).
* **Enabled path favors correctness over nanoseconds.**  Every mutate
  takes the metric's lock: a Python ``x += 1`` is a read-modify-write
  that can lose increments across threads, and the session stack is
  aggressively multi-threaded (pumps, ack threads, the sidecar).  The
  overhead budget test bounds only the disabled path.
* **Snapshots are plain dicts** (JSON-able as-is): the sidecar's
  ``--stats-fd`` dumps, ``bench.py --metrics`` attribution, and the
  conformance oracle all consume the same shape.

Histograms keep BOTH fixed-bucket counts (cheap, mergeable) and a
fixed-size ring of recent observations (wraparound overwrite) so
``snapshot()`` can report approximate quantiles of the *recent* window
without unbounded memory.
"""

from __future__ import annotations

import math
import os
import re
import threading
from typing import Optional, Sequence

__all__ = [
    "OBS",
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "to_prom_text",
    "enable",
    "disable",
]


class _Gate:
    """The hoisted enable gate.  One mutable slot; instrumentation
    sites read ``OBS.on`` and nothing else."""

    __slots__ = ("on",)

    def __init__(self) -> None:
        self.on = False


OBS = _Gate()


def enable() -> None:
    """Turn telemetry on process-wide (idempotent)."""
    OBS.on = True


def disable() -> None:
    OBS.on = False


def _seed_gate_from_env() -> None:
    # initial state only — enable()/disable() own the gate afterwards
    # (a latch by design: the whole point of the hoisted gate is that
    # hot paths never pay an environ read; see module docstring)
    if os.environ.get("DAT_OBS", "") not in ("", "0"):
        OBS.on = True


_seed_gate_from_env()


class Counter:
    """Monotonic counter.  ``inc`` under the lock: increments from pump
    threads, ack threads, and the sidecar's emitter must not be lost."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


# Default buckets span the session stack's latency range: sub-us gate
# checks up through multi-second backoff sleeps.  Upper edges are
# INCLUSIVE (observe(x) lands in the first bucket with x <= edge), with
# an implicit +inf overflow bucket.
DEFAULT_BUCKETS = (
    1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0,
)

DEFAULT_RING = 256


class Histogram:
    """Fixed buckets + a ring buffer of recent raw observations.

    The buckets give cheap, mergeable distribution counts; the ring
    gives approximate quantiles over the most recent ``ring`` samples
    (older samples are overwritten — wraparound, bounded memory).
    """

    __slots__ = ("name", "buckets", "_lock", "_counts", "_count", "_sum",
                 "_ring", "_ring_n")

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 ring: int = DEFAULT_RING):
        if list(buckets) != sorted(buckets) or len(set(buckets)) != len(
                tuple(buckets)):
            raise ValueError("histogram buckets must be sorted and unique")
        if ring < 1:
            raise ValueError("ring size must be >= 1")
        self.name = name
        self.buckets = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +1: +inf overflow
        self._count = 0
        self._sum = 0.0
        self._ring: list[float] = [0.0] * ring
        self._ring_n = 0  # total observations ever; ring index = n % len

    def observe(self, v: float) -> None:
        with self._lock:
            i = 0
            buckets = self.buckets
            n = len(buckets)
            while i < n and v > buckets[i]:
                i += 1
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            ring = self._ring
            ring[self._ring_n % len(ring)] = v
            self._ring_n += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Approximate ``q``-quantile (0..1) over the ring window, or
        None before the first observation.  Nearest-rank on a sorted
        copy — snapshot-time cost, not observe-time cost."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be in [0, 1]")
        with self._lock:
            n = min(self._ring_n, len(self._ring))
            if n == 0:
                return None
            window = sorted(self._ring[:n])
        rank = min(n - 1, max(0, math.ceil(q * n) - 1))
        return window[rank]

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._count = 0
            self._sum = 0.0
            self._ring_n = 0

    def _snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            count = self._count
            total = self._sum
            n = min(self._ring_n, len(self._ring))
            window = sorted(self._ring[:n])

        def q(frac: float) -> Optional[float]:
            if not window:
                return None
            rank = min(len(window) - 1, max(0, math.ceil(frac * len(window)) - 1))
            return window[rank]

        return {
            "count": count,
            "sum": total,
            "buckets": [[le, c] for le, c in zip(self.buckets, counts)]
            + [["+inf", counts[-1]]],
            "p50": q(0.50),
            "p90": q(0.90),
            "p99": q(0.99),
        }


class Registry:
    """Name -> metric, process-global.  Get-or-create is idempotent so
    any module can hoist a handle at import without ordering concerns;
    a name registered twice with a different TYPE is a programming
    error and raises.

    **Collectors** are the bounded-cardinality answer to per-entity
    metrics (ISSUE 8: per-session hub telemetry): registering one
    counter/gauge per session key would grow the registry forever —
    sessions come and go, metric registrations never do.  A collector
    is a callable the owner registers ONCE; at ``snapshot()`` time it
    returns ``{"counters": {...}, "gauges": {...}}`` for the entities
    *currently alive*, and those entries are merged into the snapshot
    (labeled names — ``hub.session.parked_bytes{session=k}`` — keep
    them distinguishable from registered metrics).  Dead entities
    simply stop appearing; nothing leaks.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}
        self._collectors: dict[str, object] = {}

    def _get(self, name: str, cls, *args, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                # ``cls`` is always one of THIS module's metric classes
                # (Counter/Gauge/Histogram — the three public wrappers
                # are the only callers): a cheap pure constructor, not
                # user code, so constructing under the registry lock
                # cannot block or re-enter.
                # datlint: allow-blocking-under-lock(callback)
                m = cls(name, *args, **kwargs)
                self._metrics[name] = m
            elif type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  ring: int = DEFAULT_RING) -> Histogram:
        h = self._get(name, Histogram, buckets, ring)
        # parameter drift is the same silent catalog fork the type
        # check above guards: a second registration with different
        # edges would quietly get the FIRST caller's buckets
        if h.buckets != tuple(float(b) for b in buckets) \
                or len(h._ring) != ring:
            raise ValueError(
                f"histogram {name!r} already registered with different "
                f"buckets/ring")
        return h

    def register_collector(self, name: str, fn) -> None:
        """Attach a snapshot-time collector (see class docstring).
        ``fn()`` must return a dict with optional ``counters`` /
        ``gauges`` sections; re-registering a name replaces the old
        collector (the hub re-registers on restart)."""
        with self._lock:
            self._collectors[name] = fn

    def unregister_collector(self, name: str, fn=None) -> None:
        """Remove a collector.  Pass the registered ``fn`` to make the
        removal owner-checked: a replaced collector's OLD owner closing
        late must not delete the NEW owner's live entry (the hub
        rolling-restart pattern)."""
        with self._lock:
            if fn is None or self._collectors.get(name) is fn:
                self._collectors.pop(name, None)

    def snapshot(self) -> dict:
        """Plain-dict view of every registered metric (JSON-able)."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors.values())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in metrics:
            if isinstance(m, Counter):
                out["counters"][m.name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][m.name] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][m.name] = m._snapshot()
        for fn in collectors:
            try:
                # collectors are snapshot-grade attribute reads (the
                # hub/fanout/edge `_collect` contract) — best-effort,
                # absorbed by the except arm below
                # datlint: allow-callback-escape
                contributed = fn()
            except Exception:
                # a dying collector (hub mid-close) must not take the
                # whole snapshot down — the registered metrics are the
                # contract, collector entries are best-effort extras
                continue
            for section in ("counters", "gauges"):
                out[section].update(contributed.get(section, {}))
        return out

    def reset(self) -> None:
        """Zero every metric's VALUE, keeping registrations (and the
        handles instrumentation sites hoisted) intact — per-test and
        per-bench-config isolation.  Collectors ARE dropped: they hold
        references into live owner state (a hub), and a collector
        surviving its test/config would leak that state into the next
        snapshot."""
        with self._lock:
            metrics = list(self._metrics.values())
            self._collectors.clear()
        for m in metrics:
            m._reset()


REGISTRY = Registry()


def counter(name: str) -> Counter:
    """Get-or-create a counter in the process-global registry."""
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
              ring: int = DEFAULT_RING) -> Histogram:
    return REGISTRY.histogram(name, buckets, ring)


def snapshot() -> dict:
    return REGISTRY.snapshot()


# -- Prometheus text exposition ----------------------------------------------

_PROM_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Catalog name -> Prometheus metric name: dots become underscores,
    everything namespaced under ``dat_`` (``decoder.blob.bytes`` ->
    ``dat_decoder_blob_bytes``)."""
    return "dat_" + _PROM_SANITIZE.sub("_", name)


def _prom_series(name: str) -> str:
    """Full series name for one snapshot entry.  Labeled collector
    entries (``hub.session.parked_bytes{session=k1}``) become proper
    Prometheus label sets (``dat_hub_session_parked_bytes{session="k1"}``);
    plain names pass through :func:`_prom_name`."""
    if "{" not in name or not name.endswith("}"):
        return _prom_name(name)
    base, _, labels = name[:-1].partition("{")
    pairs = []
    for part in labels.split(","):
        k, _, v = part.partition("=")
        # exposition-format escaping for label values: backslash,
        # double-quote, and (defensively — producers reject them at
        # their boundary) literal newlines
        v = v.replace("\\", "\\\\").replace('"', '\\"') \
             .replace("\n", "\\n")
        pairs.append(f'{_PROM_SANITIZE.sub("_", k.strip())}="{v}"')
    return _prom_name(base) + "{" + ",".join(pairs) + "}"


def _prom_num(v) -> str:
    if isinstance(v, float):
        if v != v:  # NaN
            return "NaN"
        if v in (float("inf"), float("-inf")):
            return "+Inf" if v > 0 else "-Inf"
        return repr(v)
    return str(v)


def to_prom_text(snap: Optional[dict] = None) -> str:
    """Prometheus text-exposition (v0.0.4) rendering of a registry
    snapshot (default: the live registry).  Counters and gauges map
    directly; histograms emit CUMULATIVE ``_bucket{le=...}`` series
    (the snapshot stores per-bucket counts) plus ``_sum``/``_count``,
    with the implicit overflow bucket as ``le="+Inf"``.  The sidecar's
    ``--stats-fd`` emitter renders this with ``--stats-format prom``."""
    if snap is None:
        snap = REGISTRY.snapshot()
    lines: list[str] = []

    def emit_section(section: str, kind: str) -> None:
        # one TYPE line per metric NAME, however many label sets the
        # collectors contribute — a second TYPE line for the same name
        # makes the whole scrape invalid exposition
        typed: set = set()
        for name, v in sorted(snap.get(section, {}).items()):
            n = _prom_series(name)
            base = n.partition("{")[0]
            if base not in typed:
                typed.add(base)
                lines.append(f"# TYPE {base} {kind}")
            lines.append(f"{n} {_prom_num(v)}")

    emit_section("counters", "counter")
    emit_section("gauges", "gauge")
    for name, h in sorted(snap.get("histograms", {}).items()):
        n = _prom_name(name)
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        for le, count in h["buckets"]:
            cum += count
            label = "+Inf" if le == "+inf" else _prom_num(float(le))
            lines.append(f'{n}_bucket{{le="{label}"}} {cum}')
        lines.append(f"{n}_sum {_prom_num(float(h['sum']))}")
        lines.append(f"{n}_count {h['count']}")
    return "\n".join(lines) + "\n"
