"""Mesh convergence plane: gossip-exchange provenance + divergence
watermarks (ISSUE 19).

The PR 15 gossip mesh converges, but until this module it converged as
a telemetry black box: the fleet plane showed rounds-behind and
quarantine counts, yet nobody could answer *which link, which round,
which record* was holding convergence back.  This is the mesh analogue
of the PR 18 event-loop flight deck — one record shape, one board, no
new protocol machinery ("Simplicity Scales"):

* every :func:`~..cluster.node.gossip_exchange` (both directions, live
  and sim) calls :func:`record_exchange`, which emits ONE structured
  ``gossip.exchange`` span — peer, round, role
  (``initiator``/``responder``), decoded diff size, wire bytes, wall
  seconds, outcome (``converged``/``progress``/``transport``/
  ``corruption``/``refused``) plus the delivered digest prefixes the
  offline meshdoctor rebuilds the propagation tree from;
* the process-global :data:`PROPAGATION` board keeps per-(replica,
  peer) **divergence watermarks** — the diff the exchange's own peel
  result measured, in records and in repair wire bytes — and exports
  them as labeled gauges (``cluster.divergence{replica=,peer=}``,
  ``cluster.divergence_bytes{replica=,peer=}``) through the PR 8
  collector machinery, alongside a ``cluster.frontier{replica=}``
  content-digest gauge (a 52-bit equality FINGERPRINT of the digest —
  two replicas are converged iff the gauges are equal; the magnitude
  means nothing);
* :meth:`PropagationBoard.snapshot` is the ``propagation`` section the
  sidecar's ``--stats-fd`` / ``/snapshot`` records carry — the fleet
  aggregator's mesh-matrix join input (per-pair divergence, per-link
  last-successful-exchange age, exchange-seconds quantiles).

Dark-path discipline (the PR 18 contract): NOTHING here runs unless
``OBS.on`` — the exchange engine forks to a dark twin that the
bytecode-level test proves references no symbol of this module, so the
disabled cost of the whole plane is one attribute load.

Event vocabulary for the offline doctor (``obs meshdoctor``):

``gossip.mesh``
    one per sim/mesh start: ``n``, ``seed``, ``bound``
    (:meth:`~..cluster.sim.ClusterSim.rounds_bound` — the budget the
    doctor's rounds-bound-exceeded flag checks against);
``gossip.hold``
    a replica acquired records OUTSIDE an exchange (initial state,
    snapshot bootstrap, feed drain): ``replica``, ``round``,
    ``digests`` (hex16 prefixes) — the propagation tree's provenance
    roots;
``gossip.exchange`` (span)
    one per exchange per direction; ``delivered`` /
    ``delivered_peer`` carry the digest prefixes each side absorbed;
``gossip.frontier``
    change-only: a replica's content digest moved (``replica``,
    ``round``, ``digest``, ``records``) — the doctor derives the
    convergence round from the LAST frontier change per replica.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Optional

from .events import emit as _emit
from .metrics import REGISTRY as _REGISTRY, OBS as _OBS
from .tracing import SPANS as _SPANS, _span_ids

__all__ = [
    "PROPAGATION",
    "PropagationBoard",
    "record_exchange",
    "note_hold",
    "note_mesh",
    "note_frontier",
    "digest_prefixes",
    "frontier_fingerprint",
    "OUTCOMES",
]

# the exchange outcome vocabulary (OBSERVABILITY.md "Mesh convergence
# plane"): converged (peel found an empty diff), progress (diff moved),
# transport (retryable, no state changed), corruption (structured
# protocol failure — suspicion accrues), refused (quarantine refusal)
OUTCOMES = ("converged", "progress", "transport", "corruption",
            "refused")

# digest prefix length (hex chars) carried by hold/exchange records:
# 64 bits of the 256-bit canonical digest — collision-safe for any
# realistic mesh while keeping JSONL lines bounded
_DIGEST_HEX = 16

# recent exchange wall-seconds window for the p50/p99 export (board-
# owned, NOT a registry histogram: reset_for_tests must drop it with
# the board, and the fleet SLO gate reads the quantile directly)
_SECONDS_RING = 512


def digest_prefixes(digests) -> list:
    """Canonical digest rows (the ``(n, 32)`` uint8 array every
    :class:`~..runtime.reconcile_driver.RatelessReplica` exposes) as
    the hex16 prefixes provenance records carry."""
    return [bytes(d).hex()[:_DIGEST_HEX] for d in digests]


def frontier_fingerprint(digest_hex: str) -> float:
    """The ``cluster.frontier`` gauge value: the content digest's first
    52 bits as a float (exact in IEEE-754 — an EQUALITY fingerprint,
    compared never ordered)."""
    return float(int(digest_hex[:13] or "0", 16))


class PropagationBoard:
    """Process-global per-link exchange provenance + divergence
    watermarks.  See module docstring; the instance is
    :data:`PROPAGATION`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # datlint: guarded-by(self._lock): self._links, self._frontier, self._seconds
        # (replica, peer) -> the last-exchange record for that directed
        # pair, monotonic-stamped
        self._links: dict[tuple, dict] = {}
        # replica -> last frontier record (content digest + count)
        self._frontier: dict[str, dict] = {}
        self._seconds: deque = deque(maxlen=_SECONDS_RING)
        self._collector_fn = self._collect

    # -- recording -----------------------------------------------------------

    def record(self, replica: str, peer: str, *, role: str, rnd: int,
               outcome: str, seconds: float, diff: Optional[int] = None,
               wire_bytes: int = 0, repair_bytes: int = 0,
               error: Optional[str] = None) -> None:
        """Fold one exchange (one direction's view) into the board.
        ``diff`` is the peel result (records in the symmetric
        difference) — only known on completed exchanges; a failed
        exchange keeps the pair's previous divergence watermark (the
        divergence did not heal, and fabricating 0 would read as
        converged — the direction an SLO gate must never err in)."""
        now = time.monotonic()
        with self._lock:
            rec = self._links.setdefault((replica, peer), {
                "role": role, "round": 0, "outcome": None,
                "divergence_records": None, "divergence_bytes": None,
                "wire_bytes": 0, "seconds": 0.0, "exchanges": 0,
                "failures": 0, "error": None, "_mono": now,
                "_ok_mono": None,
            })
            rec["role"] = role
            rec["round"] = int(rnd)
            rec["outcome"] = outcome
            rec["seconds"] = float(seconds)
            rec["wire_bytes"] = int(wire_bytes)
            rec["error"] = error
            rec["exchanges"] += 1
            rec["_mono"] = now
            if outcome in ("converged", "progress"):
                rec["_ok_mono"] = now
                rec["divergence_records"] = int(diff or 0)
                rec["divergence_bytes"] = int(repair_bytes)
            else:
                rec["failures"] += 1
            if outcome != "refused":
                self._seconds.append(float(seconds))
        _REGISTRY.register_collector("propagation", self._collector_fn)

    def note_frontier(self, replica: str, digest_hex: str,
                      records: int, rnd: int) -> bool:
        """Change-only frontier tracking: returns True when the
        replica's content digest actually moved (the caller emits the
        ``gossip.frontier`` event only then)."""
        with self._lock:
            prev = self._frontier.get(replica)
            if prev is not None and prev["digest"] == digest_hex:
                return False
            self._frontier[replica] = {"digest": digest_hex,
                                       "records": int(records),
                                       "round": int(rnd)}
        _REGISTRY.register_collector("propagation", self._collector_fn)
        return True

    # -- export --------------------------------------------------------------

    def exchange_p99(self) -> Optional[float]:
        """p99 exchange wall seconds over the recent window (None
        before the first completed exchange) — the fleet SLO's
        ``max_exchange_p99_s`` input and bench 14's ``exchange_p99_s``
        field."""
        return self._quantile(0.99)

    def _quantile(self, q: float) -> Optional[float]:
        with self._lock:
            window = sorted(self._seconds)
        if not window:
            return None
        rank = min(len(window) - 1,
                   max(0, math.ceil(q * len(window)) - 1))
        return window[rank]

    def snapshot(self) -> dict:
        """The ``propagation`` section of the sidecar snapshot record
        (JSON-able): per-directed-link last-exchange state with ages on
        THIS process's monotonic clock, per-replica frontier, and the
        exchange-seconds quantiles."""
        now = time.monotonic()
        with self._lock:
            links = {f"{r}->{p}": dict(rec)
                     for (r, p), rec in self._links.items()}
            frontier = {k: dict(v) for k, v in self._frontier.items()}
        for rec in links.values():
            rec["age_s"] = round(now - rec.pop("_mono"), 6)
            ok = rec.pop("_ok_mono")
            rec["last_success_age_s"] = (round(now - ok, 6)
                                         if ok is not None else None)
        return {
            "monotonic": now,
            "links": links,
            "frontier": frontier,
            "exchange_seconds": {
                "count": len(self._seconds),
                "p50": self._quantile(0.50),
                "p99": self._quantile(0.99),
            },
        }

    def _collect(self) -> dict:
        """Registry collector: the divergence watermarks as labeled
        gauges (bounded cardinality — one entry per live directed
        pair), plus the frontier equality fingerprints."""
        gauges: dict = {}
        with self._lock:
            links = [(k, dict(v)) for k, v in self._links.items()]
            frontier = list(self._frontier.items())
        for (replica, peer), rec in links:
            if rec["divergence_records"] is None:
                continue  # no completed peel yet: unknown, not zero
            gauges[f"cluster.divergence{{replica={replica},peer={peer}}}"] \
                = float(rec["divergence_records"])
            gauges["cluster.divergence_bytes"
                   f"{{replica={replica},peer={peer}}}"] = float(
                rec["divergence_bytes"])
        for replica, rec in frontier:
            gauges[f"cluster.frontier{{replica={replica}}}"] = \
                frontier_fingerprint(rec["digest"])
        return {"gauges": gauges}

    def reset_for_tests(self) -> None:
        """Drop every link, frontier, and the seconds window (process-
        global state — test isolation is explicit, the conftest
        ``obs_enabled`` contract)."""
        with self._lock:
            self._links.clear()
            self._frontier.clear()
            self._seconds.clear()


PROPAGATION = PropagationBoard()


# -- the instrumentation surface (callers hold the OBS.on gate) --------------


def record_exchange(replica: str, peer: str, *, role: str, rnd: int,
                    outcome: str, seconds: float,
                    diff: Optional[int] = None, wire_bytes: int = 0,
                    repair_bytes: int = 0, delivered=(),
                    delivered_peer=(), t0: Optional[float] = None,
                    error: Optional[str] = None) -> None:
    """One direction's view of one gossip exchange: board watermarks +
    the ``gossip.exchange`` span the meshdoctor consumes.

    ``delivered`` are the digest prefixes THIS replica absorbed,
    ``delivered_peer`` the ones it shipped to ``peer`` — the edges of
    the per-record propagation tree.  ``t0`` is the exchange's start
    on this process's monotonic clock (defaults to now − seconds).
    Callers gate with ``if _OBS.on:`` (dark-path discipline); the span
    ring additionally ignores records while the gate is off."""
    PROPAGATION.record(replica, peer, role=role, rnd=rnd,
                       outcome=outcome, seconds=seconds, diff=diff,
                       wire_bytes=wire_bytes, repair_bytes=repair_bytes,
                       error=error)
    start = t0 if t0 is not None else time.monotonic() - seconds
    fields = {
        "replica": replica, "peer": peer, "role": role, "round": int(rnd),
        "outcome": outcome, "wire_bytes": int(wire_bytes),
        "repair_bytes": int(repair_bytes),
        "seconds": round(float(seconds), 6),
    }
    if diff is not None:
        fields["diff"] = int(diff)
    if delivered:
        fields["delivered"] = list(delivered)
    if delivered_peer:
        fields["delivered_peer"] = list(delivered_peer)
    if error is not None:
        fields["error"] = error
    _SPANS.record("gossip.exchange", start, float(seconds),
                  next(_span_ids), None, threading.get_ident(), fields)


def note_hold(replica: str, digests, rnd: int = 0) -> None:
    """A replica acquired ``digests`` outside any exchange (initial
    state, snapshot bootstrap, broadcast-feed drain) — provenance roots
    for the meshdoctor's orphaned-digest check.  ``digests`` are hex16
    prefixes (:func:`digest_prefixes`)."""
    _emit("gossip.hold", replica=replica, round=int(rnd),
          digests=list(digests))


def note_mesh(n: int, seed: int, bound: int) -> None:
    """One mesh/sim start: the doctor's ground-truth frame (replica
    count, seed, and the bounded round budget convergence is judged
    against)."""
    _emit("gossip.mesh", n=int(n), seed=int(seed), bound=int(bound))


def note_frontier(replica: str, digest_hex: str, records: int,
                  rnd: int) -> bool:
    """Change-only ``gossip.frontier`` event + board state + the
    ``cluster.frontier`` fingerprint gauge.  Returns True when the
    frontier actually moved (callers use this to notice out-of-band
    content changes, e.g. the sim's fan-out leg)."""
    if PROPAGATION.note_frontier(replica, digest_hex, records, rnd):
        _emit("gossip.frontier", replica=replica, round=int(rnd),
              digest=digest_hex, records=int(records))
        return True
    return False


# re-exported so instrumentation call sites can assert the plane's own
# gate state in tests without importing metrics twice
OBS = _OBS
