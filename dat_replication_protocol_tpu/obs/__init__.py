"""obs — session telemetry: metrics core + structured event log.

The reference's only observability is three passive counters
(reference: encode.js:51-53, decode.js:68-70).  This package is the
host-visible telemetry layer for everything the session stack does at
runtime — retries, stalls, replay bytes, watcher-vs-poll wakeups —
exactly the datapath an offload-style deployment no longer steps
through (PAPERS: *Reliable Replication Protocols on SmartNICs*).

Deliberately zero-dependency and flat (stdlib only, no JAX, no numpy):
the layer must be importable and near-free in every process that
touches the session stack, including the stripped CI image
(PAPERS: *Simplicity Scales*).

Four parts:

* :mod:`.metrics` — Counters / Gauges / Histograms in a process-global
  registry behind ONE hoisted enable gate (``OBS.on``): the disabled
  path at an instrumentation site is a single attribute load, the same
  trick as ``_fastpath_gate``.  ``to_prom_text`` renders a snapshot in
  Prometheus text exposition.
* :mod:`.events` — a bounded-ring structured event log (monotonic ts +
  seq) with an optional fd/JSONL sink, for session *lifecycle*:
  connect, checkpoint, resume, backoff, replay, stall, truncation,
  ProtocolError.
* :mod:`.tracing` — wire-offset-correlated spans (ISSUE 4): nestable
  ``trace_span`` contexts, per-frame ``trace_instant`` tags keyed on
  the byte offset each frame starts at, and Chrome trace-event export
  with the JAX profiler annotations of :mod:`..utils.trace` joined in.
* :mod:`.flight` — the flight recorder: on any structured
  ProtocolError or reconnect exhaustion, an armed recorder atomically
  dumps a post-mortem bundle (rings + registry + checkpoint + active
  fault plans) for offline attribution.
* :mod:`.device` — the device boundary (ISSUE 5): the recompile
  sentinel (:func:`~.device.jit_site` wrappers counting traces vs
  cache hits per jit call-site, with a :class:`~.device.RecompileBudget`),
  the backend-init watchdog (staged ``backend.init`` progress with a
  deadline that dumps a flight bundle naming the stuck stage), device
  memory gauges, and engine-selection attribution.
* :mod:`.perf` — the perf-budget regression gate: compares a
  ``bench.py --metrics`` artifact against checked-in per-metric
  budgets (``artifacts/perf_budgets.json``); the CLI's ``perf-check``
  exits nonzero on regression.
* :mod:`.wirecost` — the wire cost plane (ISSUE 20): a per-link byte
  ledger attributing EVERY wire byte to a frame class (change,
  change_batch, blob, reconcile, snapshot, framing-overhead) at the
  existing choke points, with derived goodput/overhead/amplification
  watermarks, the ``obs fleet`` cost-matrix join, and the offline
  ``obs costdoctor`` auditor.  The headline invariant: the ledger
  EXACTLY TILES the wire (residual vs transport ground truth is 0 at
  convergence).
* :mod:`.watermarks` / :mod:`.http` / :mod:`.fleet` — the fleet plane
  (ISSUE 11): wire-position cursors exported as labeled gauges
  (``append − parsed`` is exact replication lag in bytes; append
  marks make lag-in-seconds clock-free), a read-only stdlib-HTTP
  scrape endpoint (sidecar ``--obs-http``: ``/metrics`` ``/snapshot``
  ``/healthz`` ``/events``), and the N-target aggregator behind
  ``obs fleet`` (TTY dashboard, declarative SLO gate).

Offline CLI: ``python -m dat_replication_protocol_tpu.obs`` merges N
peers' JSONL logs into one causally-ordered timeline (``timeline``),
converts logs/bundles to Perfetto-loadable traces (``export-trace``),
pretty-prints bundles (``dump``), and joins live replica targets into
per-link lag (``fleet``).

The fault injector (:mod:`..session.faults`) is the layer's
correctness oracle: it emits ground-truth ``fault.*`` events for every
fault it injects, and the conformance sweep
(tests/test_obs_conformance.py) asserts the session layers' telemetry
agrees — chaos and telemetry must tell the same story.

Catalog, schema, overhead budget: OBSERVABILITY.md.
"""

from __future__ import annotations

from .device import (
    SENTINEL,
    BackendInitWatchdog,
    JitSentinel,
    RecompileBudget,
    jit_site,
    note_engine,
    sample_device_gauges,
)
from .events import EVENTS, EventLog, emit
from .flight import FLIGHT, FlightRecorder, read_bundle
from .metrics import (
    OBS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    disable,
    enable,
    gauge,
    histogram,
    snapshot,
    to_prom_text,
)
from .tracing import (
    SPANS,
    SpanLog,
    attach_jsonl_sink,
    export_chrome_trace,
    to_chrome_trace,
    trace_instant,
    trace_span,
)
from .watermarks import WATERMARKS, WatermarkBoard, link_lag

__all__ = [
    "OBS",
    "REGISTRY",
    "EVENTS",
    "SPANS",
    "FLIGHT",
    "EventLog",
    "SpanLog",
    "FlightRecorder",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "to_prom_text",
    "emit",
    "enable",
    "disable",
    "trace_span",
    "trace_instant",
    "to_chrome_trace",
    "export_chrome_trace",
    "attach_jsonl_sink",
    "read_bundle",
    "SENTINEL",
    "JitSentinel",
    "RecompileBudget",
    "BackendInitWatchdog",
    "jit_site",
    "note_engine",
    "sample_device_gauges",
    "WATERMARKS",
    "WatermarkBoard",
    "link_lag",
]
