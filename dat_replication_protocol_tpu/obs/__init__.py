"""obs — session telemetry: metrics core + structured event log.

The reference's only observability is three passive counters
(reference: encode.js:51-53, decode.js:68-70).  This package is the
host-visible telemetry layer for everything the session stack does at
runtime — retries, stalls, replay bytes, watcher-vs-poll wakeups —
exactly the datapath an offload-style deployment no longer steps
through (PAPERS: *Reliable Replication Protocols on SmartNICs*).

Deliberately zero-dependency and flat (stdlib only, no JAX, no numpy):
the layer must be importable and near-free in every process that
touches the session stack, including the stripped CI image
(PAPERS: *Simplicity Scales*).

Two halves:

* :mod:`.metrics` — Counters / Gauges / Histograms in a process-global
  registry behind ONE hoisted enable gate (``OBS.on``): the disabled
  path at an instrumentation site is a single attribute load, the same
  trick as ``_fastpath_gate``.
* :mod:`.events` — a bounded-ring structured event log (monotonic ts +
  seq) with an optional fd/JSONL sink, for session *lifecycle*:
  connect, checkpoint, resume, backoff, replay, stall, truncation,
  ProtocolError.

The fault injector (:mod:`..session.faults`) is the layer's
correctness oracle: it emits ground-truth ``fault.*`` events for every
fault it injects, and the conformance sweep
(tests/test_obs_conformance.py) asserts the session layers' telemetry
agrees — chaos and telemetry must tell the same story.

Catalog, schema, overhead budget: OBSERVABILITY.md.
"""

from __future__ import annotations

from .events import EVENTS, EventLog, emit
from .metrics import (
    OBS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    disable,
    enable,
    gauge,
    histogram,
    snapshot,
)

__all__ = [
    "OBS",
    "REGISTRY",
    "EVENTS",
    "EventLog",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "emit",
    "enable",
    "disable",
]
