"""Offline telemetry CLI: causal timelines, trace export, bundle dumps.

    python -m dat_replication_protocol_tpu.obs timeline SENDER.jsonl RECEIVER.jsonl [PEER.jsonl ...]
    python -m dat_replication_protocol_tpu.obs export-trace LOG.jsonl|BUNDLE_DIR [-o OUT]
    python -m dat_replication_protocol_tpu.obs dump BUNDLE_DIR [--json]
    python -m dat_replication_protocol_tpu.obs loopdoctor LOG.jsonl|BUNDLE_DIR [--threshold S] [--json]
    python -m dat_replication_protocol_tpu.obs meshdoctor LOG... [--json]
    python -m dat_replication_protocol_tpu.obs costdoctor LOG... [--max-overhead R] [--json]
    python -m dat_replication_protocol_tpu.obs perf-check BENCH.json [--budgets PATH] [--host-only]
    python -m dat_replication_protocol_tpu.obs fleet TARGET... [--check SLO.json | --watch]

``timeline`` merges N peers' JSONL event/span logs (written by
``obs.tracing.attach_jsonl_sink`` / ``EVENTS.attach_sink``) into ONE
causally-ordered timeline keyed on wire offset — the byte offset every
frame starts at is the same number on both sides of the wire, so a
receiver record at offset X provably happened after the sender record
at X, with no clock synchronization at all.  With exactly two logs the
classic sender/receiver audit runs (unchanged output); with more, each
log's emit/dispatch streams are audited independently and dispatch
streams are paired with their emitting peer — by exact ``link`` label
when frame records carry one, else by best coverage match (one emitter
may serve many dispatchers: the fan-out shape) — the offline mirror of
the fleet aggregator's live join.  While merging it audits the frame
streams and flags:

* ``gap``        — a hole in a peer's frame coverage (bytes never
                   emitted / never dispatched);
* ``reorder``    — frame offsets moving backwards in a peer's own
                   emission order;
* ``duplicate``  — overlapping frame coverage on one peer (the
                   duplicate-delivery class resume must never produce);
* ``peer-divergence`` — the two peers' total frame coverage disagrees.

Exit code is 1 when any flag fires, 0 on a clean merge — a clean
resumed session (drop, reconnect, replay) flags NOTHING: that is the
timeline's conformance contract (tests/test_obs_timeline.py).

``export-trace`` converts a JSONL log (or a flight bundle directory)
into Chrome trace-event JSON, loadable in Perfetto.  ``dump`` renders
a flight-recorder bundle (see obs/flight.py) for humans or, with
``--json``, for tools.

``loopdoctor`` (ISSUE 18) ingests the same JSONL logs / flight
bundles and reads the edge flight deck's ``edge.turn`` spans: it
audits that recorded turns tile the loop's wall time exactly, totals
per-phase seconds, finds stall turns (non-poll work past the
threshold), and attributes their time to sessions from the profiler's
top-K captures.  Exit 1 on any flag — a stall whose heaviest session
the doctor can NAME (``stall-dominance``), a stall with no capture
(``unattributed-stall``), or a tiling break (``tile-gap`` /
``tile-overlap``).  A clean run reports final lag exactly 0 and
flags nothing.

``meshdoctor`` (ISSUE 19) is the loopdoctor's mesh sibling: it ingests
N replicas' JSONL logs / flight bundles and reads the convergence
plane's records (``gossip.mesh`` / ``gossip.hold`` /
``gossip.exchange`` spans / ``gossip.frontier``), reconstructs the
per-record propagation tree — which exchange first delivered each
digest to each replica — and attributes slow convergence to the exact
link, round, and quarantine.  Exit 1 on any flag: ``orphaned-digest``
(a delivered digest its sender never held), ``stalled-link`` (>= 2
distinct transport-failure rounds on one pair with no interleaved
success — the partition signature), ``asymmetric-link`` (one direction
persistently failing while the reverse succeeds), or
``rounds-bound-exceeded`` (convergence past the ``gossip.mesh``
record's ``rounds_bound()`` budget).  A clean converged log flags
nothing and reports final divergence exactly 0.

``costdoctor`` (ISSUE 20) audits the wire cost plane offline: it
rebuilds the per-stream byte ledger from the same frame instants the
timeline merges (``encoder.frame`` / ``decoder.frame`` /
``decoder.frame.run``), splits framing from payload by inverting the
framing arithmetic (exact for single frames, a per-header lower bound
for native dispatch runs), and audits coverage tiling, overhead, and
the amplification series from any ``--stats-fd`` records in the same
logs.  Exit 1 on any flag: ``unattributed-bytes`` (coverage holes,
double-attributed overlaps, or a nonzero live-ledger residual — wire
no class accounts for), ``overhead-anomaly`` (framing overhead past
``--max-overhead`` even at its minimum possible value, or goodput
under ``--min-goodput``), or ``amplification-regression`` (a fan-out
link's delivered/source ratio collapsing from its peak — peers not
draining the published stream).  A clean lit log flags nothing.

``perf-check`` is the perf-budget regression gate (ISSUE 5): it
compares one bench artifact (the one JSON line ``bench.py`` prints)
against the checked-in per-metric budgets
(``artifacts/perf_budgets.json`` by default; see :mod:`.perf` for the
file format) and exits 1 on any regression — the bench trajectory as
an enforced contract instead of an unread JSON trail.  ``--host-only``
evaluates only the host-group configs (CPU-safe, what tier-1 runs).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from .flight import read_bundle
from .tracing import export_chrome_trace

# span names that tag one frame (or one native-dispatch run of frames)
# with its wire start offset; "action" distinguishes the two roles
FRAME_SPANS = {
    "encoder.frame": "emit",
    "decoder.frame": "dispatch",
    "decoder.frame.run": "dispatch",
}

# event fields that carry a wire offset (used to slot non-frame records
# onto the offset axis)
_OFFSET_FIELDS = ("offset", "wire_offset", "at")


def _load_jsonl(path: str) -> list[dict]:
    records: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                records.append(json.loads(ln))
            except json.JSONDecodeError:
                # a torn FINAL line is expected when a sink latched
                # dead mid-record; keep it visible but unkeyed
                records.append({"_unparsed": ln})
    return records


def _frames(records: list[dict]) -> list[dict]:
    """Extract frame records (offset, wire_len, frames, kind) in file
    order — the per-peer audit unit."""
    out = []
    for i, r in enumerate(records):
        name = r.get("span")
        action = FRAME_SPANS.get(name)
        if action is None:
            continue
        f = r.get("fields") or {}
        off, wl = f.get("offset"), f.get("wire_len")
        if off is None or wl is None:
            continue
        out.append({
            "i": i, "seq": r.get("seq", i), "offset": off, "wire_len": wl,
            "frames": f.get("frames", 1), "kind": f.get("kind"),
            "action": action, "name": name, "link": f.get("link"),
        })
    return out


def _stream_link(frames: list[dict]):
    """The session label a frame stream carries (the first record's
    ``link`` field), or None — the N-log pairing key."""
    for fr in frames:
        if fr.get("link"):
            return fr["link"]
    return None


def _audit_role(role: str, frames: list[dict]) -> list[dict]:
    """Flag gaps / reorders / duplicates in ONE direction of one peer's
    frame stream.  Callers split a file's records by action first: a
    duplex peer (the sidecar mirrors its request-side dispatch tags AND
    its reply-side emission tags into one log) carries two independent
    wire streams whose offsets both start at 0 — auditing them as one
    stream would flag a clean session."""
    flags: list[dict] = []
    prev_off: Optional[int] = None
    for fr in frames:  # emission/dispatch order = file order
        if prev_off is not None and fr["offset"] < prev_off:
            flags.append({"flag": "reorder", "role": role,
                          "offset": fr["offset"],
                          "detail": f"frame at offset {fr['offset']} "
                                    f"recorded after offset {prev_off}"})
        prev_off = fr["offset"]
    end: Optional[int] = None
    for fr in sorted(frames, key=lambda fr: (fr["offset"], fr["i"])):
        if end is not None:
            if fr["offset"] < end:
                flags.append({"flag": "duplicate", "role": role,
                              "offset": fr["offset"],
                              "detail": f"frame coverage at offset "
                                        f"{fr['offset']} overlaps bytes "
                                        f"already covered up to {end}"})
            elif fr["offset"] > end:
                flags.append({"flag": "gap", "role": role, "offset": end,
                              "missing": fr["offset"] - end,
                              "detail": f"{fr['offset'] - end} byte(s) of "
                                        f"frame coverage missing at "
                                        f"offset {end}"})
        end = fr["offset"] + fr["wire_len"] if end is None else max(
            end, fr["offset"] + fr["wire_len"])
    return flags


def _coverage(frames: list[dict]) -> tuple[int, int]:
    """(covered bytes, end offset) of a peer's frame stream."""
    total = sum(fr["wire_len"] for fr in frames)
    endo = max((fr["offset"] + fr["wire_len"] for fr in frames), default=0)
    return total, endo


def _record_offset(rec: dict) -> Optional[int]:
    f = rec.get("fields") or {}
    for k in _OFFSET_FIELDS:
        v = f.get(k)
        if isinstance(v, (int, float)):
            return int(v)
    return None


def _merge_timeline(sender: list[dict], receiver: list[dict]) -> list[dict]:
    """Two-peer merge (the classic shape): delegates to the N-peer
    merge with the canonical sender/receiver roles."""
    return _merge_timeline_n([("sender", sender), ("receiver", receiver)])


def _merge_timeline_n(peers: list[tuple[str, list[dict]]]) -> list[dict]:
    """One causally-ordered merged timeline over N peers: primary key
    is the wire offset (earlier-listed peers first at equal offsets —
    CLI order puts emitters before their dispatchers, emission causes
    dispatch); records without an offset of their own inherit the last
    offset seen in their file, preserving their local order."""
    rows: list[dict] = []
    for rank, (role, records) in enumerate(peers):
        last = 0
        for i, r in enumerate(records):
            off = _record_offset(r)
            keyed = off is not None
            if off is None:
                off = last
            else:
                last = off
            rows.append({
                "offset": off, "role": role, "i": i, "keyed": keyed,
                "name": r.get("event") or r.get("span") or "?",
                "kind": "event" if "event" in r else (
                    "span" if "span" in r else "?"),
                "fields": r.get("fields") or {},
                "ts": r.get("ts"),
                "rank": rank,
            })
    rows.sort(key=lambda w: (w["offset"], w["rank"], w["i"]))
    return rows


def _timeline_n(paths: list[str], json_out: bool) -> int:
    """The N-log merge (>= 3 peers): audit every file's emit/dispatch
    streams independently, pair each dispatch stream with its emitting
    peer (exact ``link`` label first, best coverage match as fallback —
    one emitter may serve many dispatchers, the fan-out shape), flag
    per-pair divergence, and merge everything onto the one wire-offset
    axis.  The offline mirror of the fleet aggregator's live join."""
    names: list[str] = []
    for p in paths:
        base = os.path.basename(p)
        # duplicate basenames must stay distinguishable in roles
        names.append(base if base not in names else p)
    files = [(name, _load_jsonl(p)) for name, p in zip(names, paths)]
    flags: list[dict] = []
    streams = []
    for name, records in files:
        by = {a: [f for f in _frames(records) if f["action"] == a]
              for a in ("emit", "dispatch")}
        for action, frames in by.items():
            flags.extend(_audit_role(f"{name}:{action}", frames))
        streams.append({"name": name, "records": records, "by": by})
    emitters = [s for s in streams if s["by"]["emit"]]
    links: list[dict] = []
    for s in streams:
        disp = s["by"]["dispatch"]
        if not disp:
            continue
        cands = [e for e in emitters if e is not s]
        if not cands:
            flags.append({
                "flag": "peer-divergence", "role": f"{s['name']}:dispatch",
                "offset": 0,
                "detail": f"{s['name']} dispatched frames but no other "
                          f"peer emitted any — unpaired wire"})
            continue
        label = _stream_link(disp)
        if label is not None:
            labeled = [e for e in cands
                       if _stream_link(e["by"]["emit"]) == label]
            if labeled:
                cands = labeled
        d_cov, d_end = _coverage(disp)
        emitter = min(cands, key=lambda e: (
            abs(_coverage(e["by"]["emit"])[1] - d_end)
            + abs(_coverage(e["by"]["emit"])[0] - d_cov)))
        e_cov, e_end = _coverage(emitter["by"]["emit"])
        link = label or f"{emitter['name']}->{s['name']}"
        links.append({
            "link": link, "emitter": emitter["name"],
            "dispatcher": s["name"],
            "emit_covered": e_cov, "emit_end": e_end,
            "dispatch_covered": d_cov, "dispatch_end": d_end,
        })
        if (e_cov, e_end) != (d_cov, d_end):
            flags.append({
                "flag": "peer-divergence", "role": link,
                "offset": min(e_end, d_end),
                "detail": f"link {link}: emitter {emitter['name']} "
                          f"covered {e_cov} byte(s) ending at {e_end}, "
                          f"dispatcher {s['name']} {d_cov} ending at "
                          f"{d_end}"})
    rows = _merge_timeline_n([(s["name"], s["records"]) for s in streams])
    peers = {s["name"]: {
        "frames": len(s["by"]["emit"]) + len(s["by"]["dispatch"]),
        "emit": list(_coverage(s["by"]["emit"])),
        "dispatch": list(_coverage(s["by"]["dispatch"])),
    } for s in streams}
    if json_out:
        print(json.dumps({"flags": flags, "peers": peers, "links": links,
                          "timeline": rows}))
    else:
        for w in rows:
            mark = "@" if w["keyed"] else "~"
            extra = ""
            if w["fields"]:
                extra = " " + " ".join(
                    f"{k}={v}" for k, v in sorted(w["fields"].items()))
            print(f"{mark}{w['offset']:<10} {w['role']:<16} "
                  f"{w['name']}{extra}")
        for name, rec in peers.items():
            print(f"-- {name}: {rec['frames']} frame record(s), "
                  f"emit {rec['emit'][0]}B/end {rec['emit'][1]}, "
                  f"dispatch {rec['dispatch'][0]}B/end "
                  f"{rec['dispatch'][1]}")
        for ln in links:
            print(f"-- link {ln['link']}: {ln['emitter']} -> "
                  f"{ln['dispatcher']}, {ln['emit_covered']} -> "
                  f"{ln['dispatch_covered']} byte(s)")
        if flags:
            for fl in flags:
                print(f"FLAG {fl['flag']} [{fl['role']}] @{fl['offset']}: "
                      f"{fl['detail']}")
        else:
            print("-- clean: no gaps, reorders, or duplicate deliveries")
    return 1 if flags else 0


def cmd_timeline(args) -> int:
    if args.peers:
        return _timeline_n([args.sender, args.receiver, *args.peers],
                           args.json)
    sender = _load_jsonl(args.sender)
    receiver = _load_jsonl(args.receiver)
    # split each peer's frames by direction: emissions and dispatches
    # are separate wire streams (a duplex peer logs both)
    s_by = {a: [f for f in _frames(sender) if f["action"] == a]
            for a in ("emit", "dispatch")}
    r_by = {a: [f for f in _frames(receiver) if f["action"] == a]
            for a in ("emit", "dispatch")}
    flags: list[dict] = []
    for role, by in (("sender", s_by), ("receiver", r_by)):
        for action, frames in by.items():
            flags.extend(_audit_role(f"{role}:{action}", frames))
    # cross-peer coverage: one check per wire direction, each side of
    # the pair present — forward (sender emits, receiver dispatches)
    # and, for duplex logs, reverse (receiver emits, sender dispatches)
    for label, a, b in (("forward", s_by["emit"], r_by["dispatch"]),
                        ("reverse", r_by["emit"], s_by["dispatch"])):
        if not (a and b):
            continue
        (a_cov, a_end), (b_cov, b_end) = _coverage(a), _coverage(b)
        if a_cov != b_cov or a_end != b_end:
            flags.append({
                "flag": "peer-divergence", "role": label,
                "offset": min(a_end, b_end),
                "detail": f"{label} wire: emitter covered {a_cov} byte(s) "
                          f"ending at {a_end}, dispatcher {b_cov} ending "
                          f"at {b_end}",
            })
    sf = s_by["emit"] + s_by["dispatch"]
    rf = r_by["emit"] + r_by["dispatch"]
    (s_cov, s_end), (r_cov, r_end) = _coverage(sf), _coverage(rf)
    rows = _merge_timeline(sender, receiver)
    if args.json:
        print(json.dumps({
            "flags": flags,
            "sender": {"frames": len(sf), "covered": s_cov, "end": s_end},
            "receiver": {"frames": len(rf), "covered": r_cov, "end": r_end},
            "timeline": rows,
        }))
    else:
        for w in rows:
            mark = "@" if w["keyed"] else "~"
            extra = ""
            if w["fields"]:
                extra = " " + " ".join(
                    f"{k}={v}" for k, v in sorted(w["fields"].items()))
            print(f"{mark}{w['offset']:<10} {w['role']:<8} {w['name']}{extra}")
        print(f"-- sender: {len(sf)} frame record(s), {s_cov} byte(s) "
              f"covered, end {s_end}")
        print(f"-- receiver: {len(rf)} frame record(s), {r_cov} byte(s) "
              f"covered, end {r_end}")
        if flags:
            for fl in flags:
                print(f"FLAG {fl['flag']} [{fl['role']}] @{fl['offset']}: "
                      f"{fl['detail']}")
        else:
            print("-- clean: no gaps, reorders, or duplicate deliveries")
    return 1 if flags else 0


def cmd_export_trace(args) -> int:
    if os.path.isdir(args.log):
        bundle = read_bundle(args.log)
        spans, events = bundle["spans"], bundle["events"]
        default_out = os.path.join(args.log, "trace.json")
    else:
        records = _load_jsonl(args.log)
        spans = [r for r in records if "span" in r]
        events = [r for r in records if "event" in r]
        default_out = args.log + ".trace.json"
    out = export_chrome_trace(args.out or default_out, spans, events)
    with open(out, encoding="utf-8") as f:
        n = len(json.load(f)["traceEvents"])
    print(f"{out}: {n} trace event(s)")
    return 0


def cmd_dump(args) -> int:
    bundle = read_bundle(args.bundle)
    if args.json:
        print(json.dumps(bundle))
        return 0
    man = bundle["manifest"]
    print(f"bundle: {bundle['path']}")
    print(f"reason: {man.get('reason')}  pid: {man.get('pid')}  "
          f"ts: {man.get('ts')}")
    err = man.get("error")
    if err:
        print(f"error: {err.get('type')}: {err.get('message')}")
        print(f"  coordinates: frame={err.get('frame')} "
              f"offset={err.get('offset')} cause={err.get('cause')}")
    ckpt = man.get("checkpoint")
    if ckpt:
        print(f"checkpoint: {ckpt}")
    extra = man.get("extra")
    if extra:
        # e.g. the backend-init watchdog's stuck stage + stage timeline
        print(f"extra: {extra}")
    for plan in man.get("fault_plans", []):
        active = {k: v for k, v in plan.items()
                  if v not in (None, 0, 0.0) or k == "seed"}
        print(f"fault plan: {active}")
    faults = [e for e in bundle["events"]
              if str(e.get("event", "")).startswith("fault.")]
    for e in faults:
        print(f"injected: {e['event']} {e.get('fields')}")
    print(f"events: {len(bundle['events'])} record(s) "
          f"(dropped {man.get('events_dropped')}), "
          f"spans: {len(bundle['spans'])} record(s) "
          f"(dropped {man.get('spans_dropped')})")
    counters = bundle["metrics"].get("counters", {})
    nonzero = {k: v for k, v in sorted(counters.items()) if v}
    print(f"counters (nonzero): {nonzero}")
    return 0


# -- loopdoctor (ISSUE 18): offline event-loop stall attribution -------------

# the edge.turn span's phase field names, in the loop's phase order
_TURN_PHASES = ("poll_wait", "accept", "read", "hub_drain", "tx",
                "overload_ladder")

# tiling tolerance: edge.turn spans are change-only but EXACT — each
# span's ts is the previous recorded span's end, float-identical.  The
# epsilon only absorbs JSON round-tripping of the floats.
_TILE_TOL = 1e-6


def _loopdoctor_analyze(spans: list[dict],
                        threshold: Optional[float] = None) -> dict:
    """Attribute loop stall time to phases and sessions from
    ``edge.turn`` spans (the loopprof capture).  Returns
    ``{"loops": {name: report}, "flags": [...]}``; flags:

    * ``tile-gap`` / ``tile-overlap`` — consecutive turn spans do not
      tile the loop's wall time (a profiler bug, not a workload one);
    * ``stall-dominance`` — one session holds more than the stall
      threshold of work inside overrun turns: the doctor names the
      session AND the phase the time went to;
    * ``unattributed-stall`` — an overrun turn carries no session
      capture (the profiler should attach top-K on every lagging turn).

    The stall threshold defaults to ``max(4 * tick, 0.03)`` per loop
    (from the span's own ``tick`` field) — a turn is a stall when its
    non-poll work alone spans multiple ticks."""
    by_loop: dict = {}
    for r in spans:
        if r.get("span") != "edge.turn":
            continue
        f = r.get("fields") or {}
        by_loop.setdefault(str(f.get("loop", "?")), []).append((r, f))
    flags: list[dict] = []
    loops: dict = {}
    for lname, recs in sorted(by_loop.items()):
        recs.sort(key=lambda rf: float(rf[0].get("ts") or 0.0))
        tick = 0.05
        for _r, f in recs:
            if isinstance(f.get("tick"), (int, float)) and f["tick"] > 0:
                tick = float(f["tick"])
                break
        thr = (float(threshold) if threshold is not None
               else max(4.0 * tick, 0.03))
        phase_s = {name: 0.0 for name in _TURN_PHASES}
        sessions: dict = {}
        stall_sessions: dict = {}
        prev_end: Optional[float] = None
        turns = 0
        lag_max = 0.0
        stall_s = 0.0
        stall_turns = 0
        for r, f in recs:
            ts = float(r.get("ts") or 0.0)
            dur = float(r.get("dur") or 0.0)
            if prev_end is not None:
                delta = ts - prev_end
                if delta > _TILE_TOL:
                    flags.append({
                        "flag": "tile-gap", "loop": lname, "ts": ts,
                        "detail": f"{delta:.6f}s of loop wall time "
                                  f"missing before the span at "
                                  f"ts={ts:.6f}"})
                elif delta < -_TILE_TOL:
                    flags.append({
                        "flag": "tile-overlap", "loop": lname, "ts": ts,
                        "detail": f"span at ts={ts:.6f} overlaps the "
                                  f"previous turn by {-delta:.6f}s"})
            prev_end = ts + dur
            turns += int(f.get("turns") or 1)
            for name in _TURN_PHASES:
                v = f.get(name + "_s")
                if isinstance(v, (int, float)):
                    phase_s[name] += float(v)
            work = float(f.get("work_s") or 0.0)
            lag = float(f.get("lag_s") or 0.0)
            lag_max = max(lag_max, lag)
            top = f.get("top") or []
            for ent in top:
                key = str(ent.get("session", "?"))
                s = sessions.setdefault(
                    key, {"seconds": 0.0, "bytes": 0, "phases": {}})
                sec = float(ent.get("seconds") or 0.0)
                s["seconds"] += sec
                s["bytes"] += int(ent.get("bytes") or 0)
                ph = str(ent.get("phase", "?"))
                s["phases"][ph] = s["phases"].get(ph, 0.0) + sec
            if work > thr:
                stall_s += work
                stall_turns += 1
                if not top:
                    flags.append({
                        "flag": "unattributed-stall", "loop": lname,
                        "ts": ts,
                        "detail": f"turn work {work:.3f}s exceeds the "
                                  f"{thr:.3f}s stall threshold with no "
                                  f"session capture"})
                for ent in top:
                    key = str(ent.get("session", "?"))
                    s = stall_sessions.setdefault(
                        key, {"seconds": 0.0, "bytes": 0, "phases": {}})
                    sec = float(ent.get("seconds") or 0.0)
                    s["seconds"] += sec
                    s["bytes"] += int(ent.get("bytes") or 0)
                    ph = str(ent.get("phase", "?"))
                    s["phases"][ph] = s["phases"].get(ph, 0.0) + sec
        for key, s in sorted(stall_sessions.items(),
                             key=lambda kv: kv[1]["seconds"],
                             reverse=True):
            if s["seconds"] <= thr:
                continue
            phase = max(s["phases"].items(),
                        key=lambda kv: kv[1])[0] if s["phases"] else "?"
            flags.append({
                "flag": "stall-dominance", "loop": lname,
                "session": key, "phase": phase,
                "seconds": round(s["seconds"], 6),
                "detail": f"session {key} holds "
                          f"{s['seconds']:.3f}s of stall work, "
                          f"dominated by the {phase} phase"})
        final_lag = float(recs[-1][1].get("lag_s") or 0.0) if recs \
            else 0.0
        wall = (prev_end - float(recs[0][0].get("ts") or 0.0)) \
            if recs else 0.0
        loops[lname] = {
            "spans": len(recs),
            "turns": turns,
            "tick": tick,
            "threshold_s": round(thr, 6),
            "wall_s": round(wall, 6),
            "phase_s": {k: round(v, 6) for k, v in phase_s.items()},
            "final_lag_s": final_lag,
            "lag_max_s": round(lag_max, 6),
            "stall_s": round(stall_s, 6),
            "stall_turns": stall_turns,
            "sessions": {k: {"seconds": round(v["seconds"], 6),
                             "bytes": v["bytes"],
                             "phases": {p: round(sv, 6) for p, sv
                                        in v["phases"].items()}}
                         for k, v in sessions.items()},
        }
    return {"loops": loops, "flags": flags}


def cmd_loopdoctor(args) -> int:
    if os.path.isdir(args.log):
        bundle = read_bundle(args.log)
        spans = bundle["spans"]
    else:
        spans = [r for r in _load_jsonl(args.log) if "span" in r]
    report = _loopdoctor_analyze(spans, threshold=args.threshold)
    flags = report["flags"]
    if args.json:
        print(json.dumps(report))
        return 1 if flags else 0
    if not report["loops"]:
        print("no edge.turn spans found: the loop either never ran lit "
              "(obs gate off) or the log predates the flight deck")
        return 0
    for lname, rec in sorted(report["loops"].items()):
        print(f"loop {lname}: {rec['turns']} turn(s) in "
              f"{rec['spans']} span(s), wall {rec['wall_s']:.3f}s, "
              f"tick {rec['tick']}s")
        busy = {k: v for k, v in rec["phase_s"].items() if v}
        print(f"  phases: " + (", ".join(
            f"{k}={v:.3f}s" for k, v in sorted(
                busy.items(), key=lambda kv: kv[1], reverse=True))
            or "(idle)"))
        print(f"  lag: final {rec['final_lag_s']:.3f}s, "
              f"max {rec['lag_max_s']:.3f}s; stalls: "
              f"{rec['stall_turns']} turn(s), {rec['stall_s']:.3f}s "
              f"(threshold {rec['threshold_s']:.3f}s)")
        heavy = sorted(rec["sessions"].items(),
                       key=lambda kv: kv[1]["seconds"], reverse=True)[:5]
        for key, s in heavy:
            print(f"  session {key}: {s['seconds']:.3f}s, "
                  f"{s['bytes']} byte(s)")
    if flags:
        for fl in flags:
            where = fl.get("session") or fl.get("ts", "-")
            print(f"FLAG {fl['flag']} [{fl['loop']}] {where}: "
                  f"{fl['detail']}")
    else:
        print("-- clean: spans tile, no stall dominance")
    return 1 if flags else 0


# -- meshdoctor (ISSUE 19): offline gossip-convergence attribution -----------

# an exchange direction that moved (or proved empty) the diff vs one
# that failed: the vocabulary obs/propagation.py records
_X_OK = ("converged", "progress")
_X_FAIL = ("transport",)


def _mesh_records(paths: list[str]) -> tuple[list[dict], list[dict]]:
    """Events + spans from N JSONL logs / flight bundles, merged."""
    events: list[dict] = []
    spans: list[dict] = []
    for path in paths:
        if os.path.isdir(path):
            bundle = read_bundle(path)
            events.extend(bundle["events"])
            spans.extend(bundle["spans"])
        else:
            for r in _load_jsonl(path):
                if "span" in r:
                    spans.append(r)
                elif "event" in r:
                    events.append(r)
    return events, spans


def _dedupe_exchanges(spans: list[dict]) -> list[dict]:
    """One record per exchange: the in-process engine records BOTH
    directions of every exchange (initiator + responder views of the
    same peel), keyed here by (round, dialer, dialee) with the
    initiator's view preferred — its ``delivered``/``delivered_peer``
    orientation is the canonical one.  One-sided records (live dials,
    refusals, dead peers) pass through unchanged."""
    best: dict = {}
    order: list = []
    for r in spans:
        if r.get("span") != "gossip.exchange":
            continue
        f = r.get("fields") or {}
        role = f.get("role")
        me, peer = str(f.get("replica")), str(f.get("peer"))
        dialer, dialee = (me, peer) if role == "initiator" else (peer, me)
        key = (int(f.get("round") or 0), dialer, dialee)
        cur = best.get(key)
        if cur is None:
            best[key] = r
            order.append(key)
        elif role == "initiator" and \
                (cur.get("fields") or {}).get("role") != "initiator":
            best[key] = r
    out = []
    for key in order:
        r = best[key]
        f = dict(r.get("fields") or {})
        rnd, dialer, dialee = key
        if f.get("role") == "initiator":
            deliv_dialer = list(f.get("delivered") or ())
            deliv_dialee = list(f.get("delivered_peer") or ())
        else:
            deliv_dialer = list(f.get("delivered_peer") or ())
            deliv_dialee = list(f.get("delivered") or ())
        out.append({
            "round": rnd, "dialer": dialer, "dialee": dialee,
            "outcome": f.get("outcome"), "error": f.get("error"),
            "seconds": f.get("seconds"), "diff": f.get("diff"),
            "wire_bytes": f.get("wire_bytes"),
            "delivered_dialer": deliv_dialer,
            "delivered_dialee": deliv_dialee,
            "ts": float(r.get("ts") or 0.0),
        })
    out.sort(key=lambda x: (x["round"], x["ts"]))
    return out


def _link_runs(rounds_events: list[tuple[int, bool]]) -> list[list[int]]:
    """Maximal runs of DISTINCT failure rounds uninterrupted by a
    success, over (round, ok) observations sorted by round.  Rounds
    with no observation do not break a run — a partitioned pair is
    only sampled some rounds, and the stall spans the gap."""
    runs: list[list[int]] = []
    cur: list[int] = []
    for rnd, ok in rounds_events:
        if ok:
            if cur:
                runs.append(cur)
            cur = []
        elif not cur or cur[-1] != rnd:
            cur.append(rnd)
    if cur:
        runs.append(cur)
    return runs


def _meshdoctor_analyze(events: list[dict], spans: list[dict]) -> dict:
    """Reconstruct the per-record propagation tree and attribute
    convergence (or its failure) to exact links/rounds/quarantines.
    Flags:

    * ``orphaned-digest`` — an exchange delivered a digest its sender
      was never recorded holding (provenance break: a hold record is
      missing, or the mesh shipped content from nowhere);
    * ``stalled-link`` — an undirected pair failed transport in >= 2
      DISTINCT rounds with no successful exchange in between (the
      partition signature: one-shot chaos faults fire in at most one
      round per link, so a repeat offender is a cut, not a bad cable);
    * ``asymmetric-link`` — one DIRECTION failed >= 2 distinct rounds
      while the reverse direction succeeded inside the same span (a
      half-open link: NAT, a one-way filter, an asymmetric route);
    * ``rounds-bound-exceeded`` — the mesh converged after the
      ``gossip.mesh`` record's ``rounds_bound()`` budget, or never
      converged within it.

    A clean converged log flags nothing and reports final divergence
    exactly 0 (``distinct_frontiers == 1``)."""
    mesh = None
    for r in events:
        if r.get("event") == "gossip.mesh":
            mesh = dict(r.get("fields") or {})
    holds = [r for r in events if r.get("event") == "gossip.hold"]
    frontiers = [r for r in events if r.get("event") == "gossip.frontier"]
    quarantines = [dict((r.get("fields") or {}), ts=r.get("ts"))
                   for r in events
                   if r.get("event") == "gossip.quarantine"]
    exchanges = _dedupe_exchanges(spans)
    flags: list[dict] = []

    # -- the propagation tree: first delivery of each digest ------------------
    holding: dict[str, set] = {}
    tree: dict[str, dict] = {}
    check_provenance = bool(holds)

    def acquire(replica: str, digest: str, rnd: int, via: str) -> None:
        holding.setdefault(replica, set()).add(digest)
        tree.setdefault(digest, {}).setdefault(
            replica, {"round": rnd, "via": via})

    items: list[tuple] = []
    for r in holds:
        f = r.get("fields") or {}
        items.append((int(f.get("round") or 0), float(r.get("ts") or 0.0),
                      0, ("hold", f)))
    for x in exchanges:
        items.append((x["round"], x["ts"], 1, ("exchange", x)))
    items.sort(key=lambda it: it[:3])
    for rnd, _ts, _k, (kind, payload) in items:
        if kind == "hold":
            rep = str(payload.get("replica"))
            for d in payload.get("digests") or ():
                acquire(rep, str(d), rnd, "hold")
            continue
        x = payload
        for receiver, sender, digests in (
                (x["dialer"], x["dialee"], x["delivered_dialer"]),
                (x["dialee"], x["dialer"], x["delivered_dialee"])):
            for d in digests:
                d = str(d)
                if check_provenance and sender in holding \
                        and d not in holding[sender]:
                    flags.append({
                        "flag": "orphaned-digest", "digest": d,
                        "link": f"{sender}->{receiver}", "round": rnd,
                        "detail": f"exchange at round {rnd} delivered "
                                  f"digest {d} to {receiver}, but sender "
                                  f"{sender} was never recorded holding "
                                  f"it (provenance break)"})
                acquire(receiver, d, rnd,
                        f"exchange:{sender}->{receiver}")

    # -- link health: stalls and asymmetry ------------------------------------
    by_dir: dict[tuple, list] = {}
    for x in exchanges:
        if x["outcome"] in _X_OK or x["outcome"] in _X_FAIL:
            by_dir.setdefault((x["dialer"], x["dialee"]), []).append(
                (x["round"], x["outcome"] in _X_OK))
    pairs: dict[tuple, list] = {}
    for (a, b), obs in by_dir.items():
        pairs.setdefault(tuple(sorted((a, b))), []).extend(obs)
    for pair, obs in sorted(pairs.items()):
        obs.sort()
        for run in _link_runs(obs):
            if len(run) >= 2:
                flags.append({
                    "flag": "stalled-link",
                    "link": f"{pair[0]}<->{pair[1]}", "rounds": run,
                    "detail": f"link {pair[0]}<->{pair[1]} failed "
                              f"transport in {len(run)} distinct "
                              f"round(s) {run[0]}..{run[-1]} with no "
                              f"successful exchange in between (the "
                              f"partition signature: one-shot chaos "
                              f"faults fire at most once per link)"})
    for (a, b), obs in sorted(by_dir.items()):
        obs.sort()
        rev = sorted(by_dir.get((b, a), ()))
        for run in _link_runs(obs):
            if len(run) < 2:
                continue
            rev_ok = [rnd for rnd, ok in rev
                      if ok and run[0] <= rnd <= run[-1]]
            if rev_ok:
                flags.append({
                    "flag": "asymmetric-link", "link": f"{a}->{b}",
                    "rounds": run,
                    "detail": f"direction {a}->{b} failed transport in "
                              f"{len(run)} distinct round(s) "
                              f"{run[0]}..{run[-1]} while {b}->{a} "
                              f"succeeded in round(s) {rev_ok} — a "
                              f"half-open link, not a partition"})

    # -- convergence vs the bound ---------------------------------------------
    final: dict[str, dict] = {}
    for r in frontiers:
        f = r.get("fields") or {}
        rep = str(f.get("replica"))
        cur = final.get(rep)
        if cur is None or int(f.get("round") or 0) >= cur["round"]:
            final[rep] = {"round": int(f.get("round") or 0),
                          "digest": f.get("digest"),
                          "records": f.get("records")}
    digests = {v["digest"] for v in final.values()}
    converged = bool(final) and len(digests) == 1
    convergence_round = (max(v["round"] for v in final.values())
                         if converged else None)
    bound = int(mesh["bound"]) if mesh and "bound" in mesh else None
    last_round = max([x["round"] for x in exchanges]
                     + [v["round"] for v in final.values()] + [0])
    if bound is not None:
        if converged and convergence_round > bound:
            flags.append({
                "flag": "rounds-bound-exceeded",
                "round": convergence_round,
                "detail": f"mesh converged at round {convergence_round}, "
                          f"past the rounds_bound() budget of {bound}"})
        elif not converged and final and last_round >= bound:
            flags.append({
                "flag": "rounds-bound-exceeded", "round": last_round,
                "detail": f"mesh never converged: {len(digests)} "
                          f"distinct frontiers at round {last_round}, "
                          f"budget {bound}"})

    # -- slow-convergence attribution -----------------------------------------
    # the digests that arrived LAST, and the exact exchange that
    # finally delivered each — the "which link, which round, which
    # record" answer the plane exists for
    last_arrivals = []
    for d, deliveries in tree.items():
        worst = max(deliveries.items(), key=lambda kv: kv[1]["round"])
        last_arrivals.append({"digest": d, "replica": worst[0],
                              "round": worst[1]["round"],
                              "via": worst[1]["via"]})
    last_arrivals.sort(key=lambda e: (-e["round"], e["digest"]))

    return {
        "mesh": mesh,
        "replicas": final,
        "converged": converged,
        "convergence_round": convergence_round,
        "distinct_frontiers": len(digests),
        "bound": bound,
        "exchanges": len(exchanges),
        "quarantines": quarantines,
        "slowest": last_arrivals[:8],
        "tree_digests": len(tree),
        "flags": flags,
    }


def cmd_meshdoctor(args) -> int:
    events, spans = _mesh_records(args.logs)
    report = _meshdoctor_analyze(events, spans)
    if args.json:
        print(json.dumps(report))
        return 1 if report["flags"] else 0
    if not report["exchanges"] and not report["replicas"]:
        print("no gossip.exchange spans or gossip.frontier events "
              "found: the mesh either never ran lit (obs gate off) or "
              "the log predates the convergence plane")
        return 0
    mesh = report["mesh"] or {}
    print(f"mesh: {mesh.get('n', '?')} replica(s), "
          f"seed {mesh.get('seed', '?')}, "
          f"bound {report['bound'] if report['bound'] is not None else '?'}"
          f" — {report['exchanges']} exchange(s), "
          f"{report['tree_digests']} digest(s) tracked")
    if report["converged"]:
        print(f"converged at round {report['convergence_round']} "
              f"(final divergence exactly 0: every frontier "
              f"byte-identical)")
    else:
        print(f"NOT converged: {report['distinct_frontiers']} distinct "
              f"frontier digest(s)")
    for rep, rec in sorted(report["replicas"].items()):
        print(f"  {rep}: round {rec['round']}, "
              f"{rec.get('records', '?')} record(s), "
              f"{(rec.get('digest') or '?')[:16]}")
    for q in report["quarantines"]:
        print(f"  quarantine: {q.get('replica')} cut {q.get('peer')} "
              f"(arm {q.get('arm')}, offset {q.get('offset')})")
    for e in report["slowest"][:4]:
        print(f"  slowest: digest {e['digest']} reached {e['replica']} "
              f"at round {e['round']} via {e['via']}")
    if report["flags"]:
        for fl in report["flags"]:
            where = fl.get("link") or fl.get("digest") or \
                fl.get("round", "-")
            print(f"FLAG {fl['flag']} [{where}]: {fl['detail']}")
    else:
        print("-- clean: provenance intact, no stalled or asymmetric "
              "links, convergence within bound")
    return 1 if report["flags"] else 0


# -- costdoctor (ISSUE 20): offline wire-cost ledger audit -------------------


def _cost_records(paths: list[str]) -> tuple[list, list]:
    """Per-origin span records + stats-fd ``wirecost`` sections from N
    JSONL logs / flight bundles.  Unlike :func:`_mesh_records` the file
    origin is kept: frame streams without an explicit ``link`` label
    are keyed by origin (one log = one peer), and the amplification
    series is read per origin in file order."""
    streams: list = []
    stats: list = []
    for path in paths:
        origin = os.path.basename(path.rstrip("/"))
        if os.path.isdir(path):
            bundle = read_bundle(path)
            streams.append((origin, bundle["spans"]))
        else:
            records = _load_jsonl(path)
            streams.append((origin, records))
            for r in records:
                if isinstance(r.get("wirecost"), dict):
                    stats.append((origin, r["wirecost"]))
    return streams, stats


def _split_framing(wire_len: int) -> int:
    """Invert the framing arithmetic: the header length a single frame
    of ``wire_len`` total bytes must carry (header_len is monotone in
    payload length, so the inversion is exact and unique)."""
    from ..wire.framing import header_len
    for hl in range(2, 11):
        p = wire_len - hl
        if p >= 0 and header_len(p) == hl:
            return hl
    return 2


def _costdoctor_analyze(streams: list, stats: list,
                        max_overhead: float,
                        min_goodput: Optional[float]) -> dict:
    """Rebuild the per-stream wire cost ledger from frame instants and
    audit it: coverage must tile (no unattributed bytes), the framing
    overhead must stay under the threshold, and the amplification
    series from stats records must not regress.  Framing is EXACT for
    single-frame records (header inversion); a native dispatch run of
    k frames contributes the 2-byte-per-header lower bound — the
    overhead flag therefore only fires when even the minimum possible
    framing breaches, never on an estimate."""
    flags: list[dict] = []
    ledgers: dict = {}
    for origin, records in streams:
        frames = _frames(records)
        by_stream: dict = {}
        for fr in frames:
            key = (fr.get("link") or origin, fr["action"])
            by_stream.setdefault(key, []).append(fr)
        for (link, action), frs in by_stream.items():
            name = f"{link}|{'tx' if action == 'emit' else 'rx'}"
            classes: dict = {}
            framing_lb = 0
            exact = True
            for fr in frs:
                c = classes.setdefault(
                    fr["kind"] or "?", {"wire": 0, "frames": 0})
                c["wire"] += int(fr["wire_len"])
                c["frames"] += int(fr["frames"])
                if int(fr["frames"]) == 1:
                    framing_lb += _split_framing(int(fr["wire_len"]))
                else:
                    framing_lb += 2 * int(fr["frames"])
                    exact = False
            total = sum(c["wire"] for c in classes.values())
            # coverage audit: frames must tile [start, end) exactly —
            # a hole is wire the ledger cannot attribute to any class
            gaps = overlaps = 0
            cur = None
            for fr in sorted(frs, key=lambda f: f["offset"]):
                off, end = fr["offset"], fr["offset"] + fr["wire_len"]
                if cur is None:
                    cur = end
                elif off > cur:
                    gaps += off - cur
                    cur = end
                else:
                    overlaps += cur - off
                    cur = max(cur, end)
            overhead = (framing_lb / total) if total else None
            ledgers[name] = {
                "classes": classes, "wire_bytes": total,
                "framing_bytes_min": framing_lb,
                "framing_exact": exact,
                "overhead_ratio": overhead,
                "goodput_fraction": (1 - overhead)
                if overhead is not None else None,
                "unattributed_bytes": gaps,
                "overlapping_bytes": overlaps,
            }
            if gaps:
                flags.append({
                    "flag": "unattributed-bytes", "link": name,
                    "detail": f"{gaps} wire byte(s) on {name} fall in "
                              "coverage holes between frame instants — "
                              "bytes no class can account for"})
            if overlaps:
                flags.append({
                    "flag": "unattributed-bytes", "link": name,
                    "detail": f"{overlaps} wire byte(s) on {name} are "
                              "attributed twice (overlapping frames): "
                              "the ledger over-counts the wire"})
            if overhead is not None and overhead > max_overhead:
                qual = "" if exact else "at least "
                flags.append({
                    "flag": "overhead-anomaly", "link": name,
                    "detail": f"framing overhead {qual}{overhead:.4f} "
                              f"on {name} exceeds {max_overhead} "
                              f"({framing_lb}/{total} byte(s))"})
            if min_goodput is not None and overhead is not None \
                    and (1 - overhead) < min_goodput:
                flags.append({
                    "flag": "overhead-anomaly", "link": name,
                    "detail": f"goodput {1 - overhead:.4f} on {name} "
                              f"below the {min_goodput} floor"})
    # amplification series per link, in stats record order: the
    # cumulative delivered/source ratio recovers after transients, so a
    # FINAL value well under the peak means peers stopped draining what
    # the source kept publishing — the under-delivery regression
    amp_series: dict = {}
    residuals: dict = {}
    for _origin, wc in stats:
        for link, view in (wc.get("amplification") or {}).items():
            a = view.get("amplification")
            if a is not None:
                amp_series.setdefault(link, []).append(float(a))
        for lname, rec in (wc.get("links") or {}).items():
            residuals[lname] = rec.get("residual_bytes")
    for link, series in sorted(amp_series.items()):
        peak, final = max(series), series[-1]
        if len(series) >= 2 and final < 0.75 * peak:
            flags.append({
                "flag": "amplification-regression", "link": link,
                "detail": f"amplification on {link} fell to "
                          f"{final:.2f}x from a {peak:.2f}x peak — "
                          "peers are not draining the published "
                          "stream"})
    for lname, rb in sorted(residuals.items()):
        # the live board's own tiling verdict, from the LAST stats
        # record: a nonzero residual at rest is unattributed wire
        if rb is not None and rb != 0:
            flags.append({
                "flag": "unattributed-bytes", "link": lname,
                "detail": f"live ledger residual {rb} byte(s) on "
                          f"{lname}: transport moved wire no class "
                          "accounts for"})
    return {"ledgers": ledgers, "amplification": amp_series,
            "residuals": residuals, "flags": flags}


def cmd_costdoctor(args) -> int:
    streams, stats = _cost_records(args.logs)
    report = _costdoctor_analyze(streams, stats,
                                 max_overhead=args.max_overhead,
                                 min_goodput=args.min_goodput)
    if args.json:
        print(json.dumps(report))
        return 1 if report["flags"] else 0
    if not report["ledgers"] and not report["residuals"] \
            and not report["amplification"]:
        print("no frame instants or wirecost sections found: the wire "
              "cost plane either never ran lit (obs gate off) or the "
              "log predates it")
        return 0
    for name, led in sorted(report["ledgers"].items()):
        ov = led["overhead_ratio"]
        qual = "" if led["framing_exact"] else ">="
        print(f"{name}: {led['wire_bytes']} wire byte(s), "
              f"overhead {qual}"
              f"{('?' if ov is None else f'{ov:.4f}')} — "
              + ", ".join(f"{cls}:{c['wire']}B/{c['frames']}f"
                          for cls, c in sorted(led["classes"].items())))
    for link, series in sorted(report["amplification"].items()):
        print(f"amplification {link}: "
              + " -> ".join(f"{a:.2f}x" for a in series[-6:]))
    if report["flags"]:
        for fl in report["flags"]:
            print(f"FLAG {fl['flag']} [{fl['link']}]: {fl['detail']}")
    else:
        print("-- clean: every wire byte attributed, overhead within "
              "bounds, amplification steady")
    return 1 if report["flags"] else 0


def cmd_perf_check(args) -> int:
    from .perf import DEFAULT_BUDGETS_PATH, run_check

    budgets = args.budgets
    if budgets is None:
        # repo-checkout default first (the file is checked in next to
        # the package), falling back to CWD-relative
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        cand = os.path.join(repo, DEFAULT_BUDGETS_PATH)
        budgets = cand if os.path.exists(cand) else DEFAULT_BUDGETS_PATH
    return run_check(args.snapshot, budgets_path=budgets,
                     host_only=args.host_only)


def cmd_fleet(args) -> int:
    from .fleet import FleetView, run_dashboard, run_fleet_check

    if args.check:
        return run_fleet_check(
            args.targets, args.check,
            polls=args.polls if args.polls is not None else 3,
            interval=args.interval)
    if args.watch:
        return run_dashboard(args.targets, interval=args.interval,
                             max_polls=args.polls)
    # one-shot: a single joined sample as JSON (the scripting surface)
    view = FleetView(args.targets)
    polls = args.polls if args.polls is not None else 1
    sample = None
    import time as _time

    for i in range(max(1, polls)):
        if i:
            _time.sleep(args.interval)
        sample = view.poll(healthz=True)
    print(json.dumps(sample, default=repr))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dat_replication_protocol_tpu.obs",
        description="offline telemetry tools: causal timeline merge, "
                    "Chrome trace export, flight-bundle dumps",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    tl = sub.add_parser(
        "timeline",
        help="merge N JSONL logs into one causally-ordered timeline "
             "keyed on wire offset; flag gaps/reorders/duplicates "
             "(2 logs: the classic sender/receiver audit; more: "
             "per-link pairing, the fleet join's offline mirror)")
    tl.add_argument("sender", help="the sending peer's JSONL event/span log")
    tl.add_argument("receiver", help="the receiving peer's JSONL log")
    tl.add_argument("peers", nargs="*", metavar="PEER",
                    help="further peers' JSONL logs (N-log mode: "
                         "dispatch streams pair with their emitting "
                         "peer by link label, else best coverage match)")
    tl.add_argument("--json", action="store_true",
                    help="machine-readable output")
    tl.set_defaults(fn=cmd_timeline)

    ex = sub.add_parser(
        "export-trace",
        help="convert a JSONL log or a flight bundle into Chrome "
             "trace-event JSON (Perfetto-loadable)")
    ex.add_argument("log", help="JSONL log file, or a bundle directory")
    ex.add_argument("-o", "--out", default=None,
                    help="output path (default: <log>.trace.json)")
    ex.set_defaults(fn=cmd_export_trace)

    dp = sub.add_parser(
        "dump", help="render a flight-recorder bundle directory")
    dp.add_argument("bundle", help="bundle directory (see obs/flight.py)")
    dp.add_argument("--json", action="store_true",
                    help="machine-readable output")
    dp.set_defaults(fn=cmd_dump)

    ld = sub.add_parser(
        "loopdoctor",
        help="attribute event-loop stall time to phases and sessions "
             "from edge.turn spans (JSONL log or flight bundle); "
             "exit 1 on dominance or tiling flags")
    ld.add_argument("log", help="JSONL log file, or a bundle directory")
    ld.add_argument("--threshold", type=float, default=None,
                    metavar="SECONDS",
                    help="stall threshold per turn (default: "
                         "max(4 * tick, 0.03) from each loop's spans)")
    ld.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ld.set_defaults(fn=cmd_loopdoctor)

    md = sub.add_parser(
        "meshdoctor",
        help="reconstruct the per-record propagation tree from "
             "gossip.exchange spans (N JSONL logs / flight bundles), "
             "attribute slow convergence to exact links/rounds/"
             "quarantines; exit 1 on orphaned-digest / stalled-link / "
             "asymmetric-link / rounds-bound-exceeded flags")
    md.add_argument("logs", nargs="+", metavar="LOG",
                    help="JSONL log file(s) and/or bundle directories "
                         "from the mesh's replicas")
    md.add_argument("--json", action="store_true",
                    help="machine-readable output")
    md.set_defaults(fn=cmd_meshdoctor)

    cd = sub.add_parser(
        "costdoctor",
        help="rebuild the per-link wire cost ledger from frame "
             "instants (N JSONL logs / flight bundles) and audit it; "
             "exit 1 on unattributed-bytes / overhead-anomaly / "
             "amplification-regression flags")
    cd.add_argument("logs", nargs="+", metavar="LOG",
                    help="JSONL log file(s), --stats-fd JSONL files, "
                         "and/or bundle directories")
    cd.add_argument("--max-overhead", type=float, default=0.5,
                    metavar="RATIO",
                    help="framing-overhead flag threshold per stream "
                         "(default: 0.5; the flag only fires when even "
                         "the minimum possible framing breaches)")
    cd.add_argument("--min-goodput", type=float, default=None,
                    metavar="FRACTION",
                    help="optional goodput floor per stream (off by "
                         "default)")
    cd.add_argument("--json", action="store_true",
                    help="machine-readable output")
    cd.set_defaults(fn=cmd_costdoctor)

    pc = sub.add_parser(
        "perf-check",
        help="compare a bench.py artifact against the checked-in "
             "perf budgets; exit 1 on regression")
    pc.add_argument("snapshot", help="bench artifact JSON (the one-line "
                                     "object bench.py prints)")
    pc.add_argument("--budgets", default=None, metavar="PATH",
                    help="budget file (default: artifacts/perf_budgets.json "
                         "next to the package, else CWD-relative)")
    pc.add_argument("--host-only", action="store_true",
                    help="evaluate only host-group configs (CPU-safe)")
    pc.set_defaults(fn=cmd_perf_check)

    fl = sub.add_parser(
        "fleet",
        help="poll N replica targets (http:// endpoints and/or "
             "--stats-fd JSONL files), join watermarks into per-link "
             "replication lag; render a live dashboard or gate on a "
             "declarative SLO (exit 1 on breach)")
    fl.add_argument("targets", nargs="+", metavar="TARGET",
                    help="http://host:port scrape endpoint or path to a "
                         "--stats-fd JSONL file")
    fl.add_argument("--check", metavar="SLO.json", default=None,
                    help="evaluate the fleet against a declarative SLO "
                         "file and exit 1 on breach (the perf-check "
                         "contract for fleet health; see "
                         "OBSERVABILITY.md for the schema)")
    fl.add_argument("--watch", action="store_true",
                    help="live TTY dashboard (plain ANSI, one screen "
                         "per poll) instead of a one-shot JSON sample")
    fl.add_argument("--interval", type=float, default=2.0,
                    metavar="SECONDS",
                    help="poll period for --watch / between --check "
                         "polls (default: 2)")
    fl.add_argument("--polls", type=int, default=None, metavar="N",
                    help="stop after N polls (--watch: frames; --check: "
                         "evaluate the final poll; default: --check 3, "
                         "--watch unbounded)")
    fl.set_defaults(fn=cmd_fleet)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
