"""Live replication-lag watermarks: wire-position cursors as telemetry.

The fleet plane's data layer (ISSUE 11).  Every layer of the session
stack already maintains an exact wire-position cursor — the sender
journal's append/acked offsets, the decoder's accepted/parsed bytes and
last checkpoint, a fan-out peer's delivered offset — because the resume
and flow-control machinery need them.  This module exports those
cursors as *labeled gauges* without adding any wire traffic or hot-path
work: a cursor is registered ONCE as a zero-argument callable, and the
value is read only at snapshot time (the "Simplicity Scales" split —
the data plane is never taxed; lag is *derived* from state both sides
already keep).

Catalog shape (OBSERVABILITY.md "Fleet plane"):

* ``session.wire.offset{link=L,role=R}`` — one labeled collector entry
  per tracked cursor, merged into every registry snapshot via the PR 8
  collector machinery.  ``link`` names one wire (a session key, a
  fan-out peer); ``role`` names the cursor (see :data:`SEND_ROLES` /
  :data:`RECV_ROLES`).
* ``(append - parsed)`` for one link is the link's **exact replication
  lag in bytes**: wire bytes the sender has produced that the receiver
  has not yet fully parsed.
* The per-link **marks ring** ``[(end_offset, monotonic_t), ...]``
  records when each append advanced the wire, so lag in *seconds* is
  clock-free: the age of the oldest unparsed byte is measured entirely
  on the sender's monotonic clock (the fleet aggregator joins a
  receiver's parsed offset against the sender's marks — no wall-clock
  synchronization anywhere).

Registration is idempotent and bounded: re-tracking a (link, role)
replaces the callable (sessions reconnect), :func:`untrack` drops a
link whole (dead sessions vanish from snapshots — nothing leaks), and
the board re-registers its registry collector on every track so a
test-isolation ``Registry.reset()`` (which drops collectors by design)
cannot silently dark the watermark plane for the next owner.

Hot-path budget: the only call that may sit on a session hot path is
:meth:`WatermarkBoard.mark`, and every caller gates it behind
``if _OBS.on:`` — disabled telemetry pays one attribute load, the same
contract as every other instrumentation site.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from .metrics import REGISTRY as _REGISTRY

__all__ = [
    "WATERMARKS",
    "WatermarkBoard",
    "SEND_ROLES",
    "RECV_ROLES",
    "link_lag",
]

# the catalog's role vocabulary (OBSERVABILITY.md).  Sender-side roles
# advance as bytes are produced; receiver-side roles advance as bytes
# are consumed.  Lag joins the largest sender cursor against the
# receive cursor in preference order (parsed is exact; delivered is the
# fan-out transport's "handed to the kernel" position).
SEND_ROLES = ("append", "acked")
RECV_ROLES = ("parsed", "accepted", "checkpoint", "delivered")
# receive-cursor preference for the lag join, strongest first
_LAG_RECV_PREFERENCE = ("parsed", "delivered")

_MARK_RING = 1024
# marks exported per snapshot line: enough to cover any realistic poll
# interval without growing --stats-fd lines unboundedly
_MARK_EXPORT = 256

_BAD_LABEL_CHARS = '{},="\n\r'


def _check_label(kind: str, value: str) -> None:
    # link/role ride telemetry label sets ({link=L,role=R}) and JSON
    # breakdowns — refuse structural characters at the boundary (the
    # hub/fanout key precedent)
    if not isinstance(value, str) or not value or any(
            c in value for c in _BAD_LABEL_CHARS):
        raise ValueError(
            f"watermark {kind} {value!r} must be a non-empty string "
            'containing none of {},=" or newlines')


class _Link:
    __slots__ = ("cursors", "marks", "marks_from", "marks_dropped")

    def __init__(self) -> None:
        self.cursors: dict[str, Callable[[], int]] = {}
        self.marks: deque = deque(maxlen=_MARK_RING)
        self.marks_from: Optional[str] = None
        # marks evicted by ring wraparound: the lag-seconds join must
        # know when the OLDEST retained mark is not the oldest append
        # (an outrun ring would otherwise under-report the age of the
        # frontier byte — the dangerous direction for an SLO gate)
        self.marks_dropped = 0


def link_lag(offsets: dict, marks, now: float,
             marks_dropped: int = 0) -> tuple:
    """The one lag join, shared by the local snapshot and the fleet
    aggregator: ``(lag_bytes, lag_seconds)`` from one link's role ->
    offset dict and its ``[(end_offset, t), ...]`` marks.

    * ``lag_bytes = append - recv`` where ``recv`` is the strongest
      receive cursor present (parsed, else delivered); ``None`` when
      either side is missing (an unjoined half-link is visible, not
      fabricated as zero).
    * ``lag_seconds`` is the age of the oldest unparsed byte on the
      *sender's* clock: ``now`` must be a monotonic stamp from the same
      process that recorded ``marks``.  Exactly ``0.0`` when the link
      is fully caught up; ``None`` when behind but the age cannot be
      attributed EXACTLY — no mark covers the frontier, or
      ``marks_dropped`` says older marks were evicted and the first
      retained mark already sits past the frontier (the evicted marks
      were older: reporting the retained one would UNDER-state the
      age, which is the direction an SLO gate must never err in).
    """
    append = offsets.get("append")
    recv = None
    for role in _LAG_RECV_PREFERENCE:
        if offsets.get(role) is not None:
            recv = offsets[role]
            break
    if append is None or recv is None:
        return None, None
    lag_bytes = max(0, int(append) - int(recv))
    if lag_bytes == 0:
        return 0, 0.0
    lag_seconds = None
    for i, (end, t) in enumerate(marks or ()):
        if end > recv:
            if i == 0 and marks_dropped:
                # the frontier byte predates every retained mark: its
                # true age is OLDER than anything we can attribute
                break
            # the first mark past the receive frontier timestamps the
            # oldest byte the receiver has not consumed (exact: either
            # nothing was ever evicted, or its predecessor covers recv)
            lag_seconds = max(0.0, float(now) - float(t))
            break
    return lag_bytes, lag_seconds


class WatermarkBoard:
    """Process-global registry of wire-position cursors.  See module
    docstring; the instance to use is :data:`WATERMARKS`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # datlint: guarded-by(self._lock): self._links
        self._links: dict[str, _Link] = {}
        # datlint: guarded-by(self._lock): self._loops
        # event-loop lag exporters (ISSUE 18): loop name -> zero-arg
        # callable returning the loopprof export record
        self._loops: dict[str, Callable[[], dict]] = {}
        self._collector_fn = self._collect

    # -- registration -------------------------------------------------------

    def track(self, role: str, link: str, fn: Callable[[], int], *,
              marks_from: Optional[str] = None) -> None:
        """Track one cursor: ``fn()`` returns the current absolute wire
        offset for ``role`` on ``link``.  ``role`` is a string literal
        at every call site (the obs-discipline greppability contract —
        the catalog keys on it); ``link`` is the runtime wire name (a
        session key).  Re-tracking a (link, role) replaces the callable.

        ``marks_from`` points this link's lag-seconds computation at
        ANOTHER link's marks ring — the fan-out case: one shared
        publish ring serves every per-peer link, keeping the publish
        path O(1) in peers."""
        _check_label("role", role)
        _check_label("link", link)
        with self._lock:
            entry = self._links.get(link)
            if entry is None:
                entry = self._links[link] = _Link()
            entry.cursors[role] = fn
            if marks_from is not None:
                _check_label("link", marks_from)
                entry.marks_from = marks_from
        # idempotent re-registration: Registry.reset() (test/bench
        # isolation) drops collectors on purpose; the next track() must
        # bring the watermark plane back instead of staying dark
        _REGISTRY.register_collector("watermarks", self._collector_fn)

    def untrack(self, link: str) -> None:
        """Drop a link whole (every role + its marks).  Dead sessions
        stop appearing in snapshots; nothing leaks.  Idempotent."""
        with self._lock:
            self._links.pop(link, None)

    def track_loop(self, name: str, fn: Callable[[], dict]) -> None:
        """Track one event loop's lag exporter (ISSUE 18): ``fn()``
        returns the :meth:`~.loopprof.LoopProfiler.export` record.
        Same contract as :meth:`track` — idempotent replace, label
        hygiene at the boundary, collector re-registration so a
        ``Registry.reset()`` cannot dark the loop plane."""
        _check_label("loop", name)
        with self._lock:
            self._loops[name] = fn
        _REGISTRY.register_collector("watermarks", self._collector_fn)

    def untrack_loop(self, name: str) -> None:
        """Drop one loop's exporter (loop shutdown).  Idempotent."""
        with self._lock:
            self._loops.pop(name, None)

    def mark(self, link: str, end_offset: int) -> None:
        """Note that ``link``'s appended wire now ends at
        ``end_offset`` (monotonic-stamped).  The ONLY board call that
        sits on a hot path — callers gate it with ``if _OBS.on:``."""
        now = time.monotonic()
        with self._lock:
            entry = self._links.get(link)
            if entry is None:
                entry = self._links[link] = _Link()
            if len(entry.marks) == entry.marks.maxlen:
                entry.marks_dropped += 1
            entry.marks.append((end_offset, now))

    # -- snapshots ----------------------------------------------------------

    def _read_cursors(self, entry: _Link) -> dict:
        offsets = {}
        for role, fn in list(entry.cursors.items()):
            try:
                # registered cursor getters are attribute reads (wire
                # offsets) — snapshot-grade, never blocking
                # datlint: allow-callback-escape
                offsets[role] = int(fn())
            except Exception:
                # a dying owner (decoder mid-destroy) must not take the
                # snapshot down — its cursor simply goes missing, the
                # same best-effort contract as registry collectors
                continue
        return offsets

    def snapshot(self) -> dict:
        """Plain-dict view (JSON-able): per-link offsets, bounded marks
        tail, and the locally-computed lag when both sides of a link
        live in this process.  ``monotonic`` stamps the snapshot on
        this process's clock — the fleet aggregator's time base for
        the clock-free seconds join."""
        now = time.monotonic()
        with self._lock:
            links = {name: (entry, list(entry.marks), entry.marks_dropped)
                     for name, entry in self._links.items()}
        out: dict = {"monotonic": now, "links": {}}
        loops = self.loops_now()
        if loops:
            out["loops"] = loops
        for name, (entry, marks, dropped) in links.items():
            offsets = self._read_cursors(entry)
            if not offsets:
                # a marks-only link (the fan-out shared publish ring,
                # or a link whose every cursor died) is a clock
                # source, not a wire: exporting it as a half-link
                # would make the SLO gate fail a healthy fleet on a
                # link that can never join
                continue
            src = entry.marks_from
            if src is not None and src in links:
                marks = links[src][1]
                dropped = links[src][2]
            # the export tail is itself an eviction: marks cut off by
            # _MARK_EXPORT count as dropped for the exactness rule
            dropped += max(0, len(marks) - _MARK_EXPORT)
            lag_bytes, lag_seconds = link_lag(offsets,
                                              marks[-_MARK_EXPORT:], now,
                                              marks_dropped=dropped)
            rec: dict = {"offsets": offsets,
                         "marks": [[o, t] for o, t in marks[-_MARK_EXPORT:]],
                         "marks_dropped": dropped}
            if src is not None:
                rec["marks_from"] = src
            if lag_bytes is not None:
                rec["lag_bytes"] = lag_bytes
                rec["lag_seconds"] = (round(lag_seconds, 6)
                                      if lag_seconds is not None else None)
            out["links"][name] = rec
        return out

    def loops_now(self) -> dict:
        """Current per-loop lag records (the ``loops`` snapshot
        section): loop name -> the exporter's dict.  Best-effort, the
        same contract as cursor reads — a dying loop's exporter simply
        goes missing."""
        with self._lock:
            loops = list(self._loops.items())
        out: dict = {}
        for name, fn in loops:
            try:
                # loop exporters are plain-attribute reads off the
                # profiler (lock-free, one turn stale) — never blocking
                # datlint: allow-callback-escape
                rec = fn()
            except Exception:
                continue
            if isinstance(rec, dict):
                out[name] = rec
        return out

    def _collect(self) -> dict:
        """Registry collector: one labeled gauge per tracked cursor
        (bounded cardinality — untracked links stop appearing), plus
        the per-loop lag gauges (``edge.loop.lag{loop=}``)."""
        gauges: dict = {}
        with self._lock:
            links = list(self._links.items())
        for name, entry in links:
            for role, value in self._read_cursors(entry).items():
                gauges[f"session.wire.offset{{link={name},role={role}}}"] = \
                    float(value)
        for name, rec in self.loops_now().items():
            if rec.get("state") != "live":
                continue  # a dark loop exports nothing: stale zeros
                #   would read as "caught up", the direction an SLO
                #   gate must never err in
            gauges[f"edge.loop.lag{{loop={name}}}"] = float(
                rec.get("lag_s", 0.0))
            gauges[f"edge.loop.lag_max{{loop={name}}}"] = float(
                rec.get("lag_max_s", 0.0))
            gauges[f"edge.loop.oldest_ready{{loop={name}}}"] = float(
                rec.get("oldest_ready_s", 0.0))
        return {"gauges": gauges}

    def reset_for_tests(self) -> None:
        """Drop every link and loop (process-global state — test
        isolation is explicit, the conftest ``obs_enabled``
        contract)."""
        with self._lock:
            self._links.clear()
            self._loops.clear()


WATERMARKS = WatermarkBoard()
