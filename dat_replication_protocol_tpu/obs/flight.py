"""Flight recorder: atomic post-mortem bundles for offline attribution.

An offloaded-datapath deployment must answer "what happened to frame N"
from telemetry alone (PAPERS: *Reliable Replication Protocols on
SmartNICs*) — there is no debugger attached to a production session.
This module is the crash-dump half of that answer: when ARMED (a
directory is configured), any structured :class:`~..wire.framing.ProtocolError`
(every decoder destroy site funnels through ``Decoder._protocol_error``)
or reconnect exhaustion (``run_resumable`` / ``retrying``) dumps one
self-contained bundle for the offline CLI
(``python -m dat_replication_protocol_tpu.obs dump``).

Bundle layout (a directory, renamed into place ATOMICALLY so a
consumer never sees a half-written bundle)::

    bundle-<pid>-<seq>-<reason>/
        manifest.json   reason, wall+monotonic ts, structured error
                        (type/message/frame/offset/cause), decoder
                        checkpoint, active fault-plan seeds, ring-drop
                        accounting
        metrics.json    full registry snapshot (obs.metrics.snapshot())
        events.jsonl    the event ring, one record per line
        spans.jsonl     the span ring (last-K wire-offset-tagged spans)

Dumps are BOUNDED (``max_bundles`` per armed recorder; an error storm
cannot fill the disk) and DEDUPLICATED (the same error object never
dumps twice — the decoder builds the error, the reconnect driver
re-raises it; one incident, one bundle).

The fault injector registers every active :class:`~..session.faults.FaultPlan`
via :meth:`FlightRecorder.note_plan`, so a bundle carries the chaos
ground truth — the conformance suite asserts every injected fault's
coordinates (kind, wire offset) are recoverable from the bundle ALONE.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time
import weakref
from collections import deque
from typing import Optional

from . import events as _events
from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ["FlightRecorder", "FLIGHT", "arm", "disarm", "dump",
           "read_bundle"]

DEFAULT_MAX_BUNDLES = 16
_PLAN_HISTORY = 8


def _slug(reason: str) -> str:
    return "".join(c if c.isalnum() else "-" for c in reason)[:40] or "dump"


def _write_json(path: str, obj) -> None:
    # ABSORBED (ISSUE 17 satellite): post-mortem bundle writes go to a
    # local --flight-dir; a dump happens at most max_bundles times per
    # capture, on the failure path — never on a session's hot path
    # datlint: allow-blocking-reachable(file-io)
    with open(path, "w", encoding="utf-8") as f:
        # datlint: allow-blocking-reachable(file-io)
        json.dump(obj, f, default=repr)


def _write_jsonl(path: str, records: list) -> None:
    # ABSORBED: same local-bundle contract as _write_json above
    # datlint: allow-blocking-reachable(file-io)
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            # datlint: allow-blocking-reachable(file-io)
            f.write(json.dumps(rec, default=repr) + "\n")


class FlightRecorder:
    """Armed directory + dump budget; see module docstring."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.dir: Optional[str] = None
        self.max_bundles = DEFAULT_MAX_BUNDLES
        self._seq = 0
        self._routine = 0  # routine (non-failure) dumps this capture
        # capture generation: bumped by every arm() and NEVER reset, so
        # re-arming into the SAME directory cannot collide bundle names
        # with a previous capture (an os.rename onto an existing bundle
        # would fail and silently lose the post-mortem)
        self._capture = 0
        # dedup handle on the last bundled error: a WEAK ref, so the
        # recorder never pins an exception (and the decoder/buffers its
        # traceback frames reference) for the life of the process
        self._last_error: Optional[weakref.ref] = None
        self._plans: deque = deque(maxlen=_PLAN_HISTORY)
        self.last_bundle: Optional[str] = None
        # dumps that produced no bundle: budget spent, duplicate error,
        # or a failed write
        self.suppressed = 0

    @property
    def armed(self) -> bool:
        return self.dir is not None

    def arm(self, directory: str, max_bundles: int = DEFAULT_MAX_BUNDLES,
            enable_telemetry: bool = True) -> "FlightRecorder":
        """Start recording bundles into ``directory`` (created if
        missing).  Arming is a FRESH capture: the dump budget, the
        duplicate-error dedup, and the bundle sequence all reset — a
        re-armed recorder must never be silently out of budget from a
        previous capture.  By default also enables the obs gate — a
        dark event ring has nothing worth dumping."""
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            self.dir = directory
            self.max_bundles = max_bundles
            self._seq = 0
            self._routine = 0
            self._capture += 1
            self._last_error = None
            self.suppressed = 0
        if enable_telemetry:
            _metrics.enable()
        return self

    def disarm(self) -> None:
        with self._lock:
            self.dir = None

    def note_plan(self, plan) -> None:
        """Record an active fault plan (chaos ground truth rides in the
        next bundle's manifest).  No-op while disarmed."""
        if self.dir is None:
            return
        try:
            d = dataclasses.asdict(plan)
        except TypeError:
            d = {"repr": repr(plan)}
        with self._lock:
            self._plans.append(d)

    def dump(self, reason: str, *, error: Optional[BaseException] = None,
             checkpoint=None, extra: Optional[dict] = None,
             routine: bool = False) -> Optional[str]:
        """Write one bundle; returns its path, or None when disarmed,
        over budget, or the error object was already bundled.

        ``routine`` marks a non-failure dump (e.g. a recovered
        session's incident record): routine dumps are additionally
        capped at HALF the budget, so a long-lived process absorbing
        transient faults can never exhaust the bundles reserved for a
        genuine failure's post-mortem."""
        with self._lock:
            directory = self.dir
            if directory is None:
                return None
            # a weakref deref: returns the referent or None, no user code
            # datlint: allow-callback-escape
            last = (self._last_error() if self._last_error is not None
                    else None)
            if error is not None and error is last:
                self.suppressed += 1
                return None
            if self._seq >= self.max_bundles or (
                    routine and self._routine >= max(1, self.max_bundles // 2)):
                self.suppressed += 1
                return None
            seq = self._seq
            self._seq += 1
            if routine:
                self._routine += 1
            capture = self._capture
            if error is not None:
                try:
                    self._last_error = weakref.ref(error)
                except TypeError:  # exotic non-weakref-able exception
                    self._last_error = None
            plans = list(self._plans)
        name = f"bundle-{os.getpid()}-c{capture:02d}-{seq:04d}-{_slug(reason)}"
        final = os.path.join(directory, name)
        tmp = os.path.join(directory, f".tmp-{name}")
        manifest: dict = {
            "reason": reason,
            "ts": time.time(),
            "monotonic": time.monotonic(),
            "pid": os.getpid(),
            "fault_plans": plans,
            "events_dropped": _events.EVENTS.dropped,
            "spans_dropped": _tracing.SPANS.dropped,
        }
        if error is not None:
            cause = getattr(error, "cause", None)
            manifest["error"] = {
                "type": type(error).__name__,
                "message": str(error),
                "frame": getattr(error, "frame", None),
                "offset": getattr(error, "offset", None),
                "cause": (None if cause is None
                          else f"{type(cause).__name__}: {cause}"),
            }
        if checkpoint is not None:
            as_dict = getattr(checkpoint, "as_dict", None)
            manifest["checkpoint"] = (as_dict() if as_dict is not None
                                      else dict(checkpoint))
        if extra:
            manifest["extra"] = extra
        try:
            os.makedirs(tmp, exist_ok=True)
            _write_json(os.path.join(tmp, "manifest.json"), manifest)
            _write_json(os.path.join(tmp, "metrics.json"),
                        _metrics.snapshot())
            _write_jsonl(os.path.join(tmp, "events.jsonl"),
                         _events.EVENTS.events())
            _write_jsonl(os.path.join(tmp, "spans.jsonl"),
                         _tracing.SPANS.spans())
            os.rename(tmp, final)
        except OSError:
            # a full or vanished disk must never take the session down;
            # remove the partial tmp so no half-bundle is ever visible —
            # but the LOSS is accounted: a bundle that failed to write
            # is a suppressed dump, not a silent nothing
            shutil.rmtree(tmp, ignore_errors=True)
            with self._lock:
                self.suppressed += 1
            return None
        self.last_bundle = final
        if _metrics.OBS.on:
            _events.emit("flight.dump", reason=reason, bundle=name)
        return final

    def _reset_for_tests(self) -> None:
        with self._lock:
            self.dir = None
            self._seq = 0
            self._routine = 0
            self._last_error = None
            self._plans.clear()
            self.last_bundle = None
            self.suppressed = 0


FLIGHT = FlightRecorder()


def arm(directory: str, **kwargs) -> FlightRecorder:
    """Arm the process-global flight recorder."""
    return FLIGHT.arm(directory, **kwargs)


def disarm() -> None:
    FLIGHT.disarm()


def dump(reason: str, **kwargs) -> Optional[str]:
    """Dump one bundle from the process-global recorder (if armed)."""
    return FLIGHT.dump(reason, **kwargs)


def read_bundle(path: str) -> dict:
    """Load every part of a bundle directory back into one dict — the
    offline CLI's ``dump`` subcommand and the conformance oracle both
    read bundles exclusively through this."""
    out: dict = {"path": path}
    with open(os.path.join(path, "manifest.json"), encoding="utf-8") as f:
        out["manifest"] = json.load(f)
    with open(os.path.join(path, "metrics.json"), encoding="utf-8") as f:
        out["metrics"] = json.load(f)
    for part in ("events", "spans"):
        records = []
        with open(os.path.join(path, f"{part}.jsonl"),
                  encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if ln:
                    records.append(json.loads(ln))
        out[part] = records
    return out
