"""Bounded-ring structured event log for session lifecycle.

Events are the *rare*, *narrative* half of telemetry (metrics are the
dense half): connect, checkpoint export, resume attempt, backoff
sleep, journal replay, stall detection, truncation, ProtocolError.
Each record carries a process-wide monotonically increasing ``seq``
and a ``time.monotonic()`` timestamp, so interleavings across threads
reconstruct even when wall clocks jump.

The ring is bounded (default 1024 records): an event storm overwrites
the oldest records and bumps ``dropped`` instead of growing host RAM —
the same discipline as the histogram quantile ring.  An optional sink
(:meth:`EventLog.attach_sink`) mirrors every record as one JSON line
(JSONL) to a file descriptor or file object the moment it is emitted —
attach a dedicated fd for a live event stream.  (The sidecar's
``--stats-fd`` exports periodic *metrics snapshots* plus the ring's
``dropped`` count on its own fd; it deliberately does not share that
fd with the per-event sink, because two writers interleaving past
PIPE_BUF would corrupt the one-object-per-line contract.)

Emission is gated on the shared :data:`~.metrics.OBS` gate; hot-path
call sites additionally guard with ``if _OBS.on:`` so the disabled
path never builds the kwargs dict (see OBSERVABILITY.md's budget).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

from .metrics import OBS

__all__ = ["EventLog", "EVENTS", "emit"]

DEFAULT_CAPACITY = 1024


class EventLog:
    """Bounded ring of structured events + optional JSONL sink."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._lock = threading.Lock()
        # separate sink lock: record ordering/teardown stays cheap under
        # _lock; the (possibly slow) sink I/O serializes on its own lock
        # so concurrent emits cannot interleave characters of two records
        self._sink_lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self.dropped = 0  # records overwritten by ring wraparound
        self._sink = None  # int fd, or object with write(str)

    # -- emission -----------------------------------------------------------

    def emit(self, event: str, **fields) -> None:
        """Record one event (no-op while the obs gate is off).

        ``event`` names are dot-separated literals (greppable — the
        obs-discipline datlint rule enforces literal names at call
        sites); ``fields`` must be JSON-able scalars/strings.
        """
        if not OBS.on:
            return
        now = time.monotonic()
        with self._lock:
            seq = self._seq
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            rec = {"seq": seq, "ts": now, "event": event, "fields": fields}
            self._ring.append(rec)
            sink = self._sink
        if sink is not None:
            with self._sink_lock:
                self._write_sink(sink, rec)

    @staticmethod
    def _write_sink(sink, rec: dict) -> None:
        line = json.dumps(rec, default=repr) + "\n"
        try:
            if isinstance(sink, int):
                # write-all loop: a short write on a blocking fd must
                # not truncate the record mid-line (the consumer parses
                # one JSON object per line); a non-blocking fd's EAGAIN
                # falls through to the best-effort swallow below
                view = memoryview(line.encode("utf-8"))
                while view:
                    view = view[os.write(sink, view):]
            else:
                sink.write(line)
                flush = getattr(sink, "flush", None)
                if flush is not None:
                    flush()
        except (OSError, ValueError):
            pass  # a dead sink must never take the session down

    # -- sink management ----------------------------------------------------

    def attach_sink(self, sink) -> None:
        """Mirror every subsequent event as one JSON line to ``sink``
        (an int file descriptor, or any object with ``write(str)``)."""
        with self._lock:
            self._sink = sink

    def detach_sink(self) -> None:
        with self._lock:
            self._sink = None

    # -- inspection ---------------------------------------------------------

    def events(self, event: Optional[str] = None) -> list[dict]:
        """Snapshot of the retained records, oldest first; optionally
        filtered by exact event name."""
        with self._lock:
            records = list(self._ring)
        if event is None:
            return records
        return [r for r in records if r["event"] == event]

    def count(self, event: str) -> int:
        return len(self.events(event))

    def last(self, event: Optional[str] = None) -> Optional[dict]:
        records = self.events(event)
        return records[-1] if records else None

    def clear(self) -> None:
        """Drop retained records (seq keeps counting — per-test reset)."""
        with self._lock:
            self._ring.clear()
            self.dropped = 0


EVENTS = EventLog()


def emit(event: str, **fields) -> None:
    """Emit to the process-global event log (gated, see EventLog.emit)."""
    EVENTS.emit(event, **fields)
