"""Bounded-ring structured event log for session lifecycle.

Events are the *rare*, *narrative* half of telemetry (metrics are the
dense half): connect, checkpoint export, resume attempt, backoff
sleep, journal replay, stall detection, truncation, ProtocolError.
Each record carries a process-wide monotonically increasing ``seq``
and a ``time.monotonic()`` timestamp, so interleavings across threads
reconstruct even when wall clocks jump.

The ring is bounded (default 1024 records): an event storm overwrites
the oldest records and bumps ``dropped`` instead of growing host RAM —
the same discipline as the histogram quantile ring.  An optional sink
(:meth:`EventLog.attach_sink`) mirrors every record as one JSON line
(JSONL) to a file descriptor or file object the moment it is emitted —
attach a dedicated fd for a live event stream.  (The sidecar's
``--stats-fd`` exports periodic *metrics snapshots* plus the ring's
``dropped`` count on its own fd; it deliberately does not share that
fd with the per-event sink, because two writers interleaving past
PIPE_BUF would corrupt the one-object-per-line contract.)

Sink discipline on non-blocking fds (ISSUE 4 satellite): a record is
written whole or not at all.  ``EAGAIN`` before the first byte drops
the record atomically and bumps ``sink_dropped``; ``EAGAIN`` after a
partial write gets a short bounded retry to finish the line, and if
the pipe stays full the sink latches dead (``sink_dropped`` counts the
record) — appending any later record to a torn fragment would merge
two lines and break the one-JSON-object-per-line contract.  A torn
final line is the worst a consumer can ever see, and JSONL consumers
discard an unterminated last line harmlessly.

Emission is gated on the shared :data:`~.metrics.OBS` gate; hot-path
call sites additionally guard with ``if _OBS.on:`` so the disabled
path never builds the kwargs dict (see OBSERVABILITY.md's budget).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

from .metrics import OBS

__all__ = ["EventLog", "EVENTS", "emit"]

DEFAULT_CAPACITY = 1024

# how long a torn record may retry on EAGAIN before the sink latches
# dead — bounded: the emitter can sit on session hot paths
_SINK_RETRY_S = 0.05


class EventLog:
    """Bounded ring of structured events + optional JSONL sink."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._lock = threading.Lock()
        # separate sink lock: record ordering/teardown stays cheap under
        # _lock; the (possibly slow) sink I/O serializes on its own lock
        # so concurrent emits cannot interleave characters of two records
        self._sink_lock = threading.Lock()
        # the concurrency pass enforces these (ANALYSIS.md guarded-state):
        # datlint: guarded-by(self._lock): self._ring, self._seq, self.dropped
        # datlint: guarded-by(self._lock): self._sink, self._sink_dead
        # datlint: guarded-by(self._sink_lock): self.sink_dropped
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self.dropped = 0  # records overwritten by ring wraparound
        self.sink_dropped = 0  # records the sink dropped WHOLE (EAGAIN,
        # dead fd, torn-line latch) — never half-counted, never half-written
        self._sink = None  # int fd, or object with write(str)
        self._sink_dead = False  # a record tore on this sink: latched

    # -- emission -----------------------------------------------------------

    def emit(self, event: str, **fields) -> None:
        """Record one event (no-op while the obs gate is off).

        ``event`` names are dot-separated literals (greppable — the
        obs-discipline datlint rule enforces literal names at call
        sites); ``fields`` must be JSON-able scalars/strings.
        """
        if not OBS.on:
            return
        self._append({"seq": 0, "ts": time.monotonic(), "event": event,
                      "fields": fields})

    def _append(self, rec: dict) -> None:
        """Ring + sink plumbing shared by events and spans (the span
        ring in :mod:`.tracing` subclasses this log): assigns ``seq``
        under the lock, appends with wraparound accounting, and mirrors
        to the sink outside the ring lock."""
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)
            sink = self._sink
            dead = self._sink_dead
        if sink is not None:
            with self._sink_lock:
                if dead or self._sink_dead:
                    # latched after a torn line: the record is dropped
                    # whole (and counted), never appended to the tear
                    self.sink_dropped += 1
                else:
                    # _sink_lock exists precisely to serialize this
                    # I/O: one record = one uninterleaved JSONL line.
                    # The lock is a LEAF (lock_graph.json: nothing is
                    # acquired inside except the _latch_dead hop), and
                    # only emitters that attached a sink pay the cost.
                    # Callers holding OTHER locks are NOT excused —
                    # the allow covers this lock alone (lexical-only
                    # contract).  datlint: allow-blocking-under-lock
                    self._write_sink(sink, rec)

    def _latch_dead(self, sink) -> None:
        """Latch the dead flag ONLY if ``sink`` is still the attached
        one: a concurrent attach_sink() swapped in a fresh sink whose
        stream has no torn fragment — latching it would silently drop
        every later record on a healthy fd.  (_append takes _lock and
        _sink_lock sequentially, never nested, so taking _lock here
        while holding _sink_lock cannot deadlock.)"""
        with self._lock:
            if self._sink is sink:
                self._sink_dead = True

    def _write_sink(self, sink, rec: dict) -> None:
        """One record -> one JSONL line, whole or not at all (see the
        module docstring's sink discipline).  Runs under _sink_lock."""
        line = json.dumps(rec, default=repr) + "\n"
        if not isinstance(sink, int):
            try:
                # a file-object sink runs on the emitting thread by the
                # module-docstring contract: its promptness is the
                # attacher's problem (tests attach StringIO; production
                # attaches an fd and rides the deadline loop below).
                # datlint: allow-blocking-reachable(file-io)
                sink.write(line)
                flush = getattr(sink, "flush", None)
                if flush is not None:
                    flush()
            except (OSError, ValueError):
                # a dead sink must never take the session down; a
                # file-object write is all-or-nothing at this layer
                self.sink_dropped += 1
            return
        view = memoryview(line.encode("utf-8"))
        total = len(view)
        deadline = None
        try:
            while view:
                try:
                    # the EAGAIN/deadline loop below bounds this write
                    # on a NONBLOCKING fd; a blocking fd parks only the
                    # emitting thread, the attach_sink contract — same
                    # doctrine as the sidecar stats emitter, which
                    # flips its pipe nonblocking for exactly this.
                    # datlint: allow-blocking-reachable(os-io)
                    n = os.write(sink, view)
                except InterruptedError:
                    continue  # EINTR: retry immediately
                except BlockingIOError:
                    if len(view) == total:
                        # EAGAIN before the first byte: drop the whole
                        # record atomically — half a line would corrupt
                        # the JSONL stream for every later record
                        self.sink_dropped += 1
                        return
                    # EAGAIN mid-record: a torn line is already on the
                    # fd — bounded retry to finish it; if the pipe
                    # stays full, latch the sink dead so nothing is
                    # ever appended to the torn fragment
                    now = time.monotonic()
                    if deadline is None:
                        deadline = now + _SINK_RETRY_S
                    elif now >= deadline:
                        self._latch_dead(sink)
                        self.sink_dropped += 1
                        return
                    time.sleep(0.001)
                    continue
                view = view[n:]
        except (OSError, ValueError):
            # hard error (EPIPE, EBADF): swallow — but if the record
            # tore first, latch dead for the same torn-fragment reason
            if len(view) != total:
                self._latch_dead(sink)
            self.sink_dropped += 1

    # -- sink management ----------------------------------------------------

    def attach_sink(self, sink) -> None:
        """Mirror every subsequent event as one JSON line to ``sink``
        (an int file descriptor, or any object with ``write(str)``).
        Re-attaching clears a previous sink's dead latch."""
        with self._lock:
            self._sink = sink
            self._sink_dead = False

    def detach_sink(self) -> None:
        with self._lock:
            self._sink = None
            self._sink_dead = False

    # -- inspection ---------------------------------------------------------

    def events(self, event: Optional[str] = None) -> list[dict]:
        """Snapshot of the retained records, oldest first; optionally
        filtered by exact event name."""
        with self._lock:
            records = list(self._ring)
        if event is None:
            return records
        return [r for r in records if r.get("event") == event]

    def count(self, event: str) -> int:
        return len(self.events(event))

    def last(self, event: Optional[str] = None) -> Optional[dict]:
        records = self.events(event)
        return records[-1] if records else None

    def clear(self) -> None:
        """Drop retained records (seq keeps counting — per-test reset).
        The sink stays attached; a torn-line dead latch stays latched
        (clearing the ring cannot un-tear the fd's last line)."""
        with self._lock:
            self._ring.clear()
            self.dropped = 0
        # sink_dropped is guarded by _sink_lock (guarded-state decl
        # below): resetting it under _lock alone raced a concurrent
        # sink write's increment — a lost update the concurrency pass
        # caught.  Sequential, never nested, so no new lock-order edge.
        with self._sink_lock:
            self.sink_dropped = 0


EVENTS = EventLog()


def emit(event: str, **fields) -> None:
    """Emit to the process-global event log (gated, see EventLog.emit)."""
    EVENTS.emit(event, **fields)


class DeferredEmitQueue:
    """Events queued under a subsystem lock, emitted after release.

    The hub and fan-out dispatchers may never emit while holding their
    lock (the event sink can block — blocking-under-lock contract,
    ANALYSIS.md), so shed-style events capture their fields while the
    holder's view is consistent and drain once the lock releases.  The
    subtle part lives HERE, once: the lock-free peek (a missed peek is
    drained by the next turn's catch-all), the swap under the OWNER's
    lock, and the emission strictly outside it.

    ``queue_locked`` must be called with ``lock`` held; ``flush`` must
    be called with it released (it never waits — the name avoids the
    transport layer's ``.drain()`` vocabulary, which bounded-wait
    polices).
    """

    def __init__(self, event: str, lock):
        self._event = event
        self._lock = lock
        self._pending: list = []

    def queue_locked(self, **fields) -> None:
        self._pending.append(fields)

    def flush(self) -> None:
        if not self._pending:  # racy peek: a miss is drained later
            return
        with self._lock:
            pending, self._pending = self._pending, []
        for fields in pending:
            emit(self._event, **fields)
