"""Sidecar scrape endpoint: pull-based fleet telemetry over stdlib HTTP.

The fleet plane's transport layer (ISSUE 11).  One daemon thread runs a
``http.server.ThreadingHTTPServer`` serving four strictly READ-ONLY
routes off the same locked snapshots ``--stats-fd`` uses — a scraping
client can never perturb the hot path, because nothing here mutates
session state, takes a device dispatch, or holds a session lock while
rendering (the overhead-budget test in tests/test_obs_fleet.py proves
the budget; the datlint healthz check proves the lock discipline for
the liveness route):

* ``GET /metrics``  — Prometheus text exposition
  (:func:`~.metrics.to_prom_text` over the live registry, labeled
  collector entries included);
* ``GET /snapshot`` — the full JSON stats record (registry snapshot +
  ``jit_sites`` + ``watermarks`` + hub/fanout breakdowns when the
  caller's ``snapshot_fn`` carries them — the sidecar passes its
  ``snapshot_stats``, so the endpoint and ``--stats-fd`` serve the
  SAME dict);
* ``GET /healthz``  — staged health: backend-init watchdog state (from
  the event ring), admission open/closed (a LOCK-FREE callable the
  owner installs — see ``ReplicationHub.admission_state``), whether
  the flight recorder is armed and the obs gate is on.  HTTP 200 when
  every stage is healthy, 503 otherwise — load-balancer compatible.
  The handler must never take a device or hub lock: a wedged engine
  must not wedge the probe that exists to detect it (enforced by the
  datlint obs-discipline healthz check);
* ``GET /events``   — bounded JSONL tail of the structured event ring
  (``?n=`` caps the tail, default 256).

Zero dependencies, pull-based, no coordination: replicas export, an
aggregator (:mod:`.fleet`) joins — "Simplicity Scales".
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from . import device as _device
from .events import EVENTS as _EVENTS
from .flight import FLIGHT as _FLIGHT
from .metrics import OBS as _OBS, REGISTRY as _REGISTRY, to_prom_text
from .watermarks import WATERMARKS as _WATERMARKS

__all__ = ["ObsHttpServer", "default_snapshot", "default_healthz",
           "DEFAULT_EVENTS_TAIL"]

DEFAULT_EVENTS_TAIL = 256
_MAX_EVENTS_TAIL = 4096


def default_snapshot() -> dict:
    """The core stats record for processes that are not the sidecar
    (bench legs, embedded fleets): registry + device sentinel +
    watermarks + ring health.  The sidecar passes its richer
    ``snapshot_stats`` (same shape plus hub/fanout breakdowns)."""
    return {
        "ts": time.time(),
        "monotonic": time.monotonic(),
        "metrics": _REGISTRY.snapshot(),
        "events_dropped": _EVENTS.dropped,
        "jit_sites": _device.SENTINEL.snapshot(),
        "watermarks": _WATERMARKS.snapshot(),
    }


def default_healthz(admission_fn: Optional[Callable[[], dict]] = None
                    ) -> dict:
    """Staged health record (ROBUSTNESS.md: the stages mirror the
    staged-overload contract — each one names the FIRST line of defense
    that is currently degraded, not a single opaque boolean).

    Lock discipline: everything read here is either a plain attribute
    (``OBS.on``, ``FLIGHT.armed``), the event ring (its own ring lock,
    never a device or hub lock), or ``admission_fn`` — which owners
    must implement lock-free (``ReplicationHub.admission_state`` is
    the reference).  The datlint obs-discipline healthz check enforces
    the no-device/hub-lock half mechanically on this module."""
    stages: dict = {}
    ok = True
    # stage 1: backend init — stuck beats done beats in-progress
    stuck = _EVENTS.last("backend.init.stuck")
    done = _EVENTS.last("backend.init.done")
    stage = _EVENTS.last("backend.init.stage")
    if stuck is not None and (done is None
                              or stuck["seq"] > done["seq"]):
        stages["backend_init"] = {"ok": False, "state": "stuck",
                                  **stuck.get("fields", {})}
        ok = False
    elif done is not None:
        stages["backend_init"] = {"ok": True, "state": "done",
                                  **done.get("fields", {})}
    elif stage is not None:
        stages["backend_init"] = {"ok": True, "state": "in-progress",
                                  **stage.get("fields", {})}
    else:
        # no watchdog ran: host-only process, nothing to report
        stages["backend_init"] = {"ok": True, "state": "idle"}
    # stage 2: admission (hub/fanout owners install the callable)
    if admission_fn is not None:
        try:
            # the admission_state contract (datlint healthz check):
            # lock-free attribute reads only — a health probe must
            # never block behind an engine lock
            # datlint: allow-callback-escape
            adm = admission_fn()
        except Exception as e:
            adm = {"open": False, "error": f"{type(e).__name__}: {e}"}
        stages["admission"] = {"ok": bool(adm.get("open")), **adm}
        ok = ok and bool(adm.get("open"))
    # stage 3: event-loop lag (ISSUE 18) — a loop that has fallen
    # behind its tick is degraded the same way a closed admission gate
    # is: the flight deck's live lag view, plain attribute reads off
    # each loop's profiler (lock-free, at worst one turn stale).  Dark
    # loops (gate off) report state only — a stale zero must not read
    # as healthy OR degraded
    loops = _WATERMARKS.loops_now()
    if loops:
        behind = sorted(name for name, rec in loops.items()
                        if rec.get("state") == "live"
                        and rec.get("behind"))
        lag = {name: rec.get("lag_s", 0.0) for name, rec in
               loops.items() if rec.get("state") == "live"}
        stages["loop_lag"] = {"ok": not behind, "behind": behind,
                              "lag_s": lag}
        ok = ok and not behind
    # stage 4: observability itself (armed recorder, live gate)
    stages["flight_recorder"] = {"ok": True, "armed": _FLIGHT.armed}
    stages["obs_gate"] = {"ok": True, "on": _OBS.on}
    return {"ok": ok, "stages": stages, "ts": time.time(),
            "monotonic": time.monotonic()}


class _Handler(BaseHTTPRequestHandler):
    # set per-server via the factory in ObsHttpServer
    server_version = "dat-obs/1"
    protocol_version = "HTTP/1.1"
    # bounded per-connection reads (the bounded-wait doctrine): a
    # half-open scraper that connects and never sends a request line —
    # or parks an idle keep-alive — must release its handler thread
    # instead of pinning one forever
    timeout = 30.0

    def log_message(self, fmt, *args):  # stderr chatter off the hot path
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # a vanished scraper is its own problem

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        try:
            url = urlparse(self.path)
            route = url.path.rstrip("/") or "/"
            if route == "/metrics":
                body = to_prom_text().encode("utf-8")
                self._send(200, body, "text/plain; version=0.0.4")
            elif route == "/snapshot":
                snap = self.server.obs_snapshot_fn()  # type: ignore[attr-defined]
                body = (json.dumps(snap, default=repr) + "\n").encode()
                self._send(200, body, "application/json")
            elif route == "/healthz":
                hz = self._healthz()
                body = (json.dumps(hz, default=repr) + "\n").encode()
                self._send(200 if hz.get("ok") else 503, body,
                           "application/json")
            elif route == "/events":
                n = DEFAULT_EVENTS_TAIL
                q = parse_qs(url.query)
                if "n" in q:
                    try:
                        n = max(1, min(_MAX_EVENTS_TAIL, int(q["n"][0])))
                    except ValueError:
                        pass
                tail = _EVENTS.events()[-n:]
                body = "".join(
                    json.dumps(r, default=repr) + "\n" for r in tail
                ).encode("utf-8")
                self._send(200, body, "application/x-ndjson")
            else:
                self._send(404, b'{"error": "unknown route"}\n',
                           "application/json")
        except Exception as e:  # a broken route must not kill the thread
            try:
                self._send(500, (json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}) + "\n").encode(),
                    "application/json")
            except Exception:
                pass

    def _healthz(self) -> dict:
        """The liveness route.  READ-ONLY, lock-discipline-checked:
        nothing in this method (or the default it delegates to) may
        take a device or hub lock — see module docstring."""
        fn = self.server.obs_healthz_fn  # type: ignore[attr-defined]
        return fn()


class ObsHttpServer:
    """The ``--obs-http`` endpoint: bind, serve on a daemon thread,
    close.  ``port=0`` binds an ephemeral port (tests); the bound port
    is ``self.port`` after construction."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 healthz_fn: Optional[Callable[[], dict]] = None,
                 admission_fn: Optional[Callable[[], dict]] = None):
        if healthz_fn is None:
            healthz_fn = lambda: default_healthz(admission_fn)  # noqa: E731
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        # handler plumbing rides the server object (stdlib idiom: the
        # handler sees it as self.server)
        self._srv.obs_snapshot_fn = snapshot_fn or default_snapshot
        self._srv.obs_healthz_fn = healthz_fn
        self.host, self.port = self._srv.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ObsHttpServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="obs-http", daemon=True,
            kwargs={"poll_interval": 0.1})
        self._thread.start()
        return self

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "ObsHttpServer":
        return self.start() if self._thread is None else self

    def __exit__(self, *exc) -> None:
        self.close()
