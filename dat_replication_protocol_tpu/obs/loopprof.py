"""The event-loop flight deck: per-turn phase accounting, loop-lag
watermarks, and a sampling turn profiler for the edge plane (ISSUE 18).

PR 17's :class:`~..edge.loop.EdgeLoop` is the C10k control plane; the
``event_loop_surface.json`` certificate proves *statically* that every
call its dispatcher inlines is bounded.  This module is the dynamic
half of the same discipline: it measures what each turn actually spent,
turn by turn, phase by phase, and exports the one number that tells an
operator whether the loop is keeping up — **loop lag**.

Three planes, one writer
------------------------

Every mutating call below is made from the loop's own thread; readers
(``/healthz``, the registry collector, the fleet poller) take plain
attribute reads that are at worst one turn stale — the same lock-free
snapshot contract as :meth:`EdgeLoop.admission_state`.

* **Phase accounting** — each lit turn is split into the loop's six
  phases (:data:`PHASES`): poll-wait, accept, read, hub-drain, tx, and
  the overload ladder (rejection/shed/teardown work).  Per-phase
  seconds feed fixed-bucket histograms (``edge.turn.*_s``) and
  change-only ``edge.turn`` spans in the PR 4 SpanLog, so a loop turn
  renders as one box in the Chrome-trace export.  Idle turns (the
  selector timed out and nothing happened) coalesce into the NEXT
  active span — consecutive recorded spans tile the loop's wall time
  exactly: ``span[i+1].ts == span[i].ts + span[i].dur``.

* **Loop lag** — a turn's lag is its non-poll work beyond one tick of
  grace: ``max(0.0, work_s - tick)``.  The selector's timeout is the
  loop's sanctioned wait, so a healthy turn — microseconds of work —
  clamps to *exactly* ``0.0``, while a turn that stalls reads the
  overrun directly.  The live view extrapolates mid-turn (a probe
  during a stall sees the lag growing, not the last clean turn), and
  ``oldest_ready_s`` ages the readiness batch the loop is still
  working through.  Exported through the PR 11
  :class:`~.watermarks.WatermarkBoard` as ``edge.loop.lag{loop=}``
  gauges and the ``loops`` snapshot section the fleet plane joins.

* **Turn profiler** — every ``sample_every``-th active turn (and
  EVERY turn whose lag is positive — a stall is always attributed)
  captures the top-K heaviest sessions by callback seconds and bytes
  moved, keyed by the existing session keys.  The capture rides the
  span's ``top`` field; ``obs loopdoctor`` turns it into a stall
  attribution.

Hot-path budget: the dark path is ONE attribute load — the dispatcher
forks on ``OBS.on`` per turn and the dark twin never touches this
module (the PR 3 contract, enforced by a bytecode test).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from .metrics import OBS as _OBS, counter as _counter, \
    histogram as _histogram
from .tracing import SPANS as _SPANS, _span_ids

__all__ = ["LoopProfiler", "PHASES", "SAMPLE_EVERY", "TOP_K"]

# the loop's phase vocabulary — string literals at every accounting
# call site (the obs-discipline greppability contract; datlint enforces
# literal first args on prof.phase/prof.account)
PHASES = ("poll-wait", "accept", "read", "hub-drain", "tx",
          "overload-ladder")

# profiler sampling default: one active turn in 32 carries a top-K
# capture; overrun turns (lag > 0) always do
SAMPLE_EVERY = 32
TOP_K = 3

# per-loop work ring for the local p99 (bench config 15 reads it
# without sharing the process-global histogram across runs)
_WORK_RING = 512

# a loop is "behind its tick" for /healthz once its live lag exceeds
# half a tick beyond the one-tick grace already inside the lag formula
# (total: >1.5 ticks of non-poll work) — the margin keeps a single
# 1ms overrun from flapping the probe
_BEHIND_FRACTION = 0.5

_H_POLL = _histogram("edge.turn.poll_wait_s")
_H_ACCEPT = _histogram("edge.turn.accept_s")
_H_READ = _histogram("edge.turn.read_s")
_H_HUB = _histogram("edge.turn.hub_drain_s")
_H_TX = _histogram("edge.turn.tx_s")
_H_OVERLOAD = _histogram("edge.turn.overload_ladder_s")
_H_WORK = _histogram("edge.turn.work_s")
_M_TURNS = _counter("edge.loop.turns")

_PHASE_HIST = {
    "accept": _H_ACCEPT,
    "read": _H_READ,
    "hub-drain": _H_HUB,
    "tx": _H_TX,
    "overload-ladder": _H_OVERLOAD,
}


def _quantile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class LoopProfiler:
    """One per :class:`EdgeLoop`; every mutator runs on the loop
    thread (single-writer, lock-free readers — see module docstring).

    Turn protocol, called by the lit dispatcher::

        prof.turn_begin(t0)          # before select()
        prof.poll_done(t1, nready)   # select() returned
        prof.phase("accept", dt)     # un-attributed phase work
        prof.account("read", key, dt, nbytes)  # per-session phase work
        prof.turn_done(t2, sessions=len(table))
    """

    def __init__(self, name: str, *, tick: float,
                 sample_every: int = SAMPLE_EVERY,
                 top_k: int = TOP_K) -> None:
        self.name = name
        self.tick = float(tick)
        self.sample_every = max(1, int(sample_every))
        self.top_k = max(1, int(top_k))
        # lock-free reader surface (plain attributes, one turn stale)
        self.turns = 0
        self.active_turns = 0
        self.lag_s = 0.0
        self.lag_max_s = 0.0
        self.in_work = False
        self.running = False
        # turn-in-progress state (loop thread only)
        self._t0 = 0.0            # turn start (before select)
        self._work_t0 = 0.0       # select returned; work begins
        self._poll_s = 0.0
        self._ready_since: Optional[float] = None
        self._phases: dict[str, float] = {}
        self._sessions: dict[str, list] = {}
        # change-only span tiling state
        self._anchor: Optional[float] = None
        self._idle_turns = 0
        self._idle_poll_s = 0.0
        self._work_ring: deque = deque(maxlen=_WORK_RING)

    # -- registration --------------------------------------------------------

    def attach(self) -> None:
        """Register this loop on the watermark board (serve start)."""
        from .watermarks import WATERMARKS
        self.running = True
        WATERMARKS.track_loop(self.name, self.export)

    def detach(self, now: Optional[float] = None) -> None:
        """Flush the trailing idle span and leave the board
        (loop shutdown).  Idempotent."""
        from .watermarks import WATERMARKS
        self.running = False
        self.flush(time.monotonic() if now is None else now)
        WATERMARKS.untrack_loop(self.name)

    # -- the turn protocol (loop thread only) --------------------------------

    def turn_begin(self, t0: float) -> None:
        self._t0 = t0
        if self._anchor is None:
            self._anchor = t0

    def poll_done(self, t_poll: float, nready: int) -> None:
        self._poll_s = max(0.0, t_poll - self._t0)
        self._work_t0 = t_poll
        self._ready_since = t_poll if nready else None
        self.in_work = True

    def phase(self, name: str, seconds: float) -> None:
        """Accumulate un-attributed phase work for this turn.  ``name``
        is a :data:`PHASES` literal at the call site."""
        self._phases[name] = self._phases.get(name, 0.0) + seconds

    def account(self, name: str, session: str, seconds: float,
                nbytes: int) -> None:
        """Accumulate phase work attributed to one session (the
        profiler's top-K source).  ``name`` is a :data:`PHASES` literal
        at the call site; ``session`` is the table's session key."""
        self._phases[name] = self._phases.get(name, 0.0) + seconds
        ent = self._sessions.get(session)
        if ent is None:
            ent = self._sessions[session] = [0.0, 0, {}]
        ent[0] += seconds
        ent[1] += int(nbytes)
        ent[2][name] = ent[2].get(name, 0.0) + seconds

    def turn_done(self, t_end: float, sessions: int = 0) -> None:
        """Close the turn: histograms, lag, the change-only span."""
        self.turns += 1
        _M_TURNS.inc()
        phases = self._phases
        poll_s = self._poll_s
        work_s = max(0.0, t_end - self._work_t0)
        lag = max(0.0, work_s - self.tick)
        self.lag_s = lag
        if lag > self.lag_max_s:
            self.lag_max_s = lag
        self.in_work = False
        self._ready_since = None
        _H_POLL.observe(poll_s)
        _H_WORK.observe(work_s)
        for name, sec in phases.items():
            h = _PHASE_HIST.get(name)
            if h is not None and sec > 0.0:
                h.observe(sec)
        active = lag > 0.0 or bool(phases) or bool(self._sessions)
        if not active:
            # idle turn: coalesce into the NEXT active span so the
            # recorded spans still tile wall time exactly
            self._idle_turns += 1
            self._idle_poll_s += poll_s
            return
        self.active_turns += 1
        self._work_ring.append(work_s)
        fields = {
            "loop": self.name,
            "tick": self.tick,
            "turns": self._idle_turns + 1,
            "sessions": sessions,
            "poll_wait_s": round(self._idle_poll_s + poll_s, 9),
            "work_s": round(work_s, 9),
            "lag_s": round(lag, 9),
        }
        for name in PHASES[1:]:
            fields[name.replace("-", "_") + "_s"] = round(
                phases.get(name, 0.0), 9)
        if lag > 0.0 or self.active_turns % self.sample_every == 0:
            fields["top"] = self._top()
        anchor = self._anchor if self._anchor is not None else self._t0
        _SPANS.record("edge.turn", anchor, t_end - anchor,
                      next(_span_ids), None, threading.get_ident(),
                      fields)
        self._anchor = t_end
        self._idle_turns = 0
        self._idle_poll_s = 0.0
        self._phases = {}
        self._sessions = {}

    def flush(self, now: float) -> None:
        """Record the trailing idle span (shutdown): coverage runs to
        the loop's last turn even when it ended quiet."""
        if self._anchor is None or not self._idle_turns:
            return
        _SPANS.record("edge.turn", self._anchor,
                      max(0.0, now - self._anchor), next(_span_ids),
                      None, threading.get_ident(),
                      {"loop": self.name, "tick": self.tick,
                       "turns": self._idle_turns, "sessions": 0,
                       "poll_wait_s": round(self._idle_poll_s, 9),
                       "work_s": 0.0, "lag_s": 0.0})
        self._anchor = now
        self._idle_turns = 0
        self._idle_poll_s = 0.0

    def _top(self) -> list:
        ranked = sorted(self._sessions.items(),
                        key=lambda kv: (kv[1][0], kv[1][1]),
                        reverse=True)[:self.top_k]
        out = []
        for key, (sec, nbytes, by_phase) in ranked:
            phase = max(by_phase.items(), key=lambda kv: kv[1])[0] \
                if by_phase else "read"
            out.append({"session": key, "seconds": round(sec, 9),
                        "bytes": nbytes, "phase": phase})
        return out

    # -- reader surface ------------------------------------------------------

    def live_lag(self, now: Optional[float] = None) -> float:
        """Current lag, extrapolated mid-turn: a probe during a stall
        sees the overrun growing.  Lock-free (any thread)."""
        lag = self.lag_s
        if self.in_work:
            t = time.monotonic() if now is None else now
            lag = max(lag, (t - self._work_t0) - self.tick)
        return max(0.0, lag)

    def oldest_ready_s(self, now: Optional[float] = None) -> float:
        """Age of the oldest ready session the loop has not finished
        dispatching this turn (0.0 between turns)."""
        since = self._ready_since
        if since is None or not self.in_work:
            return 0.0
        t = time.monotonic() if now is None else now
        return max(0.0, t - since)

    def p99_work_s(self) -> float:
        return _quantile(sorted(self._work_ring), 0.99)

    def export(self) -> dict:
        """The watermark-board record (``loops`` snapshot section and
        the ``edge.loop.*`` gauges).  ``state: dark`` flags a loop
        whose gate is off — the fleet gate fails LOUDLY on it instead
        of trusting stale zeros."""
        now = time.monotonic()
        live = self.live_lag(now)
        return {
            "state": "live" if _OBS.on else "dark",
            "tick": self.tick,
            "turns": self.turns,
            "active_turns": self.active_turns,
            "lag_s": round(live, 9),
            "lag_max_s": round(self.lag_max_s, 9),
            "oldest_ready_s": round(self.oldest_ready_s(now), 9),
            "behind": live > _BEHIND_FRACTION * self.tick,
        }

    def state(self) -> dict:
        """Loop-local summary for ``EdgeLoop.snapshot()`` and bench
        config 15 (per-loop p99 without the process-global ring)."""
        return {
            "name": self.name,
            "turns": self.turns,
            "active_turns": self.active_turns,
            "lag_s": round(self.lag_s, 9),
            "lag_max_s": round(self.lag_max_s, 9),
            "p99_work_s": round(self.p99_work_s(), 9),
            "tick": self.tick,
        }
