"""The ONE lazy gate to the ``dat_fastpath`` C extension.

Both hot-path consumers (``wire.change_codec`` serialization and
``session.decoder`` bulk dispatch) must route through
:func:`.runtime.fastpath.get` so the ``DAT_FASTPATH_DISABLE`` decision
is made in exactly one place, re-read per call (the round-5 split-brain
had two private caches freeze the decision independently).  Neither
consumer can import ``runtime.fastpath`` at module load — the
``runtime -> replay -> change_codec`` import cycle — so this module
holds the shared lazy binding instead of each keeping its own copy:
two independent wrappers are precisely the drift surface that produced
the split-brain, and datlint's env-cache-policy rule cannot see a fork
that never touches ``os.environ`` itself.

Only the bound ``get`` FUNCTION is cached here (a per-call
``from .runtime import`` costs ~1.8us of import machinery — real money
next to a ~4us encode); the env decision stays inside ``get``.
"""

from __future__ import annotations

_get = None  # lazily-bound runtime.fastpath.get (import cycle)


def fastpath_mod():
    """The dat_fastpath C extension module, or ``None`` (missing
    toolchain, or ``DAT_FASTPATH_DISABLE`` set — re-read every call so
    tests can exercise both implementations in one process)."""
    global _get
    get = _get
    if get is None:
        from .runtime import fastpath

        get = _get = fastpath.get
    return get()
