// Native runtime for dat_replication_protocol_tpu: the host-side hot loops.
//
// The reference's hot receive path is a byte-at-a-time varint scan and
// per-frame dispatch in JS (reference: decode.js:144-169, 251-262).  The
// TPU-native framework needs the same parsing at change-log-replay scale
// (BASELINE.json config 2: 1M-row replay) where per-record Python costs
// ~1us each; this translation unit provides the two tight loops behind a
// plain C ABI (loaded via ctypes — no pybind11 in the image):
//
//   dat_split_frames    multibuffer framing: varint(len+1) | id | payload
//   dat_decode_changes  proto2 `Change` records -> columnar arrays
//                       (zero-copy: strings/bytes become (offset, len)
//                       views into the log buffer — the layout the device
//                       feed packs from directly)
//
// Build: g++ -O3 -shared -fPIC (runtime/native.py does this on demand and
// caches the .so; every entry point has a pure-Python fallback).

#include <cstdint>
#include <cstddef>
#include <cstring>
#include <new>

namespace {

// Decode one unsigned LEB128 varint at buf[i..len).  Returns the number of
// bytes consumed (0 = truncated, -1 = overlong/>10 bytes).
// The 10-byte cap is the wire limit shared with wire/varint.py; datlint's
// wire-constant-parity rule cross-checks it:  // wire: MAX_VARINT_LEN = 10
inline int read_uvarint(const uint8_t* buf, int64_t i, int64_t len,
                        uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  for (int k = 0; k < 10; ++k) {
    if (i + k >= len) return 0;
    uint8_t b = buf[i + k];
    // 10th byte may only contribute bit 63: anything else encodes a
    // value >= 2^64 (overlong — matches the Python decoder's rejection).
    if (k == 9 && (b & 0x7F) > 1) return -1;
    v |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return k + 1;
    }
    shift += 7;
  }
  return -1;
}

}  // namespace

extern "C" {

// Error codes shared by both entry points.
enum {
  DAT_ERR_TRUNCATED = -1,
  DAT_ERR_CAPACITY = -2,
  DAT_ERR_BAD_VARINT = -3,
  DAT_ERR_BAD_RECORD = -4,
  DAT_ERR_NOMEM = -5,
};

// Split a multibuffer stream into frames.
//
// Returns the count of complete valid frames (<= cap) and fills, per
// frame:
//   starts[f]  byte offset of the payload (after the id byte)
//   lens[f]    payload length (framed length minus the id byte)
//   ids[f]     the 1-byte type id (unvalidated; policy lives above)
// `consumed` gets the offset one past the last complete frame (a partial
// trailing frame is not an error — streaming callers re-feed the tail).
// A malformed header (overlong varint / zero framed length) STOPS the
// scan at that frame: the valid prefix is still returned and `err` gets
// the error code (0 otherwise), so a streaming caller can deliver the
// prefix and surface the error at exactly the offending frame — the same
// observable order as the byte-at-a-time scanner.  Only a capacity
// overflow (caller bug) is a negative return.
int64_t dat_split_frames(const uint8_t* buf, int64_t len, int64_t* starts,
                         int64_t* lens, uint8_t* ids, int64_t cap,
                         int64_t* consumed, int64_t* err) {
  int64_t i = 0;
  int64_t n = 0;
  *consumed = 0;
  *err = 0;
  while (i < len) {
    uint64_t framed;
    int used = read_uvarint(buf, i, len, &framed);
    if (used == 0) break;  // partial header at tail
    if (used < 0) {
      *err = DAT_ERR_BAD_VARINT;
      break;
    }
    if (framed == 0) {  // must include the id byte
      *err = DAT_ERR_BAD_RECORD;
      break;
    }
    // Unsigned compare BEFORE any int64 cast: a hostile length >= 2^63
    // must not wrap negative and walk the cursor backwards.  Anything
    // larger than the bytes on hand is a partial tail (streaming callers
    // re-feed), matching the Python fallback's NeedMoreData behavior.
    uint64_t remaining = static_cast<uint64_t>(len - i) - used;
    if (framed > remaining) break;  // partial frame at tail
    int64_t payload = static_cast<int64_t>(framed) - 1;
    int64_t frame_end = i + used + 1 + payload;
    if (n >= cap) return DAT_ERR_CAPACITY;
    ids[n] = buf[i + used];
    starts[n] = i + used + 1;
    lens[n] = payload;
    ++n;
    i = frame_end;
    *consumed = i;
  }
  return n;
}

// Greedy min/max chunk-size pass over sorted candidate byte offsets (the
// sequential tail of content-defined chunking; ops/rabin.py documents the
// algorithm).  Writes chunk end-offsets (exclusive), always ending with
// `length`.  Returns the cut count, or DAT_ERR_CAPACITY.
int64_t dat_greedy_select(const int64_t* cands, int64_t n, int64_t length,
                          int64_t min_size, int64_t max_size, int64_t* out,
                          int64_t cap) {
  int64_t start = 0, i = 0, m = 0;
  while (length - start > max_size) {
    int64_t lo = start + min_size;
    int64_t hi = start + max_size;
    while (i < n && cands[i] < lo) ++i;
    int64_t cut;
    if (i < n && cands[i] <= hi) {
      cut = cands[i];
      ++i;
    } else {
      cut = hi;
    }
    if (m >= cap) return DAT_ERR_CAPACITY;
    out[m++] = cut;
    start = cut;
  }
  if (m >= cap) return DAT_ERR_CAPACITY;
  out[m++] = length;
  return m;
}

// Proto2 tags for the Change message (reference: messages/schema.proto:1-8).
enum {
  TAG_SUBSET = (1 << 3) | 2,
  TAG_KEY = (2 << 3) | 2,
  TAG_CHANGE = (3 << 3) | 0,
  TAG_FROM = (4 << 3) | 0,
  TAG_TO = (5 << 3) | 0,
  TAG_VALUE = (6 << 3) | 2,
};

// Decode Change payloads [lo, hi) into columnar arrays; returns the index
// of the first corrupt record in the range, or -1 if all decode.  The
// rows are independent, so ranges parallelize (dat_decode_changes_mt).
static int64_t decode_changes_range(
    const uint8_t* buf, const int64_t* starts, const int64_t* lens,
    int64_t lo, int64_t hi, uint32_t* change, uint32_t* from_v,
    uint32_t* to_v, int64_t* key_off, int64_t* key_len, int64_t* sub_off,
    int64_t* sub_len, int64_t* val_off, int64_t* val_len) {
  for (int64_t r = lo; r < hi; ++r) {
    int64_t i = starts[r];
    const int64_t end = i + lens[r];
    bool has_key = false, has_change = false, has_from = false, has_to = false;
    sub_len[r] = -1;
    val_len[r] = -1;
    sub_off[r] = 0;
    val_off[r] = 0;
    while (i < end) {
      uint64_t tag;
      int used = read_uvarint(buf, i, end, &tag);
      if (used <= 0) goto bad;
      i += used;
      switch (tag & 7) {
        case 0: {  // varint
          uint64_t v;
          used = read_uvarint(buf, i, end, &v);
          if (used <= 0) goto bad;
          i += used;
          if (tag == TAG_CHANGE) {
            change[r] = static_cast<uint32_t>(v);
            has_change = true;
          } else if (tag == TAG_FROM) {
            from_v[r] = static_cast<uint32_t>(v);
            has_from = true;
          } else if (tag == TAG_TO) {
            to_v[r] = static_cast<uint32_t>(v);
            has_to = true;
          }
          break;
        }
        case 2: {  // length-delimited
          uint64_t ln;
          used = read_uvarint(buf, i, end, &ln);
          if (used <= 0) goto bad;
          i += used;
          // Unsigned compare before the cast: ln >= 2^63 would go
          // negative as int64 and slip past the bounds check below.
          if (ln > static_cast<uint64_t>(end - i)) goto bad;
          if (tag == TAG_SUBSET) {
            sub_off[r] = i;
            sub_len[r] = static_cast<int64_t>(ln);
          } else if (tag == TAG_KEY) {
            key_off[r] = i;
            key_len[r] = static_cast<int64_t>(ln);
            has_key = true;
          } else if (tag == TAG_VALUE) {
            val_off[r] = i;
            val_len[r] = static_cast<int64_t>(ln);
          }
          i += static_cast<int64_t>(ln);
          break;
        }
        case 5:  // fixed32 (unknown field)
          if (i + 4 > end) goto bad;
          i += 4;
          break;
        case 1:  // fixed64 (unknown field)
          if (i + 8 > end) goto bad;
          i += 8;
          break;
        default:
          goto bad;
      }
    }
    if (!has_key || !has_change || !has_from || !has_to) goto bad;
    continue;
  bad:
    return r;
  }
  return -1;
}

// Decode n Change payloads into columnar arrays (serial entry point).
//
// Absent optional fields get len -1 (host maps to ''/b'').  Unknown fields
// are skipped per proto2.  Returns 0, or a negative error with err_index
// set to the offending record.
int64_t dat_decode_changes(const uint8_t* buf, const int64_t* starts,
                           const int64_t* lens, int64_t n, uint32_t* change,
                           uint32_t* from_v, uint32_t* to_v, int64_t* key_off,
                           int64_t* key_len, int64_t* sub_off,
                           int64_t* sub_len, int64_t* val_off,
                           int64_t* val_len, int64_t* err_index) {
  int64_t bad = decode_changes_range(buf, starts, lens, 0, n, change, from_v,
                                     to_v, key_off, key_len, sub_off, sub_len,
                                     val_off, val_len);
  if (bad >= 0) {
    *err_index = bad;
    return DAT_ERR_BAD_RECORD;
  }
  return 0;
}

}  // extern "C"

namespace {

inline int uvarint_size(uint64_t v) {
  int n = 1;
  while (v >= 0x80) {
    v >>= 7;
    ++n;
  }
  return n;
}

inline int64_t write_uvarint(uint8_t* dst, int64_t i, uint64_t v) {
  while (v >= 0x80) {
    dst[i++] = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  dst[i++] = static_cast<uint8_t>(v);
  return i;
}

// proto payload size of record r (fields in ascending field-number order,
// absent optionals omitted) — shared by the serial and parallel encoders.
inline int64_t change_payload_size(int64_t r, const uint32_t* change,
                                   const uint32_t* from_v,
                                   const uint32_t* to_v,
                                   const int64_t* key_len,
                                   const int64_t* sub_len,
                                   const int64_t* val_len) {
  int64_t psize = 0;
  if (sub_len[r] >= 0) psize += 1 + uvarint_size(sub_len[r]) + sub_len[r];
  psize += 1 + uvarint_size(key_len[r]) + key_len[r];
  psize += 1 + uvarint_size(change[r]);
  psize += 1 + uvarint_size(from_v[r]);
  psize += 1 + uvarint_size(to_v[r]);
  if (val_len[r] >= 0) psize += 1 + uvarint_size(val_len[r]) + val_len[r];
  return psize;
}

// Encode record r's full frame at dst[w]; returns the new write offset.
// TAG_* come from the file-scope enum shared with the decoder.
int64_t encode_change_at(const uint8_t* src, int64_t r, int64_t psize,
                         const uint32_t* change, const uint32_t* from_v,
                         const uint32_t* to_v, const int64_t* key_off,
                         const int64_t* key_len, const int64_t* sub_off,
                         const int64_t* sub_len, const int64_t* val_off,
                         const int64_t* val_len, uint8_t* dst, int64_t w) {
  w = write_uvarint(dst, w, psize + 1);
  dst[w++] = 1;  // TYPE_CHANGE
  if (sub_len[r] >= 0) {
    dst[w++] = TAG_SUBSET;
    w = write_uvarint(dst, w, sub_len[r]);
    for (int64_t k = 0; k < sub_len[r]; ++k) dst[w + k] = src[sub_off[r] + k];
    w += sub_len[r];
  }
  dst[w++] = TAG_KEY;
  w = write_uvarint(dst, w, key_len[r]);
  for (int64_t k = 0; k < key_len[r]; ++k) dst[w + k] = src[key_off[r] + k];
  w += key_len[r];
  dst[w++] = TAG_CHANGE;
  w = write_uvarint(dst, w, change[r]);
  dst[w++] = TAG_FROM;
  w = write_uvarint(dst, w, from_v[r]);
  dst[w++] = TAG_TO;
  w = write_uvarint(dst, w, to_v[r]);
  if (val_len[r] >= 0) {
    dst[w++] = TAG_VALUE;
    w = write_uvarint(dst, w, val_len[r]);
    for (int64_t k = 0; k < val_len[r]; ++k) dst[w + k] = src[val_off[r] + k];
    w += val_len[r];
  }
  return w;
}

}  // namespace

extern "C" {

// Bulk-encode n Change records (columnar, offsets into `src`) as framed
// wire bytes: varint(len+1) | 0x01 | proto payload, fields in ascending
// field-number order matching the Python encoder (wire/change_codec.py).
// sub_len/val_len -1 = absent optional.  Returns bytes written into
// `dst` (capacity `cap`), or DAT_ERR_CAPACITY.
int64_t dat_encode_changes(const uint8_t* src, int64_t n,
                           const uint32_t* change, const uint32_t* from_v,
                           const uint32_t* to_v, const int64_t* key_off,
                           const int64_t* key_len, const int64_t* sub_off,
                           const int64_t* sub_len, const int64_t* val_off,
                           const int64_t* val_len, uint8_t* dst,
                           int64_t cap) {
  int64_t w = 0;
  for (int64_t r = 0; r < n; ++r) {
    int64_t psize = change_payload_size(r, change, from_v, to_v, key_len,
                                        sub_len, val_len);
    int64_t need = uvarint_size(psize + 1) + 1 + psize;
    if (w + need > cap) return DAT_ERR_CAPACITY;
    w = encode_change_at(src, r, psize, change, from_v, to_v, key_off,
                         key_len, sub_off, sub_len, val_off, val_len, dst, w);
  }
  return w;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Columnar ChangeBatch payload encoder (wire/batch_codec.py documents the
// layout; the frame rides type id  // wire: TYPE_CHANGE_BATCH = 3
// and is only emitted to peers advertising the capability).  The per-row
// work the Python tier cannot vectorize is the dictionary build — dedup of key /
// subset byte spans — so that is what lives here: an open-addressing
// FNV-1a span hash, first-appearance order, then one sequential pass
// writing every section.  Decode needs no C at all (pure array
// reinterpretation on the host side).
// ---------------------------------------------------------------------------

namespace {

constexpr int BATCH_VERSION = 1;  // wire: BATCH_VERSION = 1

inline uint64_t span_hash(const uint8_t* p, int64_t len) {
  uint64_t h = 1469598103934665603ULL;  // FNV-1a 64
  for (int64_t i = 0; i < len; ++i) {
    h ^= p[i];
    h *= 1099511628211ULL;
  }
  return h | 1;  // 0 marks an empty slot
}

// Open-addressing span dictionary over (off, len) extents of one buffer.
// Insert returns the span's first-appearance index.
struct SpanDict {
  const uint8_t* src;
  int64_t cap = 0;      // power of two
  uint64_t* hashes = nullptr;
  int64_t* slots = nullptr;   // slot -> unique index
  int64_t* u_off = nullptr;   // unique -> span
  int64_t* u_len = nullptr;
  int64_t count = 0;

  bool init(const uint8_t* s, int64_t max_entries) {
    src = s;
    cap = 16;
    while (cap < max_entries * 2) cap <<= 1;
    hashes = new (std::nothrow) uint64_t[cap]();
    slots = new (std::nothrow) int64_t[cap];
    u_off = new (std::nothrow) int64_t[max_entries > 0 ? max_entries : 1];
    u_len = new (std::nothrow) int64_t[max_entries > 0 ? max_entries : 1];
    return hashes != nullptr && slots != nullptr && u_off != nullptr &&
           u_len != nullptr;
  }
  ~SpanDict() {
    delete[] hashes;
    delete[] slots;
    delete[] u_off;
    delete[] u_len;
  }
  int64_t insert(int64_t off, int64_t len) {
    uint64_t h = span_hash(src + off, len);
    int64_t i = static_cast<int64_t>(h) & (cap - 1);
    while (true) {
      if (hashes[i] == 0) {
        hashes[i] = h;
        slots[i] = count;
        u_off[count] = off;
        u_len[count] = len;
        return count++;
      }
      if (hashes[i] == h) {
        int64_t u = slots[i];
        if (u_len[u] == len && std::memcmp(src + u_off[u], src + off,
                                           static_cast<size_t>(len)) == 0)
          return u;
      }
      i = (i + 1) & (cap - 1);
    }
  }
};

inline int batch_width(int64_t max_value) {
  // smallest width whose all-ones value strictly exceeds max_value (the
  // all-ones sentinel must stay unambiguous) — mirrors _pick_width
  if (max_value < 0xFF) return 1;
  if (max_value < 0xFFFF) return 2;
  return 4;
}

inline int64_t put_le(uint8_t* dst, int64_t w, uint64_t v, int width) {
  for (int k = 0; k < width; ++k) {
    dst[w + k] = static_cast<uint8_t>(v >> (8 * k));
  }
  return w + width;
}

}  // namespace

extern "C" {

// Encode n records (columnar spans over `src`, the ChangeColumns
// layout; sub_len/val_len -1 = absent) as ONE ChangeBatch payload into
// dst.  Returns payload bytes written, DAT_ERR_CAPACITY if cap is too
// small, or DAT_ERR_NOMEM.
int64_t dat_encode_change_batch(const uint8_t* src, int64_t n,
                                const uint32_t* change,
                                const uint32_t* from_v, const uint32_t* to_v,
                                const int64_t* key_off,
                                const int64_t* key_len,
                                const int64_t* sub_off,
                                const int64_t* sub_len,
                                const int64_t* val_off,
                                const int64_t* val_len, uint8_t* dst,
                                int64_t cap) {
  SpanDict keys, subs;
  if (!keys.init(src, n) || !subs.init(src, n)) return DAT_ERR_NOMEM;
  int64_t* kidx = new (std::nothrow) int64_t[n > 0 ? n : 1];
  int64_t* sidx = new (std::nothrow) int64_t[n > 0 ? n : 1];
  if (kidx == nullptr || sidx == nullptr) {
    delete[] kidx;
    delete[] sidx;
    return DAT_ERR_NOMEM;
  }
  int64_t max_vlen = -1, vheap = 0, max_dlen = 0;
  for (int64_t r = 0; r < n; ++r) {
    kidx[r] = keys.insert(key_off[r], key_len[r]);
    sidx[r] = sub_len[r] >= 0 ? subs.insert(sub_off[r], sub_len[r]) : -1;
    if (val_len[r] >= 0) {
      if (val_len[r] > max_vlen) max_vlen = val_len[r];
      vheap += val_len[r];
    }
  }
  int64_t kheap = 0, sheap = 0;
  for (int64_t u = 0; u < keys.count; ++u) {
    kheap += keys.u_len[u];
    if (keys.u_len[u] > max_dlen) max_dlen = keys.u_len[u];
  }
  for (int64_t u = 0; u < subs.count; ++u) {
    sheap += subs.u_len[u];
    if (subs.u_len[u] > max_dlen) max_dlen = subs.u_len[u];
  }
  // width-ladder bound, mirroring the Python tier's _pick_width raise:
  // a value that would need the 4-byte all-ones sentinel as a REAL
  // length/index must be rejected, never silently encoded as absent
  if (max_vlen >= 0xFFFFFFFFLL || max_dlen >= 0xFFFFFFFFLL ||
      keys.count > 0xFFFFFFFELL || subs.count > 0xFFFFFFFELL) {
    delete[] kidx;
    delete[] sidx;
    return DAT_ERR_BAD_RECORD;
  }
  const int kw = batch_width(keys.count > 0 ? keys.count - 1 : 0);
  const int sw = subs.count == 0 ? 0 : batch_width(subs.count - 1);
  const int vw = max_vlen < 0 ? 0 : batch_width(max_vlen);
  // dict lengths carry no sentinel, so any width REPRESENTING the max is
  // enough — but batch_width's strict bound keeps the two sides' width
  // pick identical, which the byte-exactness tests pin
  const int dw = batch_width(max_dlen);
  int64_t need = 5 + 4 * 10  // header + 4 varints (10-byte worst case)
                 + (keys.count + subs.count) * dw + kheap + sheap
                 + n * (12 + kw + sw + vw) + vheap;
  if (need > cap) {
    delete[] kidx;
    delete[] sidx;
    return DAT_ERR_CAPACITY;
  }
  int64_t w = 0;
  dst[w++] = BATCH_VERSION;
  dst[w++] = static_cast<uint8_t>(kw);
  dst[w++] = static_cast<uint8_t>(sw);
  dst[w++] = static_cast<uint8_t>(vw);
  dst[w++] = static_cast<uint8_t>(dw);
  w = write_uvarint(dst, w, n);
  w = write_uvarint(dst, w, keys.count);
  w = write_uvarint(dst, w, subs.count);
  w = write_uvarint(dst, w, vheap);
  for (int64_t u = 0; u < keys.count; ++u)
    w = put_le(dst, w, keys.u_len[u], dw);
  for (int64_t u = 0; u < keys.count; ++u) {
    std::memcpy(dst + w, src + keys.u_off[u],
                static_cast<size_t>(keys.u_len[u]));
    w += keys.u_len[u];
  }
  for (int64_t u = 0; u < subs.count; ++u)
    w = put_le(dst, w, subs.u_len[u], dw);
  for (int64_t u = 0; u < subs.count; ++u) {
    std::memcpy(dst + w, src + subs.u_off[u],
                static_cast<size_t>(subs.u_len[u]));
    w += subs.u_len[u];
  }
  std::memcpy(dst + w, change, static_cast<size_t>(n) * 4);
  w += n * 4;
  std::memcpy(dst + w, from_v, static_cast<size_t>(n) * 4);
  w += n * 4;
  std::memcpy(dst + w, to_v, static_cast<size_t>(n) * 4);
  w += n * 4;
  for (int64_t r = 0; r < n; ++r) w = put_le(dst, w, kidx[r], kw);
  if (sw) {
    const uint64_t sent = (1ULL << (8 * sw)) - 1;
    for (int64_t r = 0; r < n; ++r)
      w = put_le(dst, w, sidx[r] < 0 ? sent : sidx[r], sw);
  }
  if (vw) {
    const uint64_t sent = (1ULL << (8 * vw)) - 1;
    for (int64_t r = 0; r < n; ++r)
      w = put_le(dst, w, val_len[r] < 0 ? sent : val_len[r], vw);
  }
  for (int64_t r = 0; r < n; ++r) {
    if (val_len[r] > 0) {
      std::memcpy(dst + w, src + val_off[r],
                  static_cast<size_t>(val_len[r]));
      w += val_len[r];
    }
  }
  delete[] kidx;
  delete[] sidx;
  return w;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// BLAKE2b (RFC 7693) — unkeyed, 32-byte digests, written from the spec.
//
// Why native: reconciliation digests host-born records whose digests are
// consumed as a tiny sketch table (ops/reconcile.py) — shipping the bytes
// to the device buys nothing, and a Python hashlib loop pays ~1us of
// interpreter overhead per record (round-3 verdict weak #3: 26-65k
// records/s end-to-end).  A C loop over extents with thread-parallel
// batches turns digesting into a memory-bandwidth problem.
// ---------------------------------------------------------------------------

#include <cstring>
#include <algorithm>
#include <atomic>
#include <cmath>
#include <thread>
#include <vector>

namespace {

const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

inline uint64_t rotr64(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);  // little-endian hosts (x86/arm LE) only
  return v;
}

void b2b_compress(uint64_t h[8], const uint8_t block[128], uint64_t t,
                  bool last) {
  uint64_t v[16], m[16];
  for (int i = 0; i < 8; ++i) v[i] = h[i];
  for (int i = 0; i < 8; ++i) v[8 + i] = B2B_IV[i];
  v[12] ^= t;  // t_hi stays 0: extent lengths are int64
  if (last) v[14] = ~v[14];
  for (int i = 0; i < 16; ++i) m[i] = load64(block + 8 * i);
#define DAT_G(a, b, c, d, x, y)                      \
  v[a] += v[b] + (x);                                \
  v[d] = rotr64(v[d] ^ v[a], 32);                    \
  v[c] += v[d];                                      \
  v[b] = rotr64(v[b] ^ v[c], 24);                    \
  v[a] += v[b] + (y);                                \
  v[d] = rotr64(v[d] ^ v[a], 16);                    \
  v[c] += v[d];                                      \
  v[b] = rotr64(v[b] ^ v[c], 63);
  for (int r = 0; r < 12; ++r) {
    const uint8_t* s = B2B_SIGMA[r];
    DAT_G(0, 4, 8, 12, m[s[0]], m[s[1]])
    DAT_G(1, 5, 9, 13, m[s[2]], m[s[3]])
    DAT_G(2, 6, 10, 14, m[s[4]], m[s[5]])
    DAT_G(3, 7, 11, 15, m[s[6]], m[s[7]])
    DAT_G(0, 5, 10, 15, m[s[8]], m[s[9]])
    DAT_G(1, 6, 11, 12, m[s[10]], m[s[11]])
    DAT_G(2, 7, 8, 13, m[s[12]], m[s[13]])
    DAT_G(3, 4, 9, 14, m[s[14]], m[s[15]])
  }
#undef DAT_G
  for (int i = 0; i < 8; ++i) h[i] ^= v[i] ^ v[8 + i];
}

// One unkeyed BLAKE2b-256 digest of data[0..len).
void b2b_hash256(const uint8_t* data, int64_t len, uint8_t out[32]) {
  uint64_t h[8];
  for (int i = 0; i < 8; ++i) h[i] = B2B_IV[i];
  h[0] ^= 0x01010000ULL ^ 32ULL;  // depth=fanout=1, keylen=0, outlen=32
  int64_t t = 0;
  while (len - t > 128) {
    b2b_compress(h, data + t, static_cast<uint64_t>(t) + 128, false);
    t += 128;
  }
  uint8_t block[128];
  std::memset(block, 0, 128);
  if (len > t) std::memcpy(block, data + t, len - t);
  b2b_compress(h, block, static_cast<uint64_t>(len), true);  // empty input:
  // one all-zero final block with t=0, per the RFC
  std::memcpy(out, h, 32);
}

inline int pick_threads(int64_t requested, int64_t n, int64_t min_per) {
  int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  // an EXPLICIT request may exceed the core count (bounded
  // oversubscription is merely slower, and it lets the parallel paths
  // be exercised on single-core test machines), but never unboundedly:
  // a huge DAT_NTHREADS must not hit the OS thread limit and abort the
  // process mid-spawn.  Only the auto default clamps to the hardware.
  int64_t t = requested > 0 ? requested : hw;
  int64_t ceil_t = hw * 4 < 64 ? hw * 4 : 64;
  if (requested > 0 && t > ceil_t) t = ceil_t;
  if (t > n / min_per) t = n / min_per;  // don't spawn for tiny batches
  return static_cast<int>(t < 1 ? 1 : t);
}

// Run work(lo, hi, k) over [0, n) split across threads (serial when one
// suffices) — the one owner of the fan-out/join used by every parallel
// entry point.  ``k`` is the chunk index (< the nt pick_threads chose),
// so callers with per-chunk state never re-derive the split arithmetic.
template <class F>
void parallel_for(int64_t n, int64_t nthreads, int64_t min_per, F work) {
  int nt = pick_threads(nthreads, n, min_per);
  if (nt <= 1) {
    work(static_cast<int64_t>(0), n, 0);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (n + nt - 1) / nt;
  for (int64_t k = 0; k < nt; ++k) {
    int64_t lo = k * per, hi = lo + per > n ? n : lo + per;
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi, k);
  }
  for (auto& th : ts) th.join();
}

}  // namespace

// ---------------------------------------------------------------------------
// 4-way multi-buffer BLAKE2b (AVX2): four independent streams interleaved
// in ymm 64-bit lanes — the host-engine analogue of the device kernel's
// SoA batching.  Hashing one stream is inherently serial; hashing a BATCH
// is lane-parallel, so the 12 rounds run once per 4 blocks.  Ragged
// lengths are handled by lane refill: when a lane's stream finishes, its
// digest is extracted and the lane reloads the next job (per-lane t
// counters and final-block masks are just vectors).  Guarded by a
// runtime cpuid check; the scalar loop remains the portable path.
// ---------------------------------------------------------------------------

#if (defined(__x86_64__) || defined(_M_X64)) && \
    (defined(__GNUC__) || defined(__clang__))
#include <immintrin.h>

namespace {

// per-lane stream state for the 4-way engine
struct B2bLane {
  const uint8_t* data = nullptr;
  int64_t len = 0;
  int64_t off = 0;  // bytes consumed so far (multiple of 128)
  uint8_t* out = nullptr;
  bool active = false;
};

__attribute__((target("avx2"))) inline __m256i ror64x4(__m256i x, int r) {
  if (r == 32) return _mm256_shuffle_epi32(x, _MM_SHUFFLE(2, 3, 0, 1));
  if (r == 24) {
    const __m256i m = _mm256_setr_epi8(
        3, 4, 5, 6, 7, 0, 1, 2, 11, 12, 13, 14, 15, 8, 9, 10,
        3, 4, 5, 6, 7, 0, 1, 2, 11, 12, 13, 14, 15, 8, 9, 10);
    return _mm256_shuffle_epi8(x, m);
  }
  if (r == 16) {
    const __m256i m = _mm256_setr_epi8(
        2, 3, 4, 5, 6, 7, 0, 1, 10, 11, 12, 13, 14, 15, 8, 9,
        2, 3, 4, 5, 6, 7, 0, 1, 10, 11, 12, 13, 14, 15, 8, 9);
    return _mm256_shuffle_epi8(x, m);
  }
  // r == 63: rotl1
  return _mm256_or_si256(_mm256_srli_epi64(x, 63), _mm256_add_epi64(x, x));
}

// one compression over 4 interleaved states; m[16] message vectors,
// t = per-lane byte counters, fmask = per-lane all-ones where final
__attribute__((target("avx2")))
void b2b_compress4(__m256i h[8], const __m256i m[16], __m256i t,
                   __m256i fmask) {
  __m256i v[16];
  for (int i = 0; i < 8; ++i) v[i] = h[i];
  for (int i = 0; i < 8; ++i) v[8 + i] = _mm256_set1_epi64x(
      static_cast<long long>(B2B_IV[i]));
  v[12] = _mm256_xor_si256(v[12], t);
  v[14] = _mm256_xor_si256(v[14], fmask);
#define DAT_G4(a, b, c, d, x, y)                        \
  v[a] = _mm256_add_epi64(_mm256_add_epi64(v[a], v[b]), (x)); \
  v[d] = ror64x4(_mm256_xor_si256(v[d], v[a]), 32);     \
  v[c] = _mm256_add_epi64(v[c], v[d]);                  \
  v[b] = ror64x4(_mm256_xor_si256(v[b], v[c]), 24);     \
  v[a] = _mm256_add_epi64(_mm256_add_epi64(v[a], v[b]), (y)); \
  v[d] = ror64x4(_mm256_xor_si256(v[d], v[a]), 16);     \
  v[c] = _mm256_add_epi64(v[c], v[d]);                  \
  v[b] = ror64x4(_mm256_xor_si256(v[b], v[c]), 63);
  for (int r = 0; r < 12; ++r) {
    const uint8_t* s = B2B_SIGMA[r];
    DAT_G4(0, 4, 8, 12, m[s[0]], m[s[1]])
    DAT_G4(1, 5, 9, 13, m[s[2]], m[s[3]])
    DAT_G4(2, 6, 10, 14, m[s[4]], m[s[5]])
    DAT_G4(3, 7, 11, 15, m[s[6]], m[s[7]])
    DAT_G4(0, 5, 10, 15, m[s[8]], m[s[9]])
    DAT_G4(1, 6, 11, 12, m[s[10]], m[s[11]])
    DAT_G4(2, 7, 8, 13, m[s[12]], m[s[13]])
    DAT_G4(3, 4, 9, 14, m[s[14]], m[s[15]])
  }
#undef DAT_G4
  for (int i = 0; i < 8; ++i)
    h[i] = _mm256_xor_si256(h[i], _mm256_xor_si256(v[i], v[8 + i]));
}

// Hash extents buf[offs[i] .. offs[i]+lens[i]) for i in [0, njobs),
// digests to outbase + i*32, 4 lanes at a time with lane refill.
__attribute__((target("avx2")))
void b2b_many_avx2(const uint8_t* buf, const int64_t* offs,
                   const int64_t* lens, int64_t njobs, uint8_t* outbase) {
  if (njobs <= 0) return;
  B2bLane lanes[4];
  __m256i h[8];
  alignas(32) uint64_t hbuf[8][4] = {};  // zeroed: idle lanes load defined
  alignas(32) uint8_t pad[4][128];       // bytes even before first reset
  int64_t next = 0;
  const uint64_t param = 0x01010000ULL ^ 32ULL;

  auto reset_lane = [&](int L) -> bool {
    if (next >= njobs) {
      lanes[L].active = false;
      return false;
    }
    lanes[L] = {buf + offs[next], lens[next], 0, outbase + next * 32, true};
    ++next;
    for (int w = 0; w < 8; ++w)
      hbuf[w][L] = B2B_IV[w] ^ (w == 0 ? param : 0ULL);
    return true;
  };

  for (int L = 0; L < 4; ++L) reset_lane(L);
  for (int w = 0; w < 8; ++w)
    h[w] = _mm256_load_si256(reinterpret_cast<const __m256i*>(hbuf[w]));

  while (lanes[0].active || lanes[1].active || lanes[2].active ||
         lanes[3].active) {
    // stage one block per lane; inactive lanes chew a zero block
    const uint8_t* blk[4];
    alignas(32) uint64_t tv[4];
    alignas(32) uint64_t fv[4];
    bool finishing[4];
    for (int L = 0; L < 4; ++L) {
      B2bLane& ln = lanes[L];
      if (!ln.active) {
        std::memset(pad[L], 0, 128);
        blk[L] = pad[L];
        tv[L] = 0;
        fv[L] = 0;  // never final: state is discarded at refill anyway
        finishing[L] = false;
        continue;
      }
      int64_t rem = ln.len - ln.off;
      if (rem > 128) {
        blk[L] = ln.data + ln.off;
        ln.off += 128;
        tv[L] = static_cast<uint64_t>(ln.off);
        fv[L] = 0;
        finishing[L] = false;
      } else {  // final block (rem in [0, 128]; 0 only for empty input)
        std::memset(pad[L], 0, 128);
        if (rem > 0) std::memcpy(pad[L], ln.data + ln.off, rem);
        blk[L] = pad[L];
        tv[L] = static_cast<uint64_t>(ln.len);
        fv[L] = ~0ULL;
        finishing[L] = true;
      }
    }
    __m256i m[16];
    for (int w = 0; w < 16; ++w)
      m[w] = _mm256_set_epi64x(
          static_cast<long long>(load64(blk[3] + 8 * w)),
          static_cast<long long>(load64(blk[2] + 8 * w)),
          static_cast<long long>(load64(blk[1] + 8 * w)),
          static_cast<long long>(load64(blk[0] + 8 * w)));
    b2b_compress4(
        h, m,
        _mm256_load_si256(reinterpret_cast<const __m256i*>(tv)),
        _mm256_load_si256(reinterpret_cast<const __m256i*>(fv)));
    if (finishing[0] || finishing[1] || finishing[2] || finishing[3]) {
      // spill the state ONCE, then extract+reset every finishing lane
      // in the spilled rows (a per-lane re-spill would clobber an
      // earlier lane's freshly reset IVs), then reload
      for (int w = 0; w < 8; ++w)
        _mm256_store_si256(reinterpret_cast<__m256i*>(hbuf[w]), h[w]);
      for (int L = 0; L < 4; ++L) {
        if (!finishing[L]) continue;
        for (int w = 0; w < 4; ++w)
          std::memcpy(lanes[L].out + 8 * w, &hbuf[w][L], 8);
        reset_lane(L);
      }
      for (int w = 0; w < 8; ++w)
        h[w] = _mm256_load_si256(reinterpret_cast<const __m256i*>(hbuf[w]));
    }
  }
}

inline bool have_avx2() {
  static const bool ok = __builtin_cpu_supports("avx2");
  return ok;
}

}  // namespace
#else
namespace {
inline bool have_avx2() { return false; }
inline void b2b_many_avx2(const uint8_t*, const int64_t*, const int64_t*,
                          int64_t, uint8_t*) {}
}  // namespace
#endif

extern "C" {

// Digest n extents of buf: out[r*32..] = BLAKE2b-256(buf[offs[r] ..
// offs[r]+lens[r])).  nthreads <= 0 = auto.  Returns 0.
int64_t dat_blake2b_many(const uint8_t* buf, const int64_t* offs,
                         const int64_t* lens, int64_t n, uint8_t* out,
                         int64_t nthreads) {
  parallel_for(n, nthreads, 64, [&](int64_t lo, int64_t hi, int64_t) {
    if (have_avx2()) {
      b2b_many_avx2(buf, offs + lo, lens + lo, hi - lo, out + lo * 32);
      return;
    }
    for (int64_t r = lo; r < hi; ++r)
      b2b_hash256(buf + offs[r], lens[r], out + r * 32);
  });
  return 0;
}

// Build a key-addressed reconciliation sketch in one pass
// (ops/reconcile.py documents the protocol): per record r,
//   slot[r]  = LE32(BLAKE2b-256(key_r)[0:4]) & (nslots - 1)
//   table[slot[r]][w] += LE32words(BLAKE2b-256(rec_r))[w]   (wrapping u32)
// `table` is (1 << log2_slots) * 8 u32, caller-zeroed.  Digesting is
// thread-parallel into a scratch digest array; the scatter-add is one
// serial pass (n * 8 adds — never the bottleneck).  Returns 0, or
// DAT_ERR_CAPACITY if scratch allocation fails.
int64_t dat_sketch(const uint8_t* buf, const int64_t* rec_offs,
                   const int64_t* rec_lens, const int64_t* key_offs,
                   const int64_t* key_lens, int64_t n, int64_t log2_slots,
                   uint32_t* table, uint32_t* slots, int64_t nthreads) {
  uint8_t* scratch = new (std::nothrow) uint8_t[static_cast<size_t>(n) * 32];
  if (scratch == nullptr && n > 0) return DAT_ERR_NOMEM;
  const uint32_t mask = (log2_slots >= 32)
                            ? 0xffffffffu
                            : ((1u << log2_slots) - 1u);
  parallel_for(n, nthreads, 64, [&](int64_t lo, int64_t hi, int64_t) {
    int64_t cnt = hi - lo;
    if (have_avx2()) {
      // 4-way engine over records (straight into scratch) and keys
      // (into a range-local buffer the slot extraction reads)
      uint8_t* kds = new (std::nothrow) uint8_t[static_cast<size_t>(cnt) * 32];
      if (kds != nullptr) {
        b2b_many_avx2(buf, rec_offs + lo, rec_lens + lo, cnt,
                      scratch + lo * 32);
        b2b_many_avx2(buf, key_offs + lo, key_lens + lo, cnt, kds);
        for (int64_t r = lo; r < hi; ++r) {
          uint32_t s;
          std::memcpy(&s, kds + (r - lo) * 32, 4);
          slots[r] = s & mask;
        }
        delete[] kds;
        return;
      }  // allocation failed: scalar path below still succeeds
    }
    uint8_t kd[32];
    for (int64_t r = lo; r < hi; ++r) {
      b2b_hash256(buf + rec_offs[r], rec_lens[r], scratch + r * 32);
      b2b_hash256(buf + key_offs[r], key_lens[r], kd);
      uint32_t s;
      std::memcpy(&s, kd, 4);
      slots[r] = s & mask;
    }
  });
  for (int64_t r = 0; r < n; ++r) {
    uint32_t* cell = table + static_cast<int64_t>(slots[r]) * 8;
    uint32_t w[8];
    std::memcpy(w, scratch + r * 32, 32);
    for (int k = 0; k < 8; ++k) cell[k] += w[k];
  }
  delete[] scratch;
  return 0;
}

// -- rateless coded-symbol build (ops/rateless.py documents the scheme) --
//
// The splitmix64 constants are written down independently in
// ops/rateless.py; a fork here is a ROUTE fork — two "byte-identical"
// engines silently mapping elements to different coded symbols (the
// GEAR_C1/GEAR_C2 precedent).  Parity is machine-checked:
// wire: RATELESS_GAMMA = 0x9E3779B97F4A7C15
// wire: RATELESS_MIX1 = 0xBF58476D1CE4E5B9
// wire: RATELESS_MIX2 = 0x94D049BB133111EB
static inline uint64_t rateless_mix64(uint64_t z) {
  z ^= z >> 30;
  z *= 0xBF58476D1CE4E5B9ULL;
  z ^= z >> 27;
  z *= 0x94D049BB133111EBULL;
  z ^= z >> 31;
  return z;
}

// Advance each element's participation cursor through coded-symbol
// indices below `m`, adding its 11-word row (count=1, 2 checksum
// words, 8 digest words) into cells for every index in [base, m).
// `state` / `next` are INOUT per-element cursors (the caller seeds a
// fresh element with state = LE64(digest[0:8]), next = 0 — every
// element participates at index 0); on return every cursor sits at its
// first index >= m, so repeated calls with a growing bound build the
// prefix incrementally.  `cells` is (m - base) * 11 u32, caller-zeroed.
// The gap draw is IEEE double math (sqrt/ceil are correctly rounded),
// bit-identical to the numpy reference in ops/rateless.py.  Threaded
// over elements with private partial tables (u32 wrapping adds commute,
// so the merge order cannot change a single byte).  Returns 0, or
// DAT_ERR_NOMEM when a partial table cannot be allocated.
int64_t dat_rateless_build(const uint8_t* digests, int64_t n,
                           uint64_t* state, uint64_t* next, int64_t base,
                           int64_t m, uint32_t* cells, int64_t nthreads) {
  const int64_t width = (m - base) * 11;
  int nt = pick_threads(nthreads, n, 1024);
  // every partial table is allocated BEFORE any worker runs: the
  // cursors advance in place, so a mid-flight failure after some
  // threads finished would leave them advanced past cells that were
  // never written — a silently corrupted prefix the Python fallback
  // could not repair.  All-or-nothing: fail before touching anything.
  std::vector<uint32_t*> partials(static_cast<size_t>(nt), nullptr);
  for (int k = 1; k < nt; ++k) {
    partials[static_cast<size_t>(k)] =
        new (std::nothrow) uint32_t[static_cast<size_t>(width)]();
    if (partials[static_cast<size_t>(k)] == nullptr) {
      for (int j = 1; j < k; ++j) delete[] partials[static_cast<size_t>(j)];
      return DAT_ERR_NOMEM;
    }
  }
  parallel_for(n, nt, 1024, [&](int64_t lo, int64_t hi, int64_t k) {
    uint32_t* block = k > 0 ? partials[static_cast<size_t>(k)] : cells;
    for (int64_t e = lo; e < hi; ++e) {
      const uint8_t* d = digests + e * 32;
      uint32_t row[11];
      row[0] = 1u;
      uint64_t lanes[4];
      std::memcpy(lanes, d, 32);
      uint64_t acc = rateless_mix64(lanes[0] + 0x9E3779B97F4A7C15ULL);
      for (int i = 1; i < 4; ++i) acc = rateless_mix64(acc ^ lanes[i]);
      row[1] = static_cast<uint32_t>(acc);
      row[2] = static_cast<uint32_t>(acc >> 32);
      std::memcpy(row + 3, d, 32);
      uint64_t st = state[e], nx = next[e];
      const uint64_t bound = static_cast<uint64_t>(m);
      const uint64_t lo_b = static_cast<uint64_t>(base);
      while (nx < bound) {
        if (nx >= lo_b) {
          uint32_t* c = block + static_cast<int64_t>(nx - lo_b) * 11;
          for (int w = 0; w < 11; ++w) c[w] += row[w];
        }
        st += 0x9E3779B97F4A7C15ULL;
        uint32_t r32 = static_cast<uint32_t>(rateless_mix64(st) >> 32);
        double cur = static_cast<double>(nx);
        double gap = std::ceil(
            (cur + 1.5) * (65536.0 / std::sqrt(static_cast<double>(r32) + 1.0)
                           - 1.0));
        if (gap < 1.0) gap = 1.0;
        nx += static_cast<uint64_t>(gap);
      }
      state[e] = st;
      next[e] = nx;
    }
  });
  for (size_t k = 1; k < partials.size(); ++k) {
    if (partials[k] != nullptr) {
      for (int64_t w = 0; w < width; ++w) cells[w] += partials[k][w];
      delete[] partials[k];
    }
  }
  return 0;
}

// Weighted (variable-size element) twin of dat_rateless_build — the
// "Rateless Bloom Filters" extension the snapshot bootstrap (ISSUE 12)
// reconciles CDC chunk sets with.  Cells are 12 u32 words: count, two
// checksum words (the chain above extended by one mix over the length
// word), 8 digest words, and a wrapping-u32 LENGTH word.  The drawn
// index gap divides (integer division, clamped to >= 1) by
// weight_class + 1, where weight_class = min(W_CAP,
// bit_length(len >> W_SHIFT)) — heavy chunks participate more densely.
// The participation constants are written down independently in
// ops/rateless.py; a fork is a ROUTE fork, parity machine-checked:
// wire: RATELESS_W_SHIFT = 12
// wire: RATELESS_W_CAP = 8
int64_t dat_rateless_build_w(const uint8_t* digests, const int64_t* lens,
                             int64_t n, uint64_t* state, uint64_t* next,
                             int64_t base, int64_t m, uint32_t* cells,
                             int64_t nthreads) {
  const int64_t width = (m - base) * 12;
  int nt = pick_threads(nthreads, n, 1024);
  std::vector<uint32_t*> partials(static_cast<size_t>(nt), nullptr);
  for (int k = 1; k < nt; ++k) {
    partials[static_cast<size_t>(k)] =
        new (std::nothrow) uint32_t[static_cast<size_t>(width)]();
    if (partials[static_cast<size_t>(k)] == nullptr) {
      for (int j = 1; j < k; ++j) delete[] partials[static_cast<size_t>(j)];
      return DAT_ERR_NOMEM;
    }
  }
  parallel_for(n, nt, 1024, [&](int64_t lo, int64_t hi, int64_t k) {
    uint32_t* block = k > 0 ? partials[static_cast<size_t>(k)] : cells;
    for (int64_t e = lo; e < hi; ++e) {
      const uint8_t* d = digests + e * 32;
      const uint64_t len = static_cast<uint64_t>(lens[e]);
      uint32_t row[12];
      row[0] = 1u;
      uint64_t lanes[4];
      std::memcpy(lanes, d, 32);
      uint64_t acc = rateless_mix64(lanes[0] + 0x9E3779B97F4A7C15ULL);
      for (int i = 1; i < 4; ++i) acc = rateless_mix64(acc ^ lanes[i]);
      acc = rateless_mix64(acc ^ static_cast<uint64_t>(
                                     static_cast<uint32_t>(len)));
      row[1] = static_cast<uint32_t>(acc);
      row[2] = static_cast<uint32_t>(acc >> 32);
      std::memcpy(row + 3, d, 32);
      row[11] = static_cast<uint32_t>(len);
      uint64_t wclass = 0;
      for (uint64_t v = len >> 12; v != 0 && wclass < 8; v >>= 1) ++wclass;
      const uint64_t div = wclass + 1;
      uint64_t st = state[e], nx = next[e];
      const uint64_t bound = static_cast<uint64_t>(m);
      const uint64_t lo_b = static_cast<uint64_t>(base);
      while (nx < bound) {
        if (nx >= lo_b) {
          uint32_t* c = block + static_cast<int64_t>(nx - lo_b) * 12;
          for (int w = 0; w < 12; ++w) c[w] += row[w];
        }
        st += 0x9E3779B97F4A7C15ULL;
        uint32_t r32 = static_cast<uint32_t>(rateless_mix64(st) >> 32);
        double cur = static_cast<double>(nx);
        double gap = std::ceil(
            (cur + 1.5) * (65536.0 / std::sqrt(static_cast<double>(r32) + 1.0)
                           - 1.0));
        if (gap < 1.0) gap = 1.0;
        uint64_t g = static_cast<uint64_t>(gap) / div;
        if (g < 1) g = 1;
        nx += g;
      }
      state[e] = st;
      next[e] = nx;
    }
  });
  for (size_t k = 1; k < partials.size(); ++k) {
    if (partials[k] != nullptr) {
      for (int64_t w = 0; w < width; ++w) cells[w] += partials[k][w];
      delete[] partials[k];
    }
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Thread-parallel dat_decode_changes: rows are independent, so ranges
// decode concurrently via parallel_for; the reported error is the
// MINIMUM offending index across ranges (atomic fetch-min), preserving
// the serial entry point's first-corrupt-record semantics.
// nthreads <= 0 = auto.
int64_t dat_decode_changes_mt(const uint8_t* buf, const int64_t* starts,
                              const int64_t* lens, int64_t n,
                              uint32_t* change, uint32_t* from_v,
                              uint32_t* to_v, int64_t* key_off,
                              int64_t* key_len, int64_t* sub_off,
                              int64_t* sub_len, int64_t* val_off,
                              int64_t* val_len, int64_t* err_index,
                              int64_t nthreads) {
  std::atomic<int64_t> first(INT64_MAX);
  parallel_for(n, nthreads, 4096, [&](int64_t lo, int64_t hi, int64_t) {
    int64_t bad = decode_changes_range(buf, starts, lens, lo, hi, change,
                                       from_v, to_v, key_off, key_len,
                                       sub_off, sub_len, val_off, val_len);
    if (bad >= 0) {
      int64_t cur = first.load(std::memory_order_relaxed);
      while (bad < cur &&
             !first.compare_exchange_weak(cur, bad,
                                          std::memory_order_relaxed)) {
      }
    }
  });
  if (first.load() != INT64_MAX) {
    *err_index = first.load();
    return DAT_ERR_BAD_RECORD;
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Thread-parallel bulk encode: pass 1 sizes every frame concurrently, a
// serial prefix sum assigns offsets, pass 2 writes every frame at its
// offset concurrently.  Byte-identical to dat_encode_changes (same
// helpers).  Returns bytes written, or DAT_ERR_CAPACITY.
int64_t dat_encode_changes_mt(const uint8_t* src, int64_t n,
                              const uint32_t* change, const uint32_t* from_v,
                              const uint32_t* to_v, const int64_t* key_off,
                              const int64_t* key_len, const int64_t* sub_off,
                              const int64_t* sub_len, const int64_t* val_off,
                              const int64_t* val_len, uint8_t* dst,
                              int64_t cap, int64_t nthreads) {
  int64_t* offs = new (std::nothrow) int64_t[static_cast<size_t>(n) + 1];
  if (offs == nullptr) return DAT_ERR_NOMEM;
  parallel_for(n, nthreads, 4096, [&](int64_t lo, int64_t hi, int64_t) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t psize = change_payload_size(r, change, from_v, to_v, key_len,
                                          sub_len, val_len);
      offs[r] = uvarint_size(psize + 1) + 1 + psize;
    }
  });
  int64_t total = 0;
  for (int64_t r = 0; r < n; ++r) {
    int64_t sz = offs[r];
    offs[r] = total;
    total += sz;
  }
  offs[n] = total;
  if (total > cap) {
    delete[] offs;
    return DAT_ERR_CAPACITY;
  }
  parallel_for(n, nthreads, 4096, [&](int64_t lo, int64_t hi, int64_t) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t psize = change_payload_size(r, change, from_v, to_v, key_len,
                                          sub_len, val_len);
      encode_change_at(src, r, psize, change, from_v, to_v, key_off, key_len,
                       sub_off, sub_len, val_off, val_len, dst, offs[r]);
    }
  });
  delete[] offs;
  return total;
}

}  // extern "C"

namespace {

// One gear scan over buf[lo, hi): h is fully determined by the WINDOW
// bytes preceding a position (contributions shift out after 64 steps),
// so any range can be scanned independently by warming the state from
// the 64 bytes before it — the same seeding trick the device tiling
// uses, which is what makes the "rolling" scan embarrassingly parallel.
// Emits window-thinned positions into dst[0..cap) (straddles across
// range boundaries are resolved by the caller's merge); returns the
// count, or -1 the moment cap would overflow — fail-fast, bounded
// memory, no throwing allocations (the file's nothrow convention).
// Gear state at position lo: warmed from the preceding WINDOW bytes
// (the zero seed at the stream head) — one owner for every scan path.
inline uint64_t gear_seed(const uint8_t* buf, int64_t lo,
                          const uint64_t* tab) {
  uint64_t h = 0;
  if (lo == 0) {
    for (int64_t k = 0; k < 64; ++k) h = (h << 1) + tab[0];
  } else {
    for (int64_t k = lo - 64; k < lo; ++k) h = (h << 1) + tab[buf[k]];
  }
  return h;
}

int64_t gear_scan_range(const uint8_t* buf, int64_t lo, int64_t hi,
                        const uint64_t* tab, uint32_t mask,
                        int64_t thin_bits, int64_t* dst, int64_t cap) {
  uint64_t h = gear_seed(buf, lo, tab);
  int64_t m = 0;
  int64_t last_win = -1;
  for (int64_t j = lo; j < hi; ++j) {
    h = (h << 1) + tab[buf[j]];
    if (((static_cast<uint32_t>(h >> 32)) & mask) == 0) {
      if (thin_bits >= 0) {
        int64_t win = j >> thin_bits;
        if (win == last_win) continue;
        last_win = win;
      }
      if (m >= cap) return -1;
      dst[m++] = j;
    }
  }
  return m;
}

// Four independent sub-range chains interleaved in one loop: a single
// gear chain is latency-bound on h -> h (the byte/table loads are off
// the critical path), so interleaving converts the scan to
// throughput-bound — the scalar-ILP analogue of the Pallas kernel's
// ilp chunks.  Each chain seeds from its preceding WINDOW bytes and
// emits (window-thinned) into its own dst slab; the caller's merge
// resolves straddles at every seam.  cnts[c] = -1 flags slab overflow.
void gear_scan_range4(const uint8_t* buf, const int64_t* qlo,
                      const int64_t* qhi, const uint64_t* tab, uint32_t mask,
                      int64_t thin_bits, int64_t* dst, int64_t cap,
                      int64_t* cnts) {
  uint64_t h[4];
  int64_t j[4], lw[4], m[4];
  for (int c = 0; c < 4; ++c) {
    h[c] = gear_seed(buf, qlo[c], tab);
    j[c] = qlo[c];
    lw[c] = -1;
    m[c] = 0;
  }
  auto emit = [&](int c, int64_t pos) {
    if (m[c] < 0) return;  // STICKY overflow poison: a non-sticky check
    // would pass -1 < cap, write dst[c*cap - 1] (heap underflow /
    // cross-chain corruption) and silently reset the count
    if (thin_bits >= 0) {
      int64_t win = pos >> thin_bits;
      if (win == lw[c]) return;
      lw[c] = win;
    }
    if (m[c] >= cap) {
      m[c] = -1;
      return;
    }
    dst[c * cap + m[c]] = pos;
    ++m[c];
  };
  int64_t steps = qhi[0] - qlo[0];
  for (int c = 1; c < 4; ++c)
    if (qhi[c] - qlo[c] < steps) steps = qhi[c] - qlo[c];
  for (int64_t st = 0; st < steps; ++st) {
    // four independent chains per iteration: the compiler schedules the
    // loads of chain c+1 under the shift+add of chain c
    for (int c = 0; c < 4; ++c) {
      uint64_t hh = (h[c] << 1) + tab[buf[j[c]]];
      h[c] = hh;
      if (((static_cast<uint32_t>(hh >> 32)) & mask) == 0) emit(c, j[c]);
      ++j[c];
    }
  }
  for (int c = 0; c < 4; ++c) {  // ragged tails finish serially
    uint64_t hh = h[c];
    for (int64_t p = j[c]; p < qhi[c]; ++p) {
      hh = (hh << 1) + tab[buf[p]];
      if (((static_cast<uint32_t>(hh >> 32)) & mask) == 0) emit(c, p);
    }
    cnts[c] = m[c];  // -1 (sticky poison) or the chain's count
  }
}

}  // namespace

extern "C" {

// Host gear CDC scan: the seeded-stream definition (ops/rabin.py
// host_candidates) — per byte h = (h << 1) + g[b], candidate where the
// top word masks to zero.  g[b] = (b+1)*C1 | ((b+1)*C2 << 32) is a
// 256-entry table, so the loop is ~4 ops/byte (~1.2 GiB/s per core),
// and ranges scan thread-parallel (see gear_scan_range).  thin_bits >=
// 0 keeps only the first candidate per aligned 2**thin_bits window (the
// chunking policy); pass -1 for every candidate.  Returns the candidate
// count (<= cap; DAT_ERR_CAPACITY on overflow).  Serves CPU-routed
// chunk_stream — "batch or stay home" applies to chunking like hashing:
// the XLA scan formulation of this loop measures ~0.0002 GiB/s e2e on a
// CPU host.
int64_t dat_gear_candidates(const uint8_t* buf, int64_t n, int64_t avg_bits,
                            int64_t thin_bits, int64_t* out, int64_t cap,
                            int64_t nthreads) {
  // (1u << 32) is undefined behavior; reject out-of-range parameters
  // instead of silently computing with a garbage mask
  if (avg_bits < 1 || avg_bits > 31 || thin_bits > 31 || cap < 0)
    return DAT_ERR_BAD_RECORD;
  // wire: GEAR_C1 = 0x9E3779B1
  // wire: GEAR_C2 = 0x85EBCA77
  const uint32_t c1 = 0x9E3779B1u, c2 = 0x85EBCA77u;
  uint64_t tab[256];
  for (uint32_t b = 0; b < 256; ++b) {
    uint64_t lo = static_cast<uint32_t>((b + 1) * c1);
    uint64_t hi = static_cast<uint32_t>((b + 1) * c2);
    tab[b] = lo | (hi << 32);
  }
  const uint32_t mask = (1u << avg_bits) - 1u;
  int nt = pick_threads(nthreads, n, 1 << 22);  // >= 4 MiB per thread
  if (n < (1 << 16) || nthreads < -1) {
    // one plain chain, straight into out, fail fast — for tiny inputs,
    // and as the independently-implemented reference route (nthreads
    // < -1, a test-only sentinel: the equivalence tests need a path
    // that shares none of the quartering/merge machinery).  Explicit
    // nthreads=1 keeps the 4-chain ILP scan on its single thread —
    // bounding CPU usage must not cost the interleave speedup.
    int64_t m = gear_scan_range(buf, 0, n, tab, mask, thin_bits, out, cap);
    return m < 0 ? DAT_ERR_CAPACITY : m;
  }
  // every thread chunk runs FOUR interleaved sub-range chains
  // (gear_scan_range4); each of the nt*4 quarters writes a bounded slab
  // slice and the thinned merge resolves window straddles at every
  // seam, so the output equals the single-chain scan's exactly
  int64_t nq = static_cast<int64_t>(nt) * 4;
  // quarters share their chunk's cap budget (a lone chain legitimately
  // holding more than cap/4 trips ERR_CAPACITY and the caller's
  // geometric retry resolves it) — per-quarter FULL budgets would 4x
  // the transient slab for no correctness gain
  int64_t qcap = cap / 4 + 1;
  int64_t* slab = new (std::nothrow) int64_t[static_cast<size_t>(nq) * qcap];
  if (slab == nullptr && nq * qcap > 0) return DAT_ERR_NOMEM;
  std::vector<int64_t> counts(static_cast<size_t>(nq), 0);
  parallel_for(n, nt, 1 << 22, [&](int64_t lo, int64_t hi, int64_t k) {
    int64_t qlo[4], qhi[4];
    int64_t qlen = (hi - lo) / 4;
    for (int c = 0; c < 4; ++c) {
      qlo[c] = lo + c * qlen;
      qhi[c] = c == 3 ? hi : qlo[c] + qlen;
    }
    gear_scan_range4(buf, qlo, qhi, tab, mask, thin_bits,
                     slab + k * 4 * qcap, qcap, counts.data() + k * 4);
  });
  int64_t m = 0;
  int64_t last_win = -1;
  for (int64_t q = 0; q < nq; ++q) {
    if (counts[q] < 0) {
      delete[] slab;
      return DAT_ERR_CAPACITY;
    }
    for (int64_t i = 0; i < counts[q]; ++i) {
      int64_t j = slab[q * qcap + i];
      if (thin_bits >= 0) {
        int64_t win = j >> thin_bits;
        if (win == last_win) continue;
        last_win = win;
      }
      if (m >= cap) {
        delete[] slab;
        return DAT_ERR_CAPACITY;
      }
      out[m++] = j;
    }
  }
  delete[] slab;
  return m;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Single-pass content addressing: fused gear CDC + BLAKE2b (ISSUE 7).
//
// The two-pass host route streams every blob byte through DRAM twice —
// once for the gear candidate scan, once for the BLAKE2b digest pass.
// dat_cdc_hash collapses the pipeline into ONE sweep: the stream is
// processed in cache-sized slabs, and while slab k+1 is being gear-
// scanned, the chunks finalized in slab k (still cache-resident) are
// hashed by a multi-lane BLAKE2b engine whose compressions are
// interleaved INTO the scan loop's instruction stream.  The gear chain
// is scalar and latency-bound (it leaves the vector ports idle); the
// BLAKE2b rounds are vector-port-bound (they leave the scalar ALUs
// idle) — interleaving the two lets one out-of-order core run both
// concurrently, so the fused pass approaches max(gear, hash) instead of
// gear + hash.  Candidates, thinning, greedy min/max selection, and
// digests are all byte-identical to the two-pass route (same gear_seed,
// same per-window thinning + seam merge, same dat_greedy_select
// semantics, same RFC 7693 compression) — the fuzz suite pins this.
//
// The 8-lane engine below is AVX-512F (native 64-bit rotates via
// vprorq, double the lane width of the AVX2 engine); b2b_many_avx2 and
// its callers are untouched — the incumbent two-pass route keeps its
// tested engine, and the A/B in bench.py config 8 is route vs route.
// ---------------------------------------------------------------------------

namespace {

// Resumable 4-chain gear scanner: gear_scan_range4's machinery hoisted
// into a struct so the fused loop can advance the scan a few bytes per
// BLAKE2b round.  Same quartering, same per-chain seeding from the
// preceding WINDOW bytes, same per-window thinning with sticky
// overflow poison; the caller's ordered merge resolves seam straddles
// exactly like dat_gear_candidates' merge.
struct GearQuad {
  uint64_t h[4];
  int64_t j[4], qhi[4], lw[4], m[4];
  const uint8_t* buf = nullptr;
  const uint64_t* tab = nullptr;
  int64_t* dst = nullptr;  // 4 slabs of qcap each
  int64_t qcap = 0, thin = -1;
  uint32_t mask = 0;

  void init(const uint8_t* b, int64_t lo, int64_t hi, const uint64_t* t,
            uint32_t msk, int64_t thin_bits, int64_t* d, int64_t cap) {
    buf = b;
    tab = t;
    mask = msk;
    thin = thin_bits;
    dst = d;
    qcap = cap;
    int64_t qlen = (hi - lo) / 4;
    for (int c = 0; c < 4; ++c) {
      int64_t qlo = lo + c * qlen;
      qhi[c] = c == 3 ? hi : qlo + qlen;
      h[c] = gear_seed(buf, qlo, tab);
      j[c] = qlo;
      lw[c] = -1;
      m[c] = 0;
    }
  }

  inline void emit(int c, int64_t pos) {
    if (m[c] < 0) return;  // sticky overflow poison (see gear_scan_range4)
    if (thin >= 0) {
      int64_t win = pos >> thin;
      if (win == lw[c]) return;
      lw[c] = win;
    }
    if (m[c] >= qcap) {
      m[c] = -1;
      return;
    }
    dst[c * qcap + m[c]] = pos;
    ++m[c];
  }

  // Advance every live chain by up to per_chain bytes; returns whether
  // any chain still has bytes.  The lockstep fast path runs all four
  // chains with no per-byte bounds checks (the checked variant measured
  // ~2x slower — the branch per byte per chain defeats the 4-way ILP
  // pipelining the interleave exists for); ragged tails finish in
  // per-chain checked loops once the shortest chain drains.
  inline bool advance(int64_t per_chain) {
    int64_t steps = per_chain;
    for (int c = 0; c < 4; ++c) {
      int64_t rem = qhi[c] - j[c];
      if (rem < steps) steps = rem;
    }
    if (steps > 0) {
      // one 64-bit mask test per byte (vs shift+and+cmp): the top-word
      // candidate check as hh & (mask << 32) — test+branch macro-fuse
      const uint64_t mask64 = static_cast<uint64_t>(mask) << 32;
      uint64_t h0 = h[0], h1 = h[1], h2 = h[2], h3 = h[3];
      int64_t j0 = j[0], j1 = j[1], j2 = j[2], j3 = j[3];
      for (int64_t s = 0; s < steps; ++s) {
        h0 = (h0 << 1) + tab[buf[j0]];
        h1 = (h1 << 1) + tab[buf[j1]];
        h2 = (h2 << 1) + tab[buf[j2]];
        h3 = (h3 << 1) + tab[buf[j3]];
        if ((h0 & mask64) == 0) emit(0, j0);
        if ((h1 & mask64) == 0) emit(1, j1);
        if ((h2 & mask64) == 0) emit(2, j2);
        if ((h3 & mask64) == 0) emit(3, j3);
        ++j0;
        ++j1;
        ++j2;
        ++j3;
      }
      h[0] = h0; h[1] = h1; h[2] = h2; h[3] = h3;
      j[0] = j0; j[1] = j1; j[2] = j2; j[3] = j3;
      per_chain -= steps;
    }
    if (per_chain > 0) {
      for (int c = 0; c < 4; ++c) {
        int64_t lim = j[c] + per_chain;
        if (lim > qhi[c]) lim = qhi[c];
        uint64_t hh = h[c];
        for (int64_t p = j[c]; p < lim; ++p) {
          hh = (hh << 1) + tab[buf[p]];
          if (((static_cast<uint32_t>(hh >> 32)) & mask) == 0) emit(c, p);
        }
        h[c] = hh;
        j[c] = lim;
      }
    }
    return j[0] < qhi[0] || j[1] < qhi[1] || j[2] < qhi[2] || j[3] < qhi[3];
  }
};

}  // namespace

#if (defined(__x86_64__) || defined(_M_X64)) && \
    (defined(__GNUC__) || defined(__clang__))

namespace {

inline bool have_avx512() {
  static const bool ok = __builtin_cpu_supports("avx512f");
  return ok;
}

// Resumable 4-lane AVX2 BLAKE2b engine over (ptr, len) jobs: the lane
// machinery of b2b_many_avx2 restructured so one block-step runs per
// call (jobs addressed by pointer, not base+offset — the entry the
// hash_many_list path uses, ADVICE r5: offsets stay offsets).
struct B2b4State {
  B2bLane lanes[4];
  __m256i h[8];
  alignas(32) uint64_t hbuf[8][4];
  alignas(32) uint8_t pad[4][128];
  const uint8_t* const* jptr = nullptr;
  const int64_t* jlen = nullptr;
  uint8_t* outbase = nullptr;
  int64_t njobs = 0, next = 0;
};

__attribute__((target("avx2")))
inline bool b2b4_reset_lane(B2b4State& st, int L) {
  if (st.next >= st.njobs) {
    st.lanes[L].active = false;
    return false;
  }
  st.lanes[L] = {st.jptr[st.next], st.jlen[st.next], 0,
                 st.outbase + st.next * 32, true};
  ++st.next;
  const uint64_t param = 0x01010000ULL ^ 32ULL;
  for (int w = 0; w < 8; ++w)
    st.hbuf[w][L] = B2B_IV[w] ^ (w == 0 ? param : 0ULL);
  return true;
}

__attribute__((target("avx2")))
void b2b4_init(B2b4State& st, const uint8_t* const* jptr, const int64_t* jlen,
               uint8_t* outbase, int64_t njobs) {
  st.jptr = jptr;
  st.jlen = jlen;
  st.outbase = outbase;
  st.njobs = njobs;
  st.next = 0;
  std::memset(st.hbuf, 0, sizeof(st.hbuf));
  for (int L = 0; L < 4; ++L) b2b4_reset_lane(st, L);
  for (int w = 0; w < 8; ++w)
    st.h[w] = _mm256_load_si256(reinterpret_cast<const __m256i*>(st.hbuf[w]));
}

// One 4-lane block compression (with lane refill); false when all lanes
// are idle.  Identical block staging + spill/extract discipline to
// b2b_many_avx2.
__attribute__((target("avx2")))
bool b2b4_step(B2b4State& st) {
  if (!(st.lanes[0].active || st.lanes[1].active || st.lanes[2].active ||
        st.lanes[3].active))
    return false;
  const uint8_t* blk[4];
  alignas(32) uint64_t tv[4];
  alignas(32) uint64_t fv[4];
  bool finishing[4];
  bool anyfin = false;
  for (int L = 0; L < 4; ++L) {
    B2bLane& ln = st.lanes[L];
    if (!ln.active) {
      std::memset(st.pad[L], 0, 128);
      blk[L] = st.pad[L];
      tv[L] = 0;
      fv[L] = 0;
      finishing[L] = false;
      continue;
    }
    int64_t rem = ln.len - ln.off;
    if (rem > 128) {
      blk[L] = ln.data + ln.off;
      ln.off += 128;
      tv[L] = static_cast<uint64_t>(ln.off);
      fv[L] = 0;
      finishing[L] = false;
    } else {
      std::memset(st.pad[L], 0, 128);
      if (rem > 0) std::memcpy(st.pad[L], ln.data + ln.off, rem);
      blk[L] = st.pad[L];
      tv[L] = static_cast<uint64_t>(ln.len);
      fv[L] = ~0ULL;
      finishing[L] = true;
      anyfin = true;
    }
  }
  __m256i m[16];
  for (int w = 0; w < 16; ++w)
    m[w] = _mm256_set_epi64x(
        static_cast<long long>(load64(blk[3] + 8 * w)),
        static_cast<long long>(load64(blk[2] + 8 * w)),
        static_cast<long long>(load64(blk[1] + 8 * w)),
        static_cast<long long>(load64(blk[0] + 8 * w)));
  b2b_compress4(st.h, m,
                _mm256_load_si256(reinterpret_cast<const __m256i*>(tv)),
                _mm256_load_si256(reinterpret_cast<const __m256i*>(fv)));
  if (anyfin) {
    for (int w = 0; w < 8; ++w)
      _mm256_store_si256(reinterpret_cast<__m256i*>(st.hbuf[w]), st.h[w]);
    for (int L = 0; L < 4; ++L) {
      if (!finishing[L]) continue;
      for (int w = 0; w < 4; ++w)
        std::memcpy(st.lanes[L].out + 8 * w, &st.hbuf[w][L], 8);
      b2b4_reset_lane(st, L);
    }
    for (int w = 0; w < 8; ++w)
      st.h[w] =
          _mm256_load_si256(reinterpret_cast<const __m256i*>(st.hbuf[w]));
  }
  return true;
}

// 8-lane AVX-512F engine: same lane-refill structure at twice the width,
// with native 64-bit rotates (vprorq) replacing the AVX2 shuffle/shift
// emulation.  The fused variant interleaves a few gear-scan bytes after
// every round, so the scalar chain and the vector rounds share the core.
struct B2b8State {
  B2bLane lanes[8];
  __m512i h[8];
  alignas(64) uint64_t hbuf[8][8];
  alignas(64) uint8_t pad[8][128];
  const uint8_t* const* jptr = nullptr;
  const int64_t* jlen = nullptr;
  uint8_t* outbase = nullptr;
  int64_t njobs = 0, next = 0;
};

__attribute__((target("avx512f")))
inline bool b2b8_reset_lane(B2b8State& st, int L) {
  if (st.next >= st.njobs) {
    st.lanes[L].active = false;
    return false;
  }
  st.lanes[L] = {st.jptr[st.next], st.jlen[st.next], 0,
                 st.outbase + st.next * 32, true};
  ++st.next;
  const uint64_t param = 0x01010000ULL ^ 32ULL;
  for (int w = 0; w < 8; ++w)
    st.hbuf[w][L] = B2B_IV[w] ^ (w == 0 ? param : 0ULL);
  return true;
}

__attribute__((target("avx512f")))
void b2b8_init(B2b8State& st, const uint8_t* const* jptr, const int64_t* jlen,
               uint8_t* outbase, int64_t njobs) {
  st.jptr = jptr;
  st.jlen = jlen;
  st.outbase = outbase;
  st.njobs = njobs;
  st.next = 0;
  std::memset(st.hbuf, 0, sizeof(st.hbuf));
  for (int L = 0; L < 8; ++L) b2b8_reset_lane(st, L);
  for (int w = 0; w < 8; ++w)
    st.h[w] = _mm512_load_si512(reinterpret_cast<const void*>(st.hbuf[w]));
}

// 8x8 uint64 transpose: rows r0..r7 (lane L's 64 message bytes) ->
// out[0..7] (message word w across all 8 lanes).  24 shuffle uops
// replace the 64 scalar loads + 56 insert uops of a set_epi64 build —
// message staging was ~60% of the 8-lane engine's cycles without it.
#define DAT_T8(out, r0, r1, r2, r3, r4, r5, r6, r7)                   \
  {                                                                   \
    __m512i t0 = _mm512_unpacklo_epi64(r0, r1);                       \
    __m512i t1 = _mm512_unpackhi_epi64(r0, r1);                       \
    __m512i t2 = _mm512_unpacklo_epi64(r2, r3);                       \
    __m512i t3 = _mm512_unpackhi_epi64(r2, r3);                       \
    __m512i t4 = _mm512_unpacklo_epi64(r4, r5);                       \
    __m512i t5 = _mm512_unpackhi_epi64(r4, r5);                       \
    __m512i t6 = _mm512_unpacklo_epi64(r6, r7);                       \
    __m512i t7 = _mm512_unpackhi_epi64(r6, r7);                       \
    __m512i u0 = _mm512_shuffle_i64x2(t0, t2, 0x88);                  \
    __m512i u1 = _mm512_shuffle_i64x2(t4, t6, 0x88);                  \
    __m512i u2 = _mm512_shuffle_i64x2(t0, t2, 0xDD);                  \
    __m512i u3 = _mm512_shuffle_i64x2(t4, t6, 0xDD);                  \
    __m512i u4 = _mm512_shuffle_i64x2(t1, t3, 0x88);                  \
    __m512i u5 = _mm512_shuffle_i64x2(t5, t7, 0x88);                  \
    __m512i u6 = _mm512_shuffle_i64x2(t1, t3, 0xDD);                  \
    __m512i u7 = _mm512_shuffle_i64x2(t5, t7, 0xDD);                  \
    out[0] = _mm512_shuffle_i64x2(u0, u1, 0x88);                      \
    out[4] = _mm512_shuffle_i64x2(u0, u1, 0xDD);                      \
    out[2] = _mm512_shuffle_i64x2(u2, u3, 0x88);                      \
    out[6] = _mm512_shuffle_i64x2(u2, u3, 0xDD);                      \
    out[1] = _mm512_shuffle_i64x2(u4, u5, 0x88);                      \
    out[5] = _mm512_shuffle_i64x2(u4, u5, 0xDD);                      \
    out[3] = _mm512_shuffle_i64x2(u6, u7, 0x88);                      \
    out[7] = _mm512_shuffle_i64x2(u6, u7, 0xDD);                      \
  }

// One 8-lane block compression (with lane refill); false when all
// lanes are idle.
__attribute__((target("avx512f")))
bool b2b8_step(B2b8State& st) {
  bool any = false;
  for (int L = 0; L < 8; ++L) any = any || st.lanes[L].active;
  if (!any) return false;
  const uint8_t* blk[8];
  alignas(64) uint64_t tv[8];
  alignas(64) uint64_t fv[8];
  bool finishing[8];
  bool anyfin = false;
  for (int L = 0; L < 8; ++L) {
    B2bLane& ln = st.lanes[L];
    if (!ln.active) {
      std::memset(st.pad[L], 0, 128);
      blk[L] = st.pad[L];
      tv[L] = 0;
      fv[L] = 0;
      finishing[L] = false;
      continue;
    }
    int64_t rem = ln.len - ln.off;
    if (rem > 128) {
      blk[L] = ln.data + ln.off;
      ln.off += 128;
      tv[L] = static_cast<uint64_t>(ln.off);
      fv[L] = 0;
      finishing[L] = false;
    } else {
      std::memset(st.pad[L], 0, 128);
      if (rem > 0) std::memcpy(st.pad[L], ln.data + ln.off, rem);
      blk[L] = st.pad[L];
      tv[L] = static_cast<uint64_t>(ln.len);
      fv[L] = ~0ULL;
      finishing[L] = true;
      anyfin = true;
    }
  }
  __m512i m[16];
  {
    __m512i r[8];
    for (int L = 0; L < 8; ++L)
      r[L] = _mm512_loadu_si512(reinterpret_cast<const void*>(blk[L]));
    DAT_T8(m, r[0], r[1], r[2], r[3], r[4], r[5], r[6], r[7]);
    for (int L = 0; L < 8; ++L)
      r[L] = _mm512_loadu_si512(reinterpret_cast<const void*>(blk[L] + 64));
    DAT_T8((m + 8), r[0], r[1], r[2], r[3], r[4], r[5], r[6], r[7]);
  }
  __m512i v[16];
  for (int i = 0; i < 8; ++i) v[i] = st.h[i];
  for (int i = 0; i < 8; ++i)
    v[8 + i] = _mm512_set1_epi64(static_cast<long long>(B2B_IV[i]));
  v[12] = _mm512_xor_si512(
      v[12], _mm512_load_si512(reinterpret_cast<const void*>(tv)));
  v[14] = _mm512_xor_si512(
      v[14], _mm512_load_si512(reinterpret_cast<const void*>(fv)));
#define DAT_G8(a, b, c, d, x, y)                                    \
  v[a] = _mm512_add_epi64(_mm512_add_epi64(v[a], v[b]), (x));       \
  v[d] = _mm512_ror_epi64(_mm512_xor_si512(v[d], v[a]), 32);        \
  v[c] = _mm512_add_epi64(v[c], v[d]);                              \
  v[b] = _mm512_ror_epi64(_mm512_xor_si512(v[b], v[c]), 24);        \
  v[a] = _mm512_add_epi64(_mm512_add_epi64(v[a], v[b]), (y));       \
  v[d] = _mm512_ror_epi64(_mm512_xor_si512(v[d], v[a]), 16);        \
  v[c] = _mm512_add_epi64(v[c], v[d]);                              \
  v[b] = _mm512_ror_epi64(_mm512_xor_si512(v[b], v[c]), 63);
  for (int r = 0; r < 12; ++r) {
    const uint8_t* s = B2B_SIGMA[r];
    DAT_G8(0, 4, 8, 12, m[s[0]], m[s[1]])
    DAT_G8(1, 5, 9, 13, m[s[2]], m[s[3]])
    DAT_G8(2, 6, 10, 14, m[s[4]], m[s[5]])
    DAT_G8(3, 7, 11, 15, m[s[6]], m[s[7]])
    DAT_G8(0, 5, 10, 15, m[s[8]], m[s[9]])
    DAT_G8(1, 6, 11, 12, m[s[10]], m[s[11]])
    DAT_G8(2, 7, 8, 13, m[s[12]], m[s[13]])
    DAT_G8(3, 4, 9, 14, m[s[14]], m[s[15]])
  }
#undef DAT_G8
  for (int i = 0; i < 8; ++i)
    st.h[i] = _mm512_xor_si512(st.h[i], _mm512_xor_si512(v[i], v[8 + i]));
  if (anyfin) {
    for (int w = 0; w < 8; ++w)
      _mm512_store_si512(reinterpret_cast<void*>(st.hbuf[w]), st.h[w]);
    for (int L = 0; L < 8; ++L) {
      if (!finishing[L]) continue;
      for (int w = 0; w < 4; ++w)
        std::memcpy(st.lanes[L].out + 8 * w, &st.hbuf[w][L], 8);
      b2b8_reset_lane(st, L);
    }
    for (int w = 0; w < 8; ++w)
      st.h[w] = _mm512_load_si512(reinterpret_cast<const void*>(st.hbuf[w]));
  }
  return true;
}

}  // namespace

#else
namespace {
inline bool have_avx512() { return false; }
struct B2b4State {};
struct B2b8State {};
inline void b2b4_init(B2b4State&, const uint8_t* const*, const int64_t*,
                      uint8_t*, int64_t) {}
inline bool b2b4_step(B2b4State&) { return false; }
inline void b2b8_init(B2b8State&, const uint8_t* const*, const int64_t*,
                      uint8_t*, int64_t) {}
inline bool b2b8_step(B2b8State&) { return false; }
}  // namespace
#endif

namespace {

// One fused worker range: gear-scan [rlo, rhi) of the current slab and
// hash this thread's share of the chunks finalized in the previous slab
// (their bytes are one slab behind the scan — still cache-resident).
// Engine pick mirrors dat_blake2b_many_ptrs: AVX-512F 8-lane, AVX2
// 4-lane, scalar loop otherwise.
//
// ``hash_first`` anti-phases the two works across workers: even threads
// scan then hash, odd threads hash then scan, so at any instant half
// the threads run the scalar-port-bound gear chain while the other half
// run the vector-port-bound BLAKE2b rounds.  On SMT siblings the two
// engines then share one physical core's DISJOINT ports — measured on
// the 2-hyperthread dev box, this is where the fused pass's win over
// phase-lockstep execution comes from.  (A per-round instruction-level
// interleave inside one thread measured 35% slower: the 32 live zmm of
// state + message spill as soon as the scalar scan joins the loop.)
void fused_range(const uint8_t* buf, int64_t rlo, int64_t rhi,
                 const uint64_t* tab, uint32_t mask, int64_t thin,
                 int64_t* qdst, int64_t qcap, int64_t* qcnt,
                 const uint8_t* const* jptr, const int64_t* jlen,
                 uint8_t* outb, int64_t njobs, bool hash_first) {
  GearQuad gq;
  gq.init(buf, rlo, rhi, tab, mask, thin, qdst, qcap);
  auto scan = [&] {
    while (gq.advance(1 << 14)) {
    }
  };
  auto hash = [&] {
    if (njobs <= 0) return;
    if (have_avx512()) {
      B2b8State st;
      b2b8_init(st, jptr, jlen, outb, njobs);
      while (b2b8_step(st)) {
      }
    } else if (have_avx2()) {
      B2b4State st;
      b2b4_init(st, jptr, jlen, outb, njobs);
      while (b2b4_step(st)) {
      }
    } else {
      for (int64_t r = 0; r < njobs; ++r)
        b2b_hash256(jptr[r], jlen[r], outb + r * 32);
    }
  };
  if (hash_first) {
    hash();
    scan();
  } else {
    scan();
    hash();
  }
  for (int c = 0; c < 4; ++c) qcnt[c] = gq.m[c];
}

}  // namespace

extern "C" {

// BLAKE2b-256 of n (pointer, length) jobs -> out[r*32..]: the pointer-
// array twin of dat_blake2b_many for payloads that are NOT extents of
// one buffer (hash_many_list's zero-copy span path).  Offsets stay
// offsets; addresses ride a dedicated parameter.  nthreads <= 0 = auto.
int64_t dat_blake2b_many_ptrs(const uint8_t* const* ptrs,
                              const int64_t* lens, int64_t n, uint8_t* out,
                              int64_t nthreads) {
  parallel_for(n, nthreads, 64, [&](int64_t lo, int64_t hi, int64_t) {
    int64_t cnt = hi - lo;
    if (have_avx512()) {
      B2b8State st;
      b2b8_init(st, ptrs + lo, lens + lo, out + lo * 32, cnt);
      while (b2b8_step(st)) {
      }
      return;
    }
    if (have_avx2()) {
      B2b4State st;
      b2b4_init(st, ptrs + lo, lens + lo, out + lo * 32, cnt);
      while (b2b4_step(st)) {
      }
      return;
    }
    for (int64_t r = lo; r < hi; ++r)
      b2b_hash256(ptrs[r], lens[r], out + r * 32);
  });
  return 0;
}

// Fused single-pass content addressing: gear CDC candidates, greedy
// min/max cut selection, and per-chunk BLAKE2b-256 in ONE sweep over
// buf.  Emits chunk end-offsets (exclusive, last == n) into cuts[] and
// 32-byte digests into digests[] (digest r covers [cuts[r-1], cuts[r])).
// thin_bits must be in [5, 31] (the chunking thinning policy; callers
// with smaller min sizes take the two-pass route).  Returns the chunk
// count, DAT_ERR_CAPACITY if cap is too small, or DAT_ERR_BAD_RECORD
// for out-of-range parameters.  Byte-identical cuts and digests to
// dat_gear_candidates + dat_greedy_select + dat_blake2b_many.
int64_t dat_cdc_hash(const uint8_t* buf, int64_t n, int64_t avg_bits,
                     int64_t thin_bits, int64_t min_size, int64_t max_size,
                     int64_t* cuts, uint8_t* digests, int64_t cap,
                     int64_t nthreads) {
  if (avg_bits < 1 || avg_bits > 31 || thin_bits < 5 || thin_bits > 31 ||
      min_size < 1 || max_size < min_size || cap < 1)
    return DAT_ERR_BAD_RECORD;
  if (n <= 0) return 0;
  // wire: GEAR_C1 = 0x9E3779B1
  // wire: GEAR_C2 = 0x85EBCA77
  const uint32_t c1 = 0x9E3779B1u, c2 = 0x85EBCA77u;
  uint64_t tab[256];
  for (uint32_t b = 0; b < 256; ++b) {
    uint64_t lo = static_cast<uint32_t>((b + 1) * c1);
    uint64_t hi = static_cast<uint32_t>((b + 1) * c2);
    tab[b] = lo | (hi << 32);
  }
  const uint32_t mask = (1u << avg_bits) - 1u;
  // slab size: big enough to amortize the per-slab thread fan-out and
  // keep the anti-phase windows long, small enough that a slab plus the
  // trailing chunks being hashed stay cache-resident (the single-DRAM-
  // pass property).  Measured on the dev box (512 MiB stream, max of 5
  // reps): 8 MiB 1.02 GiB/s, 16 MiB 1.27, 32 MiB 1.31 — the fan-out
  // cost dominates below 16 MiB, cache effects are flat to 32 MiB.
  const int64_t SLAB = 32 << 20;
  std::vector<int64_t> cand;
  cand.reserve((SLAB >> thin_bits) + 64);
  size_t ci = 0;      // greedy's cursor into cand
  int64_t start = 0;  // last emitted cut
  int64_t m = 0;      // cuts emitted
  int64_t hm = 0;     // cuts already hashed
  int64_t last_win = -1;
  std::vector<const uint8_t*> jptr;
  std::vector<int64_t> jlen;
  std::vector<int64_t> qslab;
  std::vector<int64_t> qcnt;

  for (int64_t slo = 0; slo < n; slo += SLAB) {
    int64_t shi = slo + SLAB < n ? slo + SLAB : n;
    // cuts decidable from the scanned prefix [0, slo): every candidate
    // through start + max_size is known once the scan passed it
    while (slo - start > max_size) {
      int64_t lo2 = start + min_size;
      int64_t hi2 = start + max_size;
      while (ci < cand.size() && cand[ci] < lo2) ++ci;
      int64_t cut = (ci < cand.size() && cand[ci] <= hi2) ? cand[ci++] : hi2;
      if (m >= cap) return DAT_ERR_CAPACITY;
      cuts[m++] = cut;
      start = cut;
    }
    if (ci > 4096) {  // bound the candidate queue: drop consumed head
      cand.erase(cand.begin(), cand.begin() + static_cast<int64_t>(ci));
      ci = 0;
    }
    // this slab's hash jobs: the chunks finalized above (bytes one slab
    // behind the scan frontier — cache-resident by construction)
    jptr.clear();
    jlen.clear();
    for (int64_t c = hm; c < m; ++c) {
      int64_t cs = c == 0 ? 0 : cuts[c - 1];
      jptr.push_back(buf + cs);
      jlen.push_back(cuts[c] - cs);
    }
    int64_t jo = hm;
    hm = m;
    int64_t njobs = static_cast<int64_t>(jptr.size());
    int64_t span = shi - slo;
    int nt = pick_threads(nthreads, span, 1 << 20);
    // Anti-phase schedule: odd threads hash (all of it, split by bytes)
    // then scan a SMALLER range; even threads only scan.  The skew makes
    // both roles finish together, so the scalar-port gear chain and the
    // vector-port BLAKE2b rounds overlap for the whole slab instead of
    // colliding once the (faster) hash phase drains.  RS/RH is the
    // measured scan:hash single-thread rate ratio; a mis-estimate only
    // shifts work between roles, never correctness.
    const double RS_OVER_RH = 0.55;
    int nh = njobs > 0 ? nt / 2 : 0;  // hash-first thread count
    if (njobs > 0 && nh == 0) nh = 1;
    int ns = nt - nh;
    int64_t hbytes = 0;
    for (int64_t r = 0; r < njobs; ++r) hbytes += jlen[r];
    // per-thread scan quotas: even threads x, odd threads y with
    // x = y + (RS/RH) * hbytes/nh and ns*x + nh*y = span
    int64_t y = nt > 0 && nh > 0
        ? static_cast<int64_t>(
              (span - ns * RS_OVER_RH * (static_cast<double>(hbytes) / nh)) /
              nt)
        : span / (nt > 0 ? nt : 1);
    if (y < 0) y = 0;
    std::vector<int64_t> slo_k(static_cast<size_t>(nt) + 1, 0);
    {
      int64_t acc = 0;
      int64_t x = ns > 0 ? (span - nh * y) / ns : 0;
      for (int k = 0; k < nt; ++k) {
        slo_k[k] = acc;
        acc += (nh > 0 && (k & 1) == 1) ? y : x;
        if (acc > span) acc = span;
      }
      slo_k[nt] = span;
      // rounding slack lands on the last thread's range
    }
    // hash jobs: byte-balanced contiguous shares across the odd threads
    std::vector<int64_t> jsplit(static_cast<size_t>(nt) + 1, njobs);
    jsplit[0] = 0;
    if (njobs > 0) {
      int64_t acc = 0;
      int64_t r = 0;
      int hk = 0;
      for (int k = 1; k <= nt; ++k) {
        if (nh > 0 && ((k - 1) & 1) == 1) {
          ++hk;
          int64_t want = hbytes * hk / nh;
          while (r < njobs && acc < want) acc += jlen[r++];
          jsplit[k] = hk == nh ? njobs : r;
        } else {
          jsplit[k] = jsplit[k - 1];  // scan-only threads take no jobs
        }
      }
      if (nt == 1) jsplit[1] = njobs;
    }
    int64_t qcap = (span / 4 >> thin_bits) + 8;  // any thread may scan
    // up to (nearly) the whole span under the skewed split
    qslab.assign(static_cast<size_t>(nt) * 4 * qcap, 0);
    qcnt.assign(static_cast<size_t>(nt) * 4, 0);
    parallel_for(nt, nt, 1, [&](int64_t k0, int64_t, int64_t) {
      int k = static_cast<int>(k0);
      fused_range(buf, slo + slo_k[k], slo + slo_k[k + 1], tab, mask,
                  thin_bits, qslab.data() + k * 4 * qcap, qcap,
                  qcnt.data() + k * 4, jptr.data() + jsplit[k],
                  jlen.data() + jsplit[k], digests + (jo + jsplit[k]) * 32,
                  jsplit[k + 1] - jsplit[k], (k & 1) == 1);
    });
    // ordered merge of this slab's candidates (global window dedup at
    // every seam, exactly like dat_gear_candidates' merge)
    for (int64_t q = 0; q < nt * 4; ++q) {
      if (qcnt[q] < 0) return DAT_ERR_CAPACITY;  // can't trip with thinning
      for (int64_t i = 0; i < qcnt[q]; ++i) {
        int64_t p = qslab[q * qcap + i];
        int64_t win = p >> thin_bits;
        if (win == last_win) continue;
        last_win = win;
        cand.push_back(p);
      }
    }
  }
  // drain: the exact dat_greedy_select tail over the remaining stream
  while (n - start > max_size) {
    int64_t lo2 = start + min_size;
    int64_t hi2 = start + max_size;
    while (ci < cand.size() && cand[ci] < lo2) ++ci;
    int64_t cut = (ci < cand.size() && cand[ci] <= hi2) ? cand[ci++] : hi2;
    if (m >= cap) return DAT_ERR_CAPACITY;
    cuts[m++] = cut;
    start = cut;
  }
  if (m >= cap) return DAT_ERR_CAPACITY;
  cuts[m++] = n;
  // hash the tail chunks (no scan left to interleave with)
  jptr.clear();
  jlen.clear();
  for (int64_t c = hm; c < m; ++c) {
    int64_t cs = c == 0 ? 0 : cuts[c - 1];
    jptr.push_back(buf + cs);
    jlen.push_back(cuts[c] - cs);
  }
  if (!jptr.empty())
    dat_blake2b_many_ptrs(jptr.data(), jlen.data(),
                          static_cast<int64_t>(jptr.size()),
                          digests + hm * 32, nthreads);
  return m;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Transport pump (ISSUE 14): batched-syscall socket loops.
//
// The Python wire pumps (session/transport.py) cost one interpreter
// round-trip per 64 KiB chunk — at r06 that path, not the crypto, was
// the host e2e floor (ROADMAP item 5).  These entry points move whole
// BATCHES of wire bytes per ctypes call (the GIL is released for the
// call's entire duration), so the interpreter sees one wakeup per
// multi-megabyte slab instead of one per chunk:
//
//   dat_pump_probe      which batched syscalls this kernel serves
//   dat_pump_recv_scan  blocking first read + MSG_DONTWAIT recvmmsg
//                       drain + frame index over the received prefix
//                       (the SAME dat_split_frames scanner — one
//                       owner, so the pump cannot fork the framing)
//   dat_pump_send       gather-send spans to a blocking fd
//                       (sendmmsg batches; writev fallback)
//   dat_pump_send_nb    gather-send until EAGAIN on a non-blocking fd
//                       (the fan-out hot path: spans are BroadcastLog
//                       segment memory, never Python-owned copies)
//
// Every path degrades: ENOSYS / ENOTSOCK / EOPNOTSUPP fall back to
// plain read/writev batches, so pipes (sidecar --stdio) and kernels
// without the mmsg syscalls serve the same byte stream.

#include <errno.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

// geometry of one batched syscall: messages per mmsg call x iovecs per
// message.  16 x 64 = up to 1024 spans (or 16 recv slices) per kernel
// entry; past that the syscall itself stops being the bottleneck.
constexpr int PUMP_MSGS = 16;
constexpr int PUMP_IOV = 64;

// errno says this fd/kernel cannot serve the mmsg syscall at all (the
// fallback decision, distinct from transient EAGAIN/EINTR)
inline bool mmsg_unsupported(int e) {
  return e == ENOSYS || e == ENOTSOCK || e == EOPNOTSUPP || e == EINVAL;
}

struct SpanCursor {
  const int64_t* addrs;
  const int64_t* lens;
  int64_t n;
  int64_t si = 0;    // current span
  int64_t off = 0;   // bytes of span si already sent
  bool done() const { return si >= n; }
  // fill up to `cap` iovecs from the cursor; returns the count
  int fill(struct iovec* iov, int cap) const {
    int k = 0;
    int64_t s = si, o = off;
    while (k < cap && s < n) {
      if (lens[s] <= o) { ++s; o = 0; continue; }
      iov[k].iov_base = reinterpret_cast<void*>(
          static_cast<uintptr_t>(addrs[s]) + o);
      iov[k].iov_len = static_cast<size_t>(lens[s] - o);
      ++k; ++s; o = 0;
    }
    return k;
  }
  void advance(int64_t nbytes) {
    while (nbytes > 0 && si < n) {
      int64_t left = lens[si] - off;
      if (nbytes < left) { off += nbytes; return; }
      nbytes -= left;
      ++si; off = 0;
    }
  }
};

}  // namespace

extern "C" {

// Runtime probe: bit 0 = recvmmsg served, bit 1 = sendmmsg served.
// A call on fd -1 distinguishes "syscall exists" (EBADF) from "kernel
// does not serve it" (ENOSYS) without touching any real descriptor.
int64_t dat_pump_probe(void) {
  int64_t caps = 0;
  errno = 0;
  if (recvmmsg(-1, nullptr, 0, 0, nullptr) < 0 && errno != ENOSYS)
    caps |= 1;
  errno = 0;
  if (sendmmsg(-1, nullptr, 0, 0) < 0 && errno != ENOSYS)
    caps |= 2;
  return caps;
}

// Batched receive + native frame scan, one GIL-released call:
//
//   1. ONE blocking read() (the wakeup — works on sockets and pipes);
//   2. drain whatever the kernel already buffered with MSG_DONTWAIT
//      recvmmsg batches (never blocks; pipes/old kernels skip this);
//   3. index the received prefix's complete frames with
//      dat_split_frames (same scanner, same error semantics — the
//      Python side hands the index to the decoder's bulk entry).
//
// Returns total bytes received (0 = EOF before any byte, the caller
// re-observes EOF on its next call after a mid-batch EOF), or -errno.
// nframes/consumed/err are dat_split_frames' outputs over the prefix;
// stats[0] counts syscalls made, stats[1] messages (reads) landed —
// stats[1] - stats[0] is the syscalls the batching saved.
//
// cap must hold at least one maximal frame header or the scan could
// never make progress:  // wire: MAX_HEADER_LEN = 11
int64_t dat_pump_recv_scan(int64_t fd, uint8_t* dst, int64_t cap,
                           int64_t slice, int64_t* starts, int64_t* lens,
                           uint8_t* ids, int64_t icap, int64_t* nframes,
                           int64_t* consumed, int64_t* err,
                           int64_t* stats) {
  *nframes = 0;
  *consumed = 0;
  *err = 0;
  stats[0] = 0;
  stats[1] = 0;
  if (cap < 11 || slice < 1) return DAT_ERR_CAPACITY;
  if (slice > cap) slice = cap;
  int64_t total = 0;
  for (;;) {  // the blocking wakeup read
    ssize_t r = read(static_cast<int>(fd), dst, static_cast<size_t>(slice));
    ++stats[0];
    if (r < 0) {
      if (errno == EINTR) continue;
      return -static_cast<int64_t>(errno);
    }
    total = r;
    break;
  }
  if (total == 0) return 0;  // EOF
  ++stats[1];
  // drain only when the wakeup read filled its slice: a short first
  // read means the kernel buffer is (momentarily) empty, and probing
  // it with recvmmsg would just buy an EAGAIN — the exact per-batch
  // syscall this pump exists to save
  bool more = total >= slice;
  while (more && cap - total > 0) {
    struct mmsghdr hdrs[PUMP_MSGS];
    struct iovec iov[PUMP_MSGS];
    int k = 0;
    int64_t off = total;
    while (k < PUMP_MSGS && off < cap) {
      int64_t take = cap - off < slice ? cap - off : slice;
      iov[k].iov_base = dst + off;
      iov[k].iov_len = static_cast<size_t>(take);
      std::memset(&hdrs[k].msg_hdr, 0, sizeof(hdrs[k].msg_hdr));
      hdrs[k].msg_hdr.msg_iov = &iov[k];
      hdrs[k].msg_hdr.msg_iovlen = 1;
      hdrs[k].msg_len = 0;
      off += take;
      ++k;
    }
    int r = recvmmsg(static_cast<int>(fd), hdrs, static_cast<unsigned>(k),
                     MSG_DONTWAIT, nullptr);
    ++stats[0];
    if (r < 0) {
      // EAGAIN: drained.  unsupported (pipe / old kernel): the
      // blocking read stands alone.  EINTR: just deliver what we have
      // — the next pump call re-enters.  Hard errors too: the bytes
      // already received must reach the decoder before the caller can
      // surface anything.
      break;
    }
    // STREAM semantics: each message is an independent recvmsg into a
    // fixed-offset iovec, so a short message followed by a non-empty
    // one (bytes that landed between the two) leaves a HOLE at the
    // layout offsets.  Compact every message's bytes down to the
    // running cursor — the wire must be contiguous in dst.
    int64_t w = total;
    for (int m2 = 0; m2 < r; ++m2) {
      int64_t got = hdrs[m2].msg_len;
      if (got == 0) { more = false; break; }  // EOF: deliver the prefix
      if (dst + w != static_cast<uint8_t*>(iov[m2].iov_base))
        std::memmove(dst + w, iov[m2].iov_base, static_cast<size_t>(got));
      w += got;
      ++stats[1];
      if (got < static_cast<int64_t>(iov[m2].iov_len))
        more = false;  // short message: kernel buffer drained
    }
    total = w;
    if (r < k) more = false;
  }
  int64_t nf = dat_split_frames(dst, total, starts, lens, ids, icap,
                                consumed, err);
  if (nf == DAT_ERR_CAPACITY) {
    // the filled prefix is a complete, valid index (dat_split_frames
    // stores frames [0, icap) and leaves `consumed` one past the last
    // stored frame): the unindexed tail simply re-enters the decoder's
    // overflow, so callers can size the index for the TYPICAL frame
    // density instead of the 2-byte worst case
    nf = icap;
    *err = 0;
  } else if (nf < 0) {
    nf = 0;
    *consumed = 0;
    *err = 0;
  }
  *nframes = nf;
  return total;
}

}  // extern "C"

namespace {

// Shared gather-send core.  Walks the span cursor with sendmmsg
// batches (PUMP_MSGS messages x PUMP_IOV iovecs per syscall — a
// stream socket concatenates them in order) and degrades to plain
// writev batches when the fd/kernel cannot serve sendmmsg.  Partial
// acceptance (short msg_len / short writev) resumes mid-span.
// `stop_on_block`: return the accepted total at EAGAIN (non-blocking
// fan-out peers) instead of treating it as an error.  Returns total
// bytes the kernel accepted, or -errno on a hard error (the caller
// surfaces it; bytes already accepted are gone either way — same
// contract as a failed os.writev).
int64_t pump_send_core(const int64_t* addrs, const int64_t* lens,
                       int64_t n, int fd, bool stop_on_block,
                       int64_t* stats) {
  SpanCursor cur{addrs, lens, n};
  int64_t total = 0;
  bool use_mmsg = true;
  while (!cur.done()) {
    if (use_mmsg) {
      struct mmsghdr hdrs[PUMP_MSGS];
      struct iovec iov[PUMP_MSGS * PUMP_IOV];
      SpanCursor peek = cur;
      int m = 0;
      int filled = 0;
      while (m < PUMP_MSGS && !peek.done()) {
        int k = peek.fill(iov + filled, PUMP_IOV);
        if (k == 0) break;
        std::memset(&hdrs[m].msg_hdr, 0, sizeof(hdrs[m].msg_hdr));
        hdrs[m].msg_hdr.msg_iov = iov + filled;
        hdrs[m].msg_hdr.msg_iovlen = static_cast<size_t>(k);
        hdrs[m].msg_len = 0;
        int64_t span_bytes = 0;
        for (int i = 0; i < k; ++i)
          span_bytes += static_cast<int64_t>(iov[filled + i].iov_len);
        peek.advance(span_bytes);
        filled += k;
        ++m;
      }
      if (m == 0) break;
      int r = sendmmsg(fd, hdrs, static_cast<unsigned>(m),
                       stop_on_block ? MSG_DONTWAIT : 0);
      ++stats[0];
      if (r < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          if (stop_on_block) return total;
          continue;  // blocking fd: spurious; retry
        }
        if (mmsg_unsupported(errno)) {
          use_mmsg = false;  // degrade to the writev loop
          continue;
        }
        return -static_cast<int64_t>(errno);
      }
      bool partial = false;
      for (int i = 0; i < r; ++i) {
        int64_t sent = hdrs[i].msg_len;
        total += sent;
        cur.advance(sent);
        ++stats[1];
        int64_t msg_total = 0;
        for (size_t v = 0; v < hdrs[i].msg_hdr.msg_iovlen; ++v)
          msg_total += static_cast<int64_t>(hdrs[i].msg_hdr.msg_iov[v].iov_len);
        if (sent < msg_total) { partial = true; break; }
      }
      // a partial message (or fewer messages than requested) means the
      // kernel stopped accepting: non-blocking callers return with the
      // accepted total, blocking ones re-enter from the cursor
      if ((partial || r < m) && stop_on_block) return total;
      continue;
    }
    struct iovec iov[PUMP_IOV];
    int k = cur.fill(iov, PUMP_IOV);
    if (k == 0) break;
    ssize_t w = writev(fd, iov, k);
    ++stats[0];
    if (w < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (stop_on_block) return total;
        continue;
      }
      return -static_cast<int64_t>(errno);
    }
    ++stats[1];
    total += w;
    cur.advance(w);
  }
  return total;
}

}  // namespace

extern "C" {

// Gather-send `n` (address, length) spans to a BLOCKING fd.  Returns
// total bytes written (== sum of lens on success) or -errno.
// stats[0] = syscalls, stats[1] = messages/writevs accepted.
int64_t dat_pump_send(const int64_t* addrs, const int64_t* lens,
                      int64_t n, int64_t fd, int64_t* stats) {
  stats[0] = 0;
  stats[1] = 0;
  return pump_send_core(addrs, lens, n, static_cast<int>(fd), false,
                        stats);
}

// Gather-send to a NON-BLOCKING fd: pushes batches until the kernel
// stops accepting (EAGAIN / partial acceptance) and returns the bytes
// accepted so far (>= 0) — the fan-out dispatcher's bookkeeping
// contract, identical to a short os.writev.  Hard errors are -errno
// (EPIPE/EBADF: the caller sheds the peer as a disconnect).
int64_t dat_pump_send_nb(const int64_t* addrs, const int64_t* lens,
                         int64_t n, int64_t fd, int64_t* stats) {
  stats[0] = 0;
  stats[1] = 0;
  return pump_send_core(addrs, lens, n, static_cast<int>(fd), true,
                        stats);
}

}  // extern "C"
