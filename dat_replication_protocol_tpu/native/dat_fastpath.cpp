// CPython extension: the decoder's change-run dispatch loop in C.
//
// The ctypes library (dat_native.cpp) already indexes frames and
// pre-decodes change columns in bulk; what remains per frame on the
// Python side is object construction, ack bookkeeping, and the handler
// call — ~2 us/frame of interpreter work against a ~0.35 us handler
// body.  This module moves everything except the handler call itself
// into C:
//
// * FastAck: a C callable with a lock-free state machine
//   (std::atomic CAS) replacing the Python _FastAck + lock — the
//   handler-returned vs done()-from-another-thread race is settled by
//   a single compare_exchange, with no lock on any path.
// * AckBoard: one atomic outstanding-ack counter per decoder; armed
//   acks increment it, releases decrement, and the release that hits
//   zero calls dec._resume().  Decoder._stalled() consults it.
// * dispatch_changes(): the per-frame loop — slot-built Change
//   objects straight from the columnar numpy buffers (no tolist, no
//   zip, no row tuples), handler vectorcall, ack arming, stall checks.
//
// Built on demand by runtime/fastpath.py (g++, no pybind11 — plain
// CPython C API); everything degrades to the pure-Python loop in
// session/decoder.py when unavailable.
//
// reference: decode.js:144-169 is the loop this accelerates; the
// observable contract (ordering, counters, backpressure, destroy) is
// pinned by tests/test_decoder_bulk.py and the conformance suite.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <cstdint>
#include <cstring>

namespace {

// ack states
enum { FRESH = 0, SYNC_ACKED = 1, ARMED = 2 };

PyObject *s_pending, *s_paused, *s_destroyed, *s_changes, *s_resume;
PyObject *s_key, *s_change, *s_from, *s_to, *s_value, *s_subset;
PyObject *empty_bytes, *empty_str, *empty_tuple;

// ---------------------------------------------------------------------------
// AckBoard
// ---------------------------------------------------------------------------

typedef struct {
    PyObject_HEAD
    std::atomic<long> outstanding;
} AckBoard;

static PyObject *ackboard_new(PyTypeObject *type, PyObject *, PyObject *) {
    AckBoard *self = (AckBoard *)type->tp_alloc(type, 0);
    if (self != nullptr) self->outstanding.store(0);
    return (PyObject *)self;
}

static PyObject *ackboard_get_outstanding(AckBoard *self, void *) {
    return PyLong_FromLong(self->outstanding.load());
}

static PyGetSetDef ackboard_getset[] = {
    {"outstanding", (getter)ackboard_get_outstanding, nullptr,
     "armed (deferred) acks not yet released", nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

static PyTypeObject AckBoard_Type = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "dat_fastpath.AckBoard",            /* tp_name */
    sizeof(AckBoard),                   /* tp_basicsize */
};

// ---------------------------------------------------------------------------
// FastAck
// ---------------------------------------------------------------------------

typedef struct {
    PyObject_HEAD
    PyObject *dec;    // strong ref; needed for _resume on release
    PyObject *board;  // strong ref (AckBoard)
    std::atomic<int> state;
} FastAck;

static void fastack_dealloc(FastAck *self) {
    Py_XDECREF(self->dec);
    Py_XDECREF(self->board);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *fastack_call(FastAck *self, PyObject *, PyObject *) {
    // one-shot: exactly one exchange can observe ARMED, so the release
    // runs at most once; double/late calls are no-ops (same contract
    // as the decoder's _up closures)
    int prev = self->state.exchange(SYNC_ACKED);
    if (prev == ARMED) {
        AckBoard *board = (AckBoard *)self->board;
        long left = board->outstanding.fetch_sub(1) - 1;
        if (left <= 0 && self->dec != nullptr) {
            PyObject *r = PyObject_CallMethodNoArgs(self->dec, s_resume);
            if (r == nullptr) return nullptr;  // propagate handler errors
            Py_DECREF(r);
        }
    }
    Py_RETURN_NONE;
}

static PyTypeObject FastAck_Type = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "dat_fastpath.FastAck",             /* tp_name */
    sizeof(FastAck),                    /* tp_basicsize */
};

static FastAck *fastack_alloc(PyObject *dec, PyObject *board) {
    FastAck *ack = (FastAck *)FastAck_Type.tp_alloc(&FastAck_Type, 0);
    if (ack == nullptr) return nullptr;
    Py_INCREF(dec);
    ack->dec = dec;
    Py_INCREF(board);
    ack->board = board;
    ack->state.store(FRESH);
    return ack;
}

// ---------------------------------------------------------------------------
// dispatch_changes
// ---------------------------------------------------------------------------

struct View {
    Py_buffer buf{};
    bool held = false;
    int acquire(PyObject *obj) {
        if (PyObject_GetBuffer(obj, &buf, PyBUF_SIMPLE) < 0) return -1;
        held = true;
        return 0;
    }
    int acquire_writable(PyObject *obj) {
        // output buffers: a read-only target must raise cleanly, not be
        // silently scribbled on (or fault on a read-only mapping)
        if (PyObject_GetBuffer(obj, &buf, PyBUF_WRITABLE) < 0) return -1;
        held = true;
        return 0;
    }
    ~View() {
        if (held) PyBuffer_Release(&buf);
    }
};

static long get_long_attr(PyObject *o, PyObject *name, int *err) {
    PyObject *v = PyObject_GetAttr(o, name);
    if (v == nullptr) {
        *err = 1;
        return 0;
    }
    long r = PyLong_AsLong(v);
    Py_DECREF(v);
    if (r == -1 && PyErr_Occurred()) *err = 1;
    return r;
}

// dispatch_changes(dec, board, cb_or_None, change_cls, buf,
//                  ids, chg, frm, tov, koff, klen, soff, slen, voff,
//                  vlen, f, row, n, st, starts, lens, sink_or_None)
// When ``sink`` is a list, each dispatched change's raw payload
// (buf[starts[f] : starts[f]+lens[f]]) is appended as bytes — the
// digest-decoder's bulk tap; the caller batch-submits them after the
// run (ordering equivalent to per-frame submit: runs end before any
// following blob frame is processed).
// -> (new_f, new_row, status)  status: 0 ran to a non-change frame or
// n; 1 stalled (armed ack / destroy / pause / pending); 2 a change
// payload failed UTF-8 decoding — the message is left in
// st["decode_error"] and NO Python exception is set, so the caller
// can destroy with ProtocolError without ever confusing a
// handler-raised ValueError for a wire error (handler exceptions
// propagate as real exceptions, same as the Python loop).
// Progress is ALSO written into st["f"]/st["row"] before any error
// return, so a raising handler cannot desync the cursor.
static PyObject *dispatch_changes(PyObject *, PyObject *args) {
    PyObject *dec, *board_o, *cb, *cls_o, *buf_o, *ids_o;
    PyObject *chg_o, *frm_o, *tov_o, *koff_o, *klen_o, *soff_o, *slen_o,
        *voff_o, *vlen_o, *st;
    PyObject *starts_o = Py_None, *flens_o = Py_None, *sink_o = Py_None;
    Py_ssize_t f, row, n;
    if (!PyArg_ParseTuple(
            args, "OOOOOOOOOOOOOOOnnnO|OOO", &dec, &board_o, &cb, &cls_o,
            &buf_o, &ids_o, &chg_o, &frm_o, &tov_o, &koff_o, &klen_o,
            &soff_o, &slen_o, &voff_o, &vlen_o, &f, &row, &n, &st,
            &starts_o, &flens_o, &sink_o))
        return nullptr;
    const bool have_sink = (sink_o != Py_None);
    if (have_sink && (!PyList_CheckExact(sink_o) || starts_o == Py_None ||
                      flens_o == Py_None)) {
        PyErr_SetString(PyExc_TypeError,
                        "sink requires a list plus starts/lens buffers");
        return nullptr;
    }
    if (!PyObject_TypeCheck(board_o, &AckBoard_Type)) {
        PyErr_SetString(PyExc_TypeError, "board must be an AckBoard");
        return nullptr;
    }
    AckBoard *board = (AckBoard *)board_o;
    PyTypeObject *cls = (PyTypeObject *)cls_o;
    const bool have_cb = (cb != Py_None);

    View v_buf, v_ids, v_chg, v_frm, v_tov, v_koff, v_klen, v_soff,
        v_slen, v_voff, v_vlen, v_starts, v_flens;
    if (v_buf.acquire(buf_o) < 0 || v_ids.acquire(ids_o) < 0 ||
        v_chg.acquire(chg_o) < 0 || v_frm.acquire(frm_o) < 0 ||
        v_tov.acquire(tov_o) < 0 || v_koff.acquire(koff_o) < 0 ||
        v_klen.acquire(klen_o) < 0 || v_soff.acquire(soff_o) < 0 ||
        v_slen.acquire(slen_o) < 0 || v_voff.acquire(voff_o) < 0 ||
        v_vlen.acquire(vlen_o) < 0)
        return nullptr;
    if (have_sink && (v_starts.acquire(starts_o) < 0 ||
                      v_flens.acquire(flens_o) < 0))
        return nullptr;
    const int64_t *fstarts =
        have_sink ? (const int64_t *)v_starts.buf.buf : nullptr;
    const int64_t *flens =
        have_sink ? (const int64_t *)v_flens.buf.buf : nullptr;
    const char *buf = (const char *)v_buf.buf.buf;
    const uint8_t *ids = (const uint8_t *)v_ids.buf.buf;
    const uint32_t *chg = (const uint32_t *)v_chg.buf.buf;
    const uint32_t *frm = (const uint32_t *)v_frm.buf.buf;
    const uint32_t *tov = (const uint32_t *)v_tov.buf.buf;
    const int64_t *koff = (const int64_t *)v_koff.buf.buf;
    const int64_t *klen = (const int64_t *)v_klen.buf.buf;
    const int64_t *soff = (const int64_t *)v_soff.buf.buf;
    const int64_t *slen = (const int64_t *)v_slen.buf.buf;
    const int64_t *voff = (const int64_t *)v_voff.buf.buf;
    const int64_t *vlen = (const int64_t *)v_vlen.buf.buf;

    int err = 0;
    long changes = get_long_attr(dec, s_changes, &err);
    if (err) return nullptr;

    int status = 0;
    PyObject *exc = nullptr;

    while (f < n && ids[f] == 1 /* TYPE_CHANGE */) {
        // --- build the Change ------------------------------------------
        PyObject *ch = cls->tp_new(cls, empty_tuple, nullptr);
        if (ch == nullptr) { exc = (PyObject *)1; break; }
        PyObject *key = PyUnicode_DecodeUTF8(buf + koff[row],
                                             (Py_ssize_t)klen[row], nullptr);
        if (key == nullptr) {
            Py_DECREF(ch);
            if (PyErr_ExceptionMatches(PyExc_UnicodeDecodeError)) {
                PyObject *t, *v, *tb;
                PyErr_Fetch(&t, &v, &tb);
                PyErr_NormalizeException(&t, &v, &tb);
                PyObject *msg = v ? PyObject_Str(v) : nullptr;
                if (msg != nullptr) {
                    PyDict_SetItemString(st, "decode_error", msg);
                    Py_DECREF(msg);
                }
                Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
                status = 2;
                break;
            }
            exc = (PyObject *)1;
            break;
        }
        PyObject *val;
        if (vlen[row] >= 0) {
            val = PyBytes_FromStringAndSize(buf + voff[row],
                                            (Py_ssize_t)vlen[row]);
        } else {
            val = empty_bytes;
            Py_INCREF(val);
        }
        PyObject *sub;
        if (slen[row] >= 0) {
            sub = PyUnicode_DecodeUTF8(buf + soff[row],
                                       (Py_ssize_t)slen[row], nullptr);
            if (sub == nullptr &&
                PyErr_ExceptionMatches(PyExc_UnicodeDecodeError)) {
                Py_DECREF(ch);
                Py_DECREF(key);
                Py_XDECREF(val);
                PyObject *t, *v, *tb;
                PyErr_Fetch(&t, &v, &tb);
                PyErr_NormalizeException(&t, &v, &tb);
                PyObject *msg = v ? PyObject_Str(v) : nullptr;
                if (msg != nullptr) {
                    PyDict_SetItemString(st, "decode_error", msg);
                    Py_DECREF(msg);
                }
                Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
                status = 2;
                break;
            }
        } else {
            sub = empty_str;
            Py_INCREF(sub);
        }
        PyObject *cg = PyLong_FromUnsignedLong(chg[row]);
        PyObject *fr = PyLong_FromUnsignedLong(frm[row]);
        PyObject *to = PyLong_FromUnsignedLong(tov[row]);
        int bad = (val == nullptr || sub == nullptr || cg == nullptr ||
                   fr == nullptr || to == nullptr);
        if (!bad) {
            bad = PyObject_SetAttr(ch, s_key, key) < 0 ||
                  PyObject_SetAttr(ch, s_change, cg) < 0 ||
                  PyObject_SetAttr(ch, s_from, fr) < 0 ||
                  PyObject_SetAttr(ch, s_to, to) < 0 ||
                  PyObject_SetAttr(ch, s_value, val) < 0 ||
                  PyObject_SetAttr(ch, s_subset, sub) < 0;
        }
        Py_DECREF(key);
        Py_XDECREF(val);
        Py_XDECREF(sub);
        Py_XDECREF(cg);
        Py_XDECREF(fr);
        Py_XDECREF(to);
        if (bad) { Py_DECREF(ch); exc = (PyObject *)1; break; }

        if (have_sink) {
            PyObject *pl = PyBytes_FromStringAndSize(
                buf + fstarts[f], (Py_ssize_t)flens[f]);
            if (pl == nullptr || PyList_Append(sink_o, pl) < 0) {
                Py_XDECREF(pl);
                Py_DECREF(ch);
                exc = (PyObject *)1;
                break;
            }
            Py_DECREF(pl);
        }
        row += 1;
        f += 1;
        changes += 1;
        // counter visible inside the handler, same as _deliver_change
        {
            PyObject *cv = PyLong_FromLong(changes);
            if (cv == nullptr || PyObject_SetAttr(dec, s_changes, cv) < 0) {
                Py_XDECREF(cv);
                Py_DECREF(ch);
                exc = (PyObject *)1;
                break;
            }
            Py_DECREF(cv);
        }

        if (have_cb) {
            FastAck *ack = fastack_alloc(dec, board_o);
            if (ack == nullptr) { Py_DECREF(ch); exc = (PyObject *)1; break; }
            PyObject *argv[2] = {ch, (PyObject *)ack};
            PyObject *r = PyObject_Vectorcall(cb, argv, 2, nullptr);
            Py_DECREF(ch);
            if (r == nullptr) {
                Py_DECREF(ack);
                exc = (PyObject *)1;
                break;
            }
            Py_DECREF(r);
            // arm iff the handler did NOT ack synchronously.  The CAS
            // settles the cross-thread race: a done() landing between
            // the handler returning and this point flips state to
            // SYNC_ACKED and the CAS fails -> sync path.
            int expected = FRESH;
            if (ack->state.compare_exchange_strong(expected, ARMED)) {
                board->outstanding.fetch_add(1);
                Py_DECREF(ack);
                status = 1;
                break;  // park: the armed release resumes the decoder
            }
            Py_DECREF(ack);
        } else {
            Py_DECREF(ch);  // no handler: drop (reference: decode.js:54-56)
        }

        // destroy / pause / legacy-pending checks (a handler may destroy
        // the decoder or pause an earlier blob reader mid-run)
        PyObject *d = PyObject_GetAttr(dec, s_destroyed);
        if (d == nullptr) { exc = (PyObject *)1; break; }
        int is_destroyed = PyObject_IsTrue(d);
        Py_DECREF(d);
        if (is_destroyed < 0) { exc = (PyObject *)1; break; }
        if (is_destroyed) { status = 1; break; }
        long paused = get_long_attr(dec, s_paused, &err);
        if (err) { exc = (PyObject *)1; break; }
        long pending = get_long_attr(dec, s_pending, &err);
        if (err) { exc = (PyObject *)1; break; }
        if (paused > 0 || pending > 0 || board->outstanding.load() > 0) {
            status = 1;
            break;
        }
    }

    // progress writeback happens even on error: a raising handler must
    // not desync the cursor from the delivered rows
    PyObject *fv = PyLong_FromSsize_t(f);
    PyObject *rv = PyLong_FromSsize_t(row);
    if (fv != nullptr && rv != nullptr) {
        if (exc != nullptr) {
            // preserve the pending exception across the dict stores
            PyObject *t, *val2, *tb;
            PyErr_Fetch(&t, &val2, &tb);
            PyDict_SetItemString(st, "f", fv);
            PyDict_SetItemString(st, "row", rv);
            PyErr_Restore(t, val2, tb);
        } else {
            PyDict_SetItemString(st, "f", fv);
            PyDict_SetItemString(st, "row", rv);
        }
    }
    Py_XDECREF(fv);
    Py_XDECREF(rv);
    if (exc != nullptr) return nullptr;
    return Py_BuildValue("nni", f, row, status);
}


// ---------------------------------------------------------------------------
// encode_change_c — proto2 serialization of one Change (the wire/
// change_codec.py encoder's hot path; byte-identical, tested against it)
// ---------------------------------------------------------------------------

static inline int uvarint_len(uint64_t v) {
    int n = 1;
    while (v >= 0x80) { v >>= 7; n++; }
    return n;
}

static inline int put_uvarint(uint8_t *p, uint64_t v) {
    int i = 0;
    while (v >= 0x80) { p[i++] = (uint8_t)(v | 0x80); v >>= 7; }
    p[i++] = (uint8_t)v;
    return i;
}

static int as_uint32(PyObject *o, const char *name, uint32_t *out) {
    // mirror change_codec._check_uint32: int (incl. bool) in [0, 2^32)
    if (!PyLong_Check(o) && !PyBool_Check(o)) {
        PyObject *r = PyObject_Repr(o);
        PyErr_Format(PyExc_ValueError, "Change.%s must be a uint32, got %s",
                     name, r ? PyUnicode_AsUTF8(r) : "?");
        Py_XDECREF(r);
        return -1;
    }
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(o, &overflow);
    if ((v == -1 && PyErr_Occurred())) return -1;
    if (overflow || v < 0 || v > (long long)0xFFFFFFFFLL) {
        PyObject *r = PyObject_Repr(o);
        PyErr_Format(PyExc_ValueError, "Change.%s must be a uint32, got %s",
                     name, r ? PyUnicode_AsUTF8(r) : "?");
        Py_XDECREF(r);
        return -1;
    }
    *out = (uint32_t)v;
    return 0;
}

// encode_change_c(key, change, from_, to, value_or_None, subset_or_None)
// -> bytes   (proto2 tags 0x0A subset / 0x12 key / 0x18 / 0x20 / 0x28 /
// 0x32 value, ascending field order, absent optionals omitted)
static PyObject *encode_change_c(PyObject *, PyObject *args) {
    PyObject *key_o, *cg_o, *fr_o, *to_o, *val_o, *sub_o;
    if (!PyArg_ParseTuple(args, "OOOOOO", &key_o, &cg_o, &fr_o, &to_o,
                          &val_o, &sub_o))
        return nullptr;
    uint32_t cg, fr, to;
    Py_ssize_t sub_n = 0, key_n = 0, val_n = 0;
    const char *sub_p = nullptr, *key_p = nullptr;
    if (sub_o != Py_None) {
        sub_p = PyUnicode_AsUTF8AndSize(sub_o, &sub_n);
        if (sub_p == nullptr) return nullptr;
    }
    if (key_o == Py_None) {
        PyErr_SetString(PyExc_ValueError, "Change.key is required");
        return nullptr;
    }
    key_p = PyUnicode_AsUTF8AndSize(key_o, &key_n);
    if (key_p == nullptr) return nullptr;
    if (as_uint32(cg_o, "change", &cg) < 0 ||
        as_uint32(fr_o, "from", &fr) < 0 ||
        as_uint32(to_o, "to", &to) < 0)
        return nullptr;
    Py_buffer val_view{};
    bool have_val = (val_o != Py_None);
    if (have_val) {
        if (PyObject_GetBuffer(val_o, &val_view, PyBUF_SIMPLE) < 0)
            return nullptr;
        val_n = val_view.len;
    }

    Py_ssize_t total = 0;
    if (sub_p) total += 1 + uvarint_len(sub_n) + sub_n;
    total += 1 + uvarint_len(key_n) + key_n;
    total += 1 + uvarint_len(cg) + 1 + uvarint_len(fr) + 1 + uvarint_len(to);
    if (have_val) total += 1 + uvarint_len(val_n) + val_n;

    PyObject *out = PyBytes_FromStringAndSize(nullptr, total);
    if (out == nullptr) {
        if (have_val) PyBuffer_Release(&val_view);
        return nullptr;
    }
    uint8_t *p = (uint8_t *)PyBytes_AS_STRING(out);
    if (sub_p) {
        *p++ = 0x0A;
        p += put_uvarint(p, sub_n);
        memcpy(p, sub_p, sub_n);
        p += sub_n;
    }
    *p++ = 0x12;
    p += put_uvarint(p, key_n);
    memcpy(p, key_p, key_n);
    p += key_n;
    *p++ = 0x18; p += put_uvarint(p, cg);
    *p++ = 0x20; p += put_uvarint(p, fr);
    *p++ = 0x28; p += put_uvarint(p, to);
    if (have_val) {
        *p++ = 0x32;
        p += put_uvarint(p, val_n);
        memcpy(p, val_view.buf, val_n);
        p += val_n;
        PyBuffer_Release(&val_view);
    }
    return out;
}


// ---------------------------------------------------------------------------
// decode_change_c — one proto2 Change payload -> a Change object (the
// streaming scanner's per-frame decoder; semantics mirror
// wire/change_codec.py:decode_change, incl. uint32 truncation and
// unknown-field skipping; all malformed input -> ValueError)
// ---------------------------------------------------------------------------

static int read_uvarint(const uint8_t *p, Py_ssize_t n, Py_ssize_t *i,
                        uint64_t *out) {
    uint64_t v = 0;
    int shift = 0;
    while (*i < n) {
        uint8_t b = p[(*i)++];
        if (shift >= 64 || (shift == 63 && (b & 0x7E))) {
            PyErr_SetString(PyExc_ValueError,
                            "corrupt Change payload: varint exceeds 64 bits");
            return -1;
        }
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = v;
            return 0;
        }
        shift += 7;
    }
    PyErr_SetString(PyExc_ValueError,
                    "corrupt Change payload: truncated varint");
    return -1;
}

// decode_change_c(change_cls, payload_buffer) -> Change
static PyObject *decode_change_c(PyObject *, PyObject *args) {
    PyObject *cls_o, *buf_o;
    if (!PyArg_ParseTuple(args, "OO", &cls_o, &buf_o)) return nullptr;
    PyTypeObject *cls = (PyTypeObject *)cls_o;
    View v;
    if (v.acquire(buf_o) < 0) return nullptr;
    const uint8_t *p = (const uint8_t *)v.buf.buf;
    Py_ssize_t n = v.buf.len;
    Py_ssize_t i = 0;

    PyObject *key = nullptr, *value = nullptr, *subset = nullptr;
    uint32_t cg = 0, fr = 0, to = 0;
    bool have_cg = false, have_fr = false, have_to = false;

    while (i < n) {
        uint64_t tag;
        if (read_uvarint(p, n, &i, &tag) < 0) goto fail;
        switch (tag & 7) {
            case 0: {  // varint
                uint64_t val;
                if (read_uvarint(p, n, &i, &val) < 0) goto fail;
                if (tag == 0x18) { cg = (uint32_t)val; have_cg = true; }
                else if (tag == 0x20) { fr = (uint32_t)val; have_fr = true; }
                else if (tag == 0x28) { to = (uint32_t)val; have_to = true; }
                break;
            }
            case 2: {  // length-delimited
                uint64_t ln;
                if (read_uvarint(p, n, &i, &ln) < 0) goto fail;
                if ((uint64_t)(n - i) < ln) {
                    PyErr_SetString(PyExc_ValueError,
                                    "corrupt Change payload: truncated "
                                    "length-delimited field");
                    goto fail;
                }
                if (tag == 0x12) {
                    Py_XDECREF(key);
                    key = PyUnicode_DecodeUTF8((const char *)p + i,
                                               (Py_ssize_t)ln, nullptr);
                    if (key == nullptr) {
                        // mirror the Python path: UnicodeDecodeError IS
                        // a ValueError; let it propagate as-is
                        goto fail;
                    }
                } else if (tag == 0x0A) {
                    Py_XDECREF(subset);
                    subset = PyUnicode_DecodeUTF8((const char *)p + i,
                                                  (Py_ssize_t)ln, nullptr);
                    if (subset == nullptr) goto fail;
                } else if (tag == 0x32) {
                    Py_XDECREF(value);
                    value = PyBytes_FromStringAndSize((const char *)p + i,
                                                      (Py_ssize_t)ln);
                    if (value == nullptr) goto fail;
                }
                i += (Py_ssize_t)ln;
                break;
            }
            case 5:  // fixed32 (unknown field skip)
                if (n - i < 4) {
                    PyErr_SetString(PyExc_ValueError,
                                    "corrupt Change payload: truncated "
                                    "fixed32 field");
                    goto fail;
                }
                i += 4;
                break;
            case 1:  // fixed64 (unknown field skip)
                if (n - i < 8) {
                    PyErr_SetString(PyExc_ValueError,
                                    "corrupt Change payload: truncated "
                                    "fixed64 field");
                    goto fail;
                }
                i += 8;
                break;
            default:
                PyErr_Format(PyExc_ValueError,
                             "unsupported protobuf wire type %d",
                             (int)(tag & 7));
                goto fail;
        }
    }
    if (key == nullptr || !have_cg || !have_fr || !have_to) {
        PyErr_SetString(PyExc_ValueError,
                        "Change payload missing required fields");
        goto fail;
    }
    {
        PyObject *ch = cls->tp_new(cls, empty_tuple, nullptr);
        if (ch == nullptr) goto fail;
        PyObject *cgo = PyLong_FromUnsignedLong(cg);
        PyObject *fro = PyLong_FromUnsignedLong(fr);
        PyObject *too = PyLong_FromUnsignedLong(to);
        if (value == nullptr) { value = empty_bytes; Py_INCREF(value); }
        if (subset == nullptr) { subset = empty_str; Py_INCREF(subset); }
        int bad = (cgo == nullptr || fro == nullptr || too == nullptr);
        if (!bad) {
            bad = PyObject_SetAttr(ch, s_key, key) < 0 ||
                  PyObject_SetAttr(ch, s_change, cgo) < 0 ||
                  PyObject_SetAttr(ch, s_from, fro) < 0 ||
                  PyObject_SetAttr(ch, s_to, too) < 0 ||
                  PyObject_SetAttr(ch, s_value, value) < 0 ||
                  PyObject_SetAttr(ch, s_subset, subset) < 0;
        }
        Py_XDECREF(cgo);
        Py_XDECREF(fro);
        Py_XDECREF(too);
        Py_DECREF(key);
        Py_DECREF(value);
        Py_DECREF(subset);
        if (bad) { Py_DECREF(ch); return nullptr; }
        return ch;
    }
fail:
    Py_XDECREF(key);
    Py_XDECREF(value);
    Py_XDECREF(subset);
    return nullptr;
}


// ---------------------------------------------------------------------------
// bytes_spans — fill (address, length) arrays for a list of bytes
// objects, so the ctypes hash engine can consume payload lists without
// a b"".join copy (the join was ~25% of the routed host-hash path).
// Returns False when any item is not bytes (caller falls back).
// ---------------------------------------------------------------------------

static PyObject *bytes_spans(PyObject *, PyObject *args) {
    PyObject *list_o, *addrs_o, *lens_o;
    if (!PyArg_ParseTuple(args, "OOO", &list_o, &addrs_o, &lens_o))
        return nullptr;
    if (!PyList_CheckExact(list_o)) {
        PyErr_SetString(PyExc_TypeError, "payloads must be a list");
        return nullptr;
    }
    View v_addrs, v_lens;
    if (v_addrs.acquire_writable(addrs_o) < 0 ||
        v_lens.acquire_writable(lens_o) < 0)
        return nullptr;
    Py_ssize_t n = PyList_GET_SIZE(list_o);
    if (v_addrs.buf.len < (Py_ssize_t)(n * sizeof(int64_t)) ||
        v_lens.buf.len < (Py_ssize_t)(n * sizeof(int64_t))) {
        PyErr_SetString(PyExc_ValueError, "span arrays too small");
        return nullptr;
    }
    int64_t *addrs = (int64_t *)v_addrs.buf.buf;
    int64_t *lens = (int64_t *)v_lens.buf.buf;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *it = PyList_GET_ITEM(list_o, i);
        if (!PyBytes_CheckExact(it)) Py_RETURN_FALSE;
        addrs[i] = (int64_t)(intptr_t)PyBytes_AS_STRING(it);
        lens[i] = (int64_t)PyBytes_GET_SIZE(it);
    }
    Py_RETURN_TRUE;
}

static PyMethodDef module_methods[] = {
    {"dispatch_changes", dispatch_changes, METH_VARARGS,
     "Dispatch a run of change frames from columnar buffers."},
    {"encode_change_c", encode_change_c, METH_VARARGS,
     "Serialize one Change to proto2 bytes (byte-identical to "
     "wire.change_codec.encode_change)."},
    {"decode_change_c", decode_change_c, METH_VARARGS,
     "Parse one proto2 Change payload into a Change object "
     "(semantics of wire.change_codec.decode_change)."},
    {"bytes_spans", bytes_spans, METH_VARARGS,
     "Fill int64 (address, length) arrays for a list of bytes "
     "objects; False if any item is not bytes."},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "dat_fastpath",
    "C dispatch loop for the decoder's bulk change path.", -1,
    module_methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_dat_fastpath(void) {
    s_pending = PyUnicode_InternFromString("_pending");
    s_paused = PyUnicode_InternFromString("_paused_readers");
    s_destroyed = PyUnicode_InternFromString("destroyed");
    s_changes = PyUnicode_InternFromString("changes");
    s_resume = PyUnicode_InternFromString("_resume");
    s_key = PyUnicode_InternFromString("key");
    s_change = PyUnicode_InternFromString("change");
    s_from = PyUnicode_InternFromString("from_");
    s_to = PyUnicode_InternFromString("to");
    s_value = PyUnicode_InternFromString("value");
    s_subset = PyUnicode_InternFromString("subset");
    empty_bytes = PyBytes_FromStringAndSize(nullptr, 0);
    empty_str = PyUnicode_FromString("");
    empty_tuple = PyTuple_New(0);
    if (s_pending == nullptr || s_paused == nullptr ||
        s_destroyed == nullptr || s_changes == nullptr ||
        s_resume == nullptr || s_key == nullptr || s_change == nullptr ||
        s_from == nullptr || s_to == nullptr || s_value == nullptr ||
        s_subset == nullptr || empty_bytes == nullptr ||
        empty_str == nullptr || empty_tuple == nullptr)
        return nullptr;

    AckBoard_Type.tp_flags = Py_TPFLAGS_DEFAULT;
    AckBoard_Type.tp_new = ackboard_new;
    AckBoard_Type.tp_getset = ackboard_getset;
    if (PyType_Ready(&AckBoard_Type) < 0) return nullptr;

    FastAck_Type.tp_flags = Py_TPFLAGS_DEFAULT;
    FastAck_Type.tp_dealloc = (destructor)fastack_dealloc;
    FastAck_Type.tp_call = (ternaryfunc)fastack_call;
    if (PyType_Ready(&FastAck_Type) < 0) return nullptr;

    PyObject *m = PyModule_Create(&moduledef);
    if (m == nullptr) return nullptr;
    Py_INCREF(&AckBoard_Type);
    if (PyModule_AddObject(m, "AckBoard", (PyObject *)&AckBoard_Type) < 0) {
        Py_DECREF(&AckBoard_Type);
        Py_DECREF(m);
        return nullptr;
    }
    Py_INCREF(&FastAck_Type);
    if (PyModule_AddObject(m, "FastAck", (PyObject *)&FastAck_Type) < 0) {
        Py_DECREF(&FastAck_Type);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
