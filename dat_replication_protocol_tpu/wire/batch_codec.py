"""Columnar ``ChangeBatch`` payload codec (frame type ``TYPE_CHANGE_BATCH``).

N change records as ONE wire frame: column-major fixed-width arrays with
dictionary-coded keys/subsets, so bulk replay decodes with zero per-row
Python (array reinterpretation + a handful of header varints) and the
wire stops re-spelling hot keys on every row (the Jelly-Patch
observation; PAPERS.md).  This is a capability-negotiated extension —
the frame is only ever emitted to peers that advertised
``CAP_CHANGE_BATCH`` (WIRE.md "Capability negotiation"); everything
about the per-record ``Change`` frame is unchanged.

Payload layout (version 1; all integers little-endian, see WIRE.md)::

    u8      version                  (= BATCH_VERSION)
    u8      kw   key-index width     (1 | 2 | 4)
    u8      sw   subset-index width  (0 | 1 | 2 | 4; 0 = batch has none)
    u8      vw   value-length width  (0 | 1 | 2 | 4; 0 = batch has none)
    u8      dw   dict-length width   (1 | 2 | 4)
    varint  nrows
    varint  nkeys                    key-dictionary entry count
    varint  nsubs                    subset-dictionary entry count
    varint  val_heap_len             total bytes of present values
    nkeys x dw    key dict entry lengths
    [key heap]                       concatenated key bytes
    nsubs x dw    subset dict entry lengths
    [subset heap]                    concatenated subset bytes
    nrows x u32   change
    nrows x u32   from
    nrows x u32   to
    nrows x kw    key dict index
    nrows x sw    subset dict index    (all-ones sentinel = absent)
    nrows x vw    value length         (all-ones sentinel = absent)
    [value heap]                     present values, row order

Absent-vs-present-empty survives the roundtrip exactly as in the
per-record codec: an absent optional is the all-ones sentinel, a
present-empty one is a real dict entry / length of 0.  Width choices
guarantee the sentinel can never collide with a valid index/length
(``encode`` picks the smallest width whose all-ones value exceeds the
maximum it must represent).

Three tiers share this layout:

* **native C** — ``dat_encode_change_batch`` (native/dat_native.cpp via
  :func:`..runtime.native.encode_change_batch`) builds the dictionary
  with an open-addressing span hash and writes the payload in one pass:
  the bulk-replay encode path.
* **vectorized Python** — :func:`encode_columns` /
  :func:`decode_change_batch` here; decode is pure numpy (frombuffer
  views + cumsum/take), so even the fallback replays at array speed.
* **JAX feed** — :func:`..batch.feed.decode_batch_device` uploads the
  decoded columns straight to device layout.
"""

from __future__ import annotations

import numpy as np

from .varint import NeedMoreData, decode_uvarint, encode_uvarint

BATCH_VERSION = 1

# the one place the width ladder is written down (encode + decode agree)
_WIDTH_DTYPES = {1: np.uint8, 2: np.uint16, 4: np.uint32}


def _pick_width(max_value: int) -> int:
    """Smallest width whose ALL-ONES value strictly exceeds ``max_value``
    (so the sentinel stays unambiguous)."""
    for w in (1, 2, 4):
        if max_value < (1 << (8 * w)) - 1:
            return w
    raise ValueError(f"value {max_value} exceeds ChangeBatch width ladder")


def _sentinel(width: int) -> int:
    return (1 << (8 * width)) - 1


class _Writer:
    __slots__ = ("parts",)

    def __init__(self) -> None:
        self.parts: list[bytes] = []

    def u8(self, v: int) -> None:
        self.parts.append(bytes((v,)))

    def varint(self, v: int) -> None:
        self.parts.append(encode_uvarint(v))

    def array(self, arr: np.ndarray) -> None:
        self.parts.append(arr.tobytes())

    def raw(self, b) -> None:
        self.parts.append(bytes(b))

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


def encode_rows(rows) -> bytes:
    """Encode prepared row tuples as one ChangeBatch payload.

    ``rows`` is a sequence of ``(key: bytes, change: int, from_: int,
    to: int, value: bytes | None, subset: bytes | None)`` — the
    pre-validated shape the session encoder accumulates (uint32 ranges
    checked at submit time, strings already UTF-8).  The dictionary
    build is a Python dict loop (O(rows), the session-encoder tier);
    bulk replay goes through :func:`encode_columns` instead.
    """
    n = len(rows)
    key_dict: dict[bytes, int] = {}
    sub_dict: dict[bytes, int] = {}
    kidx = np.empty(n, np.int64)
    sidx = np.full(n, -1, np.int64)
    vlen = np.full(n, -1, np.int64)
    chg = np.empty(n, np.uint32)
    frm = np.empty(n, np.uint32)
    tov = np.empty(n, np.uint32)
    vals: list[bytes] = []
    for r, (key, cg, fr, to, val, sub) in enumerate(rows):
        i = key_dict.setdefault(key, len(key_dict))
        kidx[r] = i
        if sub is not None:
            sidx[r] = sub_dict.setdefault(sub, len(sub_dict))
        if val is not None:
            vlen[r] = len(val)
            vals.append(val)
        chg[r] = cg
        frm[r] = fr
        tov[r] = to
    return _encode_sections(
        n, list(key_dict), list(sub_dict), kidx, sidx, vlen,
        chg, frm, tov, b"".join(vals),
    )


def encode_columns(cols) -> bytes:
    """Encode decoded change columns (a ``runtime.replay.ChangeColumns``
    or anything with its fields) as one ChangeBatch payload — the bulk
    replay encode path.  Uses the native C encoder when available; the
    Python fallback extracts key/subset/value spans per row (fallback
    grade) but packs every column vectorized."""
    from ..runtime import native

    n = len(cols.change)
    payload = native.encode_change_batch(
        cols.buf, n, cols.change, cols.from_, cols.to,
        cols.key_off, cols.key_len, cols.sub_off, cols.sub_len,
        cols.val_off, cols.val_len,
    )
    if payload is not None:
        return payload
    buf = cols.buf
    mv = memoryview(np.ascontiguousarray(buf, dtype=np.uint8)).cast("B")
    rows = []
    for r in range(n):
        ko, kl = int(cols.key_off[r]), int(cols.key_len[r])
        so, sl = int(cols.sub_off[r]), int(cols.sub_len[r])
        vo, vl = int(cols.val_off[r]), int(cols.val_len[r])
        rows.append((
            bytes(mv[ko:ko + kl]),
            int(cols.change[r]), int(cols.from_[r]), int(cols.to[r]),
            bytes(mv[vo:vo + vl]) if vl >= 0 else None,
            bytes(mv[so:so + sl]) if sl >= 0 else None,
        ))
    return encode_rows(rows)


def _encode_sections(n, keys: list[bytes], subs: list[bytes],
                     kidx: np.ndarray, sidx: np.ndarray, vlen: np.ndarray,
                     chg: np.ndarray, frm: np.ndarray, tov: np.ndarray,
                     val_heap: bytes) -> bytes:
    """Assemble the payload from dictionary lists + index/len columns
    (sidx/vlen use -1 for absent; widths and sentinels chosen here)."""
    nkeys, nsubs = len(keys), len(subs)
    kw = _pick_width(max(nkeys - 1, 0))
    sw = 0 if nsubs == 0 else _pick_width(nsubs - 1)
    max_vlen = int(vlen.max()) if n else -1
    vw = 0 if max_vlen < 0 else _pick_width(max_vlen)
    all_lens = [len(k) for k in keys] + [len(s) for s in subs]
    dw = _pick_width(max(all_lens) if all_lens else 0)
    w = _Writer()
    w.u8(BATCH_VERSION)
    w.u8(kw)
    w.u8(sw)
    w.u8(vw)
    w.u8(dw)
    w.varint(n)
    w.varint(nkeys)
    w.varint(nsubs)
    w.varint(len(val_heap))
    ddt = _WIDTH_DTYPES[dw]
    w.array(np.asarray([len(k) for k in keys], dtype=ddt))
    w.raw(b"".join(keys))
    w.array(np.asarray([len(s) for s in subs], dtype=ddt))
    w.raw(b"".join(subs))
    w.array(np.ascontiguousarray(chg, dtype="<u4"))
    w.array(np.ascontiguousarray(frm, dtype="<u4"))
    w.array(np.ascontiguousarray(tov, dtype="<u4"))
    w.array(kidx.astype(_WIDTH_DTYPES[kw]))
    if sw:
        s = np.where(sidx < 0, _sentinel(sw), sidx)
        w.array(s.astype(_WIDTH_DTYPES[sw]))
    if vw:
        v = np.where(vlen < 0, _sentinel(vw), vlen)
        w.array(v.astype(_WIDTH_DTYPES[vw]))
    w.raw(val_heap)
    return w.getvalue()


def estimate_per_record_bytes(key_lens: np.ndarray, sub_lens: np.ndarray,
                              val_lens: np.ndarray,
                              chg: np.ndarray, frm: np.ndarray,
                              tov: np.ndarray) -> int:
    """Exact total wire bytes the same rows would cost as per-record
    ``Change`` frames — the ``wire.batch.bytes_saved`` counter's
    reference.  Vectorized uvarint-size arithmetic; -1 lens mean absent
    optionals, matching the codec."""
    # uvarint size via bit_length: ((bits - 1) // 7) + 1, bits >= 1
    def vsz(a) -> np.ndarray:
        a = np.asarray(a, dtype=np.uint64)
        bits = np.zeros(a.shape, np.int64)
        x = a.copy()
        while True:
            nz = x > 0
            if not nz.any():
                break
            bits[nz] += 1
            x = x >> np.uint64(1)
        bits = np.maximum(bits, 1)
        return (bits - 1) // 7 + 1

    kl = key_lens.astype(np.int64)
    sl = sub_lens.astype(np.int64)
    vl = val_lens.astype(np.int64)
    payload = 1 + vsz(kl) + kl
    payload = payload + np.where(sl >= 0, 1 + vsz(np.maximum(sl, 0)) + sl, 0)
    payload = payload + 1 + vsz(chg) + 1 + vsz(frm) + 1 + vsz(tov)
    payload = payload + np.where(vl >= 0, 1 + vsz(np.maximum(vl, 0)) + vl, 0)
    return int((payload + vsz(payload + 1) + 1).sum())


def decode_change_batch(payload, base: int = 0, buf=None):
    """Decode one ChangeBatch payload into change columns.

    Returns a :class:`..runtime.replay.ChangeColumns` whose ``buf`` is
    the payload itself (as uint8) and whose string/bytes extents point
    at the dictionary heaps / value heap inside it.  Callers replaying a
    whole log pass ``base`` (the payload's absolute offset) together
    with ``buf`` (the enclosing log buffer) so the extents address the
    log buffer directly — ``base`` without ``buf`` would return extents
    that overrun the payload.  Pure numpy: the only
    per-row work is ``np.take`` over the dictionaries — no Python loop,
    no per-row objects.  Raises ``ValueError`` on any structural
    corruption (bad version/width, truncated section, out-of-range
    index, heap-length mismatch, invalid dictionary UTF-8).
    """
    from ..runtime.replay import ChangeColumns

    if isinstance(payload, np.ndarray):
        arr = np.ascontiguousarray(payload, dtype=np.uint8)
        data = arr.tobytes() if len(arr) < 64 else None
    else:
        arr = np.frombuffer(payload, dtype=np.uint8)
        data = None
    total = len(arr)
    head = bytes(arr[: min(64, total)]) if data is None else data
    try:
        if total < 9:
            raise NeedMoreData("short batch header")
        version = head[0]
        if version != BATCH_VERSION:
            raise ValueError(f"unsupported ChangeBatch version {version}")
        kw, sw, vw, dw = head[1], head[2], head[3], head[4]
        if kw not in (1, 2, 4) or dw not in (1, 2, 4) \
                or sw not in (0, 1, 2, 4) or vw not in (0, 1, 2, 4):
            raise ValueError(
                f"bad ChangeBatch widths kw={kw} sw={sw} vw={vw} dw={dw}")
        i = 5
        nrows, used = decode_uvarint(head, i)
        i += used
        nkeys, used = decode_uvarint(head, i)
        i += used
        nsubs, used = decode_uvarint(head, i)
        i += used
        vheap_len, used = decode_uvarint(head, i)
        i += used
    except NeedMoreData as e:
        raise ValueError(f"corrupt ChangeBatch payload: {e}") from e
    if nrows and nkeys == 0:
        raise ValueError("ChangeBatch has rows but an empty key dictionary")

    def take(nbytes: int, what: str) -> slice:
        nonlocal i
        if i + nbytes > total:
            raise ValueError(
                f"truncated ChangeBatch: {what} needs {nbytes} byte(s) "
                f"at offset {i} of {total}")
        s = slice(i, i + nbytes)
        i += nbytes
        return s

    def column(count: int, width: int, what: str) -> np.ndarray:
        s = take(count * width, what)
        return arr[s].view(f"<u{width}").astype(np.int64)

    klens = column(nkeys, dw, "key dict lengths")
    if (klens < 0).any():
        raise ValueError("negative key dict length")
    kheap_at = i
    kheap = take(int(klens.sum()), "key heap")
    koffs = np.concatenate(([0], np.cumsum(klens)[:-1])) + kheap_at \
        if nkeys else np.zeros(0, np.int64)
    slens = column(nsubs, dw, "subset dict lengths")
    sheap_at = i
    sheap = take(int(slens.sum()), "subset heap")
    soffs = np.concatenate(([0], np.cumsum(slens)[:-1])) + sheap_at \
        if nsubs else np.zeros(0, np.int64)
    chg = arr[take(4 * nrows, "change column")].view("<u4")
    frm = arr[take(4 * nrows, "from column")].view("<u4")
    tov = arr[take(4 * nrows, "to column")].view("<u4")
    kidx = column(nrows, kw, "key index column")
    if nrows and int(kidx.max(initial=0)) >= nkeys:
        raise ValueError("ChangeBatch key index out of dictionary range")
    if sw:
        sidx = column(nrows, sw, "subset index column")
        sent = _sentinel(sw)
        s_absent = sidx == sent
        if nrows and int(np.where(s_absent, 0, sidx).max(initial=0)) >= nsubs \
                and not bool(s_absent.all()):
            raise ValueError("ChangeBatch subset index out of range")
    else:
        sidx = np.zeros(nrows, np.int64)
        s_absent = np.ones(nrows, bool)
    if vw:
        vl = column(nrows, vw, "value length column")
        sent = _sentinel(vw)
        v_absent = vl == sent
        vl = np.where(v_absent, 0, vl)
    else:
        vl = np.zeros(nrows, np.int64)
        v_absent = np.ones(nrows, bool)
    if int(vl.sum()) != vheap_len:
        raise ValueError(
            f"ChangeBatch value heap mismatch: lengths sum to "
            f"{int(vl.sum())}, header says {vheap_len}")
    vheap_at = i
    take(vheap_len, "value heap")
    if i != total:
        raise ValueError(
            f"ChangeBatch payload has {total - i} trailing byte(s)")
    # dictionary UTF-8, validated VECTORIZED: the whole heap decodes
    # once, and no entry may START on a continuation byte — together
    # that proves every single entry is valid UTF-8 (a concatenation of
    # valid strings is valid; aligned boundaries make each segment a
    # whole number of characters).  The per-record codec errors on a
    # bad key, so must this — without a per-entry Python loop.
    _check_heap_utf8(arr, kheap, koffs - kheap_at, "key")
    _check_heap_utf8(arr, sheap, soffs - sheap_at, "subset")

    voffs = (np.concatenate(([0], np.cumsum(vl)[:-1])) + vheap_at
             if nrows else np.zeros(0, np.int64))
    b = np.int64(base)
    if nsubs and nrows:
        sidx_c = np.where(s_absent, 0, sidx)
        sub_off = np.where(s_absent, 0, np.take(soffs, sidx_c) + b)
        sub_len = np.where(s_absent, -1, np.take(slens, sidx_c))
    else:
        sub_off = np.zeros(nrows, np.int64)
        sub_len = np.full(nrows, -1, np.int64)
    return ChangeColumns(
        buf=arr if buf is None else buf,
        change=np.ascontiguousarray(chg),
        from_=np.ascontiguousarray(frm),
        to=np.ascontiguousarray(tov),
        key_off=(np.take(koffs, kidx) + b if nrows
                 else np.zeros(0, np.int64)),
        key_len=(np.take(klens, kidx) if nrows else np.zeros(0, np.int64)),
        sub_off=sub_off,
        sub_len=sub_len,
        val_off=np.where(v_absent, 0, voffs + b),
        val_len=np.where(v_absent, -1, vl),
    )


def _check_heap_utf8(arr: np.ndarray, heap: slice, starts_rel: np.ndarray,
                     what: str) -> None:
    """Validate a dictionary heap's UTF-8 (see decode): one whole-heap
    decode plus a vectorized entry-boundary alignment check."""
    heap_arr = arr[heap]
    if not len(heap_arr):
        return
    try:
        heap_arr.tobytes().decode("utf-8")
    except UnicodeDecodeError as e:
        raise ValueError(
            f"ChangeBatch {what} dictionary is not UTF-8: {e}") from e
    inner = starts_rel[(starts_rel > 0) & (starts_rel < len(heap_arr))]
    if len(inner) and bool(((heap_arr[inner] & 0xC0) == 0x80).any()):
        raise ValueError(
            f"ChangeBatch {what} dictionary entry splits a multibyte "
            f"UTF-8 character")


