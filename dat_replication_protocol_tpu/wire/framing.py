"""Multibuffer framing — the L1 wire codec.

Every frame on the wire is (reference: README.md:63-71)::

    | varint( len(payload) + 1 ) | 1-byte type id | payload |

The framed length counts the id byte, which is why the decoder subtracts one
when computing how many payload bytes follow (reference: decode.js:255).

Type ids (reference: encode.js:112 / decode.js:151,155; 0 is reserved for
"scanning a header"):
"""

from __future__ import annotations

from .varint import MAX_VARINT_LEN, decode_uvarint, encode_uvarint

TYPE_HEADER = 0  # parser state only; never a valid frame id
TYPE_CHANGE = 1
TYPE_BLOB = 2
# Columnar bulk-change frame (this package's negotiated extension; NOT
# part of the reference wire — see WIRE.md "ChangeBatch" and PARITY.md).
# Emitted only to peers that advertised CAP_CHANGE_BATCH; a reference
# decoder receiving one fails with its standard unknown-type error,
# which is exactly why the capability handshake exists.
TYPE_CHANGE_BATCH = 3
# Rateless reconciliation frame (negotiated extension, WIRE.md
# "Reconcile"): coded-symbol runs and the begin/more/done/fail control
# messages of the anti-entropy protocol (wire/reconcile_codec.py).
# Same old-peer story as ChangeBatch: never emitted without
# CAP_RECONCILE, unknown-type error otherwise.
TYPE_RECONCILE = 4
# Content-addressed snapshot frame (negotiated extension, WIRE.md
# "Snapshot"): the bootstrap protocol for joiners trimmed past the
# broadcast retention window — manifest, weighted coded-symbol chunk
# reconciliation, and verified chunk transfer
# (wire/snapshot_codec.py).  Same old-peer story as ChangeBatch /
# Reconcile: never emitted without CAP_SNAPSHOT, unknown-type error
# otherwise.
TYPE_SNAPSHOT = 5

KNOWN_TYPES = (TYPE_CHANGE, TYPE_BLOB, TYPE_CHANGE_BATCH, TYPE_RECONCILE,
               TYPE_SNAPSHOT)

# -- capability negotiation (WIRE.md "Capability negotiation") --------------
#
# Capability masks are exchanged OUT OF BAND (session setup / app
# handshake): a session's wire is unidirectional, so the receiving peer
# advertises what it can parse and the encoder is constructed with (or
# later told via Encoder.negotiate) the intersection.  An encoder that
# was never told anything assumes 0 — the reference wire, byte-exact.
CAP_CHANGE_BATCH = 1  # peer parses TYPE_CHANGE_BATCH frames
CAP_RECONCILE = 2  # peer parses TYPE_RECONCILE frames
CAP_SNAPSHOT = 4  # peer parses TYPE_SNAPSHOT frames

# Everything this package's Decoder can parse (the mask a receiver
# advertises during session setup).
LOCAL_CAPS = CAP_CHANGE_BATCH | CAP_RECONCILE | CAP_SNAPSHOT

# Upper bound on header size: 10 varint bytes + 1 id byte.
MAX_HEADER_LEN = MAX_VARINT_LEN + 1


def frame_header(payload_len: int, type_id: int) -> bytes:
    """Build the wire header for a frame with ``payload_len`` payload bytes.

    The reference amortizes header allocation through a shared 65536-byte pool
    (reference: encode.js:6-7,124-137); in Python small-bytes construction is
    already pooled by the allocator, so the header is built directly.
    Single-byte-varint frames (payload < 127 bytes — every digest reply
    and most change records) skip the generic varint encoder.
    """
    if payload_len < 127:
        return bytes((payload_len + 1, type_id))
    return encode_uvarint(payload_len + 1) + bytes((type_id,))


def frame(type_id: int, payload: bytes) -> bytes:
    """A complete frame: header + payload. Used by tests and golden fixtures."""
    return frame_header(len(payload), type_id) + payload


def header_len(payload_len: int) -> int:
    """Byte length of ``frame_header(payload_len, ·)``: the varint of
    ``payload_len + 1`` plus the id byte.  The tracing layer uses this
    to recover a frame's wire START offset (and total wire length) from
    its payload length alone — both peers must compute the same number,
    so it lives here next to the encoder it mirrors."""
    if payload_len < 127:
        return 2
    v = payload_len + 1
    n = 1
    while v >= 0x80:
        v >>= 7
        n += 1
    return n + 1


def frame_wire_len(payload_len: int) -> int:
    """Total wire bytes of a frame with ``payload_len`` payload bytes."""
    return header_len(payload_len) + payload_len


def iter_frames(wire):
    """Walk a complete recorded frame stream: yields ``(start, type_id,
    payload_start, end)`` per frame, where ``wire[payload_start:end]``
    is the payload and ``wire[start:end]`` the whole frame.  The ONE
    owner of the header walk over recorded wire (cold-log replay, the
    bench's chaos-arm frame scan) — every hand-rolled copy of the
    varint/id-byte slicing is a layout fork that must track header
    changes in lockstep."""
    at = 0
    total = len(wire)
    while at < total:
        flen, used = decode_uvarint(wire[at:at + MAX_VARINT_LEN])
        end = at + used + flen
        yield at, wire[at + used], at + used + 1, end
        at = end


class ProtocolError(Exception):
    """Raised (and passed to destroy) on malformed wire data.

    The reference's sole detected fault is an unknown type id
    (reference: decode.js:159-161); this codec also rejects oversized varint
    headers.

    Structured context (ROBUSTNESS.md): a failure that can name where in
    the session it happened carries ``frame`` (0-based index of the frame
    being parsed/delivered when the fault surfaced), ``offset`` (wire
    bytes accepted up to the fault), and ``cause`` (the underlying
    exception, e.g. the ``OSError`` of a dead transport).  All three are
    optional so the bare ``ProtocolError("msg")`` form keeps working;
    when present they are folded into ``str(err)`` so even unstructured
    logging shows them.
    """

    def __init__(self, message: str = "", *, frame: int | None = None,
                 offset: int | None = None,
                 cause: BaseException | None = None):
        self.frame = frame
        self.offset = offset
        self.cause = cause
        context = []
        if frame is not None:
            context.append(f"frame={frame}")
        if offset is not None:
            context.append(f"byte={offset}")
        if cause is not None:
            context.append(f"cause={type(cause).__name__}: {cause}")
        super().__init__(
            f"{message} [{', '.join(context)}]" if context else message
        )
