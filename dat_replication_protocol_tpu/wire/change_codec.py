"""The ``Change`` record and its protobuf (proto2) wire codec.

Capability parity: the reference compiles `messages/schema.proto` at require
time via the `protocol-buffers` npm package (reference: messages/index.js:5)
and defines one message (reference: messages/schema.proto:1-8)::

    message Change {
      optional string subset = 1;
      required string key    = 2;
      required uint32 change = 3;
      required uint32 from   = 4;
      required uint32 to     = 5;
      optional bytes  value  = 6;
    }

Semantics: row ``key`` moved from version ``from`` to version ``to`` by change
sequence number ``change``, carrying the new ``value``, optionally scoped to a
``subset`` (a sub-dataset). Decoded absent optionals default to ``''``/``b''``
— the reference conformance suite asserts ``subset: ''`` on a change encoded
without one (reference: test/basic.js:10-17).

This is a hand-rolled, dependency-free proto2 codec for exactly this message,
byte-compatible with standard protobuf encoders (fields emitted in ascending
field-number order, absent optionals omitted).
"""

from __future__ import annotations

import dataclasses

# the ONE shared lazy binding to runtime.fastpath.get (env decision
# re-read per call — this codec once kept a private cache that froze
# the decision while the decoder's re-read it, the split-brain
# datlint's env-cache-policy rule now rejects; the shared gate module
# keeps the two layers from re-forking)
from .._fastpath_gate import fastpath_mod as _fastpath_mod
from .varint import NeedMoreData, decode_uvarint, encode_uvarint

_UINT32_MAX = 0xFFFFFFFF

# Precomputed proto2 tags: (field_number << 3) | wire_type
_TAG_SUBSET = (1 << 3) | 2  # len-delimited
_TAG_KEY = (2 << 3) | 2  # len-delimited
_TAG_CHANGE = (3 << 3) | 0  # varint
_TAG_FROM = (4 << 3) | 0  # varint
_TAG_TO = (5 << 3) | 0  # varint
_TAG_VALUE = (6 << 3) | 2  # len-delimited


@dataclasses.dataclass(slots=True)
class Change:
    """One replicated row mutation.

    ``from_`` / ``to`` carry the version transition (named with a trailing
    underscore because ``from`` is a Python keyword; dict conversion uses the
    wire names).  ``slots=True``: the decoder's bulk path constructs one
    of these per change frame — slot storage shaves ~40% off construction
    and a third off memory at the million-row scale of BASELINE config 2.
    """

    key: str
    change: int
    from_: int
    to: int
    value: bytes | None = None
    subset: str | None = None

    @classmethod
    def from_dict(cls, d: dict) -> "Change":
        if "from" in d:
            from_ = d["from"]
        elif "from_" in d:
            from_ = d["from_"]
        else:
            raise KeyError("from")  # required field, same as 'key'/'to'
        return cls(
            key=d["key"],
            change=d["change"],
            from_=from_,
            to=d["to"],
            value=d.get("value"),
            subset=d.get("subset"),
        )

    def to_dict(self) -> dict:
        return {
            "subset": self.subset,
            "key": self.key,
            "change": self.change,
            "from": self.from_,
            "to": self.to,
            "value": self.value,
        }


def _check_uint32(name: str, v: int) -> int:
    if not isinstance(v, int) or v < 0 or v > _UINT32_MAX:
        raise ValueError(f"Change.{name} must be a uint32, got {v!r}")
    return v


def encode_change(change: Change | dict) -> bytes:
    """Serialize a Change to protobuf bytes (proto2 wire format)."""
    return _encode_change_with(_fastpath_mod(), change)


def _encode_change_with(fp, change: Change | dict) -> bytes:
    """Encode with an already-resolved fastpath module (or None).

    Bulk callers (``runtime.replay.encode_change_log`` at ~1M rows)
    bind the gate ONCE per call instead of paying the per-record env
    re-read (~1.3us of a ~3.4us encode); the correctness requirement is
    per-process-flip visibility, which a per-bulk-call read preserves.
    """
    # C serializer for the typed common case (byte-identical — fuzzed
    # against the Python path); exotic-but-accepted inputs (e.g. a
    # list as value, which bytes() coerces) keep the Python semantics.
    # Dict inputs are read field-wise — no intermediate Change object —
    # with from_dict's exact KeyError behavior.
    if fp is not None:
        if isinstance(change, dict):
            if "from" in change:
                fr = change["from"]
            elif "from_" in change:
                fr = change["from_"]
            else:
                raise KeyError("from")  # required, same as from_dict
            key = change["key"]
            cg = change["change"]
            to = change["to"]
            value = change.get("value")
            subset = change.get("subset")
        else:
            key = change.key
            cg = change.change
            fr = change.from_
            to = change.to
            value = change.value
            subset = change.subset
        if (
            isinstance(key, str)
            and (value is None
                 or type(value) in (bytes, bytearray)
                 # strided or multi-byte-item views would fail the C
                 # side's PyBUF_SIMPLE (or, worse, encode nbytes where
                 # the old Python path wrote element counts): only the
                 # plain flat case rides C
                 or (isinstance(value, memoryview) and value.c_contiguous
                     and value.itemsize == 1 and value.ndim == 1))
            and (subset is None or isinstance(subset, str))
        ):
            return fp.encode_change_c(key, cg, fr, to, value, subset)
    return _encode_change_py(change)


def _encode_change_py(change: Change | dict) -> bytes:
    """The pure-Python serializer (also the C path's fuzz oracle)."""
    if isinstance(change, dict):
        change = Change.from_dict(change)
    out = bytearray()
    if change.subset is not None:
        raw = change.subset.encode("utf-8")
        out.append(_TAG_SUBSET)
        out += encode_uvarint(len(raw))
        out += raw
    if change.key is None:
        raise ValueError("Change.key is required")
    raw = change.key.encode("utf-8")
    out.append(_TAG_KEY)
    out += encode_uvarint(len(raw))
    out += raw
    out.append(_TAG_CHANGE)
    out += encode_uvarint(_check_uint32("change", change.change))
    out.append(_TAG_FROM)
    out += encode_uvarint(_check_uint32("from", change.from_))
    out.append(_TAG_TO)
    out += encode_uvarint(_check_uint32("to", change.to))
    if change.value is not None:
        raw = bytes(change.value)
        out.append(_TAG_VALUE)
        # length of the SERIALIZED bytes: len(value) on e.g. a 4-byte-
        # itemsize memoryview is the element count, which would stamp a
        # length prefix shorter than the payload written below (latent
        # wire corruption, caught by the round-5 C-parity review)
        out += encode_uvarint(len(raw))
        out += raw
    return bytes(out)


def decode_change(buf) -> Change:
    """Parse protobuf bytes into a Change.

    Unknown fields are skipped (proto2 semantics). Missing required fields
    raise ``ValueError``; absent optionals default to ``''`` / ``b''``
    (matching what the reference suite observes for ``subset``,
    reference: test/basic.js:16).
    """
    fp = _fastpath_mod()
    if fp is not None:
        # C parser, differentially fuzzed against the Python loop below
        # on random bytes (same records, same error class).  Routed by
        # INSPECTION, not exception-sniffing: a strided numpy array
        # raises ValueError (not BufferError) from the buffer protocol,
        # which would be indistinguishable from a corrupt payload, and a
        # multi-byte-itemsize view parses per-element on the Python path
        # — both must keep their Python semantics.
        t = type(buf)
        if t is bytes or t is bytearray:
            return fp.decode_change_c(Change, buf)
        if t is memoryview:
            mv = buf
        else:
            try:
                mv = memoryview(buf)
            except TypeError:
                mv = None
        if (mv is not None and mv.c_contiguous and mv.itemsize == 1
                and mv.ndim == 1):
            return fp.decode_change_c(Change, mv)
    return _decode_change_py(buf)


def _decode_change_py(buf) -> Change:
    """The pure-Python parser (also the C path's differential oracle)."""
    buf = memoryview(buf)
    n = len(buf)
    i = 0
    subset: str | None = None
    key: str | None = None
    change_seq: int | None = None
    from_: int | None = None
    to: int | None = None
    value: bytes | None = None
    try:
        while i < n:
            tag, used = decode_uvarint(buf, i)
            i += used
            wire_type = tag & 7
            if wire_type == 0:  # varint
                v, used = decode_uvarint(buf, i)
                i += used
                # proto2 uint32 semantics: a wider varint from a foreign
                # encoder truncates to the low 32 bits (keeps this path
                # bit-identical with the native columnar decoder)
                if tag == _TAG_CHANGE:
                    change_seq = v & _UINT32_MAX
                elif tag == _TAG_FROM:
                    from_ = v & _UINT32_MAX
                elif tag == _TAG_TO:
                    to = v & _UINT32_MAX
            elif wire_type == 2:  # length-delimited
                ln, used = decode_uvarint(buf, i)
                i += used
                if i + ln > n:
                    raise NeedMoreData("truncated length-delimited field")
                raw = bytes(buf[i : i + ln])
                i += ln
                if tag == _TAG_SUBSET:
                    subset = raw.decode("utf-8")
                elif tag == _TAG_KEY:
                    key = raw.decode("utf-8")
                elif tag == _TAG_VALUE:
                    value = raw
            elif wire_type == 5:  # fixed32 (unknown field skip)
                if i + 4 > n:
                    raise NeedMoreData("truncated fixed32 field")
                i += 4
            elif wire_type == 1:  # fixed64 (unknown field skip)
                if i + 8 > n:
                    raise NeedMoreData("truncated fixed64 field")
                i += 8
            else:
                raise ValueError(f"unsupported protobuf wire type {wire_type}")
    except NeedMoreData as e:
        raise ValueError(f"corrupt Change payload: {e}") from e
    if key is None or change_seq is None or from_ is None or to is None:
        raise ValueError("Change payload missing required fields")
    return Change(
        key=key,
        change=change_seq,
        from_=from_,
        to=to,
        value=value if value is not None else b"",
        subset=subset if subset is not None else "",
    )
