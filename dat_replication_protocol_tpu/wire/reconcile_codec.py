"""TYPE_RECONCILE payload codec — the anti-entropy control messages.

A reconcile frame's payload is one message of the rateless
reconciliation protocol (WIRE.md "Reconcile"; the symbol math lives in
:mod:`..ops.rateless`, the driver in :mod:`..runtime.reconcile_driver`).
First byte is the subtype; every message is self-delimiting and a
decoder must reject structural corruption (bad subtype/version,
truncated section, trailing bytes) with ``ValueError`` — the session
decoder maps that to its standard :class:`~.framing.ProtocolError`.

Layouts (all integers little-endian, varints unsigned LEB128)::

    BEGIN   u8 subtype=0 | u8 version=1 | varint n_elements
    SYMBOLS u8 subtype=1 | varint start_index | varint count
            | count x 44-byte coded symbols
            (11 u32 words each: [count | checksum lo | checksum hi
             | sum word 0..8) — ops/rateless.py's cell layout verbatim)
    DONE    u8 subtype=2 | varint symbols_used | varint n_digests
            | n_digests x 32-byte digests   (the records the DECODING
            side is missing — "send me these")
    MORE    u8 subtype=3 | varint symbols_seen   (not decoded yet)
    FAIL    u8 subtype=4 | varint symbols_seen | utf-8 reason (to end
            of payload)

Sent only to peers that advertised ``CAP_RECONCILE`` (capability
negotiation is out of band, WIRE.md); a capability-less encoder cannot
emit these frames at all, so the reference wire stays byte-exact.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..ops.rateless import DIGEST_BYTES, SYMBOL_BYTES, SYMBOL_WORDS
from .varint import decode_uvarint, encode_uvarint

RECONCILE_VERSION = 1

RC_BEGIN = 0
RC_SYMBOLS = 1
RC_DONE = 2
RC_MORE = 3
RC_FAIL = 4

_KIND_NAMES = {RC_BEGIN: "begin", RC_SYMBOLS: "symbols", RC_DONE: "done",
               RC_MORE: "more", RC_FAIL: "fail"}


@dataclasses.dataclass(frozen=True)
class ReconcileMsg:
    """One decoded reconcile message.

    ``kind`` is the subtype; the populated fields depend on it:
    ``n`` (begin: sender's element count; more/done/fail:
    symbols seen/used), ``start`` + ``cells`` (symbols: run start index
    and the ``(count, 11)`` u32 cells), ``digests`` (done: the
    ``(k, 32)`` u8 digests being requested), ``reason`` (fail)."""

    kind: int
    n: int = 0
    start: int = 0
    cells: np.ndarray | None = None
    digests: np.ndarray | None = None
    reason: str = ""

    @property
    def kind_name(self) -> str:
        return _KIND_NAMES.get(self.kind, str(self.kind))


def encode_begin(n_elements: int) -> bytes:
    return (bytes((RC_BEGIN, RECONCILE_VERSION))
            + encode_uvarint(n_elements))


def encode_symbols(start: int, cells: np.ndarray) -> bytes:
    cells = np.ascontiguousarray(cells, dtype=np.uint32)
    if cells.ndim != 2 or cells.shape[1] != SYMBOL_WORDS:
        raise ValueError(f"cells must be (k, {SYMBOL_WORDS}) u32")
    if not cells.flags.c_contiguous:
        cells = np.ascontiguousarray(cells)
    return (bytes((RC_SYMBOLS,)) + encode_uvarint(start)
            + encode_uvarint(len(cells))
            + cells.astype("<u4", copy=False).tobytes())


def encode_done(symbols_used: int, digests: np.ndarray) -> bytes:
    digests = np.ascontiguousarray(digests, dtype=np.uint8)
    if digests.ndim != 2 or digests.shape[1] != DIGEST_BYTES:
        raise ValueError(f"digests must be (k, {DIGEST_BYTES}) u8")
    return (bytes((RC_DONE,)) + encode_uvarint(symbols_used)
            + encode_uvarint(len(digests)) + digests.tobytes())


def encode_more(symbols_seen: int) -> bytes:
    return bytes((RC_MORE,)) + encode_uvarint(symbols_seen)


def encode_fail(symbols_seen: int, reason: str) -> bytes:
    return (bytes((RC_FAIL,)) + encode_uvarint(symbols_seen)
            + reason.encode("utf-8"))


def _uvarint(payload, at: int, what: str) -> tuple[int, int]:
    try:
        v, used = decode_uvarint(payload[at:])
    except Exception as e:
        raise ValueError(f"reconcile {what}: bad varint") from e
    return v, at + used


def decode_reconcile(payload) -> ReconcileMsg:
    """Parse one TYPE_RECONCILE payload; ``ValueError`` on any
    structural fault (the decoder maps it to a ProtocolError)."""
    payload = bytes(payload)
    if not payload:
        raise ValueError("empty reconcile payload")
    kind = payload[0]
    if kind == RC_BEGIN:
        if len(payload) < 2:
            raise ValueError("reconcile begin: truncated")
        version = payload[1]
        if version != RECONCILE_VERSION:
            raise ValueError(
                f"reconcile begin: unsupported version {version}")
        n, at = _uvarint(payload, 2, "begin")
        if at != len(payload):
            raise ValueError("reconcile begin: trailing bytes")
        return ReconcileMsg(kind=RC_BEGIN, n=n)
    if kind == RC_SYMBOLS:
        start, at = _uvarint(payload, 1, "symbols")
        count, at = _uvarint(payload, at, "symbols")
        need = count * SYMBOL_BYTES
        if len(payload) - at != need:
            raise ValueError(
                f"reconcile symbols: {len(payload) - at} cell bytes for "
                f"{count} symbols (need {need})")
        cells = np.frombuffer(payload, dtype="<u4", offset=at).reshape(
            count, SYMBOL_WORDS)
        return ReconcileMsg(kind=RC_SYMBOLS, start=start, cells=cells)
    if kind == RC_DONE:
        used, at = _uvarint(payload, 1, "done")
        k, at = _uvarint(payload, at, "done")
        need = k * DIGEST_BYTES
        if len(payload) - at != need:
            raise ValueError(
                f"reconcile done: {len(payload) - at} digest bytes for "
                f"{k} digests (need {need})")
        digests = np.frombuffer(payload, dtype=np.uint8,
                                offset=at).reshape(k, DIGEST_BYTES)
        return ReconcileMsg(kind=RC_DONE, n=used, digests=digests)
    if kind == RC_MORE:
        seen, at = _uvarint(payload, 1, "more")
        if at != len(payload):
            raise ValueError("reconcile more: trailing bytes")
        return ReconcileMsg(kind=RC_MORE, n=seen)
    if kind == RC_FAIL:
        seen, at = _uvarint(payload, 1, "fail")
        try:
            reason = payload[at:].decode("utf-8")
        except UnicodeDecodeError as e:
            raise ValueError("reconcile fail: non-UTF-8 reason") from e
        return ReconcileMsg(kind=RC_FAIL, n=seen, reason=reason)
    raise ValueError(f"unknown reconcile subtype {kind}")
