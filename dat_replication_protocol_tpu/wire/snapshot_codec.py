"""TYPE_SNAPSHOT payload codec — the content-addressed bootstrap messages.

A snapshot frame's payload is one message of the snapshot-transfer
protocol (WIRE.md "Snapshot"; the weighted symbol math lives in
:mod:`..ops.rateless`, the driver in :mod:`..runtime.snapshot_driver`).
First byte is the subtype; every message is self-delimiting and a
decoder must reject structural corruption (bad subtype/version,
truncated section, trailing bytes) with ``ValueError`` — the session
decoder maps that to its standard :class:`~.framing.ProtocolError`.

Layouts (all integers little-endian, varints unsigned LEB128)::

    BEGIN   u8 subtype=0 | u8 version=1 | varint n_positions
            | varint n_chunks | varint total_bytes | 32-byte root
            | varint wire_offset | u8 avg_bits | varint min_size
            | varint max_size
            (the manifest summary: n_positions chunk slots totalling
             total_bytes, n_chunks UNIQUE chunks, Merkle root over the
             per-position digests, the live-log wire offset the dataset
             materializes — where an assembled joiner attaches — and
             the CDC parameters the joiner must cut its stale bytes
             with to share chunks)
    SYMBOLS u8 subtype=1 | varint start_index | varint count
            | count x 48-byte weighted coded symbols
            (12 u32 words each: [count | checksum lo | checksum hi
             | sum word 0..8 | length] — ops/rateless.py's weighted
             cell layout verbatim)
    WANT    u8 subtype=2 | u8 mode | mode payload —
            mode 0 (MORE):    varint symbols_seen   (not decoded yet)
            mode 1 (DIGESTS): varint k | k x 32-byte chunk digests
                              (the chunks the joiner is missing)
            mode 2 (ALL):     empty  (cold joiner: every chunk)
    CHUNKS  u8 subtype=3 | varint count
            | count x (32-byte digest | varint length | length bytes)
    DONE    u8 subtype=4 | varint symbols_used | varint n_positions
            | n_positions x varint rank
            (the assembly plan: position i holds the chunk at sorted
             rank[i] of the responder's LEXICOGRAPHICALLY sorted unique
             digest set — an order both sides can compute locally, so
             the manifest's chunk ORDER costs ~log2(n_chunks)/7 bytes
             per position instead of 32)
    FAIL    u8 subtype=5 | varint progress | utf-8 reason (to end of
            payload)

Sent only to peers that advertised ``CAP_SNAPSHOT`` (capability
negotiation is out of band, WIRE.md); a capability-less encoder cannot
emit these frames at all, so the reference wire stays byte-exact.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..ops.rateless import DIGEST_BYTES, WSYMBOL_BYTES, WSYMBOL_WORDS
from .varint import decode_uvarint, encode_uvarint

SNAPSHOT_VERSION = 1

SN_BEGIN = 0
SN_SYMBOLS = 1
SN_WANT = 2
SN_CHUNKS = 3
SN_DONE = 4
SN_FAIL = 5

WANT_MORE = 0
WANT_DIGESTS = 1
WANT_ALL = 2

_KIND_NAMES = {SN_BEGIN: "begin", SN_SYMBOLS: "symbols", SN_WANT: "want",
               SN_CHUNKS: "chunks", SN_DONE: "done", SN_FAIL: "fail"}
_WANT_NAMES = {WANT_MORE: "more", WANT_DIGESTS: "digests", WANT_ALL: "all"}


@dataclasses.dataclass(frozen=True)
class SnapshotManifest:
    """The BEGIN message's summary of one materialized dataset."""

    n_positions: int       # manifest slots (chunks in dataset order)
    n_chunks: int          # unique chunks (what CHUNKS can ever ship)
    total_bytes: int       # dataset length
    root: bytes            # 32-byte Merkle root over position digests
    wire_offset: int       # live-log offset the dataset materializes
    avg_bits: int          # CDC parameters (joiner must match them)
    min_size: int
    max_size: int


@dataclasses.dataclass(frozen=True)
class SnapshotMsg:
    """One decoded snapshot message.

    ``kind`` is the subtype; populated fields depend on it:
    ``manifest`` (begin), ``start`` + ``cells`` (symbols: run start and
    the ``(count, 12)`` u32 weighted cells), ``mode`` + ``n`` +
    ``digests`` (want), ``chunks`` (chunks: list of ``(digest bytes,
    chunk bytes)``), ``n`` + ``ranks`` (done: symbols used + the
    assembly plan), ``n`` + ``reason`` (fail)."""

    kind: int
    manifest: SnapshotManifest | None = None
    n: int = 0
    start: int = 0
    mode: int = 0
    cells: np.ndarray | None = None
    digests: np.ndarray | None = None
    chunks: list | None = None
    ranks: np.ndarray | None = None
    reason: str = ""

    @property
    def kind_name(self) -> str:
        return _KIND_NAMES.get(self.kind, str(self.kind))

    @property
    def mode_name(self) -> str:
        return _WANT_NAMES.get(self.mode, str(self.mode))


def encode_begin(man: SnapshotManifest) -> bytes:
    if len(man.root) != DIGEST_BYTES:
        raise ValueError(f"root must be {DIGEST_BYTES} bytes")
    if not 1 <= man.avg_bits <= 255:
        raise ValueError("avg_bits must fit a u8")
    return (bytes((SN_BEGIN, SNAPSHOT_VERSION))
            + encode_uvarint(man.n_positions)
            + encode_uvarint(man.n_chunks)
            + encode_uvarint(man.total_bytes)
            + bytes(man.root)
            + encode_uvarint(man.wire_offset)
            + bytes((man.avg_bits,))
            + encode_uvarint(man.min_size)
            + encode_uvarint(man.max_size))


def encode_symbols(start: int, cells: np.ndarray) -> bytes:
    cells = np.ascontiguousarray(cells, dtype=np.uint32)
    if cells.ndim != 2 or cells.shape[1] != WSYMBOL_WORDS:
        raise ValueError(f"cells must be (k, {WSYMBOL_WORDS}) u32")
    return (bytes((SN_SYMBOLS,)) + encode_uvarint(start)
            + encode_uvarint(len(cells))
            + cells.astype("<u4", copy=False).tobytes())


def encode_want_more(symbols_seen: int) -> bytes:
    return (bytes((SN_WANT, WANT_MORE)) + encode_uvarint(symbols_seen))


def encode_want_digests(digests: np.ndarray) -> bytes:
    digests = np.ascontiguousarray(digests, dtype=np.uint8)
    if digests.ndim != 2 or digests.shape[1] != DIGEST_BYTES:
        raise ValueError(f"digests must be (k, {DIGEST_BYTES}) u8")
    return (bytes((SN_WANT, WANT_DIGESTS)) + encode_uvarint(len(digests))
            + digests.tobytes())


def encode_want_all() -> bytes:
    return bytes((SN_WANT, WANT_ALL))


def encode_chunks(chunks: list) -> bytes:
    """``chunks``: list of ``(digest 32B, bytes-like payload)``."""
    parts = [bytes((SN_CHUNKS,)), encode_uvarint(len(chunks))]
    for digest, data in chunks:
        digest = bytes(digest)
        if len(digest) != DIGEST_BYTES:
            raise ValueError(f"chunk digest must be {DIGEST_BYTES} bytes")
        parts.append(digest)
        parts.append(encode_uvarint(len(data)))
        parts.append(bytes(data))
    return b"".join(parts)


def encode_done_tail(ranks: np.ndarray) -> bytes:
    """The DONE payload minus its ``symbols_used`` prefix: varint
    n_positions + per-rank varints.  Constant per manifest — a source
    caches this blob once and prepends the per-session prefix, instead
    of redoing ~n_positions Python-level varint encodes per session."""
    ranks = np.ascontiguousarray(ranks, dtype=np.int64)
    if ranks.ndim != 1 or (len(ranks) and ranks.min() < 0):
        raise ValueError("ranks must be a 1-D array of >= 0 ints")
    parts = [encode_uvarint(len(ranks))]
    parts.extend(encode_uvarint(int(r)) for r in ranks)
    return b"".join(parts)


def encode_done(symbols_used: int, ranks: np.ndarray | None = None, *,
                tail: bytes | None = None) -> bytes:
    if tail is None:
        tail = encode_done_tail(ranks)
    return bytes((SN_DONE,)) + encode_uvarint(symbols_used) + tail


def encode_fail(progress: int, reason: str) -> bytes:
    return (bytes((SN_FAIL,)) + encode_uvarint(progress)
            + reason.encode("utf-8"))


def _uvarint(payload, at: int, what: str) -> tuple[int, int]:
    try:
        v, used = decode_uvarint(payload, at)
    except Exception as e:
        raise ValueError(f"snapshot {what}: bad varint") from e
    return v, at + used


def decode_snapshot(payload) -> SnapshotMsg:
    """Parse one TYPE_SNAPSHOT payload; ``ValueError`` on any
    structural fault (the decoder maps it to a ProtocolError)."""
    payload = bytes(payload)
    if not payload:
        raise ValueError("empty snapshot payload")
    kind = payload[0]
    if kind == SN_BEGIN:
        if len(payload) < 2:
            raise ValueError("snapshot begin: truncated")
        version = payload[1]
        if version != SNAPSHOT_VERSION:
            raise ValueError(
                f"snapshot begin: unsupported version {version}")
        npos, at = _uvarint(payload, 2, "begin")
        nchunks, at = _uvarint(payload, at, "begin")
        total, at = _uvarint(payload, at, "begin")
        if len(payload) - at < DIGEST_BYTES + 1:
            raise ValueError("snapshot begin: truncated root")
        root = payload[at:at + DIGEST_BYTES]
        at += DIGEST_BYTES
        wire_offset, at = _uvarint(payload, at, "begin")
        if at >= len(payload):
            raise ValueError("snapshot begin: truncated params")
        avg_bits = payload[at]
        at += 1
        min_size, at = _uvarint(payload, at, "begin")
        max_size, at = _uvarint(payload, at, "begin")
        if at != len(payload):
            raise ValueError("snapshot begin: trailing bytes")
        if nchunks > npos:
            raise ValueError(
                "snapshot begin: more unique chunks than positions")
        return SnapshotMsg(kind=SN_BEGIN, manifest=SnapshotManifest(
            n_positions=npos, n_chunks=nchunks, total_bytes=total,
            root=root, wire_offset=wire_offset, avg_bits=avg_bits,
            min_size=min_size, max_size=max_size))
    if kind == SN_SYMBOLS:
        start, at = _uvarint(payload, 1, "symbols")
        count, at = _uvarint(payload, at, "symbols")
        need = count * WSYMBOL_BYTES
        if len(payload) - at != need:
            raise ValueError(
                f"snapshot symbols: {len(payload) - at} cell bytes for "
                f"{count} symbols (need {need})")
        cells = np.frombuffer(payload, dtype="<u4", offset=at).reshape(
            count, WSYMBOL_WORDS)
        return SnapshotMsg(kind=SN_SYMBOLS, start=start, cells=cells)
    if kind == SN_WANT:
        if len(payload) < 2:
            raise ValueError("snapshot want: truncated")
        mode = payload[1]
        if mode == WANT_MORE:
            seen, at = _uvarint(payload, 2, "want")
            if at != len(payload):
                raise ValueError("snapshot want: trailing bytes")
            return SnapshotMsg(kind=SN_WANT, mode=mode, n=seen)
        if mode == WANT_DIGESTS:
            k, at = _uvarint(payload, 2, "want")
            need = k * DIGEST_BYTES
            if len(payload) - at != need:
                raise ValueError(
                    f"snapshot want: {len(payload) - at} digest bytes "
                    f"for {k} digests (need {need})")
            digests = np.frombuffer(payload, dtype=np.uint8,
                                    offset=at).reshape(k, DIGEST_BYTES)
            return SnapshotMsg(kind=SN_WANT, mode=mode, n=k,
                               digests=digests)
        if mode == WANT_ALL:
            if len(payload) != 2:
                raise ValueError("snapshot want: trailing bytes")
            return SnapshotMsg(kind=SN_WANT, mode=mode)
        raise ValueError(f"snapshot want: unknown mode {mode}")
    if kind == SN_CHUNKS:
        count, at = _uvarint(payload, 1, "chunks")
        chunks = []
        for _ in range(count):
            if len(payload) - at < DIGEST_BYTES:
                raise ValueError("snapshot chunks: truncated digest")
            digest = payload[at:at + DIGEST_BYTES]
            at += DIGEST_BYTES
            ln, at = _uvarint(payload, at, "chunks")
            if len(payload) - at < ln:
                raise ValueError(
                    f"snapshot chunks: {len(payload) - at} payload bytes "
                    f"for a {ln}-byte chunk")
            chunks.append((digest, payload[at:at + ln]))
            at += ln
        if at != len(payload):
            raise ValueError("snapshot chunks: trailing bytes")
        return SnapshotMsg(kind=SN_CHUNKS, n=count, chunks=chunks)
    if kind == SN_DONE:
        used, at = _uvarint(payload, 1, "done")
        npos, at = _uvarint(payload, at, "done")
        # every rank is >= 1 varint byte: bound the claimed count by the
        # bytes actually present BEFORE allocating (a byzantine n here
        # must fail structured, not MemoryError/OOM)
        if npos > len(payload) - at:
            raise ValueError(
                f"snapshot done: {npos} positions claimed, "
                f"{len(payload) - at} payload bytes remain")
        ranks = np.empty(npos, dtype=np.int64)
        for i in range(npos):
            r, at = _uvarint(payload, at, "done")
            ranks[i] = r
        if at != len(payload):
            raise ValueError("snapshot done: trailing bytes")
        return SnapshotMsg(kind=SN_DONE, n=used, ranks=ranks)
    if kind == SN_FAIL:
        progress, at = _uvarint(payload, 1, "fail")
        try:
            reason = payload[at:].decode("utf-8")
        except UnicodeDecodeError as e:
            raise ValueError("snapshot fail: non-UTF-8 reason") from e
        return SnapshotMsg(kind=SN_FAIL, n=progress, reason=reason)
    raise ValueError(f"unknown snapshot subtype {kind}")
