"""L1/L3 wire layer: varint, framing, and the Change protobuf codec."""

from .change_codec import Change, decode_change, encode_change
from .framing import (
    CAP_CHANGE_BATCH,
    CAP_RECONCILE,
    CAP_SNAPSHOT,
    KNOWN_TYPES,
    LOCAL_CAPS,
    MAX_HEADER_LEN,
    TYPE_BLOB,
    TYPE_CHANGE,
    TYPE_CHANGE_BATCH,
    TYPE_HEADER,
    TYPE_RECONCILE,
    TYPE_SNAPSHOT,
    ProtocolError,
    frame,
    frame_header,
)
from .varint import NeedMoreData, decode_uvarint, encode_uvarint, uvarint_length

# batch_codec / reconcile_codec are imported lazily by their consumers
# (they need numpy; the bare protocol surface must stay importable
# without it on the path)

__all__ = [
    "Change",
    "decode_change",
    "encode_change",
    "CAP_CHANGE_BATCH",
    "CAP_RECONCILE",
    "CAP_SNAPSHOT",
    "KNOWN_TYPES",
    "LOCAL_CAPS",
    "MAX_HEADER_LEN",
    "TYPE_BLOB",
    "TYPE_CHANGE",
    "TYPE_CHANGE_BATCH",
    "TYPE_RECONCILE",
    "TYPE_SNAPSHOT",
    "TYPE_HEADER",
    "ProtocolError",
    "frame",
    "frame_header",
    "NeedMoreData",
    "decode_uvarint",
    "encode_uvarint",
    "uvarint_length",
]
