"""The shared broadcast log: one encoder's wire, many independent cursors.

A :class:`~..session.resume.WireJournal` retains a *single* window of
produced wire bytes for one resuming receiver.  Broadcast replication
(ROADMAP item 4) needs the same bytes readable by *thousands* of
receivers at independent offsets — and it needs handing a chunk to peer
N+1 to cost zero additional copies, because the frame bytes were
already assembled once by the encoder ("Simplicity Scales",
arxiv 2604.09591: one simple shared log, many independent cursors).

:class:`BroadcastLog` is that multi-reader extension:

* **Segmented storage, zero-copy reads.**  Appended chunks are kept as
  immutable segments (small chunks coalesce into a tail buffer that is
  frozen once, on first read past it — one copy per coalesced run, not
  per peer).  :meth:`read_slices` returns ``memoryview`` slices over
  the retained segments, ready for ``os.writev`` scatter-gather: frame
  bytes are assembled once by the encoder and never re-copied per peer.
* **Per-peer cursors, budget-bounded trim.**  Each attached cursor
  carries its own acked offset.  The log never trims past the
  **minimum** acked offset across live cursors *except* under budget
  pressure — and below the budget it does not trim at all, so a full
  ``retention_budget`` of history stays servable for late joiners.
* **Retention budget.**  One laggard must not pin unbounded memory:
  when retained bytes exceed ``retention_budget`` the log trims to the
  budget window and *invalidates* the cursors it trimmed past — their
  next read raises a structured :class:`SnapshotNeeded` naming the
  retained range, and the fan-out server sheds them (ROBUSTNESS.md
  peer-shed contract).

The log satisfies the encoder journal-tee contract (``append`` /
``seek``), so ``encoder.attach_journal(broadcast_log)`` wires a live
session straight into the fan-out path.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Optional

from ..obs.events import emit as _emit
from ..obs.metrics import (
    OBS as _OBS,
    counter as _counter,
    gauge as _gauge,
)
from ..session.resume import ResumeError

__all__ = ["BroadcastLog", "BroadcastCursor", "SnapshotNeeded"]

# fanout telemetry (OBSERVABILITY.md `fanout.*` catalog)
_M_APPEND = _counter("fanout.append.bytes")
_M_TRIMMED = _counter("fanout.trimmed.bytes")
_M_RETAINED = _gauge("fanout.retained.bytes")
_M_CURSORS = _gauge("fanout.cursors")
_M_SNAPSHOT_NEEDED = _counter("fanout.snapshot_needed")

# appends below this coalesce into the mutable tail; at or above it the
# chunk becomes its own immutable segment with no copy at read time
_COALESCE_BELOW = 4096


class SnapshotNeeded(ResumeError):
    """The requested offset is below the log's retained window: the
    receiver cannot be served from the log alone and must fetch a
    snapshot first.  ``retained`` is the ``(start, end)`` window that
    *is* servable; ``hint`` (when the deployment serves the snapshot
    bootstrap protocol, ISSUE 12) names where — a dict like
    ``{"port": N, "cap": CAP_SNAPSHOT}`` the fan-out server attaches so
    joiners can redirect without out-of-band config."""

    def __init__(self, message: str, *, offset: int,
                 retained: tuple[int, int], hint: dict | None = None):
        super().__init__(message, offset=offset)
        self.retained = retained
        self.hint = hint


class BroadcastCursor:
    """One reader's position in the log.  ``acked`` is the offset below
    which this reader has confirmed delivery (the trim input); the
    *send* position is the fan-out server's business, not the log's."""

    __slots__ = ("key", "acked", "invalidated", "gone")

    def __init__(self, key: str, offset: int):
        self.key = key
        self.acked = offset
        self.invalidated = False  # trimmed past by the retention budget
        self.gone = False


class BroadcastLog:
    """See module docstring.  Thread-safe; one writer, many readers."""

    def __init__(self, *, retention_budget: int = 64 << 20):
        if retention_budget <= 0:
            raise ValueError("retention_budget must be > 0")
        self.retention_budget = int(retention_budget)
        self._lock = threading.Lock()
        # the concurrency pass enforces these (ANALYSIS.md):
        # datlint: guarded-by(self._lock): self._segs, self._seg_offs, self._cursors
        # datlint: guarded-by(self._lock): self._start, self._end, self._sealed
        # datlint: guarded-by(self._lock): self._tail, self._tail_off
        # immutable segments as parallel arrays: _seg_offs[i] is the
        # absolute wire offset of _segs[i][0]; bisect finds the segment
        # containing any retained offset in O(log n)
        self._segs: list[bytes] = []
        self._seg_offs: list[int] = []
        self._tail = bytearray()  # coalescing buffer for small appends
        self._tail_off = 0        # absolute offset of _tail[0]
        self._start = 0           # first retained (servable) offset
        self._end = 0             # one past the last appended byte
        self._sealed = False
        self._cursors: dict[str, BroadcastCursor] = {}
        self._on_append: Optional[Callable[[], None]] = None

    # -- writer section (datlint fanout-hot-path: O(1) in peers) ------------

    def append(self, data) -> None:
        """Record produced wire bytes.  This is the broadcast write
        path: it does NO per-peer work — the fan-out dispatcher owns the
        O(peers) bookkeeping (and never touches these bytes again; they
        leave as memoryview slices)."""
        n = len(data)
        if n == 0:
            return
        with self._lock:
            if self._sealed:
                raise ValueError("append to a sealed broadcast log")
            if n < _COALESCE_BELOW:
                if not self._tail:
                    self._tail_off = self._end
                self._tail += data
            else:
                self._freeze_tail_locked()
                self._seg_offs.append(self._end)
                self._segs.append(bytes(data))
            self._end += n
            if _OBS.on:
                _M_APPEND.inc(n)
                _M_RETAINED.set(self._end - self._start)
        hook = self._on_append
        if hook is not None:
            hook()

    def seek(self, offset: int) -> None:
        """Align an EMPTY log's window to an absolute wire offset (the
        encoder journal-tee contract: attaching after bytes were already
        emitted starts the window past them)."""
        with self._lock:
            if self._end != self._start or self._segs or self._tail:
                raise ValueError("seek on a non-empty broadcast log")
            self._start = self._end = offset

    def seal(self) -> None:
        """No more appends: ``end`` is final.  The fan-out server
        completes peers once their cursor reaches a sealed end."""
        hook = None
        with self._lock:
            if not self._sealed:
                self._sealed = True
                hook = self._on_append
        if hook is not None:
            hook()  # wake the dispatcher so drained peers complete

    # -- geometry -----------------------------------------------------------

    @property
    def start(self) -> int:
        return self._start

    @property
    def end(self) -> int:
        return self._end

    @property
    def sealed(self) -> bool:
        return self._sealed

    @property
    def retained_bytes(self) -> int:
        return self._end - self._start

    def __len__(self) -> int:
        return self._end - self._start

    def set_append_hook(self, hook: Optional[Callable[[], None]]) -> None:
        """Install the (single) append/seal notification hook — the
        fan-out server's dispatcher wakeup.  Runs outside the log lock."""
        self._on_append = hook

    # -- cursors ------------------------------------------------------------

    def attach(self, key: str, offset: Optional[int] = None
               ) -> BroadcastCursor:
        """Attach a reader at ``offset`` (default: the earliest retained
        byte).  A late joiner may attach at ANY retained offset; below
        the retained window raises :class:`SnapshotNeeded` (structured —
        the caller learns exactly what range is still servable), beyond
        ``end`` raises :class:`~..session.resume.ResumeError`."""
        with self._lock:
            off = self._start if offset is None else int(offset)
            if off < self._start:
                # built under the lock (consistent range), emitted and
                # raised by _snapshot_refusal OUTSIDE it: the event
                # sink can block, and every appender/reader contends
                # on this lock (blocking-under-lock contract)
                snap = SnapshotNeeded(
                    f"peer {key!r} asked for byte {off} below the "
                    f"retained range [{self._start}, {self._end}); a "
                    "snapshot (or restart) is required",
                    offset=off, retained=(self._start, self._end))
            else:
                if off > self._end:
                    raise ResumeError(
                        f"peer {key!r} asked for byte {off} ahead of "
                        f"everything produced (retained range "
                        f"[{self._start}, {self._end}))",
                        offset=off)
                if key in self._cursors:
                    raise ValueError(
                        f"cursor key {key!r} already attached")
                cur = BroadcastCursor(key, off)
                self._cursors[key] = cur
                if _OBS.on:
                    _M_CURSORS.set(len(self._cursors))
                return cur
        raise self._snapshot_refusal(snap, key=key, offset=off)

    @staticmethod
    def _snapshot_refusal(snap: "SnapshotNeeded", **fields
                          ) -> "SnapshotNeeded":
        """Count + emit a SnapshotNeeded refusal — called with the log
        lock RELEASED (the structured error was built under it)."""
        if _OBS.on:
            _M_SNAPSHOT_NEEDED.inc()
            start, end = snap.retained
            _emit("fanout.snapshot_needed", start=start, end=end,
                  **fields)
        return snap

    def detach(self, cursor: BroadcastCursor) -> None:
        """Remove a reader; its acked offset stops constraining the
        trim (a departed laggard releases its pinned window).
        Idempotent."""
        with self._lock:
            if cursor.gone:
                return
            cursor.gone = True
            if self._cursors.get(cursor.key) is cursor:
                del self._cursors[cursor.key]
            if _OBS.on:
                _M_CURSORS.set(len(self._cursors))
            trim = self._maybe_trim_locked()
        self._emit_trim(trim)

    def ack(self, cursor: BroadcastCursor, offset: int) -> None:
        """The reader confirmed delivery below ``offset``.  Acks feed
        the trim policy (see :meth:`_maybe_trim_locked`): below the
        retention budget nothing trims; above it the budget window
        wins and laggard cursors are invalidated."""
        with self._lock:
            if cursor.invalidated:
                raise SnapshotNeeded(
                    f"peer {cursor.key!r} was trimmed past by the "
                    f"retention budget (retained range "
                    f"[{self._start}, {self._end}))",
                    offset=cursor.acked,
                    retained=(self._start, self._end))
            if offset < cursor.acked or offset > self._end:
                # an ack that regresses or runs ahead of production is
                # not a flow-control signal — it is a byzantine peer;
                # the server turns this into a structured shed
                raise ValueError(
                    f"byzantine ack from {cursor.key!r}: offset {offset} "
                    f"outside [{cursor.acked}, {self._end}]")
            cursor.acked = offset
            trim = self._maybe_trim_locked()
        self._emit_trim(trim)

    def enforce_retention(self) -> None:
        """Apply the retention budget now.  The write path stays O(1) in
        peers, so budget pressure from a burst of appends is enforced
        here — called by the fan-out dispatcher each turn (and by any
        caller with no dispatcher at all)."""
        with self._lock:
            trim = self._maybe_trim_locked()
        self._emit_trim(trim)

    @staticmethod
    def _emit_trim(trim) -> None:
        """Emit the trim event with the log lock RELEASED (the fields
        were captured under it by :meth:`_maybe_trim_locked`)."""
        if trim is not None:
            start, end, trimmed = trim
            _emit("fanout.trim", start=start, end=end, trimmed=trimmed)

    def cursors_snapshot(self) -> dict:
        """{key: acked offset} for live cursors (telemetry/debugging)."""
        with self._lock:
            return {k: c.acked for k, c in self._cursors.items()}

    # -- reads --------------------------------------------------------------

    def read_slices(self, offset: int, max_bytes: int,
                    max_iov: int = 64) -> list:
        """Up to ``max_bytes`` of retained bytes at ``offset`` as
        ``memoryview`` slices over the internal segments (at most
        ``max_iov`` of them — the ``os.writev`` IOV budget).  ZERO
        copies: the views alias the log's own immutable segments.  An
        empty list means nothing is available at ``offset`` yet.

        Raises :class:`SnapshotNeeded` when ``offset`` was already
        trimmed away — a structured error naming the retained range,
        never a silent short read."""
        out: list = []
        with self._lock:
            if offset < self._start:
                # built under the lock, emitted + raised AFTER it is
                # released via _snapshot_refusal
                # (blocking-under-lock contract)
                snap = SnapshotNeeded(
                    f"byte {offset} is below the retained range "
                    f"[{self._start}, {self._end})",
                    offset=offset, retained=(self._start, self._end))
            else:
                if offset >= self._end or max_bytes <= 0:
                    return out
                self._freeze_tail_locked()
                want = min(max_bytes, self._end - offset)
                i = bisect.bisect_right(self._seg_offs, offset) - 1
                while want > 0 and i < len(self._segs) \
                        and len(out) < max_iov:
                    seg_off = self._seg_offs[i]
                    seg = self._segs[i]
                    lo = offset - seg_off
                    hi = min(len(seg), lo + want)
                    view = memoryview(seg)[lo:hi]
                    out.append(view)
                    taken = hi - lo
                    want -= taken
                    offset += taken
                    i += 1
                return out
        raise self._snapshot_refusal(snap, offset=snap.offset)

    def read_from(self, offset: int) -> bytes:
        """WireJournal-compatible copy read: every retained byte at
        ``offset`` and beyond, as one bytes object (tests, resume
        interop).  The scatter-gather path is :meth:`read_slices`."""
        views = self.read_slices(offset, max(0, self._end - offset),
                                 max_iov=1 << 30)
        return b"".join(bytes(v) for v in views)

    # -- trim ---------------------------------------------------------------

    def _maybe_trim_locked(self) -> Optional[tuple]:
        # Returns (start, end, trimmed) when a trim happened with the
        # obs gate on — the CALLER must pass it to _emit_trim once the
        # lock releases (the return value IS the deferred fanout.trim
        # event; dropping it loses the event), else None.
        # Lazy, budget-driven trim: the log retains a full
        # ``retention_budget`` of history even once every live cursor
        # acked past it — that window is what late joiners attach into.
        # Only budget pressure trims, and then the budget WINS over the
        # min-acked floor (the bounded-laggard clause): cursors below
        # the new start are invalidated, never silently short-read.
        target = self._end - self.retention_budget
        if target <= self._start:
            return
        trimmed = target - self._start
        self._start = target
        # laggards the budget trimmed past: invalidate, never short-read
        for c in self._cursors.values():
            if not c.invalidated and c.acked < target:
                c.invalidated = True
        # drop whole segments now fully below the window; a segment
        # straddling the boundary stays until its last byte is trimmed
        drop = 0
        while drop < len(self._segs) and \
                self._seg_offs[drop] + len(self._segs[drop]) <= target:
            drop += 1
        if drop:
            del self._segs[:drop]
            del self._seg_offs[:drop]
        if self._tail and self._tail_off + len(self._tail) <= target:
            self._tail.clear()
        if _OBS.on:
            _M_TRIMMED.inc(trimmed)
            _M_RETAINED.set(self._end - self._start)
            # the EVENT is the caller's to emit once the lock releases
            # (blocking-under-lock contract): return the fields
            return (self._start, self._end, trimmed)
        return None

    def _freeze_tail_locked(self) -> None:
        """Promote the mutable coalescing tail to an immutable segment.
        Needed before any read exports views (a memoryview over a live
        bytearray would pin it against resize) and before a large append
        lands behind it.  One copy per coalesced run — never per peer."""
        if self._tail:
            self._seg_offs.append(self._tail_off)
            self._segs.append(bytes(self._tail))
            self._tail.clear()
