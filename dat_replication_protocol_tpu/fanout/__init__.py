"""Broadcast replication: one shared log, many independent cursors.

One encoder's wire journal becomes an offset-addressed
:class:`BroadcastLog` that thousands of downstream peers stream from at
independent offsets — merkle/hash work done ONCE (wherever the source
session decodes), frames fanned out by a zero-copy scatter-gather
:class:`FanoutServer` with per-peer flow-control windows and the
three-stage overload contract (admission → window stall →
heaviest-offender shed).  See DESIGN.md §fan-out and ROBUSTNESS.md
peer-shed contract.
"""

from .log import BroadcastCursor, BroadcastLog, SnapshotNeeded
from .server import FanoutBusy, FanoutPeer, FanoutServer, PeerShed

__all__ = [
    "BroadcastLog",
    "BroadcastCursor",
    "SnapshotNeeded",
    "FanoutServer",
    "FanoutPeer",
    "FanoutBusy",
    "PeerShed",
]
