"""One-to-many fan-out: hash once, serve every peer windowed writev.

:class:`FanoutServer` streams one :class:`~.log.BroadcastLog` to many
downstream peers at independent offsets.  The division of labor is the
whole design (the SmartNIC reliable-replication shape, arxiv
2503.18093: per-peer ack/retransmit bookkeeping lives OFF the hot
path):

* **The write path is O(1) in peers.**  :meth:`publish` appends to the
  log and notes a latency mark — no per-peer loop, no per-peer
  allocation (the ``fanout-hot-path`` datlint rule keeps this honest).
  All digest/merkle work happens wherever the *source* session decodes
  (``DigestPipeline`` / ``ReplicationHub``) — exactly once, regardless
  of peer count.
* **Per-peer bookkeeping lives in the dispatcher.**  One thread walks
  peers with backlog and an open flow-control window and hands each a
  scatter-gather slice run (``os.writev`` on fd peers, a ``sink``
  callable otherwise).  The dispatcher never touches frame payloads:
  it moves ``memoryview`` slices the log already holds.
* **Per-peer flow-control windows** (``window_bytes`` of unacked
  in-flight data, ``max_iov`` slices per writev) sized for lossy
  high-latency links: a slow peer's window closes and ONLY its own
  stream pauses — the kernel socket buffer absorbs its burst, nobody
  else waits.
* **Three-stage overload contract** (the hub's, restated for peers —
  ROBUSTNESS.md): *admission* (``max_peers``, :class:`FanoutBusy`) →
  *window stall* (a slow peer is bounded by its own window) →
  *heaviest-offender shed* (a peer making no progress for
  ``stall_timeout`` seconds, a byzantine acker, or the laggard the
  retention budget trimmed past is shed with a structured
  :class:`PeerShed`; the broadcast never slows).

Late joiners attach at any retained offset
(:meth:`BroadcastLog.attach`); past the window they get the structured
:class:`~.log.SnapshotNeeded` instead of silently wrong bytes — and
when the deployment serves the snapshot bootstrap (ISSUE 12,
``snapshot_hint``), the refusal carries the redirect that answers it.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from ..obs.events import DeferredEmitQueue as _DeferredEmitQueue
from ..obs.events import emit as _emit
from ..obs.metrics import (
    OBS as _OBS,
    REGISTRY as _REGISTRY,
    counter as _counter,
    gauge as _gauge,
    histogram as _histogram,
)
from ..obs.tracing import trace_span as _trace_span
from ..obs.watermarks import WATERMARKS as _WATERMARKS
from ..obs import wirecost as _wirecost
from ..session import pump as _pump
from .log import BroadcastLog, SnapshotNeeded

__all__ = ["FanoutServer", "FanoutPeer", "FanoutBusy", "PeerShed"]

# fanout telemetry (OBSERVABILITY.md `fanout.*` catalog)
_M_PEERS = _gauge("fanout.peers")
_M_ATTACHED = _counter("fanout.peers.attached")
_M_DETACHED = _counter("fanout.peers.detached")
_M_REJECTED = _counter("fanout.rejected")
_M_SHED = _counter("fanout.peer.shed")
_M_SENT = _counter("fanout.sent.bytes")
_M_WRITEV = _counter("fanout.dispatch.writev")
_M_TURNS = _counter("fanout.dispatch.turns")
_H_FRAME_LAT = _histogram("fanout.frame.latency")

_WAKE_FALLBACK = 0.05
# append->delivery latency marks kept for attribution; peers that lag
# past the ring simply miss those samples (bounded memory by design)
_MARK_RING = 1024
_PEER_LAT_RING = 512

# fleet-plane link for the shared broadcast wire (ISSUE 11): ONE marks
# ring for the publish path (O(1) in peers by contract); per-peer links
# alias it via marks_from so every peer's lag-in-seconds reads the same
# sender clock
_WM_LINK = "fanout"


class FanoutBusy(RuntimeError):
    """Structured admission rejection: the fan-out is at capacity."""

    def __init__(self, message: str, *, peers: int, max_peers: int):
        super().__init__(message)
        self.peers = peers
        self.max_peers = max_peers


class PeerShed(RuntimeError):
    """This peer was shed by the fan-out's overload policy.  ``reason``
    is the policy arm (``stall`` / ``byzantine`` / ``retention`` /
    ``disconnect``); ``offset`` is the peer's send position when shed."""

    def __init__(self, key: str, reason: str, offset: int):
        super().__init__(
            f"peer {key!r} shed by fan-out ({reason}, at byte {offset})")
        self.key = key
        self.reason = reason
        self.offset = offset


class _PeerState:
    """Per-peer edge state.  Window/offset fields are mutated only
    under the server lock; the transport handle is used only by the
    dispatcher thread."""

    __slots__ = (
        "key", "cursor", "sent", "window_bytes", "max_iov",
        "fd", "sink", "explicit_ack", "cv",
        "last_progress", "shed", "gone", "done",
        "sent_bytes", "writev_calls", "attached_at",
        "lat", "mark_seq",
    )

    def __init__(self, key: str, cursor, *, window_bytes: int,
                 max_iov: int, fd: Optional[int],
                 sink: Optional[Callable], explicit_ack: bool,
                 lock: threading.Lock):
        self.key = key
        self.cursor = cursor
        self.sent = cursor.acked          # bytes handed to the transport
        self.window_bytes = window_bytes  # unacked in-flight bound
        self.max_iov = max_iov
        self.fd = fd
        self.sink = sink
        self.explicit_ack = explicit_ack
        self.cv = threading.Condition(lock)
        self.last_progress = time.monotonic()
        self.shed: Optional[str] = None
        self.gone = False
        self.done = False                 # sealed end fully delivered
        self.sent_bytes = 0
        self.writev_calls = 0
        self.attached_at = time.monotonic()
        self.lat: deque = deque(maxlen=_PEER_LAT_RING)
        self.mark_seq = 0                 # next latency mark to consume

    def window_remaining(self, acked: int) -> int:
        return self.window_bytes - (self.sent - acked)


class FanoutPeer:
    """A peer's handle on the fan-out (returned by
    :meth:`FanoutServer.attach_peer`)."""

    def __init__(self, server: "FanoutServer", state: _PeerState):
        self._server = server
        self._state = state

    @property
    def key(self) -> str:
        return self._state.key

    @property
    def shed_reason(self) -> Optional[str]:
        return self._state.shed

    @property
    def sent(self) -> int:
        return self._state.sent

    def ack(self, offset: int) -> None:
        """Confirm delivery below ``offset`` (explicit-ack peers only —
        the app-level ack for transports where kernel acceptance is not
        delivery).  A regressing or ahead-of-production ack is
        byzantine and sheds THIS peer."""
        self._server._ack_peer(self._state, offset)

    def wait_done(self, timeout: Optional[float] = 30.0) -> bool:
        """Block until the sealed log is fully delivered to this peer,
        it is shed, or ``timeout`` elapses.  Returns ``done``."""
        return self._server._wait_peer_done(self._state, timeout)

    def raise_if_shed(self) -> None:
        st = self._state
        if st.shed is not None:
            raise PeerShed(st.key, st.shed, st.sent)

    def stats(self) -> dict:
        return self._server._peer_stats(self._state)

    def close(self) -> None:
        """Detach; the peer's acked offset stops pinning the log.
        Idempotent."""
        self._server._detach(self._state)

    def __enter__(self) -> "FanoutPeer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class FanoutServer:
    """See module docstring.  One server per :class:`BroadcastLog`."""

    def __init__(
        self,
        log: Optional[BroadcastLog] = None,
        *,
        retention_budget: int = 64 << 20,
        max_peers: int = 4096,
        window_bytes: int = 1 << 20,
        max_iov: int = 64,
        stall_timeout: float = 30.0,
        linger_s: float = 0.0005,
        snapshot_hint: Optional[dict] = None,
    ):
        self.log = log if log is not None else BroadcastLog(
            retention_budget=retention_budget)
        self.max_peers = int(max_peers)
        self.window_bytes = int(window_bytes)
        self.max_iov = int(max_iov)
        self.stall_timeout = float(stall_timeout)
        self._linger_s = float(linger_s)
        # where the snapshot bootstrap answers what this log cannot
        # (ISSUE 12): a dict like {"port": N, "cap": CAP_SNAPSHOT}
        # attached to every SnapshotNeeded raised at attach, so a
        # trimmed-past joiner learns the redirect IN the refusal —
        # no out-of-band config.  Settable after construction (the
        # sidecar binds the snapshot listener late).
        self.snapshot_hint = snapshot_hint
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._peers: dict[str, _PeerState] = {}
        # shed events queued under the lock, emitted by
        # _drain_shed_events once the holder releases (the event sink
        # can block; blocking under the server lock stalls everyone)
        self._shed_events = _DeferredEmitQueue("fanout.shed", self._lock)
        # the concurrency pass enforces these (ANALYSIS.md):
        # datlint: guarded-by(self._lock): self._peers
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # owned fds of gone/shed peers, parked for the dispatcher to
        # close (only the writing thread may close — see _reap_dead_fds)
        self._dead_fds: list[int] = []
        # append->delivery latency marks: (end_offset, t) ring + an
        # absolute base so peers index marks with a plain counter
        self._marks: deque = deque(maxlen=_MARK_RING)
        self._mark_base = 0
        self.log.set_append_hook(self._on_append)
        self._collector_fn = self._collect
        _REGISTRY.register_collector("fanout", self._collector_fn)
        # kernel-bypass gather (ISSUE 14): when the pump route is
        # native, fd peers are served through one sendmmsg/writev
        # batch per turn — BroadcastLog segment memoryviews go to the
        # kernel as (address, length) spans, so the broadcast hot path
        # moves ZERO Python-owned payload bytes.  Resolved once at
        # construction (the dispatcher is one long-lived thread); all
        # window/ack/shed bookkeeping is identical on both routes —
        # only the byte mover changes (ROBUSTNESS.md).
        self._gather = (_pump.SpanGather()
                        if _pump.effective_pump_route() == "native"
                        else None)
        # one native batch carries PUMP_MSGS x PUMP_IOV spans, so a
        # native turn may serve more slices than one os.writev could
        self._serve_iov_factor = 16 if self._gather is not None else 1
        # the dispatcher starts NOW, not at first attach: it is also
        # the retention enforcer, and a source can publish gigabytes
        # before the first subscriber ever attaches — budget pressure
        # must trim regardless of peer count (the write path itself
        # stays O(1) in peers and never trims)
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="fanout-dispatch",
            daemon=True)
        self._thread.start()

    # -- writer section (datlint fanout-hot-path: O(1) in peers) ------------

    def publish(self, data) -> None:
        """Append produced wire bytes to the shared log and note a
        latency mark.  The broadcast write path: no per-peer loop, no
        per-peer allocation — peers are the dispatcher's business.
        The mark update is O(1) under the server lock (the dispatcher
        indexes the ring by absolute sequence; an unlocked evict would
        shift its base mid-read)."""
        self.log.append(data)
        end = self.log.end
        now = time.monotonic()
        with self._lock:
            if len(self._marks) == self._marks.maxlen:
                self._mark_base += 1
            self._marks.append((end, now))
        if _OBS.on:
            _WATERMARKS.mark(_WM_LINK, end)
            self._lit_cost_published(len(data))

    # -- wire cost lit helpers (ISSUE 20) ------------------------------------
    # The fan-out choke points fork ONCE on `_OBS.on`; these helpers
    # hold the plane's symbols so the hot paths' bytecode provably
    # references no wirecost symbol (tests/test_wirecost.py).  The
    # fan-out ledger is the amplification pair — source bytes in,
    # per-peer delivered bytes out; frame classes were already
    # attributed by the session encoder that produced the bytes.

    def _lit_cost_published(self, nbytes: int) -> None:
        _wirecost.note_source(_WM_LINK, nbytes)

    def _lit_cost_served(self, peer: str, nbytes: int) -> None:
        _wirecost.note_delivered(_WM_LINK, peer, nbytes)

    def seal(self) -> None:
        """No more bytes: peers complete once fully delivered."""
        self.log.seal()

    def _on_append(self) -> None:
        with self._lock:
            self._work.notify_all()

    # -- admission / lifecycle ----------------------------------------------

    def attach_peer(self, key: str, *, fd: Optional[int] = None,
                    sink: Optional[Callable] = None,
                    offset: Optional[int] = None,
                    window_bytes: Optional[int] = None,
                    max_iov: Optional[int] = None,
                    explicit_ack: bool = False) -> FanoutPeer:
        """Admit one downstream peer at ``offset`` (default: earliest
        retained byte).

        Exactly one transport must be given: ``fd`` (streamed with
        non-blocking ``os.writev`` — the scatter-gather zero-copy path)
        or ``sink`` (a callable ``sink(views) -> accepted_bytes``; 0
        means would-block).  ``explicit_ack`` defers log trimming to
        app-level :meth:`FanoutPeer.ack` calls instead of transport
        acceptance.

        Raises :class:`FanoutBusy` at ``max_peers`` (admission — stage
        one of the overload contract) and the structured
        :class:`~.log.SnapshotNeeded` for an offset below the retained
        window — carrying ``snapshot_hint`` when set, so the caller can
        redirect the joiner to the bootstrap protocol."""
        if (fd is None) == (sink is None):
            raise ValueError("exactly one of fd/sink is required")
        if not isinstance(key, str) or not key or any(
                c in key for c in "{},=\"\n\r"):
            # keys ride telemetry label sets ({peer=KEY}) — refuse
            # structural characters at the boundary (hub precedent)
            raise ValueError(
                f"peer key {key!r} must be a non-empty string containing "
                'none of {},=" or newlines')
        if offset is not None:
            # coerce HERE so log.attach's only remaining ValueError is
            # the duplicate-cursor refusal (translated below) — a bad
            # offset type must surface as itself, not as duplicate-key
            offset = int(offset)
        if self._closed:
            # racy fast-fail (the in-lock check below is authoritative):
            # a closed server must refuse BEFORE the log can answer a
            # stale offset with SnapshotNeeded + hint — misdirecting a
            # joiner into a snapshot fetch it cannot use
            raise RuntimeError("fan-out server is closed")
        peers_seen = len(self._peers)
        if peers_seen >= self.max_peers and key not in self._peers:
            # (duplicate keys fall through to the duplicate-cursor
            # refusal below — a caller bug outranks the capacity
            # verdict, as the pre-fast-fail contract had it)
            # same racy fast-fail for admission: at capacity, refusal
            # must stay the CHEAP first gate — before the cursor
            # attach, the fd dup, and before a stale offset can be
            # answered with SnapshotNeeded + hint (amplifying load
            # with a snapshot fetch the full server would then reject)
            busy = FanoutBusy(
                f"fan-out at capacity ({peers_seen}/"
                f"{self.max_peers} peers)",
                peers=peers_seen, max_peers=self.max_peers)
            if _OBS.on:
                _M_REJECTED.inc()
                _emit("fanout.reject", key=key, peers=busy.peers,
                      max_peers=self.max_peers)
            raise busy
        # register the log cursor FIRST, outside the server lock: the
        # log serializes on its own lock, and its SnapshotNeeded
        # refusal path emits — neither may run under the server lock
        # (blocking-under-lock contract, ANALYSIS.md).  A duplicate key
        # fails here too (every peer owns a same-keyed cursor).
        try:
            cursor = self.log.attach(key, offset)
        except SnapshotNeeded as e:
            # the one refusal the stack can now ANSWER: attach the
            # bootstrap hint so the joiner redirects to the snapshot
            # protocol instead of being stranded
            e.hint = self.snapshot_hint
            raise
        except ValueError:
            # every attached peer owns a same-keyed log cursor, so the
            # log's duplicate-cursor refusal IS the duplicate-peer
            # check — restate it at this API's level
            raise ValueError(
                f"peer key {key!r} already attached") from None
        busy = None
        admitted = False
        owned_fd = None
        try:
            if fd is not None:
                # the server OWNS a duplicate: the caller may close its
                # fd at any time (teardown races the dispatcher's
                # writev), and a closed number can be reused by the
                # kernel for an unrelated connection — the dup keeps our
                # writes pointed at THIS peer's socket until the
                # dispatcher itself reaps it (_reap_dead_fds).  Inside
                # the rollback scope: an EMFILE here must detach the
                # provisional cursor, or the key is unusable forever.
                owned_fd = os.dup(fd)
                os.set_blocking(owned_fd, False)
            with self._lock:
                if self._closed:
                    raise RuntimeError("fan-out server is closed")
                if len(self._peers) >= self.max_peers:
                    # built under the lock (consistent count), emitted
                    # and raised OUTSIDE it
                    busy = FanoutBusy(
                        f"fan-out at capacity ({len(self._peers)}/"
                        f"{self.max_peers} peers)",
                        peers=len(self._peers), max_peers=self.max_peers)
                else:
                    st = _PeerState(
                        key, cursor,
                        window_bytes=(self.window_bytes
                                      if window_bytes is None
                                      else int(window_bytes)),
                        max_iov=(self.max_iov if max_iov is None
                                 else int(max_iov)),
                        fd=owned_fd, sink=sink,
                        explicit_ack=explicit_ack,
                        lock=self._lock)
                    # skip latency marks fully delivered pre-attach
                    st.mark_seq = self._mark_base + len(self._marks)
                    self._peers[key] = st
                    peers_now = len(self._peers)
                    attach_offset = cursor.acked
                    if _OBS.on:
                        # gauge set under the lock: concurrent
                        # attach/detach post-lock sets interleave out
                        # of order and latch a stale count (the EVENT
                        # still emits outside — only it can block)
                        _M_PEERS.set(peers_now)
                    self._work.notify_all()
                    # fleet-plane watermarks: this peer's wire is one
                    # link — append is the shared log's frontier,
                    # delivered is the peer's transport position;
                    # seconds come from the shared publish marks ring
                    # (marks_from)
                    log = self.log
                    _WATERMARKS.track("append", f"fanout/{key}",
                                      lambda: log.end,
                                      marks_from=_WM_LINK)
                    _WATERMARKS.track("delivered", f"fanout/{key}",
                                      lambda st=st: st.sent)
                    admitted = True
        finally:
            if not admitted:
                # roll the provisional cursor (and owned fd) back out
                if owned_fd is not None:
                    os.close(owned_fd)
                self.log.detach(cursor)
        if busy is not None:
            if _OBS.on:
                _M_REJECTED.inc()
                _emit("fanout.reject", key=key, peers=busy.peers,
                      max_peers=self.max_peers)
            raise busy
        if _OBS.on:
            _M_ATTACHED.inc()
            _emit("fanout.attach", key=key, offset=attach_offset,
                  peers=peers_now)
        return FanoutPeer(self, st)

    def _peer_state(self, key: str) -> _PeerState:
        """THE peer-keyed accessor: every key-addressed reach into
        per-peer state goes through here (hub-isolation precedent)."""
        return self._peers[key]

    def _detach(self, st: _PeerState) -> None:
        with self._lock:
            if st.gone:
                return
            st.gone = True
            self._park_fd_locked(st)
            if self._peers.get(st.key) is st:
                del self._peers[st.key]
            if _OBS.on:
                # under the lock for the same stale-interleaving reason
                # as the attach-side set
                _M_PEERS.set(len(self._peers))
            st.cv.notify_all()
            self._work.notify_all()
        # emit outside the lock (the event sink can block); st.gone
        # above makes this path single-shot, so the event fires once
        if _OBS.on:
            _M_DETACHED.inc()
            _emit("fanout.detach", key=st.key, sent=st.sent,
                  shed=st.shed)
        _WATERMARKS.untrack(f"fanout/{st.key}")
        self.log.detach(st.cursor)

    def _ack_peer(self, st: _PeerState, offset: int) -> None:
        shed_reason = None
        with self._lock:
            if st.gone or st.shed is not None:
                return
            if offset > st.sent:
                # acking bytes never sent is byzantine even when the
                # log (which only knows production) would accept it
                self._shed_locked(st, "byzantine")
                shed_reason = "byzantine"
        if shed_reason is None:
            # the log serializes on its own lock (and its refusal/trim
            # paths emit) — call it with the server lock RELEASED;
            # racing acks were already byzantine-on-regression before
            try:
                self.log.ack(st.cursor, offset)
            except SnapshotNeeded:
                # an honest ack from a cursor the retention budget
                # already trimmed past: a laggard, not an attacker
                shed_reason = "retention"
            except ValueError:
                # a regressing ack is byzantine
                shed_reason = "byzantine"
            if shed_reason is None:
                with self._lock:
                    st.last_progress = time.monotonic()
                    self._work.notify_all()
            else:
                with self._lock:
                    self._shed_locked(st, shed_reason)
        self._drain_shed_events()
        if shed_reason is not None:
            raise PeerShed(st.key, shed_reason, st.sent)

    def _wait_peer_done(self, st: _PeerState,
                        timeout: Optional[float]) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not (st.done or st.shed is not None or st.gone
                       or self._closed):
                if deadline is not None and time.monotonic() >= deadline:
                    break
                st.cv.wait(_WAKE_FALLBACK)
            return st.done

    # -- the dispatcher (the only thread that touches transports) -----------

    def _dispatch_loop(self) -> None:
        try:
            while True:
                with self._lock:
                    while not (self._closed or self._turn_ready_locked()
                               or self._retention_due()):
                        self._work.wait(_WAKE_FALLBACK)
                    if self._closed:
                        return
                    turn = self._compose_turn_locked()
                progressed = 0
                if turn:
                    with _trace_span("fanout.dispatch", peers=len(turn)):
                        for st, want in turn:
                            progressed += self._serve_peer(st, want)
                    if _OBS.on:
                        _M_TURNS.inc()
                self.log.enforce_retention()
                self._scan_stalls()
                self._reap_dead_fds()
                self._drain_shed_events()  # per-turn catch-all
                if not progressed:
                    # every serveable peer would-blocked (or there was
                    # nothing to serve): back off instead of spinning —
                    # kernel buffers drain on their own clock
                    time.sleep(max(self._linger_s, 0.002)
                               if turn else self._linger_s)
        except BaseException as exc:  # noqa: BLE001 — fanned out below
            # emit BEFORE taking the lock: the event sink can block,
            # and the peers notified below contend on this lock
            _emit("fanout.error", error=f"{type(exc).__name__}: {exc}")
            with self._lock:
                for key in list(self._peers):
                    st = self._peer_state(key)
                    if st.shed is None:
                        st.shed = "dispatcher-error"
                    st.cv.notify_all()

    def _retention_due(self) -> bool:
        """The dispatcher must wake for budget pressure even with zero
        serveable peers — a source can publish gigabytes before the
        first subscriber attaches, and the write path never trims."""
        return self.log.retained_bytes > self.log.retention_budget

    def _turn_ready_locked(self) -> bool:
        end = self.log.end
        sealed = self.log.sealed
        for st in self._peers.values():
            if st.shed is not None or st.gone:
                continue
            if st.sent < end and \
                    st.window_remaining(st.cursor.acked) > 0:
                return True
            if sealed and st.sent >= end and not st.done:
                return True
        return False

    def _compose_turn_locked(self) -> list:
        """Pick (peer, byte budget) pairs for this turn: peers with
        backlog and an open window.  O(peers) bookkeeping — payload
        bytes are never touched here or anywhere in the dispatcher."""
        end = self.log.end
        sealed = self.log.sealed
        turn = []
        for st in self._peers.values():
            if st.shed is not None or st.gone:
                continue
            if sealed and st.sent >= end and not st.done:
                st.done = True
                st.cv.notify_all()
                continue
            if st.sent >= end:
                continue
            want = min(end - st.sent,
                       st.window_remaining(st.cursor.acked))
            if want > 0:
                turn.append((st, want))
        return turn

    def _serve_peer(self, st: _PeerState, want: int) -> int:
        """One windowed scatter-gather push to one peer — runs outside
        the server lock; only the dispatcher thread calls transports.
        Returns the bytes the transport accepted."""
        # only native fd peers can take the larger slice run (one
        # sendmmsg batch); sink peers keep their declared max_iov bound
        factor = (self._serve_iov_factor if st.sink is None else 1)
        try:
            views = self.log.read_slices(st.sent, want,
                                         st.max_iov * factor)
        except SnapshotNeeded:
            with self._lock:
                self._shed_locked(st, "retention")
            self._drain_shed_events()
            return 0
        if not views:
            return 0
        # capture once: a marking thread may park st.fd (-> None) any
        # time; the captured number stays open until THIS thread reaps
        fd = st.fd
        try:
            if st.sink is None:
                if fd is None:
                    return 0  # parked between compose and serve
                if self._gather is not None:
                    # native gather: log-segment addresses go straight
                    # to sendmmsg/writev with the GIL released; EAGAIN
                    # comes back as a short accept, hard errors as
                    # OSError — exactly the os.writev contract the
                    # bookkeeping below is written against
                    n_spans = self._gather.fill(views)
                    try:
                        accepted = _pump.send_spans_nb(
                            fd, self._gather, n_spans)
                    finally:
                        # drop the span pins BEFORE views release below
                        # (a pinned buffer would make release() raise)
                        self._gather.release()
                else:
                    try:
                        # wire-peer fds are O_NONBLOCK (attach dups the
                        # fd and set_blocking(False)s it): EAGAIN comes
                        # straight back as a short turn, never a stall.
                        # datlint: allow-blocking-reachable(os-io)
                        accepted = os.writev(fd, views[:st.max_iov])
                    except (BlockingIOError, InterruptedError):
                        accepted = 0
            else:
                # sink peers are the in-process delivery surface (tests,
                # local taps); the attach contract puts the sink's
                # promptness on the attacher — it runs ON the broadcast
                # turn, and a stalling sink stalls only its own server's
                # fairness window, which the tests exercise.
                # datlint: allow-callback-escape
                accepted = int(st.sink(views))
        except OSError:
            # EPIPE/ECONNRESET/EBADF: the peer's transport died — shed
            # it as a disconnect; nobody else notices
            with self._lock:
                self._shed_locked(st, "disconnect")
            self._drain_shed_events()
            return 0
        finally:
            for v in views:
                v.release()
        if accepted <= 0:
            return 0
        now = time.monotonic()
        with self._lock:
            st.sent += accepted
            st.sent_bytes += accepted
            st.writev_calls += 1
            st.last_progress = now
            self._consume_marks_locked(st, now)
            do_ack = (not st.explicit_ack and st.shed is None
                      and not st.gone)
            ack_to = st.sent
        if do_ack:
            # the log serializes on its own lock (and its trim path
            # emits) — ack with the server lock RELEASED; only this
            # dispatcher thread acks implicit-ack peers, so ack_to is
            # monotone
            try:
                self.log.ack(st.cursor, ack_to)
            except SnapshotNeeded:
                with self._lock:
                    self._shed_locked(st, "retention")
                self._drain_shed_events()
        if _OBS.on:
            _M_SENT.inc(accepted)
            _M_WRITEV.inc()
            self._lit_cost_served(st.key, accepted)
        return accepted

    def _consume_marks_locked(self, st: _PeerState, now: float) -> None:
        # latency attribution: marks this peer's send position has now
        # fully covered become samples; marks that fell off the ring
        # are skipped (the peer lagged past attribution, not delivery)
        if st.mark_seq < self._mark_base:
            st.mark_seq = self._mark_base
        while st.mark_seq < self._mark_base + len(self._marks):
            off, t = self._marks[st.mark_seq - self._mark_base]
            if off > st.sent:
                break
            lat = now - t
            st.lat.append(lat)
            if _OBS.on:
                _H_FRAME_LAT.observe(lat)
            st.mark_seq += 1

    def _scan_stalls(self) -> None:
        """Stage three of the overload contract: a peer with backlog
        making no progress for ``stall_timeout`` is shed (the heaviest
        offender by construction — it is the one pinning the log)."""
        now = time.monotonic()
        with self._lock:
            end = self.log.end
            for key in list(self._peers):
                st = self._peer_state(key)
                if st.shed is not None or st.gone or st.sent >= end:
                    continue
                if now - st.last_progress > self.stall_timeout:
                    self._shed_locked(st, "stall")
        self._drain_shed_events()

    def _park_fd_locked(self, st: _PeerState) -> None:
        """Hand a dead peer's owned fd to the dispatcher for closing.
        Marking threads never close: the dispatcher may be mid-writev
        on this very fd, and a concurrent close would free the number
        for kernel reuse under its write."""
        if st.fd is not None:
            self._dead_fds.append(st.fd)
            st.fd = None

    def _reap_dead_fds(self) -> None:
        """Close parked fds — dispatcher thread only, so a close can
        never race this same thread's writev."""
        with self._lock:
            dead, self._dead_fds = self._dead_fds, []
        for fd in dead:
            try:
                os.close(fd)
            except OSError:
                pass

    def _shed_locked(self, st: _PeerState, reason: str) -> None:
        if st.shed is not None or st.gone:
            return
        st.shed = reason
        st.cursor.invalidated = True  # stop pinning the trim floor
        self._park_fd_locked(st)
        st.cv.notify_all()
        if _OBS.on:
            _M_SHED.inc()
        # the EVENT is deferred: queued here (fields captured while
        # consistent), emitted by _drain_shed_events after release
        self._shed_events.queue_locked(
            key=st.key, reason=reason, sent=st.sent,
            peers=len(self._peers))

    def _drain_shed_events(self) -> None:
        """Emit queued shed events with the server lock RELEASED.
        Called by every path that can shed, plus once per dispatcher
        turn as the catch-all."""
        self._shed_events.flush()

    # -- snapshots / lifecycle ----------------------------------------------

    def _peer_stats_locked(self, st: _PeerState) -> dict:
        lat = sorted(st.lat)
        return {
            "sent_bytes": st.sent_bytes,
            "offset": st.sent,
            "acked": st.cursor.acked,
            "backlog_bytes": max(0, self.log.end - st.sent),
            "writev_calls": st.writev_calls,
            "shed": st.shed,
            "done": st.done,
            "lat_p50_ms": round(lat[len(lat) // 2] * 1e3, 3) if lat else None,
            "lat_p99_ms": round(
                lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1e3, 3)
            if lat else None,
        }

    def _peer_stats(self, st: _PeerState) -> dict:
        with self._lock:
            return self._peer_stats_locked(st)

    def peers_snapshot(self) -> dict:
        """{key: per-peer stats} for every attached peer — the
        ``peers`` breakdown the sidecar's ``--stats-fd`` lines carry in
        fan-out mode."""
        with self._lock:
            return {key: self._peer_stats_locked(self._peer_state(key))
                    for key in self._peers}

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "peers": len(self._peers),
                "retained_bytes": self.log.retained_bytes,
                "log_start": self.log.start,
                "log_end": self.log.end,
                "sealed": self.log.sealed,
            }

    def admission_state(self) -> dict:
        """Lock-free admission view for ``/healthz`` (ISSUE 11): plain
        attribute reads, at worst one update stale — the health probe
        must never block behind the dispatcher's lock (the hub's
        ``admission_state`` contract, restated for peers)."""
        peers = len(self._peers)
        return {
            "open": not self._closed and peers < self.max_peers,
            "peers": peers,
            "max_peers": self.max_peers,
            "sealed": self.log.sealed,
        }

    def _collect(self) -> dict:
        """Registry collector: labeled per-peer entries for peers
        currently attached (bounded cardinality by construction — the
        PR 8 labeled-collector machinery)."""
        counters: dict = {}
        gauges: dict = {}
        with self._lock:
            gauges["fanout.peers"] = float(len(self._peers))
            end = self.log.end
            for key in self._peers:
                st = self._peer_state(key)
                label = f"{{peer={key}}}"
                counters["fanout.peer.sent_bytes" + label] = st.sent_bytes
                counters["fanout.peer.writev" + label] = st.writev_calls
                gauges["fanout.peer.backlog_bytes" + label] = \
                    float(max(0, end - st.sent))
        return {"counters": counters, "gauges": gauges}

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every live peer has the sealed log fully
        delivered (or is shed); returns True on full delivery."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                live = [st for st in self._peers.values()
                        if st.shed is None and not st.gone]
                if self.log.sealed and all(st.done for st in live):
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)

    def close(self) -> None:
        """Stop the dispatcher and release the collector; attached
        peers observe ``shed``-free ``gone`` semantics via their
        handles.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for key in list(self._peers):
                self._peer_state(key).cv.notify_all()
            self._work.notify_all()
            thread = self._thread
        self.log.set_append_hook(None)
        if thread is not None:
            thread.join(timeout=5)
        # the dispatcher is down: closing owned fds cannot race it now
        with self._lock:
            for key in list(self._peers):
                self._park_fd_locked(self._peer_state(key))
            dead, self._dead_fds = self._dead_fds, []
        for fd in dead:
            try:
                os.close(fd)
            except OSError:
                pass
        with self._lock:
            keys = list(self._peers)
        for key in keys:
            _WATERMARKS.untrack(f"fanout/{key}")
        _WATERMARKS.untrack(_WM_LINK)
        _REGISTRY.unregister_collector("fanout", self._collector_fn)

    def __enter__(self) -> "FanoutServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
