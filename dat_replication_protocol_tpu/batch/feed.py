"""Batching feed layer: ragged host data -> fixed-shape device batches.

SURVEY.md §7 step 2: "accumulate decoded blob chunks / change payloads
into fixed-shape padded batches (lengths + offsets arrays), the
host<->device contract every kernel consumes."  This module is that
contract's packer:

* :func:`pack_ragged` — vectorized (offset, length) extents over one
  buffer -> the padded (B, nblocks, 16) hi/lo uint32 word batch of
  :func:`..ops.blake2b.blake2b_packed`.  One numpy scatter moves all
  payload bytes (no per-item Python loop — at 1M-record replay scale the
  per-item path costs more than the hash itself).
* :func:`bucketed_extents` — groups extents into power-of-two block-count
  buckets (same policy as ``blake2b_batch``) so padding waste and compile
  count stay bounded.
* :func:`leaves_from_columns` — the config-2 -> config-5 bridge: replayed
  change records -> batched device BLAKE2b -> Merkle leaf digests, in
  log order.

The reference's analogue of this discipline is its O(chunk) streaming
(blobs never materialized, reference: README.md:73); here the bound is
per-dispatch batch volume, enforced upstream by the DigestPipeline caps.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..obs.device import note_engine as _note_engine
from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter
from ..obs.tracing import trace_span as _trace_span
from ..ops import blake2b

BLOCK_BYTES = blake2b.BLOCK_BYTES

# staged uploads / digest fetches through the feed layer (device-path
# telemetry; OBSERVABILITY.md catalog) — same names as ops.blake2b's
# batch edge: one pair of counters tells the whole transfer story
_M_H2D = _counter("device.h2d.bytes")
_M_D2H = _counter("device.d2h.bytes")
# bytes staged while earlier dispatches were still in flight: the
# transfer/compute-overlap evidence of the double-buffered upload path
# (ISSUE 7; OBSERVABILITY.md single-pass catalog).  overlap == h2d on a
# saturated pipeline; 0 means every upload waited for an idle device.
_M_H2D_OVERLAP = _counter("device.h2d.overlap")


def pack_ragged(buf: np.ndarray, offs: np.ndarray, lens: np.ndarray,
                nblocks: int | None = None):
    """Pack extents of ``buf`` into padded (B, nblocks, 16) hi/lo words.

    Equivalent to ``blake2b.pack_payloads([bytes of each extent])`` but
    vectorized: destination positions are computed with a repeat/cumsum
    ragged scatter, so the copy runs at numpy memcpy speed for any B.
    """
    offs = np.asarray(offs, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    B = len(offs)
    max_len = int(lens.max()) if B else 0
    need = max(1, -(-max_len // BLOCK_BYTES))
    if nblocks is None:
        nblocks = need
    elif nblocks < need:
        raise ValueError(f"nblocks={nblocks} < required {need}")
    width = nblocks * BLOCK_BYTES
    out = np.zeros((B, width), dtype=np.uint8)
    total = int(lens.sum())
    if not total:
        pass
    elif B and np.all(lens == lens[0]) and np.all(np.diff(offs) == lens[0]):
        # uniform contiguous extents (replay logs, slab hashing): one
        # reshape-copy at memcpy speed — the index-scatter below builds
        # ~6 int64 temp arrays per payload byte (~50 B of traffic per
        # byte packed) and was the silent cost behind round 3's
        # e2e_host_gib_s sitting far below even the H2D link rate
        item = int(lens[0])
        out[:, :item] = buf[offs[0]:offs[0] + B * item].reshape(B, item)
    elif B <= 4096:
        # few items: per-item slice assignment is a memcpy each; the
        # Python loop costs ~1us/item, never the dominant term at this B
        for i in range(B):
            ln = lens[i]
            out[i, :ln] = buf[offs[i]:offs[i] + ln]
    else:
        # many tiny items: vectorized ragged scatter
        # within-item byte ranks: [0..len0), [0..len1), ...
        ranks = np.arange(total, dtype=np.int64) - np.repeat(
            np.cumsum(lens) - lens, lens
        )
        src = np.repeat(offs, lens) + ranks
        dst = np.repeat(np.arange(B, dtype=np.int64) * width, lens) + ranks
        out.reshape(-1)[dst] = buf[src]
    words = out.view("<u4").reshape(B, nblocks, 32)
    return (
        np.ascontiguousarray(words[:, :, 1::2]),
        np.ascontiguousarray(words[:, :, 0::2]),
        lens.astype(np.uint32),
    )


def bucketed_extents(lens: np.ndarray) -> dict[int, np.ndarray]:
    """Indices grouped by power-of-two padded block count."""
    lens = np.asarray(lens, dtype=np.int64)
    blocks = np.maximum(1, -(-lens // BLOCK_BYTES))
    nb = 1 << np.ceil(np.log2(blocks)).astype(np.int64)
    out: dict[int, np.ndarray] = {}
    for b in np.unique(nb):
        out[int(b)] = np.nonzero(nb == b)[0]
    return out


def hash_extents(buf: np.ndarray, offs, lens,
                 use_pallas: bool | None = None, **pipeline_kw) -> np.ndarray:
    """BLAKE2b-256 digests of extents, submit order, as (N, 32) uint8.

    The bucketed, vectorized-pack version of
    :func:`..ops.blake2b.blake2b_batch` for data already resident in one
    buffer (replay logs, reassembled blobs).  The digests ride D2H here;
    device-side consumers should stay on :func:`hash_extents_device`.
    """
    n = len(offs)
    if not n:
        return np.empty((0, 32), dtype=np.uint8)
    hh, hl = hash_extents_device(buf, offs, lens, use_pallas, **pipeline_kw)
    if _OBS.on:
        _M_D2H.inc(32 * n)  # (N, 4) u32 hi + lo halves fetched
    raw = np.empty((n, 8), dtype="<u4")
    raw[:, 0::2] = np.asarray(hl)
    raw[:, 1::2] = np.asarray(hh)
    return raw.view(np.uint8).reshape(n, 32)


def hash_extents_device(buf: np.ndarray, offs, lens,
                        use_pallas: bool | None = None,
                        pipeline_bytes: int = 64 << 20,
                        pipeline_depth: int = 3):
    """Digests of extents as DEVICE arrays ``(hh, hl)``, each (N, 4) u32.

    The HBM-resident core of :func:`hash_extents`: columns are the four
    (hi, lo) u32 word pairs of the 32-byte digest (byte k*8..k*8+3 = lo
    word k, k*8+4..k*8+7 = hi word k, little-endian).  For consumers
    that keep reducing on device (sketch scatter-adds, Merkle leaf
    levels), fetching N 32-byte digests only to re-upload them is pure
    tunnel tax — at 1M digests that is 32 MB of D2H for nothing.

    Buckets whose padded volume exceeds ``pipeline_bytes`` are split
    into equal-shape chunks and PIPELINED: chunk k+1 is packed on the
    host and its upload staged (``device_put`` returns immediately)
    while chunk k compresses — H2D rides under compute instead of ahead
    of it.  A lagged fence bounds host memory to ``pipeline_depth``
    staged chunks (round-3 verdict weak #5: nothing overlapped).
    """
    import jax

    import jax.numpy as jnp

    offs = np.asarray(offs, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    n = len(offs)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    out_hh = jnp.zeros((max(1, n), 4), dtype=jnp.uint32)
    out_hl = jnp.zeros((max(1, n), 4), dtype=jnp.uint32)
    if not n:
        return out_hh[:0], out_hl[:0]
    # in-flight bound is in BYTES across ALL buckets (per-bucket counting
    # would let many small buckets dispatch unfenced, and a chunk forced
    # wide by the pallas floor would overrun a count-based bound):
    # staged host+HBM message arrays never exceed this.
    budget = max(1, pipeline_depth) * pipeline_bytes
    fences: list[tuple] = []  # (device array, staged bytes), oldest first
    inflight = 0
    for nb, idx in bucketed_extents(lens).items():
        # pad the batch axis to a power of two: jit specializes per
        # (B, nblocks) shape, and without bucketing B every distinct
        # batch size pays a fresh compile (minutes on the CPU backend's
        # scanned path).  Zero rows are valid empty payloads; their
        # digests land in rows the scatter below never touches.
        B = len(idx)
        chunk_b = max(1, pipeline_bytes // (nb * BLOCK_BYTES))
        if use_pallas:
            # chunks below the pallas tile width would route the WHOLE
            # bucket to the scan path (fn is picked per bucket, below);
            # keep the bucket kernel-eligible even when that makes one
            # chunk larger than pipeline_bytes — the byte budget above
            # still bounds how many ride in flight
            chunk_b = max(chunk_b, blake2b._PALLAS_MIN_ITEMS)
        chunk_b = blake2b._bucket_nblocks(min(chunk_b, max(1, B)))
        donate = blake2b.donation_supported()
        pallas_pick = use_pallas and chunk_b >= blake2b._PALLAS_MIN_ITEMS
        if pallas_pick:
            if donate:
                from ..ops.blake2b_pallas import (
                    blake2b_packed_pallas_donated as fn,
                )
            else:
                from ..ops.blake2b_pallas import blake2b_packed_pallas as fn
        else:
            fn = (blake2b.blake2b_packed_donated if donate
                  else blake2b.blake2b_packed)
        if _OBS.on:
            # keyed per bucket, same rationale as the blake2b batch edge
            _note_engine(
                "feed.hash_extents",
                "pallas" if pallas_pick else "xla-scan",
                key=nb, items=B, nblocks=nb)
        for c0 in range(0, B, chunk_b):
            sub = idx[c0:c0 + chunk_b]
            bs = len(sub)
            with _trace_span("device.dispatch", site="feed.hash_extents",
                             items=bs, nblocks=nb):
                mh, ml, blens = pack_ragged(buf, offs[sub], lens[sub], nb)
                if bs != chunk_b:  # tail chunk: same shape, one compile
                    pad = ((0, chunk_b - bs),)
                    mh = np.pad(mh, pad + ((0, 0), (0, 0)))
                    ml = np.pad(ml, pad + ((0, 0), (0, 0)))
                    blens = np.pad(blens, (0, chunk_b - bs))
                if _OBS.on:
                    _M_H2D.inc(mh.nbytes + ml.nbytes + blens.nbytes)
                    if fences:
                        # staged while older dispatches still compress:
                        # this upload rides UNDER compute, not after it
                        _M_H2D_OVERLAP.inc(mh.nbytes + ml.nbytes)
                # stage the upload: the transfer streams while earlier
                # chunks are still compressing, into HBM the donated
                # dispatches below keep recycling (double-buffering)
                mh_d = jax.device_put(mh)
                ml_d = jax.device_put(ml)
                hh, hl = fn(mh_d, ml_d, jnp.asarray(blens))
            at = jnp.asarray(sub)
            out_hh = out_hh.at[at].set(hh[:bs, :4])
            out_hl = out_hl.at[at].set(hl[:bs, :4])
            # fence the OLDEST in-flight chunks only (waiting on the
            # newest would drain the pipeline each iteration)
            fences.append((hh, mh.nbytes + ml.nbytes))
            inflight += mh.nbytes + ml.nbytes
            while fences and inflight > budget:
                h0, v0 = fences.pop(0)
                np.asarray(h0[:1, :1])
                inflight -= v0
    return out_hh, out_hl


@dataclasses.dataclass
class DeviceChangeBatch:
    """A decoded ``ChangeBatch`` resident in device layout.

    ``change`` / ``from_`` / ``to`` are (n,) uint32 device arrays (the
    columns land exactly as the wire carried them — no per-row host
    work); ``buf`` is the payload buffer on device with ``val_off`` /
    ``val_len`` extents addressing the value heap inside it, the shape
    the digest/merkle kernels gather from.
    """

    change: object
    from_: object
    to: object
    buf: object
    val_off: object
    val_len: object

    def __len__(self) -> int:
        return int(self.change.shape[0])


def decode_batch_device(payload, base: int = 0) -> DeviceChangeBatch:
    """Decode one ChangeBatch payload STRAIGHT into device arrays.

    The wire's columnar layout is already the device layout: the u32
    seq columns and the payload buffer upload as-is (``device_put`` from
    zero-copy numpy views), so merkle/digest work downstream starts from
    data that never took a per-row host detour.  Value extents ride
    along for device-side gathers; key/subset dictionaries stay host-
    side in the returned buffer (kernels address bytes, not strings).
    """
    import jax

    from ..wire.batch_codec import decode_change_batch

    cols = decode_change_batch(payload, base=base)
    n = len(cols.change)
    with _trace_span("device.dispatch", site="feed.decode_batch",
                     items=n):
        if _OBS.on:
            _M_H2D.inc(cols.buf.nbytes + 12 * n + 16 * n)
            _note_engine("feed.decode_batch", "device")
        return DeviceChangeBatch(
            change=jax.device_put(cols.change),
            from_=jax.device_put(cols.from_),
            to=jax.device_put(cols.to),
            buf=jax.device_put(cols.buf),
            val_off=jax.device_put(cols.val_off),
            val_len=jax.device_put(cols.val_len),
        )


def leaves_from_change_columns(cols) -> np.ndarray:
    """Merkle leaf digests for decoded change columns WITHOUT a matching
    per-record frame index — the batch-framed replay path.

    The leaf contract is framing-independent: a row's leaf is the
    BLAKE2b-256 of its canonical per-record payload encoding, so a
    batch-framed log and a per-record log of the same rows produce
    identical trees (PARITY.md).  Rows are re-encoded canonically in one
    native pass and hashed as extents — no per-row Python."""
    from ..runtime.replay import canonical_change_extents

    buf, offs, lens = canonical_change_extents(cols)
    return hash_extents(buf, offs, lens)


def leaves_from_columns(cols, frames=None) -> np.ndarray:
    """Merkle leaf digests for replayed change records, in log order.

    A leaf is the BLAKE2b-256 of the record's serialized payload bytes —
    content addressing over the change feed (the reference carries only
    version counters for this, reference: messages/schema.proto:4-5).
    ``cols`` is a :class:`..runtime.replay.ChangeColumns`; if ``frames``
    (the matching FrameIndex) is given, the raw framed payload extents
    are used directly, avoiding re-serialization.
    """
    if frames is not None:
        from ..wire.framing import TYPE_CHANGE

        sel = frames.ids == TYPE_CHANGE
        if int(sel.sum()) == len(cols):
            return hash_extents(frames.buf, frames.starts[sel],
                                frames.lens[sel])
        # batch frames carry rows the per-record extents don't cover:
        # hash the canonical re-encoding (identical digests either way)
        return leaves_from_change_columns(cols)
    # otherwise hash each record's re-encoded bytes (rarely needed) —
    # gate resolved once for the loop, same as replay's bulk encoders
    from ..wire.change_codec import _encode_change_with, _fastpath_mod

    fp = _fastpath_mod()
    payloads = [_encode_change_with(fp, cols.row(i))
                for i in range(len(cols))]
    return np.frombuffer(
        b"".join(blake2b.blake2b_batch(payloads)), dtype=np.uint8
    ).reshape(len(payloads), 32)
