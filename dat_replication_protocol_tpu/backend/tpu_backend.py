"""``backend='tpu'`` — the device-offloaded session ends.

Capability addition over the reference (which has no accelerator code at all):
`TpuEncoder` / `TpuDecoder` keep the exact session API and semantics of the
host :class:`~..session.encoder.Encoder` / :class:`~..session.decoder.Decoder`
— the reference's callback contract is unchanged — and additionally
content-hash every blob and change payload, batching thousands of payloads
per XLA dispatch on the device.

Digests are delivered through :meth:`on_digest` callbacks and, crucially,
**flushed before finalize**: the finalize hook only runs once digests for all
submitted work have been delivered (the TPU-native analogue of the
reference's drain-before-finalize discipline, reference: decode.js:124-142).

The hash engine is pluggable: :class:`DigestPipeline` talks to a callable
``hash_batch(payloads) -> list[bytes]``; by default it uses the batched
device BLAKE2b from :mod:`..ops.blake2b` when JAX is importable and falls
back to ``hashlib.blake2b`` otherwise, so the API works on any host.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional

from ..session.decoder import BlobReader, Decoder
from ..session.encoder import Encoder

DIGEST_SIZE = 32  # BLAKE2b-256, dat's content-hash size

OnDigest = Callable[[str, int, bytes], None]  # (kind, seq, digest)


def _host_hash_batch(payloads: list[bytes]) -> list[bytes]:
    return [
        hashlib.blake2b(p, digest_size=DIGEST_SIZE).digest() for p in payloads
    ]


def _device_hash_batch_factory() -> Callable[[list[bytes]], list[bytes]] | None:
    try:
        from ..ops.blake2b import blake2b_batch  # noqa: PLC0415

        return blake2b_batch
    except Exception:
        return None


class DigestPipeline:
    """Accumulates payloads into batches and dispatches them to the hash
    engine, mapping batch slots back to per-item completion callbacks.

    This is the completion-queue pattern SURVEY §7 calls out as the hard
    part: per-message callback ordering is preserved while the device sees
    large batches. Bounded in-flight work (``max_batch``) is the
    backpressure analogue of the reference's pending counter.
    """

    def __init__(
        self,
        hash_batch: Callable[[list[bytes]], list[bytes]] | None = None,
        max_batch: int = 1024,
        max_batch_bytes: int = 1 << 30,
    ):
        if hash_batch is None:
            hash_batch = _device_hash_batch_factory() or _host_hash_batch
        self._hash_batch = hash_batch
        self._max_batch = max_batch
        # byte cap bounds device/HBM footprint per dispatch — the item cap
        # alone would admit e.g. 1024 x 8 MiB blobs in one batch
        self._max_batch_bytes = max_batch_bytes
        self._payloads: list[bytes] = []
        self._cbs: list[Callable[[bytes], None]] = []
        self._pending_bytes = 0
        self.dispatches = 0
        self.hashed_bytes = 0

    def submit(self, payload: bytes, on_digest: Callable[[bytes], None]) -> None:
        self._payloads.append(payload)
        self._cbs.append(on_digest)
        self._pending_bytes += len(payload)
        if (
            len(self._payloads) >= self._max_batch
            or self._pending_bytes >= self._max_batch_bytes
        ):
            self.flush()

    def flush(self) -> None:
        """Dispatch everything queued; digests delivered in submit order."""
        if not self._payloads:
            return
        payloads, self._payloads = self._payloads, []
        cbs, self._cbs = self._cbs, []
        self._pending_bytes = 0
        self.dispatches += 1
        self.hashed_bytes += sum(len(p) for p in payloads)
        digests = self._hash_batch(payloads)
        if len(digests) != len(payloads):
            raise RuntimeError(
                f"hash backend returned {len(digests)} digests for "
                f"{len(payloads)} payloads"
            )
        for cb, digest in zip(cbs, digests):
            cb(bytes(digest))


class TpuDecoder(Decoder):
    """Decoder that additionally content-hashes every change value and blob.

    The wire-facing behavior is identical to the host Decoder — same
    callbacks, ordering, backpressure, destroy semantics. Digest delivery:

    * ``on_digest(kind, seq, digest)`` — ``kind`` is ``'change'`` or
      ``'blob'``; ``seq`` is that kind's 0-based arrival index.
    * all digests for submitted work are flushed before the finalize hook
      runs (flush-before-finalize).
    """

    def __init__(self, pipeline: DigestPipeline | None = None, **kwargs):
        super().__init__(**kwargs)
        self._pipeline = pipeline if pipeline is not None else DigestPipeline()
        self._digest_cbs: list[OnDigest] = []
        self._change_seq = 0
        self._blob_seq = 0
        self._blob_parts: dict[int, list[bytes]] = {}

    def on_digest(self, cb: OnDigest) -> "TpuDecoder":
        self._digest_cbs.append(cb)
        return self

    @property
    def digest_pipeline(self) -> DigestPipeline:
        return self._pipeline

    # -- hooks into the parser ----------------------------------------------

    def _emit_digest(self, kind: str, seq: int, digest: bytes) -> None:
        for cb in self._digest_cbs:
            cb(kind, seq, digest)

    def _finish_change(self, payload) -> None:
        if self._digest_cbs:
            seq = self._change_seq
            self._pipeline.submit(
                bytes(payload), lambda d, s=seq: self._emit_digest("change", s, d)
            )
        self._change_seq += 1
        super()._finish_change(payload)

    def _open_blob_if_ready(self) -> None:
        if self._digest_cbs:
            self._blob_parts[self._blob_seq] = []
        self._blob_seq += 1
        super()._open_blob_if_ready()

    def _blob_data(self, chunk):
        seq = self._blob_seq - 1
        take = min(len(chunk), self._missing)
        if self._digest_cbs and seq in self._blob_parts:
            self._blob_parts[seq].append(bytes(chunk[:take]))
        return super()._blob_data(chunk)

    def _end_blob(self) -> None:
        seq = self._blob_seq - 1
        parts = self._blob_parts.pop(seq, None)
        if parts is not None:
            self._pipeline.submit(
                b"".join(parts), lambda d, s=seq: self._emit_digest("blob", s, d)
            )
        super()._end_blob()

    def _maybe_finalize(self) -> None:
        # flush-before-finalize: digests for all submitted work are delivered
        # before the app's finalize hook runs.
        if (
            self._end_queued
            and not self.finished
            and not self.destroyed
            and not self._overflow
            and not self._stalled()
        ):
            self._pipeline.flush()
        super()._maybe_finalize()


class TpuEncoder(Encoder):
    """Encoder that content-hashes outgoing work on the device.

    Same wire output and ordering as the host Encoder; digests of every
    change payload and completed blob are delivered via ``on_digest``.
    """

    def __init__(self, pipeline: DigestPipeline | None = None, **kwargs):
        super().__init__(**kwargs)
        self._pipeline = pipeline if pipeline is not None else DigestPipeline()
        self._digest_cbs: list[OnDigest] = []
        self._change_seq = 0
        self._blob_seq = 0

    def on_digest(self, cb: OnDigest) -> "TpuEncoder":
        self._digest_cbs.append(cb)
        return self

    @property
    def digest_pipeline(self) -> DigestPipeline:
        return self._pipeline

    def _emit_digest(self, kind: str, seq: int, digest: bytes) -> None:
        for cb in self._digest_cbs:
            cb(kind, seq, digest)

    def _frame_change(self, payload: bytes, on_flush) -> bool:
        if self._digest_cbs:
            seq = self._change_seq
            self._pipeline.submit(
                payload, lambda d, s=seq: self._emit_digest("change", s, d)
            )
        self._change_seq += 1
        return super()._frame_change(payload, on_flush)

    def blob(self, length: int, on_flush=None):
        ws = super().blob(length, on_flush)
        if self._digest_cbs:
            seq = self._blob_seq
            parts: list[bytes] = []
            orig_write = ws.write
            orig_end = ws.end

            def write(data, on_flush=None):
                if isinstance(data, str):
                    data = data.encode("utf-8")
                parts.append(bytes(data))
                return orig_write(data, on_flush)

            def end(data=None, on_flush=None):
                # a final chunk routes through BlobWriter.end -> self.write,
                # which is the wrapped write above — it records `parts` there.
                was_ended = ws._ended
                orig_end(data, on_flush)
                if not was_ended:  # double end() must not duplicate the digest
                    self._pipeline.submit(
                        b"".join(parts),
                        lambda d, s=seq: self._emit_digest("blob", s, d),
                    )

            ws.write = write
            ws.end = end
        self._blob_seq += 1
        return ws

    def finalize(self, on_flush=None) -> None:
        self._pipeline.flush()  # flush-before-finalize
        super().finalize(on_flush)
