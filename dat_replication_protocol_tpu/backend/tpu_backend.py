"""``backend='tpu'`` — the device-offloaded session ends.

Capability addition over the reference (which has no accelerator code at all):
`TpuEncoder` / `TpuDecoder` keep the exact session API and semantics of the
host :class:`~..session.encoder.Encoder` / :class:`~..session.decoder.Decoder`
— the reference's callback contract is unchanged — and additionally
content-hash every blob and change payload, batching thousands of payloads
per XLA dispatch on the device.

Digests are delivered through :meth:`on_digest` callbacks and, crucially,
**flushed before finalize**: the finalize hook only runs once digests for all
submitted work have been delivered (the TPU-native analogue of the
reference's drain-before-finalize discipline, reference: decode.js:124-142).

The hash engine is pluggable: :class:`DigestPipeline` talks to a callable
``hash_batch(payloads) -> list[bytes]``; by default it uses the batched
device BLAKE2b from :mod:`..ops.blake2b` when JAX is importable and falls
back to ``hashlib.blake2b`` otherwise, so the API works on any host.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional

from ..obs.device import note_engine as _note_engine
from ..obs.metrics import OBS as _OBS, counter as _counter
from ..obs.tracing import trace_span as _trace_span
from ..session.decoder import BlobReader, Decoder
from ..session.encoder import Encoder
from ..utils.trace import span

DIGEST_SIZE = 32  # BLAKE2b-256, dat's content-hash size

# digest deliveries by session end (OBSERVABILITY.md catalog)
_M_DEC_DIGESTS = _counter("decoder.digests")
_M_ENC_DIGESTS = _counter("encoder.digests")
# device-path pipeline traffic (OBSERVABILITY.md device-telemetry
# catalog): payloads queued for hashing and batches dispatched.  Submit
# accounting is counters, not per-item spans — the bulk decoder submits
# per change, and the span story lives at the dispatch/deliver batch
# boundaries (same run-granularity discipline as `decoder.changes`).
_M_SUBMIT_ITEMS = _counter("device.submit.items")
_M_SUBMIT_BYTES = _counter("device.submit.bytes")
_M_DISPATCHES = _counter("device.dispatch.batches")

OnDigest = Callable[[str, int, bytes], None]  # (kind, seq, digest)


def _host_hash_batch(payloads: list[bytes]) -> list[bytes]:
    if len(payloads) >= 64:
        # many-payload batches: the native thread-parallel C pass skips
        # the ~1us/call interpreter overhead that binds a hashlib loop
        from ..runtime import native  # noqa: PLC0415

        if native.available():
            import numpy as np  # noqa: PLC0415

            # zero-copy span path first (no join); falls back to the
            # joined layout for non-bytes payloads or no extension
            out = native.hash_many_list(payloads)
            if out is None:
                lens = np.array([len(p) for p in payloads], dtype=np.int64)
                offs = np.cumsum(lens) - lens
                out = native.hash_many(
                    np.frombuffer(b"".join(payloads), np.uint8), offs, lens
                )
            if out is not None:
                if _OBS.on:
                    _note_engine("digest.hash", "native-host",
                                 items=len(payloads))
                return [row.tobytes() for row in out]
    if _OBS.on:
        _note_engine("digest.hash", "hashlib", items=len(payloads))
    return [
        hashlib.blake2b(p, digest_size=DIGEST_SIZE).digest() for p in payloads
    ]


def _device_hash_begin_factory():
    """Pick the batch engine by what actually backs jax, not by whether
    jax imports: on a CPU-only host the XLA scan loses to hashlib's C
    loop ~10x (measured 0.031 vs 0.33 GiB/s, round-3 verdict weak #4) —
    "batch or stay home" (DESIGN.md §2 rule 0) applies to the host too.
    ``DAT_DEVICE_HASH=1`` forces the device path (tests / experiments),
    ``=0`` forces the host engine."""
    import os  # noqa: PLC0415

    from ..utils.routing import prefer_host  # noqa: PLC0415

    if prefer_host("DAT_DEVICE_HASH"):
        return None
    try:
        from ..ops.blake2b import blake2b_batch_begin  # noqa: PLC0415

        if _OBS.on:
            _note_engine("digest.hash", "device-batch")
        return blake2b_batch_begin
    except Exception:
        return None


# blobs at least this long hash incrementally instead of being joined in
# host RAM for the batch path
DEFAULT_STREAM_THRESHOLD = 8 << 20


class _HostStream:
    """hashlib-backed incremental hasher — the PRIMARY engine for single
    blob streams (see :func:`_make_stream`: serial chains idle the
    device's vector lanes; measured 326 MiB/s here vs 2 MiB/s batch-1
    device scan).  Also the path on JAX-less hosts."""

    def __init__(self):
        self._h = hashlib.blake2b(digest_size=DIGEST_SIZE)
        self.length = 0

    def update(self, data) -> "_HostStream":
        # hashlib consumes buffer-protocol objects directly — copying a
        # memoryview/bytearray chunk here would tax the primary path
        self._h.update(data)
        self.length += memoryview(data).nbytes
        return self

    def digest(self) -> bytes:
        return self._h.digest()


def _make_stream():
    """Incremental hasher for ONE over-threshold blob: the host engine.

    A single BLAKE2b stream is inherently serial (each block chains into
    the next) — batch width 1 leaves the device's vector lanes idle, and
    the measured gap is decisive: 326 MiB/s (hashlib's C loop) vs
    2 MiB/s (the batch-1 device scan) on a 32 MiB stream.  The device
    earns its keep on BATCHES (thousands of blobs per dispatch, the
    DigestPipeline path below the threshold); routing serial streams to
    the host is the architecture, not a fallback.
    :class:`..ops.blake2b.Blake2bStream` remains the device-resident
    chaining engine for pipelines that need digests to stay in HBM.
    """
    return _HostStream()


class DigestPipeline:
    """Accumulates payloads into batches, dispatches them asynchronously,
    and maps batch slots back to per-item completion callbacks.

    This is the completion-queue pattern SURVEY §7 calls out as the hard
    part: per-message callback ordering is preserved while the device sees
    large batches.  Dispatch is **asynchronous**: when a batch fills, the
    device starts hashing while the host keeps parsing; digests are
    collected (oldest batch first, entries in submit order within each)
    when ``max_inflight`` batches are outstanding — the backpressure bound
    — or at ``flush()``, which drains everything (the finalize barrier).
    """

    def __init__(
        self,
        hash_batch: Callable[[list[bytes]], list[bytes]] | None = None,
        max_batch: int = 1024,
        max_batch_bytes: int = 1 << 30,
        max_inflight: int = 2,
        hash_begin=None,
    ):
        # engines: ``hash_begin(payloads) -> collect()`` is the async
        # interface; a plain ``hash_batch`` callable (tests, custom
        # engines) is wrapped to compute eagerly at dispatch time
        if hash_begin is None:
            if hash_batch is not None:
                hash_begin = lambda ps: (lambda out=hash_batch(ps): out)  # noqa: E731
            else:
                hash_begin = _device_hash_begin_factory() or (
                    lambda ps: (lambda out=_host_hash_batch(ps): out)
                )
        self._hash_begin = hash_begin
        self._max_batch = max_batch
        # byte cap bounds device/HBM footprint per dispatch — the item cap
        # alone would admit e.g. 1024 x 8 MiB blobs in one batch
        self._max_batch_bytes = max_batch_bytes
        self._max_inflight = max(1, max_inflight)
        # ordered queue of ("payload", bytes, cb) | ("stream", stream, cb):
        # payload entries batch into one device dispatch; stream entries
        # were already hashed incrementally (their bytes never queue here)
        # and only finalize at delivery, preserving submit-order delivery
        self._entries: list[tuple] = []
        self._pending_bytes = 0
        self._inflight: list[tuple[list[tuple], Callable[[], list[bytes]]]] = []
        self.dispatches = 0
        self.hashed_bytes = 0

    def submit(self, payload: bytes, on_digest: Callable[[bytes], None],
               tag=None) -> None:
        """Queue one payload.  ``tag`` (when not None) is passed back as
        ``on_digest(tag, digest)`` — a shared bound method + tag costs no
        per-item closure, which matters at the bulk decoder's change
        rates (a lambda per change was ~20% of the digest path)."""
        if _OBS.on:
            _M_SUBMIT_ITEMS.inc()
            _M_SUBMIT_BYTES.inc(len(payload))
        self._entries.append(("payload", payload, on_digest, tag))
        self._pending_bytes += len(payload)
        if (
            len(self._entries) >= self._max_batch
            or self._pending_bytes >= self._max_batch_bytes
        ):
            self.dispatch()

    def submit_stream(self, stream, on_digest: Callable[[bytes], None],
                      tag=None) -> None:
        """Queue a finished incremental hash (:class:`..ops.blake2b.
        Blake2bStream`-shaped: ``.digest()``/``.length``) for in-order
        digest delivery alongside batched payloads."""
        if _OBS.on:
            _M_SUBMIT_ITEMS.inc()
            # a blob-heavy session carries its dominant byte volume
            # through streams — the bytes counter must say so
            _M_SUBMIT_BYTES.inc(int(getattr(stream, "length", 0)))
        self._entries.append(("stream", stream, on_digest, tag))
        if len(self._entries) >= self._max_batch:
            self.dispatch()

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    def dispatch(self) -> None:
        """Start hashing everything queued WITHOUT waiting for results.

        If more than ``max_inflight`` batches would be outstanding, the
        oldest is collected first — bounded in-flight work is the
        device-side analogue of the reference's pending counter.

        Pipelined readback (ISSUE 7 part 3): the moment a NEWER batch is
        dispatched, every older in-flight batch's digest D2H is STARTED
        (``collect.start_d2h``, non-blocking) — so when the in-flight
        bound forces ``_deliver_oldest`` below, the transfer has been
        streaming under this batch's compute instead of starting cold
        inside the deliver, and the next submit never waits on a full
        link round-trip.
        """
        if not self._entries:
            return
        entries, self._entries = self._entries, []
        pending = self._pending_bytes
        self._pending_bytes = 0
        self.dispatches += 1
        if _OBS.on:
            _M_DISPATCHES.inc()
        payloads = [e[1] for e in entries if e[0] == "payload"]
        with _trace_span("device.dispatch", items=len(entries),
                         bytes=pending), span("digest.dispatch"):
            collect = self._hash_begin(payloads) if payloads else (lambda: [])
        self._prefetch_inflight()  # older batches' D2H rides under this
        # batch's compute (idempotent per closure)
        self._inflight.append((entries, collect))
        while len(self._inflight) > self._max_inflight:
            self._deliver_oldest()

    def _prefetch_inflight(self) -> None:
        for _, collect in self._inflight:
            start = getattr(collect, "start_d2h", None)
            if start is not None:
                start()

    def _deliver_oldest(self) -> None:
        entries, collect = self._inflight.pop(0)
        payload_count = sum(1 for e in entries if e[0] == "payload")
        with _trace_span("device.deliver", items=len(entries)), \
                span("digest.collect"):
            digest_list = collect()
        if len(digest_list) != payload_count:
            raise RuntimeError(
                f"hash backend returned {len(digest_list)} digests for "
                f"{payload_count} payloads"
            )
        digests = iter(digest_list)
        for kind, item, cb, tag in entries:
            if kind == "payload":
                self.hashed_bytes += len(item)
                d = bytes(next(digests))
            else:
                self.hashed_bytes += item.length
                d = item.digest()
            if tag is None:
                cb(d)
            else:
                cb(tag, d)

    def flush(self) -> None:
        """Dispatch anything queued and deliver ALL outstanding digests in
        submit order — the flush-before-finalize barrier."""
        self.dispatch()
        self._prefetch_inflight()  # all readbacks stream concurrently;
        # the in-order delivery loop below then waits on warm transfers
        while self._inflight:
            self._deliver_oldest()


class TpuDecoder(Decoder):
    """Decoder that additionally content-hashes every change value and blob.

    The wire-facing behavior is identical to the host Decoder — same
    callbacks, ordering, backpressure, destroy semantics. Digest delivery:

    * ``on_digest(kind, seq, digest)`` — ``kind`` is ``'change'`` or
      ``'blob'``; ``seq`` is that kind's 0-based arrival index.
    * all digests for submitted work are flushed before the finalize hook
      runs (flush-before-finalize).
    """

    def __init__(self, pipeline: DigestPipeline | None = None,
                 stream_threshold: int = DEFAULT_STREAM_THRESHOLD, **kwargs):
        super().__init__(**kwargs)
        self._pipeline = pipeline if pipeline is not None else DigestPipeline()
        self._digest_cbs: list[OnDigest] = []
        self._change_seq = 0
        self._blob_seq = 0
        self._blob_parts: dict[int, list[bytes]] = {}
        # blobs at least this long hash incrementally (O(segment) memory,
        # no < 2 GiB cap) instead of joining chunks for the batch path
        self._stream_threshold = stream_threshold
        self._blob_streams: dict[int, object] = {}

    def on_digest(self, cb: OnDigest) -> "TpuDecoder":
        self._digest_cbs.append(cb)
        return self

    @property
    def digest_pipeline(self) -> DigestPipeline:
        return self._pipeline

    def _checkpoint_digest(self) -> dict:
        # the running digest state a resumed session must continue from:
        # the next change/blob digest sequence numbers.  Per-payload
        # digests are independent (no chaining across frames), so the
        # counters ARE the whole state — a reconnected decoder keeps
        # numbering without gaps or repeats (see ROBUSTNESS.md).
        return {"change_seq": self._change_seq, "blob_seq": self._blob_seq}

    # -- hooks into the parser ----------------------------------------------

    def _emit_digest(self, kind: str, seq: int, digest: bytes) -> None:
        if _OBS.on:
            _M_DEC_DIGESTS.inc()
        for cb in self._digest_cbs:
            cb(kind, seq, digest)

    def _emit_change_digest(self, seq: int, digest: bytes) -> None:
        self._emit_digest("change", seq, digest)

    def _emit_blob_digest(self, seq: int, digest: bytes) -> None:
        self._emit_digest("blob", seq, digest)

    # ride the base bulk fast loop (C dispatch included): the ONLY
    # per-change addition here is payload digesting, which the loop
    # taps via _note_change_payloads — exactly the sink contract
    _bulk_payload_sink = True

    def _payload_sink_active(self) -> bool:
        # collection (payload slicing + hashing) only when someone is
        # listening — the streaming path's `if self._digest_cbs:` guard,
        # bulk edition; sequence accounting advances either way
        return bool(self._digest_cbs)

    def _deliver_change(self, change, payload) -> None:
        # hooked at _deliver_change (not _finish_change) so BOTH parse
        # paths — the streaming scanner and the native bulk index, which
        # skips _finish_change's re-parse — hash every change payload.
        # ``change`` may be None here (no handler registered; see the
        # base hook's private contract) — only ``payload`` is used.
        # (The bulk fast loop bypasses this method entirely and delivers
        # payloads through _note_change_payloads below.)
        if self._digest_cbs:
            seq = self._change_seq
            self._pipeline.submit(bytes(payload), self._emit_change_digest,
                                  seq)
        self._change_seq += 1
        super()._deliver_change(change, payload)

    def _note_change_payloads(self, payloads, count: int) -> None:
        # the bulk loop's tap: payloads arrive in delivery order for the
        # whole run; per-seq submit order (and therefore digest delivery
        # order) matches the per-frame path exactly.  A pipeline with a
        # bulk surface (the hub's session facade: one window check and
        # one lock round-trip per run instead of per payload) gets the
        # whole run at once — identical tags/ordering either way.
        seq = self._change_seq
        if payloads:
            submit_many = getattr(self._pipeline, "submit_many", None)
            if submit_many is not None:
                submit_many(payloads, self._emit_change_digest, seq)
                self._change_seq = seq + len(payloads)
                return
            submit = self._pipeline.submit
            emit = self._emit_change_digest
            for p in payloads:
                submit(p, emit, seq)
                seq += 1
            self._change_seq = seq
        else:
            self._change_seq = seq + count

    def _note_change_batch(self, cols, n: int) -> None:
        # ChangeBatch frames carry no per-record protobuf bytes on the
        # wire, but the digest CONTRACT is framing-independent: a row's
        # digest is the BLAKE2b of its canonical per-record encoding, so
        # batch-framed and per-record peers produce identical digest
        # streams (WIRE.md sidecar convention, PARITY.md).  Re-encoding
        # rides the native columnar encoder — one C pass, no per-row
        # Python — and submit order matches wire row order.
        if not self._digest_cbs:
            self._change_seq += n
            return
        from ..runtime.replay import canonical_change_payloads

        seq = self._change_seq
        submit = self._pipeline.submit
        emit = self._emit_change_digest
        for p in canonical_change_payloads(cols):
            submit(p, emit, seq)
            seq += 1
        self._change_seq = seq

    def _open_blob_if_ready(self) -> None:
        if self._digest_cbs:
            # self._missing is the blob's wire length at header time
            if self._missing >= self._stream_threshold:
                self._blob_streams[self._blob_seq] = _make_stream()
            else:
                self._blob_parts[self._blob_seq] = []
        self._blob_seq += 1
        super()._open_blob_if_ready()

    def _note_blob_bytes(self, data: bytes) -> None:
        # shares the decoder's already-materialized bytes object — the
        # digest path holds references, not a second copy of the blob
        # (round-2 verdict weak #5)
        seq = self._blob_seq - 1
        if seq in self._blob_streams:
            self._blob_streams[seq].update(data)
        elif seq in self._blob_parts:
            self._blob_parts[seq].append(data)

    def _end_blob(self) -> None:
        seq = self._blob_seq - 1
        parts = self._blob_parts.pop(seq, None)
        stream = self._blob_streams.pop(seq, None)
        if stream is not None:
            self._pipeline.submit_stream(stream, self._emit_blob_digest, seq)
        elif parts is not None:
            self._pipeline.submit(b"".join(parts), self._emit_blob_digest,
                                  seq)
        super()._end_blob()

    def _maybe_finalize(self) -> None:
        # flush-before-finalize: digests for all submitted work are delivered
        # before the app's finalize hook runs.
        if (
            self._end_queued
            and not self.finished
            and not self.destroyed
            and not self._overflow
            and not self._stalled()
        ):
            self._pipeline.flush()
        super()._maybe_finalize()


class TpuEncoder(Encoder):
    """Encoder that content-hashes outgoing work on the device.

    Same wire output and ordering as the host Encoder; digests of every
    change payload and completed blob are delivered via ``on_digest``.
    """

    def __init__(self, pipeline: DigestPipeline | None = None,
                 stream_threshold: int = DEFAULT_STREAM_THRESHOLD, **kwargs):
        super().__init__(**kwargs)
        self._pipeline = pipeline if pipeline is not None else DigestPipeline()
        self._digest_cbs: list[OnDigest] = []
        self._change_seq = 0
        self._blob_seq = 0
        self._stream_threshold = stream_threshold

    def on_digest(self, cb: OnDigest) -> "TpuEncoder":
        self._digest_cbs.append(cb)
        return self

    @property
    def digest_pipeline(self) -> DigestPipeline:
        return self._pipeline

    def _emit_digest(self, kind: str, seq: int, digest: bytes) -> None:
        if _OBS.on:
            _M_ENC_DIGESTS.inc()
        for cb in self._digest_cbs:
            cb(kind, seq, digest)

    def _emit_change_digest(self, seq: int, digest: bytes) -> None:
        self._emit_digest("change", seq, digest)

    def _emit_blob_digest(self, seq: int, digest: bytes) -> None:
        self._emit_digest("blob", seq, digest)

    def _frame_change(self, payload: bytes, on_flush) -> bool:
        if self._digest_cbs:
            seq = self._change_seq
            self._pipeline.submit(payload, self._emit_change_digest, seq)
        self._change_seq += 1
        return super()._frame_change(payload, on_flush)

    def _note_batch_rows(self, rows) -> None:
        # negotiated ChangeBatch flush: the frame carries no per-record
        # bytes, but the digest contract is framing-independent
        # (WIRE.md) — each row's digest hashes its canonical per-record
        # encoding, in the same seq stream _frame_change would have
        # produced, submitted before the frame is queued.
        if not self._digest_cbs:
            self._change_seq += len(rows)
            return
        from ..wire.change_codec import _encode_change_with, _fastpath_mod

        fp = _fastpath_mod()  # bound once for the batch
        seq = self._change_seq
        submit = self._pipeline.submit
        emit = self._emit_change_digest
        for key, cg, fr, to, val, sub in rows:
            payload = _encode_change_with(fp, {
                "key": key.decode("utf-8"), "change": cg, "from": fr,
                "to": to, "value": val,
                "subset": None if sub is None else sub.decode("utf-8"),
            })
            submit(payload, emit, seq)
            seq += 1
        self._change_seq = seq

    def blob(self, length: int, on_flush=None):
        ws = super().blob(length, on_flush)
        if self._digest_cbs:
            seq = self._blob_seq
            streaming = length >= self._stream_threshold
            sink = _make_stream() if streaming else []
            orig_write = ws.write
            orig_end = ws.end

            def write(data, on_flush=None):
                if isinstance(data, str):
                    data = data.encode("utf-8")
                if streaming:
                    sink.update(data)
                else:
                    sink.append(bytes(data))
                return orig_write(data, on_flush)

            def end(data=None, on_flush=None):
                # a final chunk routes through BlobWriter.end -> self.write,
                # which is the wrapped write above — it records `sink` there.
                was_ended = ws._ended
                orig_end(data, on_flush)
                if not was_ended:  # double end() must not duplicate the digest
                    if streaming:
                        self._pipeline.submit_stream(
                            sink, self._emit_blob_digest, seq)
                    else:
                        self._pipeline.submit(
                            b"".join(sink), self._emit_blob_digest, seq)

            ws.write = write
            ws.end = end
        self._blob_seq += 1
        return ws

    def finalize(self, on_flush=None) -> None:
        self._pipeline.flush()  # flush-before-finalize
        super().finalize(on_flush)
