"""The event-driven edge loop: ONE epoll session table (ISSUE 17).

``EdgeLoop._dispatch_loop`` is the C10k rewrite of the sidecar's
thread-per-connection edge: a ``selectors``/epoll loop whose per-turn
I/O primitive is the PR 14 pump's batched recv/send
(:func:`~..session.pump.recv_step` / ``send_step``), serving hub
sessions, broadcast subscribers, reconcile/snapshot responders, and
gossip exchanges from one session table — one hub serving N broadcast
groups — with per-session QoS classes mapped onto the hub's
weight presets.

**The staged-overload contract is preserved verbatim** (ROBUSTNESS.md):

1. **Admission** — the hub's :class:`~..hub.HubBusy` and the fan-out's
   ``FanoutBusy`` make the SAME decision with the SAME structured
   rejection records as the threaded edge; the loop adds no new arm.
2. **Per-session windows** — the submit window moves from a blocked
   session thread to a READ GATE: while
   :meth:`~..hub.HubSession.window_room` (the identical predicate) is
   false, the session's fd leaves the readable set, the kernel socket
   buffer fills, and the peer's TCP window closes.  Identical ladder,
   new mechanism.
3. **Heaviest-offender shed** — the hub's policy, unchanged; a shed
   surfaces on this session's next submit or poll exactly as it
   surfaced on the threaded session's next submit or wait.

A faulted or slow session never perturbs a neighbor: every kernel call
the loop inlines is bounded (non-blocking fds set at admission; the
certifier's ``edge-dispatch`` entry in
``artifacts/event_loop_surface.json`` is the review artifact), and a
stalled reply tears down on the same ``drain_timeout`` clock as the
threaded edge.
"""

from __future__ import annotations

import itertools
import os
import selectors
import socket
import sys
import time
from typing import Callable, Optional

from ..hub import HubBusy, SessionShed
from ..obs.events import emit as _emit
from ..obs.loopprof import LoopProfiler, SAMPLE_EVERY
from ..obs.metrics import (
    OBS as _OBS,
    REGISTRY as _REGISTRY,
    counter as _counter,
)
from ..session.pump import (
    PUMP_BUF,
    EdgePump,
    effective_pump_route,
    recv_step,
    send_step,
)
from ..sidecar import DEFAULT_DRAIN_TIMEOUT, _send_refusal
from .machines import (
    hub_machine,
    reconcile_machine,
    replica_machine,
    snapshot_machine,
)

__all__ = ["EdgeLoop", "serve_edge", "QOS_PRESETS", "EDGE_TICK"]

# per-QoS-class presets mapped onto the hub's existing weight knob
# (ISSUE 17): latency-tier sessions get a 4x weighted-fair share (the
# hub's quota pass) and a small per-turn receive slab, so one
# throughput session's megabyte batches never sit between a latency
# session's frame and its digest; throughput-tier sessions keep the
# pump's full batch geometry
QOS_PRESETS = {
    "latency": {"weight": 4.0, "recv_cap": 256 << 10},
    "throughput": {"weight": 1.0, "recv_cap": PUMP_BUF},
}

# selector timeout: the loop's guarded fallback, NOT its pacing — I/O
# readiness wakes it immediately; the tick only bounds how stale a
# timer-driven check (stall clocks, subscriber done-probes) can get
EDGE_TICK = 0.05

# accepted connections per accept turn: bounds one turn's admission
# work so a connect flood cannot starve live sessions' I/O
ACCEPT_BURST = 64

_M_SESSIONS = _counter("sidecar.sessions")
_M_STALLS = _counter("sidecar.stalls")

# edge.served/admitted/rejected/shed are exported by the loop's
# registry COLLECTOR (labeled by loop name, read straight off the
# admission attributes) rather than gate-dependent registered counters:
# the gate-off path used to under-report them as zero while
# admission_state() told the truth (ISSUE 18 satellite)

# default loop names for telemetry labels when the owner passes none:
# edge0, edge1, ... in construction order (deterministic per process)
_LOOP_SEQ = itertools.count()


class EdgeSession:
    """One row of the unified session table."""

    __slots__ = ("n", "fd", "conn", "peer", "kind", "key", "qos",
                 "pump", "machine", "group", "is_source", "fanout_peer",
                 "tap", "rx_eof", "tx_done", "tx_ready", "tx_blocked",
                 "mask", "progress", "error", "dead", "not_source",
                 "sub_done")

    def __init__(self, n: int, conn: socket.socket, peer, kind: str,
                 key: str, qos: str):
        self.n = n
        self.fd = conn.fileno()
        self.conn = conn
        self.peer = peer
        self.kind = kind          # hub | subscriber | reconcile |
        self.key = key            #   replica | snapshot
        self.qos = qos
        self.pump: Optional[EdgePump] = None
        self.machine = None
        self.group: Optional[str] = None
        self.is_source = False
        self.fanout_peer = None
        self.tap = None
        self.rx_eof = False
        self.tx_done = False
        self.tx_ready = True      # first sweep probes the encoder once
        self.tx_blocked = False
        self.mask = 0
        self.progress = time.monotonic()
        self.error: Optional[BaseException] = None
        self.dead = False
        self.not_source = False
        self.sub_done = False


class EdgeLoop:
    """See module docstring.  Construct, :meth:`serve` (blocking; run
    on a thread in tests), :meth:`close` from any thread.

    ``mode_of(n, peer)`` picks each accepted connection's leg —
    ``"hub" | "fanout" | "reconcile" | "replica" | "snapshot"`` — and
    defaults to the threaded ``serve_tcp`` precedence over whichever
    legs are configured; ``qos_of(n, peer, mode)`` picks the QoS class
    (default ``"throughput"``); ``group_of(n, peer)`` picks the
    broadcast group for ``"fanout"`` connections (default: the first
    configured group).
    """

    def __init__(self, hub=None, *, fanouts=None, reconcile_replica=None,
                 snapshot_source=None, replica_node=None,
                 mode_of: Optional[Callable] = None,
                 qos_of: Optional[Callable] = None,
                 group_of: Optional[Callable] = None,
                 drain_timeout: Optional[float] = DEFAULT_DRAIN_TIMEOUT,
                 max_sessions: Optional[int] = None,
                 tick: float = EDGE_TICK,
                 name: Optional[str] = None,
                 profile_every: int = SAMPLE_EVERY):
        self._hub = hub
        self._fanouts = dict(fanouts) if fanouts else {}
        self._reconcile_replica = reconcile_replica
        self._snapshot_source = snapshot_source
        self._replica_node = replica_node
        self._mode_of = mode_of if mode_of is not None else self._default_mode
        self._qos_of = qos_of if qos_of is not None else (
            lambda n, peer, mode: "throughput")
        self._group_of = group_of
        self._drain_timeout = drain_timeout
        self._max_sessions = max_sessions
        self._tick = float(tick)
        # the flight deck (ISSUE 18): per-turn phase accounting, the
        # loop-lag watermark, and the sampling turn profiler — only the
        # lit dispatch twin ever touches it
        self.profiler = LoopProfiler(name or f"edge{next(_LOOP_SEQ)}",
                                     tick=self._tick,
                                     sample_every=profile_every)

        self._sel = selectors.DefaultSelector()
        self._srv: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._table: dict[int, EdgeSession] = {}
        self._served = 0
        self._admitted = 0
        self._rejected = 0
        self._shed = 0
        self._closed = False
        # source-slot claims, one per broadcast group (the serve_tcp
        # election, per group): claimed at admit, released by a source
        # that published nothing
        self._src_claims: dict[str, bool] = {g: False for g in self._fanouts}
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        self._collector_fn = self._collect
        _REGISTRY.register_collector("edge", self._collector_fn)

    def _default_mode(self, n: int, peer) -> str:
        if self._snapshot_source is not None:
            return "snapshot"
        if self._replica_node is not None:
            return "replica"
        if self._reconcile_replica is not None:
            return "reconcile"
        if self._fanouts:
            return "fanout"
        return "hub"

    # -- lifecycle -----------------------------------------------------------

    def bind(self, host: str, port: int) -> int:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((host, port))
            srv.listen(128)
        except OSError:
            srv.close()
            raise
        srv.setblocking(False)
        self._srv = srv
        self.port = srv.getsockname()[1]
        self._sel.register(srv.fileno(), selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        return self.port

    def serve(self, ready_cb=None) -> None:
        """Run the loop on the calling thread until :meth:`close` — or,
        with ``max_sessions`` set (tests), until that many connections
        were served AND the table drained."""
        if self._srv is None:
            raise RuntimeError("bind() first")
        print(f"sidecar: edge listening on :{self.port}",
              file=sys.stderr, flush=True)
        if ready_cb is not None:
            ready_cb(self.port)
        self.profiler.attach()
        try:
            self._dispatch_loop()
        finally:
            self._shutdown()

    def close(self) -> None:
        """Signal the loop to exit (thread-safe, idempotent)."""
        self._closed = True
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass

    def _shutdown(self) -> None:
        self.profiler.detach()
        _REGISTRY.unregister_collector("edge", self._collector_fn)
        for sess in list(self._table.values()):
            try:
                if sess.fanout_peer is not None:
                    sess.fanout_peer.close()
                sess.conn.close()
            except OSError:
                pass
        self._table.clear()
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        self._sel.close()

    # -- the loop (the enforced dispatcher: edge-dispatch) ------------------

    def _dispatch_loop(self) -> None:
        # one gate check per TURN forks the lit/dark twins: the dark
        # twin is the certified dispatcher verbatim — disabled
        # telemetry pays the one attribute load and nothing else (the
        # PR 3 budget contract, enforced by a bytecode test)
        while not self._closed:
            if _OBS.on:
                self._lit_turn()
            else:
                self._dark_turn()
            if (self._max_sessions is not None
                    and self._served >= self._max_sessions
                    and not self._table):
                return

    def _dark_turn(self) -> None:
        events = self._sel.select(self._tick)
        now = time.monotonic()
        for skey, mask in events:
            tag = skey.data
            if tag == "accept":
                self._accept_burst()
            elif tag == "wake":
                self._drain_wake()
            else:
                self._io_turn(tag, mask, now)
        self._sweep(time.monotonic())

    def _lit_turn(self) -> None:
        # the dark twin with the flight deck's monotonic splits: every
        # timer is two time.monotonic() reads around work the loop was
        # doing anyway — no new kernel calls, no new blocking surface
        prof = self.profiler
        prof.turn_begin(time.monotonic())
        events = self._sel.select(self._tick)
        now = time.monotonic()
        prof.poll_done(now, len(events))
        for skey, mask in events:
            tag = skey.data
            if tag == "accept":
                t0 = time.monotonic()
                self._accept_burst()
                prof.phase("accept", time.monotonic() - t0)
            elif tag == "wake":
                self._drain_wake()
            else:
                self._io_turn(tag, mask, now, prof)
        self._sweep(time.monotonic(), prof)
        prof.turn_done(time.monotonic(), sessions=len(self._table))

    def _drain_wake(self) -> None:
        try:
            # bounded: the wake pipe is O_NONBLOCK (set at construction)
            # datlint: allow-blocking-reachable(os-io)
            os.read(self._wake_r, 4096)
        except OSError:
            pass

    # -- admission (overload stage 1: the hub/fanout decision) --------------

    def _accept_burst(self) -> None:
        for _ in range(ACCEPT_BURST):
            if (self._max_sessions is not None
                    and self._served >= self._max_sessions):
                return
            try:
                # bounded: the listener is O_NONBLOCK (bind() flips it)
                # — no connection pending returns EAGAIN, never sleeps
                # datlint: allow-blocking-reachable(socket)
                conn, peer = self._srv.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                # EMFILE/ECONNABORTED burst: stop this turn; the
                # listener stays registered, next turn retries — the
                # tick is the backoff
                return
            self._served += 1
            try:
                self._admit(conn, peer, self._served)
            except Exception as e:  # an admission failure is one
                # connection's problem, never the loop's
                _emit("edge.error",
                      error=f"admit: {type(e).__name__}: {e}")
                try:
                    conn.close()
                except OSError:
                    pass

    def _admit(self, conn: socket.socket, peer, n: int) -> None:
        conn.setblocking(False)
        # mode/qos/group selectors are admission-table lookups (tests
        # hand in dict.__getitem__ over a precomputed schedule) — the
        # injection contract is "classify, don't compute": any failure
        # is absorbed by _accept_burst's per-admission except arm,
        # which closes THIS conn and leaves the table untouched
        # datlint: allow-callback-escape
        mode = self._mode_of(n, peer)
        # datlint: allow-callback-escape
        qos = self._qos_of(n, peer, mode)
        preset = QOS_PRESETS[qos]
        host_port = f"{peer[0]}:{peer[1]}"
        if mode == "fanout":
            # datlint: allow-callback-escape
            group = (self._group_of(n, peer) if self._group_of is not None
                     else next(iter(self._fanouts)))
            fanout = self._fanouts[group]
            is_source = False
            if not fanout.log.sealed and not self._src_claims[group]:
                self._src_claims[group] = True
                is_source = True
            if is_source:
                sess = self._admit_hub(conn, peer, n, qos, preset,
                                       key=f"c{n}:{host_port}")
                if sess is not None:
                    sess.group = group
                    sess.is_source = True
                    sess.tap = fanout.publish
                else:
                    # rejected at the hub: the slot goes back
                    self._src_claims[group] = False
                return
            self._admit_subscriber(conn, peer, n, qos, fanout,
                                   key=f"p{n}:{host_port}", group=group)
            return
        if mode == "hub":
            self._admit_hub(conn, peer, n, qos, preset,
                            key=f"c{n}:{host_port}")
            return
        # responder legs: reconcile / replica / snapshot
        if mode == "reconcile":
            machine = reconcile_machine(self._reconcile_replica, host_port)
        elif mode == "replica":
            machine = replica_machine(self._replica_node, host_port)
        elif mode == "snapshot":
            machine = snapshot_machine(self._snapshot_source, host_port)
        else:
            raise ValueError(f"unknown edge mode {mode!r}")
        sess = EdgeSession(n, conn, peer, mode, host_port, qos)
        sess.machine = machine
        sess.pump = EdgePump(conn.fileno(), cap=preset["recv_cap"])
        self._install(sess)

    def _admit_hub(self, conn, peer, n, qos, preset,
                   key: str) -> Optional[EdgeSession]:
        from .. import decode, encode  # lazy, like the threaded leg

        try:
            machine = hub_machine(encode, decode, self._hub, key,
                                  weight=preset["weight"])
        except HubBusy as e:
            # the threaded leg's exact rejection record: no decoder, no
            # reply bytes — the client observes EOF (overload stage 1)
            out = {"changes": 0, "blobs": 0, "bytes": 0, "digests": 0,
                   "ok": False, "rejected": True,
                   "sessions": e.sessions, "parked_bytes": e.parked_bytes}
            self._rejected += 1
            if _OBS.on:
                _emit("sidecar.session", **out)
            try:
                conn.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            conn.close()
            return None
        sess = EdgeSession(n, conn, peer, "hub", key, qos)
        sess.machine = machine
        sess.pump = EdgePump(conn.fileno(), cap=preset["recv_cap"])
        self._install(sess)
        return sess

    def _admit_subscriber(self, conn, peer, n, qos, fanout, key: str,
                          group: str) -> None:
        from ..fanout import FanoutBusy, SnapshotNeeded

        try:
            fanout_peer = fanout.attach_peer(key, fd=conn.fileno(),
                                             offset=0)
        except SnapshotNeeded as e:
            out = {"fanout_peer": key, "ok": False,
                   "snapshot_needed": True, "retained": list(e.retained)}
            if e.hint is not None:
                out["hint"] = dict(e.hint)
            _send_refusal(conn, out)
            if _OBS.on:
                _emit("sidecar.session", **out)
            conn.close()
            return
        except FanoutBusy as e:
            out = {"fanout_peer": key, "ok": False, "rejected": True,
                   "peers": e.peers, "max_peers": e.max_peers}
            self._rejected += 1
            if _OBS.on:
                _emit("sidecar.session", **out)
            _send_refusal(conn, out)
            conn.close()
            return
        sess = EdgeSession(n, conn, peer, "subscriber", key, qos)
        sess.fanout_peer = fanout_peer
        sess.group = group
        self._install(sess)

    def _install(self, sess: EdgeSession) -> None:
        self._table[sess.fd] = sess
        self._admitted += 1
        self._update_mask(sess)

    # -- per-session turns ---------------------------------------------------

    def _io_turn(self, sess: EdgeSession, mask: int, now: float,
                 prof: Optional[LoopProfiler] = None) -> None:
        if sess.dead:
            return
        try:
            if mask & selectors.EVENT_READ:
                if sess.kind == "subscriber":
                    self._probe_subscriber(sess)
                elif prof is not None:
                    t0 = time.monotonic()
                    rx = self._read_turn(sess, now)
                    prof.account("read", sess.key,
                                 time.monotonic() - t0, rx)
                else:
                    self._read_turn(sess, now)
            if mask & selectors.EVENT_WRITE and not sess.dead:
                if prof is not None:
                    t0 = time.monotonic()
                    tx = self._tx_turn(sess, now)
                    prof.account("tx", sess.key,
                                 time.monotonic() - t0, tx)
                else:
                    self._tx_turn(sess, now)
        except Exception as e:
            if prof is not None:
                t0 = time.monotonic()
                self._session_error(sess, e)
                prof.account("overload-ladder", sess.key,
                             time.monotonic() - t0, 0)
            else:
                self._session_error(sess, e)
        if not sess.dead:
            self._update_mask(sess)

    def _read_turn(self, sess: EdgeSession, now: float) -> int:
        dec = sess.machine.dec
        if sess.rx_eof or dec.destroyed or not self._read_gate_open(sess):
            return 0
        nbytes, eof = recv_step(sess.pump, dec, sess.tap)
        if eof:
            sess.rx_eof = True
            if not dec.destroyed and not dec.finished:
                dec.end()
        if nbytes or eof:
            sess.tx_ready = True  # machine hooks may have queued reply
        return nbytes

    def _probe_subscriber(self, sess: EdgeSession) -> None:
        # the threaded run_subscriber's EOF/misroute probe, event-driven
        try:
            # bounded: the fd is O_NONBLOCK (set at admission; the
            # fan-out's dup shares the open file description)
            # datlint: allow-blocking-reachable(socket)
            probe = sess.conn.recv(4096)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            sess.rx_eof = True
            self._finish_session(sess)
            return
        if probe == b"":
            sess.rx_eof = True  # client went away: release the slot
        else:
            # a subscriber has nothing to say — inbound bytes mean a
            # SOURCE got routed here; fail LOUDLY (threaded contract)
            sess.not_source = True
        self._finish_session(sess)

    def _tx_turn(self, sess: EdgeSession, now: float) -> int:
        m = sess.machine
        if m is None or m.enc is None or sess.tx_done:
            return 0
        sess.tx_ready = False
        accepted, finished, blocked = send_step(sess.pump, m.enc)
        sess.tx_blocked = blocked
        if accepted or not blocked:
            sess.progress = now  # reply byte reached the kernel (or
            #   there was nothing pending): the stall clock resets
        if finished:
            sess.tx_done = True
            try:
                sess.conn.shutdown(socket.SHUT_WR)  # reply EOF
            except OSError:
                pass
        return accepted

    def _read_gate_open(self, sess: EdgeSession) -> bool:
        m = sess.machine
        if not m.dec.writable():
            return False
        if sess.kind == "hub" and m.hub_session is not None:
            # overload stage 2: the hub window, applied as a read gate
            return m.hub_session.window_room()
        return True

    def _session_error(self, sess: EdgeSession, e: BaseException) -> None:
        # transport/shed/protocol failure: destroy both directions (the
        # threaded legs' cascade) and let the teardown predicate finish
        m = sess.machine
        if sess.error is None:
            sess.error = e
        if m is not None:
            if m.dec is not None and not m.dec.destroyed:
                m.dec.destroy(e)
            if m.enc is not None and not m.enc.destroyed:
                m.enc.destroy(e)

    # -- the per-turn sweep --------------------------------------------------

    def _sweep(self, now: float,
               prof: Optional[LoopProfiler] = None) -> None:
        for sess in list(self._table.values()):
            if sess.dead:
                continue
            try:
                self._sweep_one(sess, now, prof)
            except Exception as e:
                if prof is not None:
                    t0 = time.monotonic()
                    self._session_error(sess, e)
                    prof.account("overload-ladder", sess.key,
                                 time.monotonic() - t0, 0)
                else:
                    self._session_error(sess, e)
            if not sess.dead:
                self._maybe_finish(sess)
            if not sess.dead:
                self._update_mask(sess)

    def _sweep_one(self, sess: EdgeSession, now: float,
                   prof: Optional[LoopProfiler] = None) -> None:
        if sess.kind == "subscriber":
            p = sess.fanout_peer
            if p.wait_done(timeout=0):
                sess.sub_done = True
                self._finish_session(sess)
            elif p.shed_reason is not None:
                self._finish_session(sess)
            return
        m = sess.machine
        hs = getattr(m, "hub_session", None)
        if hs is not None:
            if hs.shed_reason is not None and sess.error is None:
                # overload stage 3 surfacing: the hub shed this session
                # between submits — the threaded leg observed it on its
                # next wait; the loop observes it here
                raise SessionShed(hs.key, hs.shed_reason, 0)
            if hs.has_completions and not m.enc.destroyed \
                    and m.enc.writable():
                # reply backpressure gate: while the encoder sits above
                # its high-water mark, completions PARK in the hub —
                # parked bytes grow, the window gate closes reads, and
                # eventually the shed policy fires: the threaded leg's
                # flushed.wait ladder, event-driven
                if prof is not None:
                    t0 = time.monotonic()
                    polled = hs.poll()
                    prof.account("hub-drain", sess.key,
                                 time.monotonic() - t0, 0)
                else:
                    polled = hs.poll()
                if polled:
                    sess.tx_ready = True
            if (getattr(m, "rx_finalized", False) and hs.drained
                    and not m.enc.finalized and not m.enc.destroyed):
                # flush-before-finalize, the loop's half: every digest
                # for submitted work is encoded before the reply seals
                if prof is not None:
                    t0 = time.monotonic()
                    m.enc.finalize()
                    prof.account("hub-drain", sess.key,
                                 time.monotonic() - t0, 0)
                else:
                    m.enc.finalize()
                sess.tx_ready = True
        if sess.tx_ready and not sess.tx_blocked and not sess.tx_done:
            if prof is not None:
                t0 = time.monotonic()
                tx = self._tx_turn(sess, now)
                prof.account("tx", sess.key, time.monotonic() - t0, tx)
            else:
                self._tx_turn(sess, now)
        if (self._drain_timeout is not None and not sess.tx_done
                and m.enc is not None and not m.enc.destroyed
                and (sess.tx_blocked or sess.rx_eof)
                and now - sess.progress > self._drain_timeout):
            if prof is not None:
                t0 = time.monotonic()
                self._teardown_stalled(sess)
                prof.account("overload-ladder", sess.key,
                             time.monotonic() - t0, 0)
            else:
                self._teardown_stalled(sess)

    def _teardown_stalled(self, sess: EdgeSession) -> None:
        # the client stopped reading its reply: the threaded leg's
        # reply-drain teardown, same structured stall event
        m = sess.machine
        if _OBS.on:
            _M_STALLS.inc()
            _emit("sidecar.stall", kind="reply-drain",
                  seconds=self._drain_timeout, reply_bytes=m.enc.bytes)
        m.enc.destroy(TimeoutError(
            f"reply stream stalled for {self._drain_timeout}s"))
        try:
            sess.conn.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def _maybe_finish(self, sess: EdgeSession) -> None:
        if sess.kind == "subscriber":
            return  # finished from the sweep/probe paths directly
        m = sess.machine
        rx_over = sess.rx_eof or m.dec.destroyed
        tx_over = sess.tx_done or m.enc.destroyed
        if rx_over and tx_over:
            self._finish_session(sess)

    # -- teardown + records --------------------------------------------------

    def _unregister(self, sess: EdgeSession) -> None:
        if sess.mask:
            try:
                self._sel.unregister(sess.fd)
            except KeyError:
                pass
            sess.mask = 0

    def _finish_session(self, sess: EdgeSession) -> None:
        sess.dead = True
        self._unregister(sess)
        self._table.pop(sess.fd, None)
        try:
            if sess.kind == "subscriber":
                out = self._subscriber_record(sess)
            elif sess.kind == "hub":
                out = sess.machine.record(tx_done=sess.tx_done)
                if sess.is_source:
                    fanout = self._fanouts[sess.group]
                    if fanout.log.end > fanout.log.start:
                        fanout.seal()
                    else:
                        # nothing published: a probe connection, not
                        # the feed — give the slot back
                        self._src_claims[sess.group] = False
                if out.get("shed") is not None:
                    self._shed += 1
                if _OBS.on:
                    _M_SESSIONS.inc()
                    _emit("sidecar.session", **out)
            else:
                err = sess.error
                out = sess.machine.record(error=err)
                if _OBS.on:
                    _M_SESSIONS.inc()
                    _emit("sidecar.session", **out)
            print(f"sidecar: {sess.peer} {out}", file=sys.stderr,
                  flush=True)
        finally:
            try:
                sess.conn.close()
            except OSError:
                pass

    def _subscriber_record(self, sess: EdgeSession) -> dict:
        p = sess.fanout_peer
        stats = p.stats()
        p.close()
        if stats["shed"] is not None:
            self._shed += 1
        if sess.not_source:
            out = {"fanout_peer": sess.key, "ok": False,
                   "not_source": True,
                   "detail": "subscriber connections must not send "
                             "data; the broadcast source slot was "
                             "already claimed — reconnect to retry as "
                             "source"}
            _send_refusal(sess.conn, out)
            if _OBS.on:
                _emit("sidecar.session", **out)
            return out
        try:
            sess.conn.shutdown(socket.SHUT_WR)  # clean EOF
        except OSError:
            pass
        out = {"fanout_peer": sess.key, "sent_bytes": stats["sent_bytes"],
               "shed": stats["shed"],
               "ok": sess.sub_done and stats["shed"] is None}
        if _OBS.on:
            _M_SESSIONS.inc()
            _emit("sidecar.session", **out)
        return out

    # -- readiness mask ------------------------------------------------------

    def _update_mask(self, sess: EdgeSession) -> None:
        want = 0
        if not sess.dead:
            if sess.kind == "subscriber":
                want |= selectors.EVENT_READ  # EOF/misroute probe
            else:
                m = sess.machine
                if (not sess.rx_eof and not m.dec.destroyed
                        and self._read_gate_open(sess)):
                    want |= selectors.EVENT_READ
                if sess.tx_blocked and not sess.tx_done \
                        and not m.enc.destroyed:
                    want |= selectors.EVENT_WRITE
        if want == sess.mask:
            return
        if sess.mask == 0:
            self._sel.register(sess.fd, want, sess)
        elif want == 0:
            try:
                self._sel.unregister(sess.fd)
            except KeyError:
                pass
        else:
            self._sel.modify(sess.fd, want, sess)
        sess.mask = want

    # -- telemetry -----------------------------------------------------------

    def snapshot(self) -> dict:
        """The edge record ``--stats-fd`` / ``/snapshot`` lines carry:
        the session-table aggregate with per-QoS-class and per-kind
        breakdowns (lock-free reads; snapshot-grade consistency)."""
        by_class: dict = {}
        by_kind: dict = {}
        for sess in list(self._table.values()):
            by_class[sess.qos] = by_class.get(sess.qos, 0) + 1
            by_kind[sess.kind] = by_kind.get(sess.kind, 0) + 1
        return {
            "sessions": len(self._table),
            "served": self._served,
            "admitted": self._admitted,
            "rejected": self._rejected,
            "shed": self._shed,
            "by_class": by_class,
            "by_kind": by_kind,
            "pump_route": effective_pump_route(),
            "loop": self.profiler.state(),
        }

    def admission_state(self) -> dict:
        """Lock-free admission view for ``/healthz`` (the hub's
        contract, restated for the unified edge): plain attribute
        reads, at worst one update stale — a health probe never blocks
        behind the loop."""
        out = {"stage": "edge", "sessions": len(self._table),
               "served": self._served, "rejected": self._rejected,
               "shed": self._shed, "open": not self._closed}
        if self._hub is not None:
            hub_state = self._hub.admission_state()
            out["open"] = bool(out["open"] and hub_state["open"])
            out["hub"] = hub_state
        return out

    def _collect(self) -> dict:
        """Registry collector: per-QoS-class session gauges (bounded
        cardinality: the class set is the preset table's) plus the
        admission counters, labeled by loop and read straight off the
        same attributes :meth:`admission_state` reports — the fleet
        ``max_shed``/``max_rejected`` ceilings read the registry, so
        these must be authoritative with or without the obs gate
        (ISSUE 18 satellite: they used to be gate-dependent registered
        counters that under-reported as zero)."""
        loop = self.profiler.name
        gauges: dict = {"edge.sessions": float(len(self._table))}
        counts: dict = {}
        for sess in list(self._table.values()):
            counts[sess.qos] = counts.get(sess.qos, 0) + 1
        for qos in QOS_PRESETS:
            gauges[f"edge.sessions{{class={qos}}}"] = float(
                counts.get(qos, 0))
        counters = {
            f"edge.served{{loop={loop}}}": self._served,
            f"edge.admitted{{loop={loop}}}": self._admitted,
            f"edge.rejected{{loop={loop}}}": self._rejected,
            f"edge.shed{{loop={loop}}}": self._shed,
        }
        return {"counters": counters, "gauges": gauges}


def serve_edge(host: str, port: int, *, hub=None, fanouts=None,
               reconcile_replica=None, snapshot_source=None,
               replica_node=None, mode_of=None, qos_of=None,
               group_of=None, max_sessions: Optional[int] = None,
               ready_cb=None,
               drain_timeout: Optional[float] = DEFAULT_DRAIN_TIMEOUT,
               tick: float = EDGE_TICK,
               name: Optional[str] = None) -> None:
    """Bind + run one :class:`EdgeLoop` on the calling thread — the
    event-driven twin of :func:`~..sidecar.serve_tcp` (``max_sessions``
    bounds the loop for tests; ``ready_cb(port)`` fires once bound)."""
    loop = EdgeLoop(hub, fanouts=fanouts,
                    reconcile_replica=reconcile_replica,
                    snapshot_source=snapshot_source,
                    replica_node=replica_node, mode_of=mode_of,
                    qos_of=qos_of, group_of=group_of,
                    drain_timeout=drain_timeout,
                    max_sessions=max_sessions, tick=tick, name=name)
    loop.bind(host, port)
    loop.serve(ready_cb)
