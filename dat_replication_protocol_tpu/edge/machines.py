"""Per-session protocol machines for the event-driven edge.

Each machine is the THREADED sidecar leg with its threads removed: the
same encoder/decoder wiring, the same hub/fanout/driver calls, the same
structured record shapes — only the byte movement moved out (the loop
steps :func:`~..session.pump.recv_step` / ``send_step`` per selector
turn where the threaded legs ran blocking pumps).  The chaos parity
sweep (tests/test_edge_chaos.py) holds the two shapes byte-identical;
ROBUSTNESS.md restates the overload contract for this table.

Analyzer shape (ANALYSIS.md): these constructors are called by
``EdgeLoop._dispatch_loop`` as imported module-level functions, so the
blocking-reachability certifier walks them — every callback
registration below carries its audited ``allow-callback-escape``
marker, and nothing here blocks: the hooks only flip encoder/decoder
state or note flags the loop polls.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..obs.watermarks import WATERMARKS as _WATERMARKS
from ..sidecar import DIGEST_SUBSET_BLOB, DIGEST_SUBSET_CHANGE

__all__ = ["HubMachine", "ResponderMachine", "hub_machine",
           "reconcile_machine", "replica_machine", "snapshot_machine"]


class HubMachine:
    """State for one edge hub session (the ``run_session`` leg): the
    tpu decoder rides a ``nowait`` hub registration, digests route back
    through :meth:`HubSession.poll` on the loop thread, and the
    flush-before-finalize barrier is the LOOP's (``rx_finalized`` +
    ``HubSession.drained`` gate ``enc.finalize``)."""

    __slots__ = ("enc", "dec", "hub_session", "wm_link", "digests",
                 "rx_finalized", "shed_rejected")

    def __init__(self, enc, dec, hub_session, wm_link: str):
        self.enc = enc
        self.dec = dec
        self.hub_session = hub_session
        self.wm_link = wm_link
        self.digests = 0
        self.rx_finalized = False
        self.shed_rejected = False

    def record(self, tx_done: bool) -> dict:
        """The ``sidecar.session`` record — field-for-field the
        threaded ``run_session`` shape (``tx_done`` stands in for "the
        sender thread exited": the reply fully drained)."""
        enc, dec = self.enc, self.dec
        out = {
            "changes": dec.changes,
            "blobs": dec.blobs,
            "bytes": dec.bytes,
            "digests": self.digests,
            "ok": (dec.finished and not dec.destroyed
                   and not enc.destroyed and tx_done),
        }
        if self.hub_session is not None:
            out["session"] = self.hub_session.key
            out["shed"] = self.hub_session.shed_reason
            # release the hub slot LAST (the threaded leg's ordering):
            # queued work drops, in-flight completions discard
            self.hub_session.close()
        _WATERMARKS.untrack(self.wm_link)
        return out


def hub_machine(encode: Callable, decode: Callable, hub, session_key: str,
                weight: float = 1.0) -> HubMachine:
    """Build one edge hub session: ``encode()``/``decode()`` are the
    package factories (passed in so this module never imports the
    package root at call time), ``hub`` the shared
    :class:`~..hub.ReplicationHub`.  Raises :class:`~..hub.HubBusy`
    through — admission stage 1 is the HUB's decision, and the loop
    answers it with the threaded leg's exact rejection record."""
    hub_session = hub.register(session_key, weight, nowait=True)
    # the package factories themselves: constructors, not user hooks —
    # they allocate an Encoder/Decoder and return (no I/O, no waits)
    # datlint: allow-callback-escape
    enc = encode()  # reply stream: plain host encoder (digest payloads)
    # datlint: allow-callback-escape
    dec = decode(backend="tpu", pipeline=hub_session)
    m = HubMachine(enc, dec, hub_session, session_key)
    dec.watermark(session_key)

    def on_digest(kind: str, seq: int, digest: bytes) -> None:
        # the threaded leg's Change shape verbatim; no flushed.wait —
        # reply backpressure is the loop's poll gate (enc.writable()
        # False parks completions in the hub, parked bytes grow, the
        # window gate stops reads: the identical ladder, new mechanism)
        m.digests += 1
        enc.change({
            "key": f"{kind}-{seq}",
            "change": seq,
            "from": 0,
            "to": 1,
            "value": digest,
            "subset": DIGEST_SUBSET_CHANGE if kind == "change"
            else DIGEST_SUBSET_BLOB,
        })

    # digest hook runs on the LOOP thread (inside HubSession.poll):
    # enc.change only appends to the reply queue, never blocks
    # datlint: allow-callback-escape
    dec.on_digest(on_digest)

    def _note_finalized(done) -> None:
        # the decoder's flush-before-finalize flush is nowait: note the
        # request stream finalized and let the LOOP hold the barrier
        # (enc.finalize waits for HubSession.drained)
        m.rx_finalized = True
        done()

    dec.finalize(_note_finalized)
    # error hooks, not user code: destroy() flips state and wakes
    # watchers — never blocks the loop
    # datlint: allow-callback-escape
    dec.on_error(lambda _e: enc.destroy())
    # datlint: allow-callback-escape
    enc.on_error(lambda _e: None if dec.destroyed else dec.destroy())
    return m


class ResponderMachine:
    """State for one edge responder session (reconcile / replica /
    snapshot): wraps the driver machine's ``(enc, dec, finish)`` and
    renders the threaded leg's record shape on teardown."""

    __slots__ = ("enc", "dec", "_finish", "_shape", "peer")

    def __init__(self, enc, dec, finish, shape: Callable, peer: str):
        self.enc = enc
        self.dec = dec
        self._finish = finish
        self._shape = shape
        self.peer = peer

    def record(self, error: Optional[BaseException] = None) -> dict:
        """Finish the driver machine and render the session record —
        the threaded legs' ``try/except (ProtocolError, OSError)``
        collapse, with ``error`` standing in for a transport exception
        the loop already observed."""
        from ..wire.framing import ProtocolError

        if error is None:
            try:
                return self._shape(self._finish())
            except (ProtocolError, OSError) as e:
                error = e
        return self._shape(None, error)


def reconcile_machine(replica, peer: str) -> ResponderMachine:
    """The ``--reconcile`` leg (``run_reconcile_session``'s shape)."""
    from ..runtime.reconcile_driver import responder_machine

    enc, dec, finish = responder_machine(replica)

    def shape(stats, error=None) -> dict:
        if stats is None:
            return {"reconcile": True, "ok": False, "peer": peer,
                    "error": f"{type(error).__name__}: {error}"}
        return {"reconcile": True, "ok": stats["ok"],
                "symbols": stats["symbols"], "rounds": stats["rounds"],
                "records_sent": stats["records_sent"],
                "records_received": len(stats["received"])}

    return ResponderMachine(enc, dec, finish, shape, peer)


def replica_machine(node, peer: str) -> ResponderMachine:
    """The ``--replica`` gossip leg (``run_replica_session``'s shape):
    received records are absorbed into the LIVE node on completion."""
    from ..cluster.live import absorb_responder_stats
    from ..runtime.reconcile_driver import responder_machine

    enc, dec, finish = responder_machine(node.replica)

    def shape(stats, error=None) -> dict:
        if stats is None:
            return {"replica": node.key, "ok": False, "peer": peer,
                    "error": f"{type(error).__name__}: {error}"}
        stats = absorb_responder_stats(node, stats)
        return {"replica": node.key, "ok": stats["ok"],
                "symbols": stats["symbols"], "rounds": stats["rounds"],
                "records_sent": stats["records_sent"],
                "applied": stats["applied"]}

    return ResponderMachine(enc, dec, finish, shape, peer)


def snapshot_machine(source, peer: str,
                     link: Optional[str] = None) -> ResponderMachine:
    """The ``--snapshot`` bootstrap leg (``run_snapshot_session``'s
    shape), BEGIN already queued on the encoder."""
    from ..runtime.snapshot_driver import snapshot_responder_machine

    enc, dec, finish = snapshot_responder_machine(source, link=link)

    def shape(stats, error=None) -> dict:
        if stats is None:
            return {"snapshot": True, "ok": False, "peer": peer,
                    "error": f"{type(error).__name__}: {error}"}
        return {"snapshot": True, "ok": stats["ok"],
                "cold": stats["cold"], "chunks_sent": stats["chunks_sent"],
                "chunk_bytes_sent": stats["chunk_bytes_sent"],
                "symbols": stats["symbols"], "rounds": stats["rounds"]}

    return ResponderMachine(enc, dec, finish, shape, peer)
