"""The event-driven edge (ISSUE 17): ONE selector/epoll session table
serving hub sessions, broadcast subscribers, reconcile/snapshot
responders, and gossip exchanges from a single loop, with the staged
overload ladder (admission -> per-session windows -> heaviest-offender
shed) preserved verbatim.  See DESIGN.md "The event-driven edge"."""

from .loop import QOS_PRESETS, EdgeLoop, serve_edge

__all__ = ["EdgeLoop", "serve_edge", "QOS_PRESETS"]
