"""Sequence-parallel content-defined chunking over a device mesh.

The long-context discipline of this framework (SURVEY.md §5: blobs
stream in O(chunk) memory) scales across chips the same way sequence /
context parallelism scales attention: the byte stream is sharded into
contiguous spans, each chip scans its span locally, and the only
cross-chip traffic is a GROUP-wide (256-byte) **halo** row at each span
boundary, of which the last WINDOW=64 bytes are the real rolling-hash
context — a single ``ppermute`` neighbor exchange over ICI, the
ring-attention communication pattern reduced to its minimal case (the
gear hash forgets beyond WINDOW bytes, so one fixed-size halo replaces
ring attention's full KV rotation).

Layout: the caller tiles the stream exactly like :mod:`..ops.rabin` —
rows of ``[GROUP context | stride payload]`` — but the row axis is
sharded over the mesh's data axis.  Each shard builds its local rows
from its local payload slab plus the halo row received from its left
neighbor, then runs the same tiled gear scan every single-chip path
uses (zero-seeded per row; rows are independent by construction, which
is what makes the whole scan embarrassingly parallel after the halo).

This module is deliberately thin: the kernels and extraction live in
:mod:`..ops.rabin`; only the halo exchange and the shard_map plumbing
are mesh-specific.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ..utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from ..obs.device import jit_site as _jit_site
from ..ops.rabin import GROUP, _PREFIX_WORDS, gear_candidates_tiled
from ..ops.u64 import U32
from .mesh import DATA_AXIS, Mesh


@functools.lru_cache(maxsize=None)
def _scan_program(mesh: Mesh, avg_bits: int, use_pallas: bool):
    n_dev = mesh.devices.size

    def step(payload, pre_row):
        """``payload``: (T_local, sw) uint32 payload rows of this shard's
        contiguous span; ``pre_row``: (1, 64) uint32 — the stream-global
        seed row (zeros + WINDOW context), used by shard 0 only.
        """
        idx = jax.lax.axis_index(DATA_AXIS)
        # halo: my last row's context tail -> right neighbor
        tail = payload[-1:, -_PREFIX_WORDS:]
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        left_tail = jax.lax.ppermute(tail, DATA_AXIS, perm)
        first_ctx = jnp.where(idx == 0, pre_row, left_tail)
        ctx = jnp.concatenate(
            [first_ctx, payload[:-1, -_PREFIX_WORDS:]], axis=0
        )
        rows = jnp.concatenate([ctx, payload], axis=1)
        if use_pallas:
            from ..ops.rabin_pallas import gear_candidates_pallas

            return gear_candidates_pallas(rows, avg_bits)
        return gear_candidates_tiled(rows, avg_bits)

    return _jit_site(
        "parallel.cdc_mesh.scan",
        jax.jit(
            shard_map(
                step,
                mesh=mesh,
                in_specs=(P(DATA_AXIS), P()),
                out_specs=P(DATA_AXIS),
                check_vma=False,
            )
        ),
    )


def sharded_gear_scan(mesh: Mesh, payload_rows, prefix=None,
                      avg_bits: int = 13, use_pallas: bool | None = None):
    """Candidate bitmask of a sharded byte stream, one halo exchange.

    ``payload_rows``: (T, stride/4) uint32 — the stream's payload tiles
    (row t = bytes [t*stride, (t+1)*stride), zero-padded tail), with T
    divisible by the mesh size; shard over the row axis before or let
    jit move it.  ``prefix``: optional WINDOW bytes preceding the stream
    (16 uint32 words; None = zero seed).  Returns the (T, width/32)
    packed candidate bitmask, sharded like the rows; valid bit-words per
    row are ``[GROUP/32, GROUP/32 + stride/32)`` exactly as on one chip.

    The cross-chip traffic is ONE (1, 64)-word ppermute per scan —
    constant in stream length, the sequence-parallel ideal.
    """
    T, sw = payload_rows.shape
    if (sw * 4) % GROUP:
        raise ValueError(f"stride must be a multiple of {GROUP}")
    n = mesh.devices.size
    if T % n:
        raise ValueError(f"row count {T} not divisible by mesh size {n}")
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    pre = jnp.zeros((1, _PREFIX_WORDS), U32)
    if prefix is not None:
        ctx = jnp.asarray(prefix, dtype=U32).reshape(1, -1)
        pre = pre.at[:, -ctx.shape[1]:].set(ctx)
    fn = _scan_program(mesh, avg_bits, use_pallas)
    return fn(payload_rows, pre)
