"""Multi-chip scale-out: sharded digest + Merkle pipeline over a device mesh.

The reference's only transport is a Node stream pair and its only
"parallelism" is head-of-line blob serialization (reference:
encode.js:87-95); it has no distributed backend at all (SURVEY.md §2).
The TPU-native framework scales the data plane the XLA way instead:

* a 1-D ``jax.sharding.Mesh`` over the ``data`` axis shards the blob batch
  (and the Merkle leaf axis) across chips;
* per-chip work — batched BLAKE2b, local Merkle subtree — runs inside
  ``shard_map`` with zero communication;
* the only collectives are an ``all_gather`` of per-chip subtree roots
  (one 32-byte digest per chip, riding ICI) and a ``psum`` of byte
  counters — the whole cross-chip Merkle merge costs O(devices) bytes.

This module is also what ``__graft_entry__.dryrun_multichip`` compiles on a
virtual device mesh: it is the framework's "full step" — payload batch in,
sharded digests + global Merkle root + global counters out.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..obs.device import jit_site as _jit_site
from ..ops import merkle
from ..ops.blake2b import blake2b_packed
from ..ops.u64 import U32

from ..utils.jax_compat import shard_map

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D data mesh over the first ``n_devices`` local devices.

    Power-of-two device counts only: the cross-chip Merkle merge builds a
    binary top tree over per-chip roots.
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
    if n_devices & (n_devices - 1):
        raise ValueError(f"device count {n_devices} is not a power of two")
    return Mesh(np.asarray(devs[:n_devices]), (DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch / leaf) axis across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _merge_roots(root_hh, root_hl):
    """all_gather per-chip roots and finish the top tree, replicated.

    ``root_hh/hl``: (1, 4) local subtree root. Gathered to (n_dev, 4) on
    every chip (32 bytes per chip over ICI), then the log2(n_dev)-level top
    tree is built redundantly everywhere — cheaper than round-tripping a
    tiny tree through one chip.
    """
    g_hh = jax.lax.all_gather(root_hh[0], DATA_AXIS, axis=0)
    g_hl = jax.lax.all_gather(root_hl[0], DATA_AXIS, axis=0)
    return merkle.root(g_hh, g_hl)


def _check_shard(mesh: Mesh, B: int, what: str) -> None:
    n = mesh.devices.size
    per = B // n if n and B % n == 0 else None
    if per is None or per & (per - 1) or per == 0:
        raise ValueError(
            f"{what}: batch size {B} over {n} devices needs a power-of-two "
            f"per-chip shard (got {B}/{n}); pad the batch first "
            f"(:func:`pad_batch` does)"
        )


def pad_batch(mesh: Mesh, mh, ml, lengths):
    """Pad a packed batch so every chip gets a power-of-two shard.

    Padding items are zero-length payloads — valid BLAKE2b inputs whose
    digests land in the padded tail of the leaf axis.  Both replicas of
    a comparison must pad with the same policy (this one: smallest
    ``n_devices * 2**k >= B``) so their Merkle roots stay comparable;
    the caller slices per-item results with the returned original B.

    Returns ``(mh, ml, lengths, B)``.
    """
    from ..utils.num import next_pow2

    n = mesh.devices.size
    B = mh.shape[0]
    Bp = n * next_pow2(-(-B // n))
    if Bp != B:
        pad = ((0, Bp - B),)
        mh = jnp.pad(mh, pad + ((0, 0), (0, 0)))
        ml = jnp.pad(ml, pad + ((0, 0), (0, 0)))
        lengths = jnp.pad(lengths, (0, Bp - B))
    return mh, ml, lengths, B


@functools.lru_cache(maxsize=None)
def _digest_root_program(mesh: Mesh):
    """Jitted sharded digest step, cached per mesh.

    Built once per mesh so repeated per-batch calls hit jax's jit cache
    (a fresh closure per call would retrace and recompile every time).
    """

    def step(mh, ml, lengths):
        hh, hl = blake2b_packed(mh, ml, lengths)
        leaf_hh, leaf_hl = hh[:, :4], hl[:, :4]
        root_hh, root_hl = _merge_roots(*merkle.root(leaf_hh, leaf_hl))
        # exact byte counter without 64-bit lanes: sum the 16-bit halves
        # separately (each partial sum stays < 2**32 for any batch up to
        # 2**16 items) and recombine as hi*2**16 + lo on the host
        lengths = lengths.astype(U32)
        total_lo = jax.lax.psum(jnp.sum(lengths & U32(0xFFFF)), DATA_AXIS)
        total_hi = jax.lax.psum(jnp.sum(lengths >> U32(16)), DATA_AXIS)
        return leaf_hh, leaf_hl, root_hh, root_hl, total_hi, total_lo

    sharded = P(DATA_AXIS)
    rep = P()
    return _jit_site(
        "parallel.mesh.digest_root",
        jax.jit(
            shard_map(
                step,
                mesh=mesh,
                in_specs=(sharded, sharded, sharded),
                out_specs=(sharded, sharded, rep, rep, rep, rep),
                check_vma=False,
            )
        ),
    )


def digest_root_step(mesh: Mesh, mh, ml, lengths):
    """The sharded full step: padded payload batch in -> digests + root.

    Inputs follow the :func:`..ops.blake2b.blake2b_packed` layout —
    ``mh/ml`` (B, nblocks, 16) uint32 message words, ``lengths`` (B,) —
    with B divisible by the mesh size and a power-of-two per-chip shard
    (the local Merkle fold is a binary tree).  Per chip: hash the local
    shard, fold the local digests into a subtree root.  Cross-chip:
    gather the per-chip roots, finish the top tree, psum the byte
    counter.

    Returns ``(leaf_hh, leaf_hl, root_hh, root_hl, total_bytes)`` where the
    leaf digests stay sharded over the batch axis and the root/counter are
    replicated.  ``total_bytes`` is an exact Python int (recombined from
    16-bit partial sums, immune to uint32 wrap for batches up to 2**16
    items of any size).
    """
    _check_shard(mesh, mh.shape[0], "digest_root_step")
    fn = _digest_root_program(mesh)
    leaf_hh, leaf_hl, root_hh, root_hl, hi, lo = fn(mh, ml, lengths)
    total = (int(hi) << 16) + int(lo)
    return leaf_hh, leaf_hl, root_hh, root_hl, total


@functools.lru_cache(maxsize=None)
def _sharded_hash_program(mesh: Mesh):
    """Jitted hash-only sharded step, cached per mesh: the cross-session
    digest batch (ISSUE 8) needs no Merkle fold or collectives at all —
    every chip hashes its shard of the batch axis and the results stay
    sharded, so the whole program is communication-free."""

    def step(mh, ml, lengths):
        return blake2b_packed(mh, ml, lengths)

    sharded = P(DATA_AXIS)
    return _jit_site(
        "parallel.mesh.sharded_hash",
        jax.jit(
            shard_map(
                step,
                mesh=mesh,
                in_specs=(sharded, sharded, sharded),
                out_specs=(sharded, sharded),
                check_vma=False,
            )
        ),
    )


def sharded_hash_begin(mesh: Mesh, payloads, digest_size: int = 32):
    """Dispatch one cross-session payload batch sharded over the mesh;
    returns a zero-arg ``collect()`` closure (``.start_d2h`` attached) —
    the same async contract as :func:`..ops.blake2b.blake2b_batch_begin`,
    so the hub's shared :class:`~..backend.tpu_backend.DigestPipeline`
    can use either engine interchangeably.

    Items are bucketed by power-of-two block count (bounded compile
    count, same policy as the single-device engine); each bucket's batch
    axis is padded to ``n_devices * 2**k`` and uploaded with a batch-dim
    :class:`~jax.sharding.NamedSharding` so every chip receives only its
    shard over the interconnect and hashes it locally — the multiplexed
    sessions' combined digest work is what finally fills an 8-chip mesh
    (MULTICHIP_r05.json) that any single session's batch rarely could.
    """
    from ..utils.num import next_pow2

    from ..ops.blake2b import BLOCK_BYTES, digests_to_bytes, pack_payloads

    n = mesh.devices.size
    spec = batch_sharding(mesh)
    buckets: dict[int, list[int]] = {}
    for i, p in enumerate(payloads):
        nb = next_pow2(max(1, -(-len(p) // BLOCK_BYTES)))
        buckets.setdefault(nb, []).append(i)
    fn = _sharded_hash_program(mesh)
    handles = []
    for nb, idxs in buckets.items():
        batch = [payloads[i] for i in idxs]
        Bp = n * next_pow2(-(-len(batch) // n))
        batch += [b""] * (Bp - len(batch))
        mh, ml, lengths = pack_payloads(batch, nblocks=nb)
        mh_d = jax.device_put(mh, spec)
        ml_d = jax.device_put(ml, spec)
        len_d = jax.device_put(lengths, spec)
        hh, hl = fn(mh_d, ml_d, len_d)
        handles.append((idxs, hh[: len(idxs)], hl[: len(idxs)]))

    def start_d2h() -> None:
        for _, hh, hl in handles:
            for arr in (hh, hl):
                copy_async = getattr(arr, "copy_to_host_async", None)
                if copy_async is not None:
                    copy_async()

    def collect() -> list[bytes]:
        out: list[bytes | None] = [None] * len(payloads)
        for idxs, hh, hl in handles:
            for i, d in zip(idxs, digests_to_bytes(hh, hl, digest_size)):
                out[i] = d
        return out  # type: ignore[return-value]

    collect.start_d2h = start_d2h  # type: ignore[attr-defined]
    return collect


@functools.lru_cache(maxsize=None)
def _sharded_diff_program(mesh: Mesh):
    """Jitted sharded diff, cached per mesh (see _digest_root_program)."""

    def step(a_hh, a_hl, b_hh, b_hl):
        mask, (lra_hh, lra_hl), (lrb_hh, lrb_hl) = merkle.diff_root_guided(
            a_hh, a_hl, b_hh, b_hl
        )
        ra = _merge_roots(lra_hh, lra_hl)
        rb = _merge_roots(lrb_hh, lrb_hl)
        return mask, ra[0], ra[1], rb[0], rb[1]

    sharded = P(DATA_AXIS)
    rep = P()
    return _jit_site(
        "parallel.mesh.sharded_diff",
        jax.jit(
            shard_map(
                step,
                mesh=mesh,
                in_specs=(sharded, sharded, sharded, sharded),
                out_specs=(sharded, rep, rep, rep, rep),
                check_vma=False,
            )
        ),
    )


@functools.lru_cache(maxsize=None)
def _sharded_sketch_program(mesh: Mesh, log2_slots: int):
    """Jitted sharded sketch build, cached per (mesh, slot count)."""

    from ..ops.reconcile import sketch_table

    nslots = 1 << log2_slots

    def step(rec_hh, rec_hl, slots):
        # local partial table via the shared kernel, then: cells are
        # wrapping-u32 sums, so a psum over chips IS the cell combine —
        # order-independent, exact
        return jax.lax.psum(
            sketch_table(rec_hh, rec_hl, slots, nslots), DATA_AXIS
        )

    return _jit_site(
        "parallel.mesh.sharded_sketch",
        jax.jit(
            shard_map(
                step,
                mesh=mesh,
                in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
                out_specs=P(),
                check_vma=False,
            )
        ),
    )


def sharded_sketch(mesh: Mesh, rec_hh, rec_hl, slots, log2_slots: int):
    """Key-addressed reconciliation sketch built across the mesh.

    ``rec_hh/hl``: (B, 4) record digest word columns (the
    :func:`..batch.feed.hash_extents_device` layout), sharded over
    chips; ``slots``: (B,) cell indices (uint32/int32).  Each chip
    scatter-adds its shard into a local table; one ``psum`` of the
    (nslots, 8) table over ICI yields the replicated global sketch —
    byte-identical to the single-device build
    (:func:`..ops.reconcile._summarize`), because cells are wrapping
    uint32 sums (commutative, associative).

    The batch is zero-padded to the mesh size: a zero digest adds
    nothing to cell 0, so padding rows cannot perturb the sketch.
    """
    if not 0 < log2_slots <= 31:
        raise ValueError("log2_slots must be in [1, 31]")
    n = mesh.devices.size
    B = rec_hh.shape[0]
    if B % n:
        pad = ((0, n - B % n),)
        rec_hh = jnp.pad(rec_hh, pad + ((0, 0),))
        rec_hl = jnp.pad(rec_hl, pad + ((0, 0),))
        slots = jnp.pad(slots, (0, n - B % n))
    fn = _sharded_sketch_program(mesh, log2_slots)
    return fn(rec_hh, rec_hl, jnp.asarray(slots))


def sharded_diff(mesh: Mesh, a_hh, a_hl, b_hh, b_hl):
    """Tree-guided diff of two snapshots with leaves sharded over chips.

    Each chip diffs its local subtree pair (no communication needed for
    the leaf mask — a differing local leaf is decidable locally); the
    global roots are merged over ICI so callers get the replicated
    snapshot digests alongside the sharded mask.

    Returns ``(mask, a_root, b_root)`` with ``mask`` sharded like the
    leaves and each root a replicated ``((1,4),(1,4))`` hi/lo pair.
    """
    _check_shard(mesh, a_hh.shape[0], "sharded_diff")
    fn = _sharded_diff_program(mesh)
    mask, ra_hh, ra_hl, rb_hh, rb_hl = fn(a_hh, a_hl, b_hh, b_hl)
    return mask, (ra_hh, ra_hl), (rb_hh, rb_hl)
