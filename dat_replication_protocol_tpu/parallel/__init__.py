"""Multi-chip parallelism: mesh construction + sharded data-plane steps."""

from .mesh import (
    DATA_AXIS,
    batch_sharding,
    digest_root_step,
    make_mesh,
    replicated,
    sharded_diff,
)

__all__ = [
    "DATA_AXIS",
    "batch_sharding",
    "digest_root_step",
    "make_mesh",
    "replicated",
    "sharded_diff",
]
