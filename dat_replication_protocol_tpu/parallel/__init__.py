"""Multi-chip parallelism: mesh construction + sharded data-plane steps."""

from .cdc_mesh import sharded_gear_scan
from .mesh import (
    DATA_AXIS,
    batch_sharding,
    digest_root_step,
    make_mesh,
    pad_batch,
    replicated,
    sharded_diff,
    sharded_sketch,
)

__all__ = [
    "DATA_AXIS",
    "batch_sharding",
    "digest_root_step",
    "make_mesh",
    "pad_batch",
    "replicated",
    "sharded_diff",
    "sharded_sketch",
    "sharded_gear_scan",
]
