"""Multi-session replication hub: one shared device engine, many sessions.

ISSUE 8 / ROADMAP item 1: the sidecar used to build one
:class:`~..backend.tpu_backend.DigestPipeline` per connection — per-peer
ownership of the device path, where one misbehaving peer is
indistinguishable from engine failure.  The hub inverts that: sessions
register with a key, their digest work is coalesced *across sessions*
into single XLA dispatches on ONE shared pipeline, and completions route
back by session key.  Per-session state (queues, windows, stats) lives
at the edge; the shared hot path carries none of it — the shape the
SmartNIC reliable-replication work (PAPERS.md) argues for.

See ROBUSTNESS.md §"Overload behavior" for the admission / shedding /
isolation contract and OBSERVABILITY.md for the ``hub.*`` catalog.
"""

from .engine import (
    HubBusy,
    HubError,
    HubSession,
    ReplicationHub,
    SessionShed,
)

__all__ = [
    "ReplicationHub",
    "HubSession",
    "HubBusy",
    "HubError",
    "SessionShed",
]
