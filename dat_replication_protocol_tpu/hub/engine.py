"""The shared replication engine: admission, QoS, fault isolation.

One :class:`ReplicationHub` owns one
:class:`~..backend.tpu_backend.DigestPipeline` (or a mesh-sharded hash
engine over it) and multiplexes every registered session onto it:

* **Edge state, shared engine.**  Each session keeps its own queues,
  window accounting, and stats in a :class:`_SessionState`; the device
  path sees only coalesced batches.  Completions carry the session's
  state in their tag and route back without any shared-path lookup.
* **Admission control.**  ``register()`` rejects with a structured
  :class:`HubBusy` (never unbounded queue growth) once the session
  count or the global parked-bytes budget is exhausted.
* **Per-session backpressure windows.**  ``submit()`` blocks the
  *calling session's* thread while that session's parked work (queued +
  in-pipeline + undelivered completions) exceeds its window — a slow
  consumer stalls only its own window; the dispatcher never runs user
  callbacks, so it can never be parked by one.
* **Weighted-fair batching.**  Each cross-session batch is composed
  round-robin with per-session quotas proportional to ``weight``, then
  greedily filled (work-conserving): a heavy session cannot monopolize
  a dispatch, an idle one costs nothing.
* **Load shedding.**  When global parked bytes exceed the budget — or
  the recent ``hub.dispatch.latency`` p99 crosses ``latency_shed_s``
  while parked bytes are past half budget — the heaviest offender (max
  per-session parked bytes) is shed: its queued work is dropped, its
  in-flight completions are discarded on arrival, its waiters wake
  into :class:`SessionShed`, and a ``hub.shed`` event names it.  The
  other sessions never notice.

Locking discipline (enforced by the ``hub-isolation`` datlint rule):
**no lock is ever held across a device dispatch** — batches are
composed under ``self._lock``, dispatched outside it — and per-session
state is only ever reached through the session-keyed accessor
(:meth:`ReplicationHub._session_state`) or a handle captured from it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..obs.events import DeferredEmitQueue as _DeferredEmitQueue
from ..obs.events import emit as _emit
from ..obs.metrics import (
    OBS as _OBS,
    REGISTRY as _REGISTRY,
    counter as _counter,
    gauge as _gauge,
    histogram as _histogram,
)

__all__ = [
    "ReplicationHub",
    "HubSession",
    "HubBusy",
    "HubError",
    "SessionShed",
]

# hub telemetry (OBSERVABILITY.md `hub.*` catalog)
_M_SESSIONS = _gauge("hub.sessions")
_M_PARKED = _gauge("hub.parked.bytes")
_M_ADMITTED = _counter("hub.admitted")
_M_REJECTED = _counter("hub.rejected")
_M_SHED = _counter("hub.shed")
_M_BATCHES = _counter("hub.dispatch.batches")
_M_ITEMS = _counter("hub.dispatch.items")
_M_BYTES = _counter("hub.dispatch.bytes")
_M_DROPPED = _counter("hub.completions.dropped")
_H_LATENCY = _histogram("hub.dispatch.latency")

# dispatcher/waiter guarded-fallback period: wakeups are event-driven
# (condition notifies); the bound only matters if one is ever lost
_WAKE_FALLBACK = 0.05


class HubBusy(RuntimeError):
    """Structured admission rejection: the hub is at capacity.

    Carries the decision's inputs so a caller (the sidecar's accept
    loop, a future RPC layer) can answer with a meaningful retry hint
    instead of letting queues grow: ``sessions``/``max_sessions`` and
    ``parked_bytes``/``parked_budget`` at rejection time.
    """

    def __init__(self, message: str, *, sessions: int, max_sessions: int,
                 parked_bytes: int, parked_budget: int):
        super().__init__(message)
        self.sessions = sessions
        self.max_sessions = max_sessions
        self.parked_bytes = parked_bytes
        self.parked_budget = parked_budget


class SessionShed(RuntimeError):
    """This session was shed by the hub's overload policy.  ``reason``
    is the policy arm (``parked-budget`` / ``dispatch-latency``);
    ``parked_bytes`` is what the session held when shed."""

    def __init__(self, key: str, reason: str, parked_bytes: int):
        super().__init__(
            f"session {key!r} shed by hub ({reason}, "
            f"{parked_bytes} parked bytes)")
        self.key = key
        self.reason = reason
        self.parked_bytes = parked_bytes


class HubError(RuntimeError):
    """The shared engine itself failed (dispatcher died / hub closed);
    every session observes the same structured error."""


class _SessionState:
    """Per-session edge state.  Mutated ONLY under the hub lock, reached
    ONLY through the hub's session-keyed accessor or a handle captured
    from it (the hub-isolation contract)."""

    __slots__ = (
        "key", "weight", "cv", "q", "q_items", "q_bytes",
        "out_items", "out_bytes", "comp", "comp_items", "comp_bytes",
        "submitted", "submitted_bytes", "delivered", "delivered_bytes",
        "dispatches", "shed", "shed_parked", "gone", "flush_goal",
        "nowait",
    )

    def __init__(self, key: str, weight: float, lock: threading.Lock,
                 nowait: bool = False):
        self.key = key
        self.weight = weight
        self.nowait = nowait
        self.cv = threading.Condition(lock)
        self.q: deque = deque()   # (kind, item, cb, tag, nbytes)
        self.q_items = 0
        self.q_bytes = 0
        self.out_items = 0        # in the shared pipeline
        self.out_bytes = 0
        self.comp: deque = deque()  # (cb, tag, digest, nbytes)
        self.comp_items = 0
        self.comp_bytes = 0
        self.submitted = 0
        self.submitted_bytes = 0
        self.delivered = 0
        self.delivered_bytes = 0
        self.dispatches = 0       # batches this session contributed to
        self.shed: Optional[str] = None
        self.shed_parked = 0      # parked bytes at shed time (the verdict)
        self.gone = False
        self.flush_goal: Optional[int] = None

    @property
    def parked_bytes(self) -> int:
        return self.q_bytes + self.out_bytes + self.comp_bytes

    @property
    def parked_items(self) -> int:
        return self.q_items + self.out_items + self.comp_items


class HubSession:
    """A session's handle on the hub — and a drop-in ``pipeline`` for
    :class:`~..backend.tpu_backend.TpuDecoder` / ``TpuEncoder``: the
    same ``submit`` / ``submit_stream`` / ``flush`` surface as
    :class:`~..backend.tpu_backend.DigestPipeline`, with the work
    coalesced across sessions behind it.  Completions are delivered on
    the session's OWN thread (inside ``submit``/``flush``), in submit
    order, so a callback that blocks — the sidecar's reply backpressure
    — parks only this session."""

    def __init__(self, hub: "ReplicationHub", state: _SessionState):
        self._hub = hub
        self._state = state

    @property
    def key(self) -> str:
        return self._state.key

    @property
    def shed_reason(self) -> Optional[str]:
        return self._state.shed

    def submit(self, payload, on_digest: Callable, tag=None) -> None:
        self._hub._submit_run(
            self._state,
            (("payload", payload, on_digest, tag, len(payload)),),
            len(payload))

    def submit_many(self, payloads, on_digest: Callable,
                    tag_base: int = 0) -> None:
        """Bulk submit: one window check and ONE lock round-trip for a
        whole run (tags ``tag_base..tag_base+n-1``) — the bulk decoder
        feeds thousands of change payloads per wire chunk, and a lock
        acquisition per payload was ~3x the whole submit cost.  The
        window is enforced at run granularity (a run is admitted whole
        once there is any room — same policy as the oversized single
        item)."""
        entries = []
        total = 0
        for k, p in enumerate(payloads):
            n = len(p)
            entries.append(("payload", p, on_digest, tag_base + k, n))
            total += n
        if entries:
            self._hub._submit_run(self._state, entries, total)

    def submit_stream(self, stream, on_digest: Callable, tag=None) -> None:
        nbytes = int(getattr(stream, "length", 0))
        self._hub._submit_run(
            self._state, (("stream", stream, on_digest, tag, nbytes),),
            nbytes)

    def flush(self) -> None:
        self._hub._flush_session(self._state)

    # -- nowait surface (the event-driven edge, ISSUE 17) -------------------

    def poll(self) -> int:
        """One non-blocking completion turn: pop whatever digests have
        routed back and deliver them (in submit order, on THIS thread —
        the edge loop's), never waiting.  Returns the count delivered;
        raises :class:`SessionShed` / :class:`HubError` exactly like
        ``submit`` when the hub's overload policy hit this session."""
        return self._hub._poll_session(self._state)

    @property
    def has_completions(self) -> bool:
        """Lock-free: are completions waiting for :meth:`poll`?  A
        plain GIL-atomic attribute read (at worst one update stale) so
        the edge loop can skip the hub lock for idle sessions."""
        return self._state.comp_items > 0

    def window_room(self) -> bool:
        """Lock-free mirror of the submit window check — the SAME
        predicate ``_submit_run_inner`` gates on, read without the
        lock.  The edge loop gates READS on this: a full window stops
        the session's socket from being drained, so the kernel buffer
        (then the peer's TCP window) absorbs the overload — the
        identical ladder, enforced by backpressure instead of a
        blocked thread."""
        st, hub = self._state, self._hub
        return st.parked_items < hub.window_items and (
            st.parked_bytes < hub.window_bytes or st.parked_items == 0)

    @property
    def drained(self) -> bool:
        """Lock-free: nothing parked (queued, in-pipeline, or
        undelivered) — the edge's flush-before-finalize barrier
        predicate."""
        return self._state.parked_items == 0

    def close(self) -> None:
        """Unregister; queued work is dropped, in-flight completions are
        discarded on arrival.  Idempotent."""
        self._hub._unregister(self._state)

    def stats(self) -> dict:
        return self._hub._session_stats(self._state)

    def __enter__(self) -> "HubSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _mesh_hash_begin_factory(n_devices: Optional[int] = None):
    """The cross-session mesh engine: shard the coalesced hash batch
    over the device mesh with batch-dim ``NamedSharding`` (SNIPPETS.md
    idiom; 8 devices in MULTICHIP_r05.json).  Returns None — fall back
    to the pipeline's default engine — on host-routed or single-device
    backends."""
    from ..utils.routing import prefer_host

    if prefer_host("DAT_DEVICE_HASH"):
        return None
    try:
        import jax  # noqa: PLC0415

        from ..parallel import mesh as pmesh  # noqa: PLC0415

        n_avail = len(jax.devices())
        n = n_devices if n_devices is not None else n_avail
        while n & (n - 1):
            n -= 1  # largest power of two the mesh layer accepts
        if n < 2:
            return None
        m = pmesh.make_mesh(n)
        if _OBS.on:
            from ..obs.device import note_engine as _note_engine

            _note_engine("digest.hash", "mesh-sharded", devices=n)
        return lambda payloads: pmesh.sharded_hash_begin(m, payloads)
    except Exception:
        return None


class ReplicationHub:
    """See module docstring.  One hub per process/daemon; sessions come
    and go via :meth:`register` / :meth:`HubSession.close`.

    ``mesh="auto"`` shards cross-session batches over every local device
    (falling back to the pipeline's default engine on host/single-chip
    backends); an int pins the device count; ``None`` (default) keeps
    the single-device engine.
    """

    def __init__(
        self,
        pipeline=None,
        *,
        hash_batch: Optional[Callable] = None,
        mesh=None,
        max_sessions: int = 1024,
        parked_budget: int = 256 << 20,
        window_items: int = 4096,
        window_bytes: int = 32 << 20,
        max_batch: int = 1024,
        max_batch_bytes: int = 1 << 30,
        linger_s: float = 0.002,
        latency_shed_s: Optional[float] = None,
    ):
        if pipeline is None:
            from ..backend.tpu_backend import DigestPipeline

            hash_begin = None
            if mesh is not None and hash_batch is None:
                hash_begin = _mesh_hash_begin_factory(
                    None if mesh == "auto" else int(mesh))
            # the hub owns batching: the inner pipeline's item cap is
            # effectively ours (we dispatch explicitly per composed
            # batch), its inflight bound stays the readback pipeline
            pipeline = DigestPipeline(
                hash_batch=hash_batch, hash_begin=hash_begin,
                max_batch=max_batch, max_batch_bytes=max_batch_bytes)
        self._pipeline = pipeline
        self.max_sessions = int(max_sessions)
        self.parked_budget = int(parked_budget)
        self.window_items = int(window_items)
        self.window_bytes = int(window_bytes)
        self._max_batch = int(max_batch)
        self._max_batch_bytes = int(max_batch_bytes)
        self._linger_s = float(linger_s)
        self.latency_shed_s = latency_shed_s

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._sessions: dict[str, _SessionState] = {}
        # shed events queued under the lock, emitted by
        # _drain_shed_events once the holder releases (the event sink
        # can block; blocking under the hub lock convoys every session)
        self._shed_events = _DeferredEmitQueue("hub.shed", self._lock)
        # the concurrency pass enforces these (ANALYSIS.md):
        # datlint: guarded-by(self._lock): self._sessions
        self._next_id = 0
        self._rr = 0
        self._q_items = 0            # global queued (not yet in pipeline)
        self._q_bytes = 0
        self._parked_bytes = 0       # global queued+outstanding+undelivered
        self._oldest_ts: Optional[float] = None
        self._routed: list = []     # dispatcher-thread-local (see _route)
        # recent dispatch-turn latencies (dispatcher-thread-local ring):
        # the latency shed arm triggers on this window's p99, not on one
        # isolated slow turn (a first-bucket compile must not shed)
        self._lat_ring: deque = deque(maxlen=64)
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._failed: Optional[BaseException] = None
        # bind the collector ONCE: close() unregisters owner-checked by
        # identity, so an old hub draining past a successor's startup
        # cannot delete the successor's live collector
        self._collector_fn = self._collect
        _REGISTRY.register_collector("hub", self._collector_fn)

    # -- registration / admission -------------------------------------------

    def register(self, key: Optional[str] = None,
                 weight: float = 1.0, *,
                 nowait: bool = False) -> HubSession:
        """Admit one session.  Raises :class:`HubBusy` (structured) when
        the session count or parked-bytes budget is exhausted — bounded
        state instead of queue growth is the overload contract.

        ``nowait=True`` registers an event-driven session (the edge
        loop's, ISSUE 17): ``submit``/``flush`` never block and never
        deliver inline — completions are drained by
        :meth:`HubSession.poll` and the window is enforced by the
        caller gating reads on :meth:`HubSession.window_room` (the
        same predicate, applied as backpressure instead of a blocked
        thread).  Admission and shed policy are identical."""
        if weight <= 0:
            raise ValueError("session weight must be > 0")
        if key is not None and (not key or any(
                c in key for c in "{},=\"\n\r")):
            # keys ride telemetry label sets ({session=KEY}) and JSON
            # stats breakdowns: structural characters would corrupt the
            # exposition for EVERY session, so refuse at the boundary
            raise ValueError(
                f"session key {key!r} must be non-empty and contain "
                'none of {},=" or newlines')
        busy = None
        with self._lock:
            self._check_alive_locked()
            if key is None:
                key = f"s{self._next_id}"
            self._next_id += 1
            if key in self._sessions:
                raise ValueError(f"session key {key!r} already registered")
            # admission closes at HALF the shed budget: new sessions
            # are refused while the hub still has headroom to serve the
            # ones it already admitted — rejecting a newcomer is cheap,
            # shedding a live session is not, so the former guards the
            # latter (ROBUSTNESS.md overload behavior)
            if len(self._sessions) >= self.max_sessions or \
                    self._parked_bytes >= self.parked_budget // 2:
                # built under the lock (consistent counts), emitted and
                # raised OUTSIDE it: the event sink can block, and
                # blocking under the hub lock convoys every session
                # (blocking-under-lock contract, ANALYSIS.md)
                busy = HubBusy(
                    f"hub at capacity ({len(self._sessions)}/"
                    f"{self.max_sessions} sessions, "
                    f"{self._parked_bytes}/{self.parked_budget} parked "
                    f"bytes)",
                    sessions=len(self._sessions),
                    max_sessions=self.max_sessions,
                    parked_bytes=self._parked_bytes,
                    parked_budget=self.parked_budget,
                )
            else:
                st = _SessionState(key, float(weight), self._lock,
                                   nowait=nowait)
                self._sessions[key] = st
                sessions_now = len(self._sessions)
                if _OBS.on:
                    # gauge set under the lock: a concurrent
                    # unregister's set would otherwise interleave out
                    # of order and latch a stale session count
                    _M_SESSIONS.set(sessions_now)
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._dispatch_loop, name="hub-dispatch",
                        daemon=True)
                    self._thread.start()
        if busy is not None:
            if _OBS.on:
                _M_REJECTED.inc()
                _emit("hub.reject", key=key, sessions=busy.sessions,
                      max_sessions=self.max_sessions,
                      parked_bytes=busy.parked_bytes,
                      parked_budget=self.parked_budget)
            raise busy
        if _OBS.on:
            _M_ADMITTED.inc()
            _emit("hub.admit", key=key, weight=float(weight),
                  sessions=sessions_now)
        return HubSession(self, st)

    def _session_state(self, key: str) -> _SessionState:
        """THE session-keyed accessor (hub-isolation contract): every
        key-addressed reach into per-session state goes through here."""
        return self._sessions[key]

    def _unregister(self, st: _SessionState) -> None:
        done_stats = None
        with self._lock:
            if st.gone:
                return
            st.gone = True
            # queued + undelivered completions leave the parked set now;
            # in-pipeline bytes leave as their completions route back
            self._q_items -= st.q_items
            self._q_bytes -= st.q_bytes
            self._parked_bytes -= st.q_bytes + st.comp_bytes
            st.q.clear()
            st.q_items = st.q_bytes = 0
            st.comp.clear()
            st.comp_items = st.comp_bytes = 0
            if self._sessions.get(st.key) is st:
                del self._sessions[st.key]
            st.cv.notify_all()
            self._work.notify_all()
            if _OBS.on:
                _M_SESSIONS.set(len(self._sessions))
                _M_PARKED.set(self._parked_bytes)
                done_stats = self._session_stats_locked(st)
        if done_stats is not None:
            _emit("hub.session.done", key=st.key, shed=st.shed,
                  **{k: v for k, v in done_stats.items()
                     if k in ("submitted", "delivered", "submitted_bytes",
                              "dispatches")})

    # -- session-side paths (run on the session's own thread) ---------------

    def _submit_run(self, st: _SessionState, entries, run_bytes: int) -> None:
        """Admit a run of entries (possibly one) into the session's
        queue — ONE lock round-trip per run, window-checked at run
        granularity.  Blocks (delivering ready completions meanwhile)
        while the session's window is full."""
        n = len(entries)
        try:
            self._submit_run_inner(st, entries, run_bytes, n)
        finally:
            # emit any shed this submit triggered (possibly our own
            # SessionShed unwinding) with the lock released
            self._drain_shed_events()

    def _submit_run_inner(self, st: _SessionState, entries,
                          run_bytes: int, n: int) -> None:
        if st.nowait:
            # event-driven session: never wait, never deliver inline.
            # The caller (the edge loop) gated reads on window_room()
            # before decoding these entries, so overshoot is bounded by
            # one read turn's decode product — the same run-granularity
            # admission the blocking path applies to an oversized run.
            # Accounting, shed policy, and liveness checks are the
            # blocking path's verbatim.
            with self._lock:
                self._check_session_alive_locked(st)
                st.q.extend(entries)
                st.q_items += n
                st.q_bytes += run_bytes
                st.submitted += n
                st.submitted_bytes += run_bytes
                was_idle = self._q_items == 0
                self._q_items += n
                self._q_bytes += run_bytes
                self._parked_bytes += run_bytes
                if self._oldest_ts is None:
                    self._oldest_ts = time.monotonic()
                if _OBS.on:
                    _M_PARKED.set(self._parked_bytes)
                self._maybe_shed_locked()
                self._check_session_alive_locked(st)
                if was_idle or self._q_items >= self._max_batch:
                    self._work.notify_all()
            return
        while True:
            with self._lock:
                self._check_session_alive_locked(st)
                ready = self._pop_completions_locked(st)
                if not ready:
                    # window: parked work (queued + in-pipeline +
                    # undelivered) bounds this session; a run (or an
                    # oversized single item) is admitted whole once
                    # there is any room, rather than deadlocking an
                    # empty window
                    if st.parked_items < self.window_items and (
                            st.parked_bytes < self.window_bytes
                            or st.parked_items == 0):
                        st.q.extend(entries)
                        st.q_items += n
                        st.q_bytes += run_bytes
                        st.submitted += n
                        st.submitted_bytes += run_bytes
                        was_idle = self._q_items == 0
                        self._q_items += n
                        self._q_bytes += run_bytes
                        self._parked_bytes += run_bytes
                        if self._oldest_ts is None:
                            self._oldest_ts = time.monotonic()
                        if _OBS.on:
                            _M_PARKED.set(self._parked_bytes)
                        self._maybe_shed_locked()
                        self._check_session_alive_locked(st)
                        # wake the dispatcher only on the transitions it
                        # acts on (first work after idle, batch full) —
                        # a notify per submit was pure GIL churn
                        if was_idle or self._q_items >= self._max_batch:
                            self._work.notify_all()
                        return
                    st.cv.wait(_WAKE_FALLBACK)
                    continue
            self._deliver(st, ready)

    def _flush_session(self, st: _SessionState) -> None:
        """Block until every item this session submitted *before this
        call* has had its digest delivered — the per-session
        flush-before-finalize barrier on the shared engine."""
        with self._lock:
            self._check_session_alive_locked(st)
            st.flush_goal = st.submitted
            self._work.notify_all()
        if st.nowait:
            # event-driven session: the flush BARRIER moves to the
            # caller (the edge defers enc.finalize until the session is
            # drained); setting the goal above is what matters — the
            # dispatcher now drains the readback pipeline promptly so
            # completions land without waiting for the next batch
            return
        try:
            while True:
                with self._lock:
                    ready = self._pop_completions_locked(st)
                    if not ready:
                        self._check_session_alive_locked(st)
                        if st.delivered >= (st.flush_goal or 0):
                            return
                        st.cv.wait(_WAKE_FALLBACK)
                        continue
                self._deliver(st, ready)
        finally:
            with self._lock:
                st.flush_goal = None

    def _poll_session(self, st: _SessionState) -> int:
        """One non-blocking completion turn for a nowait session (see
        :meth:`HubSession.poll`): pop under the lock, deliver outside
        it — the thread delivering is the edge loop's, so a slow
        digest consumer parks only its own session's turn."""
        with self._lock:
            ready = self._pop_completions_locked(st)
            if not ready:
                # surface shed/closure HERE (the poll path is the nowait
                # session's only recurring hub call when the wire is idle)
                self._check_session_alive_locked(st)
                return 0
        self._deliver(st, ready)
        return len(ready)

    def _pop_completions_locked(self, st: _SessionState) -> list:
        if not st.comp:
            return []
        ready = list(st.comp)
        st.comp.clear()
        st.comp_items = 0
        freed = st.comp_bytes
        st.comp_bytes = 0
        # delivery accounting happens at pop time, in bulk: the popping
        # thread IS the delivering thread (the session's own), so the
        # counter can never run ahead of an observable delivery by more
        # than that thread's own call stack
        st.delivered += len(ready)
        st.delivered_bytes += freed
        self._parked_bytes -= freed
        if _OBS.on:
            _M_PARKED.set(self._parked_bytes)
        return ready

    @staticmethod
    def _deliver(st: _SessionState, ready: list) -> None:
        # user callbacks run here, on the session's own thread, with no
        # hub lock held: a blocking consumer parks only itself
        for cb, tag, digest, nbytes in ready:
            if tag is None:
                cb(digest)
            else:
                cb(tag, digest)

    def _check_alive_locked(self) -> None:
        if self._failed is not None:
            raise HubError(
                f"hub dispatcher failed: {self._failed!r}") from self._failed
        if self._closed:
            raise HubError("hub is closed")

    def _check_session_alive_locked(self, st: _SessionState) -> None:
        self._check_alive_locked()
        if st.shed is not None:
            raise SessionShed(st.key, st.shed, st.shed_parked)
        if st.gone:
            raise HubError(f"session {st.key!r} is closed")

    # -- the dispatcher (the only thread that touches the pipeline) ---------

    def _dispatch_loop(self) -> None:
        try:
            while True:
                with self._lock:
                    while not (self._closed or self._failed
                               or self._turn_ready_locked()):
                        self._work.wait(self._wait_s_locked())
                    if self._closed or self._failed:
                        return
                    batch = self._compose_locked()
                    engine_flush = self._flush_needed_locked()
                t0 = time.monotonic()
                turn_bytes = 0
                for entry_st, kind, item, cb, tag, nbytes in batch:
                    routed = (entry_st, cb, tag, nbytes)
                    if kind == "payload":
                        self._pipeline.submit(item, self._route, routed)
                    else:
                        self._pipeline.submit_stream(item, self._route,
                                                     routed)
                    turn_bytes += nbytes
                if batch:
                    self._pipeline.dispatch()
                with self._lock:
                    drain_idle = (self._q_items == 0
                                  and self._pipeline.inflight > 0)
                if engine_flush or drain_idle:
                    # queue is dry (or a session is at its finalize
                    # barrier): drain the readback pipeline so windows
                    # free and flush barriers release promptly
                    self._pipeline.flush()
                self._distribute_routed()
                if batch or engine_flush:
                    latency = time.monotonic() - t0
                    self._lat_ring.append(latency)
                    if _OBS.on:
                        _H_LATENCY.observe(latency)
                        if batch:
                            _M_BATCHES.inc()
                            _M_ITEMS.inc(len(batch))
                            _M_BYTES.inc(turn_bytes)
                    ordered = sorted(self._lat_ring)
                    p99 = ordered[min(len(ordered) - 1,
                                      int(0.99 * len(ordered)))]
                    with self._lock:
                        self._maybe_shed_locked(latency_p99=p99)
                self._drain_shed_events()  # per-turn catch-all
        except BaseException as exc:  # noqa: BLE001 — fanned out below
            # emit BEFORE taking the lock: the event sink can block,
            # and the waiters notified below contend on this lock
            _emit("hub.error", error=f"{type(exc).__name__}: {exc}")
            with self._lock:
                self._failed = exc
                for key in list(self._sessions):
                    self._session_state(key).cv.notify_all()
                self._work.notify_all()

    def _turn_ready_locked(self) -> bool:
        if self._flush_needed_locked():
            return True
        if self._q_items == 0:
            return self._pipeline.inflight > 0
        if self._q_items >= self._max_batch or \
                self._q_bytes >= self._max_batch_bytes:
            return True
        return (self._oldest_ts is not None
                and time.monotonic() - self._oldest_ts >= self._linger_s)

    def _wait_s_locked(self) -> float:
        if self._oldest_ts is not None:
            remaining = self._linger_s - (time.monotonic() - self._oldest_ts)
            if remaining > 0:
                return min(_WAKE_FALLBACK, remaining)
        return _WAKE_FALLBACK

    def _flush_needed_locked(self) -> bool:
        for st in self._sessions.values():
            # a shed session's goal can never be met (its queue was
            # dropped); its own thread is about to observe SessionShed
            # and clear the goal — don't spin the engine on it
            if st.flush_goal is not None and st.shed is None and \
                    st.delivered + st.comp_items < st.flush_goal:
                return True
        return False

    def _compose_locked(self) -> list:
        """Weighted-fair cross-session batch: one quota pass
        proportional to session weight, then a greedy work-conserving
        fill.  Moves accounting queued -> outstanding; the caller
        dispatches OUTSIDE the lock."""
        order = [st for st in self._sessions.values()
                 if st.q_items and st.shed is None]
        if not order:
            return []
        start = self._rr % len(order)
        order = order[start:] + order[:start]
        self._rr += 1
        total_w = sum(st.weight for st in order)
        items_left = self._max_batch
        bytes_left = self._max_batch_bytes
        batch: list = []

        def take(st: _SessionState, limit: int) -> int:
            nonlocal items_left, bytes_left
            n = 0
            while n < limit and items_left and st.q:
                nbytes = st.q[0][4]
                if st.q[0][0] == "payload" and nbytes > bytes_left \
                        and batch:
                    break  # oversized item waits for its own batch
                kind, item, cb, tag, nbytes = st.q.popleft()
                st.q_items -= 1
                st.q_bytes -= nbytes
                st.out_items += 1
                st.out_bytes += nbytes
                self._q_items -= 1
                self._q_bytes -= nbytes
                batch.append((st, kind, item, cb, tag, nbytes))
                items_left -= 1
                if kind == "payload":
                    bytes_left -= nbytes
                n += 1
            return n

        for st in order:  # quota pass: weight-proportional shares
            quota = max(1, int(self._max_batch * st.weight / total_w))
            if take(st, quota):
                st.dispatches += 1
        for st in order:  # greedy fill: unused budget is not wasted
            if items_left <= 0 or bytes_left <= 0:
                break
            take(st, items_left)
        self._oldest_ts = time.monotonic() if self._q_items else None
        return batch

    def _route(self, routed, digest: bytes) -> None:
        """Pipeline completion -> the dispatcher-local buffer.  ONLY the
        dispatcher thread runs pipeline calls, so this append needs no
        lock; :meth:`_distribute_routed` moves the buffer into the
        per-session completion queues in one locked pass per turn —
        one lock round-trip for a whole batch instead of one per item."""
        self._routed.append((routed, digest))

    def _distribute_routed(self) -> None:
        routed, self._routed = self._routed, []
        if not routed:
            return
        dropped = 0
        with self._lock:
            touched = set()
            for (st, cb, tag, nbytes), digest in routed:
                st.out_items -= 1
                st.out_bytes -= nbytes
                if st.gone or st.shed is not None:
                    # the session is no longer listening: its bytes
                    # leave the parked set here (queued/comp already did)
                    self._parked_bytes -= nbytes
                    dropped += 1
                else:
                    st.comp.append((cb, tag, digest, nbytes))
                    st.comp_items += 1
                    st.comp_bytes += nbytes
                touched.add(st)
            for st in touched:
                st.cv.notify_all()
            if dropped and _OBS.on:
                _M_DROPPED.inc(dropped)
                _M_PARKED.set(self._parked_bytes)

    # -- overload policy ----------------------------------------------------

    def _maybe_shed_locked(self,
                           latency_p99: Optional[float] = None) -> None:
        over_budget = self._parked_bytes > self.parked_budget
        slow = (latency_p99 is not None
                and self.latency_shed_s is not None
                and latency_p99 > self.latency_shed_s
                and self._parked_bytes > self.parked_budget // 2)
        if not (over_budget or slow):
            return
        reason = "parked-budget" if over_budget else "dispatch-latency"
        live = [st for st in self._sessions.values() if st.shed is None]
        if not live:
            return
        victim = max(live, key=lambda st: st.parked_bytes)
        self._shed_locked(victim, reason)

    def _shed_locked(self, st: _SessionState, reason: str) -> None:
        held = st.parked_bytes
        st.shed = reason
        st.shed_parked = held
        # queued + undelivered leave the parked set now; in-pipeline
        # bytes leave as their (discarded) completions route back
        self._q_items -= st.q_items
        self._q_bytes -= st.q_bytes
        self._parked_bytes -= st.q_bytes + st.comp_bytes
        st.q.clear()
        st.q_items = st.q_bytes = 0
        st.comp.clear()
        st.comp_items = st.comp_bytes = 0
        st.cv.notify_all()
        if _OBS.on:
            _M_SHED.inc()
            _M_PARKED.set(self._parked_bytes)
        # the EVENT is deferred: queued here (fields captured while
        # consistent), emitted by _drain_shed_events after release
        self._shed_events.queue_locked(
            key=st.key, reason=reason, parked_bytes=held,
            sessions=len(self._sessions))

    def _drain_shed_events(self) -> None:
        """Emit queued shed events with the hub lock RELEASED.  Called
        by the submit path and once per dispatcher turn."""
        self._shed_events.flush()

    # -- snapshots / lifecycle ----------------------------------------------

    def _session_stats_locked(self, st: _SessionState) -> dict:
        return {
            "parked_bytes": st.parked_bytes,
            "submitted": st.submitted,
            "submitted_bytes": st.submitted_bytes,
            "delivered": st.delivered,
            "dispatches": st.dispatches,
            "shed": st.shed,
        }

    def _session_stats(self, st: _SessionState) -> dict:
        with self._lock:
            return self._session_stats_locked(st)

    def sessions_snapshot(self) -> dict:
        """{key: per-session stats} for every live session — the
        ``sessions`` breakdown the sidecar's ``--stats-fd`` lines carry
        in hub mode (and the chaos oracle cross-checks)."""
        with self._lock:
            return {key: self._session_stats_locked(self._session_state(key))
                    for key in self._sessions}

    def snapshot(self) -> dict:
        # the pump route resolves OUTSIDE the lock (env read + cached
        # library check, but blocking-under-lock stays trivially clean)
        from ..session.pump import effective_pump_route

        pump_route = effective_pump_route()
        with self._lock:
            return {
                "sessions": len(self._sessions),
                "parked_bytes": self._parked_bytes,
                "queued_items": self._q_items,
                # which byte mover feeds the sessions multiplexed here
                # (ISSUE 14): hub aggregate scaling is only legible next
                # to the wire route that produced it
                "pump_route": pump_route,
                "failed": (None if self._failed is None
                           else f"{type(self._failed).__name__}: "
                                f"{self._failed}"),
            }

    def admission_state(self) -> dict:
        """Lock-free admission view for ``/healthz`` (ISSUE 11): plain
        attribute reads only — GIL-atomic, at worst one update stale,
        by design.  A health probe must never block behind the hub
        lock: a wedged dispatcher holding it would turn the liveness
        check itself into a hang, inverting its purpose.  The datlint
        healthz check keeps the handler side of this contract honest;
        this method is the hub's matching half."""
        sessions = len(self._sessions)
        parked = self._parked_bytes
        return {
            "open": (not self._closed and self._failed is None
                     and sessions < self.max_sessions
                     and parked < self.parked_budget // 2),
            "sessions": sessions,
            "max_sessions": self.max_sessions,
            "parked_bytes": parked,
            "parked_budget": self.parked_budget,
            "failed": self._failed is not None,
        }

    def _collect(self) -> dict:
        """Registry snapshot collector: labeled per-session entries for
        sessions currently alive (bounded cardinality by construction —
        dead sessions simply stop appearing)."""
        counters: dict = {}
        gauges: dict = {}
        with self._lock:
            gauges["hub.sessions"] = float(len(self._sessions))
            for key in self._sessions:
                st = self._session_state(key)
                label = f"{{session={key}}}"
                gauges["hub.session.parked_bytes" + label] = \
                    float(st.parked_bytes)
                counters["hub.session.submitted" + label] = st.submitted
                counters["hub.session.delivered" + label] = st.delivered
                counters["hub.session.dispatches" + label] = st.dispatches
        return {"counters": counters, "gauges": gauges}

    def close(self) -> None:
        """Stop the dispatcher and release the collector.  Sessions
        still registered observe :class:`HubError` on their next call;
        callers should drain/close sessions first."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for key in list(self._sessions):
                self._session_state(key).cv.notify_all()
            self._work.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
        _REGISTRY.unregister_collector("hub", self._collector_fn)

    def __enter__(self) -> "ReplicationHub":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
