"""Streaming rateless reconciliation driver (ISSUE 10, ROADMAP item 2).

Two long-lived replicas that diverged during a partition converge by
exchanging O(diff) wire bytes: the *initiator* streams coded-symbol
batches (:mod:`..ops.rateless`) over ``TYPE_RECONCILE`` frames until
the *responder*'s peeling decoder completes, then both sides exchange
exactly the differing records over the existing ``ChangeBatch`` bulk
frames.  No table exchange, no tree walk, no prior estimate of the
diff size.

Layering:

* :class:`RatelessReplica` — one replica's reconciliation state over a
  change log (columnar decode, canonical per-record digests, the
  digest -> row index).
* :class:`ResponderState` — the transport-free protocol core: feed it
  decoded :class:`~..wire.reconcile_codec.ReconcileMsg` messages, it
  returns reply payloads and accumulates the decoded diff.  The chaos
  suite drives THIS against the fault injector; the live drivers wrap
  it.
* :func:`reconcile_local` — both sides in one process with exact wire
  metering (every message round-trips the real codec); the bench's A/B
  harness and the property suite's workhorse.
* :func:`run_initiator` / :func:`run_responder` — the live duplex
  drivers over blocking byte pairs (the :mod:`..session.transport`
  contract), composing with PR 2's resume machinery: both directions
  are ordinary wire sessions, so checkpoints, wire journals, and
  ``run_resumable`` apply unchanged — a reconnect mid-symbol-stream
  resumes the stream instead of restarting it (the decoder object and
  its accumulated symbols survive the transport).
* The sidecar serves :func:`run_responder` under ``--reconcile`` (the
  mode IS the out-of-band capability advertisement; WIRE.md).

Failure contract (the chaos arm's oracle): a reconcile session either
completes with the exact symmetric difference or raises ONE structured
:class:`~..wire.framing.ProtocolError` — a torn/flipped/truncated
symbol stream can never deliver a wrong diff (wrong-element recovery
needs a 64-bit checksum collision; everything structural is validated
at decode).
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs.events import emit as _emit
from ..obs.metrics import OBS as _OBS, counter as _counter, gauge as _gauge
from ..ops import rateless
from ..session.decoder import Decoder
from ..session.encoder import Encoder
from ..session.transport import recv_over, send_over
from ..utils.trace import span
from ..wire import reconcile_codec as rc
from ..wire.framing import CAP_CHANGE_BATCH, CAP_RECONCILE, ProtocolError, \
    frame_wire_len

__all__ = ["RatelessReplica", "ResponderState", "reconcile_local",
           "run_initiator", "run_responder", "responder_machine",
           "DEFAULT_BATCH0"]

# first symbol batch; each round doubles (the classic rateless
# schedule: total streamed <= 2x the decode point, log2(k) rounds)
DEFAULT_BATCH0 = 128

# decode-failure bound, in symbols per element of the two sets: a
# healthy decode needs ~1.35-2.2x the DIFF, which is <= n_a + n_b, so
# overshooting this cap means corruption, not bad luck
DEFAULT_OVERHEAD_CAP = 4.0

# absolute responder-side symbol budget, independent of the remote
# peer's CLAIMED set size (the overhead cap scales with BEGIN's
# n_elements, which is unverifiable — without this bound a byzantine
# initiator claiming 2**50 elements could stream symbols forever and
# grow the responder's cell/cursor state without limit; the three-stage
# overload doctrine of the hub/fanout modes, restated for anti-entropy:
# past the budget the session fails STRUCTURED, never grows).  4M
# symbols = ~176 MiB of remote cells, enough to bootstrap an empty
# replica against ~2M records; raise per-deployment via max_symbols=.
DEFAULT_MAX_SYMBOLS = 4 << 20

_M_ROUNDS = _counter("reconcile.rounds")
_M_RECORDS = _counter("reconcile.records")
# fleet-plane convergence watermarks (ISSUE 11): the aggregator reads
# these to track anti-entropy progress — symbols streamed so far (the
# wire cost cursor) and the decoded symmetric-difference size (0 means
# the replicas proved identical; >0 names how far apart they were when
# the decode landed)
_G_SYMBOLS = _gauge("reconcile.symbols.seen")
_G_DIFF = _gauge("reconcile.decoded.diff")


def _hash_extents(buf: np.ndarray, offs: np.ndarray,
                  lens: np.ndarray) -> np.ndarray:
    from . import native

    return native.hash_many_fallback(buf, offs, lens)


def _select_rows(cols, rows: np.ndarray):
    """Arbitrary-row-subset view of decoded columns (shared buffer)."""
    from . import replay

    rows = np.ascontiguousarray(rows, dtype=np.int64)
    return replay.ChangeColumns(
        buf=cols.buf,
        change=np.ascontiguousarray(cols.change[rows]),
        from_=np.ascontiguousarray(cols.from_[rows]),
        to=np.ascontiguousarray(cols.to[rows]),
        key_off=np.ascontiguousarray(cols.key_off[rows]),
        key_len=np.ascontiguousarray(cols.key_len[rows]),
        sub_off=np.ascontiguousarray(cols.sub_off[rows]),
        sub_len=np.ascontiguousarray(cols.sub_len[rows]),
        val_off=np.ascontiguousarray(cols.val_off[rows]),
        val_len=np.ascontiguousarray(cols.val_len[rows]),
    )


class RatelessReplica:
    """One replica's reconciliation state over a change log.

    ``source`` is decoded columns (:class:`~.replay.ChangeColumns`),
    raw change-log wire bytes (``bytes`` / uint8 array — per-record
    and/or batch frames), or a list of Change records/dicts.  Elements
    are the canonical per-record payload digests (framing-independent,
    the digest-pipeline contract), deduplicated — reconciliation is
    over the SET of record states.
    """

    def __init__(self, source):
        from . import replay

        if isinstance(source, replay.ChangeColumns):
            cols = source
        elif isinstance(source, (bytes, bytearray, memoryview, np.ndarray)):
            cols, _ = replay.replay_log(
                np.frombuffer(bytes(source), np.uint8)
                if not isinstance(source, np.ndarray) else source)
        else:
            wire = replay.encode_change_log(list(source))
            cols, _ = replay.replay_log(np.frombuffer(wire, np.uint8))
        self.cols = cols
        with span("reconcile.digest"):
            buf, offs, lens = replay.canonical_change_extents(cols)
            digests = np.ascontiguousarray(_hash_extents(buf, offs, lens))
        # dedupe + the sorted-first-word lookup (digest -> row, no dict
        # of n Python objects) share ONE argsort on the common path —
        # all first words distinct, which real digests are overwhelming-
        # ly; colliding/duplicate runs take the exact slow path
        k0 = digests.view("<u8")[:, 0]
        order = np.argsort(k0, kind="stable").astype(np.int64)
        sk = k0[order]
        if len(sk) == 0 or not (sk[1:] == sk[:-1]).any():
            self.digests = digests
            self._digest_rows = np.arange(len(digests), dtype=np.int64)
            self._order = order
            self._sorted_k0 = sk
        else:
            self.digests, self._digest_rows = \
                rateless.dedupe_digests(digests)
            uk = self.digests.view("<u8")[:, 0]
            self._order = np.argsort(uk, kind="stable").astype(np.int64)
            self._sorted_k0 = uk[self._order]

    @property
    def n(self) -> int:
        return len(self.digests)

    def coded_symbols(self, engine: str = "auto") -> rateless.CodedSymbols:
        return rateless.CodedSymbols(self.digests, engine=engine)

    def peel_decoder(self, engine: str = "auto") -> rateless.PeelDecoder:
        return rateless.PeelDecoder(self.digests, engine=engine,
                                    assume_unique=True)

    def rows_for_digests(self, digests: np.ndarray) -> np.ndarray:
        """Log rows for digest queries; -1 where the digest is unknown
        (the reconcile protocol treats that as corruption — a decoded
        element the supposed owner does not hold)."""
        q = np.ascontiguousarray(digests, dtype=np.uint8)
        if q.ndim != 2 or q.shape[1] != rateless.DIGEST_BYTES:
            raise ValueError("digest queries must be (k, 32) u8")
        out = np.full(len(q), -1, dtype=np.int64)
        if not len(q) or not self.n:
            return out
        qk = q.view("<u8")[:, 0]
        pos = np.searchsorted(self._sorted_k0, qk)
        ok = pos < len(self._sorted_k0)
        ok[ok] &= self._sorted_k0[pos[ok]] == qk[ok]
        cand = np.nonzero(ok)[0]
        uni = self._order[pos[cand]]
        exact = (self.digests[uni] == q[cand]).all(axis=1)
        out[cand[exact]] = self._digest_rows[uni[exact]]
        # first-word match but row mismatch: a collision run — resolve
        # against every member of the run (astronomically rare)
        for qi in cand[~exact].tolist():
            at = pos[qi]
            while at < len(self._sorted_k0) \
                    and self._sorted_k0[at] == qk[qi]:
                u = self._order[at]
                if (self.digests[u] == q[qi]).all():
                    out[qi] = self._digest_rows[u]
                    break
                at += 1
        return out

    def columns_for_rows(self, rows: np.ndarray):
        return _select_rows(self.cols, rows)

    def records_for_rows(self, rows: np.ndarray) -> list:
        return [self.cols.row(int(i)) for i in rows]


class ResponderState:
    """Transport-free responder core: one reconcile session's decode
    state.  :meth:`handle` consumes a decoded message and returns reply
    payloads (reconcile-codec bytes); record frames from the remote are
    fed through :meth:`note_remote_record`.  :meth:`result` is the
    failure-contract choke point: the exact diff, or ONE structured
    ProtocolError."""

    def __init__(self, replica: RatelessReplica, engine: str = "auto",
                 overhead_cap: float = DEFAULT_OVERHEAD_CAP,
                 max_symbols: int = DEFAULT_MAX_SYMBOLS):
        self.replica = replica
        self.peeler = replica.peel_decoder(engine)
        self.overhead_cap = overhead_cap
        self.max_symbols = max_symbols
        self.begun = False
        self.n_remote: int | None = None
        self.decoded = None  # (digests, signs) on completion
        self.failed: ProtocolError | None = None
        self.remote_records: list = []
        self.rounds = 0

    # -- protocol ------------------------------------------------------------

    def _fail(self, message: str) -> list[bytes]:
        self.failed = ProtocolError(message, offset=self.peeler.symbols_seen)
        if _OBS.on:
            _emit("reconcile.fail", symbols=self.peeler.symbols_seen,
                  message=message)
        return [rc.encode_fail(self.peeler.symbols_seen, message)]

    def _symbol_cap(self) -> int:
        n_remote = self.n_remote if self.n_remote is not None else 0
        claim_cap = int(self.overhead_cap
                        * max(n_remote + self.replica.n, 64)) + 256
        # the absolute budget WINS over the claim-scaled cap: the claim
        # is the remote's word, the budget is this process's memory
        return min(claim_cap, self.max_symbols)

    def handle(self, msg: rc.ReconcileMsg) -> list[bytes]:
        if self.failed is not None:
            return []
        if msg.kind == rc.RC_BEGIN:
            if self.begun:
                return self._fail("duplicate reconcile begin")
            self.begun = True
            self.n_remote = msg.n
            return []
        if msg.kind == rc.RC_SYMBOLS:
            if not self.begun:
                return self._fail("reconcile symbols before begin")
            if self.decoded is not None:
                return []  # late batch after completion: ignorable
            try:
                self.peeler.add_symbols(msg.start, msg.cells)
            except ValueError as e:
                return self._fail(str(e))
            self.rounds += 1
            if _OBS.on:
                _M_ROUNDS.inc()
                _G_SYMBOLS.set(self.peeler.symbols_seen)
            out = self.peeler.try_decode()
            if out is not None:
                self.decoded = out
                digests, signs = out
                if _OBS.on:
                    _G_DIFF.set(len(digests))
                    _emit("reconcile.decoded", diff=len(digests),
                          symbols=self.peeler.symbols_seen,
                          rounds=self.rounds)
                # sanity: every remote-only element must be unknown to
                # us, every local-only element known — a violation is a
                # decode gone wrong (checksum-collision grade), caught
                # here rather than shipped
                rows = self.replica.rows_for_digests(digests)
                if ((signs == 1) & (rows >= 0)).any() \
                        or ((signs == -1) & (rows < 0)).any():
                    return self._fail(
                        "reconcile decode produced inconsistent elements")
                return [rc.encode_done(self.peeler.symbols_seen,
                                       digests[signs == 1])]
            if self.peeler.symbols_seen > self._symbol_cap():
                return self._fail(
                    f"no decode after {self.peeler.symbols_seen} symbols "
                    f"(sets of {self.n_remote}+{self.replica.n})")
            return [rc.encode_more(self.peeler.symbols_seen)]
        # DONE/MORE/FAIL are initiator-bound; receiving one here is a
        # misrouted peer
        return self._fail(
            f"unexpected reconcile message {msg.kind_name!r} at responder")

    # -- record exchange ------------------------------------------------------

    def note_remote_record(self, change) -> None:
        self.remote_records.append(change)
        if _OBS.on:
            _M_RECORDS.inc()

    def local_only_rows(self) -> np.ndarray:
        """Rows of THIS replica's log the remote is missing (decoded
        sign −1), to be sent over ChangeBatch frames."""
        if self.decoded is None:
            return np.empty(0, np.int64)
        digests, signs = self.decoded
        return self.replica.rows_for_digests(digests[signs == -1])

    # -- outcome --------------------------------------------------------------

    def result(self) -> tuple[np.ndarray, np.ndarray]:
        """The decoded diff ``(digests, signs)``; raises the session's
        ONE structured ProtocolError when the stream failed or ended
        before decode completed."""
        if self.failed is not None:
            raise self.failed
        if self.decoded is None:
            raise ProtocolError(
                "reconcile stream ended before decode completed",
                offset=self.peeler.symbols_seen)
        return self.decoded


def _batch_wire_len(cols) -> int:
    """Exact ChangeBatch wire bytes for a column subset (metering)."""
    from . import replay

    return len(replay.encode_batch_frames(cols)) if len(cols) else 0


def reconcile_local(replica_a: RatelessReplica, replica_b: RatelessReplica,
                    batch0: int = DEFAULT_BATCH0, engine: str = "auto",
                    overhead_cap: float = DEFAULT_OVERHEAD_CAP) -> dict:
    """Run the full protocol between two in-memory replicas with exact
    wire metering — every message round-trips the real payload codec
    and is billed at its framed wire length, record exchange included.

    Returns ``{"symbols", "rounds", "wire_a2b", "wire_b2a",
    "wire_bytes", "a_rows", "b_rows", "a_cols", "b_cols"}`` where
    ``a_rows`` are A-log rows B was missing (shipped A->B... A->B is
    the symbol direction; records travel both ways) and ``a_cols`` /
    ``b_cols`` the exchanged column subsets (apply = replay them)."""
    state = ResponderState(replica_b, engine=engine,
                           overhead_cap=overhead_cap)
    syms = replica_a.coded_symbols(engine)
    wire = {"a2b": 0, "b2a": 0}

    def a2b(payload: bytes) -> list[bytes]:
        wire["a2b"] += frame_wire_len(len(payload))
        replies = state.handle(rc.decode_reconcile(payload))
        for r in replies:
            wire["b2a"] += frame_wire_len(len(r))
        return replies

    replies = a2b(rc.encode_begin(replica_a.n))
    sent = 0
    m = 0
    rounds = 0
    final = None
    while final is None:
        if replies and (final := rc.decode_reconcile(replies[-1])).kind \
                in (rc.RC_DONE, rc.RC_FAIL):
            break
        final = None
        m = batch0 if m == 0 else m * 2
        cells = syms.extend(m)[sent:]
        payload = rc.encode_symbols(sent, cells)
        sent = m
        rounds += 1
        replies = a2b(payload)
    if final.kind == rc.RC_FAIL:
        state.result()  # raises the structured error
    # record exchange: A ships the rows B requested, B ships its
    # local-only rows — both metered at real ChangeBatch wire size
    a_rows = replica_a.rows_for_digests(final.digests)
    if (a_rows < 0).any():
        raise ProtocolError(
            "peer requested records this replica does not hold",
            offset=wire["a2b"])
    b_rows = state.local_only_rows()
    a_cols = replica_a.columns_for_rows(a_rows)
    b_cols = replica_b.columns_for_rows(b_rows)
    wire["a2b"] += _batch_wire_len(a_cols)
    wire["b2a"] += _batch_wire_len(b_cols)
    return {
        "symbols": sent,
        "rounds": rounds,
        "wire_a2b": wire["a2b"],
        "wire_b2a": wire["b2a"],
        "wire_bytes": wire["a2b"] + wire["b2a"],
        "a_rows": a_rows,
        "b_rows": b_rows,
        "a_cols": a_cols,
        "b_cols": b_cols,
    }


# -- live duplex drivers -----------------------------------------------------


def run_initiator(replica: RatelessReplica, read_bytes, write_bytes,
                  close_write=None, batch0: int = DEFAULT_BATCH0,
                  engine: str = "auto", journal=None,
                  chunk_size: int = 64 * 1024) -> dict:
    """Drive one reconciliation as the initiator over a duplex byte
    pair (the :mod:`..session.transport` contract: blocking
    ``read_bytes(n)`` / ``write_bytes(data)``).

    Streams BEGIN + doubling symbol batches, answers the responder's
    MORE/DONE/FAIL, ships the requested records as ChangeBatch frames,
    and collects the responder's differing records.  ``journal`` (a
    :class:`~..session.resume.WireJournal`) tees the outgoing wire for
    resume-after-reconnect.  Returns
    ``{"ok", "symbols", "rounds", "records_sent", "received"}``;
    raises the session's structured ProtocolError on failure."""
    enc = Encoder(peer_caps=CAP_RECONCILE | CAP_CHANGE_BATCH)
    if journal is not None:
        enc.attach_journal(journal)
    dec = Decoder()
    syms = replica.coded_symbols(engine)
    received: list = []
    stats = {"sent": 0, "rounds": 0, "records_sent": 0}
    err: list[ProtocolError] = []

    def send_next() -> None:
        m = batch0 if stats["sent"] == 0 else stats["sent"] * 2
        cells = syms.extend(m)[stats["sent"]:]
        enc.reconcile_frame(rc.encode_symbols(stats["sent"], cells))
        stats["sent"] = m
        stats["rounds"] += 1
        if _OBS.on:
            _M_ROUNDS.inc()
            _G_SYMBOLS.set(m)

    def on_reconcile(msg, done) -> None:
        if msg.kind == rc.RC_MORE:
            send_next()
        elif msg.kind == rc.RC_DONE:
            rows = replica.rows_for_digests(msg.digests)
            if (rows < 0).any():
                e = ProtocolError(
                    "peer requested records this replica does not hold",
                    frame=dec._frames_delivered(), offset=dec.bytes)
                err.append(e)
                done()
                dec.destroy(e)
                return
            recs = replica.records_for_rows(rows)
            if recs:
                enc.change_many(recs)
            stats["records_sent"] = len(recs)
            if _OBS.on and recs:
                _M_RECORDS.inc(len(recs))
            enc.finalize()
        elif msg.kind == rc.RC_FAIL:
            e = ProtocolError(
                f"reconcile failed at peer: {msg.reason}",
                frame=dec._frames_delivered(), offset=dec.bytes)
            err.append(e)
            done()
            dec.destroy(e)
            return
        else:
            e = ProtocolError(
                f"unexpected reconcile message {msg.kind_name!r} at "
                "initiator", frame=dec._frames_delivered(),
                offset=dec.bytes)
            err.append(e)
            done()
            dec.destroy(e)
            return
        done()

    dec.reconcile(on_reconcile)
    dec.change(lambda c, done_cb: (received.append(c), done_cb()))
    # error hook, not user code: destroy() only flips state and wakes
    # watchers — it never blocks the registering loop
    # datlint: allow-callback-escape
    dec.on_error(lambda _e: None if enc.destroyed else enc.destroy())

    enc.reconcile_frame(rc.encode_begin(replica.n))
    send_next()

    sender = threading.Thread(
        target=lambda: send_over(enc, write_bytes, close_write,
                                 chunk_size=chunk_size),
        name="reconcile-init-send", daemon=True)
    sender.start()
    try:
        recv_over(dec, read_bytes, chunk_size=chunk_size)
    except Exception as e:
        if not dec.destroyed:
            dec.destroy(e)
        if not enc.destroyed:
            enc.destroy(e)
        raise
    finally:
        if dec.destroyed and not enc.destroyed:
            enc.destroy()
        sender.join(timeout=30)
    if err:
        raise err[0]
    if not dec.finished or enc.destroyed:
        raise ProtocolError("reconcile session ended unexpectedly",
                            offset=dec.bytes)
    return {"ok": True, "symbols": stats["sent"],
            "rounds": stats["rounds"],
            "records_sent": stats["records_sent"], "received": received}


def responder_machine(replica: RatelessReplica, *, engine: str = "auto",
                      overhead_cap: float = DEFAULT_OVERHEAD_CAP,
                      max_symbols: int = DEFAULT_MAX_SYMBOLS) -> tuple:
    """The responder's protocol machine, factored off its threads
    (ISSUE 17): the encoder/decoder pair with the full MORE/DONE/FAIL
    + record exchange wired, returned as ``(enc, dec, finish)``.  The
    caller owns byte movement — the threaded :func:`run_responder`
    pumps them with a sender thread + blocking recv loop, the
    event-driven edge steps them from ONE selector turn with the same
    frames on the wire.  ``finish()`` is idempotent: tears down a
    half-open encoder, raises the session's structured ProtocolError
    if the decode failed, and returns the stats record both callers
    emit (``{"ok", "symbols", "rounds", "records_sent",
    "received"}``)."""
    enc = Encoder(peer_caps=CAP_RECONCILE | CAP_CHANGE_BATCH)
    dec = Decoder()
    state = ResponderState(replica, engine=engine,
                           overhead_cap=overhead_cap,
                           max_symbols=max_symbols)
    sent_records = {"n": 0}

    def on_reconcile(msg, done) -> None:
        replies = state.handle(msg)
        done_now = state.decoded is not None and replies
        for r in replies:
            enc.reconcile_frame(r)
        if done_now:
            rows = state.local_only_rows()
            recs = replica.records_for_rows(rows)
            if recs:
                enc.change_many(recs)
            sent_records["n"] = len(recs)
            if _OBS.on and recs:
                _M_RECORDS.inc(len(recs))
            enc.finalize()
        elif state.failed is not None:
            enc.finalize()  # the FAIL frame is the last word
        done()

    dec.reconcile(on_reconcile)
    dec.change(lambda c, done_cb: (state.note_remote_record(c), done_cb()))
    # error hook, not user code: destroy() only flips state and wakes
    # watchers — it never blocks the registering loop
    # datlint: allow-callback-escape
    dec.on_error(lambda _e: None if enc.destroyed else enc.destroy())

    def finish() -> dict:
        if not enc.destroyed and not enc.finalized:
            # peer went away before decode completed: release the
            # reply pump / drop the reply tail
            enc.destroy()
        state.result()  # raises the structured error on a failed session
        return {"ok": dec.finished and not dec.destroyed,
                "symbols": state.peeler.symbols_seen,
                "rounds": state.rounds,
                "records_sent": sent_records["n"],
                "received": state.remote_records}

    return enc, dec, finish


def run_responder(replica: RatelessReplica, read_bytes, write_bytes,
                  close_write=None, engine: str = "auto",
                  overhead_cap: float = DEFAULT_OVERHEAD_CAP,
                  max_symbols: int = DEFAULT_MAX_SYMBOLS,
                  chunk_size: int = 64 * 1024) -> dict:
    """Serve one reconciliation as the responder over a duplex byte
    pair: decode the initiator's symbol stream, answer MORE/DONE/FAIL,
    ship this replica's differing records, collect the initiator's.
    Returns ``{"ok", "symbols", "rounds", "records_sent",
    "received"}``; raises the session's structured ProtocolError on a
    failed decode (after tearing both directions down)."""
    enc, dec, finish = responder_machine(replica, engine=engine,
                                         overhead_cap=overhead_cap,
                                         max_symbols=max_symbols)
    sender = threading.Thread(
        target=lambda: send_over(enc, write_bytes, close_write,
                                 chunk_size=chunk_size),
        name="reconcile-resp-send", daemon=True)
    sender.start()
    try:
        recv_over(dec, read_bytes, chunk_size=chunk_size)
    except Exception as e:
        if not dec.destroyed:
            dec.destroy(e)
        if not enc.destroyed:
            enc.destroy(e)
        raise
    finally:
        if not enc.destroyed and not enc.finalized:
            # initiator went away before decode completed: release the
            # reply pump so the thread does not park forever
            enc.destroy()
        sender.join(timeout=30)
    return finish()
