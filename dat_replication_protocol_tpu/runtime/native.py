"""On-demand build + ctypes bindings for the native runtime.

The image has a C++ toolchain but no pybind11 (and nothing may be pip
installed), so the native layer is a plain C ABI compiled with g++ on
first use and loaded via ctypes.  The compiled object is cached next to
the source keyed by a content hash, so rebuilds only happen when
``dat_native.cpp`` changes.  Everything degrades gracefully: callers use
:func:`get_lib` and fall back to pure Python when it returns ``None``
(no toolchain, read-only filesystem, ...).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np

from ..obs.events import emit as _emit
from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter

# host-engine digest traffic (device-telemetry catalog): bytes hashed by
# the native C pass — the host-side counterpart of device.h2d.bytes
_M_NATIVE_HASH_BYTES = _counter("device.native.hash.bytes")

_SRC = Path(__file__).resolve().parent.parent / "native" / "dat_native.cpp"
# location config, not behavior gating: where build products land may
# freeze at import  # datlint: disable=env-cache-policy
_BUILD_DIR = Path(
    os.environ.get(
        "DAT_NATIVE_BUILD_DIR",
        Path(__file__).resolve().parent.parent / "native" / "_build",
    )
)

ERR_TRUNCATED = -1
ERR_CAPACITY = -2
ERR_BAD_VARINT = -3
ERR_BAD_RECORD = -4
ERR_NOMEM = -5

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_I64P = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
_U32P = np.ctypeslib.ndpointer(dtype=np.uint32, flags="C_CONTIGUOUS")
_U64P = np.ctypeslib.ndpointer(dtype=np.uint64, flags="C_CONTIGUOUS")
_U8P = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")


def _build() -> Path | None:
    digest = hashlib.blake2b(_SRC.read_bytes(), digest_size=8).hexdigest()
    so = _BUILD_DIR / f"dat_native-{digest}.so"
    if so.exists():
        return so
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    tmp = so.with_suffix(f".tmp{os.getpid()}.so")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"dat_native build failed ({e}); using Python fallbacks",
              file=sys.stderr)
        return None
    os.replace(tmp, so)  # atomic: concurrent builders race benignly
    return so


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.dat_split_frames.restype = ctypes.c_int64
    lib.dat_split_frames.argtypes = [
        _U8P, ctypes.c_int64, _I64P, _I64P, _U8P, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.dat_greedy_select.restype = ctypes.c_int64
    lib.dat_greedy_select.argtypes = [
        _I64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, _I64P, ctypes.c_int64,
    ]
    lib.dat_decode_changes.restype = ctypes.c_int64
    lib.dat_decode_changes.argtypes = [
        _U8P, _I64P, _I64P, ctypes.c_int64,
        _U32P, _U32P, _U32P,
        _I64P, _I64P, _I64P, _I64P, _I64P, _I64P,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.dat_encode_changes.restype = ctypes.c_int64
    lib.dat_encode_changes.argtypes = [
        _U8P, ctypes.c_int64,
        _U32P, _U32P, _U32P,
        _I64P, _I64P, _I64P, _I64P, _I64P, _I64P,
        _U8P, ctypes.c_int64,
    ]
    lib.dat_encode_changes_mt.restype = ctypes.c_int64
    lib.dat_encode_changes_mt.argtypes = [
        _U8P, ctypes.c_int64,
        _U32P, _U32P, _U32P,
        _I64P, _I64P, _I64P, _I64P, _I64P, _I64P,
        _U8P, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.dat_decode_changes_mt.restype = ctypes.c_int64
    lib.dat_decode_changes_mt.argtypes = [
        _U8P, _I64P, _I64P, ctypes.c_int64,
        _U32P, _U32P, _U32P,
        _I64P, _I64P, _I64P, _I64P, _I64P, _I64P,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.dat_encode_change_batch.restype = ctypes.c_int64
    lib.dat_encode_change_batch.argtypes = [
        _U8P, ctypes.c_int64,
        _U32P, _U32P, _U32P,
        _I64P, _I64P, _I64P, _I64P, _I64P, _I64P,
        _U8P, ctypes.c_int64,
    ]
    lib.dat_gear_candidates.restype = ctypes.c_int64
    lib.dat_gear_candidates.argtypes = [
        _U8P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        _I64P, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.dat_blake2b_many.restype = ctypes.c_int64
    lib.dat_blake2b_many.argtypes = [
        _U8P, _I64P, _I64P, ctypes.c_int64, _U8P, ctypes.c_int64,
    ]
    # pointer-array twin: payload ADDRESSES ride a dedicated parameter
    # (an int64 address array on the Python side) instead of being
    # smuggled through the offset column (ADVICE r5 low)
    lib.dat_blake2b_many_ptrs.restype = ctypes.c_int64
    lib.dat_blake2b_many_ptrs.argtypes = [
        _I64P, _I64P, ctypes.c_int64, _U8P, ctypes.c_int64,
    ]
    lib.dat_cdc_hash.restype = ctypes.c_int64
    lib.dat_cdc_hash.argtypes = [
        _U8P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, _I64P, _U8P, ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.dat_sketch.restype = ctypes.c_int64
    lib.dat_sketch.argtypes = [
        _U8P, _I64P, _I64P, _I64P, _I64P,
        ctypes.c_int64, ctypes.c_int64, _U32P, _U32P, ctypes.c_int64,
    ]
    lib.dat_rateless_build.restype = ctypes.c_int64
    lib.dat_rateless_build.argtypes = [
        _U8P, ctypes.c_int64, _U64P, _U64P,
        ctypes.c_int64, ctypes.c_int64, _U32P, ctypes.c_int64,
    ]
    lib.dat_rateless_build_w.restype = ctypes.c_int64
    lib.dat_rateless_build_w.argtypes = [
        _U8P, _I64P, ctypes.c_int64, _U64P, _U64P,
        ctypes.c_int64, ctypes.c_int64, _U32P, ctypes.c_int64,
    ]
    # transport pump (ISSUE 14): batched-syscall socket loops
    lib.dat_pump_probe.restype = ctypes.c_int64
    lib.dat_pump_probe.argtypes = []
    lib.dat_pump_recv_scan.restype = ctypes.c_int64
    lib.dat_pump_recv_scan.argtypes = [
        ctypes.c_int64, _U8P, ctypes.c_int64, ctypes.c_int64,
        _I64P, _I64P, _U8P, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), _I64P,
    ]
    lib.dat_pump_send.restype = ctypes.c_int64
    lib.dat_pump_send.argtypes = [
        _I64P, _I64P, ctypes.c_int64, ctypes.c_int64, _I64P,
    ]
    lib.dat_pump_send_nb.restype = ctypes.c_int64
    lib.dat_pump_send_nb.argtypes = [
        _I64P, _I64P, ctypes.c_int64, ctypes.c_int64, _I64P,
    ]
    return lib


def get_lib() -> ctypes.CDLL | None:
    """The bound native library, or None (callers fall back to Python).

    Same gating policy as :func:`runtime.fastpath.get` (the shared
    env-cache policy datlint's env-cache-policy rule enforces): the
    DISABLE env var is re-read every call, only the build+load is
    cached, and a call made while disabled does not poison the cache.
    """
    if os.environ.get("DAT_NATIVE_DISABLE"):
        return None
    if _tried:  # lock-free hot path: _lib is set before _tried
        return _lib
    return _load_once()


def _load_once() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        lib = None
        # the ONE-TIME toolchain build runs under the load lock by
        # design: every caller needs its result, and serializing here
        # is what makes the load a process-wide once — audited escape:
        # datlint: allow-blocking-under-lock
        so = _build()
        if so is not None:
            try:
                lib = _bind(ctypes.CDLL(str(so)))
            except OSError as e:
                print(f"dat_native load failed ({e}); using Python fallbacks",
                      file=sys.stderr)
                lib = None
        _lib = lib
        _tried = True
    if _OBS.on:
        # once per process (only the winning builder reaches here —
        # emitted AFTER the lock releases, the sink can block): which
        # engine tier this host actually has, the first question when
        # a bench number moves between runners
        _emit("device.native.load", ok=lib is not None)
    return _lib


def reset_for_tests() -> None:
    """Drop the cached load so the next :func:`get_lib` re-decides (disk
    build cache untouched); the fastpath twin is
    :func:`runtime.fastpath.reset_for_tests`."""
    global _lib, _tried
    with _lock:
        _lib = None
        _tried = False


def available() -> bool:
    return get_lib() is not None


def _nthreads() -> int:
    return int(os.environ.get("DAT_NTHREADS", "0"))  # 0 = auto (hw cap)


def encode_change_batch(buf, n: int, change, from_, to, key_off, key_len,
                        sub_off, sub_len, val_off, val_len) -> bytes | None:
    """One columnar ``ChangeBatch`` payload from record spans over
    ``buf`` (the ChangeColumns layout; -1 lens = absent optionals), or
    ``None`` when the native library is unavailable (callers fall back
    to the Python codec in ``wire/batch_codec.py``).  The C pass owns
    the dictionary dedup — the only per-row work numpy cannot
    vectorize."""
    lib = get_lib()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    key_len = np.ascontiguousarray(key_len, dtype=np.int64)
    sub_len = np.ascontiguousarray(sub_len, dtype=np.int64)
    val_len = np.ascontiguousarray(val_len, dtype=np.int64)
    # capacity: header + worst-case dictionaries (every span unique) +
    # fixed columns at max widths + value heap
    heap = int(key_len.sum()) \
        + int(np.where(sub_len > 0, sub_len, 0).sum()) \
        + int(np.where(val_len > 0, val_len, 0).sum())
    cap = 64 + 32 * n + heap
    dst = np.empty(cap, np.uint8)
    w = lib.dat_encode_change_batch(
        buf, n,
        np.ascontiguousarray(change, np.uint32),
        np.ascontiguousarray(from_, np.uint32),
        np.ascontiguousarray(to, np.uint32),
        np.ascontiguousarray(key_off, np.int64), key_len,
        np.ascontiguousarray(sub_off, np.int64), sub_len,
        np.ascontiguousarray(val_off, np.int64), val_len,
        dst, cap,
    )
    if w < 0:
        if w == ERR_NOMEM:
            return None  # degrade to the Python codec
        if w == ERR_BAD_RECORD:
            # same contract (and failure class) as _pick_width's raise
            raise ValueError(
                "value exceeds ChangeBatch width ladder")
        raise RuntimeError(f"native batch encode failed (code {w})")
    return dst[:w].tobytes()


def hash_many_list(payloads: list) -> np.ndarray | None:
    """BLAKE2b-256 of a list of ``bytes`` payloads -> (n, 32) uint8, or
    ``None`` when unavailable (callers join + :func:`hash_many`).

    Zero-copy: the C engine reads each payload in place via
    (address, length) spans filled by the dat_fastpath extension,
    passed through ``dat_blake2b_many_ptrs``'s dedicated pointer-array
    parameter (ADVICE r5: the earlier detour through the offset column
    relative to a 1-byte dummy base was out-of-object pointer
    arithmetic — UB, and brittle against any future bounds check in the
    engine).  The ``b"".join`` this path replaces was ~25% of the
    routed host-hash path at digest-pipeline batch shapes.
    """
    lib = get_lib()
    if lib is None or not payloads:
        return None
    from . import fastpath

    fp = fastpath.get()
    if fp is None:
        return None
    n = len(payloads)
    addrs = np.empty(n, dtype=np.int64)
    lens = np.empty(n, dtype=np.int64)
    if not fp.bytes_spans(payloads, addrs, lens):
        return None  # non-bytes entries: caller falls back to the join
    out = np.empty((n, 32), dtype=np.uint8)
    # `payloads` stays referenced (and its bytes pinned) for the call
    rc = lib.dat_blake2b_many_ptrs(addrs, lens, n, out.reshape(-1),
                                   _nthreads())
    if rc != 0:
        return None
    if _OBS.on:
        _M_NATIVE_HASH_BYTES.inc(int(lens.sum()))
    return out


def hash_many(buf: np.ndarray, offs: np.ndarray, lens: np.ndarray):
    """BLAKE2b-256 of ``n`` extents of ``buf`` -> (n, 32) uint8 array, or
    ``None`` when the native library is unavailable (callers fall back).

    Thread-parallel C loop: no per-record interpreter cost, no device
    transfer — the host engine for digesting host-born bytes.
    """
    lib = get_lib()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    n = len(offs)
    out = np.empty((n, 32), dtype=np.uint8)
    rc = lib.dat_blake2b_many(buf, offs, lens, n, out.reshape(-1), _nthreads())
    if rc != 0:  # only allocation failure today
        return None
    if _OBS.on:
        _M_NATIVE_HASH_BYTES.inc(int(lens.sum()))
    return out


def sketch(buf: np.ndarray, rec_offs, rec_lens, key_offs, key_lens,
           log2_slots: int):
    """One-pass reconciliation sketch (see ops/reconcile.py): returns
    ``(table, slots)`` as numpy arrays, or ``None`` if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    rec_offs = np.ascontiguousarray(rec_offs, dtype=np.int64)
    rec_lens = np.ascontiguousarray(rec_lens, dtype=np.int64)
    key_offs = np.ascontiguousarray(key_offs, dtype=np.int64)
    key_lens = np.ascontiguousarray(key_lens, dtype=np.int64)
    n = len(rec_offs)
    table = np.zeros(((1 << log2_slots), 8), dtype=np.uint32)
    slots = np.empty(n, dtype=np.uint32)
    rc = lib.dat_sketch(buf, rec_offs, rec_lens, key_offs, key_lens, n,
                        log2_slots, table.reshape(-1), slots, _nthreads())
    if rc != 0:
        return None
    return table, slots


def hash_many_fallback(buf: np.ndarray, offs: np.ndarray,
                       lens: np.ndarray) -> np.ndarray:
    """:func:`hash_many`, degrading to a hashlib loop on toolchain-less
    hosts — the ONE owner of that fallback shape (consumers previously
    each carried a copy; the digest convention must have one home)."""
    out = hash_many(buf, offs, lens)
    if out is not None:
        return out
    import hashlib

    data = np.ascontiguousarray(buf, dtype=np.uint8).tobytes()
    out = np.empty((len(offs), 32), dtype=np.uint8)
    for i, (o, ln) in enumerate(zip(np.asarray(offs).tolist(),
                                    np.asarray(lens).tolist())):
        out[i] = np.frombuffer(
            hashlib.blake2b(data[o:o + ln], digest_size=32).digest(),
            np.uint8)
    return out


def rateless_build(digests: np.ndarray, state: np.ndarray,
                   next_idx: np.ndarray, m: int, base: int = 0):
    """Rateless coded-symbol build (see ops/rateless.py): advance the
    per-element cursors ``state`` / ``next_idx`` (IN PLACE — the same
    postcondition as ``IndexCursor.advance``) and return the
    ``(m - base, 11)`` u32 cell block for indices ``[base, m)``, or
    ``None`` when the native library is unavailable (callers fall back
    to the numpy reference — byte-identical by construction)."""
    lib = get_lib()
    if lib is None:
        return None
    digests = np.ascontiguousarray(digests, dtype=np.uint8)
    cells = np.zeros((m - base, 11), dtype=np.uint32)
    rc = lib.dat_rateless_build(digests.reshape(-1), len(state), state,
                                next_idx, base, m, cells.reshape(-1),
                                _nthreads())
    if rc != 0:
        return None
    return cells


def rateless_build_w(digests: np.ndarray, lens: np.ndarray,
                     state: np.ndarray, next_idx: np.ndarray,
                     m: int, base: int = 0):
    """Weighted coded-symbol build over (digest, length) elements (see
    ops/rateless.py's variable-size extension): same INOUT cursor
    contract as :func:`rateless_build`, 12-word cells, or ``None`` when
    the native library is unavailable (callers fall back to the numpy
    reference — byte-identical by construction)."""
    lib = get_lib()
    if lib is None:
        return None
    digests = np.ascontiguousarray(digests, dtype=np.uint8)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    cells = np.zeros((m - base, 12), dtype=np.uint32)
    rc = lib.dat_rateless_build_w(digests.reshape(-1), lens, len(state),
                                  state, next_idx, base, m,
                                  cells.reshape(-1), _nthreads())
    if rc != 0:
        return None
    return cells


def cdc_hash(buf: np.ndarray, avg_bits: int, thin_bits: int,
             min_size: int, max_size: int):
    """Fused single-pass content addressing: chunk cuts AND per-chunk
    BLAKE2b-256 digests in ONE sweep over ``buf`` (the ``fused1p``
    route's host engine).  Returns ``(cuts, digests)`` — cuts as int64
    end-offsets (exclusive, last == len), digests (nchunks, 32) uint8 —
    or ``None`` when the native library is unavailable or the shape is
    out of the fused kernel's range (``thin_bits`` outside [5, 31]):
    callers fall back to the two-pass route, which is byte-identical.
    """
    if not 5 <= thin_bits <= 31:
        return None
    lib = get_lib()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    n = len(buf)
    cap = n // max(min_size, 1) + 2
    cuts = np.empty(cap, dtype=np.int64)
    digests = np.empty((cap, 32), dtype=np.uint8)
    rc = lib.dat_cdc_hash(buf, n, avg_bits, thin_bits, min_size, max_size,
                          cuts, digests.reshape(-1), cap, _nthreads())
    if rc < 0:
        return None  # parameter out of range: two-pass route serves it
    if _OBS.on:
        _M_NATIVE_HASH_BYTES.inc(n)
    return cuts[:rc], digests[:rc]


def gear_candidates(buf: np.ndarray, avg_bits: int, thin_bits: int = -1,
                    serial_reference: bool = False):
    """Host gear CDC candidate scan (seeded-stream definition); sorted
    absolute positions as int64, or None when unavailable.

    ``serial_reference=True`` forces the independently-implemented
    single-chain route (tests compare the 4-chain machinery against it;
    never faster, only simpler)."""
    if not 1 <= avg_bits <= 31:
        raise ValueError("avg_bits must be in [1, 31]")
    if thin_bits > 31:
        raise ValueError("thin_bits must be < 32")
    lib = get_lib()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    n = len(buf)
    cap = max(256, (n >> max(avg_bits - 2, 0)) + 16)
    if thin_bits >= 0:
        cap = min(cap, (n >> thin_bits) + 16)
    while True:
        out = np.empty(cap, dtype=np.int64)
        rc = lib.dat_gear_candidates(buf, n, avg_bits, thin_bits, out, cap,
                                     -2 if serial_reference else _nthreads())
        if rc == ERR_CAPACITY:
            cap *= 4
            continue
        if rc < 0:
            return None
        return out[:rc]


# -- transport pump (ISSUE 14) ----------------------------------------------
# Thin ctypes fronts for the batched-syscall socket loops; the policy
# layer (route selection, decoder feeding, flow control, telemetry)
# lives in session/pump.py.  All of these return ``None`` when the
# native library is unavailable — callers take the Python pumps.


def pump_probe() -> int | None:
    """Bitmask of batched syscalls this kernel serves (bit 0 recvmmsg,
    bit 1 sendmmsg), or ``None`` without the native library.  The pump
    itself degrades per call (ENOSYS/ENOTSOCK fall back to plain
    read/writev batches); this probe only feeds telemetry."""
    lib = get_lib()
    if lib is None:
        return None
    return int(lib.dat_pump_probe())


def pump_recv_scan(fd: int, buf: np.ndarray, slice_bytes: int,
                   starts: np.ndarray, lens: np.ndarray, ids: np.ndarray,
                   stats: np.ndarray):
    """One batched receive into ``buf`` plus a native frame index over
    the received prefix (``dat_pump_recv_scan``): returns
    ``(nbytes, nframes, consumed, err)`` — ``nbytes`` 0 at EOF,
    negative ``-errno`` on a transport error; ``nframes``/``consumed``
    are ``dat_split_frames``' outputs (the decoder's bulk-index input).
    ``stats`` (int64[2]) receives [syscalls, messages] for the call.
    ``None`` when the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    nf = ctypes.c_int64(0)
    consumed = ctypes.c_int64(0)
    err = ctypes.c_int64(0)
    n = lib.dat_pump_recv_scan(fd, buf, len(buf), slice_bytes,
                               starts, lens, ids, len(starts),
                               ctypes.byref(nf), ctypes.byref(consumed),
                               ctypes.byref(err), stats)
    return int(n), int(nf.value), int(consumed.value), int(err.value)


def pump_send_spans(fd: int, addrs: np.ndarray, lens: np.ndarray,
                    n: int, stats: np.ndarray, nonblocking: bool = False):
    """Gather-send ``n`` (address, length) spans (``dat_pump_send`` /
    ``_nb``): the whole batch goes through sendmmsg/writev loops with
    the GIL released; returns bytes the kernel accepted (the full sum
    on a blocking fd) or ``-errno``.  The caller owns keeping every
    span's backing buffer alive across the call.  ``None`` when the
    native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    fn = lib.dat_pump_send_nb if nonblocking else lib.dat_pump_send
    return int(fn(addrs, lens, n, fd, stats))
