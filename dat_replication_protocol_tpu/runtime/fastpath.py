"""On-demand build + import of the ``dat_fastpath`` CPython extension.

Unlike :mod:`.native` (a plain C ABI loaded via ctypes), the dispatch
loop needs to create Python objects and call handlers, so it is a real
extension module compiled against this interpreter's headers.  Same
degrade-gracefully contract: :func:`get` returns ``None`` (and callers
use the pure-Python loop) when the toolchain or headers are missing or
``DAT_FASTPATH_DISABLE`` is set.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import sysconfig
import threading
from pathlib import Path

_SRC = Path(__file__).resolve().parent.parent / "native" / "dat_fastpath.cpp"
# location config, not behavior gating: where build products land may
# freeze at import  # datlint: disable=env-cache-policy
_BUILD_DIR = Path(
    os.environ.get(
        "DAT_NATIVE_BUILD_DIR",
        Path(__file__).resolve().parent.parent / "native" / "_build",
    )
)

_lock = threading.Lock()
_mod = None
_tried = False


def _build() -> Path | None:
    # keyed by source AND interpreter ABI: an extension built for one
    # CPython must never be loaded into another
    key = hashlib.blake2b(
        _SRC.read_bytes() + sys.version.encode(), digest_size=8
    ).hexdigest()
    so = _BUILD_DIR / f"dat_fastpath-{key}.so"
    if so.exists():
        return so
    include = sysconfig.get_paths().get("include")
    if not include or not os.path.isdir(include):
        return None
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    tmp = so.with_suffix(f".tmp{os.getpid()}.so")
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        f"-I{include}", str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"dat_fastpath build failed ({e}); using the Python loop",
              file=sys.stderr)
        return None
    os.replace(tmp, so)  # atomic: concurrent builders race benignly
    return so


def get():
    """The extension module, or None (callers fall back to the Python
    dispatch loop).

    THE fast-path gate — the decoder's dispatch loop and the wire
    codec's encode/decode both route through here so one process has
    exactly one policy (the round-5 advisor found the two layers had
    grown caches with opposite policies, a split-brain where flipping
    ``DAT_FASTPATH_DISABLE`` mid-process disabled one C path and not
    the other).  Policy: the DISABLE env var is re-read on EVERY call
    (so tests can exercise both implementations in one process); only
    the expensive build+import is cached.  A first call made while
    disabled does not poison the cache — enabling later still builds.
    """
    if os.environ.get("DAT_FASTPATH_DISABLE"):
        return None
    if _tried:  # lock-free hot path: _mod is set before _tried
        return _mod
    return _load_once()


def _load_once():
    global _mod, _tried
    with _lock:
        if _tried:
            return _mod
        mod = None
        # the ONE-TIME toolchain build runs under the load lock by
        # design: every caller needs its result, and serializing here
        # is what makes the load a process-wide once — audited escape:
        # datlint: allow-blocking-under-lock
        so = _build()
        if so is not None:
            try:
                import importlib.util

                spec = importlib.util.spec_from_file_location(
                    "dat_fastpath", str(so))
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
            except Exception as e:  # load/ABI failure: fall back, once
                print(f"dat_fastpath load failed ({e}); using the Python "
                      f"loop", file=sys.stderr)
                mod = None
        _mod = mod
        _tried = True
        return _mod


def reset_for_tests():
    """Drop the cached import so the next :func:`get` re-decides from a
    clean slate (build cache on disk is untouched).  Test hook only:
    live Decoder/Encoder objects keep references to the old module."""
    global _mod, _tried
    with _lock:
        _mod = None
        _tried = False
