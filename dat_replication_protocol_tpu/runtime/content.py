"""Content addressing: chunk -> hash -> Merkle root, and version deltas.

The reference moves *already content-addressed* data — dat core above it
chunks blobs, hashes chunks, and exchanges only missing pieces; the wire
protocol's ``Change.value``/blob frames carry the results (reference:
README.md:73, messages/schema.proto:6).  This module composes the
framework's device pipeline into that exact workflow as one API:

* :func:`content_address` — CDC cut a byte stream
  (:func:`..ops.rabin.chunk_stream`), BLAKE2b every chunk in batched
  device dispatches (:func:`..batch.feed.hash_extents`), fold the chunk
  digests to a Merkle root (:mod:`..ops.merkle`).
* :func:`delta` — the transfer set between two versions of a blob: chunks
  of ``new`` whose digests ``old`` does not hold.  Because the cuts are
  content-defined, an insertion/deletion reshuffles only the chunks it
  touches — the delta stays O(edit), not O(blob), which is the entire
  point of CDC dedup.

Everything heavy runs on device; the host sees cut offsets, digests, and
the root.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter
from ..obs.tracing import trace_span as _trace_span

_M_D2H = _counter("device.d2h.bytes")


def _extents_from_cuts(cuts) -> tuple[np.ndarray, np.ndarray]:
    """Chunk end-offsets -> (offsets, lengths); single owner of the
    exclusive-ends convention."""
    ends = np.asarray(cuts, dtype=np.int64)
    offs = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
    return offs, ends - offs


@dataclasses.dataclass(frozen=True, eq=False)
class ContentSummary:
    """One blob version's content-addressed identity.

    ``cuts``: chunk end-offsets (exclusive, ascending, last == length);
    ``digests``: (nchunks, 32) uint8 BLAKE2b-256 per chunk, in order;
    ``root``: 32-byte Merkle root over the chunk digests (zero-padded to
    a power of two, so equal content always folds to an equal root).

    Equality/hash use the identity triple (length, cuts, root) — the
    dataclass defaults would tuple-compare the ndarray field, which
    raises; the root already commits to every digest.
    """

    length: int
    cuts: list[int]
    digests: np.ndarray
    root: bytes

    def __eq__(self, other) -> bool:
        if not isinstance(other, ContentSummary):
            return NotImplemented
        return (self.length == other.length and self.cuts == other.cuts
                and self.root == other.root)

    def __hash__(self) -> int:
        return hash((self.length, tuple(self.cuts), self.root))

    @property
    def nchunks(self) -> int:
        return len(self.cuts)

    def extents(self) -> tuple[np.ndarray, np.ndarray]:
        """(offsets, lengths) arrays of the chunks."""
        return _extents_from_cuts(self.cuts)


def content_address(data, avg_bits: int = 13,
                    min_size: int | None = None,
                    max_size: int | None = None) -> ContentSummary:
    """Chunk, hash, and root a byte stream on device.

    ``data``: bytes or uint8 array.  Empty input has zero chunks and the
    all-zero root (the empty-subtree sentinel of
    :func:`..ops.merkle.pad_leaves`).
    """
    from ..batch.feed import hash_extents_device
    from ..ops import merkle
    from ..ops.rabin import chunk_stream

    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)
    ) else np.asarray(data, dtype=np.uint8)
    if buf.size == 0:
        return ContentSummary(0, [], np.empty((0, 32), np.uint8), b"\0" * 32)
    with _trace_span("device.content.address", bytes=int(buf.size)):
        cuts = chunk_stream(buf, avg_bits, min_size, max_size)
        offs, lens = _extents_from_cuts(cuts)
        # digests stay in HBM through the tree fold; the host copy is one
        # interleave off the same device arrays (no fetch-then-reupload)
        hh, hl = hash_extents_device(buf, offs, lens)
        (root_bytes,) = merkle.digests_from_device(
            *merkle.root(*merkle.pad_leaves(hh, hl))
        )
        n = len(cuts)
        if _OBS.on:
            _M_D2H.inc(32 * n + 32)  # chunk digests + the root
        raw = np.empty((n, 8), dtype="<u4")
        raw[:, 0::2] = np.asarray(hl)
        raw[:, 1::2] = np.asarray(hh)
        digests = raw.view(np.uint8).reshape(n, 32)
    return ContentSummary(int(buf.size), list(map(int, cuts)), digests,
                          root_bytes)


def delta(old: ContentSummary, new: ContentSummary) -> list[int]:
    """Chunk indices of ``new`` that ``old`` cannot supply.

    The sender ships exactly these chunks (plus the cut table); the
    receiver reassembles everything else from chunks it already holds —
    dat's dedup exchange, here decided by digest set membership.  Equal
    roots short-circuit to an empty delta.
    """
    if old.root == new.root and old.cuts == new.cuts:
        return []
    have = {old.digests[i].tobytes() for i in range(old.nchunks)}
    return [
        i for i in range(new.nchunks)
        if new.digests[i].tobytes() not in have
    ]


def reassemble(new: ContentSummary, old_data,
               old: ContentSummary, sent: dict[int, bytes]) -> bytes:
    """Receiver-side reconstruction: old chunks + the delta -> new bytes.

    ``sent`` maps chunk index -> bytes for every index in
    ``delta(old, new)``.  Raises ``KeyError`` if a needed chunk is
    neither held nor sent, ``ValueError`` if a supplied chunk's digest
    does not match the summary (corruption check — digests are the
    addresses, so verification is free).
    """
    import hashlib

    old_buf = np.frombuffer(old_data, dtype=np.uint8) if isinstance(
        old_data, (bytes, bytearray, memoryview)
    ) else np.asarray(old_data, dtype=np.uint8)
    by_digest: dict[bytes, tuple[int, int]] = {}
    o_offs, o_lens = old.extents()
    for i in range(old.nchunks):
        by_digest[old.digests[i].tobytes()] = (int(o_offs[i]), int(o_lens[i]))
    out = bytearray()
    for i in range(new.nchunks):
        d = new.digests[i].tobytes()
        if i in sent:
            piece = sent[i]
            if hashlib.blake2b(piece, digest_size=32).digest() != d:
                raise ValueError(f"chunk {i} digest mismatch")
        else:
            off, ln = by_digest[d]
            piece = old_buf[off:off + ln].tobytes()
        out += piece
    return bytes(out)
