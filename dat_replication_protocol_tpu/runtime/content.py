"""Content addressing: chunk -> hash -> Merkle root, and version deltas.

The reference moves *already content-addressed* data — dat core above it
chunks blobs, hashes chunks, and exchanges only missing pieces; the wire
protocol's ``Change.value``/blob frames carry the results (reference:
README.md:73, messages/schema.proto:6).  This module composes the
framework's device pipeline into that exact workflow as one API:

* :func:`content_address` — CDC cut a byte stream
  (:func:`..ops.rabin.chunk_stream`), BLAKE2b every chunk in batched
  device dispatches (:func:`..batch.feed.hash_extents`), fold the chunk
  digests to a Merkle root (:mod:`..ops.merkle`).
* :func:`delta` — the transfer set between two versions of a blob: chunks
  of ``new`` whose digests ``old`` does not hold.  Because the cuts are
  content-defined, an insertion/deletion reshuffles only the chunks it
  touches — the delta stays O(edit), not O(blob), which is the entire
  point of CDC dedup.

Everything heavy runs on device; the host sees cut offsets, digests, and
the root.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from ..obs.device import note_engine as _note_engine
from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter
from ..obs.tracing import trace_span as _trace_span

_M_D2H = _counter("device.d2h.bytes")
# single-pass route volume (OBSERVABILITY.md single-pass catalog)
_M_FUSED_BYTES = _counter("cdc.fused.bytes")
_M_FUSED_CHUNKS = _counter("cdc.fused.chunks")


def _extents_from_cuts(cuts) -> tuple[np.ndarray, np.ndarray]:
    """Chunk end-offsets -> (offsets, lengths); single owner of the
    exclusive-ends convention."""
    ends = np.asarray(cuts, dtype=np.int64)
    offs = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
    return offs, ends - offs


@dataclasses.dataclass(frozen=True, eq=False)
class ContentSummary:
    """One blob version's content-addressed identity.

    ``cuts``: chunk end-offsets (exclusive, ascending, last == length);
    ``digests``: (nchunks, 32) uint8 BLAKE2b-256 per chunk, in order;
    ``root``: 32-byte Merkle root over the chunk digests (zero-padded to
    a power of two, so equal content always folds to an equal root).

    Equality/hash use the identity triple (length, cuts, root) — the
    dataclass defaults would tuple-compare the ndarray field, which
    raises; the root already commits to every digest.
    """

    length: int
    cuts: list[int]
    digests: np.ndarray
    root: bytes

    def __eq__(self, other) -> bool:
        if not isinstance(other, ContentSummary):
            return NotImplemented
        return (self.length == other.length and self.cuts == other.cuts
                and self.root == other.root)

    def __hash__(self) -> int:
        return hash((self.length, tuple(self.cuts), self.root))

    @property
    def nchunks(self) -> int:
        return len(self.cuts)

    def extents(self) -> tuple[np.ndarray, np.ndarray]:
        """(offsets, lengths) arrays of the chunks."""
        return _extents_from_cuts(self.cuts)


def _as_u8(data) -> np.ndarray:
    return np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)
    ) else np.asarray(data, dtype=np.uint8)


def resolve_cdc_route() -> str:
    """The ONE owner of the host content-addressing route decision.

    ``fused1p`` (the default) is the single-pass native engine: gear
    candidates, greedy cuts, and chunk BLAKE2b in one sweep
    (``dat_cdc_hash``; cuts and digests byte-identical to the two-pass
    route — the fuzz suite pins it).  Setting ``DAT_CDC_ROUTE`` to any
    OTHER recognized value pins the two-pass route with that extraction
    kernel; unrecognized values resolve to the default, mirroring
    :func:`..ops.rabin.effective_route`.
    """
    route = os.environ.get("DAT_CDC_ROUTE")
    if route in ("bitmask", "first", "fused"):
        return "2p"
    return "fused1p"


def content_digests(data, avg_bits: int = 13,
                    min_size: int | None = None,
                    max_size: int | None = None,
                    route: str | None = None):
    """Chunk cuts AND per-chunk BLAKE2b-256 digests for a byte stream —
    the single-pass bytes->digests API (ISSUE 7 tentpole).

    Returns ``(cuts, digests)``: cut end-offsets (list[int], exclusive,
    last == length) and (nchunks, 32) uint8 digests.  ``route``:
    ``None`` resolves via :func:`resolve_cdc_route`; ``"fused1p"``
    forces the single-pass engine (falls back to two-pass when the
    native library is absent or the shape is out of its range);
    ``"2p"`` forces the two-pass route (the A/B incumbent).

    Host routing ("batch or stay home", same decision as
    :func:`..ops.rabin.chunk_stream`): on a CPU-backed jax the native
    engines serve both routes; on an accelerator the device
    single-residency pipeline does (:mod:`..ops.fused_cdc_hash_pallas`).
    """
    from ..ops.rabin import chunk_stream, _clamp_thin_bits
    from ..utils.routing import prefer_host

    buf = _as_u8(data)
    n = int(buf.size)
    if n == 0:
        return [], np.empty((0, 32), np.uint8)
    if min_size is None:
        min_size = 1 << (avg_bits - 2)
    if max_size is None:
        max_size = 1 << (avg_bits + 2)
    if route is None:
        route = resolve_cdc_route()

    host = prefer_host("DAT_DEVICE_CDC")
    if host and route == "fused1p":
        from . import native

        # the SAME thinning policy as every other route (one owner:
        # _clamp_thin_bits), so cuts are identical across all of them
        thin = _clamp_thin_bits(max(min_size, 1).bit_length() - 1, 1 << 17)
        out = native.cdc_hash(buf, avg_bits, -1 if thin is None else thin,
                              min_size, max_size)
        if out is not None:
            cuts_arr, digests = out
            if _OBS.on:
                _M_FUSED_BYTES.inc(n)
                _M_FUSED_CHUNKS.inc(len(cuts_arr))
                _note_engine("cdc.hash", "fused1p-native", bytes=n)
            return cuts_arr.tolist(), digests
        # out of the fused kernel's range (tiny min_size, no native
        # library): the two-pass route serves it byte-identically
    if host:
        from . import native

        cuts = chunk_stream(buf, avg_bits, min_size, max_size)
        offs, lens = _extents_from_cuts(cuts)
        digests = native.hash_many(buf, offs, lens)
        if digests is None:  # no native library: hashlib loop
            import hashlib

            digests = np.empty((len(cuts), 32), np.uint8)
            for i, (o, ln) in enumerate(zip(offs, lens)):
                digests[i] = np.frombuffer(
                    hashlib.blake2b(buf[o:o + ln].tobytes(),
                                    digest_size=32).digest(), np.uint8)
        if _OBS.on:
            _note_engine("cdc.hash", "two-pass-host", bytes=n)
        return list(map(int, cuts)), digests

    # device: the single-residency pipeline (one upload, CDC + hash off
    # the same resident words) for buffers within its per-call cap; the
    # slabbed two-pass composition for anything larger — and for an
    # EXPLICIT route="2p" (the A/B incumbent must stay the two-pass
    # host-repack composition on every backend, or the bench's
    # comparison label lies about what ran)
    from ..ops.fused_cdc_hash_pallas import RESIDENCY_CAP

    if route != "2p" and n < RESIDENCY_CAP:
        from ..ops.fused_cdc_hash_pallas import content_begin

        cuts, hh, hl = content_begin(buf, avg_bits, min_size, max_size)()
        if _OBS.on:
            _M_D2H.inc(32 * len(cuts))
            _note_engine("cdc.hash", "device-1residency", bytes=n)
        from ..ops.merkle import digest_matrix

        return list(map(int, cuts)), digest_matrix(hh, hl)
    from ..batch.feed import hash_extents

    cuts = chunk_stream(buf, avg_bits, min_size, max_size)
    offs, lens = _extents_from_cuts(cuts)
    if _OBS.on:
        _note_engine("cdc.hash", "device-two-pass", bytes=n)
    return list(map(int, cuts)), hash_extents(buf, offs, lens)


def content_address(data, avg_bits: int = 13,
                    min_size: int | None = None,
                    max_size: int | None = None) -> ContentSummary:
    """Chunk, hash, and root a byte stream.

    ``data``: bytes or uint8 array.  Empty input has zero chunks and the
    all-zero root (the empty-subtree sentinel of
    :func:`..ops.merkle.pad_leaves`).

    Single-pass restructuring (ISSUE 7): blob bytes are read ONCE.  On a
    CPU host the fused native engine computes cuts and digests in one
    sweep (the old host route streamed the data through the gear scan,
    then re-read every byte through an XLA-scan BLAKE2b that measured
    ~0.001 GiB/s); on an accelerator the words are uploaded once and
    both the CDC kernels and the chunk hash read the same resident
    buffer.  The Merkle fold consumes the digest columns either way.
    """
    from ..ops import merkle
    from ..utils.routing import prefer_host

    buf = _as_u8(data)
    if buf.size == 0:
        return ContentSummary(0, [], np.empty((0, 32), np.uint8), b"\0" * 32)
    with _trace_span("device.content.address", bytes=int(buf.size)):
        on_device = not prefer_host("DAT_DEVICE_CDC")
        if on_device:
            from ..ops.fused_cdc_hash_pallas import RESIDENCY_CAP

            on_device = buf.size < RESIDENCY_CAP
        if on_device:
            # device route: digests stay in HBM through the tree fold;
            # the host copy is one interleave off the same device arrays
            from ..ops.fused_cdc_hash_pallas import content_begin

            cuts, hh, hl = content_begin(buf, avg_bits, min_size,
                                         max_size)()
            (root_bytes,) = merkle.digests_from_device(
                *merkle.root(*merkle.pad_leaves(hh, hl))
            )
            n = len(cuts)
            if _OBS.on:
                _M_D2H.inc(32 * n + 32)  # chunk digests + the root
            digests = merkle.digest_matrix(hh, hl)
            return ContentSummary(int(buf.size), list(map(int, cuts)),
                                  digests, root_bytes)
        cuts, digests = content_digests(buf, avg_bits, min_size, max_size)
        # host tree fold (native engine): byte-identical to the device
        # fold, without routing 32 B/chunk through an XLA CPU program
        root_bytes = merkle.root_host(digests)
    return ContentSummary(int(buf.size), cuts, digests, root_bytes)


def delta(old: ContentSummary, new: ContentSummary) -> list[int]:
    """Chunk indices of ``new`` that ``old`` cannot supply.

    The sender ships exactly these chunks (plus the cut table); the
    receiver reassembles everything else from chunks it already holds —
    dat's dedup exchange, here decided by digest set membership.  Equal
    roots short-circuit to an empty delta.
    """
    if old.root == new.root and old.cuts == new.cuts:
        return []
    have = {old.digests[i].tobytes() for i in range(old.nchunks)}
    return [
        i for i in range(new.nchunks)
        if new.digests[i].tobytes() not in have
    ]


def reassemble(new: ContentSummary, old_data,
               old: ContentSummary, sent: dict[int, bytes]) -> bytes:
    """Receiver-side reconstruction: old chunks + the delta -> new bytes.

    ``sent`` maps chunk index -> bytes for every index in
    ``delta(old, new)``.  Raises ``KeyError`` if a needed chunk is
    neither held nor sent, ``ValueError`` if a supplied chunk's digest
    does not match the summary (corruption check — digests are the
    addresses, so verification is free).
    """
    import hashlib

    old_buf = np.frombuffer(old_data, dtype=np.uint8) if isinstance(
        old_data, (bytes, bytearray, memoryview)
    ) else np.asarray(old_data, dtype=np.uint8)
    by_digest: dict[bytes, tuple[int, int]] = {}
    o_offs, o_lens = old.extents()
    for i in range(old.nchunks):
        by_digest[old.digests[i].tobytes()] = (int(o_offs[i]), int(o_lens[i]))
    out = bytearray()
    for i in range(new.nchunks):
        d = new.digests[i].tobytes()
        if i in sent:
            piece = sent[i]
            if hashlib.blake2b(piece, digest_size=32).digest() != d:
                raise ValueError(f"chunk {i} digest mismatch")
        else:
            off, ln = by_digest[d]
            piece = old_buf[off:off + ln].tobytes()
        out += piece
    return bytes(out)
